"""Device compute at 4K/8K-row serving dispatches, 128 MiB table (1M keys):
the coalesce_limit operating points for the p99<2ms co-located budget."""
import sys, time
import numpy as np
import gubernator_tpu  # noqa
import jax
from bench import Case, make_req_batch

def log(m): print(m, file=sys.stderr, flush=True)
rng = np.random.default_rng(42)
now = int(time.time() * 1000)
log(f"device: {jax.devices()[0]}")
cap, live = 1 << 21, 1_000_000
keyspace = rng.integers(1, (1 << 63) - 1, size=live, dtype=np.int64)
perm = rng.permutation(live)
for BATCH in (1 << 12, 1 << 13):
    batches = [jax.device_put(make_req_batch(keyspace[perm[i*BATCH:(i+1)*BATCH]], now)) for i in range(8)]
    seed = [jax.device_put(make_req_batch(keyspace[i*BATCH:(i+1)*BATCH], now)) for i in range(live // BATCH)]
    c = Case(f"serve-{BATCH}", cap, batches, seed_batches=seed, math="token")
    res = c.run(dispatches=8, latency_probes=2)
    log(f"RESULT {BATCH}: device_ms={res.get('device_ms')} dec/s={res.get('device_decisions_per_sec')}")
    del c, batches, seed
