"""Memory-op microbenchmarks on the real TPU: what dominates decide()'s 73ms?

Timing through the tunnel: block_until_ready doesn't round-trip, so every
variant chains its output into a scalar fetch and we report the SLOPE between
a short and long loop (bench.py technique).
"""

import time
import sys

sys.path.insert(0, "/root/repo")

import numpy as np

import gubernator_tpu  # noqa: F401 (x64 on)
import jax
import jax.numpy as jnp
from functools import partial

C = 1 << 24  # 16.7M slots
B = 1 << 17  # 131072 rows
P = 15  # planes
K = 8

rng = np.random.default_rng(0)
slots_np = rng.permutation(C)[:B].astype(np.int64)  # unique random slots
vals_np = rng.standard_normal(B).astype(np.float32)
buckets_np = (slots_np // K).astype(np.int64)


def timed(name, fn, *args, n_long=24, n_short=4):
    out = fn(*args)  # compile
    _ = float(jax.tree.leaves(out)[0].reshape(-1)[0])

    def run(n):
        t0 = time.perf_counter()
        acc = args
        o = None
        for i in range(n):
            o = fn(*args)
        _ = float(jax.tree.leaves(o)[0].reshape(-1)[0])
        return time.perf_counter() - t0

    run(2)
    ts = min(run(n_short) for _ in range(2))
    tl = min(run(n_long) for _ in range(2))
    ms = (tl - ts) / (n_long - n_short) * 1e3
    print(f"{name:55s} {ms:8.2f} ms", file=sys.stderr, flush=True)
    return ms


def main():
    print(f"device: {jax.devices()[0]}", file=sys.stderr)
    slots = jnp.asarray(slots_np)
    slots32 = jnp.asarray(slots_np.astype(np.int32))
    ssorted = jnp.asarray(np.sort(slots_np))
    ssorted32 = jnp.asarray(np.sort(slots_np).astype(np.int32))
    vals = jnp.asarray(vals_np)
    buckets = jnp.asarray(buckets_np)
    buckets32 = jnp.asarray(buckets_np.astype(np.int32))

    planes_f32 = [jnp.zeros(C, dtype=jnp.float32) for _ in range(P)]
    big_f32 = jnp.zeros(P * C, dtype=jnp.float32)
    tbl2d = jnp.zeros((C // K, K), dtype=jnp.float32)
    tbl_row16 = jnp.zeros((C, 16), dtype=jnp.int32)

    # ---- A: P separate flat f32 scatters (the current kernel's write phase)
    @jax.jit
    def scatter_P_sep(planes, s, v):
        return [p.at[s].set(v + i, mode="drop") for i, p in enumerate(planes)]

    timed("A: 15 separate flat f32 scatters (i64 idx)", scatter_P_sep, planes_f32, slots, vals)

    @jax.jit
    def scatter_P_sep32(planes, s, v):
        return [p.at[s].set(v + i, mode="drop") for i, p in enumerate(planes)]

    timed("A2: 15 separate flat f32 scatters (i32 idx)", scatter_P_sep32, planes_f32, slots32, vals)

    # ---- B: ONE fused scatter into (P*C,) with plane-offset indices
    @jax.jit
    def scatter_fused(big, s, v):
        idx = (jnp.arange(P, dtype=jnp.int64)[:, None] * C + s[None, :]).reshape(-1)
        vv = (v[None, :] + jnp.arange(P, dtype=jnp.float32)[:, None]).reshape(-1)
        return big.at[idx].set(vv, mode="drop")

    timed("B: 1 fused scatter of 15*B rows into (15C,)", scatter_fused, big_f32, slots, vals)

    # ---- C: sorted & unique hints
    @jax.jit
    def scatter_sorted(planes, s, v):
        return [
            p.at[s].set(v + i, mode="drop", unique_indices=True, indices_are_sorted=True)
            for i, p in enumerate(planes)
        ]

    timed("C: 15 flat scatters, sorted+unique hints (i64)", scatter_sorted, planes_f32, ssorted, vals)
    timed("C2: 15 flat scatters, sorted+unique hints (i32)", scatter_sorted, planes_f32, ssorted32, vals)

    # ---- D: row scatter into (C,16) int32 — one contiguous 64B write per row
    @jax.jit
    def scatter_row16(tbl, s, v):
        rows = jnp.broadcast_to(v[:, None].astype(jnp.int32), (B, 16))
        return tbl.at[s].set(rows, mode="drop")

    timed("D: row scatter (B,16)int32 into (C,16) (i64 idx)", scatter_row16, tbl_row16, slots, vals)
    timed("D2: row scatter sorted idx", scatter_row16, tbl_row16, ssorted, vals)

    # ---- E: gathers
    @jax.jit
    def gather_P_sep(planes, s):
        return sum(p[s] for p in planes)

    timed("E: 15 separate flat f32 gathers", gather_P_sep, planes_f32, slots)

    @jax.jit
    def gather_fused(big, s):
        idx = (jnp.arange(P, dtype=jnp.int64)[:, None] * C + s[None, :]).reshape(-1)
        return big[idx].reshape(P, B).sum(0)

    timed("F: 1 fused gather of 15*B from (15C,)", gather_fused, big_f32, slots)

    @jax.jit
    def gather_row16(tbl, s):
        return tbl[s].sum(1)

    timed("G: row gather (B,16)i32 from (C,16)", gather_row16, tbl_row16, slots)

    @jax.jit
    def gather_bucket(tbl, b):
        return tbl[b].sum(1)  # (B, K) row gather from (C/K, K)

    timed("H: bucket row gather (B,8)f32 from (C/8,8)", gather_bucket, tbl2d, buckets)

    # ---- I: scatter-max (the claim phase op)
    @jax.jit
    def scatter_max(p, s, v):
        return p.at[s].max(v, mode="drop")

    timed("I: 1 flat f32 scatter-max", scatter_max, planes_f32[0], slots, vals)

    # ---- J: i32 scatter (no f32 carrier)
    planes_i32 = [jnp.zeros(C, dtype=jnp.int32) for _ in range(P)]

    @jax.jit
    def scatter_P_i32(planes, s, v):
        vi = v.astype(jnp.int32)
        return [p.at[s].set(vi + i, mode="drop") for i, p in enumerate(planes)]

    timed("J: 15 separate flat i32 scatters", scatter_P_i32, planes_i32, slots, vals)

    # ---- K: full decide() for reference
    from tests.oracle.kernel_v1 import decide
    from tests.oracle.table_v1 import new_table
    from bench import make_req_batch

    table = new_table(C)
    _rng = np.random.default_rng(42)
    batches = [
        jax.device_put(
            make_req_batch(
                _rng.integers(1, (1 << 63) - 1, size=1 << 17, dtype=np.int64),
                1_700_000_000_000,
            )
        )
        for _ in range(8)
    ]

    def dec(i=[0]):
        pass

    tbl = [table]

    @partial(jax.jit, donate_argnums=0)
    def _noop(t):
        return t

    def run_decide(b):
        tbl[0], resp, stats = decide(tbl[0], b)
        return stats.cache_hits

    out = run_decide(batches[0])
    _ = int(out)

    def runN(n):
        t0 = time.perf_counter()
        o = None
        for i in range(n):
            o = run_decide(batches[i % len(batches)])
        _ = int(o)
        return time.perf_counter() - t0

    runN(2)
    ts = min(runN(4) for _ in range(2))
    tl = min(runN(24) for _ in range(2))
    print(f"{'K: full decide()':55s} {(tl-ts)/20*1e3:8.2f} ms", file=sys.stderr, flush=True)


if __name__ == "__main__":
    main()
