"""Measure dispatch cost at BASELINE #5 scale (100M keys, 8 GiB table) on a
real chip, across batch sizes — is the full-table sweep amortizable, or does
the big table need a banked write? (VERDICT r3 weak #6)

Run: python exp/exp_bigtable.py [capacity_log2=27] [live=100e6]
"""

import sys
import time

import numpy as np

import gubernator_tpu  # noqa: F401
import jax

from gubernator_tpu.ops.batch import ReqBatch
from gubernator_tpu.ops.kernel2 import decide2
from gubernator_tpu.ops.table2 import new_table2

import jax.numpy as jnp

CAP_LOG2 = int(sys.argv[1]) if len(sys.argv) > 1 else 27
LIVE = int(float(sys.argv[2])) if len(sys.argv) > 2 else 100_000_000
NOW = 1_700_000_000_000


def make_batch(fps: np.ndarray) -> ReqBatch:
    b = fps.shape[0]
    return ReqBatch(
        fp=jnp.asarray(fps),
        algo=jnp.zeros(b, dtype=jnp.int32),
        behavior=jnp.zeros(b, dtype=jnp.int32),
        hits=jnp.ones(b, dtype=jnp.int64),
        limit=jnp.full(b, 1 << 30, dtype=jnp.int64),
        burst=jnp.full(b, 1 << 30, dtype=jnp.int64),
        duration=jnp.full(b, 3_600_000, dtype=jnp.int64),
        created_at=jnp.full(b, NOW, dtype=jnp.int64),
        expire_new=jnp.full(b, NOW + 3_600_000, dtype=jnp.int64),
        greg_interval=jnp.zeros(b, dtype=jnp.int64),
        duration_eff=jnp.full(b, 3_600_000, dtype=jnp.int64),
        active=jnp.ones(b, dtype=bool),
    )


def main():
    cap = 1 << CAP_LOG2
    table = new_table2(cap)
    nb = table.rows.shape[0]
    print(f"table: {cap} slots, {nb} buckets, {nb * 512 / 2**30:.1f} GiB")
    rng = np.random.default_rng(0)
    keyspace = rng.integers(1, (1 << 63) - 1, size=LIVE, dtype=np.int64)

    # seed all live keys, streaming (no staging of 100M rows on device)
    SEED_B = 1 << 19
    t0 = time.perf_counter()
    stats = None
    for i in range(0, LIVE, SEED_B):
        chunk = keyspace[i : i + SEED_B]
        if chunk.shape[0] < SEED_B:
            chunk = np.pad(chunk, (0, SEED_B - chunk.shape[0]))
        b = jax.device_put(make_batch(chunk))
        table, resp, stats = decide2(table, b, write="sweep")
        if i % (SEED_B * 32) == 0 and stats is not None:
            _ = int(stats.cache_hits)  # periodic sync to bound queueing
            print(
                f"  seeded {i + SEED_B:>11,} / {LIVE:,} "
                f"({time.perf_counter() - t0:.0f}s)", flush=True,
            )
    evic = int(stats.evicted_unexpired)
    print(f"seeding done in {time.perf_counter() - t0:.0f}s")

    import os

    blogs = [int(x) for x in os.environ.get("BLOGS", "17,18,19").split(",")]
    table2 = table  # donated through every dispatch below — never reuse `table`
    for BLOG in blogs:
        B = 1 << BLOG
        perm = rng.permutation(LIVE)[: B * 8]
        batches = [
            jax.device_put(make_batch(keyspace[perm[j * B : (j + 1) * B]]))
            for j in range(8)
        ]
        # warm compile
        for b in batches[:2]:
            table2, resp, stats = decide2(table2, b, write="sweep")
        _ = int(stats.cache_hits)

        def run(k):
            nonlocal table2
            t0 = time.perf_counter()
            for i in range(k):
                table2, resp, stats = decide2(
                    table2, batches[i % 8], write="sweep"
                )
            _ = int(stats.cache_hits)
            return time.perf_counter() - t0, stats

        run(2)
        t_short = min(run(4)[0] for _ in range(3))
        k_long = 4 + 64
        t_long, stats = min(run(k_long) for _ in range(3))
        dt = t_long - t_short
        dps = 64 * B / dt
        print(
            f"batch 2^{BLOG} ({B}): {dt/64*1e3:.2f} ms/dispatch, "
            f"{dps/1e6:.2f}M decisions/s; hits={int(stats.cache_hits)} "
            f"misses={int(stats.cache_misses)} evict={evic}", flush=True,
        )


if __name__ == "__main__":
    main()
