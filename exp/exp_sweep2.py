"""Sweep-write kernel variants, timed standalone on the real TPU.

Current kernel: 4 int8 one-hot matmuls (byte planes) + per-plane (&0xFF)<<s |
reassembly over the full (BLK, 128) block — VPU-bound. Variants:

  copy     — out=in DMA floor
  cur      — the shipping kernel
  i8acc    — ONE int8 matmul to (BLK, 512) int8 accumulators + bitcast
  f32x2    — two 16-bit planes accumulated in f32 (exact ≤ 2^24), 4 VPU ops
  fused    — f32x2 + mask folded into a widened payload (one matmul total)
"""

import sys
import time
from functools import partial

import numpy as np

import gubernator_tpu  # noqa: F401
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NB = 1 << 21  # 2M buckets = 1 GiB table
ROW = 128
K = 8
F = 16
BATCH = 1 << 17
BLK = 2048
U = 256
NBLK = NB // BLK

i32 = jnp.int32


def log(m):
    print(m, file=sys.stderr, flush=True)


def slope(fn, n_long=16):
    fn()
    int(fn()[0, 0])

    def run(k):
        t0 = time.perf_counter()
        out = None
        for _ in range(k):
            out = fn()
        _ = int(out[0, 0])
        return time.perf_counter() - t0

    run(2)
    t_short = min(run(2) for _ in range(3))
    t_long = min(run(2 + n_long) for _ in range(3))
    return (t_long - t_short) / n_long


def k_copy(new16_ref, slot_ref, bkt_ref, in_ref, out_ref):
    out_ref[:] = in_ref[:]


def k_cur(new16_ref, slot_ref, bkt_ref, in_ref, out_ref):
    blk_rows = in_ref[:]
    new16 = new16_ref[:]
    slot = slot_ref[:]
    lb = bkt_ref[:]
    B_, U_ = blk_rows.shape[0], new16.shape[0]
    lane_slot = jax.lax.broadcasted_iota(i32, (U_, ROW), 1) // F
    upd = jnp.concatenate([new16] * K, axis=1)
    msk = (lane_slot == slot).astype(jnp.int8)
    iot = jax.lax.broadcasted_iota(i32, (B_, U_), 0)
    onehot = (iot == lb[:, 0][None, :]).astype(jnp.int8)
    written = jax.lax.dot_general(
        onehot, msk, (((1,), (0,)), ((), ())), preferred_element_type=i32
    )
    acc = None
    for s in range(4):
        plane = (((upd >> (8 * s)) & 0xFF) * msk.astype(i32)).astype(jnp.int8)
        p = jax.lax.dot_general(
            onehot, plane, (((1,), (0,)), ((), ())), preferred_element_type=i32
        )
        p = (p & 0xFF) << (8 * s)
        acc = p if acc is None else acc | p
    out_ref[:] = jnp.where(written > 0, acc, blk_rows)


def k_f32x2(new16_ref, slot_ref, bkt_ref, in_ref, out_ref):
    blk_rows = in_ref[:]
    new16 = new16_ref[:]
    slot = slot_ref[:]
    lb = bkt_ref[:]
    B_, U_ = blk_rows.shape[0], new16.shape[0]
    lane_slot = jax.lax.broadcasted_iota(i32, (U_, ROW), 1) // F
    upd = jnp.concatenate([new16] * K, axis=1)
    mskb = lane_slot == slot
    msk = mskb.astype(jnp.float32)
    iot = jax.lax.broadcasted_iota(i32, (B_, U_), 0)
    onehot = (iot == lb[:, 0][None, :]).astype(jnp.float32)
    lo = ((upd & 0xFFFF).astype(jnp.float32)) * msk
    hi = (((upd >> 16) & 0xFFFF).astype(jnp.float32)) * msk
    dot = partial(
        jax.lax.dot_general,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    w = dot(onehot, msk)
    plo = dot(onehot, lo).astype(i32)
    phi = dot(onehot, hi).astype(i32)
    acc = plo | (phi << 16)
    out_ref[:] = jnp.where(w > 0, acc, blk_rows)


def k_i8acc(new16_ref, slot_ref, bkt_ref, in_ref, out_ref):
    blk_rows = in_ref[:]
    new16 = new16_ref[:]
    slot = slot_ref[:]
    lb = bkt_ref[:]
    B_, U_ = blk_rows.shape[0], new16.shape[0]
    # payload as bytes: (U, 512) int8, lane l -> field (l//4)%16... we build
    # byte planes interleaved via shifts on the narrow side then bitcast wide
    lane_slot = jax.lax.broadcasted_iota(i32, (U_, ROW), 1) // F
    upd = jnp.concatenate([new16] * K, axis=1)
    mskb = lane_slot == slot
    # bytes: (U, 128, 4) -> (U, 512)
    b0 = (upd & 0xFF).astype(jnp.uint8)
    b1 = ((upd >> 8) & 0xFF).astype(jnp.uint8)
    b2 = ((upd >> 16) & 0xFF).astype(jnp.uint8)
    b3 = ((upd >> 24) & 0xFF).astype(jnp.uint8)
    bytes_ = jnp.stack([b0, b1, b2, b3], axis=2).reshape(U_, ROW * 4)
    bytes_ = jnp.where(
        jnp.repeat(mskb, 4, axis=1), bytes_, jnp.uint8(0)
    ).astype(jnp.int8)
    iot = jax.lax.broadcasted_iota(i32, (B_, U_), 0)
    onehot = (iot == lb[:, 0][None, :]).astype(jnp.int8)
    msk = mskb.astype(jnp.int8)
    w = jax.lax.dot_general(
        onehot, msk, (((1,), (0,)), ((), ())), preferred_element_type=i32
    )
    acc8 = jax.lax.dot_general(
        onehot, bytes_, (((1,), (0,)), ((), ())), preferred_element_type=i32
    )
    # reassemble from the int32 accumulators of byte lanes
    acc8 = acc8.reshape(B_, ROW, 4)
    acc = (
        (acc8[:, :, 0] & 0xFF)
        | ((acc8[:, :, 1] & 0xFF) << 8)
        | ((acc8[:, :, 2] & 0xFF) << 16)
        | ((acc8[:, :, 3] & 0xFF) << 24)
    )
    out_ref[:] = jnp.where(w > 0, acc, blk_rows)


def build(kernel):
    def run(wnew, wslot, wlb, rows):
        with jax.enable_x64(False):
            return pl.pallas_call(
                kernel,
                out_shape=jax.ShapeDtypeStruct(rows.shape, rows.dtype),
                grid=(NBLK,),
                in_specs=[
                    pl.BlockSpec((U, F), lambda i: (i, 0)),
                    pl.BlockSpec((U, 1), lambda i: (i, 0)),
                    pl.BlockSpec((U, 1), lambda i: (i, 0)),
                    pl.BlockSpec((BLK, ROW), lambda i: (i, 0)),
                ],
                out_specs=pl.BlockSpec((BLK, ROW), lambda i: (i, 0)),
                input_output_aliases={3: 0},
            )(wnew, wslot, wlb, rows)

    return jax.jit(run)


def main():
    rng = np.random.default_rng(3)
    rows = jax.device_put(
        jnp.asarray(rng.integers(0, 1 << 30, size=(NB, ROW), dtype=np.int32))
    )
    wnew = jax.device_put(
        jnp.asarray(
            rng.integers(-(1 << 31), 1 << 31, size=(NBLK * U, F), dtype=np.int64
                         ).astype(np.int32)
        )
    )
    wslot = jax.device_put(
        jnp.asarray(rng.integers(0, K, size=(NBLK * U, 1), dtype=np.int64).astype(np.int32))
    )
    # ~half the window live, unique local buckets per block
    lb = np.full((NBLK, U), -1, dtype=np.int32)
    for i in range(U // 2):
        lb[:, i] = rng.integers(0, BLK)
    wlb = jax.device_put(jnp.asarray(lb.reshape(-1, 1)))

    for name, kern in [
        ("copy", k_copy),
        ("cur", k_cur),
        ("f32x2", k_f32x2),
        ("i8acc", k_i8acc),
    ]:
        try:
            fn = build(kern)
            state = {"rows": rows}

            def step():
                # aliasing donates the table; rebind like the engine does
                state["rows"] = fn(wnew, wslot, wlb, state["rows"])
                return state["rows"]

            t = slope(step)
            log(f"{name:8s}: {t * 1e3:7.2f} ms")
            rows = state["rows"]
        except Exception as exc:
            log(f"{name:8s}: FAILED {type(exc).__name__}: {str(exc)[:200]}")


if __name__ == "__main__":
    main()
