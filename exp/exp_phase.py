"""Phase breakdown of decide2 on the real TPU at headline scale.

Times (slope method — pipelined dispatches between two run lengths, so tunnel
RTT cancels): full decide2, probe+claim only, row-gather only, sweep write
only, xla write variant, and a decide2 variant with the write disabled.
"""

import sys
import time

import numpy as np

import gubernator_tpu  # noqa: F401
import jax
import jax.numpy as jnp

from gubernator_tpu.ops import kernel2 as k2
from gubernator_tpu.ops.batch import ReqBatch
from gubernator_tpu.ops.table2 import new_table2

CAP = 1 << 24  # 16.7M slots → NB = 2M buckets
BATCH = 1 << 17
LIVE = 10_000_000


def log(m):
    print(m, file=sys.stderr, flush=True)


def slope(fn, fetch, n_long=24):
    fn()  # compile
    fetch(fn())

    def run(k):
        t0 = time.perf_counter()
        out = None
        for _ in range(k):
            out = fn()
        fetch(out)
        return time.perf_counter() - t0

    run(2)
    t_short = min(run(2) for _ in range(3))
    t_long = min(run(2 + n_long) for _ in range(3))
    return (t_long - t_short) / n_long


def main():
    rng = np.random.default_rng(7)
    now = 1_700_000_000_000
    table = new_table2(CAP)
    keyspace = rng.integers(1, (1 << 63) - 1, size=LIVE, dtype=np.int64)

    def mk(fps):
        b = fps.shape[0]
        return ReqBatch(
            fp=jnp.asarray(fps),
            algo=jnp.zeros(b, dtype=jnp.int32),
            behavior=jnp.zeros(b, dtype=jnp.int32),
            hits=jnp.ones(b, dtype=jnp.int64),
            limit=jnp.full(b, 1000, dtype=jnp.int64),
            burst=jnp.zeros(b, dtype=jnp.int64),
            duration=jnp.full(b, 60_000, dtype=jnp.int64),
            created_at=jnp.full(b, now, dtype=jnp.int64),
            expire_new=jnp.full(b, now + 60_000, dtype=jnp.int64),
            greg_interval=jnp.zeros(b, dtype=jnp.int64),
            duration_eff=jnp.full(b, 60_000, dtype=jnp.int64),
            active=jnp.ones(b, dtype=bool),
        )

    # seed all 10M keys
    log("seeding 10M keys...")
    for i in range(LIVE // BATCH):
        table, _, _ = k2.decide2(table, mk(keyspace[i * BATCH : (i + 1) * BATCH]))
    batches = [
        jax.device_put(mk(keyspace[rng.permutation(LIVE)[:BATCH]])) for _ in range(4)
    ]
    state = {"t": table, "i": 0}

    blk, u = k2.sweep_geometry(table.rows.shape[0], BATCH)
    log(f"NB={table.rows.shape[0]} blk={blk} u={u} nblk={table.rows.shape[0]//blk}")

    # --- full decide2 (sweep)
    def full():
        b = batches[state["i"] % 4]
        state["i"] += 1
        state["t"], resp, stats = k2.decide2(state["t"], b, write="sweep", math="token")
        return stats.cache_hits

    log(f"full decide2(sweep): {slope(full, lambda x: int(x)) * 1e3:.2f} ms")

    # --- full decide2 (xla write)
    def full_xla():
        b = batches[state["i"] % 4]
        state["i"] += 1
        state["t"], resp, stats = k2.decide2(state["t"], b, write="xla")
        return stats.cache_hits

    log(f"full decide2(xla):   {slope(full_xla, lambda x: int(x)) * 1e3:.2f} ms")

    tbl_rows = state["t"].rows

    # --- probe+claim only
    @jax.jit
    def probe_only(rows, b):
        c = k2._probe_claim2(rows, b.fp, b.created_at, b.active, blk, u)
        return c.written.sum()

    def probe():
        b = batches[state["i"] % 4]
        state["i"] += 1
        return probe_only(tbl_rows, b)

    log(f"probe+claim only:    {slope(probe, lambda x: int(x)) * 1e3:.2f} ms")

    # --- row gather only
    @jax.jit
    def gather_only(rows, b):
        bucket = (b.fp % rows.shape[0]).astype(jnp.int32)
        return rows[bucket].sum(dtype=jnp.int32)

    def gth():
        b = batches[state["i"] % 4]
        state["i"] += 1
        return gather_only(tbl_rows, b)

    log(f"row gather only:     {slope(gth, lambda x: int(x)) * 1e3:.2f} ms")

    # --- sort machinery only (the 3 sorts without gather)
    @jax.jit
    def sorts_only(rows, b):
        B = b.fp.shape[0]
        NB = rows.shape[0]
        bucket = (b.fp % NB).astype(jnp.int32)
        idx = jnp.arange(B, dtype=jnp.int32)
        k1, k2_, i1 = jax.lax.sort((bucket, idx, idx), num_keys=1)
        skey = bucket * 2
        s2, i2 = jax.lax.sort((skey, idx), num_keys=1)
        _, i3 = jax.lax.sort((i2, i1), num_keys=1)
        return (k1[-1] + s2[-1] + i3[-1]).astype(jnp.int32)

    def srt():
        b = batches[state["i"] % 4]
        state["i"] += 1
        return sorts_only(tbl_rows, b)

    log(f"3x sort only:        {slope(srt, lambda x: int(x)) * 1e3:.2f} ms")

    # --- sweep write only (fixed claim from one probe)
    b0 = batches[0]
    c0 = jax.jit(
        lambda rows, b: k2._probe_claim2(rows, b.fp, b.created_at, b.active, blk, u)
    )(tbl_rows, b0)
    new16 = jnp.zeros((BATCH, 16), dtype=jnp.int32)

    @jax.jit
    def sweep_only(rows, c):
        return k2._write_sweep(rows, new16, c, blk, u)

    def swp():
        return sweep_only(tbl_rows, c0)

    log(f"sweep write only:    {slope(swp, lambda x: int(x[0, 0])) * 1e3:.2f} ms")

    # --- everything except the write
    def no_write(rows, b):
        table_, resp, stats = k2.decide2_impl(
            k2.Table2(rows=rows) if hasattr(k2, "Table2") else rows, b, write="xla"
        )
        return stats.cache_hits

    from gubernator_tpu.ops.table2 import Table2

    @jax.jit
    def nw(rows, b):
        c = k2._probe_claim2(rows, b.fp, b.created_at, b.active, blk, u)
        lane16 = jnp.take_along_axis(c.slots, c.chosen[:, None, None], axis=1)[:, 0, :]
        g = lambda f: lane16[:, f]
        i64_ = jnp.int64
        s_exp = k2._join64(g(k2.EXP_LO), g(k2.EXP_HI))
        exists = c.owns & (s_exp >= b.created_at)
        s_flags = g(k2.FLAGS)
        from gubernator_tpu.ops.math import StoredState, bucket_math
        f32 = jnp.float32
        f64 = jnp.float64
        stored = StoredState(
            limit=g(k2.LIMIT).astype(i64_), burst=g(k2.BURST).astype(i64_),
            rem_i=g(k2.REM_I).astype(i64_), algo=s_flags & 0xFF,
            status=s_flags >> 8, duration=k2._join64(g(k2.DUR_LO), g(k2.DUR_HI)),
            stamp=k2._join64(g(k2.STAMP_LO), g(k2.STAMP_HI)), exp=s_exp,
            rem_f=jax.lax.bitcast_convert_type(g(k2.REMF_HI), f32).astype(f64)
            + jax.lax.bitcast_convert_type(g(k2.REMF_LO), f32).astype(f64),
        )
        d = bucket_math(stored, b, exists)
        return d.resp_rem.sum() + d.rem_i_out.sum()

    def nwf():
        b = batches[state["i"] % 4]
        state["i"] += 1
        return nw(tbl_rows, b)

    log(f"probe+claim+math:    {slope(nwf, lambda x: int(x)) * 1e3:.2f} ms")


if __name__ == "__main__":
    main()
