"""Does the on-device fori_loop harness (ops/loop.decide_loop) compile and
run the Pallas-sweep kernel on the real TPU, and does its rate agree with
the host-slope at headline geometry?  (Round-5 RTT-immune bench check.)"""
import sys, time
import numpy as np
import gubernator_tpu  # noqa
import jax, jax.numpy as jnp
from bench import Case, make_req_batch

def log(m): print(m, file=sys.stderr, flush=True)

rng = np.random.default_rng(42)
now = int(time.time() * 1000)
log(f"device: {jax.devices()[0]}")
CAP, LIVE, BATCH = 1 << 24, 10_000_000, 1 << 17
keyspace = rng.integers(1, (1 << 63) - 1, size=LIVE, dtype=np.int64)
perm = rng.permutation(LIVE)
batches = [jax.device_put(make_req_batch(keyspace[perm[i*BATCH:(i+1)*BATCH]], now)) for i in range(8)]
c = Case("loop-headline", CAP, batches, math="token")
res = c.run(dispatches=24, latency_probes=6)
log(f"RESULT: {res}")
