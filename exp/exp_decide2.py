"""decide2 perf on real TPU: seeded table, steady-state dispatch timing."""

import sys
import time

sys.path.insert(0, "/root/repo")

import numpy as np

import gubernator_tpu  # noqa: F401
import jax
import jax.numpy as jnp

from gubernator_tpu.ops.batch import ReqBatch
from gubernator_tpu.ops.kernel2 import decide2
from gubernator_tpu.ops.table2 import new_table2
from gubernator_tpu.types import Algorithm

CAPACITY = 1 << 24
LIVE_KEYS = 10_000_000
BATCH = 1 << 17
N_STAGED = 8


def make_batches(rng, now, batch=BATCH):
    keyspace = rng.integers(1, (1 << 63) - 1, size=LIVE_KEYS, dtype=np.int64)
    perm = rng.permutation(LIVE_KEYS)
    batches = []
    zeros = np.zeros(batch, dtype=np.int64)
    for i in range(N_STAGED):
        fps = keyspace[perm[i * batch : (i + 1) * batch]]
        rb = ReqBatch(
            fp=jnp.asarray(fps),
            algo=jnp.full(batch, int(Algorithm.TOKEN_BUCKET), dtype=jnp.int32),
            behavior=jnp.zeros(batch, dtype=jnp.int32),
            hits=jnp.ones(batch, dtype=jnp.int64),
            limit=jnp.full(batch, 1000, dtype=jnp.int64),
            burst=jnp.asarray(zeros),
            duration=jnp.full(batch, 60_000, dtype=jnp.int64),
            created_at=jnp.full(batch, now, dtype=jnp.int64),
            expire_new=jnp.full(batch, now + 60_000, dtype=jnp.int64),
            greg_interval=jnp.asarray(zeros),
            duration_eff=jnp.full(batch, 60_000, dtype=jnp.int64),
            active=jnp.ones(batch, dtype=bool),
        )
        batches.append(jax.device_put(rb))
    return batches


def main():
    print(f"device: {jax.devices()[0]}", file=sys.stderr)
    now = 1_700_000_000_000
    rng = np.random.default_rng(42)
    table = new_table2(CAPACITY)
    print(f"table: {table.rows.shape} = {table.rows.size*4/2**30:.2f} GiB", file=sys.stderr)
    batches = make_batches(rng, now)

    t0 = time.perf_counter()
    for i in range(3):
        table, resp, stats = decide2(table, batches[i % N_STAGED], write="sweep")
    _ = int(stats.cache_hits)
    print(f"compile+warmup: {time.perf_counter()-t0:.1f}s", file=sys.stderr)

    # seed all staged batches (≈1M live keys… seed full 10M via more batches)
    seed_reps = LIVE_KEYS // (N_STAGED * BATCH) + 1
    # reuse the 8 staged batches only — keys repeat, fine for perf measurement
    for b in batches:
        table, resp, stats = decide2(table, b, write="sweep")
    _ = int(stats.cache_hits)

    def run(n):
        nonlocal table
        t0 = time.perf_counter()
        stats = None
        for i in range(n):
            table, resp, stats = decide2(table, batches[i % N_STAGED], write="sweep")
        _ = int(stats.cache_hits)
        return time.perf_counter() - t0

    run(2)
    ts = min(run(4) for _ in range(3))
    tl = min(run(52) for _ in range(3))
    dt = tl - ts
    dps = 48 * BATCH / dt
    print(
        f"steady state: 48 x {BATCH} in {dt:.3f}s = {dps/1e6:.2f}M decisions/s "
        f"({dt/48*1e3:.2f} ms/dispatch)", file=sys.stderr,
    )
    print(f"hits={int(stats.cache_hits)} miss={int(stats.cache_misses)} dropped={int(stats.dropped)}", file=sys.stderr)
    print(f"vs per-chip baseline (6.25M/s): {dps/6.25e6:.2f}x", file=sys.stderr)


if __name__ == "__main__":
    main()
