"""Sweep-write optimization matrix (real TPU).

exp_phase.py shows the sweep write is 8.15 ms of the 10.86 ms headline
dispatch and is MXU-bound, not DMA-bound: per block the kernel runs 10 one-hot
int8 matmuls (2 halves x [1 mask dot + 4 byte-plane dots]) of
(blk, u) @ (u, 128) — total MACs = NB * u * 1280, ~687G at headline geometry
(u=256) vs a ~2.6 ms DMA floor for the 2 GiB of table traffic.

Variants measured here (write-only, slope-timed):
  base      current production geometry/kernel (blk=2048, u=256)
  geom      smaller update window u via tighter tail bound (MACs ~ u)
  marker    payload field 15 carries a 1-marker; the lane mask is derived
            from the composed payload instead of a separate mask dot (5->4)
  skip2     pl.when-gate the second half on "this block's run actually
            crosses its first window" (scalar-prefetched per block)
  all       geom + marker + skip2
"""

import functools
import sys
import time

sys.path.insert(0, "/root/repo")

import numpy as np

import gubernator_tpu  # noqa: F401
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gubernator_tpu.ops.table2 import F, K, ROW, new_table2

i32 = jnp.int32


def log(m):
    print(m, file=sys.stderr, flush=True)


def make_kernel(nwin: int, blk: int, u: int, marker: bool, skip2: bool):
    KBLK = K * blk

    def kern(s_ref, n2_ref, p1, p2, t1, t2, tbl_in, tbl_out):
        i = pl.program_id(0)
        blk_base = i * KBLK
        dot = functools.partial(
            jax.lax.dot_general,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=i32,
        )

        def half(pay_ref, tgt_ref, valid):
            pay = pay_ref[:]
            tgt = tgt_ref[:]
            rel = tgt - blk_base
            live = (rel >= 0) & (rel < KBLK) & valid
            slot = jnp.where(live, rel % K, -1)
            lb = jnp.where(live, rel // K, -1)
            lane_slot = jax.lax.broadcasted_iota(i32, (u, ROW), 1) // F
            upd = jnp.concatenate([pay] * K, axis=1)
            msk = (lane_slot == slot).astype(jnp.int8)
            iot = jax.lax.broadcasted_iota(i32, (blk, u), 0)
            onehot = (iot == lb[:, 0][None, :]).astype(jnp.int8)
            acc = None
            for s in range(4):
                plane = (((upd >> (8 * s)) & 0xFF) * msk.astype(i32)).astype(
                    jnp.int8
                )
                p = dot(onehot, plane)
                p = (p & 0xFF) << (8 * s)
                acc = p if acc is None else acc | p
            if marker:
                # lane mask from the composed marker field (payload[:, 15]
                # = 1 on every written row): slot s received an update iff
                # acc[:, s*F + 15] != 0; broadcast over the slot's F lanes
                m = acc.reshape(blk, K, F)[:, :, 15]  # (blk, K)
                w = jnp.repeat(m, F, axis=1)  # (blk, 128)
            else:
                w = dot(onehot, msk)
            return acc, w

        if skip2:
            need2 = n2_ref[i] != 0

            @pl.when(need2)
            def _():
                a1, w1 = half(p1, t1, True)
                a2, w2 = half(p2, t2, True)
                tbl_out[:] = jnp.where(w1 + w2 > 0, a1 | a2, tbl_in[:])

            @pl.when(jnp.logical_not(need2))
            def _():
                a1, w1 = half(p1, t1, True)
                tbl_out[:] = jnp.where(w1 > 0, a1, tbl_in[:])
        else:
            second_ok = s_ref[i] + 1 <= nwin - 1
            a1, w1 = half(p1, t1, True)
            a2, w2 = half(p2, t2, second_ok)
            tbl_out[:] = jnp.where(w1 + w2 > 0, a1 | a2, tbl_in[:])

    return kern


def sweep_call(rows_tbl, pay_s, tgt_eff, blk, u, marker, skip2):
    NB = rows_tbl.shape[0]
    B = pay_s.shape[0]
    nblk = NB // blk
    nwin = B // u
    starts = jnp.searchsorted(
        tgt_eff[:, 0], (jnp.arange(nblk, dtype=i32) * (K * blk)).astype(i32)
    ).astype(i32)
    ends = jnp.concatenate([starts[1:], jnp.full((1,), B, dtype=i32)])
    s_blk = jnp.clip(starts // u, 0, nwin - 1)
    need2 = (ends > (s_blk + 1) * u).astype(i32)

    second = lambda i, s, n2: (jnp.minimum(s[i] + 1, nwin - 1), 0)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec((u, F), lambda i, s, n2: (s[i], 0)),
            pl.BlockSpec((u, F), second),
            pl.BlockSpec((u, 1), lambda i, s, n2: (s[i], 0)),
            pl.BlockSpec((u, 1), second),
            pl.BlockSpec((blk, ROW), lambda i, s, n2: (i, 0)),
        ],
        out_specs=pl.BlockSpec((blk, ROW), lambda i, s, n2: (i, 0)),
    )
    with jax.enable_x64(False):
        out = pl.pallas_call(
            make_kernel(nwin, blk, u, marker, skip2),
            out_shape=jax.ShapeDtypeStruct(rows_tbl.shape, rows_tbl.dtype),
            grid_spec=grid_spec,
            input_output_aliases={6: 0},
        )(s_blk, need2, pay_s, pay_s, tgt_eff, tgt_eff, rows_tbl)
    return out


def slope(fn, n_long=24):
    out = fn()
    _ = np.asarray(out[0, :1])

    def run(k):
        t0 = time.perf_counter()
        o = None
        for _ in range(k):
            o = fn()
        _ = np.asarray(o[0, :1])
        return time.perf_counter() - t0

    run(2)
    t_short = min(run(2) for _ in range(3))
    t_long = min(run(2 + n_long) for _ in range(3))
    return (t_long - t_short) / n_long


def case(name, NB, B, blk, u, marker, skip2, rng):
    # fabricated sorted unique targets + payload (content is irrelevant to
    # speed; uniqueness + sortedness match the claim contract)
    tgt = np.sort(
        rng.choice(NB * K, size=B, replace=False).astype(np.int32)
    )[:, None]
    pay = rng.integers(-(2**31), 2**31 - 1, size=(B, F), dtype=np.int64).astype(
        np.int32
    )
    if marker:
        pay[:, 15] = 1
    rows = jnp.zeros((NB, ROW), dtype=jnp.int32)
    payd = jnp.asarray(pay)
    tgtd = jnp.asarray(tgt)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(rows):
        return sweep_call(rows, payd, tgtd, blk, u, marker, skip2)

    cell = [rows]

    def fn():
        cell[0] = step(cell[0])
        return cell[0]

    dt = slope(fn)
    nwin = B // u
    log(
        f"[{name}] NB={NB} B={B} blk={blk} u={u} nwin={nwin} "
        f"marker={marker} skip2={skip2}: {dt*1e3:.2f} ms"
    )
    return dt


def main():
    rng = np.random.default_rng(3)
    NB, B = 1 << 21, 1 << 17  # headline: 2M bucket rows (1 GiB), 131K updates
    log(f"device: {jax.devices()[0]}")
    import os

    which = os.environ.get("SWEEP5_CASES", "skip2-2048-256,all-1024-128")
    matrix = {
        "base": (NB, B, 2048, 256, False, False),
        "geom-1024-128": (NB, B, 1024, 128, False, False),
        "geom-512-64": (NB, B, 512, 64, False, False),
        "skip2-2048-256": (NB, B, 2048, 256, False, True),
        "all-1024-128": (NB, B, 1024, 128, False, True),
        "all-512-64": (NB, B, 512, 64, False, True),
        # config5 scale: 16.7M bucket rows (8 GiB), 1M updates
        "c5-base": (1 << 24, 1 << 20, 2048, 256, False, False),
        "c5-all-1024-128": (1 << 24, 1 << 20, 1024, 128, False, True),
        "c5-all-512-64": (1 << 24, 1 << 20, 512, 64, False, True),
    }
    for name in which.split(","):
        try:
            nb, b, blk, u, marker, skip2 = matrix[name.strip()]
            case(name.strip(), nb, b, blk, u, marker, skip2, rng)
        except Exception as e:
            log(f"[{name}] FAILED: {type(e).__name__}: {e}")


if __name__ == "__main__":
    main()
