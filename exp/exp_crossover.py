"""Calibrate engine._write_mode_for: sweep (cost ∝ table) vs XLA scatter
(cost ∝ batch) at serving-size batches on the headline 1 GiB table."""

import sys
import time

sys.path.insert(0, "/root/repo")

import numpy as np

import gubernator_tpu  # noqa: F401
import jax

from gubernator_tpu.ops import kernel2 as k2
from gubernator_tpu.ops.batch import ReqBatch
from gubernator_tpu.ops.table2 import new_table2
import jax.numpy as jnp


def log(m):
    print(m, file=sys.stderr, flush=True)


def mk(fps, now):
    b = fps.shape[0]
    z = jnp.zeros(b, dtype=jnp.int64)
    return ReqBatch(
        fp=jnp.asarray(fps), algo=jnp.zeros(b, dtype=jnp.int32),
        behavior=jnp.zeros(b, dtype=jnp.int32), hits=jnp.ones(b, dtype=jnp.int64),
        limit=jnp.full(b, 1 << 20, dtype=jnp.int64), burst=z,
        duration=jnp.full(b, 3_600_000, dtype=jnp.int64),
        created_at=jnp.full(b, now, dtype=jnp.int64),
        expire_new=jnp.full(b, now + 3_600_000, dtype=jnp.int64),
        greg_interval=z, duration_eff=jnp.full(b, 3_600_000, dtype=jnp.int64),
        active=jnp.ones(b, dtype=bool),
    )


def slope(fn, n_long=48):
    fn()

    def run(k):
        t0 = time.perf_counter()
        s = None
        for _ in range(k):
            s = fn()
        _ = int(s)
        return time.perf_counter() - t0

    run(2)
    t_s = min(run(2) for _ in range(3))
    t_l = min(run(2 + n_long) for _ in range(3))
    return (t_l - t_s) / n_long


def main():
    rng = np.random.default_rng(5)
    now = 1_700_000_000_000
    CAP = 1 << 24  # 1 GiB table, NB=2M rows
    LIVE = 2_000_000
    keyspace = rng.integers(1, (1 << 63) - 1, size=LIVE, dtype=np.int64)
    state = {}
    for write in ("sweep", "xla"):
        table = new_table2(CAP)
        for i in range(0, LIVE, 1 << 17):
            table, _, s = k2.decide2(table, mk(keyspace[i : i + (1 << 17)], now),
                                     write="sweep")
        _ = int(s.cache_hits)
        state[write] = table
    for B in (2048, 4096, 8192, 16384):
        batches = []
        for _ in range(4):
            draw = np.unique(keyspace[rng.integers(0, LIVE, size=2 * B)])
            assert draw.shape[0] >= B
            batches.append(jax.device_put(mk(rng.permutation(draw)[:B], now)))
        for write in ("sweep", "xla"):
            tb = {"t": state[write], "i": 0}

            def fn():
                b = batches[tb["i"] % 4]
                tb["i"] += 1
                tb["t"], _, s = k2.decide2(tb["t"], b, write=write, math="token")
                return s.cache_hits

            dt = slope(fn)
            state[write] = tb["t"]
            log(f"B={B:6d} write={write:5s}: {dt*1e3:7.3f} ms/dispatch")


if __name__ == "__main__":
    main()
