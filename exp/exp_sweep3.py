"""Decompose _write_sweep cost: routing-only vs pallas-call vs donation."""

import sys
import time
from functools import partial

import numpy as np

import gubernator_tpu  # noqa: F401
import jax
import jax.numpy as jnp

from gubernator_tpu.ops import kernel2 as k2
from gubernator_tpu.ops.batch import ReqBatch
from gubernator_tpu.ops.table2 import new_table2

CAP = 1 << 24
BATCH = 1 << 17


def log(m):
    print(m, file=sys.stderr, flush=True)


def slope(fn, fetch, n_long=16):
    fn()
    fetch(fn())

    def run(k):
        t0 = time.perf_counter()
        out = None
        for _ in range(k):
            out = fn()
        fetch(out)
        return time.perf_counter() - t0

    run(2)
    t_short = min(run(2) for _ in range(3))
    t_long = min(run(2 + n_long) for _ in range(3))
    return (t_long - t_short) / n_long


def main():
    rng = np.random.default_rng(7)
    now = 1_700_000_000_000
    table = new_table2(CAP)
    NB = table.rows.shape[0]
    blk, u = k2.sweep_geometry(NB, BATCH)
    fps = rng.integers(1, (1 << 63) - 1, size=BATCH, dtype=np.int64)
    b = ReqBatch(
        fp=jnp.asarray(fps),
        algo=jnp.zeros(BATCH, dtype=jnp.int32),
        behavior=jnp.zeros(BATCH, dtype=jnp.int32),
        hits=jnp.ones(BATCH, dtype=jnp.int64),
        limit=jnp.full(BATCH, 1000, dtype=jnp.int64),
        burst=jnp.zeros(BATCH, dtype=jnp.int64),
        duration=jnp.full(BATCH, 60_000, dtype=jnp.int64),
        created_at=jnp.full(BATCH, now, dtype=jnp.int64),
        expire_new=jnp.full(BATCH, now + 60_000, dtype=jnp.int64),
        greg_interval=jnp.zeros(BATCH, dtype=jnp.int64),
        duration_eff=jnp.full(BATCH, 60_000, dtype=jnp.int64),
        active=jnp.ones(BATCH, dtype=bool),
    )
    c0 = jax.jit(
        lambda rows, bb: k2._probe_claim2(rows, bb.fp, bb.created_at, bb.active, blk, u)
    )(table.rows, b)
    c0 = jax.tree.map(jax.device_put, c0)
    new16 = jax.device_put(jnp.zeros((BATCH, 16), dtype=jnp.int32))

    # routing only (everything in _write_sweep before the pallas_call)
    @jax.jit
    def routing(c, n16):
        nblk = NB // blk
        starts = jnp.searchsorted(
            c.tgt_sorted, (jnp.arange(nblk, dtype=jnp.int32) * (k2.K * blk)).astype(jnp.int32)
        ).astype(jnp.int32)
        win = (starts[:, None] + jnp.arange(u, dtype=jnp.int32)[None, :]).reshape(-1)
        win_valid = win < BATCH
        winc = jnp.clip(win, 0, BATCH - 1)
        data_idx = c.order[winc]
        tgt_w = c.tgt_sorted[winc]
        blk_ids = jnp.repeat(jnp.arange(nblk, dtype=jnp.int32), u)
        in_block = (tgt_w // jnp.int32(k2.K * blk)) == blk_ids
        livew = win_valid & in_block & c.written[data_idx]
        wnew = n16[data_idx] * livew[:, None].astype(jnp.int32)
        wslot = jnp.where(livew, tgt_w % k2.K, -1).astype(jnp.int32)
        wlb = jnp.where(livew, (tgt_w // k2.K) - blk_ids * blk, -1).astype(jnp.int32)
        return wnew.sum() + wslot.sum() + wlb.sum()

    log(f"routing only:            {slope(lambda: routing(c0, new16), lambda x: int(x)) * 1e3:.2f} ms")

    # full _write_sweep WITHOUT donation (what exp_phase measured)
    f_nodon = jax.jit(lambda rows, c: k2._write_sweep(rows, new16, c, blk, u))
    log(f"_write_sweep (no donate): {slope(lambda: f_nodon(table.rows, c0), lambda x: int(x[0, 0])) * 1e3:.2f} ms")

    # full _write_sweep WITH donation (what decide2 effectively gets)
    f_don = jax.jit(
        lambda rows, c: k2._write_sweep(rows, new16, c, blk, u), donate_argnums=(0,)
    )
    state = {"rows": table.rows}

    def step():
        state["rows"] = f_don(state["rows"], c0)
        return state["rows"]

    log(f"_write_sweep (donated):   {slope(step, lambda x: int(x[0, 0])) * 1e3:.2f} ms")


if __name__ == "__main__":
    main()
