"""Device-only compute time at SERVING dispatch shapes (16K-row coalesced
batches) for the README's co-located p99 budget: 128 MiB table (1M keys)
and 1 GiB table (10M keys). Device-loop timing — RTT-immune."""
import sys, time
import numpy as np
import gubernator_tpu  # noqa
import jax
from bench import Case, make_req_batch

def log(m): print(m, file=sys.stderr, flush=True)

rng = np.random.default_rng(42)
now = int(time.time() * 1000)
log(f"device: {jax.devices()[0]}")
BATCH = 1 << 14
for cap, live, tag in ((1 << 21, 1_000_000, "128MiB-1M"), ((1 << 24), 10_000_000, "1GiB-10M")):
    keyspace = rng.integers(1, (1 << 63) - 1, size=live, dtype=np.int64)
    perm = rng.permutation(live)
    nb = 8
    batches = [jax.device_put(make_req_batch(keyspace[perm[i*BATCH:(i+1)*BATCH]], now)) for i in range(nb)]
    seed = [jax.device_put(make_req_batch(keyspace[i*BATCH:(i+1)*BATCH], now)) for i in range(live // BATCH)]
    c = Case(f"serve-{tag}", cap, batches, seed_batches=seed, math="token")
    res = c.run(dispatches=8, latency_probes=2)
    log(f"RESULT {tag}: device_ms={res.get('device_ms')} dec/s={res.get('device_decisions_per_sec')}")
    del c, batches, seed
