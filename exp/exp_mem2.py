"""Round 2 microbenchmarks: validate the decide_v2 design on real TPU.

1. (B,128) row gather from (NB,128) i32/f32 — the fused probe+apply fetch
2. XLA sort of B i64 keys (+payload) — the claim-by-rank prerequisite
3. Pallas sweep skeleton: DMA-only pass over the whole (NB,128) table
4. Pallas sweep with int8 one-hot matmul scatter of updates
"""

import time
import sys

sys.path.insert(0, "/root/repo")

import numpy as np

import gubernator_tpu  # noqa: F401 (x64 on)
import jax
import jax.numpy as jnp
from functools import partial
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NB = 1 << 21  # 2M buckets (= 16.7M slots at K=8)
ROW = 128  # 8 slots x 16 fields
B = 1 << 17

rng = np.random.default_rng(0)
buckets_np = rng.integers(0, NB, size=B).astype(np.int64)


def timed(name, fn, *args, n_long=24, n_short=4):
    out = fn(*args)
    _ = np.asarray(jax.tree.leaves(out)[0].reshape(-1)[0])

    def run(n):
        t0 = time.perf_counter()
        o = None
        for i in range(n):
            o = fn(*args)
        _ = np.asarray(jax.tree.leaves(o)[0].reshape(-1)[0])
        return time.perf_counter() - t0

    run(2)
    ts = min(run(n_short) for _ in range(2))
    tl = min(run(n_long) for _ in range(2))
    ms = (tl - ts) / (n_long - n_short) * 1e3
    print(f"{name:55s} {ms:8.2f} ms", file=sys.stderr, flush=True)
    return ms


def main():
    print(f"device: {jax.devices()[0]}", file=sys.stderr)
    tbl_i32 = jnp.zeros((NB, ROW), dtype=jnp.int32)
    tbl_f32 = jnp.zeros((NB, ROW), dtype=jnp.float32)
    buckets = jnp.asarray(buckets_np)
    buckets32 = jnp.asarray(buckets_np.astype(np.int32))
    keys = jnp.asarray(rng.integers(1, 1 << 62, size=B, dtype=np.int64))
    keys32pair = (jnp.asarray(buckets_np.astype(np.int32)), jnp.asarray(np.arange(B, dtype=np.int32)))

    @jax.jit
    def g_i32(t, b):
        return t[b]

    timed("G1: (B,128) i32 row gather from (2M,128)", g_i32, tbl_i32, buckets)
    timed("G2: (B,128) f32 row gather from (2M,128)", g_i32, tbl_f32, buckets)
    timed("G3: same, i32 idx", g_i32, tbl_i32, buckets32)

    @jax.jit
    def g_take(t, b):
        return jnp.take(t, b, axis=0)

    timed("G4: jnp.take rows", g_take, tbl_i32, buckets)

    # sort experiments
    @jax.jit
    def sort_i64(k):
        return jnp.sort(k)

    timed("S1: sort B i64 keys", sort_i64, keys)

    @jax.jit
    def argsort_i64(k):
        return jnp.argsort(k)

    timed("S2: argsort B i64 keys", argsort_i64, keys)

    @jax.jit
    def sort_pair32(kv):
        k, v = kv
        return jax.lax.sort((k, v), num_keys=1)

    timed("S3: lax.sort (i32 key, i32 payload)", sort_pair32, keys32pair)

    with jax.enable_x64(False):
        # pallas sweep skeleton: copy table through VMEM, blockwise
        BLK = 2048  # bucket rows per block → (2048, 128) i32 = 1MB

        def copy_kernel(in_ref, out_ref):
            out_ref[:] = in_ref[:]

        @jax.jit
        def sweep_copy(t):
            return pl.pallas_call(
                copy_kernel,
                out_shape=jax.ShapeDtypeStruct(t.shape, t.dtype),
                grid=(NB // BLK,),
                in_specs=[pl.BlockSpec((BLK, ROW), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((BLK, ROW), lambda i: (i, 0)),
            )(t)

        timed("P1: pallas sweep copy (2M,128) i32 blocks=1MB", sweep_copy, tbl_i32)

        # pallas sweep + int8 one-hot matmul scatter
        U = 64  # updates per block window

        upd_rows = jnp.zeros((NB // BLK * U, ROW), dtype=jnp.int32)  # payload rows
        upd_mask = jnp.zeros((NB // BLK * U, ROW), dtype=jnp.int8)  # lane masks
        upd_bucket = jnp.tile(jnp.arange(U, dtype=jnp.int32), NB // BLK)  # local bucket ids

        def scat_kernel(rows_ref, mask_ref, bkt_ref, in_ref, out_ref):
            blk = in_ref[:]  # (BLK, ROW) i32
            rows = rows_ref[:]  # (U, ROW) i32
            mask = mask_ref[:]  # (U, ROW) i8
            bkt = bkt_ref[:]  # (U, 1) i32 local bucket row of each update
            U_loc = rows.shape[0]
            # one-hot (BLK, U) int8
            iot = jax.lax.broadcasted_iota(jnp.int32, (BLK, U_loc), 0)
            onehot = (iot == bkt[:, 0][None, :]).astype(jnp.int8)
            # mask matmul: which (row, lane) positions are written
            written = jax.lax.dot_general(
                onehot, mask, (((1,), (0,)), ((), ())), preferred_element_type=jnp.int32
            )
            # payload: 4x i8 planes matmul
            acc = []
            for s in range(4):
                plane = ((rows >> (8 * s)) & 0xFF).astype(jnp.int8)
                p = jax.lax.dot_general(
                    onehot, plane, (((1,), (0,)), ((), ())), preferred_element_type=jnp.int32
                )
                acc.append(p << (8 * s))
            scat = acc[0] | acc[1] | acc[2] | acc[3]
            out_ref[:] = jnp.where(written > 0, scat, blk)

        @jax.jit
        def sweep_scatter(t, rows, mask, bkt):
            return pl.pallas_call(
                scat_kernel,
                out_shape=jax.ShapeDtypeStruct(t.shape, t.dtype),
                grid=(NB // BLK,),
                in_specs=[
                    pl.BlockSpec((U, ROW), lambda i: (i, 0)),
                    pl.BlockSpec((U, ROW), lambda i: (i, 0)),
                    pl.BlockSpec((U, 1), lambda i: (i, 0)),
                    pl.BlockSpec((BLK, ROW), lambda i: (i, 0)),
                ],
                out_specs=pl.BlockSpec((BLK, ROW), lambda i: (i, 0)),
            )(rows, mask, bkt.reshape(-1, 1), t)

        timed(
            "P2: pallas sweep + i8 onehot matmul scatter U=64",
            sweep_scatter, tbl_i32, upd_rows, upd_mask, upd_bucket,
        )

        # P3: same with U=16 (B=131k over 1024 blocks → avg 16/2048-bucket block... actually 131k/1024=128)
        # try BLK=2048, U=128: matches B=131k uniform on 2M buckets → 131k/1024 blocks = 128/blk
        U2 = 128
        upd_rows2 = jnp.zeros((NB // BLK * U2, ROW), dtype=jnp.int32)
        upd_mask2 = jnp.zeros((NB // BLK * U2, ROW), dtype=jnp.int8)
        upd_bucket2 = jnp.tile(jnp.arange(U2, dtype=jnp.int32), NB // BLK)

        def scat_kernel2(rows_ref, mask_ref, bkt_ref, in_ref, out_ref):
            scat_kernel(rows_ref, mask_ref, bkt_ref, in_ref, out_ref)

        @jax.jit
        def sweep_scatter2(t, rows, mask, bkt):
            return pl.pallas_call(
                scat_kernel2,
                out_shape=jax.ShapeDtypeStruct(t.shape, t.dtype),
                grid=(NB // BLK,),
                in_specs=[
                    pl.BlockSpec((U2, ROW), lambda i: (i, 0)),
                    pl.BlockSpec((U2, ROW), lambda i: (i, 0)),
                    pl.BlockSpec((U2, 1), lambda i: (i, 0)),
                    pl.BlockSpec((BLK, ROW), lambda i: (i, 0)),
                ],
                out_specs=pl.BlockSpec((BLK, ROW), lambda i: (i, 0)),
            )(rows, mask, bkt.reshape(-1, 1), t)

        timed(
            "P3: pallas sweep + i8 onehot matmul scatter U=128",
            sweep_scatter2, tbl_i32, upd_rows2, upd_mask2, upd_bucket2,
        )

        # P4: input_output_aliasing (donate table) — avoids one allocation
        @partial(jax.jit, donate_argnums=0)
        def sweep_scatter_alias(t, rows, mask, bkt):
            return pl.pallas_call(
                scat_kernel,
                out_shape=jax.ShapeDtypeStruct(t.shape, t.dtype),
                grid=(NB // BLK,),
                in_specs=[
                    pl.BlockSpec((U, ROW), lambda i: (i, 0)),
                    pl.BlockSpec((U, ROW), lambda i: (i, 0)),
                    pl.BlockSpec((U, 1), lambda i: (i, 0)),
                    pl.BlockSpec((BLK, ROW), lambda i: (i, 0)),
                ],
                out_specs=pl.BlockSpec((BLK, ROW), lambda i: (i, 0)),
                input_output_aliases={3: 0},
            )(rows, mask, bkt.reshape(-1, 1), t)

        t_alias = jnp.zeros((NB, ROW), dtype=jnp.int32)
        out = sweep_scatter_alias(t_alias, upd_rows, upd_mask, upd_bucket)
        _ = np.asarray(out[0, 0])

        def runA(n):
            nonlocal out
            t0 = time.perf_counter()
            for i in range(n):
                out = sweep_scatter_alias(out, upd_rows, upd_mask, upd_bucket)
            _ = np.asarray(out[0, 0])
            return time.perf_counter() - t0

        runA(2)
        ts = min(runA(4) for _ in range(2))
        tl = min(runA(24) for _ in range(2))
        print(f"{'P4: sweep scatter U=64 + io alias (donated)':55s} {(tl-ts)/20*1e3:8.2f} ms", file=sys.stderr, flush=True)


if __name__ == "__main__":
    main()
