"""Prototype: scalar-prefetch sweep write — windowing moves INTO the kernel.

Current _write_sweep materializes (nblk*u) window gathers host-side (~8 ms at
headline scale — dominates the whole write). Here the updates stay in
target-sorted order; each grid step uses PrefetchScalarGridSpec dynamic block
index maps to DMA the two u-aligned payload blocks covering its run, and
derives slot/lane-mask/liveness in-kernel. Correctness checked against
_write_xla; speed vs the shipping sweep at blk ∈ {2048, 4096, 8192}.
"""

import sys
import time
from functools import partial

import numpy as np

import gubernator_tpu  # noqa: F401
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gubernator_tpu.ops import kernel2 as k2
from gubernator_tpu.ops.table2 import ROW, K, F, new_table2
from gubernator_tpu.ops.batch import ReqBatch

i32 = jnp.int32


def log(m):
    print(m, file=sys.stderr, flush=True)


def make_sweep2(NB, B, blk, u):
    nblk = NB // blk
    nwin = B // u
    KBLK = K * blk

    def kern(s_ref, p1, p2, t1, t2, tbl_in, tbl_out):
        i = pl.program_id(0)
        blk_base = i * KBLK
        dot = partial(
            jax.lax.dot_general,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=i32,
        )

        def half(pay_ref, tgt_ref, valid):
            pay = pay_ref[:]  # (u, F)
            tgt = tgt_ref[:]  # (u, 1)
            rel = tgt - blk_base  # (u, 1)
            live = (rel >= 0) & (rel < KBLK) & valid
            slot = jnp.where(live, rel % K, -1)  # (u, 1)
            lb = jnp.where(live, rel // K, -1)  # (u, 1)
            lane_slot = jax.lax.broadcasted_iota(i32, (u, ROW), 1) // F
            upd = jnp.concatenate([pay] * K, axis=1)  # (u, 128)
            msk = (lane_slot == slot).astype(jnp.int8)
            iot = jax.lax.broadcasted_iota(i32, (blk, u), 0)
            onehot = (iot == lb[:, 0][None, :]).astype(jnp.int8)
            w = dot(onehot, msk)
            acc = None
            for s in range(4):
                plane = (((upd >> (8 * s)) & 0xFF) * msk.astype(i32)).astype(jnp.int8)
                p = dot(onehot, plane)
                p = (p & 0xFF) << (8 * s)
                acc = p if acc is None else acc | p
            return acc, w

        second_ok = s_ref[i] + 1 <= nwin - 1
        acc1, w1 = half(p1, t1, True)
        acc2, w2 = half(p2, t2, second_ok)
        written = w1 + w2
        acc = acc1 | acc2
        tbl_out[:] = jnp.where(written > 0, acc, tbl_in[:])

    def write(rows_tbl, new16, c):
        # device-side prep: ONE payload gather into sorted order + starts
        pay_s = new16[c.order]
        written_s = c.written[c.order]
        NBK = jnp.int32(NB * K)
        tgt_eff = jnp.where(written_s, c.tgt_sorted, NBK).astype(i32)
        starts = jnp.searchsorted(
            c.tgt_sorted, (jnp.arange(nblk, dtype=i32) * KBLK).astype(i32)
        ).astype(i32)
        s_blk = jnp.clip(starts // u, 0, nwin - 1)
        s2 = jnp.minimum(s_blk + 1, nwin - 1)

        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(nblk,),
            in_specs=[
                pl.BlockSpec((u, F), lambda i, s: (s[i], 0)),
                pl.BlockSpec((u, F), lambda i, s: (jnp.minimum(s[i] + 1, nwin - 1), 0)),
                pl.BlockSpec((u, 1), lambda i, s: (s[i], 0)),
                pl.BlockSpec((u, 1), lambda i, s: (jnp.minimum(s[i] + 1, nwin - 1), 0)),
                pl.BlockSpec((blk, ROW), lambda i, s: (i, 0)),
            ],
            out_specs=pl.BlockSpec((blk, ROW), lambda i, s: (i, 0)),
        )
        with jax.enable_x64(False):
            return pl.pallas_call(
                kern,
                out_shape=jax.ShapeDtypeStruct(rows_tbl.shape, rows_tbl.dtype),
                grid_spec=grid_spec,
                input_output_aliases={5: 0},
            )(s_blk, pay_s, pay_s, tgt_eff[:, None], tgt_eff[:, None], rows_tbl)

    return write


def mk_batch(fps, now):
    b = fps.shape[0]
    return ReqBatch(
        fp=jnp.asarray(fps),
        algo=jnp.zeros(b, dtype=jnp.int32),
        behavior=jnp.zeros(b, dtype=jnp.int32),
        hits=jnp.ones(b, dtype=jnp.int64),
        limit=jnp.full(b, 1000, dtype=jnp.int64),
        burst=jnp.zeros(b, dtype=jnp.int64),
        duration=jnp.full(b, 60_000, dtype=jnp.int64),
        created_at=jnp.full(b, now, dtype=jnp.int64),
        expire_new=jnp.full(b, now + 60_000, dtype=jnp.int64),
        greg_interval=jnp.zeros(b, dtype=jnp.int64),
        duration_eff=jnp.full(b, 60_000, dtype=jnp.int64),
        active=jnp.ones(b, dtype=bool),
    )


def slope(fn, fetch, n_long=16):
    fn()
    fetch(fn())

    def run(k):
        t0 = time.perf_counter()
        out = None
        for _ in range(k):
            out = fn()
        fetch(out)
        return time.perf_counter() - t0

    run(2)
    t_short = min(run(2) for _ in range(3))
    t_long = min(run(2 + n_long) for _ in range(3))
    return (t_long - t_short) / n_long


def main():
    rng = np.random.default_rng(11)
    now = 1_700_000_000_000

    # ---------- correctness on a small table vs the XLA write
    CAPs, Bs = 1 << 14, 1 << 10
    tbl = new_table2(CAPs)
    NBs = tbl.rows.shape[0]
    blk_s, u_s = k2.sweep_geometry(NBs, Bs)
    fps = rng.integers(1, (1 << 63) - 1, size=Bs, dtype=np.int64)
    fps[:100] = fps[0]  # duplicates exercise dedup sentinels
    b = mk_batch(fps, now)
    c = jax.jit(
        lambda rows, bb: k2._probe_claim2(rows, bb.fp, bb.created_at, bb.active, blk_s, u_s)
    )(tbl.rows, b)
    new16 = jnp.asarray(
        rng.integers(-(1 << 31), 1 << 31, size=(Bs, F), dtype=np.int64).astype(np.int32)
    )
    ref = k2._write_xla(tbl.rows, new16, c)
    w2 = make_sweep2(NBs, Bs, blk_s, u_s)
    got = jax.jit(w2)(tbl.rows, new16, c)
    same = bool(jnp.array_equal(ref, got))
    log(f"correctness vs xla (small): {same}")
    if not same:
        d = np.argwhere(np.asarray(ref) != np.asarray(got))
        log(f"  mismatches: {d.shape[0]}; first: {d[:5]}")
        return

    # ---------- speed at headline scale
    CAP, B = 1 << 24, 1 << 17
    table = new_table2(CAP)
    NB = table.rows.shape[0]
    fps = rng.integers(1, (1 << 63) - 1, size=B, dtype=np.int64)
    bb = jax.device_put(mk_batch(fps, now))
    for blk in (2048, 4096, 8192):
        u = 256
        if NB % blk:
            continue
        c0 = jax.jit(
            lambda rows, x: k2._probe_claim2(rows, x.fp, x.created_at, x.active, blk, u)
        )(table.rows, bb)
        c0 = jax.tree.map(jax.device_put, c0)
        n16 = jax.device_put(jnp.zeros((B, F), dtype=i32))
        w2 = make_sweep2(NB, B, blk, u)
        f = jax.jit(w2, donate_argnums=(0,))
        state = {"rows": table.rows}

        def step():
            state["rows"] = f(state["rows"], n16, c0)
            return state["rows"]

        try:
            t = slope(step, lambda x: int(x[0, 0]))
            log(f"sweep2 blk={blk:5d}: {t * 1e3:6.2f} ms")
        except Exception as exc:
            log(f"sweep2 blk={blk:5d}: FAILED {type(exc).__name__}: {str(exc)[:160]}")
        table = new_table2(CAP)


if __name__ == "__main__":
    main()
