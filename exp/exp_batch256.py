"""Does a 256K-row dispatch beat 2x 131K dispatches at headline scale?
(Halves the per-dispatch fixed costs' share — probe sorts, DMA floor.)
Device-loop timing (RTT-immune), 2^24-slot table, 10M live keys."""
import sys, time
import numpy as np
import gubernator_tpu  # noqa
import jax
from bench import Case, make_req_batch

def log(m): print(m, file=sys.stderr, flush=True)

rng = np.random.default_rng(42)
now = int(time.time() * 1000)
log(f"device: {jax.devices()[0]}")
CAP, LIVE = 1 << 24, 10_000_000
keyspace = rng.integers(1, (1 << 63) - 1, size=LIVE, dtype=np.int64)
perm = rng.permutation(LIVE)
for BATCH in (1 << 18, 1 << 19):
    nb = min(8, LIVE // BATCH)
    batches = [jax.device_put(make_req_batch(keyspace[perm[i*BATCH:(i+1)*BATCH]], now)) for i in range(nb)]
    c = Case(f"loop-{BATCH//1024}K", CAP, batches, math="token")
    res = c.run(dispatches=8, latency_probes=2)
    log(f"RESULT {BATCH}: {res.get('device_decisions_per_sec')} dec/s, {res.get('device_ms')} ms/dispatch")
    del c, batches
