"""Small deterministic CPU benchmark for the CI regression gate.

The reference gates PRs on a relative benchmark regression (±200% vs master,
reference .github/workflows/on-pull-request.yml:47-80). CI runners have no
TPU, so the gate measures the XLA-CPU lowering of the same serving path
(LocalEngine.check_columns → decision kernel, scatter write): base and PR
trees run in the SAME job and only their ratio matters — machine speed
cancels out.

Prints one JSON line: {"decisions_per_sec": N}.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import gubernator_tpu  # noqa: F401,E402  (x64 on)
from gubernator_tpu.ops.batch import RequestColumns
from gubernator_tpu.ops.engine import LocalEngine

NOW = 1_700_000_000_000
B = 4096


def cols(fp: np.ndarray) -> RequestColumns:
    n = fp.shape[0]
    return RequestColumns(
        fp=fp,
        algo=(np.arange(n) % 2).astype(np.int32),
        behavior=np.zeros(n, dtype=np.int32),
        hits=np.ones(n, dtype=np.int64),
        limit=np.full(n, 1 << 20, dtype=np.int64),
        burst=np.zeros(n, dtype=np.int64),
        duration=np.full(n, 3_600_000, dtype=np.int64),
        created_at=np.full(n, NOW, dtype=np.int64),
        err=np.zeros(n, dtype=np.int8),
    )


def main() -> None:
    eng = LocalEngine(capacity=1 << 15, write_mode="xla")
    rng = np.random.default_rng(0)
    fps = [
        rng.integers(1, (1 << 63) - 1, size=B, dtype=np.int64) for _ in range(4)
    ]
    for f in fps:  # compile + seed
        eng.check_columns(cols(f), now_ms=NOW)
    best = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        n_disp = 64
        for i in range(n_disp):
            eng.check_columns(cols(fps[i % 4]), now_ms=NOW)
        dt = time.perf_counter() - t0
        best = max(best, n_disp * B / dt)
    print(json.dumps({"decisions_per_sec": round(best, 1)}))


if __name__ == "__main__":
    main()
