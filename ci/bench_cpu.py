"""Small deterministic CPU benchmark for the CI regression gate.

The reference gates PRs on a relative benchmark regression (±200% vs master,
reference .github/workflows/on-pull-request.yml:47-80). CI runners have no
TPU, so the gate measures the XLA-CPU lowering of the same serving path
(LocalEngine.check_columns → decision kernel, scatter write): base and PR
trees run in the SAME job and only their ratio matters — machine speed
cancels out.

Also runs a sharded-dispatch ingress smoke on a virtual 8-device mesh
(route="device" + in-trace dedup — the TPU serving default): regressions
that re-grow the host staging cost with batch size (a reintroduced host
group-by or argsort on the dispatch path) fail fast here, gated by
bench_guard.check_dropped so a drop-storm can't masquerade as fast staging.

Prints one JSON line: {"decisions_per_sec": N, "sharded_smoke": {...}}.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the ingress smoke needs a multi-device mesh; must be set before jax init
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import gubernator_tpu  # noqa: F401,E402  (x64 on)
from gubernator_tpu.bench_guard import check_dropped
from gubernator_tpu.ops.batch import RequestColumns
from gubernator_tpu.ops.engine import LocalEngine

NOW = 1_700_000_000_000
B = 4096


def cols(fp: np.ndarray) -> RequestColumns:
    n = fp.shape[0]
    return RequestColumns(
        fp=fp,
        algo=(np.arange(n) % 2).astype(np.int32),
        behavior=np.zeros(n, dtype=np.int32),
        hits=np.ones(n, dtype=np.int64),
        limit=np.full(n, 1 << 20, dtype=np.int64),
        burst=np.zeros(n, dtype=np.int64),
        duration=np.full(n, 3_600_000, dtype=np.int64),
        created_at=np.full(n, NOW, dtype=np.int64),
        err=np.zeros(n, dtype=np.int8),
    )


def sharded_smoke() -> dict:
    """Ingress-path regression gate: host-stage ms per dispatch through the
    device-routed, in-trace-dedup mesh path must stay batch-proportional.
    Staging at 8× the rows may cost up to 8× (proportional) times slack —
    a reintroduced keyspace-bound or super-linear host step (np.unique,
    argsort routing, per-dispatch grid realloc at table scale) blows the
    bound; flat-to-linear passes. check_dropped rejects a run that 'wins'
    by shedding rows into terminal drops."""
    from gubernator_tpu.parallel import make_mesh
    from gubernator_tpu.parallel.sharded import ShardedEngine

    mesh = make_mesh(8)
    eng = ShardedEngine(
        mesh, capacity_per_shard=1 << 12, write_mode="xla",
        route="device", dedup="device",
    )
    rng = np.random.default_rng(1)
    big, small = 4096, 512
    fps = rng.integers(1, (1 << 63) - 1, size=big * 4, dtype=np.int64)
    batches = {
        n: [fps[i * n : (i + 1) * n] for i in range(4)] for n in (big, small)
    }
    for n in (small, big):  # compile + seed
        for f in batches[n]:
            eng.check_columns(cols(f), now_ms=NOW)

    def stage_ms_per_dispatch(n: int, k: int = 12) -> float:
        eng.take_stage_deltas()
        d0 = eng.stage_dispatches
        for i in range(k):
            eng.check_columns(cols(batches[n][i % 4]), now_ms=NOW)
        stage = eng.take_stage_deltas()
        return sum(stage.values()) / max(1, eng.stage_dispatches - d0)

    small_ms = min(stage_ms_per_dispatch(small) for _ in range(3))
    big_ms = min(stage_ms_per_dispatch(big) for _ in range(3))
    rows_ratio = big / small
    SLACK = 4.0
    ok = big_ms <= rows_ratio * SLACK * max(small_ms, 1e-4)
    guard = check_dropped(eng.stats.dropped, max(1, eng.stats.checks))
    out = {
        "host_stage_small_ms": round(small_ms, 4),
        "host_stage_big_ms": round(big_ms, 4),
        "rows_ratio": rows_ratio,
        "proportional": bool(ok),
        "dropped_guard": guard or "ok",
    }
    if not ok:
        print(json.dumps({"error": "sharded ingress host-stage cost is "
                          "super-linear in batch rows", **out}))
        sys.exit(1)
    if guard:
        print(json.dumps({"error": f"sharded smoke drop storm: {guard}", **out}))
        sys.exit(1)
    return out


def wire_smoke() -> dict:
    """Compact-wire regression gate (ISSUE 5): on the 8-device mesh with
    the TPU serving defaults forced (device route/dedup, compact wire),

      * responses must match the full-width oracle row-for-row over
        token/leaky/duplicate-key/flagged traffic;
      * marginal bytes/row across two batch sizes must stay within the
        wire budget — put ≤ 24 B/row and fetch ≤ 16 B/row (marginal cost
        is the honest transport-proportionality metric: it cancels the
        fixed per-dispatch base column and stats rows) — and beat the
        full-width layout ≥3× on put, ≥2× on fetch;
      * double-buffered dispatch wall time must stay batch-proportional
        (the sharded_smoke bound, driven through the depth-2 pipelined
        issue/finish split this time);
      * the transport gate must not reject the window for claiming bytes
        it could not have moved (impossible-bandwidth side only — CI
        runners are legitimately slow, so the drift side is reported,
        not fatal).
    """
    import time as _time

    from gubernator_tpu.bench_guard import check_transport
    from gubernator_tpu.ops.engine import (
        finish_check_columns,
        issue_check_columns,
        prepare_check_columns,
    )
    from gubernator_tpu.parallel import make_mesh
    from gubernator_tpu.parallel.sharded import ShardedEngine

    mesh = make_mesh(8)
    kw = dict(
        capacity_per_shard=1 << 12, write_mode="xla",
        route="device", dedup="device",
    )
    ec = ShardedEngine(mesh, wire="compact", **kw)
    ef = ShardedEngine(mesh, wire="full", **kw)
    rng = np.random.default_rng(7)
    big, small = 4096, 512

    def mixed_cols(fp):
        n = fp.shape[0]
        c = cols(fp)
        return c._replace(
            behavior=rng.choice([0, 8, 32], size=n).astype(np.int32),
            hits=rng.integers(0, 4, n).astype(np.int64),
        )

    state = rng.bit_generator.state
    for eng in (ec, ef):
        rng.bit_generator.state = state
        for step in range(4):
            n = big if step % 2 else small
            fp = rng.integers(1, (1 << 63) - 1, size=n, dtype=np.int64)
            if step == 3:
                fp[n // 2 :] = fp[: n - n // 2]  # duplicate keys
            rc = eng.check_columns(mixed_cols(fp), now_ms=NOW)
            if eng is ec:
                saved = getattr(ec, "_smoke", [])
                saved.append(rc)
                ec._smoke = saved
            else:
                want = ec._smoke[step]
                for f in ("status", "limit", "remaining", "reset_time", "err"):
                    if not np.array_equal(getattr(rc, f), getattr(want, f)):
                        print(json.dumps({
                            "error": f"wire smoke: compact/full mismatch in "
                                     f"{f} at step {step}"}))
                        sys.exit(1)

    def bytes_per_dispatch(eng, n, k=6):
        eng.take_wire_deltas()
        fps = rng.integers(1, (1 << 63) - 1, size=(k, n), dtype=np.int64)
        for i in range(k):
            eng.check_columns(cols(fps[i]), now_ms=NOW)
        w = eng.take_wire_deltas()
        return w["put"] / k, w["fetch"] / k

    marg = {}
    for label, eng in (("compact", ec), ("full", ef)):
        put_s, fetch_s = bytes_per_dispatch(eng, small)
        put_b, fetch_b = bytes_per_dispatch(eng, big)
        marg[label] = (
            (put_b - put_s) / (big - small),
            (fetch_b - fetch_s) / (big - small),
        )
    put_row, fetch_row = marg["compact"]
    put_ratio = marg["full"][0] / max(put_row, 1e-9)
    fetch_ratio = marg["full"][1] / max(fetch_row, 1e-9)
    out = {
        "put_bytes_per_row": round(put_row, 2),
        "fetch_bytes_per_row": round(fetch_row, 2),
        "put_reduction_vs_full": round(put_ratio, 2),
        "fetch_reduction_vs_full": round(fetch_ratio, 2),
    }
    if put_row > 24 or fetch_row > 16:
        print(json.dumps({"error": "compact wire over budget (put ≤ 24, "
                          "fetch ≤ 16 B/row)", **out}))
        sys.exit(1)
    if put_ratio < 3.0 or fetch_ratio < 2.0:
        print(json.dumps({"error": "compact wire reduction under the "
                          "acceptance floor (≥3x put, ≥2x fetch)", **out}))
        sys.exit(1)

    # double-buffered wall-time proportionality through the depth-2 split
    def piped_wall(n, k=12):
        fps = rng.integers(1, (1 << 63) - 1, size=(k, n), dtype=np.int64)
        fixup = lambda fn: fn()
        t0 = _time.perf_counter()
        pend = []
        for i in range(k):
            pend.append(issue_check_columns(
                ec, prepare_check_columns(ec, cols(fps[i]), now_ms=NOW)
            ))
            if len(pend) > 2:
                _rc, delta = finish_check_columns(ec, pend.pop(0), fixup)
                ec.stats.merge(delta)
        while pend:
            _rc, delta = finish_check_columns(ec, pend.pop(0), fixup)
            ec.stats.merge(delta)
        return _time.perf_counter() - t0

    piped_wall(small, k=2)  # warm
    piped_wall(big, k=2)
    small_s = min(piped_wall(small) for _ in range(3))
    big_s = min(piped_wall(big) for _ in range(3))
    SLACK = 4.0
    ok = big_s <= (big / small) * SLACK * max(small_s, 1e-4)
    out["piped_small_s"] = round(small_s, 4)
    out["piped_big_s"] = round(big_s, 4)
    out["piped_proportional"] = bool(ok)
    if not ok:
        print(json.dumps({"error": "double-buffered sharded dispatch wall "
                          "time is super-linear in batch rows", **out}))
        sys.exit(1)

    # transport gate: only the impossible-bandwidth side is fatal on CI
    ec.take_wire_deltas()
    ec.take_stage_deltas()
    for i in range(6):
        ec.check_columns(cols(rng.integers(1, (1 << 63) - 1, size=big,
                                           dtype=np.int64)), now_ms=NOW)
    w = ec.take_wire_deltas()
    put_ms = ec.take_stage_deltas()["put"]
    guard = check_transport(put_ms / 1e3, w["put"], min_bandwidth=0.0)
    out["transport_guard"] = guard or "ok"
    if guard:
        print(json.dumps({"error": f"wire smoke transport gate: {guard}",
                          **out}))
        sys.exit(1)
    return out


def handoff_smoke() -> dict:
    """Topology-handoff regression gate: extract + conservative-merge of
    ~100k live rows across an 8-device mesh must be batch-proportional on
    the host (no full-table host loop — the device does the partition pass)
    and lose zero rows in the no-fault case (row parity src extract → dst
    merge). Host cost is measured as wall time of the merge path at 1× vs
    8× the rows: super-linear growth (a reintroduced per-row Python loop or
    keyspace-bound staging) blows the bound."""
    from gubernator_tpu.parallel import make_mesh
    from gubernator_tpu.parallel.sharded import ShardedEngine

    mesh = make_mesh(8)
    cap = 1 << 15  # 256K slots across the mesh — rows ≪ table
    src = ShardedEngine(mesh, capacity_per_shard=cap, write_mode="xla")
    rng = np.random.default_rng(3)
    n = 100_000
    fps_in = np.unique(rng.integers(1, (1 << 63) - 1, size=n + n // 8,
                                    dtype=np.int64))[:n]
    ones = np.ones(n, dtype=np.int64)
    installed = src.install_columns(
        fp=fps_in,
        algo=np.zeros(n, dtype=np.int32),
        status=np.zeros(n, dtype=np.int32),
        limit=ones * 100,
        remaining=ones * 37,
        reset_time=ones * (NOW + 3_600_000),
        duration=ones * 3_600_000,
        now_ms=NOW,
    )
    # a few installs drop to per-bucket overflow (the claim auction's
    # documented behavior at this load) — parity is against what LANDED
    t0 = time.perf_counter()
    fps, slots = src.extract_live(NOW)
    t_extract = time.perf_counter() - t0
    if fps.shape[0] != installed:
        print(json.dumps({"error": "handoff smoke: extract lost rows",
                          "extracted": int(fps.shape[0]),
                          "expected": installed}))
        sys.exit(1)

    n_live = int(fps.shape[0])

    def merge_time(rows: int) -> float:
        dst = ShardedEngine(mesh, capacity_per_shard=cap, write_mode="xla")
        dst.merge_rows(fps[:rows], slots[:rows], now_ms=NOW)  # compile+seed
        t0 = time.perf_counter()
        merged = dst.merge_rows(fps[:rows], slots[:rows], now_ms=NOW)
        dt = time.perf_counter() - t0
        if merged != rows:  # idempotent replay must re-ack every row
            print(json.dumps({"error": "handoff smoke: merge lost rows",
                              "merged": merged, "expected": rows}))
            sys.exit(1)
        return dt

    small, big = n_live // 8, n_live
    small_s = min(merge_time(small) for _ in range(3))
    big_s = min(merge_time(big) for _ in range(3))
    SLACK = 4.0
    ok = big_s <= (big / small) * SLACK * max(small_s, 1e-4)
    out = {
        "rows": n,
        "extract_s": round(t_extract, 4),
        "merge_small_s": round(small_s, 4),
        "merge_big_s": round(big_s, 4),
        "proportional": bool(ok),
    }
    if not ok:
        print(json.dumps({"error": "handoff merge cost is super-linear in "
                          "rows", **out}))
        sys.exit(1)
    return out


def serving_smoke() -> dict:
    """Serving-plane regression gate (loopback daemon, CPU backend):

    (a) **parse once, stage once** — an encodable distinct-key corpus must
        ride the fused wire→grid path (no column re-pack on any dispatch),
        and the native parse must stay ∝ bytes (a reintroduced per-item
        Python stage shows up as a super-linear ratio);
    (b) **front-door workers** — serving the same concurrent load with 4
        flush workers must not be slower than with 1 (the multi-worker door
        exists to overlap chunk form/dispatch/fan-out; losing that overlap
        is the regression this gates);
    (c) **adaptive window** — under synthetic backlog the coalesce window
        must close on accumulated ROWS, not ride out a (deliberately huge)
        wall-clock window.
    """
    import asyncio

    from gubernator_tpu.config import BehaviorConfig, DaemonConfig
    from gubernator_tpu.proto import gubernator_pb2 as pb
    from gubernator_tpu.service.daemon import Daemon
    from gubernator_tpu.service.wire import wire_batch_from_wire

    os.environ["GUBER_WIRE_COMPACT"] = "1"  # fused path needs compact wire

    def corpus(reqs: int, rows: int, tag: str):
        return [
            pb.GetRateLimitsReq(
                requests=[
                    pb.RateLimitReq(
                        name="smoke", unique_key=f"{tag}r{r}i{i}", hits=1,
                        limit=1 << 20, duration=3_600_000, created_at=NOW,
                    )
                    for i in range(rows)
                ]
            ).SerializeToString()
            for r in range(reqs)
        ]

    # ---- (a) parse cost ∝ bytes (native parser, one traversal)
    small = corpus(1, 250, "s")[0]
    big = corpus(1, 1000, "b")[0]
    wire_batch_from_wire(small), wire_batch_from_wire(big)  # warm
    K = 50

    def parse_ms(data: bytes) -> float:
        t0 = time.perf_counter()
        for _ in range(K):
            wire_batch_from_wire(data)
        return (time.perf_counter() - t0) / K * 1e3

    p_small, p_big = parse_ms(small), parse_ms(big)
    bytes_ratio = len(big) / len(small)
    parse_ratio = p_big / max(p_small, 1e-9)
    out: dict = {
        "parse_ms_250": round(p_small, 4),
        "parse_ms_1000": round(p_big, 4),
        "parse_bytes_ratio": round(bytes_ratio, 2),
        "parse_time_ratio": round(parse_ratio, 2),
    }
    if parse_ratio > bytes_ratio * 2.5:
        print(json.dumps({"error": "serving smoke: parse cost super-linear "
                          "in bytes", **out}))
        sys.exit(1)

    def conf(**beh) -> DaemonConfig:
        beh.setdefault("batch_wait_ms", 1.0)
        return DaemonConfig(
            grpc_address="127.0.0.1:0", http_address="",
            cache_size=1 << 15,
            behaviors=BehaviorConfig(**beh),
        )

    async def drive(d: Daemon, datas) -> float:
        t0 = time.perf_counter()
        await asyncio.gather(*(d.get_rate_limits_raw(x) for x in datas))
        return time.perf_counter() - t0

    # ---- (a) fused path engaged, zero re-packs; (b) worker scaling
    async def fused_and_workers():
        res = {}
        for label, workers in (("w1", 1), ("w4", 4)):
            d = await Daemon.spawn(conf(front_workers=workers))
            datas = corpus(64, 64, label)
            await drive(d, datas)  # shape warm
            best = min([await drive(d, datas) for _ in range(3)])
            res[label] = best
            if label == "w4":
                res["fused"] = d.batcher.fused_dispatches
                res["fallbacks"] = d.batcher.wire_fallbacks
                res["columns"] = d.batcher.column_dispatches
            await d.close()
        return res

    r = asyncio.run(fused_and_workers())
    out["serve_s_workers1"] = round(r["w1"], 4)
    out["serve_s_workers4"] = round(r["w4"], 4)
    out["worker_speedup"] = round(r["w1"] / max(r["w4"], 1e-9), 3)
    out["fused_dispatches"] = r["fused"]
    out["wire_fallbacks"] = r["fallbacks"]
    if r["fused"] == 0 or r["fallbacks"] > 0:
        print(json.dumps({"error": "serving smoke: encodable corpus did not "
                          "ride the fused parse path", **out}))
        sys.exit(1)
    # CI machines are noisy: gate on "multi-worker must not LOSE the
    # overlap", not on a specific speedup
    if r["w4"] > r["w1"] * 1.5:
        print(json.dumps({"error": "serving smoke: 4 front-door workers "
                          "slower than 1", **out}))
        sys.exit(1)

    # ---- (c) adaptive window closes on rows under backlog
    async def adaptive():
        d = await Daemon.spawn(conf(
            front_workers=2, batch_wait_ms=300.0, adaptive_batch=True,
            batch_close_rows=2048,
        ))
        datas = corpus(64, 64, "a")
        await drive(d, datas)  # shape warm
        wall = await drive(d, corpus(64, 64, "a2"))
        closes, expires = d.batcher.adaptive_closes, d.batcher.window_expires
        await d.close()
        return wall, closes, expires

    wall, closes, expires = asyncio.run(adaptive())
    out["adaptive_wall_s"] = round(wall, 4)
    out["adaptive_closes"] = closes
    out["window_expires"] = expires
    # riding the 300 ms wall-clock window even once per flush cycle would
    # put the wall well past a second for this backlog
    if closes < 1 or wall > 2.0:
        print(json.dumps({"error": "serving smoke: adaptive window did not "
                          "close on rows under backlog", **out}))
        sys.exit(1)
    return out


def ring_smoke() -> dict:
    """Device-resident request-ring regression gate (always-on-chip PR,
    loopback daemon, CPU backend — the functional emulation of the
    persistent-kernel ring protocol):

    (a) **byte-identity** — a ring-fed daemon must serve byte-identical
        responses to a direct-dispatch daemon over the same distinct-key
        corpus under 4-worker concurrency (the ring drives the exact
        runner surface the direct path drives, so any divergence is a
        protocol bug: misordered slot consumption, crossed futures,
        stale staging);
    (b) **bounded backpressure, zero loss** — with a deliberately tiny
        ring (GUBER_RING_SLOTS=2) and per-request chunks, submits must
        WAIT rather than drop: every published ticket launches exactly
        once, in ticket order, and every response comes back;
    (c) **zero-loss drain** — daemon close retires every published slot
        before parking the loop (published == consumed, occupancy 0);
    (d) **bounded host overhead** — the ring protocol's per-dispatch host
        cost (claim/stage/fence/poll) must stay within 2.5× the direct
        path's dispatch wall at small batches. (On CPU the emulation can
        only ADD overhead — the round-trip it deletes is priced by
        bench.py's `dispatch` phase on a real TPU, where the persistent
        kernel skips the launch entirely.)
    """
    import asyncio

    from gubernator_tpu.config import BehaviorConfig, DaemonConfig
    from gubernator_tpu.service.daemon import Daemon

    os.environ["GUBER_WIRE_COMPACT"] = "1"  # fused path needs compact wire
    now = int(time.time() * 1000)  # honored by created_at tolerance →
    # reset_time is corpus-determined, so responses are byte-comparable
    # across daemons serving seconds apart

    def corpus(reqs: int, rows: int, tag: str):
        from gubernator_tpu.proto import gubernator_pb2 as pb

        return [
            pb.GetRateLimitsReq(
                requests=[
                    pb.RateLimitReq(
                        name="ring", unique_key=f"{tag}r{r}i{i}", hits=1,
                        limit=1 << 20, duration=3_600_000, created_at=now,
                    )
                    for i in range(rows)
                ]
            ).SerializeToString()
            for r in range(reqs)
        ]

    def conf(**beh) -> DaemonConfig:
        beh.setdefault("batch_wait_ms", 1.0)
        beh.setdefault("front_workers", 4)
        # per-request chunks: 64-row requests + a 64-row coalesce cap mean
        # every request is its own ring ticket — the protocol stress shape
        beh.setdefault("coalesce_limit", 64)
        return DaemonConfig(
            grpc_address="127.0.0.1:0", http_address="",
            cache_size=1 << 15, behaviors=BehaviorConfig(**beh),
        )

    async def drive(d: Daemon, datas):
        t0 = time.perf_counter()
        rs = await asyncio.gather(*(d.get_rate_limits_raw(x) for x in datas))
        return time.perf_counter() - t0, rs

    async def run():
        out: dict = {}
        dr = await Daemon.spawn(conf(ring_enable=True, ring_slots=2))
        dd = await Daemon.spawn(conf())
        await drive(dr, corpus(8, 64, "w"))  # shape warm
        await drive(dd, corpus(8, 64, "w"))
        datas = corpus(64, 64, "x")
        t_ring, r1 = await drive(dr, datas)
        t_direct, r2 = await drive(dd, datas)
        dbg = dr.ring.debug()
        out["identical"] = r1 == r2
        out["ring_dispatches"] = dr.batcher.ring_dispatches
        out["ring_launches"] = dbg["launches"]
        out["ring_published"] = dbg["published"]
        out["backpressure_waits"] = dbg["backpressure_waits"]
        out["max_occupancy"] = dbg["max_occupancy"]
        out["fallbacks"] = dbg["fallbacks"]
        out["serve_s_ring"] = round(t_ring, 4)
        out["serve_s_direct"] = round(t_direct, 4)
        out["ring_overhead_ratio"] = round(t_ring / max(t_direct, 1e-9), 3)
        await dr.close()
        await dd.close()
        post = dr.ring.debug()
        out["drained_clean"] = (
            post["closed"] and post["occupancy"] == 0
            and post["published"] == post["consumed"]
        )
        return out

    out = asyncio.run(run())
    if not out["identical"]:
        print(json.dumps({"error": "ring smoke: ring-fed responses diverge "
                          "from the direct dispatch path", **out}))
        sys.exit(1)
    if out["ring_dispatches"] == 0 or out["ring_launches"] == 0:
        print(json.dumps({"error": "ring smoke: ring plane never engaged",
                          **out}))
        sys.exit(1)
    if out["ring_launches"] != out["ring_published"]:
        print(json.dumps({"error": "ring smoke: published tickets were "
                          "dropped (launch/publish mismatch)", **out}))
        sys.exit(1)
    if out["max_occupancy"] > 2:
        print(json.dumps({"error": "ring smoke: occupancy exceeded the "
                          "slot bound", **out}))
        sys.exit(1)
    if out["backpressure_waits"] == 0:
        print(json.dumps({"error": "ring smoke: 64 per-request tickets "
                          "through a 2-slot ring never hit backpressure — "
                          "the bound is not being exercised", **out}))
        sys.exit(1)
    if not out["drained_clean"]:
        print(json.dumps({"error": "ring smoke: drain left unconsumed "
                          "slots", **out}))
        sys.exit(1)
    if out["ring_overhead_ratio"] > 2.5:
        print(json.dumps({"error": "ring smoke: ring protocol host "
                          "overhead exceeds 2.5x the direct path", **out}))
        sys.exit(1)
    return out


def ring_drain_smoke() -> dict:
    """Fused multi-slot drain regression gate (kill-the-launch-tax PR,
    ops/ring_drain.py — the jitted while_loop consumer behind
    GUBER_RING_ISSUE=fused):

    (a) **byte parity at ~1M keys** — a fused-drain daemon must serve
        byte-identical responses to a direct-dispatch daemon over a
        distinct-key corpus of 64×16384 = 1 048 576 keys (the fused graph
        walks the same decide2_wire_cols per slot, in ticket order — any
        divergence is a drain-protocol bug: misgrouped slots, stale bank
        rows, fence skew);
    (b) **launches/decision strictly decreasing in K** — the whole point
        of the PR: over the same concurrent corpus, raising
        GUBER_RING_DRAIN_K must strictly reduce drain launches (K=1 is
        one-launch-per-slot; K=8 retires groups);
    (c) **zero-loss drain** — drain() racing live fused launches strands
        nothing: every submitter resolves, published == consumed,
        occupancy 0.
    """
    import asyncio

    from gubernator_tpu.config import BehaviorConfig, DaemonConfig
    from gubernator_tpu.service.daemon import Daemon

    os.environ["GUBER_WIRE_COMPACT"] = "1"  # fused path needs compact wire
    now = int(time.time() * 1000)

    def corpus(reqs: int, rows: int, tag: str):
        from gubernator_tpu.proto import gubernator_pb2 as pb

        return [
            pb.GetRateLimitsReq(
                requests=[
                    pb.RateLimitReq(
                        name="drain", unique_key=f"{tag}r{r}i{i}", hits=1,
                        limit=1 << 20, duration=3_600_000, created_at=now,
                    )
                    for i in range(rows)
                ]
            ).SerializeToString()
            for r in range(reqs)
        ]

    def conf(**beh) -> DaemonConfig:
        beh.setdefault("batch_wait_ms", 1.0)
        beh.setdefault("front_workers", 8)
        return DaemonConfig(
            grpc_address="127.0.0.1:0", http_address="",
            cache_size=1 << 21, max_batch_size=4096,
            behaviors=BehaviorConfig(**beh),
        )

    async def drive(d: Daemon, datas):
        t0 = time.perf_counter()
        rs = await asyncio.gather(*(d.get_rate_limits_raw(x) for x in datas))
        return time.perf_counter() - t0, rs

    async def parity():
        out: dict = {}
        # one 4096-row request per ring slot: ~1M distinct keys total
        df = await Daemon.spawn(conf(
            ring_enable=True, ring_issue="fused", ring_slots=8,
            ring_drain_k=8, coalesce_limit=4096,
        ))
        dd = await Daemon.spawn(conf(coalesce_limit=4096))
        await drive(df, corpus(4, 4096, "w"))  # shape warm
        await drive(dd, corpus(4, 4096, "w"))
        datas = corpus(256, 4096, "m")
        t_fused, r1 = await drive(df, datas)
        t_direct, r2 = await drive(dd, datas)
        dbg = df.ring.debug()
        out["identical"] = r1 == r2
        out["keys"] = 256 * 4096
        out["drain_launches"] = dbg["drain_launches"]
        out["drained_slots"] = dbg["drained_slots"]
        out["host_slots"] = dbg["host_slots"]
        out["serve_s_fused"] = round(t_fused, 4)
        out["serve_s_direct"] = round(t_direct, 4)
        await df.close()
        await dd.close()
        return out

    async def k_sweep():
        # same concurrent corpus per K: drain launches must strictly fall
        launches = {}
        for k in (1, 2, 8):
            d = await Daemon.spawn(conf(
                ring_enable=True, ring_issue="fused", ring_slots=8,
                ring_drain_k=k, coalesce_limit=64,
            ))
            await drive(d, corpus(8, 64, f"w{k}"))  # shape warm
            await drive(d, corpus(64, 64, f"s{k}"))
            dbg = d.ring.debug()
            launches[k] = dbg["drain_launches"] + dbg["host_slots"]
            await d.close()
        return launches

    async def zero_loss():
        d = await Daemon.spawn(conf(
            ring_enable=True, ring_issue="fused", ring_slots=4,
            ring_drain_k=4, coalesce_limit=64,
        ))
        pending = [
            asyncio.create_task(d.get_rate_limits_raw(x))
            for x in corpus(32, 64, "z")
        ]
        await asyncio.sleep(0.02)  # fused launches in flight
        await d.ring.drain()
        outs = await asyncio.gather(*pending)
        dbg = d.ring.debug()
        await d.close()
        return (
            all(isinstance(o, bytes) for o in outs)
            and dbg["closed"] and dbg["occupancy"] == 0
            and dbg["published"] == dbg["consumed"]
        )

    out = asyncio.run(parity())
    out["launches_by_k"] = asyncio.run(k_sweep())
    out["drain_zero_loss"] = asyncio.run(zero_loss())
    if not out["identical"]:
        print(json.dumps({"error": "ring drain smoke: fused-drain "
                          "responses diverge from the direct path at 1M "
                          "keys", **out}))
        sys.exit(1)
    if out["drain_launches"] == 0 or out["drained_slots"] == 0:
        print(json.dumps({"error": "ring drain smoke: fused drain never "
                          "engaged", **out}))
        sys.exit(1)
    lk = out["launches_by_k"]
    if not (lk[1] > lk[2] > lk[8]):
        print(json.dumps({"error": "ring drain smoke: launches/decision "
                          "not strictly decreasing in K — the drain is "
                          "not amortizing the launch tax", **out}))
        sys.exit(1)
    if not out["drain_zero_loss"]:
        print(json.dumps({"error": "ring drain smoke: drain through live "
                          "fused launches lost or stranded work", **out}))
        sys.exit(1)
    return out


def telemetry_smoke() -> dict:
    """Table-telemetry regression gate (observability PR) at a 1M-key
    population:

    (a) **parity** — the fused device scan must match the numpy host oracle
        field-for-field on the seeded table (and on an 8-dev mesh slice);
    (b) **off the serving path** — the scan's only engine-thread cost is
        its LAUNCH (begin ≪ total: the device streams the table while
        serving keeps dispatching). Gated: launch ≤ 25% of scan wall and
        under 10 ms;
    (c) **<5% throughput cost at the shipped cadence** — the MARGINAL wall
        cost of one scan overlapped with serving (measured, not assumed:
        XLA-CPU shares one intra-op pool, so 'it runs on another thread'
        is exactly the claim that must be priced) divided by the default
        GUBER_TELEMETRY_INTERVAL_MS duty cycle must stay under 5%.
    """
    import queue
    import threading

    from gubernator_tpu.ops.telemetry import finish_scan, host_telemetry

    eng = LocalEngine(capacity=1 << 21, write_mode="xla")
    rng = np.random.default_rng(5)
    n = 1 << 20
    fps = np.unique(
        rng.integers(1, (1 << 63) - 1, size=n + (n >> 3), dtype=np.int64)
    )[:n]
    for i in range(0, n, 1 << 17):
        sl = fps[i : i + (1 << 17)]
        m = sl.shape[0]
        o = np.ones(m, dtype=np.int64)
        eng.install_columns(
            fp=sl, algo=np.zeros(m, np.int32), status=np.zeros(m, np.int32),
            limit=o * 100, remaining=o * 37,
            reset_time=o * (NOW + 3_600_000), duration=o * 3_600_000,
            now_ms=NOW,
        )

    # ---- (a) parity vs the host oracle (local + mesh slice)
    snap = finish_scan(eng.telemetry_begin(NOW))
    oracle = host_telemetry(np.asarray(eng.table.rows), NOW)
    for f in ("live_keys", "occupied_slots", "over_keys", "bucket_occupancy",
              "ttl_horizon", "remaining_frac", "block_fill"):
        if getattr(snap, f) != getattr(oracle, f):
            print(json.dumps({"error": f"telemetry smoke: device scan != "
                              f"host oracle in {f}"}))
            sys.exit(1)
    from gubernator_tpu.parallel import make_mesh
    from gubernator_tpu.parallel.sharded import ShardedEngine

    mesh_eng = ShardedEngine(make_mesh(8), capacity_per_shard=1 << 12,
                             write_mode="xla")
    m = 1 << 14
    o = np.ones(m, dtype=np.int64)
    mesh_eng.install_columns(
        fp=fps[:m], algo=np.zeros(m, np.int32), status=np.zeros(m, np.int32),
        limit=o * 100, remaining=o * 37, reset_time=o * (NOW + 3_600_000),
        duration=o * 3_600_000, now_ms=NOW,
    )
    msnap = finish_scan(mesh_eng.telemetry_begin(NOW))
    morcl = host_telemetry(np.asarray(mesh_eng.table.rows), NOW)
    if (msnap.live_keys != morcl.live_keys
            or msnap.bucket_occupancy != morcl.bucket_occupancy
            or sum(msnap.per_shard_live) != msnap.live_keys):
        print(json.dumps({"error": "telemetry smoke: mesh scan parity "
                          "failed"}))
        sys.exit(1)

    # ---- (b) launch ≪ total (the begin/finish split actually overlaps)
    t0 = time.perf_counter()
    pend = eng.telemetry_begin(NOW)
    t_launch = time.perf_counter() - t0
    finish_scan(pend)
    t_total = time.perf_counter() - t0
    out = {
        "live_keys": snap.live_keys,
        "scan_launch_ms": round(t_launch * 1e3, 3),
        "scan_total_ms": round(t_total * 1e3, 3),
    }
    if t_launch > 0.010 or t_launch > 0.25 * t_total:
        print(json.dumps({"error": "telemetry smoke: scan launch blocks the "
                          "engine thread (begin must enqueue, not compute)",
                          **out}))
        sys.exit(1)

    # ---- (c) marginal overlapped-scan cost vs the shipped duty cycle
    B_ = 4096
    batches = [fps[i * B_ : (i + 1) * B_] for i in range(4)]
    for f in batches:
        eng.check_columns(cols(f), now_ms=NOW)
    K = 64
    SCAN_EVERY = 8

    def window(q=None):
        t0 = time.perf_counter()
        for i in range(K):
            if q is not None and i % SCAN_EVERY == 0:
                # launch inline (the engine thread's real cost), finish on
                # the background worker — the runner's exact split
                q.put(eng.telemetry_begin(NOW))
            eng.check_columns(cols(batches[i % 4]), now_ms=NOW)
        return time.perf_counter() - t0

    base = min(window() for _ in range(3))

    def with_scans():
        q: "queue.Queue" = queue.Queue()
        done = [0]

        def worker():
            while True:
                p = q.get()
                if p is None:
                    return
                finish_scan(p)
                done[0] += 1

        t = threading.Thread(target=worker)
        t.start()
        dt = window(q)
        q.put(None)
        t.join()
        return dt, done[0]

    runs = [with_scans() for _ in range(3)]
    wt = min(r[0] for r in runs)
    n_scans = K // SCAN_EVERY
    marginal_s = max(0.0, (wt - base)) / n_scans
    # duty cycle at the shipped default cadence (config.py: 5000 ms)
    duty = marginal_s / 5.0
    out.update({
        "serve_base_s": round(base, 4),
        "serve_with_scans_s": round(wt, 4),
        "scan_marginal_ms": round(marginal_s * 1e3, 2),
        "cost_at_default_cadence": round(duty, 4),
    })
    if duty >= 0.05:
        print(json.dumps({"error": "telemetry smoke: background scan costs "
                          ">=5% of serving throughput at the default "
                          "cadence", **out}))
        sys.exit(1)
    return out


def mesh_smoke() -> dict:
    """Pod-scale mesh regression gate on a SIMULATED 2-host mesh (the 8
    forced-host-platform devices folded into 2 × 4 (host, device) rows):

    (a) **ring/collective parity** — the hand-rolled ring schedule
        (parallel/ring.py) must be byte-identical to the lax.all_to_all
        oracle through real engine traffic (responses AND canonical table
        state), duplicates included;
    (b) **batch-proportional host staging** — the 2-D topology must not
        re-grow per-dispatch host routing work (same bound as
        sharded_smoke, driven on the (host, device) mesh through the ring
        exchange);
    (c) **hierarchical GLOBAL sync convergence** — replica answers + the
        collective reconcile on the 2-host mesh must converge to the exact
        per-key totals, and the inter-slice compact codec must round-trip
        exactly (send half of the SyncGlobalsWire path)."""
    from gubernator_tpu.parallel import make_mesh
    from gubernator_tpu.parallel.global_sync import GlobalShardedEngine
    from gubernator_tpu.parallel.sharded import ShardedEngine

    mesh = make_mesh(8, hosts=2)
    out: dict = {"axes": list(mesh.axis_names)}

    # ---- (a) ring vs collective engine parity (byte-for-byte)
    kw = dict(capacity_per_shard=1 << 12, write_mode="xla",
              route="device", dedup="device")
    ring = ShardedEngine(mesh, a2a="ring", **kw)
    coll = ShardedEngine(mesh, a2a="collective", **kw)
    rng = np.random.default_rng(11)
    for step in range(3):
        n = 1024
        fp = rng.integers(1, (1 << 63) - 1, size=n, dtype=np.int64)
        if step == 2:
            fp[n // 2:] = fp[: n - n // 2]  # duplicate keys
        c = cols(fp)
        want = coll.check_columns(c, now_ms=NOW)
        got = ring.check_columns(c, now_ms=NOW)
        for f in ("status", "limit", "remaining", "reset_time", "err"):
            if not np.array_equal(getattr(want, f), getattr(got, f)):
                print(json.dumps({"error": f"mesh smoke: ring/collective "
                                  f"mismatch in {f} at step {step}"}))
                sys.exit(1)
    if not np.array_equal(np.asarray(ring.table.rows),
                          np.asarray(coll.table.rows)):
        # identical dispatch order ⇒ even slot order must agree
        print(json.dumps({"error": "mesh smoke: ring/collective table "
                          "state diverged"}))
        sys.exit(1)
    out["ring_parity"] = True

    # ---- (b) batch-proportional host staging on the 2-D topology
    big, small = 4096, 512
    fps = rng.integers(1, (1 << 63) - 1, size=big * 4, dtype=np.int64)
    batches = {
        n: [fps[i * n: (i + 1) * n] for i in range(4)] for n in (big, small)
    }
    for n in (small, big):  # compile + seed
        for f in batches[n]:
            ring.check_columns(cols(f), now_ms=NOW)

    def stage_ms_per_dispatch(n: int, k: int = 12) -> float:
        ring.take_stage_deltas()
        d0 = ring.stage_dispatches
        for i in range(k):
            ring.check_columns(cols(batches[n][i % 4]), now_ms=NOW)
        stage = ring.take_stage_deltas()
        return sum(stage.values()) / max(1, ring.stage_dispatches - d0)

    small_ms = min(stage_ms_per_dispatch(small) for _ in range(3))
    big_ms = min(stage_ms_per_dispatch(big) for _ in range(3))
    SLACK = 4.0
    ok = big_ms <= (big / small) * SLACK * max(small_ms, 1e-4)
    out["host_stage_small_ms"] = round(small_ms, 4)
    out["host_stage_big_ms"] = round(big_ms, 4)
    out["proportional"] = bool(ok)
    if not ok:
        print(json.dumps({"error": "mesh smoke: 2-host staging cost is "
                          "super-linear in batch rows", **out}))
        sys.exit(1)
    guard = check_dropped(ring.stats.dropped, max(1, ring.stats.checks))
    if guard:
        print(json.dumps({"error": f"mesh smoke drop storm: {guard}", **out}))
        sys.exit(1)

    # ---- (c) hierarchical GLOBAL sync convergence on the 2-host mesh
    geng = GlobalShardedEngine(mesh, a2a="ring", sync_out=64, **kw)
    m = 96
    gfp = rng.integers(1, (1 << 63) - 1, size=m, dtype=np.int64)
    hits_total = np.zeros(m, dtype=np.int64)
    for step in range(4):  # rotating homes: hits land on several replicas
        h = rng.integers(1, 4, size=m).astype(np.int64)
        hits_total += h
        c = cols(gfp)._replace(
            hits=h, behavior=np.full(m, 2, dtype=np.int32)  # GLOBAL
        )
        rc = geng.check_columns(c, now_ms=NOW)
        if (rc.err != 0).any():
            print(json.dumps({"error": "mesh smoke: GLOBAL serve error",
                              **out}))
            sys.exit(1)
    geng.sync(now_ms=NOW)
    if geng.has_pending():
        print(json.dumps({"error": "mesh smoke: sync left pending hits",
                          **out}))
        sys.exit(1)
    probe = cols(gfp)._replace(
        hits=np.zeros(m, dtype=np.int64),
        behavior=np.full(m, 2, dtype=np.int32),
    )
    # every rotating home's replica must answer the reconciled total
    for _ in range(3):
        rc = geng.check_columns(probe, now_ms=NOW)
        want = (1 << 20) - hits_total
        if not np.array_equal(np.asarray(rc.remaining), want):
            print(json.dumps({"error": "mesh smoke: hierarchical GLOBAL "
                              "sync did not converge", **out}))
            sys.exit(1)
    out["global_sync_rounds"] = geng.global_stats.sync_rounds
    out["global_converged"] = True

    # inter-slice codec half: lane pack → item decode must be exact
    from gubernator_tpu.proto import gubernator_pb2 as pb
    from gubernator_tpu.service.wire import sync_wire_items, sync_wire_pb

    pairs = [
        (f"ms_k{i}", pb.RateLimitReq(
            name="ms", unique_key=f"k{i}", hits=(1 << 19) + i, limit=100,
            duration=60_000, algorithm=i % 2, behavior=2, created_at=NOW,
            burst=100 if i % 2 else 0,
        ))
        for i in range(8)
    ]
    req = sync_wire_pb(pairs, "ci")
    if req is None:
        print(json.dumps({"error": "mesh smoke: sync codec refused an "
                          "encodable batch", **out}))
        sys.exit(1)
    back = sync_wire_items(req)
    for (_k, a), b in zip(pairs, back):
        if (a.name, a.unique_key, a.hits, a.limit, a.duration, a.algorithm,
                a.created_at) != (b.name, b.unique_key, b.hits, b.limit,
                                  b.duration, b.algorithm, b.created_at):
            print(json.dumps({"error": "mesh smoke: sync codec roundtrip "
                              "mismatch", **out}))
            sys.exit(1)
    out["wire_sync_codec"] = True
    return out


def durability_smoke() -> dict:
    """Incremental-checkpoint regression gate (docs/durability.md):

    (a) **delta cost ∝ dirty rows, not table size** — the same fixed write
        rate into a 1M-key and a 10M-key table must produce delta frames
        within 2× of each other (bytes AND rows), each ≥3× smaller than
        the full base snapshot at 10M keys (in practice ~100×);
    (b) **warm-restart replay parity** — base + delta frames replayed
        through the conservative merge reconstruct the source's live rows
        byte-for-byte (and replay wall is reported against re-seeding);
    (c) **background loop < 5% of serving** — the MARGINAL wall cost of
        one overlapped take→extract→append cycle (the runner's exact
        engine-thread-launch / off-thread-fetch split, measured against
        the same serving window without it) divided by a 1 s reference
        cadence must stay under 5% (telemetry-smoke methodology).
    """
    import queue
    import tempfile
    import threading

    from gubernator_tpu.ops.checkpoint import (
        EpochTracker, extract_begin, finish_extract,
    )
    from gubernator_tpu.store import (
        DeltaLog, encode_delta_frame, fps_from_slots,
    )
    from gubernator_tpu.ops.table2 import decode_live_slots

    rng = np.random.default_rng(13)
    WRITE_KEYS = 1 << 14  # fixed write rate: 16K distinct keys per window
    fps = np.unique(rng.integers(1, (1 << 63) - 1, size=WRITE_KEYS * 2,
                                 dtype=np.int64))[:WRITE_KEYS]

    def dcols(fp: np.ndarray, hits: int = 1) -> RequestColumns:
        # algo keyed off the FP (not batch position, like the shared
        # cols()): a real key keeps one algorithm across waves, and an
        # algo flip would make merge2's cross-semantics min legitimately
        # tighter than the serving path — conservative, but not parity
        return cols(fp)._replace(
            algo=(fp & 1).astype(np.int32),
            hits=np.full(fp.shape[0], hits, dtype=np.int64),
        )

    # ---- (a) fixed write rate into 1M vs 10M-key tables
    out: dict = {}
    deltas = {}
    engines = {}
    for label, cap in (("1M", 1_000_000), ("10M", 10_000_000)):
        eng = LocalEngine(capacity=cap, write_mode="xla")
        eng.ckpt = EpochTracker(eng.table.rows.shape[0])
        for i in range(4):
            eng.check_columns(dcols(fps[i::4]), now_ms=NOW)
        _, gids = eng.ckpt.take()
        t0 = time.perf_counter()
        e_fps, e_slots = finish_extract(
            extract_begin(eng.table.rows, gids, eng.ckpt.blk, NOW)
        )
        extract_s = time.perf_counter() - t0
        frame = encode_delta_frame(1, NOW, e_slots)
        full = int(np.asarray(eng.table.rows).nbytes)
        deltas[label] = dict(
            dirty_blocks=int(gids.shape[0]), rows=int(e_fps.shape[0]),
            delta_bytes=len(frame), full_bytes=full,
            extract_s=round(extract_s, 4),
            reduction=round(full / len(frame), 1),
        )
        engines[label] = (eng, e_fps, e_slots)
    out["delta"] = deltas
    ratio = deltas["10M"]["delta_bytes"] / max(deltas["1M"]["delta_bytes"], 1)
    out["delta_bytes_ratio_10M_vs_1M"] = round(ratio, 3)
    if ratio > 2.0:
        print(json.dumps({"error": "durability smoke: delta bytes grew "
                          "with table size at a fixed write rate", **out}))
        sys.exit(1)
    if deltas["10M"]["reduction"] < 3.0:
        print(json.dumps({"error": "durability smoke: delta frame is not "
                          ">=3x smaller than the 10M full snapshot", **out}))
        sys.exit(1)

    # ---- (b) warm-restart replay parity (1M table)
    src, e_fps, e_slots = engines["1M"]
    base = src.snapshot()
    src.check_columns(dcols(fps[: 1 << 12], hits=3), now_ms=NOW + 5)
    _, gids = src.ckpt.take()
    d_fps, d_slots = finish_extract(
        extract_begin(src.table.rows, gids, src.ckpt.blk, NOW + 5)
    )
    dst = LocalEngine(capacity=1_000_000, write_mode="xla")
    t0 = time.perf_counter()
    dst.restore(base)
    dst.merge_rows(d_fps, d_slots, now_ms=NOW + 5)
    replay_s = time.perf_counter() - t0

    def live_map(eng):
        slots, fp, _ = decode_live_slots(np.asarray(eng.table.rows), NOW + 5)
        return {int(f): s.tobytes() for f, s in zip(fp, slots)}

    if live_map(dst) != live_map(src):
        print(json.dumps({"error": "durability smoke: base+delta replay "
                          "did not reconstruct the live rows", **out}))
        sys.exit(1)
    if fps_from_slots(d_slots).shape[0] != d_fps.shape[0]:
        print(json.dumps({"error": "durability smoke: frame fps decode "
                          "mismatch", **out}))
        sys.exit(1)
    out["replay_s"] = round(replay_s, 4)
    out["replay_rows"] = int(d_fps.shape[0]) + WRITE_KEYS

    # ---- (c) marginal overlapped checkpoint cost vs a 1 s cadence
    eng = engines["1M"][0]
    tmp = tempfile.mkdtemp()
    log = DeltaLog(os.path.join(tmp, "smoke.delta"))
    B_ = 4096
    batches = [fps[i * B_: (i + 1) * B_] for i in range(4)]
    for f in batches:
        eng.check_columns(dcols(f), now_ms=NOW)
    K = 48
    SCAN_EVERY = 8

    def window(q=None):
        t0 = time.perf_counter()
        for i in range(K):
            if q is not None and i % SCAN_EVERY == 0:
                # take+launch inline (the engine thread's real cost),
                # fetch+append on the background worker — the runner's
                # exact split (EngineRunner.checkpoint_extract)
                epoch, gids = eng.ckpt.take()
                q.put((epoch, extract_begin(
                    eng.table.rows, gids, eng.ckpt.blk, NOW)))
            eng.check_columns(dcols(batches[i % 4]), now_ms=NOW)
        return time.perf_counter() - t0

    base_s = min(window() for _ in range(3))

    def with_ckpt():
        q: "queue.Queue" = queue.Queue()

        def worker():
            while True:
                item = q.get()
                if item is None:
                    return
                epoch, pend = item
                _f, slots = finish_extract(pend)
                log.append(epoch, NOW, slots)

        t = threading.Thread(target=worker)
        t.start()
        dt = window(q)
        q.put(None)
        t.join()
        return dt

    wt = min(with_ckpt() for _ in range(3))
    marginal_s = max(0.0, wt - base_s) / (K // SCAN_EVERY)
    duty = marginal_s / 1.0  # 1 s reference cadence (docs/durability.md)
    out.update({
        "serve_base_s": round(base_s, 4),
        "serve_with_ckpt_s": round(wt, 4),
        "ckpt_marginal_ms": round(marginal_s * 1e3, 2),
        "cost_at_1s_cadence": round(duty, 4),
    })
    if duty >= 0.05:
        print(json.dumps({"error": "durability smoke: background "
                          "checkpointing costs >=5% of serving at a 1 s "
                          "cadence", **out}))
        sys.exit(1)
    return out


def algo_smoke() -> dict:
    """Scenario-breadth regression gate (ISSUE 10):

    (a) **per-algorithm oracle parity at 1M live keys** — GCRA, sliding
        window and concurrency leases must match the pure-Python oracles
        decision-for-decision against a table already holding ~1M live
        rows (the headline-geometry analog CI can afford);
    (b) **cascade single-dispatch engaged** — an encodable 3-level cascade
        batch rides the compact wire in ONE engine dispatch (zero
        full-width fallbacks, in-trace verdict fold);
    (c) **cascade-vs-sequential e2e ratio** — through a loopback daemon, N
        3-level cascade checks (one RPC, one dispatch each) must clear
        ≥ 2.5× the checks/s of the same N checks issued as three DEPENDENT
        single-level round trips (the deployment pattern cascades replace).
    """
    import asyncio

    from tests.oracle.algos import GcraOracle, LeaseOracle, SlidingWindowOracle

    from gubernator_tpu.hashing import fingerprint
    from gubernator_tpu.ops import wire as wire_mod
    from gubernator_tpu.ops.batch import pack_columns
    from gubernator_tpu.types import Algorithm

    out: dict = {}
    rng = np.random.default_rng(31)

    def acols(fps, algo, hits, limit, dur, levels=None, now=NOW):
        n = fps.shape[0]
        return RequestColumns(
            fp=fps.astype(np.int64),
            algo=np.asarray(algo, dtype=np.int32) if np.ndim(algo) else
            np.full(n, algo, dtype=np.int32),
            behavior=np.array(
                [lvl << 8 for lvl in (levels or [0] * n)], dtype=np.int32
            ),
            hits=np.asarray(hits, dtype=np.int64) if np.ndim(hits) else
            np.full(n, hits, dtype=np.int64),
            limit=np.full(n, limit, dtype=np.int64),
            burst=np.zeros(n, dtype=np.int64),
            duration=np.full(n, dur, dtype=np.int64),
            created_at=np.full(n, now, dtype=np.int64),
            err=np.zeros(n, dtype=np.int8),
        )

    # ---- (a) parity at ~1M live keys
    eng = LocalEngine(capacity=1 << 20, write_mode="xla", wire="compact")
    seed_fps = []
    seed_b = 1 << 16
    for i in range(16):  # ~1M distinct live rows, algorithm-striped
        fps = rng.integers(1, (1 << 63) - 1, size=seed_b, dtype=np.int64)
        seed_fps.append(fps)
        algos = (np.arange(seed_b) % 4).astype(np.int32)
        algos[algos == 1] = 4  # token/gcra/window/lease stripes (no leaky f64)
        eng.check_columns(
            acols(fps, 0, 1, 1 << 20, 3_600_000)._replace(algo=algos),
            now_ms=NOW,
        )
    live = eng.live_count(now_ms=NOW)
    out["seeded_live_keys"] = int(live)

    oracles = {
        int(Algorithm.GCRA): GcraOracle(),
        int(Algorithm.SLIDING_WINDOW): SlidingWindowOracle(),
        int(Algorithm.CONCURRENCY_LEASE): LeaseOracle(),
    }
    mismatches = 0
    t = NOW
    # parity keys from UNCONTESTED buckets: the near-capacity seed makes
    # some buckets overflow their 8 slots, and GCRA/lease parity keys (exp
    # near now by design) would be the soonest-expiring eviction victims —
    # eviction behavior is the claim layer's contract (tests/test_kernel2),
    # this gate pins the ALGORITHM math against the 1M-live geometry
    NB = int(eng.table.rows.shape[0])
    bucket_load = np.bincount(
        (np.concatenate(seed_fps) % NB).astype(np.int64), minlength=NB
    )

    def calm_keys(a, want=512):
        picked, i = [], 0
        while len(picked) < want:
            fp = fingerprint("algsm", f"{a}k{i}")
            if bucket_load[fp % NB] <= 4:
                picked.append(fp)
            i += 1
        return np.array(picked, dtype=np.int64)

    keys = {a: calm_keys(a) for a in oracles}
    for step in range(6):
        t += int(rng.integers(100, 2_000))
        for a, oracle in oracles.items():
            hits = rng.integers(-2 if a == 4 else 0, 4, size=512)
            rc = eng.check_columns(
                acols(keys[a], a, hits, 16, 8_000, now=t), now_ms=t
            )
            for j in range(512):
                st, rem, reset = oracle.check(
                    int(keys[a][j]), t, int(hits[j]), 16, 8_000
                )
                if (int(rc.status[j]), int(rc.remaining[j]),
                        int(rc.reset_time[j])) != (st, rem, reset):
                    mismatches += 1
    out["parity_mismatches"] = mismatches
    if mismatches:
        print(json.dumps({"error": "algo smoke: device/oracle parity "
                          "mismatch at 1M keys", **out}))
        sys.exit(1)

    # ---- (b) cascade single-dispatch, compact wire, zero fallbacks
    def cascade_batch(n_casc, now, tag="c"):
        # distinct keys per level: the single-device engine host-plans
        # duplicate (fp, level) groups into sequential passes for exact
        # semantics — shared tenant/global keys aggregate to one dispatch
        # on the mesh engines' in-trace dedup path (tests/test_algorithms
        # test_same_level_cascade_rows_aggregate)
        rows = []
        for i in range(n_casc):
            rows.extend([
                (fingerprint("casc", f"{tag}u{i}"), 0, 0, 100),
                (fingerprint("casc", f"{tag}t{i}"), int(Algorithm.SLIDING_WINDOW), 1, 10_000),
                (fingerprint("casc", f"{tag}g{i}"), int(Algorithm.GCRA), 2, 1 << 20),
            ])
        n = len(rows)
        return RequestColumns(
            fp=np.array([r[0] for r in rows], dtype=np.int64),
            algo=np.array([r[1] for r in rows], dtype=np.int32),
            behavior=np.array([r[2] << 8 for r in rows], dtype=np.int32),
            hits=np.ones(n, dtype=np.int64),
            limit=np.array([r[3] for r in rows], dtype=np.int64),
            burst=np.zeros(n, dtype=np.int64),
            duration=np.full(n, 60_000, dtype=np.int64),
            created_at=np.full(n, now, dtype=np.int64),
            err=np.zeros(n, dtype=np.int8),
        )

    ceng = LocalEngine(capacity=1 << 15, write_mode="xla", wire="compact")
    cb = cascade_batch(64, NOW)
    hb, errs = pack_columns(cb, NOW)
    enc = wire_mod.wire_encodable(hb, wire_mod.pick_base(hb))
    d0 = ceng.stats.dispatches
    rc = ceng.check_columns(cb, now_ms=NOW)
    out["cascade_encodable"] = bool(enc)
    out["cascade_dispatches"] = int(ceng.stats.dispatches - d0)
    if not enc or ceng.stats.dispatches - d0 != 1 or rc.err.any():
        print(json.dumps({"error": "algo smoke: encodable 3-level cascade "
                          "did not resolve in one compact dispatch", **out}))
        sys.exit(1)

    # ---- (c) cascade vs three dependent sequential checks, e2e loopback
    from gubernator_tpu.config import BehaviorConfig, DaemonConfig
    from gubernator_tpu.proto import gubernator_pb2 as pb
    from gubernator_tpu.service.daemon import Daemon

    def creq(i, now):
        r = pb.RateLimitReq(name="cas", unique_key=f"u{i}", hits=1,
                            limit=1 << 20, duration=60_000, created_at=now)
        r.cascade.add(name="cas_t", unique_key=f"t{i % 8}", limit=1 << 20,
                      duration=60_000, algorithm=pb.SLIDING_WINDOW)
        r.cascade.add(name="cas_g", unique_key="all", limit=1 << 20,
                      duration=60_000, algorithm=pb.GCRA)
        return r

    def sreqs(i, now):
        return [
            pb.RateLimitReq(name="cas", unique_key=f"u{i}", hits=1,
                            limit=1 << 20, duration=60_000, created_at=now),
            pb.RateLimitReq(name="cas_t", unique_key=f"t{i % 8}", hits=1,
                            limit=1 << 20, duration=60_000, created_at=now,
                            algorithm=pb.SLIDING_WINDOW),
            pb.RateLimitReq(name="cas_g", unique_key="all", hits=1,
                            limit=1 << 20, duration=60_000, created_at=now,
                            algorithm=pb.GCRA),
        ]

    N_CHECKS, WORKERS = 256, 32

    async def run_e2e():
        d = await Daemon.spawn(DaemonConfig(
            grpc_address="127.0.0.1:0", http_address="",
            cache_size=1 << 15,
            behaviors=BehaviorConfig(batch_wait_ms=0.5),
        ))

        async def casc_worker(w, now):
            for i in range(w, N_CHECKS, WORKERS):
                data = pb.GetRateLimitsReq(
                    requests=[creq(i, now)]
                ).SerializeToString()
                await d.get_rate_limits_raw(data)

        async def seq_worker(w, now):
            for i in range(w, N_CHECKS, WORKERS):
                # three DEPENDENT round trips — each level waits for the
                # previous verdict, the pattern a cascade replaces
                for r in sreqs(i, now):
                    data = pb.GetRateLimitsReq(
                        requests=[r]
                    ).SerializeToString()
                    await d.get_rate_limits_raw(data)

        async def wall(worker, now) -> float:
            t0 = time.perf_counter()
            await asyncio.gather(*(worker(w, now) for w in range(WORKERS)))
            return time.perf_counter() - t0

        # warm both shapes, then best-of-3 each
        await wall(casc_worker, NOW)
        await wall(seq_worker, NOW)
        casc = min([await wall(casc_worker, NOW + 1 + k) for k in range(3)])
        seq = min([await wall(seq_worker, NOW + 10 + k) for k in range(3)])
        await d.close()
        return casc, seq

    casc_s, seq_s = asyncio.run(run_e2e())
    ratio = seq_s / max(casc_s, 1e-9)
    out["cascade_wall_s"] = round(casc_s, 4)
    out["sequential_wall_s"] = round(seq_s, 4)
    out["cascade_speedup"] = round(ratio, 2)
    if ratio < 2.5:
        print(json.dumps({"error": "algo smoke: 3-level cascade under 2.5x "
                          "the checks/s of three sequential round trips",
                          **out}))
        sys.exit(1)
    return out


def layout_smoke() -> dict:
    """Packed slot-layout regression gate (PR 11):

    (a) **bytes/slot** — the packed layouts must hold ≥1.8× fewer bytes
        per slot than the full layout (measured on the actual table
        arrays, not the descriptor constants), i.e. bytes/slot ≤ 0.55×;
    (b) **decision parity at scale** — a gcra32 (and token32) table must
        match the full-layout oracle decision-for-decision over ~1M-key
        traffic with duplicates and time steps (the CPU-CI proxy for the
        TPU 100M-key acceptance run);
    (c) **checkpoint/delta bytes shrink proportionally** — the same dirty
        set's delta frame under the packed layout must be ≤ 0.6× the
        full-layout frame's bytes;
    (d) **full stays bit-identical** — layout="full" and the pre-layout
        default produce byte-equal tables for identical traffic.
    """
    from gubernator_tpu.ops.checkpoint import (
        EpochTracker, extract_begin, finish_extract,
    )
    from gubernator_tpu.store import encode_delta_frame

    rng = np.random.default_rng(17)
    out: dict = {}

    # ---- (d) full byte-identity pin
    fp0 = rng.integers(1, (1 << 63) - 1, size=B, dtype=np.int64)
    e_full = LocalEngine(capacity=1 << 14, write_mode="xla", layout="full")
    e_def = LocalEngine(capacity=1 << 14, write_mode="xla")
    for t in (NOW, NOW + 1000):
        e_full.check_columns(cols(fp0), now_ms=t)
        e_def.check_columns(cols(fp0), now_ms=t)
    if not np.array_equal(np.asarray(e_full.table.rows),
                          np.asarray(e_def.table.rows)):
        print(json.dumps({"error": "layout smoke: layout=full diverged "
                          "from the pre-layout default table bytes"}))
        sys.exit(1)
    out["full_bit_identical"] = True

    # ---- (a)+(b) packed parity over a ~1M-key population
    def pcols(fp, algo, hits, t):
        n = fp.shape[0]
        return cols(fp)._replace(
            algo=np.full(n, algo, dtype=np.int32),
            hits=np.asarray(hits, dtype=np.int64),
            limit=np.full(n, 64, dtype=np.int64),
            duration=np.full(n, 60_000, dtype=np.int64),
            created_at=np.full(n, t, dtype=np.int64),
        )

    n_seed = 1 << 20
    seed_fps = np.unique(rng.integers(
        1, (1 << 63) - 1, size=n_seed + (n_seed >> 3), dtype=np.int64
    ))[:n_seed]
    for lay, algo in (("gcra32", 2), ("token32", 0)):
        full_e = LocalEngine(capacity=1 << 21, write_mode="xla",
                             layout="full")
        pack_e = LocalEngine(capacity=1 << 21, write_mode="xla", layout=lay)
        bytes_full = np.asarray(full_e.table.rows).nbytes
        bytes_pack = np.asarray(pack_e.table.rows).nbytes
        ratio = bytes_pack / bytes_full
        out[f"{lay}_bytes_per_slot_ratio"] = round(ratio, 3)
        out[f"{lay}_live_keys_per_gb_gain"] = round(1.0 / ratio, 2)
        if ratio > 0.55:
            print(json.dumps({"error": f"layout smoke: {lay} bytes/slot "
                              f"ratio {ratio:.3f} above the 0.55 floor",
                              **out}))
            sys.exit(1)
        t = NOW
        bsz = 1 << 16
        for i in range(0, n_seed, bsz):  # seed ~1M live keys
            sl = seed_fps[i:i + bsz]
            h = np.ones(sl.shape[0], dtype=np.int64)
            full_e.check_columns(pcols(sl, algo, h, t), now_ms=t)
            pack_e.check_columns(pcols(sl, algo, h, t), now_ms=t)
        mism = 0
        for step in range(4):  # re-hit a slice, duplicates included
            t += int(rng.integers(100, 5_000))
            sel = seed_fps[rng.integers(0, n_seed, size=4096)]
            h = rng.integers(0, 4, size=4096)
            a = full_e.check_columns(pcols(sel, algo, h, t), now_ms=t)
            b = pack_e.check_columns(pcols(sel, algo, h, t), now_ms=t)
            for f in ("status", "remaining", "reset_time", "err"):
                mism += int((np.asarray(getattr(a, f))
                             != np.asarray(getattr(b, f))).sum())
        out[f"{lay}_parity_mismatches"] = mism
        out[f"{lay}_live"] = pack_e.live_count(t)
        if mism or pack_e.stats.layout_migrations:
            print(json.dumps({"error": f"layout smoke: {lay} parity vs the "
                              "full-layout oracle failed", **out}))
            sys.exit(1)
        if pack_e.live_count(t) != full_e.live_count(t):
            print(json.dumps({"error": f"layout smoke: {lay} live-key count "
                              "diverged from full", **out}))
            sys.exit(1)

        # ---- (c) checkpoint bytes shrink with the layout
        if lay == "gcra32":
            for e, label in ((full_e, "full"), (pack_e, "packed")):
                e.ckpt = EpochTracker(e.table.rows.shape[0])
                e.check_columns(
                    pcols(seed_fps[: 1 << 14],
                          algo, np.ones(1 << 14, dtype=np.int64), t),
                    now_ms=t,
                )
                _, gids = e.ckpt.take()
                _f, slots = finish_extract(extract_begin(
                    e.table.rows, gids, e.ckpt.blk, t, layout=e.table.layout
                ))
                frame = encode_delta_frame(1, t, slots, layout=e.table.layout)
                out[f"delta_bytes_{label}"] = len(frame)
            dratio = out["delta_bytes_packed"] / max(out["delta_bytes_full"], 1)
            out["delta_bytes_ratio"] = round(dratio, 3)
            if dratio > 0.6:
                print(json.dumps({"error": "layout smoke: packed delta "
                                  "frame not proportionally smaller", **out}))
                sys.exit(1)
    return out


def region_smoke() -> dict:
    """Multi-region active-active regression gate (docs/robustness.md
    "Multi-region active-active"; ISSUE 12 acceptance):

    (a) **exact convergence** — a two-region loopback cluster with
        concurrent hits on K keys in BOTH regions converges every key to
        the exact union of hits, within a bounded number of sync
        intervals;
    (b) **bounded partition over-admission** — with the inter-region link
        blackholed under live traffic, each region keeps serving locally
        with zero request errors, total admissions stay ≤ Σ per-region
        limits, and the over-admission beyond one region's limit stays ≤
        the sum of unreplicated deltas (the documented bound); after heal
        both regions reconverge;
    (c) **compact-wire engagement** — encodable replication traffic rides
        the SyncRegionsWire merge codec with ZERO proto fallbacks.
    """
    import asyncio

    from gubernator_tpu.config import BehaviorConfig
    from gubernator_tpu.proto import gubernator_pb2 as pb
    from gubernator_tpu.types import Behavior
    from tests.cluster import Cluster, wait_for

    MR = int(Behavior.MULTI_REGION)
    SYNC_S = 0.025
    out: dict = {}

    def mr(key, hits, limit=100):
        return pb.RateLimitReq(
            name="rs", unique_key=key, hits=hits, limit=limit,
            duration=600_000, behavior=MR,
        )

    async def run():
        beh = BehaviorConfig(
            batch_wait_ms=1.0,
            global_sync_wait_ms=SYNC_S * 1e3,
            batch_timeout_ms=5000.0,
            global_timeout_ms=300.0,
            region_requeue_retries=100_000,  # ride out the partition
            peer_breaker_errors=3,
            peer_breaker_backoff_base_ms=200.0,
            peer_breaker_backoff_cap_ms=1_000.0,
        )
        c = await Cluster.start(
            2, dcs=["dc-a", "dc-b"], chaos=True, behaviors=beh
        )
        a, b = c.daemons
        try:
            # ---- (a) exact per-key convergence of totals
            rng = np.random.default_rng(7)
            K = 64
            ha = rng.integers(1, 30, size=K)
            hb = rng.integers(1, 30, size=K)
            ra = await a.get_rate_limits(
                [mr(f"k{i}", int(ha[i])) for i in range(K)]
            )
            rb = await b.get_rate_limits(
                [mr(f"k{i}", int(hb[i])) for i in range(K)]
            )
            if any(r.error for r in ra + rb):
                print(json.dumps({"error": "region smoke: serve error",
                                  **out}))
                sys.exit(1)
            want = [100 - int(ha[i] + hb[i]) for i in range(K)]
            t0 = time.perf_counter()

            async def conv():
                xa = await a.get_rate_limits(
                    [mr(f"k{i}", 0) for i in range(K)]
                )
                xb = await b.get_rate_limits(
                    [mr(f"k{i}", 0) for i in range(K)]
                )
                return all(
                    xa[i].remaining == xb[i].remaining == want[i]
                    for i in range(K)
                )

            try:
                await wait_for(conv, timeout_s=20)
            except TimeoutError:
                print(json.dumps({"error": "region smoke: two-region "
                                  "totals did not converge to the exact "
                                  "union", **out}))
                sys.exit(1)
            wall = time.perf_counter() - t0
            out["converged_keys"] = K
            out["convergence_wall_s"] = round(wall, 3)
            out["convergence_sync_intervals"] = round(wall / SYNC_S, 1)

            # ---- (c) compact-wire engagement, zero fallbacks
            out["wire_sent"] = (
                a.region_manager.wire_sent + b.region_manager.wire_sent
            )
            out["wire_fallback"] = (
                a.region_manager.wire_fallback
                + b.region_manager.wire_fallback
            )
            out["rows_merged"] = (
                a.region_manager.rows_merged + b.region_manager.rows_merged
            )
            if out["wire_sent"] == 0 or out["wire_fallback"] != 0:
                print(json.dumps({"error": "region smoke: encodable "
                                  "traffic did not ride the compact merge "
                                  "codec", **out}))
                sys.exit(1)
            # steady-state replication entries (strings + slots only on a
            # key's FIRST batch) must stay a fixed 40 B/row (32 B lane+hits
            # + 8 B cumulative dedup counter) — smaller than the classic
            # proto fallback for the same items
            from gubernator_tpu.proto import peers_pb2 as peers_pb
            from gubernator_tpu.service.wire import (
                split_region_encodable, sync_regions_pb,
            )

            bp = [(f"rs_b{i}", pb.RateLimitReq(
                name="rs", unique_key=f"tenant-{i:03d}/user-{i:08d}",
                hits=3, limit=100, duration=600_000, behavior=MR,
                created_at=a.now_ms(),
            )) for i in range(256)]
            e2, f2 = split_region_encodable(bp)
            steady = sync_regions_pb(
                e2, "ci", "dc-a",
                detail_rows=np.zeros(len(e2), dtype=bool),
                cums=np.arange(1, len(e2) + 1, dtype=np.int64) * 1000,
            ).ByteSize() / len(e2)
            proto_b = peers_pb.GetPeerRateLimitsReq(
                requests=[it for _k, it in bp]
            ).ByteSize() / len(bp)
            out["steady_state_bytes_per_row"] = round(steady, 1)
            out["proto_bytes_per_row"] = round(proto_b, 1)
            if f2 or steady > 44 or steady >= proto_b:
                print(json.dumps({"error": "region smoke: steady-state "
                                  "codec rows are not proportionally "
                                  "smaller than the proto fallback",
                                  **out}))
                sys.exit(1)

            # ---- (b) partition: degraded-local + bounded over-admission
            LIMIT = 50

            def pk(hits):
                return pb.RateLimitReq(
                    name="rs", unique_key="part", hits=hits, limit=LIMIT,
                    duration=600_000, behavior=MR,
                )

            for p in c.proxies:
                p.set_mode("blackhole")
            t0 = time.monotonic()
            admitted = errors = 0
            while time.monotonic() - t0 < 1.0:  # ≥ 40 sync intervals
                for d in (a, b):
                    r = (await d.get_rate_limits([pk(1)]))[0]
                    if r.error:
                        errors += 1
                    elif r.status == pb.UNDER_LIMIT:
                        admitted += 1
                await asyncio.sleep(0.005)
            out["partition_admitted"] = admitted
            out["partition_errors"] = errors
            if errors:
                print(json.dumps({"error": "region smoke: request errors "
                                  "during the partition", **out}))
                sys.exit(1)
            if admitted > 2 * LIMIT:
                print(json.dumps({"error": "region smoke: partition "
                                  "admissions exceeded Σ per-region "
                                  "limits", **out}))
                sys.exit(1)
            unreplicated = 0
            for d in (a, b):
                for pend in d.region_manager._pending.values():
                    it = pend.get("rs_part")
                    if it is not None:
                        unreplicated += it.hits
            over = max(0, admitted - LIMIT)
            out["partition_over_admission"] = over
            out["partition_unreplicated_deltas"] = int(unreplicated)
            if over > unreplicated:
                print(json.dumps({"error": "region smoke: over-admission "
                                  "exceeded the documented Σ-unreplicated-"
                                  "deltas bound", **out}))
                sys.exit(1)

            # ---- heal: backlog drains through the merge, reconverge
            for p in c.proxies:
                p.heal()

            async def healed():
                xa = (await a.get_rate_limits([pk(0)]))[0].remaining
                xb = (await b.get_rate_limits([pk(0)]))[0].remaining
                return xa == xb == max(0, LIMIT - admitted)

            try:
                await wait_for(healed, timeout_s=20, interval_s=0.1)
            except TimeoutError:
                print(json.dumps({"error": "region smoke: regions did not "
                                  "reconverge after heal", **out}))
                sys.exit(1)
            out["healed"] = True

            async def drained():
                return max(
                    a.region_manager.oldest_delta_age_s(),
                    b.region_manager.oldest_delta_age_s(),
                ) == 0.0

            try:
                await wait_for(drained, timeout_s=10, interval_s=0.1)
            except TimeoutError:
                print(json.dumps({"error": "region smoke: staleness did "
                                  "not drain to 0 after heal", **out}))
                sys.exit(1)
            out["staleness_drained"] = True
        finally:
            await c.stop()

    asyncio.run(run())
    return out


def lease_smoke() -> dict:
    """Edge quota-lease regression gate (ISSUE 13 acceptance):

    (a) **fan-in cut ≥50×** — a LocalLimiter under LEASE CHURN (short
        TTL, adaptive grants, live renew/return traffic) must serve
        client-side admissions at ≥50× the e2e per-check RPC rate
        through the same loopback daemon;
    (b) **over-admission bound** — total admissions ≤ limit + Σ
        outstanding leases, asserted exactly, INCLUDING across a daemon
        kill -9 + checkpoint-backed warm restart (the restarted daemon
        remembers leased consumption; the edge keeps only its
        outstanding slice);
    (c) **TTL reclamation** — an unrenewed lease's ledger tokens flow
        back by TTL eviction alone (fresh acquires regain the full cap)
        while the real-limit consumption stays (conservative).
    """
    import asyncio
    import tempfile

    from gubernator_tpu.client import V1Client
    from gubernator_tpu.edge import LocalLimiter
    from gubernator_tpu.proto import gubernator_pb2 as pb
    from tests.cluster import Cluster, wait_for

    MINUTE = 60_000
    out: dict = {}

    async def run():
        tmp = tempfile.mkdtemp()
        c = await Cluster.start(
            1,
            checkpoint_path=os.path.join(tmp, "ckpt.bin"),
            checkpoint_interval_ms=25.0,
        )
        d = c.daemons[0]
        try:
            cl = V1Client(d.conf.grpc_address)

            # ---- per-check RPC baseline: 8 concurrent single-item
            # checkers through the full front door (the fan-in every
            # check pays without leases)
            rpc_n = 0

            async def rpc_worker(i, deadline):
                nonlocal rpc_n
                while time.perf_counter() < deadline:
                    r = (await cl.get_rate_limits([pb.RateLimitReq(
                        name="rpcrate", unique_key=f"u{i}", hits=1,
                        limit=1 << 30, duration=MINUTE,
                    )])).responses[0]
                    assert not r.error
                    rpc_n += 1

            t0 = time.perf_counter()
            deadline = t0 + 0.4
            await asyncio.gather(*(rpc_worker(i, deadline)
                                   for i in range(8)))
            rpc_rate = rpc_n / (time.perf_counter() - t0)
            out["per_check_rpc_per_sec"] = round(rpc_rate, 1)

            # ---- client-side admission rate under lease churn: short
            # TTL + modest initial grant force live renew/return traffic
            # while 2 threads hammer the local budget
            lim = LocalLimiter(
                d.conf.grpc_address, "edge", "hot", limit=1 << 24,
                duration=MINUTE, ttl_ms=200, initial_grant=4096,
            )
            await lim.start()
            stop = [False]
            counts = [0, 0]

            def admit_worker(i):
                while not stop[0]:
                    if lim.allow():
                        counts[i] += 1
                    else:
                        time.sleep(0.0005)

            loop = asyncio.get_running_loop()
            t0 = time.perf_counter()
            futs = [loop.run_in_executor(None, admit_worker, i)
                    for i in range(2)]
            await asyncio.sleep(0.6)
            stop[0] = True
            await asyncio.gather(*futs)
            wall = time.perf_counter() - t0
            local_rate = sum(counts) / wall
            out["client_admissions_per_sec"] = round(local_rate, 1)
            out["lease_renewals"] = lim.stats.grants
            out["grant_sizes"] = lim.stats.grant_sizes[:12]
            out["fanin_cut_x"] = round(local_rate / max(rpc_rate, 1), 1)
            if lim.stats.grants < 2:
                print(json.dumps({"error": "lease smoke: no lease churn "
                                  "(renewals did not fire)", **out}))
                sys.exit(1)
            if local_rate < 50 * rpc_rate:
                print(json.dumps({"error": "lease smoke: client-side "
                                  "admission rate under lease churn is "
                                  "below 50x the per-check RPC rate",
                                  **out}))
                sys.exit(1)
            # no-crash over-admission: grants pre-consume, so admissions
            # can never exceed server-side consumption
            await lim.close()
            srv = (await cl.get_rate_limits([pb.RateLimitReq(
                name="edge", unique_key="hot", hits=0, limit=1 << 24,
                duration=MINUTE,
            )])).responses[0]
            consumed = (1 << 24) - srv.remaining
            out["admitted_total"] = lim.stats.local_admits
            out["consumed_server_side"] = int(consumed)
            if lim.stats.local_admits > consumed:
                print(json.dumps({"error": "lease smoke: admissions "
                                  "exceeded server-side consumption",
                                  **out}))
                sys.exit(1)

            # ---- kill -9 / warm restart: admissions ≤ limit + Σ
            # outstanding-at-crash
            LIMIT = 200
            lim2 = LocalLimiter(
                d.conf.grpc_address, "boom", "k", limit=LIMIT,
                duration=10 * MINUTE, ttl_ms=20_000, initial_grant=60,
            )
            await lim2.start()
            for _ in range(20):
                assert lim2.allow()
            outstanding = lim2.budget
            await asyncio.sleep(0.3)  # checkpoint covers the grant writes
            await c.crash_restart(0)
            d2 = c.daemons[0]
            while lim2.allow():
                pass
            for _ in range(3 * LIMIT):
                await lim2.check()
            total = lim2.stats.local_admits + lim2.stats.rpc_admits
            out["restart_outstanding_at_crash"] = outstanding
            out["restart_admitted_total"] = total
            out["restart_bound"] = LIMIT + outstanding
            if total > LIMIT + outstanding:
                print(json.dumps({"error": "lease smoke: admissions "
                                  "across kill/restart exceeded limit + "
                                  "outstanding-at-crash", **out}))
                sys.exit(1)
            if total < outstanding:
                print(json.dumps({"error": "lease smoke: the restarted "
                                  "plane served nothing", **out}))
                sys.exit(1)
            await lim2.close()

            # ---- TTL reclamation without any scan
            cl2 = V1Client(d2.conf.grpc_address)
            r1 = await cl2.lease_quota(pb.LeaseQuotaReq(
                name="ttl", unique_key="k", tokens=50, limit=100,
                duration=10 * MINUTE, ttl_ms=150,
            ))
            assert r1.granted == 50, r1

            async def reclaimed():
                r = await cl2.lease_quota(pb.LeaseQuotaReq(
                    name="ttl", unique_key="k", tokens=50, limit=100,
                    duration=10 * MINUTE, ttl_ms=150,
                ))
                return r.granted == 50

            await wait_for(reclaimed, timeout_s=5)
            srv = (await cl2.get_rate_limits([pb.RateLimitReq(
                name="ttl", unique_key="k", hits=0, limit=100,
                duration=10 * MINUTE,
            )])).responses[0]
            out["ttl_reclaimed"] = True
            if srv.remaining != 0:
                print(json.dumps({"error": "lease smoke: expiry refunded "
                                  "real-limit consumption (must stay "
                                  "conservative)", **out}))
                sys.exit(1)
            await cl.close()
            await cl2.close()
        finally:
            await c.stop()

    asyncio.run(run())
    return out


def probe_smoke() -> dict:
    """Fused Pallas probe-kernel gate (ops/pallas_probe.py, interpret
    mode — the same lowering CPU CI's oracle suite runs):

    * BIT-IDENTITY: both kernels drive the same seeded ~1M-live-key table
      through the same mixed-algorithm batch sequence; any output-row or
      table-byte divergence fails the build;
    * WALL-TIME: the Pallas path must stay within 10% of the XLA path per
      dispatch at the 1M-key config (interleaved best-of-3, so machine
      weather cancels) — the interpret movement layer discharges to the
      same gather/scatter XLA runs, and a regression here means someone
      re-introduced a per-row loop or a full-table copy into it.
    """
    import jax.numpy as jnp

    from gubernator_tpu.ops.kernel2 import decide2_packed_cols
    from gubernator_tpu.ops.table2 import Table2, new_table2

    B_P = 4096
    CAP = 1 << 21  # ~1M live keys at ~0.5 load
    LIVE = 1_000_000
    rng = np.random.default_rng(23)
    keys = np.unique(rng.integers(1, (1 << 62), size=LIVE + (LIVE >> 3),
                                  dtype=np.int64))[:LIVE]

    def arr12(fp, algo, hits, now):
        n = fp.shape[0]
        z = np.zeros(n, dtype=np.int64)
        a = np.stack([
            fp, algo.astype(np.int64), z, hits,
            np.full(n, 1 << 16, dtype=np.int64), z,
            np.full(n, 3_600_000, dtype=np.int64),
            np.full(n, now, dtype=np.int64),
            np.full(n, now + 3_600_000, dtype=np.int64), z,
            np.full(n, 3_600_000, dtype=np.int64),
            np.ones(n, dtype=np.int64),
        ])
        return jnp.asarray(a)

    def batch(i, now, algos=False):
        fp = keys[(i * B_P) % LIVE:][:B_P]
        if fp.shape[0] < B_P:
            fp = keys[:B_P]
        algo = (
            np.array([(0, 2, 3, 4)[j % 4] for j in range(B_P)],
                     dtype=np.int64)
            if algos else np.zeros(B_P, dtype=np.int64)
        )
        hits = rng.integers(0, 3, size=B_P).astype(np.int64)
        return arr12(fp, algo, hits, now)

    # seed ONCE through the XLA kernel, then hand both kernels identical
    # table bytes (seeding twice would double the smoke's wall time)
    t_seed = new_table2(CAP)
    for i in range(LIVE // B_P):
        t_seed, out = decide2_packed_cols(
            t_seed, batch(i, NOW), write="xla", math="token"
        )
        if i % 32 == 31:
            np.asarray(out)
    rows_np = np.asarray(t_seed.rows)
    tx = Table2(rows=jnp.asarray(rows_np))
    tp = Table2(rows=jnp.asarray(rows_np.copy()))

    # ---- parity drive: mixed algorithms over the seeded keyspace
    mismatches = 0
    for i in range(24):
        b = batch(7 * i, NOW + 1_000 * i, algos=True)
        tx, ox = decide2_packed_cols(tx, b, write="xla", math="int")
        tp, op = decide2_packed_cols(
            tp, b, write="xla", math="int", probe="pallas"
        )
        if not np.array_equal(np.asarray(ox), np.asarray(op)):
            mismatches += 1
    byte_equal = bool(np.array_equal(np.asarray(tx.rows), np.asarray(tp.rows)))
    out = {"parity_dispatches": 24, "mismatched_dispatches": mismatches,
           "table_bytes_equal": byte_equal}
    if mismatches or not byte_equal:
        print(json.dumps({"error": "probe smoke: pallas/xla divergence",
                          **out}))
        sys.exit(1)

    # ---- wall-time: staged batches, reps interleaved so machine weather
    # hits both kernels alike; best-of-3 per kernel
    timed = [batch(3 * i, NOW) for i in range(16)]
    tables = {
        p: Table2(rows=jnp.asarray(rows_np.copy())) for p in ("xla", "pallas")
    }
    walls = {"xla": float("inf"), "pallas": float("inf")}
    for p in walls:  # compile + warm
        tables[p], o = decide2_packed_cols(
            tables[p], timed[0], write="xla", math="token", probe=p
        )
        np.asarray(o)
    for _ in range(3):
        for p in walls:
            t = tables[p]
            t0 = time.perf_counter()
            for b in timed:
                t, o = decide2_packed_cols(
                    t, b, write="xla", math="token", probe=p
                )
            np.asarray(o)
            walls[p] = min(walls[p], time.perf_counter() - t0)
            tables[p] = t

    xla_ms = walls["xla"] / 16 * 1e3
    pallas_ms = walls["pallas"] / 16 * 1e3
    ratio = pallas_ms / xla_ms
    from gubernator_tpu.ops.layout import FULL
    from gubernator_tpu.ops.pallas_probe import hbm_bytes_per_decision

    out.update({
        "xla_ms_per_dispatch": round(xla_ms, 2),
        "pallas_ms_per_dispatch": round(pallas_ms, 2),
        "pallas_over_xla": round(ratio, 3),
        "hbm_bytes_per_decision": {
            p: round(hbm_bytes_per_decision(FULL, B_P, CAP >> 3, "xla", p), 1)
            for p in ("xla", "pallas")
        },
    })
    if ratio > 1.10:
        print(json.dumps({"error": "probe smoke: pallas interpret path "
                          ">10% over the XLA path", **out}))
        sys.exit(1)
    return out


def tier_smoke() -> dict:
    """Hot-set tiering gate (ISSUE 15 acceptance, docs/tiering.md):

    (a) **capacity**: ≥4× tracked keys beyond table capacity with ZERO
        over-grants vs the token-bucket oracle (non-refilling window ⇒
        per-key admissions ≤ limit) — eviction is a tiering event, not a
        permissive re-grant. A control run without tiering must
        over-grant, or the scenario stopped exercising eviction;
    (b) **hot-set throughput**: Zipf traffic whose hot set lives in HBM
        must stay within 15% of the no-tiering engine on the SAME
        batches (interleaved best-of-5). The CPU proxy's serial python
        front end exaggerates the sidecar/probe overhead a TPU pipeline
        overlaps — run-to-run machine noise alone swings this ratio
        ±5%, so the CPU gate carries margin and the ≥0.9× acceptance
        bit is recorded by the bench `tiering` phase on the device run
        (the same split as the layout/probe TPU claims);
    (c) **byte bound**: the shadow's RAM set stays within
        GUBER_TIER_SHADOW_BYTES with LRU shedding counted.
    """
    from gubernator_tpu.tier import ROW_BYTES, ShadowTable

    rng = np.random.default_rng(31)
    CAP = 1 << 12          # 4096 slots (512 buckets)
    TRACKED = 4 * CAP      # the ≥4× capacity claim
    LIMIT = 10
    keys = np.unique(
        rng.integers(1, 1 << 62, size=TRACKED + 256, dtype=np.int64)
    )[:TRACKED]

    def mkcols(fp, now, hits):
        n = fp.shape[0]
        return RequestColumns(
            fp=fp, algo=np.zeros(n, dtype=np.int32),
            behavior=np.zeros(n, dtype=np.int32),
            hits=np.full(n, hits, dtype=np.int64),
            limit=np.full(n, LIMIT, dtype=np.int64),
            burst=np.zeros(n, dtype=np.int64),
            duration=np.full(n, 3_600_000, dtype=np.int64),
            created_at=np.full(n, now, dtype=np.int64),
            err=np.zeros(n, dtype=np.int8),
        )

    def drive(eng):
        adm = np.zeros(TRACKED, dtype=np.int64)
        t = NOW
        for _ in range(4):
            for i in range(0, TRACKED, 2048):
                rc = eng.check_columns(mkcols(keys[i:i + 2048], t, 3),
                                       now_ms=t)
                ok = (rc.status == 0) & (rc.err == 0)
                adm[i:i + 2048][ok] += 3
                t += 7
        return adm

    eng = LocalEngine(capacity=CAP, write_mode="xla")
    eng.attach_shadow(ShadowTable(max_bytes=TRACKED * ROW_BYTES))
    adm = drive(eng)
    over = int((adm > LIMIT).sum())
    st = eng.shadow.stats()
    out = {
        "capacity_slots": CAP,
        "tracked_keys": TRACKED,
        "tracked_x_capacity": TRACKED / CAP,
        "over_granted_keys": over,
        "demoted_evict": st["demoted_evict"],
        "promoted": st["promoted"],
    }
    if over:
        print(json.dumps({"error": "tier smoke: over-grants with tiering "
                          "on (eviction lost state)", **out}))
        sys.exit(1)
    if st["demoted_evict"] == 0:
        print(json.dumps({"error": "tier smoke: no demotions — the drive "
                          "no longer exercises eviction", **out}))
        sys.exit(1)
    ctrl = LocalEngine(capacity=CAP, write_mode="xla")
    adm_ctrl = drive(ctrl)
    out["control_over_granted_keys"] = int((adm_ctrl > LIMIT).sum())
    if out["control_over_granted_keys"] == 0:
        print(json.dumps({"error": "tier smoke: the no-tiering control "
                          "did not over-grant", **out}))
        sys.exit(1)

    # ---- (b) hot-set throughput, interleaved best-of-3. The claim under
    # test: the tiering MACHINERY (sidecar fetch, shadow probes) costs
    # the HBM-resident hot set ≤ 10% — so the gate times Zipf-shaped
    # HOT-SET batches on an engine tracking 4× capacity (cold majority
    # demoted by the sweep, the TierManager operating point) against the
    # all-HBM no-tiering baseline. The mixed 90/10 stream — where ~10% of
    # rows FAULT BACK through the merge, work the baseline skips by
    # over-granting — is measured and REPORTED (mixed_rate_*), not
    # gated: paging the tail is the new capability, not overhead.
    # the hot set is the LAST-seeded slice: the idle reference is the
    # stored stamp (a token row's window creation — docs/tiering.md
    # "idle detection"), so the sweep separates hot from cold by
    # creation order here. Collision-capped at ≤6 keys per bucket so no
    # bucket hosts > K hot keys (a bucket that does thrashes by
    # GEOMETRY, tiering or not — the >K pathology docs/tiering.md
    # bounds); Zipf-shaped draws at the serving plane's coalesced batch
    # size (unique ~1.7K rows/dispatch).
    NBUCK = CAP // 8
    tail = keys[TRACKED - CAP // 2:]
    per = {}
    hot_sel = []
    for k in tail.tolist():
        b = k % NBUCK
        if per.get(b, 0) < 6:
            per[b] = per.get(b, 0) + 1
            hot_sel.append(k)
    hot = np.asarray(hot_sel, dtype=np.int64)
    HOT = hot.shape[0]
    zr = np.minimum(rng.zipf(1.05, size=80 * 2048) - 1, HOT - 1)
    t = NOW + 10_000_000
    hot_batches = []
    for i in range(16):
        fp = np.unique(hot[zr[i * 3072:(i + 1) * 3072]])
        hot_batches.append((fp, t))
        t += 13
    mixed_batches = []
    for i in range(8):
        h = hot[zr[(16 + i) * 3072:(16 + i) * 3072 + 1844]]
        cold_draw = keys[:TRACKED - HOT][
            rng.integers(0, TRACKED - HOT, size=204)
        ]
        fp = np.unique(np.concatenate([h, cold_draw]))
        mixed_batches.append((fp, t))
        t += 13
    engines = {}
    for tag in ("tiering", "baseline"):
        e = LocalEngine(capacity=CAP, write_mode="xla")
        tt = NOW + 9_000_000
        if tag == "tiering":
            e.attach_shadow(ShadowTable(max_bytes=TRACKED * ROW_BYTES))
            # seed the COLD majority, then the hot set a beat later —
            # the idle sweep keys off the stored stamp (a token row's
            # window creation, docs/tiering.md), so the age gap is what
            # separates the tiers here
            cold_keys = keys[:TRACKED - HOT]
            for i in range(0, cold_keys.shape[0], 2048):
                e.check_columns(mkcols(cold_keys[i:i + 2048], tt, 1),
                                now_ms=tt)
                tt += 7
            tt += 2_000
            e.check_columns(mkcols(hot, tt, 1), now_ms=tt)
            # the cadence sweep a live daemon runs (TierManager):
            # demotes the cold seed waves, keeps the fresher hot set
            fps, slots = e.extract_idle(tt + 100, 1_000, max_rows=TRACKED)
            if fps.shape[0]:
                e.tombstone_fps(fps)
                e.shadow.offer(
                    fps, np.asarray(e.table.layout.unpack(slots)), tt + 100,
                    reason="idle",
                )
        else:
            e.check_columns(mkcols(hot, tt, 1), now_ms=tt)
        # warm every compiled shape before timing
        for fp, bt in hot_batches[:4]:
            e.check_columns(mkcols(fp, bt, 1), now_ms=bt)
        engines[tag] = e
    walls = {"tiering": float("inf"), "baseline": float("inf")}
    rows_total = sum(b[0].shape[0] for b in hot_batches[4:])
    for _ in range(5):  # interleaved best-of-5: CI-runner weather cancels
        for tag, e in engines.items():
            t0 = time.perf_counter()
            for fp, bt in hot_batches[4:]:
                e.check_columns(mkcols(fp, bt, 1), now_ms=bt)
            walls[tag] = min(walls[tag], time.perf_counter() - t0)
    rate = {k: rows_total / v for k, v in walls.items()}
    ratio = rate["tiering"] / rate["baseline"]
    # mixed 90/10 stream with live fault-backs — reported, not gated
    # (best-of-3; early reps eat the promote/rehydrate compiles)
    mixed_rows = sum(b[0].shape[0] for b in mixed_batches)
    mixed = {}
    for tag, e in engines.items():
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for fp, bt in mixed_batches:
                e.check_columns(mkcols(fp, bt, 1), now_ms=bt)
            best = min(best, time.perf_counter() - t0)
        mixed[tag] = mixed_rows / best
    out["mixed_rate_tiering"] = round(mixed["tiering"], 1)
    out["mixed_rate_baseline"] = round(mixed["baseline"], 1)
    out["mixed_ratio"] = round(mixed["tiering"] / mixed["baseline"], 3)
    out.update({
        "hot_set_rate_tiering": round(rate["tiering"], 1),
        "hot_set_rate_baseline": round(rate["baseline"], 1),
        "hot_set_ratio": round(ratio, 3),
    })
    if ratio < 0.85:
        print(json.dumps({"error": "tier smoke: hot-set rate with "
                          "tiering fell below 0.85x the no-tiering "
                          "baseline (CPU-proxy gate; the 0.9x claim is "
                          "the device bench's)", **out}))
        sys.exit(1)

    # ---- (c) byte bound + LRU shed accounting
    sh = ShadowTable(max_bytes=64 * ROW_BYTES)
    fps = np.arange(1, 257, dtype=np.int64)
    rows = np.zeros((256, 16), dtype=np.int32)
    rows[:, 0] = fps.astype(np.int32)
    rows[:, 10] = 1
    sh.offer(fps, rows, 0)
    out["shadow_bound_bytes"] = sh.max_bytes
    out["shadow_nominal_bytes"] = sh.nominal_bytes
    out["shadow_shed"] = sh.shed
    if sh.nominal_bytes > sh.max_bytes or sh.shed != 256 - 64:
        print(json.dumps({"error": "tier smoke: shadow byte bound or "
                          "shed accounting broken", **out}))
        sys.exit(1)
    return out


def overload_smoke() -> dict:
    """Overload-plane regression gate (docs/robustness.md "Overload &
    QoS"): a 10× flash crowd through a loopback daemon with the overload
    plane armed (bounded ring, 75 ms enqueue deadline, tier-major
    dispatch). Gated:

    (a) **zero priority inversions** — a request must never be shed for
        capacity while strictly-lower-tier rows sit admitted (the
        preempt-before-shed rule's runtime proof, counted in the batcher);
    (b) **the plane engages** — the flash step must actually shed (an
        overload gate that never sheds is gating nothing);
    (c) **goodput floor** — during the 10× step the door must keep serving:
        goodput ≥ 25% of the offered flood AND ≥ 80% of the pre-flash
        goodput (the anti-collapse bound — shedding is for the excess, not
        the base load);
    (d) **bounded top-tier p99** — tier-3 requests must clear the flash
        step under a fixed wall (generous for CI weather; the disarmed
        door's queue grows without bound here, so ANY fixed bound
        separates armed from unarmed).
    """
    from bench import drive_overload_scenario

    res = drive_overload_scenario(
        "flash_crowd", seconds_per_step=1.5, base_workers=4,
        rows_per_req=128, keys=1 << 14, coalesce_limit=1024,
        batch_queue_rows=2048, overload_deadline_ms=75.0,
    )
    steps = {s["step"]: s for s in res["curve"]}
    pre, flash = steps["pre"], steps["flash"]
    shed_total = sum(flash["sheds"].values())
    tier3_p99 = flash["request_p99_ms_by_tier"].get("3", 0.0)
    out = {
        "offered_flash_rows_per_s": flash["offered_rows_per_s"],
        "goodput_flash_rows_per_s": flash["goodput_rows_per_s"],
        "goodput_pre_rows_per_s": pre["goodput_rows_per_s"],
        "flash_sheds": flash["sheds"],
        "tier3_flash_p99_ms": tier3_p99,
        "priority_inversions": res["priority_inversions"],
        "shed_by_tier": res["shed_by_tier"],
    }
    if res["priority_inversions"]:
        print(json.dumps({"error": "overload smoke: priority inversions "
                          "under the saturated ring", **out}))
        sys.exit(1)
    if shed_total == 0:
        print(json.dumps({"error": "overload smoke: the 10x flash crowd "
                          "never shed — the overload plane did not engage",
                          **out}))
        sys.exit(1)
    if (flash["goodput_rows_per_s"] < 0.25 * flash["offered_rows_per_s"]
            or flash["goodput_rows_per_s"]
            < 0.8 * pre["goodput_rows_per_s"]):
        print(json.dumps({"error": "overload smoke: goodput collapsed "
                          "under the flash crowd", **out}))
        sys.exit(1)
    if tier3_p99 > 2_000.0:
        print(json.dumps({"error": "overload smoke: top-tier p99 unbounded "
                          "under the flash crowd", **out}))
        sys.exit(1)
    return out


def main() -> None:
    eng = LocalEngine(capacity=1 << 15, write_mode="xla")
    rng = np.random.default_rng(0)
    fps = [
        rng.integers(1, (1 << 63) - 1, size=B, dtype=np.int64) for _ in range(4)
    ]
    for f in fps:  # compile + seed
        eng.check_columns(cols(f), now_ms=NOW)
    best = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        n_disp = 64
        for i in range(n_disp):
            eng.check_columns(cols(fps[i % 4]), now_ms=NOW)
        dt = time.perf_counter() - t0
        best = max(best, n_disp * B / dt)
    print(json.dumps({
        "decisions_per_sec": round(best, 1),
        "sharded_smoke": sharded_smoke(),
        "wire_smoke": wire_smoke(),
        "handoff_smoke": handoff_smoke(),
        "serving_smoke": serving_smoke(),
        "telemetry_smoke": telemetry_smoke(),
        "mesh_smoke": mesh_smoke(),
        "durability_smoke": durability_smoke(),
        "algo_smoke": algo_smoke(),
        "layout_smoke": layout_smoke(),
        "probe_smoke": probe_smoke(),
        "region_smoke": region_smoke(),
        "lease_smoke": lease_smoke(),
        "tier_smoke": tier_smoke(),
        "ring_smoke": ring_smoke(),
        "ring_drain_smoke": ring_drain_smoke(),
        "overload_smoke": overload_smoke(),
    }))


if __name__ == "__main__":
    main()
