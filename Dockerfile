# gubernator-tpu server image (reference: the Go repo's multi-stage
# Dockerfile; here the runtime is Python + JAX, so one stage suffices).
#
# The base image must provide jax for your accelerator:
#   CPU:  python:3.12 + pip install jax
#   TPU:  a jax[tpu] image for your libtpu release
ARG BASE_IMAGE=python:3.12-slim
FROM ${BASE_IMAGE}

WORKDIR /opt/gubernator-tpu

RUN pip install --no-cache-dir \
    "jax>=0.4.30" numpy aiohttp grpcio protobuf prometheus_client xxhash

COPY gubernator_tpu/ ./gubernator_tpu/
COPY example.conf ./

ENV PYTHONPATH=/opt/gubernator-tpu
ENV GUBER_GRPC_ADDRESS=0.0.0.0:1051
ENV GUBER_HTTP_ADDRESS=0.0.0.0:1050

EXPOSE 1050 1051 7946

# k8s probes: python -m gubernator_tpu.cmd.healthcheck
ENTRYPOINT ["python", "-m", "gubernator_tpu"]
