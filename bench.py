"""Headline benchmark: rate-limit decisions/sec on one TPU chip.

Measures steady-state decision throughput of the core kernel against the
north-star target (BASELINE.md: ≥50M decisions/sec on a v5e-8 with 10M live
keys, p99 < 2 ms → per-chip share 6.25M decisions/sec).

Setup mirrors BASELINE config #2/#3 scale on a single chip:
* 16.7M-slot HBM table (~1.5 GB), pre-seeded with 10M live keys
* token-bucket traffic over the live keyspace, 128K-decision batches,
  pipelined dispatches (async, donated table buffer)

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
plus human-readable detail on stderr.
"""

import json
import sys
import time

import numpy as np

import gubernator_tpu  # noqa: F401  (enables x64)
import jax
import jax.numpy as jnp

from gubernator_tpu.ops.batch import ReqBatch
from gubernator_tpu.ops.kernel import decide
from gubernator_tpu.ops.table import new_table
from gubernator_tpu.types import Algorithm

CAPACITY = 1 << 24  # 16.7M slots
LIVE_KEYS = 10_000_000
BATCH = 1 << 17  # 131072
N_STAGED = 8  # distinct pre-staged batches cycled through
WARMUP = 3
DISPATCHES = 48
PER_CHIP_BASELINE = 50e6 / 8  # north-star 50M/s on v5e-8 → per-chip share


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def make_batches(rng: np.random.Generator, now: int) -> list:
    """Disjoint windows of a keyspace permutation → unique fps per batch."""
    keyspace = rng.integers(1, (1 << 63) - 1, size=LIVE_KEYS, dtype=np.int64)
    perm = rng.permutation(LIVE_KEYS)
    batches = []
    zeros = np.zeros(BATCH, dtype=np.int64)
    for i in range(N_STAGED):
        fps = keyspace[perm[i * BATCH : (i + 1) * BATCH]]
        rb = ReqBatch(
            fp=jnp.asarray(fps),
            algo=jnp.full(BATCH, int(Algorithm.TOKEN_BUCKET), dtype=jnp.int32),
            behavior=jnp.zeros(BATCH, dtype=jnp.int32),
            hits=jnp.ones(BATCH, dtype=jnp.int64),
            limit=jnp.full(BATCH, 1000, dtype=jnp.int64),
            burst=jnp.asarray(zeros),
            duration=jnp.full(BATCH, 60_000, dtype=jnp.int64),
            created_at=jnp.full(BATCH, now, dtype=jnp.int64),
            expire_new=jnp.full(BATCH, now + 60_000, dtype=jnp.int64),
            greg_interval=jnp.asarray(zeros),
            duration_eff=jnp.full(BATCH, 60_000, dtype=jnp.int64),
            active=jnp.ones(BATCH, dtype=bool),
        )
        batches.append(jax.device_put(rb))
    return batches


def main() -> None:
    dev = jax.devices()[0]
    log(f"device: {dev}")
    now = int(time.time() * 1000)
    rng = np.random.default_rng(42)

    table = new_table(CAPACITY)
    batches = make_batches(rng, now)

    # seed the table: every staged batch inserted once (1M+ live keys) —
    # then cycle again so the timed phase is pure cache-hit steady state.
    # NOTE on timing: block_until_ready does not actually round-trip on the
    # tunneled axon platform, so every measurement below forces completion by
    # fetching a scalar from the dependency chain, and throughput is derived
    # from the SLOPE between a short and a long pipelined run (subtracting the
    # fixed fetch RTT).
    t0 = time.perf_counter()
    for i in range(WARMUP):
        table, resp, stats = decide(table, batches[i % N_STAGED])
    _ = int(stats.cache_hits)
    log(f"compile+warmup: {time.perf_counter() - t0:.1f}s")
    for b in batches:
        table, resp, stats = decide(table, b)
    _ = int(stats.cache_hits)

    def timed_run(n: int) -> float:
        nonlocal table
        t0 = time.perf_counter()
        stats = None
        for i in range(n):
            table, resp, stats = decide(table, batches[i % N_STAGED])
        _ = int(stats.cache_hits)  # forces the whole chain (donated table deps)
        return time.perf_counter() - t0

    timed_run(2)
    n_short, n_long = 4, 4 + DISPATCHES
    t_short = min(timed_run(n_short) for _ in range(3))
    t_long = min(timed_run(n_long) for _ in range(3))
    dt = max(t_long - t_short, 1e-9)
    dps = DISPATCHES * BATCH / dt
    per_dispatch_ms = dt / DISPATCHES * 1e3
    log(
        f"throughput (slope): {DISPATCHES} x {BATCH} decisions in {dt:.3f}s "
        f"= {dps/1e6:.2f}M/s  ({per_dispatch_ms:.2f} ms/dispatch)"
    )
    log(f"fixed overhead (short run incl. fetch RTT): {t_short*1e3:.1f} ms")
    log(f"stats sample: hits={int(stats.cache_hits)} miss={int(stats.cache_misses)}")

    print(
        json.dumps(
            {
                "metric": "ratelimit_decisions_per_sec_per_chip",
                "value": round(dps, 1),
                "unit": "decisions/s",
                "vs_baseline": round(dps / PER_CHIP_BASELINE, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
