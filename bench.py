"""Headline benchmark: rate-limit decisions/sec on one TPU chip (v2 kernel).

Measures steady-state decision throughput of the packed-row kernel
(ops/kernel2.py, Pallas sweep write) against the north-star target
(BASELINE.md: ≥50M decisions/sec on a v5e-8 with 10M live keys, p99 < 2 ms →
per-chip share 6.25M decisions/sec), plus the BASELINE config matrix:

  headline  token bucket, 16.7M-slot table, 10M live keys       (config #3 scale)
  config1   token bucket, 1K hot keys, small table              (config #1)
  config2   leaky bucket, 1M keys, Zipf-1.1 skewed traffic      (config #2)
  config4   mixed token+leaky with RESET_REMAINING/DRAIN flags  (config #4)

The headline is measured through an on-device fori_loop window
(ops/loop.decide_loop) so one launch covers the whole timed run and tunnel
RTT cancels — see Case.device_loop; every published number passes the
bench_guard sanity gates (dt floor, RTT-dominance ratio, physical rate
ceiling, proof-of-work counter reconciliation). The host-driven slope is
reported per case as the secondary serving_* figures (those DO absorb the
tunnel RTT per dispatch). Also reports per-dispatch p99 latency
(fetch-forced round trips — an upper bound on device latency) and runs a
sweep-vs-XLA write parity smoke on the real TPU (the only place the Pallas
sweep runs un-interpreted; CI meshes are CPU).

Prints exactly ONE JSON line on stdout:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "matrix": {...}}
plus human-readable detail on stderr.
"""

import json
import sys
import time

import numpy as np

import gubernator_tpu  # noqa: F401  (enables x64)
import jax
import jax.numpy as jnp

from gubernator_tpu.bench_guard import (
    WorkMismatchError,
    check_dropped,
    check_transport,
    check_work,
    slope,
)
from gubernator_tpu.ops.batch import ReqBatch
from gubernator_tpu.ops.engine import default_write_mode
from gubernator_tpu.ops.kernel2 import decide2
from gubernator_tpu.ops.loop import decide_loop, stack_batches
from gubernator_tpu.ops.table2 import new_table2
from gubernator_tpu.types import Algorithm, Behavior

PER_CHIP_BASELINE = 50e6 / 8  # north-star 50M/s on v5e-8 → per-chip share
WRITE = default_write_mode()


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def make_req_batch(
    fps: np.ndarray,
    now: int,
    hits: np.ndarray = None,
    algo: np.ndarray = None,
    behavior: np.ndarray = None,
    limit: int = 1000,
    duration: int = 60_000,
) -> ReqBatch:
    b = fps.shape[0]
    zeros = np.zeros(b, dtype=np.int64)
    algo = (
        np.full(b, int(Algorithm.TOKEN_BUCKET), dtype=np.int32)
        if algo is None
        else algo
    )
    # burst defaults to limit for the tolerance-shaped algorithms (leaky —
    # algorithms.go:259-261 — and GCRA; host packing rule)
    bursty = (algo == int(Algorithm.LEAKY_BUCKET)) | (algo == int(Algorithm.GCRA))
    limit_arr = np.full(b, limit, dtype=np.int64)
    return ReqBatch(
        fp=jnp.asarray(fps),
        algo=jnp.asarray(algo),
        behavior=jnp.asarray(
            np.zeros(b, dtype=np.int32) if behavior is None else behavior
        ),
        hits=jnp.asarray(np.ones(b, dtype=np.int64) if hits is None else hits),
        limit=jnp.asarray(limit_arr),
        burst=jnp.asarray(np.where(bursty, limit_arr, 0)),
        duration=jnp.full(b, duration, dtype=jnp.int64),
        created_at=jnp.full(b, now, dtype=jnp.int64),
        expire_new=jnp.full(b, now + duration, dtype=jnp.int64),
        greg_interval=jnp.asarray(zeros),
        duration_eff=jnp.full(b, duration, dtype=jnp.int64),
        active=jnp.ones(b, dtype=bool),
    )


def unique_agg(fps: np.ndarray) -> "tuple[np.ndarray, np.ndarray]":
    """Aggregate duplicate keys in a batch (sum hits) — the same-key
    aggregation the host pass planner / GLOBAL accumulator performs
    (reference global.go:109-123) so the kernel sees unique fingerprints."""
    ufp, counts = np.unique(fps, return_counts=True)
    return ufp, counts.astype(np.int64)


class Case:
    """One benchmark case: pre-staged device batches cycled through a
    donated-table dispatch loop.

    The HEADLINE number comes from the on-device loop (ops/loop.decide_loop):
    K kernel iterations inside one jitted fori_loop, so one launch + one
    scalar fetch covers the whole timed window and tunnel RTT cancels in the
    short/long difference — chip compute, not transport weather. The window
    grows adaptively until the guard (bench_guard.slope) accepts the timing,
    and the loop's accumulated counters must reconcile with the decision
    count the rate claims (bench_guard.check_work) before anything is
    published.

    The host-driven slope (one dispatch per host call, fetch at the end) is
    kept as the SECONDARY "serving overhead" figure — on the tunneled dev
    platform it absorbs a round trip per dispatch and is reported as such.

    `math` mirrors the engine's per-dispatch static specialization
    (ops/engine._math_mode): all-token cases compile the decision graph
    without the emulated-f64 leaky lanes. `write` overrides the backend
    default write mode (the config6 latency phase compares sweep vs sparse
    vs xla on identical traffic)."""

    def __init__(self, name, capacity, batches, seed_batches=None, seed_iter=None,
                 math="mixed", active_counts=None, write=None, layout=None,
                 probe="xla"):
        self.name = name
        from gubernator_tpu.ops.layout import resolve_layout

        self.table = new_table2(
            capacity, layout=resolve_layout(layout or "full")
        )
        self.batches = batches
        self.seed_batches = seed_batches if seed_batches is not None else batches
        self.seed_iter = seed_iter  # lazy seeding for huge keyspaces
        self.math = math
        self.write = write or WRITE
        # table-walk kernel (GUBER_PROBE_KERNEL): "xla" = gather + sweep,
        # "pallas" = the fused megakernel (ops/pallas_probe.py) — the
        # probe phase drives both on identical traffic
        self.probe = probe
        # active rows per staged batch, known host-side at construction
        # (padded cases pass the real counts; fetching active.sum() from the
        # device would cost a serialized tunnel RTT per batch)
        self.active_counts = (
            active_counts
            if active_counts is not None
            else [int(b.fp.shape[0]) for b in batches]
        )
        self.last_stats = None

    def dispatch(self, b):
        self.table, resp, stats = decide2(
            self.table, b, write=self.write, math=self.math,
            probe=self.probe,
        )
        return stats

    def seed(self) -> None:
        """Run the seed pass (compile + populate the live keyspace)."""
        t0 = time.perf_counter()
        stats = None
        for j, b in enumerate(
            self.seed_iter() if self.seed_iter else self.seed_batches
        ):
            stats = self.dispatch(b)
            if j % 8 == 7:
                # bound the async enqueue depth: a long un-synchronized seed
                # chain (config5 queues 96 dispatches x ~100 MB of staged
                # batches) can wedge the tunneled device transport
                _ = int(stats.cache_hits)
        _ = int(stats.cache_hits)
        log(f"[{self.name}] compile+seed: {time.perf_counter() - t0:.1f}s")

    def expected_decisions(self, k: int) -> int:
        """Active decisions made by k dispatches cycling the staged batches
        from batch 0 — both the proof-of-work expectation for the device
        loop and the decision unit for every published rate (padding rows
        are not decisions)."""
        n = len(self.batches)
        full, rem = divmod(k, n)
        return full * sum(self.active_counts) + sum(self.active_counts[:rem])

    def device_loop(self) -> dict:
        """Primary measurement: slope between a short and a long on-device
        fori_loop window (each is ONE launch — RTT appears once per run and
        cancels in the difference). Adaptive: on guard rejection the long
        window grows until device time dominates jitter."""
        stacked = stack_batches(self.batches)
        expected = self.expected_decisions

        def timed(k: int):
            t0 = time.perf_counter()
            self.table, acc = decide_loop(
                self.table, stacked, jnp.int32(k), write=self.write,
                math=self.math, probe=self.probe
            )
            # ONE fetch of the whole counter vector forces the launch chain
            # (per-element int() would pay one tunnel RTT per counter)
            acc = [int(x) for x in np.asarray(acc)]
            t = time.perf_counter() - t0
            bad = check_work(acc[0] + acc[1], expected(k)) or check_dropped(
                acc[3], expected(k)
            )
            if bad:
                raise WorkMismatchError(f"device loop k={k}: {bad}")
            return t, acc

        t0 = time.perf_counter()
        try:
            timed(2)  # compile + warm
        except WorkMismatchError as exc:
            # a failed proof-of-work must refuse, not kill the record
            log(f"[{self.name}] device loop invalid: {exc}")
            return {"device_invalid": str(exc)}
        log(f"[{self.name}] device-loop compile: {time.perf_counter() - t0:.1f}s")

        # dt acceptance floor for the PRIMARY rate, above the guard default:
        # small-batch cases otherwise accept windows barely past the floor,
        # where +-30 ms launch jitter still moves the rate 2x between runs.
        # The retry target and window cap derive from it so the adaptive
        # loop can always reach an acceptable window.
        MIN_DT = 0.15
        K_CAP = 65536  # at the smallest case (~60 us/iter) dt reaches ~4s
        # Autotune the short/long split PER CONFIG instead of the fixed
        # 4/68 the 10M cases were sized for: at the 100M-key config a
        # 68-iteration window conflates loop-entry overhead with the
        # table-walk cost it is supposed to isolate (the BENCH_r05 note).
        # One probe window prices this config's own per-iteration cost;
        # the short window is sized past launch jitter and the long one
        # straight to the acceptance floor, and the JSON records the
        # resolved split + the probe estimate so a recorded rate is
        # auditable against its window geometry.
        t_probe, _ = timed(4)
        per_est = max(t_probe / 4, 1e-5)
        k_short = max(4, int(0.2 * MIN_DT / per_est) + 1)
        k_long = k_short + min(K_CAP, int(1.5 * MIN_DT / per_est) + 1)
        for attempt in range(8):
            try:
                t_short = min(timed(k_short)[0] for _ in range(3))
                t_long = min(timed(k_long)[0] for _ in range(3))
            except WorkMismatchError as exc:
                log(f"[{self.name}] device loop invalid: {exc}")
                return {"device_invalid": str(exc)}
            rows_eff = (expected(k_long) - expected(k_short)) / (k_long - k_short)
            s = slope(t_short, t_long, k_short, k_long, rows_eff, min_dt=MIN_DT)
            if s.reason is None:
                log(
                    f"[{self.name}] device loop: {k_long - k_short} x "
                    f"{rows_eff:.0f} decisions in {t_long - t_short:.3f}s = "
                    f"{s.rate/1e6:.2f}M/s ({s.per_iter_ms:.2f} ms/dispatch "
                    f"on-device; t_short={t_short:.3f}s t_long={t_long:.3f}s)"
                )
                return {
                    "device_decisions_per_sec": round(s.rate, 1),
                    "device_ms": round(s.per_iter_ms, 3),
                    "device_loop_k": [k_short, k_long],
                    "device_loop_autotuned": True,
                    "device_loop_per_iter_probe_ms": round(per_est * 1e3, 3),
                }
            # size the next window from whatever signal this one carried;
            # 1.5x overshoot on the floor because the per_iter estimate is
            # itself jittered (observed: a window sized to land at 1.2x the
            # floor measured 8% under it and burned the attempt)
            dt = t_long - t_short
            if dt > 0.02:
                per_iter = dt / (k_long - k_short)
                need_dt = max(1.5 * MIN_DT, 0.8 * t_short)
                k_long = k_short + min(K_CAP, int(need_dt / per_iter) + 1)
            else:
                k_long = k_short + min(K_CAP, 2 * (k_long - k_short))
            log(f"[{self.name}] device loop rejected ({s.reason}); retry "
                f"k_long={k_long}")
        return {"device_invalid": s.reason}

    def run(self, dispatches=48, latency_probes=24):
        self.seed()
        device = self.device_loop()
        n = len(self.batches)
        # small batches dispatch in ~µs — scale the dispatch count up so the
        # timed work dwarfs tunnel RTT jitter, or the slope is pure noise
        batch_rows = int(self.batches[0].fp.shape[0])
        dispatches = min(4096, max(dispatches, dispatches * ((1 << 17) // batch_rows)))

        def timed_run(k: int):
            t0 = time.perf_counter()
            stats = None
            for i in range(k):
                stats = self.dispatch(self.batches[i % n])
            hits = int(stats.cache_hits)  # forces the chain (donated deps)
            return time.perf_counter() - t0, hits, int(stats.cache_misses)

        timed_run(2)
        n_short, n_long = 4, 4 + dispatches
        t_short = min(timed_run(n_short)[0] for _ in range(3))
        t_long, hits, misses = min(timed_run(n_long) for _ in range(3))
        batch = batch_rows
        # serving-overhead slope: one host call per dispatch, so on the
        # tunneled platform this number absorbs a round trip per dispatch —
        # it is the secondary figure; min_ratio=1.0 because RTT-dominance is
        # exactly what it reports. dt-floor and rate-ceiling still apply.
        # Decision unit = ACTIVE rows, same as the device loop (padded cases
        # would otherwise inflate the serving figure vs the device one).
        rows_eff = (
            self.expected_decisions(n_long) - self.expected_decisions(n_short)
        ) / (n_long - n_short)
        s = slope(t_short, t_long, n_short, n_long, rows_eff, min_ratio=1.0)
        # per-dispatch latency: force a round trip EVERY iteration (no
        # pipelining) — includes the host↔device fetch RTT, upper bound
        lat = []
        for i in range(latency_probes):
            t0 = time.perf_counter()
            stats = self.dispatch(self.batches[i % n])
            _ = int(stats.cache_hits)
            lat.append(time.perf_counter() - t0)
        lat_ms = np.asarray(lat) * 1e3
        p50, p99 = float(np.percentile(lat_ms, 50)), float(np.percentile(lat_ms, 99))
        out = {
            "batch": batch,
            "rt_latency_p50_ms": round(p50, 2),
            "rt_latency_p99_ms": round(p99, 2),
            "timed_hits": hits,
            "timed_misses": misses,
            **device,
        }
        if s.reason is None:
            log(
                f"[{self.name}] serving slope: {dispatches} x {rows_eff:.0f} decisions"
                f" in {t_long - t_short:.3f}s = {s.rate/1e6:.2f}M/s"
                f" ({s.per_iter_ms:.2f} ms/dispatch incl. tunnel RTT);"
                f" round-trip latency p50={p50:.1f}ms p99={p99:.1f}ms;"
                f" timed-phase stats: hits={hits} misses={misses}"
            )
            out["serving_decisions_per_sec"] = round(s.rate, 1)
            out["serving_dispatch_ms"] = round(s.per_iter_ms, 3)
        else:
            log(f"[{self.name}] serving slope rejected: {s.reason}")
            out["serving_invalid"] = s.reason
        return out


def headline_case(rng, now) -> Case:
    CAPACITY = 1 << 24  # 16.7M slots
    LIVE = 10_000_000
    BATCH = 1 << 17
    keyspace = rng.integers(1, (1 << 63) - 1, size=LIVE, dtype=np.int64)
    perm = rng.permutation(LIVE)
    batches = [
        jax.device_put(
            make_req_batch(keyspace[perm[i * BATCH : (i + 1) * BATCH]], now)
        )
        for i in range(8)
    ]
    # seed = one full pass over all staged batches → timed phase is pure
    # cache-hit steady state over 10M live keys (subset cycled)
    return Case("headline-10M", CAPACITY, batches, math="token")


def config1_case(rng, now) -> Case:
    """BASELINE config #1: token bucket over 1K hot keys. Every batch row is a
    duplicate of one of 1K keys → host aggregation, unique-key dispatches."""
    BATCH = 1 << 17
    keys = rng.integers(1, (1 << 63) - 1, size=1024, dtype=np.int64)
    batches = []
    active_counts = []
    for _ in range(8):
        draw = keys[rng.integers(0, 1024, size=BATCH)]
        ufp, hits = unique_agg(draw)
        pad = 1024 - ufp.shape[0]
        if pad:
            ufp = np.concatenate([ufp, np.zeros(pad, dtype=np.int64)])
            hits = np.concatenate([hits, np.zeros(pad, dtype=np.int64)])
        b = make_req_batch(ufp, now, hits=hits, limit=1 << 30)
        b = b._replace(active=jnp.asarray(ufp != 0))
        active_counts.append(int((ufp != 0).sum()))
        batches.append(jax.device_put(b))
    c = Case("config1-token-1K", 1 << 14, batches, math="token",
             active_counts=active_counts)
    c.logical_batch = BATCH  # decisions represented per dispatch
    return c


def config2_case(rng, now) -> Case:
    """BASELINE config #2: leaky bucket, 1M keyspace, Zipf-1.1 skew."""
    LIVE = 1 << 20  # "1M" = 8 x 131072 so the seed pass covers every key
    BATCH = 1 << 17
    keyspace = rng.integers(1, (1 << 63) - 1, size=LIVE, dtype=np.int64)
    batches = []
    active_counts = []
    for _ in range(8):
        z = rng.zipf(1.1, size=BATCH * 2) - 1
        z = z[z < LIVE][:BATCH]
        draw = keyspace[z]
        ufp, hits = unique_agg(draw)
        pad = BATCH - ufp.shape[0]
        ufp = np.concatenate([ufp, np.zeros(pad, dtype=np.int64)])
        hits = np.concatenate([hits, np.zeros(pad, dtype=np.int64)])
        algo = np.full(BATCH, int(Algorithm.LEAKY_BUCKET), dtype=np.int32)
        b = make_req_batch(ufp, now, hits=hits, algo=algo, limit=1 << 30)
        b = b._replace(active=jnp.asarray(ufp != 0))
        active_counts.append(int((ufp != 0).sum()))
        batches.append(jax.device_put(b))
    # seed with the full keyspace so steady state has 1M live keys
    seed = [
        jax.device_put(
            make_req_batch(
                keyspace[i * BATCH : (i + 1) * BATCH],
                now,
                algo=np.full(BATCH, int(Algorithm.LEAKY_BUCKET), dtype=np.int32),
                limit=1 << 30,
            )
        )
        for i in range(LIVE // BATCH)
    ] + batches
    c = Case("config2-leaky-1M-zipf", 1 << 21, batches, seed_batches=seed,
             math="mixed", active_counts=active_counts)
    # each dispatch's ~30K unique keys answer BATCH client rows (Zipf
    # duplicates aggregated host-side) → client_decisions_per_sec scaling
    c.logical_batch = BATCH
    return c


def config4_case(rng, now) -> Case:
    """BASELINE config #4: mixed token+leaky, RESET_REMAINING and
    DRAIN_OVER_LIMIT flags on random rows, 1M keys."""
    LIVE = 1 << 20  # 8 full batches cover the keyspace exactly
    BATCH = 1 << 17
    keyspace = rng.integers(1, (1 << 63) - 1, size=LIVE, dtype=np.int64)
    perm = rng.permutation(LIVE)
    batches = []
    for i in range(8):
        fps = keyspace[perm[i * BATCH : (i + 1) * BATCH]]
        algo = (rng.random(BATCH) < 0.5).astype(np.int32)  # half leaky
        r = rng.random(BATCH)
        behavior = np.zeros(BATCH, dtype=np.int32)
        behavior[r < 0.15] |= int(Behavior.RESET_REMAINING)
        behavior[(r >= 0.15) & (r < 0.3)] |= int(Behavior.DRAIN_OVER_LIMIT)
        hits = rng.integers(0, 4, size=BATCH).astype(np.int64)
        b = make_req_batch(fps, now, hits=hits, algo=algo, behavior=behavior, limit=100)
        batches.append(jax.device_put(b))
    return Case("config4-mixed-flags-1M", 1 << 21, batches, math="mixed")


def config5_case(rng, now) -> Case:
    """BASELINE config #5 scale, single chip: 100M live keys in an 8 GiB
    packed-row table (134M slots, 16.7M bucket rows). The Pallas sweep
    streams the WHOLE table per dispatch (~26 ms at 8 GiB), so throughput at
    this scale comes from amortization: a 2^20-row batch measured 8.5M
    decisions/s on v5e vs 3.8M at the 2^17 sweet spot of the 1 GiB table
    (exp/exp_bigtable.py). Seeding streams 100M keys in 96 dispatches
    without staging them on host/device."""
    CAPACITY = 1 << 27
    LIVE = 100_000_000
    BATCH = 1 << 20
    keyspace = rng.integers(1, (1 << 63) - 1, size=LIVE, dtype=np.int64)
    # 8 distinct staged batches without materializing a 100M permutation:
    # oversample indices, unique, trim (distinct fps per batch is the
    # kernel's unique-fingerprint contract)
    idx = np.unique(rng.integers(0, LIVE, size=BATCH * 10, dtype=np.int64))
    idx = rng.permutation(idx)[: BATCH * 8]
    assert idx.shape[0] == BATCH * 8
    batches = [
        jax.device_put(
            make_req_batch(keyspace[idx[i * BATCH : (i + 1) * BATCH]], now,
                           limit=1 << 20, duration=3_600_000)
        )
        for i in range(8)
    ]

    def seed_iter():
        t0 = time.perf_counter()
        for i in range(0, LIVE, BATCH):
            chunk = keyspace[i : i + BATCH]
            if chunk.shape[0] < BATCH:
                chunk = np.pad(chunk, (0, BATCH - chunk.shape[0]))
            if i and i % (BATCH * 32) == 0:
                log(
                    f"[config5-100M] seeded {i:,}/{LIVE:,} "
                    f"({time.perf_counter() - t0:.0f}s)"
                )
            b = make_req_batch(chunk, now, limit=1 << 20, duration=3_600_000)
            if (chunk == 0).any():
                # padded tail rows must be inactive (fp=0 is the empty-slot
                # sentinel, cf. config1/config2 masking)
                b = b._replace(active=jnp.asarray(chunk != 0))
            yield jax.device_put(b)

    return Case("config5-100M", CAPACITY, batches, seed_iter=seed_iter,
                math="token")


def regions_case(rng, now) -> dict:
    """Multi-region replication phase (ISSUE 12): (a) CODEC — replication
    bytes per row on the compact SyncRegionsWire merge codec (full and
    packed-sender slot rows) vs the classic GetPeerRateLimits proto
    fallback for the same batch; (b) E2E — a two-region loopback cluster's
    convergence wall: concurrent hits on K keys in both regions until every
    key's total converges to the exact union, expressed in sync intervals
    (the bound docs/robustness.md documents)."""
    import asyncio

    from gubernator_tpu.ops.engine import LocalEngine
    from gubernator_tpu.ops.layout import FULL, TOKEN32
    from gubernator_tpu.proto import gubernator_pb2 as pb
    from gubernator_tpu.proto import peers_pb2 as peers_pb
    from gubernator_tpu.service.wire import (
        split_region_encodable, sync_regions_pb,
    )
    from gubernator_tpu.types import Behavior

    out: dict = {}
    MR = int(Behavior.MULTI_REGION)
    B = 4096

    def item(i, hits=5, name="ratelimit-bench"):
        # realistic key shape: a tenant/user compound, ~27 chars
        return pb.RateLimitReq(
            name=name, unique_key=f"tenant-{i % 97:03d}/user-{i:08d}",
            hits=hits, limit=1 << 20, duration=3_600_000, behavior=MR,
            created_at=now,
        )

    pairs = [(f"rb_{i:06d}", item(i)) for i in range(B)]
    enc, fb = split_region_encodable(pairs)
    assert len(enc) == B and not fb
    # bootstrap rows carry strings + the sender's stored slot row; steady-
    # state rows are pure lane+hits entries merged by fingerprint
    for lay, label in ((FULL, "bootstrap_full"), (TOKEN32,
                                                  "bootstrap_token32")):
        slots = np.zeros((B, lay.F), dtype=np.int32)
        req = sync_regions_pb(enc, "bench", "dc-a", slots, lay)
        out[f"{label}_bytes_per_row"] = round(req.ByteSize() / B, 1)
    steady = sync_regions_pb(
        enc, "bench", "dc-a", detail_rows=np.zeros(B, dtype=bool),
        # per-key cumulative dedup counters ride every production batch
        # (+8 B/row — the price of exact convergence under retries)
        cums=np.arange(1, B + 1, dtype=np.int64) * 1000,
    )
    out["steady_state_bytes_per_row"] = round(steady.ByteSize() / B, 1)
    proto = peers_pb.GetPeerRateLimitsReq(
        requests=[it for _k, it in pairs]
    )
    out["proto_bytes_per_row"] = round(proto.ByteSize() / B, 1)
    out["steady_reduction_vs_proto"] = round(
        out["proto_bytes_per_row"] / out["steady_state_bytes_per_row"], 2
    )

    # ---- e2e rung: two-region loopback convergence wall
    from gubernator_tpu.config import BehaviorConfig, DaemonConfig
    from gubernator_tpu.service.daemon import Daemon
    from gubernator_tpu.types import PeerInfo

    K = 256
    SYNC_MS = 25.0

    async def run():
        def conf(dc):
            return DaemonConfig(
                grpc_address="127.0.0.1:0", http_address="127.0.0.1:0",
                data_center=dc, cache_size=1 << 16,
                behaviors=BehaviorConfig(
                    batch_wait_ms=1.0, global_sync_wait_ms=SYNC_MS,
                    batch_timeout_ms=5000.0, global_timeout_ms=5000.0,
                ),
            )

        a = await Daemon.spawn(conf("dc-a"))
        b = await Daemon.spawn(conf("dc-b"))
        try:
            peers = [a.peer_info(), b.peer_info()]
            for d in (a, b):
                d.set_peers([PeerInfo(**vars(p)) for p in peers])
            ha = rng.integers(1, 50, size=K)
            hb = rng.integers(1, 50, size=K)
            await a.get_rate_limits(
                [item(i, int(ha[i])) for i in range(K)]
            )
            await b.get_rate_limits(
                [item(i, int(hb[i])) for i in range(K)]
            )
            want = [(1 << 20) - int(ha[i] + hb[i]) for i in range(K)]
            t0 = time.perf_counter()
            deadline = t0 + 30.0
            while time.perf_counter() < deadline:
                xa = await a.get_rate_limits(
                    [item(i, 0) for i in range(K)]
                )
                xb = await b.get_rate_limits(
                    [item(i, 0) for i in range(K)]
                )
                if all(
                    xa[i].remaining == xb[i].remaining == want[i]
                    for i in range(K)
                ):
                    break
                await asyncio.sleep(0.02)
            else:
                raise RuntimeError("two-region totals did not converge")
            wall = time.perf_counter() - t0
            return {
                "keys": K,
                "convergence_wall_s": round(wall, 3),
                "convergence_sync_intervals": round(
                    wall / (SYNC_MS / 1e3), 1
                ),
                "wire_sent": (
                    a.region_manager.wire_sent + b.region_manager.wire_sent
                ),
                "wire_fallback": (
                    a.region_manager.wire_fallback
                    + b.region_manager.wire_fallback
                ),
                "rows_merged": (
                    a.region_manager.rows_merged
                    + b.region_manager.rows_merged
                ),
            }
        finally:
            await asyncio.gather(a.close(), b.close())

    out.update(asyncio.run(run()))
    out["converged_exact"] = True
    return out


def leases_case(rng, now) -> dict:
    """Edge quota-lease phase (ISSUE 13): the fan-in cut the client-side
    admission plane buys. One loopback daemon serves (a) a per-check RPC
    baseline — 8 concurrent single-item GetRateLimits checkers, the cost
    every check pays without delegation — and (b) a LocalLimiter under
    LEASE CHURN (200 ms TTL, adaptive grants, live renew/return RPCs)
    hammered by 2 admission threads. Records both rates, the ≥50× accept
    bit, the adaptive grant-size trace, and the exact-conservation check
    (admissions == server-side consumption — grants pre-consume, so the
    no-crash over-admission is zero by construction; the crash-edge bound
    is CI-gated in lease_smoke)."""
    import asyncio

    from gubernator_tpu.client import V1Client
    from gubernator_tpu.edge import LocalLimiter
    from gubernator_tpu.proto import gubernator_pb2 as pb
    from tests.cluster import Cluster

    MINUTE = 60_000
    out: dict = {}

    async def run():
        c = await Cluster.start(1)
        d = c.daemons[0]
        try:
            cl = V1Client(d.conf.grpc_address)
            rpc_n = 0

            async def rpc_worker(i, deadline):
                nonlocal rpc_n
                while time.perf_counter() < deadline:
                    await cl.get_rate_limits([pb.RateLimitReq(
                        name="bench-rpc", unique_key=f"u{i}", hits=1,
                        limit=1 << 30, duration=MINUTE,
                    )])
                    rpc_n += 1

            t0 = time.perf_counter()
            await asyncio.gather(
                *(rpc_worker(i, t0 + 0.5) for i in range(8))
            )
            rpc_rate = rpc_n / (time.perf_counter() - t0)
            out["per_check_rpc_per_sec"] = round(rpc_rate, 1)

            lim = LocalLimiter(
                d.conf.grpc_address, "bench-edge", "hot",
                limit=1 << 24, duration=MINUTE, ttl_ms=200,
                initial_grant=4096,
            )
            await lim.start()
            stop = [False]
            counts = [0, 0]

            def admit_worker(i):
                while not stop[0]:
                    if lim.allow():
                        counts[i] += 1
                    else:
                        time.sleep(0.0005)

            loop = asyncio.get_running_loop()
            t0 = time.perf_counter()
            futs = [loop.run_in_executor(None, admit_worker, i)
                    for i in range(2)]
            await asyncio.sleep(0.8)
            stop[0] = True
            await asyncio.gather(*futs)
            wall = time.perf_counter() - t0
            local_rate = sum(counts) / wall
            await lim.close()
            srv = (await cl.get_rate_limits([pb.RateLimitReq(
                name="bench-edge", unique_key="hot", hits=0,
                limit=1 << 24, duration=MINUTE,
            )])).responses[0]
            await cl.close()
            return {
                "client_admissions_per_sec": round(local_rate, 1),
                "fanin_cut_x": round(local_rate / max(rpc_rate, 1), 1),
                "accept_ge_50x": bool(local_rate >= 50 * rpc_rate),
                "lease_renewals": lim.stats.grants,
                "grant_size_trace": lim.stats.grant_sizes[:16],
                "tokens_granted": lim.stats.tokens_granted,
                "tokens_returned": lim.stats.tokens_returned,
                "admitted_total": lim.stats.local_admits,
                "consumed_server_side": int((1 << 24) - srv.remaining),
                "conservation_exact": bool(
                    lim.stats.local_admits
                    <= (1 << 24) - srv.remaining
                ),
            }
        finally:
            await c.stop()

    out.update(asyncio.run(run()))
    return out


def tiering_case(rng, now) -> dict:
    """Hot-set tiering phase (ISSUE 15, docs/tiering.md): capacity past
    the HBM wall. (a) tracked-keys-vs-capacity curve — drive 1×/2×/4×
    table capacity in tracked keys through a shadow-armed engine and
    record where the state actually lives (HBM live rows vs shadow rows)
    plus a zero-over-grant sample check; (b) hot-set decisions/s with
    tiering armed vs the no-tiering engine on identical Zipf hot-set
    batches (interleaved best-of-3) — the ≥0.9× acceptance bit belongs
    to THIS phase on the TPU run (the CPU proxy's serial front end
    exaggerates the fixed overhead; tier_smoke gates it at 0.85 with the
    rationale in its docstring). HBM bytes/decision attached per engine
    from the roofline model (ops/pallas_probe)."""
    from gubernator_tpu.ops.batch import RequestColumns
    from gubernator_tpu.tier import ROW_BYTES, ShadowTable

    on_tpu = jax.default_backend() == "tpu"
    CAP = (1 << 23) if on_tpu else (1 << 20)  # slots: 8M TPU / 1M CPU
    TRACKED = 4 * CAP                         # 32M TPU / 4M CPU keys
    BATCH = (1 << 16) if on_tpu else (1 << 13)
    LIMIT = 12
    keys = rng.integers(1, (1 << 62), size=TRACKED, dtype=np.int64)
    keys = np.unique(keys)
    TRACKED = keys.shape[0]

    def mkcols(fp, t, hits=1):
        n = fp.shape[0]
        return RequestColumns(
            fp=fp, algo=np.zeros(n, dtype=np.int32),
            behavior=np.zeros(n, dtype=np.int32),
            hits=np.full(n, hits, dtype=np.int64),
            limit=np.full(n, LIMIT, dtype=np.int64),
            burst=np.zeros(n, dtype=np.int64),
            duration=np.full(n, 86_400_000, dtype=np.int64),
            created_at=np.full(n, t, dtype=np.int64),
            err=np.zeros(n, dtype=np.int8),
        )

    from gubernator_tpu.ops.engine import LocalEngine

    eng = LocalEngine(capacity=CAP)
    eng.attach_shadow(ShadowTable(max_bytes=TRACKED * ROW_BYTES))
    t = now
    curve = []
    sample = rng.permutation(TRACKED)[:4096]
    consumed = np.zeros(TRACKED, dtype=np.int64)
    for mult in (1, 2, 4):
        hi = min(TRACKED, mult * CAP)
        lo = 0 if mult == 1 else min(TRACKED, (mult // 2) * CAP)
        for i in range(lo, hi, BATCH):
            w = keys[i:i + BATCH]
            rc = eng.check_columns(mkcols(w, t, hits=3), now_ms=t)
            ok = (np.asarray(rc.status) == 0) & (rc.err == 0)
            consumed[i:i + BATCH][ok] += 3
            t += 7
        st = eng.shadow.stats()
        curve.append({
            "tracked_keys": hi,
            "tracked_x_capacity": round(hi / CAP, 2),
            "hbm_live": eng.live_count(t),
            "shadow_ram_rows": st["ram_rows"],
            "demoted_evict": st["demoted_evict"],
            "promoted": st["promoted"],
        })
    # zero-over-grant sample: drain each sampled key to its limit
    over = 0
    for i in range(0, sample.shape[0], BATCH):
        si = sample[i:i + BATCH]
        rc = eng.check_columns(mkcols(keys[si], t, hits=LIMIT), now_ms=t)
        ok = (np.asarray(rc.status) == 0) & (rc.err == 0)
        consumed[si[ok]] += LIMIT
        t += 7
    over = int((consumed[sample] > LIMIT).sum())
    out = {
        "capacity_slots": CAP,
        "tracked_keys": int(TRACKED),
        "curve": curve,
        "over_grant_sample_keys": over,
        "zero_over_grant": over == 0,
        "shadow_nominal_bytes": eng.shadow.nominal_bytes,
    }

    # ---- hot-set rate, tiering vs baseline (identical Zipf batches)
    HOT = CAP // 8
    hot = keys[:HOT]
    zr = np.minimum(rng.zipf(1.05, size=16 * BATCH) - 1, HOT - 1)
    batches = []
    tb = t + 10_000_000
    for i in range(12):
        batches.append((np.unique(hot[zr[i * BATCH:(i + 1) * BATCH]]), tb))
        tb += 13
    rates = {}
    for tag in ("tiering", "baseline"):
        if tag == "tiering":
            e = eng  # already tracks 4× capacity; re-warm the hot set
        else:
            e = LocalEngine(capacity=CAP)
        e.check_columns(mkcols(hot, tb, hits=0), now_ms=tb)
        for fp, bt in batches[:2]:
            e.check_columns(mkcols(fp, bt, hits=0), now_ms=bt)
        rows_total = sum(b[0].shape[0] for b in batches[2:])
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for fp, bt in batches[2:]:
                e.check_columns(mkcols(fp, bt, hits=0), now_ms=bt)
            best = min(best, time.perf_counter() - t0)
        rates[tag] = rows_total / best
        out[f"hot_set_rate_{tag}"] = round(rates[tag], 1)
    ratio = rates["tiering"] / rates["baseline"]
    out["hot_set_ratio"] = round(ratio, 3)
    out["accept_ge_0_9x"] = bool(ratio >= 0.9)
    out["hbm_bytes_per_decision"] = round(
        eng.hbm_bytes_per_decision_estimate(), 1
    )
    out["backend"] = jax.default_backend()
    return out


def layout_case(rng, now) -> dict:
    """Packed slot-layout phase (PR 11): device decisions/s for the SAME
    all-GCRA traffic on the full 64 B layout vs the packed 32 B gcra32
    layout, at the largest live-key geometry the backend affords (TPU:
    the 100M-key acceptance scale, the table walk BENCH_r05 measured
    HBM-bound; CPU: a 1M-key proxy). Also records bytes/slot and live
    keys per HBM GB — the ≥1.5×-decisions / 2×-capacity targets."""
    from gubernator_tpu.ops.layout import FULL, GCRA32

    on_tpu = jax.default_backend() == "tpu"
    LIVE = 100_000_000 if on_tpu else 1 << 20
    BATCH = (1 << 20) if on_tpu else (1 << 14)
    CAPACITY = (1 << 27) if on_tpu else (1 << 21)
    LIMIT, DUR = 16, 86_400_000  # T = 90 min — GCRA state stays live
    keyspace = rng.integers(1, (1 << 63) - 1, size=LIVE, dtype=np.int64)
    idx = np.unique(rng.integers(0, LIVE, size=BATCH * 10, dtype=np.int64))
    idx = rng.permutation(idx)[: BATCH * 8]
    algo = np.full(BATCH, int(Algorithm.GCRA), dtype=np.int32)

    def batches():
        return [
            jax.device_put(
                make_req_batch(
                    keyspace[idx[i * BATCH : (i + 1) * BATCH]], now,
                    algo=algo, limit=LIMIT, duration=DUR,
                )
            )
            for i in range(8)
        ]

    def seed_iter():
        for i in range(0, LIVE, BATCH):
            chunk = keyspace[i : i + BATCH]
            if chunk.shape[0] < BATCH:
                chunk = np.pad(chunk, (0, BATCH - chunk.shape[0]))
            b = make_req_batch(chunk, now, algo=algo, limit=LIMIT,
                               duration=DUR)
            if (chunk == 0).any():
                b = b._replace(active=jnp.asarray(chunk != 0))
            yield jax.device_put(b)

    out: dict = {"live_keys": LIVE, "batch": BATCH}
    rates = {}
    for label, lay in (("full", "full"), ("gcra32", "gcra32")):
        case = Case(
            f"layout-{label}", CAPACITY, batches(), seed_iter=seed_iter,
            math="gcra", layout=lay,
        )
        table_bytes = int(np.prod(case.table.rows.shape)) * 4
        case.seed()
        res = case.device_loop()
        rates[label] = res.get("device_decisions_per_sec")
        out[label] = {
            **res,
            "table_bytes": table_bytes,
            "bytes_per_slot": case.table.layout.slot_bytes,
            "live_keys_per_hbm_gb": round(
                LIVE / (table_bytes / 2**30), 1
            ),
        }
        del case  # release the table before the next layout's HBM claim
    if rates.get("full") and rates.get("gcra32"):
        out["packed_speedup"] = round(rates["gcra32"] / rates["full"], 3)
    out["capacity_gain"] = round(
        out["full"]["table_bytes"] / out["gcra32"]["table_bytes"], 2
    )
    return out


def probe_case(rng, now) -> dict:
    """Fused-megakernel phase (ISSUE 14): the XLA gather + sweep/sparse
    write kernel vs the Pallas probe→decide→write megakernel
    (GUBER_PROBE_KERNEL, ops/pallas_probe.py) on identical all-GCRA
    traffic, both slot layouts, at the HBM-bound geometries — TPU: 10M
    AND 100M live keys (the record-book claim is ≥1.3× device decisions/s
    at the 100M config); CPU: a 1M-key interpret-mode proxy so the phase
    stays exercised. HBM bytes/decision is reported per kernel × layout
    from the roofline model (docs/kernel.md), so the headline number
    ships with its bandwidth argument attached."""
    from gubernator_tpu.ops.layout import LAYOUTS
    from gubernator_tpu.ops.pallas_probe import hbm_bytes_per_decision
    from gubernator_tpu.ops.table2 import n_buckets_for

    on_tpu = jax.default_backend() == "tpu"
    sizes = (
        (
            ("10M", 10_000_000, 1 << 24, 1 << 17),
            ("100M", 100_000_000, 1 << 27, 1 << 20),
        )
        if on_tpu
        else (("1M", 1 << 20, 1 << 21, 1 << 14),)
    )
    LIMIT, DUR = 16, 86_400_000  # GCRA state stays live across the loop
    out: dict = {}
    for label, live, capacity, batch in sizes:
        keyspace = rng.integers(1, (1 << 63) - 1, size=live, dtype=np.int64)
        idx = np.unique(
            rng.integers(0, live, size=batch * 10, dtype=np.int64)
        )
        idx = rng.permutation(idx)[: batch * 8]
        algo = np.full(batch, int(Algorithm.GCRA), dtype=np.int32)

        def batches(idx=idx, keyspace=keyspace, batch=batch, algo=algo):
            return [
                jax.device_put(
                    make_req_batch(
                        keyspace[idx[i * batch : (i + 1) * batch]], now,
                        algo=algo, limit=LIMIT, duration=DUR,
                    )
                )
                for i in range(8)
            ]

        def seed_iter(keyspace=keyspace, live=live, batch=batch, algo=algo):
            for i in range(0, live, batch):
                chunk = keyspace[i : i + batch]
                if chunk.shape[0] < batch:
                    chunk = np.pad(chunk, (0, batch - chunk.shape[0]))
                b = make_req_batch(chunk, now, algo=algo, limit=LIMIT,
                                   duration=DUR)
                if (chunk == 0).any():
                    b = b._replace(active=jnp.asarray(chunk != 0))
                yield jax.device_put(b)

        sz: dict = {"live_keys": live, "batch": batch}
        rates = {}
        nb = n_buckets_for(capacity)
        for lay_name in ("full", "gcra32"):
            for probe in ("xla", "pallas"):
                case = Case(
                    f"probe-{label}-{lay_name}-{probe}", capacity,
                    batches(), seed_iter=seed_iter, math="gcra",
                    layout=lay_name, probe=probe,
                )
                case.seed()
                res = case.device_loop()
                rates[(lay_name, probe)] = res.get(
                    "device_decisions_per_sec"
                )
                sz[f"{lay_name}-{probe}"] = {
                    **res,
                    "hbm_bytes_per_decision": round(
                        hbm_bytes_per_decision(
                            LAYOUTS[lay_name], batch, nb, WRITE, probe
                        ),
                        1,
                    ),
                }
                del case  # release the table before the next HBM claim
        for lay_name in ("full", "gcra32"):
            a = rates.get((lay_name, "xla"))
            b = rates.get((lay_name, "pallas"))
            if a and b:
                sz[f"pallas_speedup_{lay_name}"] = round(b / a, 3)
        out[label] = sz
    # the record-book acceptance bit lives on the LARGEST geometry; the
    # CPU proxy records the ratio but claims nothing (interpret mode
    # prices the movement emulation, not the chip)
    sp = out[sizes[-1][0]].get("pallas_speedup_full")
    out["accept_ge_1_3x"] = (bool(sp >= 1.3) if (on_tpu and sp) else None)
    return out


def dispatch_case(rng, now) -> dict:
    """Dispatch-budget phase (always-on-chip ISSUE 17 — docs/latency.md
    "Dispatch budget"): what the host wraps around one device walk, and
    what the fused walks save over the probe-then-scatter two-pass.

    Part 1 — serving dispatch wall per batch size × {ring, direct}: a bare
    EngineRunner (no gRPC, no batcher) is fed the SAME pre-parsed
    WireBatch the batcher stages, through (a) the direct `check_wire` call
    and (b) a RequestRing submit. Per size the record carries
    serving_dispatch_ms for both paths next to device_ms — the bare
    engine check of the identical shape — so the gap IS the per-dispatch
    host budget the ring exists to retire. On CPU the ring is the
    functional emulation and can only ADD protocol overhead, so the
    ring≤direct acceptance bit is claimed on the TPU run only.

    Part 2 — fused vs two-pass install/merge walls at 1M live keys (CPU
    proxy smaller: interpret mode prices the emulation, not the chip):
    two engines share one seeded table snapshot and differ only in
    walk_mode; install_columns and merge_rows walls are timed on each.
    This is the number the fused VMEM probe→install/merge→write walk
    moves — one pass instead of probe + host round-trip + scatter.
    """
    import asyncio

    from gubernator_tpu.ops.engine import LocalEngine
    from gubernator_tpu.proto import gubernator_pb2 as pb
    from gubernator_tpu.service.ring import RequestRing
    from gubernator_tpu.service.runner import EngineRunner
    from gubernator_tpu.service.wire import wire_batch_from_wire

    on_tpu = jax.default_backend() == "tpu"
    sizes = (
        (1 << 10, "1K"), (1 << 13, "8K"), (1 << 15, "32K"), (1 << 17, "128K")
    ) if on_tpu else ((1 << 10, "1K"), (1 << 13, "8K"))
    REPS = 12 if on_tpu else 6
    out = {}

    # created_at must sit inside the serving tolerance window
    # (config.created_at_tolerance_ms) or the engine re-derives reset_time
    # from its own wall clock on every dispatch
    wall_ms = int(time.time() * 1000)

    def corpus(n, tag):
        return pb.GetRateLimitsReq(requests=[
            pb.RateLimitReq(
                name="dispatch", unique_key=f"{tag}k{i}", hits=1,
                limit=1 << 20, duration=3_600_000, created_at=wall_ms,
            ) for i in range(n)
        ]).SerializeToString()

    # ------------------------------------------- part 1: serving dispatch
    cap = (1 << 21) if on_tpu else (1 << 17)
    eng = LocalEngine(capacity=cap, write_mode=WRITE, wire="compact")
    runner = EngineRunner(eng)

    async def serve():
        res = {}
        for n, label in sizes:
            parsed = wire_batch_from_wire(corpus(n, label))
            if parsed is None:  # native parser unavailable on this host
                res[label] = {"error": "native parser unavailable"}
                continue
            parts = [parsed[0]]
            cols = parts[0].cols
            ring = RequestRing(runner, slots=8)

            async def direct():
                rc = await runner.check_wire(parts)
                assert rc is not None  # compact engine + encodable rows

            async def ringed():
                await ring.submit(parts)

            entry = {"rows": n}
            for path, fn in (("direct", direct), ("ring", ringed)):
                await fn()  # trace once; warmed shapes never retrace
                t0 = time.perf_counter()
                for _ in range(REPS):
                    await fn()
                entry[f"serving_dispatch_ms_{path}"] = round(
                    (time.perf_counter() - t0) / REPS * 1e3, 3)
            await ring.drain()
            assert ring.debug()["launches"] == REPS + 1
            # bare engine term of the identical shape (pack+device+fetch,
            # no runner): the floor the serving walls are priced against
            eng.check_columns(cols, now_ms=wall_ms)
            t0 = time.perf_counter()
            for _ in range(REPS):
                eng.check_columns(cols, now_ms=wall_ms)
            entry["device_ms"] = round(
                (time.perf_counter() - t0) / REPS * 1e3, 3)
            entry["ring_vs_direct"] = round(
                entry["serving_dispatch_ms_ring"]
                / max(entry["serving_dispatch_ms_direct"], 1e-9), 3)
            res[label] = entry
            log(f"[dispatch] {label}: direct "
                f"{entry['serving_dispatch_ms_direct']} ms, ring "
                f"{entry['serving_dispatch_ms_ring']} ms, device "
                f"{entry['device_ms']} ms")
        return res

    out["serving"] = asyncio.run(serve())
    small = out["serving"].get(sizes[0][1], {})
    rv = small.get("ring_vs_direct")
    # the ring pays for itself where dispatches are smallest/most frequent;
    # claimed only where the round-trip it removes exists (the chip)
    out["accept_ring_le_direct"] = (
        bool(rv is not None and rv <= 1.0) if on_tpu else None)

    # ------------------- part 1b: fused drain K-sweep (the launch tax)
    # The kill-the-launch-tax record: at the smallest (most launch-bound)
    # batch size, 32 concurrent submitters through the fused multi-slot
    # drain (ops/ring_drain.py) at K ∈ {1,2,4,8} vs the host issue loop.
    # Per mode: launches per retired slot (the amortization factor) and
    # the submit p50/p99. The persistent tier (GUBER_RING_ISSUE=
    # persistent) is staged — interpreter-parity-tested, priced on the
    # next TPU run.
    async def fused_sweep():
        n, label = sizes[0]
        parsed = wire_batch_from_wire(corpus(n, "fk"))
        if parsed is None:
            return {"error": "native parser unavailable"}
        parts = [parsed[0]]
        SUBMITS = 32

        async def timed(ring, lat):
            t0 = time.perf_counter()
            await ring.submit(parts)
            lat.append(time.perf_counter() - t0)

        def xla_launches(dbg, mode):
            return (dbg["drain_launches"] + dbg["host_slots"]
                    if mode == "fused" else dbg["launches"])

        async def drive(mode, k):
            ring = RequestRing(
                runner, slots=8, issue_mode=mode, drain_k=k)
            await asyncio.gather(*(
                timed(ring, []) for _ in range(8)))  # trace + warm
            d0 = xla_launches(ring.debug(), mode)
            lat: list = []
            t0 = time.perf_counter()
            await asyncio.gather(*(
                timed(ring, lat) for _ in range(SUBMITS)))
            wall = time.perf_counter() - t0
            launches = xla_launches(ring.debug(), mode) - d0
            await ring.drain()
            return {
                "rows": n,
                "serving_dispatch_ms": round(wall / SUBMITS * 1e3, 3),
                "submit_p50_ms": round(
                    float(np.percentile(lat, 50)) * 1e3, 3),
                "submit_p99_ms": round(
                    float(np.percentile(lat, 99)) * 1e3, 3),
                "launches": launches,
                "launches_per_slot": round(launches / SUBMITS, 4),
            }

        res = {"host": await drive("host", 8)}
        for k in (1, 2, 4, 8):
            res[f"fused_k{k}"] = await drive("fused", k)
            log(f"[dispatch] fused K={k}: "
                f"{res[f'fused_k{k}']['launches']} launches/"
                f"{SUBMITS} slots, p99 "
                f"{res[f'fused_k{k}']['submit_p99_ms']} ms (host p99 "
                f"{res['host']['submit_p99_ms']} ms)")
        res["persistent"] = (
            "staged: interpreter-mode fence parity green "
            "(tests/test_ring_drain.py); awaits device run"
        )
        return res

    out["fused_drain"] = asyncio.run(fused_sweep())
    fd = out["fused_drain"]
    if "error" not in fd:
        # acceptance: launches/decision reduced ≥4× at K=8, p99 no worse
        # than the host issue loop (10% CI-noise allowance)
        out["accept_drain_amortize_4x"] = bool(
            fd["host"]["launches"] >= 4 * fd["fused_k8"]["launches"]
            and fd["fused_k8"]["submit_p99_ms"]
            <= fd["host"]["submit_p99_ms"] * 1.1
        )

    # -------------------------- part 2: fused vs two-pass install/merge
    LIVE = (1 << 20) if on_tpu else (1 << 14)
    BATCH = (1 << 17) if on_tpu else (1 << 10)
    seed_eng = LocalEngine(
        capacity=int(LIVE * 1.7), write_mode=WRITE, walk="xla")

    def install_args(n, base):
        # odd-multiplier bijection keeps every fingerprint distinct; |1
        # dodges the empty-slot sentinel
        fp = ((np.arange(n, dtype=np.int64) + base)
              * np.int64(0x9E3779B97F4A7C15 - (1 << 64))) | 1
        return dict(
            fp=fp,
            algo=np.zeros(n, dtype=np.int32),
            status=np.zeros(n, dtype=np.int32),
            limit=np.full(n, 1 << 20, dtype=np.int64),
            remaining=np.full(n, 1 << 19, dtype=np.int64),
            reset_time=np.full(n, now + 3_600_000, dtype=np.int64),
            duration=np.full(n, 3_600_000, dtype=np.int64),
            now_ms=now,
        )

    CH = (1 << 16) if on_tpu else (1 << 12)
    for off in range(0, LIVE, CH):
        seed_eng.install_columns(**install_args(min(CH, LIVE - off), off))
    # installs DONATE the table buffer, so each walk engine gets its own
    # device copy of the seeded snapshot (host round-trip paid once here,
    # outside every timed window)
    from gubernator_tpu.ops.table2 import Table2

    snap_rows = np.asarray(seed_eng.table.rows)
    snap_layout = seed_eng.table.layout
    ext_fps, ext_slots = seed_eng.extract_live(now_ms=now)
    mfp, mslots = ext_fps[:BATCH], np.asarray(ext_slots)[:BATCH]
    del seed_eng

    walls = {}
    for walk in ("xla", "pallas"):
        e = LocalEngine(
            table=Table2(jnp.asarray(snap_rows), snap_layout),
            write_mode=WRITE, walk=walk)
        # fresh keys beyond the seeded range: the walk really installs
        e.install_columns(**install_args(BATCH, LIVE))  # trace + warm
        t_i = []
        for r in range(3):
            a = install_args(BATCH, LIVE + (r + 1) * BATCH)
            t0 = time.perf_counter()
            e.install_columns(**a)
            t_i.append(time.perf_counter() - t0)
        # idempotent re-merge of live rows: conservative no-op semantics,
        # full walk cost — the steady-state transfer/reconcile shape
        e.merge_rows(mfp, mslots, now_ms=now)  # trace + warm
        t_m = []
        for _ in range(3):
            t0 = time.perf_counter()
            e.merge_rows(mfp, mslots, now_ms=now)
            t_m.append(time.perf_counter() - t0)
        walls[walk] = (min(t_i), min(t_m))
        out[f"install_wall_ms_{walk}"] = round(min(t_i) * 1e3, 3)
        out[f"merge_wall_ms_{walk}"] = round(min(t_m) * 1e3, 3)
        del e
    out["live_keys"] = LIVE
    out["wall_batch"] = BATCH
    out["fused_install_speedup"] = round(
        walls["xla"][0] / max(walls["pallas"][0], 1e-9), 3)
    out["fused_merge_speedup"] = round(
        walls["xla"][1] / max(walls["pallas"][1], 1e-9), 3)
    # parity: the two engines walked identical traffic — byte-equal tables
    # (the fused-walk contract, asserted here against real bench shapes)
    out["accept_fused_ge_1x"] = (
        bool(out["fused_install_speedup"] >= 1.0
             and out["fused_merge_speedup"] >= 1.0) if on_tpu else None)
    log(f"[dispatch] walls @ {LIVE} keys: install "
        f"{out['install_wall_ms_xla']} → {out['install_wall_ms_pallas']} ms "
        f"({out['fused_install_speedup']}x), merge "
        f"{out['merge_wall_ms_xla']} → {out['merge_wall_ms_pallas']} ms "
        f"({out['fused_merge_speedup']}x)")
    return out


def _pipelined_checks(eng, cols_iter, now, depth=2):
    """Drive check batches through the engine's prepare/issue/finish split
    with a depth-`depth` software pipeline — the serving loop the daemon's
    EngineRunner runs across threads, single-threaded here. At the default
    depth 2 the stage/put of dispatch N+1 and the fetch of N−1 both overlap
    device execution of N (double-buffered transfers: the ingress staging
    ring holds both in-flight grids, parallel/sharded._StagingPool). The
    serial check_columns loop paid host stage + launch + fetch back-to-back
    per dispatch — on an RTT-bound transport that is the whole config3 gap
    (BENCH_r05: 2412 ms/dispatch vs ~10 ms of device time)."""
    from collections import deque

    from gubernator_tpu.ops.engine import (
        finish_check_columns,
        issue_check_columns,
        prepare_check_columns,
    )

    fixup = lambda fn: fn()
    pend = deque()
    for cols in cols_iter:
        pend.append(
            issue_check_columns(
                eng, prepare_check_columns(eng, cols, now_ms=now)
            )
        )
        if len(pend) > depth:
            _rc, delta = finish_check_columns(eng, pend.popleft(), fixup)
            eng.stats.merge(delta)
    while pend:
        _rc, delta = finish_check_columns(eng, pend.popleft(), fixup)
        eng.stats.merge(delta)


def pod_scaling_case(rng, now) -> dict:
    """Horizontal-scaling phase (pod-scale mesh tentpole): device-routed
    decisions/s vs device count (1→2→4→8) for BOTH exchange schedules
    (GUBER_A2A_IMPL ring vs collective, parallel/ring.py), plus an
    exchange-only probe at each width — total wall per impl and the ring's
    per-hop split (truncated-prefix probes expose the marginal hop cost,
    which is where the double-buffered overlap shows: hops 2..D-1 must cost
    well under hop 1's launch+transfer). The acceptance surface: ring
    exchange wall no worse than the collective baseline on the widest mesh,
    and decisions/s growing with D. Transport accounting rides the same
    wire-bytes gate as sharded-ingress."""
    from jax.sharding import NamedSharding

    from gubernator_tpu.ops.batch import RequestColumns
    from gubernator_tpu.parallel import make_mesh
    from gubernator_tpu.parallel.mesh import shard_spec
    from gubernator_tpu.parallel.ring import make_exchange_probe
    from gubernator_tpu.parallel.a2a import pair_capacity
    from gubernator_tpu.ops.engine import _pad_size
    from gubernator_tpu.parallel.sharded import ShardedEngine

    on_tpu = jax.default_backend() == "tpu"
    n_all = len(jax.devices())
    counts = [d for d in (1, 2, 4, 8) if d <= n_all]
    if n_all not in counts:
        counts.append(n_all)
    batch = 1 << 15 if on_tpu else 2048
    cap = (1 << 22) if on_tpu else (1 << 13)
    n_disp = 24

    def cols_for(fps):
        n = fps.shape[0]
        return RequestColumns(
            fp=fps,
            algo=np.zeros(n, dtype=np.int32),
            behavior=np.zeros(n, dtype=np.int32),
            hits=np.ones(n, dtype=np.int64),
            limit=np.full(n, 1 << 30, dtype=np.int64),
            burst=np.zeros(n, dtype=np.int64),
            duration=np.full(n, 3_600_000, dtype=np.int64),
            created_at=np.full(n, now, dtype=np.int64),
            err=np.zeros(n, dtype=np.int8),
        )

    staged = [
        rng.integers(1, (1 << 63) - 1, size=batch, dtype=np.int64)
        for _ in range(4)
    ]
    out: dict = {
        "batch": batch,
        "device_counts": counts,
        # CPU "devices" share one socket — decisions/s is flat by
        # construction there and only the parity/overlap figures carry
        # signal; TPU runs are where the scaling column means throughput
        "backend": jax.default_backend(),
        "scaling": {},
    }
    for D in counts:
        mesh = make_mesh(D)
        impls = ("collective", "ring") if D > 1 else ("collective",)
        entry: dict = {}
        for impl in impls:
            eng = ShardedEngine(
                mesh, capacity_per_shard=max(1024, cap // D),
                route="device", dedup="device", a2a=impl,
            )
            _pipelined_checks(
                eng, (cols_for(staged[i % 4]) for i in range(3)), now
            )  # compile + seed
            eng.take_stage_deltas()
            eng.take_wire_deltas()

            def timed(k, eng=eng):
                t0 = time.perf_counter()
                _pipelined_checks(
                    eng, (cols_for(staged[i % 4]) for i in range(k)), now
                )
                return time.perf_counter() - t0

            n_short, n_long = 2, 2 + n_disp
            t_short = min(timed(n_short) for _ in range(3))
            t_long = min(timed(n_long) for _ in range(3))
            s = slope(t_short, t_long, n_short, n_long, batch, min_ratio=1.0)
            rec: dict = {}
            if s.reason is None:
                rec["dispatch_ms"] = round(s.per_iter_ms, 3)
                rec["decisions_per_sec"] = round(s.rate, 1)
            else:
                rec["invalid"] = s.reason
            stage = eng.take_stage_deltas()
            wire = eng.take_wire_deltas()
            bad = check_transport(
                stage["put"] / 1e3, wire["put"], label=f"pod-D{D}-{impl}-put"
            )
            if bad:
                rec["transport_guard"] = bad
            guard = check_dropped(eng.stats.dropped, eng.stats.checks or 1)
            if guard:
                rec["guard"] = guard
            rec["a2a_overflow"] = eng.a2a_overflow
            entry[impl] = rec
            log(f"[pod-scaling:D{D}] {impl}: "
                f"{rec.get('decisions_per_sec', rec.get('invalid'))} dec/s")

        # exchange-only probe at this width's real dispatch geometry: the
        # stage-split view of the exchange leg (per-hop ms = marginal cost
        # of ring prefix k vs k-1; hop 1 carries the fixed launch cost)
        if D > 1:
            c = _pad_size(max(1, -(-batch // D)), floor=8)
            block = (D, 12, pair_capacity(c, D))
            x = jnp.asarray(rng.integers(
                1, 1 << 40, size=(D,) + block, dtype=np.int64
            ))
            x = jax.device_put(x, NamedSharding(mesh, shard_spec(mesh)))

            def wall_ms(fn, k=12):
                fn(x).block_until_ready()
                # block per iteration: XLA:CPU collective programs deadlock
                # when many are dispatched concurrently
                t0 = time.perf_counter()
                for _ in range(k):
                    fn(x).block_until_ready()
                return (time.perf_counter() - t0) / k * 1e3

            ring_ms = min(
                wall_ms(make_exchange_probe(mesh, block, "ring"))
                for _ in range(2)
            )
            coll_ms = min(
                wall_ms(make_exchange_probe(mesh, block, "collective"))
                for _ in range(2)
            )
            per_hop = []
            prev = 0.0
            for hops in range(1, D):
                t = wall_ms(
                    make_exchange_probe(mesh, block, "ring", hops=hops), k=6
                )
                per_hop.append(round(t - prev, 4))
                prev = t
            entry["exchange"] = {
                "block_shape": list((D,) + block),
                "ring_ms": round(ring_ms, 4),
                "collective_ms": round(coll_ms, 4),
                "ring_per_hop_ms": per_hop,
            }
        out["scaling"][f"D{D}"] = entry

    # acceptance surface: ring exchange no worse than collective on the
    # widest mesh (25% tolerance absorbs launch-overhead noise at CPU
    # smoke shapes; on TPU the ring's DMA overlap is the whole point)
    top = out["scaling"].get(f"D{max(counts)}", {})
    ex = top.get("exchange")
    if ex:
        ratio = ex["ring_ms"] / max(ex["collective_ms"], 1e-9)
        out["ring_vs_collective"] = round(ratio, 3)
        out["ring_no_worse"] = bool(ratio <= 1.25)
    rates = {
        D: out["scaling"][f"D{D}"]
        .get("ring" if D > 1 else "collective", {})
        .get("decisions_per_sec")
        for D in counts
    }
    if rates.get(counts[0]) and rates.get(max(counts)):
        out["scaling_ratio"] = round(
            rates[max(counts)] / rates[counts[0]], 3
        )
    return out


def sharded_ingress_case(rng, now, batch=1 << 17) -> dict:
    """Sharded-vs-local dispatch with the host-stage/device split (the
    tentpole's proof surface): the mesh serving path (ShardedEngine at the
    backend-default route/dedup — on-device a2a routing + in-trace dedup on
    TPU) against LocalEngine on identical 131K-row batches at 1M and 10M
    live keys. Reports per-dispatch wall ms through the pipelined split,
    the mesh path's host-staging split (route/pack/put ms — the shard_*
    stage_duration labels), and a batch-proportionality probe: host-stage
    ms per dispatch at batch vs batch/8 must scale with ROWS, not live
    keys, now that routing/dedup live in-trace and staging buffers persist.
    On non-TPU backends runs a shrunken smoke through the identical code
    path (ci/bench_cpu.py gates on the same figures)."""
    from gubernator_tpu.ops.batch import RequestColumns
    from gubernator_tpu.ops.engine import LocalEngine
    from gubernator_tpu.parallel import make_mesh
    from gubernator_tpu.parallel.sharded import ShardedEngine

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        lives = [1 << 20, 10_000_000]
        cap = 1 << 24
        n_disp = 24
    else:
        lives = [8192]
        cap = 1 << 15
        batch = min(batch, 2048)
        n_disp = 48  # small CPU dispatches need a longer window for the
        # slope's dt floor

    def cols_for(fps):
        n = fps.shape[0]
        return RequestColumns(
            fp=fps,
            algo=np.zeros(n, dtype=np.int32),
            behavior=np.zeros(n, dtype=np.int32),
            hits=np.ones(n, dtype=np.int64),
            limit=np.full(n, 1 << 30, dtype=np.int64),
            burst=np.zeros(n, dtype=np.int64),
            duration=np.full(n, 3_600_000, dtype=np.int64),
            created_at=np.full(n, now, dtype=np.int64),
            err=np.zeros(n, dtype=np.int8),
        )

    mesh = make_mesh()
    out: dict = {"batch": batch, "mesh_devices": int(mesh.devices.size)}
    for live in lives:
        keyspace = rng.integers(1, (1 << 63) - 1, size=live, dtype=np.int64)
        perm = rng.permutation(live)
        nb = max(1, live // batch)
        staged = [
            keyspace[perm[(i % nb) * batch : (i % nb) * batch + batch]]
            for i in range(8)
        ]
        staged = [s for s in staged if s.shape[0] == batch]
        entry: dict = {"live_keys": live}
        sharded = ShardedEngine(
            mesh, capacity_per_shard=max(1024, cap // int(mesh.devices.size))
        )
        local = LocalEngine(capacity=cap)
        entry["route"] = sharded.route
        entry["dedup"] = sharded.dedup
        for name, eng in (("sharded", sharded), ("local", local)):
            # seed through the SAME double-buffered issue/finish split the
            # timed loop uses: the serial per-batch round trips were ~80
            # tunnel RTTs of dead time at 10M keys (ISSUE 5 satellite)
            _pipelined_checks(
                eng,
                (cols_for(keyspace[i : i + batch])
                 for i in range(0, live, batch)),
                now,
            )
            _pipelined_checks(eng, (cols_for(staged[i % len(staged)])
                                    for i in range(2)), now)  # warm

            def timed(k, eng=eng):
                t0 = time.perf_counter()
                _pipelined_checks(
                    eng,
                    (cols_for(staged[i % len(staged)]) for i in range(k)),
                    now,
                )
                return time.perf_counter() - t0

            n_short, n_long = 2, 2 + n_disp
            if hasattr(eng, "take_stage_deltas"):
                eng.take_stage_deltas()  # reset the split to the timed window
                eng.take_wire_deltas()
                d0 = eng.stage_dispatches
            t_short = min(timed(n_short) for _ in range(3))
            t_long = min(timed(n_long) for _ in range(3))
            s = slope(t_short, t_long, n_short, n_long, batch, min_ratio=1.0)
            rec: dict = {}
            if s.reason is None:
                rec["dispatch_ms"] = round(s.per_iter_ms, 3)
                rec["decisions_per_sec"] = round(s.rate, 1)
            else:
                rec["invalid"] = s.reason
            if hasattr(eng, "take_stage_deltas"):
                stage = eng.take_stage_deltas()
                wire = eng.take_wire_deltas()
                nd = max(1, eng.stage_dispatches - d0)
                rec["host_stage_ms"] = {
                    k: round(v / nd, 3) for k, v in stage.items()
                }
                rec["host_stage_total_ms"] = round(
                    sum(stage.values()) / nd, 3
                )
                rec["wire"] = eng.wire
                # denominator = client decisions in the timed window (3
                # repetitions of each slope point); retry sub-dispatches'
                # bytes stay in the numerator — this is bytes/DECISION,
                # the acceptance surface, not bytes/transfer
                rows_timed = 3 * (n_short + n_long) * batch
                rec["wire_bytes_per_row"] = {
                    k: round(v / rows_timed, 2) for k, v in wire.items()
                }
                # transport-dominance gate: the timed window's put share
                # must be accountable against the bytes it shipped
                bad = check_transport(
                    stage["put"] / 1e3, wire["put"], label=f"{name}-put"
                )
                if bad:
                    rec["transport_guard"] = bad
            # a drop storm would let a "fast" path publish while shedding
            # work into retries (bench_guard gate, same as config6)
            guard = check_dropped(
                eng.stats.dropped, eng.stats.checks or 1
            )
            if guard:
                rec["guard"] = guard
            entry[name] = rec
            log(f"[sharded-ingress:{live}] {name}: "
                f"{rec.get('dispatch_ms', rec.get('invalid'))} ms/dispatch"
                + (f", host stage {rec['host_stage_total_ms']} ms"
                   if "host_stage_total_ms" in rec else ""))
        # batch-proportionality probe on the mesh path: host-stage ms at
        # batch/8 — in-trace dedup + persistent staging must make staging
        # scale with rows shipped, not with the keyspace or a host sort
        small = batch // 8
        sharded.take_stage_deltas()
        d0 = sharded.stage_dispatches
        _pipelined_checks(
            sharded,
            (cols_for(staged[i % len(staged)][:small]) for i in range(6)),
            now,
        )
        stage_small = sharded.take_stage_deltas()
        nd = max(1, sharded.stage_dispatches - d0)
        small_ms = sum(stage_small.values()) / nd
        entry["host_stage_small_ms"] = round(small_ms, 3)
        big_ms = entry["sharded"].get("host_stage_total_ms")
        if big_ms:
            # rows ratio is 8×; proportional staging keeps the cost ratio in
            # the same decade, keyspace-bound staging would not move at all
            entry["host_stage_big_vs_small"] = round(big_ms / max(small_ms, 1e-6), 2)
        # table-health snapshot at this population (ops/telemetry.py): the
        # same scan the daemon runs on its background cadence, so BENCH
        # records carry occupancy/collision pressure alongside the rates
        from gubernator_tpu.ops.telemetry import finish_scan

        entry["table_telemetry"] = finish_scan(
            sharded.telemetry_begin(now)
        ).to_dict()
        out[f"{live}"] = entry
    return out


def config3_global_case(rng, now, live=10_000_000, batch=1 << 17,
                        sync_out=16384) -> dict:
    """BASELINE config #3: GLOBAL behavior at 10M keys (8-peer cluster ↦
    mesh; reference global.go:31-307). On the one available chip the mesh is
    1 device, so every key is owner-here: the measured GLOBAL path is
    queue-merge (vectorized group-by, parallel/global_sync.PendingHits) +
    owner-side authoritative dispatch + broadcast markers — the host-work
    side that round 4 left unmeasured (the replica-answer dispatch is the
    same kernel against the replica table, i.e. the plain-dispatch figure).
    Reports:
      * global vs plain dispatch throughput through the SAME engine-serving
        loop (both absorb identical per-dispatch tunnel RTTs, so the RATIO
        isolates the GLOBAL path's host overhead — the verdict's
        within-2x-of-non-GLOBAL criterion);
      * collective sync cost: ms per _sync_round tick and reconciled
        entries/s at the configured outbox size (GlobalSyncWait analog,
        reference config.go:142-146).
    """
    from gubernator_tpu.ops.batch import RequestColumns
    from gubernator_tpu.parallel import make_mesh
    from gubernator_tpu.parallel.global_sync import GlobalShardedEngine
    from gubernator_tpu.parallel.sharded import ShardedEngine

    GLOBAL = int(Behavior.GLOBAL)

    def cols_for(fps, behavior):
        n = fps.shape[0]
        return RequestColumns(
            fp=fps,
            algo=np.zeros(n, dtype=np.int32),
            behavior=np.full(n, behavior, dtype=np.int32),
            hits=np.ones(n, dtype=np.int64),
            limit=np.full(n, 1 << 30, dtype=np.int64),
            burst=np.zeros(n, dtype=np.int64),
            duration=np.full(n, 3_600_000, dtype=np.int64),
            created_at=np.full(n, now, dtype=np.int64),
            err=np.zeros(n, dtype=np.int8),
        )

    mesh = make_mesh(1)
    keyspace = rng.integers(1, (1 << 63) - 1, size=live, dtype=np.int64)
    perm = rng.permutation(live)
    staged = [keyspace[perm[i * batch: (i + 1) * batch]] for i in range(8)]

    out: dict = {"batch": batch, "live_keys": live, "sync_out": sync_out}
    engines = {
        "global": GlobalShardedEngine(
            mesh, capacity_per_shard=1 << 24, sync_out=sync_out
        ),
        "plain": ShardedEngine(mesh, capacity_per_shard=1 << 24),
    }
    for name, eng in engines.items():
        t0 = time.perf_counter()
        # seed the full keyspace through the PLAIN path on both engines
        # (GLOBAL seeding would queue 10M broadcast markers), driven by the
        # double-buffered issue/finish split: the serial loop paid one
        # blocking round trip per 131K-row batch — ~80 tunnel RTTs of dead
        # time per engine at 10M keys (ISSUE 5 satellite)
        _pipelined_checks(
            eng,
            (cols_for(keyspace[i: i + batch], 0)
             for i in range(0, live, batch)),
            now,
        )
        log(f"[config3-global] {name}: seeded {live:,} keys in "
            f"{time.perf_counter() - t0:.0f}s")

    def drain_queue(eng):
        # zero-cost queue reset modeling the steady state where the
        # GlobalSyncWait tick (~1 per dispatch at this rate) keeps the
        # accumulator drained; WITHOUT this the bench-only absence of sync
        # ticks grows pending unboundedly and the group-by merge measures
        # queue depth, not serving cost. The consume side is priced
        # separately in sync_ms_per_round below.
        if hasattr(eng, "pending"):
            for p in eng.pending:
                p.clear()

    def timed(name, k):
        # the daemon's serving loop, not the serial path: prepare/issue of
        # dispatch N+1 overlaps the on-device execution and fetch of N
        # (depth-1 software pipeline, cf. _pipelined_checks) — the serial
        # check_columns loop measured transport round trips, not the path
        # requests actually take through EngineRunner
        from gubernator_tpu.ops.engine import (
            finish_check_columns,
            issue_check_columns,
            prepare_check_columns,
        )

        eng = engines[name]
        behavior = GLOBAL if name == "global" else 0
        fixup = lambda fn: fn()
        prev = None
        t0 = time.perf_counter()
        for i in range(k):
            pending = issue_check_columns(
                eng,
                prepare_check_columns(
                    eng, cols_for(staged[i % 8], behavior), now_ms=now
                ),
            )
            drain_queue(eng)
            if prev is not None:
                _rc, delta = finish_check_columns(eng, prev, fixup)
                eng.stats.merge(delta)
            prev = pending
        _rc, delta = finish_check_columns(eng, prev, fixup)
        eng.stats.merge(delta)
        return time.perf_counter() - t0

    # INTERLEAVED timing: tunnel RTT drifts on the minutes scale, so
    # back-to-back per-engine phases would hand one engine better weather
    # than the other and corrupt the ratio (observed: the identical seed
    # path measured 175s vs 107s across two phases). Alternating runs give
    # both engines the same weather distribution; min-of-3 per point.
    n_short, n_long = 2, 14
    for name in engines:
        timed(name, 2)  # warm residual shapes
        # scope the wire-byte and stage-delta windows to the timed phase
        engines[name].take_wire_deltas()
        engines[name].take_stage_deltas()
    samples = {name: {"s": [], "l": []} for name in engines}
    for _rep in range(3):
        for name in engines:
            samples[name]["s"].append(timed(name, n_short))
        for name in engines:
            samples[name]["l"].append(timed(name, n_long))
    for name in engines:
        s = slope(
            min(samples[name]["s"]), min(samples[name]["l"]),
            n_short, n_long, batch, min_ratio=1.0,
        )
        if s.reason is None:
            out[f"{name}_decisions_per_sec"] = round(s.rate, 1)
            out[f"{name}_dispatch_ms"] = round(s.per_iter_ms, 3)
            log(f"[config3-global] {name}: {s.rate/1e6:.2f}M/s "
                f"({s.per_iter_ms:.2f} ms/dispatch incl. RTT)")
        else:
            out[f"{name}_invalid"] = s.reason
            log(f"[config3-global] {name} slope rejected: {s.reason}")
        # the mesh path's host-staging split (route/pack/put ms per
        # dispatch, cumulative average — the shard_* stage_duration series)
        eng = engines[name]
        nd = max(1, eng.stage_dispatches)
        out[f"{name}_host_stage_ms"] = {
            k: round(v / nd, 3) for k, v in eng.stage_ms.items()
        }
        out[f"{name}_route"] = eng.route
        out[f"{name}_dedup"] = eng.dedup
        out[f"{name}_wire"] = eng.wire
        # bytes/decision over the timed phase (the acceptance surface for
        # the compact-wire reduction), plus the transport-dominance gate
        wire = eng.take_wire_deltas()
        stage_d = eng.take_stage_deltas()
        # denominator = client decisions in the interleaved timed phase
        # (bytes/DECISION — retry sub-dispatch bytes stay in the numerator)
        rows_timed = 3 * (n_short + n_long) * batch
        out[f"{name}_wire_bytes_per_row"] = {
            k: round(v / rows_timed, 2) for k, v in wire.items()
        }
        bad = check_transport(
            stage_d["put"] / 1e3, wire["put"], label=f"config3-{name}-put"
        )
        if bad:
            out[f"{name}_transport_guard"] = bad

    # (b) collective sync: queue a few batches' worth of hits, then time
    # the FUSED drain (sync() runs R rounds per launch); the first pass is
    # an untimed prewarm that pays the fused step's compile
    eng = engines["global"]
    for phase in ("prewarm", "timed"):
        for i in range(4):
            eng.check_columns(cols_for(staged[i], GLOBAL), now_ms=now)
        queued = eng.global_stats.send_queue_length
        r0 = eng.global_stats.sync_rounds
        t0 = time.perf_counter()
        eng.sync(now_ms=now)
        dt = time.perf_counter() - t0
        rounds = eng.global_stats.sync_rounds - r0
    if rounds:
        out["sync_ms_per_round"] = round(dt / rounds * 1e3, 2)
        out["sync_entries_per_sec"] = round(queued / dt, 1)
        log(f"[config3-global] fused sync drain: {queued} entries in "
            f"{rounds} rounds x {sync_out} outbox, {dt:.2f}s = "
            f"{out['sync_ms_per_round']}ms/round, "
            f"{out['sync_entries_per_sec']/1e3:.0f}K entries/s")
    drain_queue(eng)  # defensive: nothing should remain after sync()
    if ("global_decisions_per_sec" in out and "plain_decisions_per_sec" in out):
        out["global_vs_plain"] = round(
            out["global_decisions_per_sec"] / out["plain_decisions_per_sec"], 3
        )
    return out


def config6_latency_case(rng, now, batch=4096) -> dict:
    """Latency-focused phase (the p99 < 2 ms half of the north star):
    `device_ms` of a serving-shape dispatch for write ∈ {sweep, sparse, xla}
    at 1M / 10M / 100M live keys, measured by the RTT-immune on-device loop,
    plus the co-located request budget computed from the measured device
    term.

    Budget model (README "Co-located budget" with GUBER_BATCH_WAIT=0.2 ms,
    coalesce ≤ 4K rows): parse 0.2 + window (mean 0.1 / full 0.2) + put 0.2
    + issue 0.3 + DEVICE + fetch 0.3 + encode 0.1 → p50 ≈ 1.2 + device_ms,
    p99 ≈ 1.3 + device_ms. The sweep write makes the device term table-bound
    (~4 ms/GiB streamed per dispatch); the sparse write's target is a
    batch-bound term — within 2× of the 128 MiB table's at equal batch —
    which puts the 10M-key (1 GiB) p99 budget under 2 ms.

    On non-TPU backends runs a shrunken smoke through the identical code
    path (interpret-mode Pallas) so the phase itself stays exercised."""
    from gubernator_tpu.ops.kernel2 import resolve_write
    from gubernator_tpu.ops.table2 import n_buckets_for

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        # (label, slot capacity, live keys, seed batch)
        sizes = [
            ("1M", 1 << 21, 1 << 20, 1 << 17),
            ("10M", 1 << 24, 10_000_000, 1 << 17),
            ("100M", 1 << 27, 100_000_000, 1 << 20),
        ]
    else:
        batch = min(batch, 128)
        sizes = [("8K-smoke", 1 << 19, 8192, 2048)]
    out = {"batch": batch}
    for label, cap, live, seed_batch in sizes:
        keyspace = rng.integers(1, (1 << 63) - 1, size=live, dtype=np.int64)
        # 8 distinct staged latency batches without a live-sized permutation
        # (cf. config5): oversample, unique, trim
        idx = np.unique(rng.integers(0, live, size=batch * 10, dtype=np.int64))
        idx = rng.permutation(idx)[: batch * 8]
        assert idx.shape[0] == batch * 8
        nb = n_buckets_for(cap)
        entry = {"live_keys": live, "table_mib": nb * 128 * 4 // (1 << 20)}

        def seed_iter():
            for i in range(0, live, seed_batch):
                chunk = keyspace[i : i + seed_batch]
                if chunk.shape[0] < seed_batch:
                    chunk = np.pad(chunk, (0, seed_batch - chunk.shape[0]))
                b = make_req_batch(chunk, now, limit=1 << 30,
                                   duration=3_600_000)
                if (chunk == 0).any():
                    b = b._replace(active=jnp.asarray(chunk != 0))
                yield jax.device_put(b)

        for w in ("sweep", "sparse", "xla"):
            if w == "xla" and cap >= (1 << 27):
                # the XLA scatter at 8 GiB risks doubling HBM (non-aliasing
                # copy) and measured 58 ms/dispatch at 1 GiB — skip, noted
                entry[w] = {"skipped": "xla scatter at 8 GiB table"}
                continue

            def build(w=w):
                batches = [
                    jax.device_put(
                        make_req_batch(
                            keyspace[idx[i * batch : (i + 1) * batch]], now,
                            limit=1 << 30, duration=3_600_000,
                        )
                    )
                    for i in range(8)
                ]
                case = Case(
                    f"config6-{label}-{w}", cap, batches,
                    seed_iter=seed_iter, math="token", write=w,
                )
                case.seed()
                res = case.device_loop()
                res["resolved_write"] = resolve_write(w, nb, batch)
                dev = res.get("device_ms")
                if dev is not None:
                    res["budget_p50_ms"] = round(1.2 + dev, 2)
                    res["budget_p99_ms"] = round(1.3 + dev, 2)
                    log(f"[config6-{label}] write={w} "
                        f"(resolved {res['resolved_write']}): device "
                        f"{dev:.2f} ms → co-located budget p50 "
                        f"{res['budget_p50_ms']} / p99 {res['budget_p99_ms']} ms")
                return res

            entry[w] = _attempt(f"config6-{label}-{w}", build)
        out[label] = entry
    return out


def durability_case(rng, now) -> dict:
    """Durability phase (docs/durability.md): incremental checkpoint cost
    vs the full snapshot, and warm-restart replay vs cold re-seed, at 10M
    live keys on TPU (1M on CPU runs so the phase stays exercised).

    Reported (acceptance surface):
      * delta_bytes / full_bytes — a serving-rate write wave's frame must
        be ≥3× smaller than the base snapshot (measured ~60–600×);
      * extract+frame wall vs full-snapshot wall — checkpoint cost ∝
        write rate, not table size;
      * warm restart (base put + frame replay) vs cold re-seed of the
        same live set — the ≥10× floor behind "minutes of re-seeding
        becomes seconds of replay".
    """
    import tempfile

    from gubernator_tpu.ops.checkpoint import (
        EpochTracker, extract_begin, finish_extract,
    )
    from gubernator_tpu.ops.engine import LocalEngine
    from gubernator_tpu.store import (
        encode_delta_frame, fps_from_slots, load_snapshot_meta,
        save_snapshot,
    )

    tpu = jax.default_backend() == "tpu"
    LIVE = 10_000_000 if tpu else 1_000_000
    BATCH = 1 << 17
    eng = LocalEngine(capacity=int(LIVE * 1.7), write_mode=WRITE)
    eng.ckpt = EpochTracker(eng.table.rows.shape[0])
    keyspace = rng.integers(1, (1 << 63) - 1, size=LIVE, dtype=np.int64)

    def cols_for(fps):
        n = fps.shape[0]
        from gubernator_tpu.ops.batch import RequestColumns

        return RequestColumns(
            fp=fps, algo=np.zeros(n, dtype=np.int32),
            behavior=np.zeros(n, dtype=np.int32),
            hits=np.ones(n, dtype=np.int64),
            limit=np.full(n, 1 << 20, dtype=np.int64),
            burst=np.zeros(n, dtype=np.int64),
            duration=np.full(n, 3_600_000, dtype=np.int64),
            created_at=np.full(n, now, dtype=np.int64),
            err=np.zeros(n, dtype=np.int8),
        )

    # cold re-seed wall: the restart cost the warm path must beat
    t0 = time.perf_counter()
    for i in range(0, LIVE, BATCH):
        eng.check_columns(cols_for(keyspace[i : i + BATCH]), now_ms=now)
    seed_s = time.perf_counter() - t0
    eng.ckpt.take()  # seeding dirt is the base's job, not a delta's

    d = tempfile.mkdtemp()
    base_path = f"{d}/base.npz"
    t0 = time.perf_counter()
    base_rows = eng.snapshot()
    save_snapshot(base_path, base_rows, epoch=1)
    full_s = time.perf_counter() - t0
    full_bytes = int(base_rows.nbytes)

    # one serving-rate write wave → one delta epoch
    wave = np.unique(
        keyspace[rng.integers(0, LIVE, size=BATCH, dtype=np.int64)]
    )
    eng.check_columns(cols_for(wave), now_ms=now + 5)
    epoch, gids = eng.ckpt.take()
    t0 = time.perf_counter()
    d_fps, d_slots = finish_extract(
        extract_begin(eng.table.rows, gids, eng.ckpt.blk, now + 5)
    )
    frame = encode_delta_frame(epoch, now + 5, d_slots)
    delta_s = time.perf_counter() - t0

    # warm restart: base put + frame replay vs the cold re-seed above
    dst = LocalEngine(capacity=int(LIVE * 1.7), write_mode=WRITE)
    t0 = time.perf_counter()
    rows, _base_epoch, _layout = load_snapshot_meta(base_path)
    dst.restore(rows)
    dst.merge_rows(fps_from_slots(d_slots), d_slots, now_ms=now + 5)
    restore_s = time.perf_counter() - t0

    # spot parity: the wave's keys answer identically on both engines
    probe = cols_for(wave[: 1 << 12])
    probe = probe._replace(hits=np.zeros(probe.fp.shape[0], dtype=np.int64))
    a = eng.check_columns(probe, now_ms=now + 6)
    b = dst.check_columns(probe, now_ms=now + 6)
    parity = bool(
        np.array_equal(a.remaining, b.remaining)
        and np.array_equal(a.status, b.status)
    )
    out = {
        "live_keys": LIVE,
        "seed_s": round(seed_s, 2),
        "full_snapshot_s": round(full_s, 2),
        "full_snapshot_bytes": full_bytes,
        "delta_rows": int(d_fps.shape[0]),
        "delta_bytes": len(frame),
        "delta_s": round(delta_s, 3),
        "delta_reduction": round(full_bytes / len(frame), 1),
        "warm_restart_s": round(restore_s, 2),
        "warm_vs_cold_speedup": round(seed_s / max(restore_s, 1e-6), 1),
        "replay_parity": parity,
    }
    if not parity:
        out["invalid"] = "warm-restarted engine diverged from the source"
    return out


def sweep_parity_smoke(rng, now):
    """Real-TPU check that BOTH Pallas write paths — the full sweep and the
    block-sparse grid — produce the same table and responses as the XLA
    scatter write. This is also the sparse path's proof-of-work anchor: the
    RTT-immune device loop can't reveal a write that lands in the wrong
    blocks (hits still reconcile), so the record carries this explicit
    state-equality check next to every published rate. Returns True/False,
    or "skipped" on backends without the TPU Pallas path (CPU covers the
    same comparisons in interpret mode under pytest — tests/test_kernel2.py,
    tests/test_sparse_write.py)."""
    from gubernator_tpu.ops.kernel2 import resolve_write
    from gubernator_tpu.ops.table2 import n_buckets_for

    if WRITE == "xla":
        log("[parity] skipped (no TPU Pallas write path on this backend)")
        return "skipped"
    # geometry chosen so "sparse" actually resolves sparse (a 2^21-bucket
    # table over a 4K batch stays well inside the coverage crossover)
    cap = 1 << 24
    B = 4096
    nb = n_buckets_for(cap)
    resolved = resolve_write("sparse", nb, B)
    if resolved != "sparse":
        log(f"[parity] WARNING: sparse resolved to {resolved!r} at NB={nb} "
            f"B={B}; smoke would not exercise the sparse grid")
    fps = rng.integers(1, (1 << 63) - 1, size=B, dtype=np.int64)
    tables = {w: new_table2(cap) for w in ("sweep", "sparse", "xla")}
    ok = True
    for step in range(3):
        b = make_req_batch(fps, now + step * 1000, limit=3)
        resps = {}
        for w in tables:
            tables[w], resps[w], _ = decide2(tables[w], b, write=w)
        for w in ("sweep", "sparse"):
            same_resp = bool(
                jnp.array_equal(resps[w].status, resps["xla"].status)
                & jnp.array_equal(resps[w].remaining, resps["xla"].remaining)
                & jnp.array_equal(resps[w].reset_time, resps["xla"].reset_time)
            )
            ok = ok and same_resp
    for w in ("sweep", "sparse"):
        ok = ok and bool(jnp.array_equal(tables[w].rows, tables["xla"].rows))
    log(f"[parity] sweep+sparse vs xla on {jax.default_backend()}: "
        f"responses+tables equal = {ok}")
    return ok


def wire_parity_smoke(rng, now):
    """Compact-wire vs full-width parity on the real backend: two
    ShardedEngines at the backend-default route/dedup, one forced
    wire="compact" and one wire="full" (the oracle), serve identical
    token/leaky/duplicate-key/flagged batches — responses must match
    row-for-row. This is the record's proof that the wire win is an
    encoding, not a semantics change: the RTT-immune timing loops cannot
    see a decode that reconstructs the wrong request. Returns True/False."""
    from gubernator_tpu.ops.batch import RequestColumns
    from gubernator_tpu.parallel import make_mesh
    from gubernator_tpu.parallel.sharded import ShardedEngine

    mesh = make_mesh()
    n = 4096
    kw = dict(capacity_per_shard=1 << 15)
    ec = ShardedEngine(mesh, wire="compact", **kw)
    ef = ShardedEngine(mesh, wire="full", **kw)
    ok = True
    for step in range(3):
        fp = rng.integers(1, (1 << 63) - 1, size=n, dtype=np.int64)
        if step == 1:
            fp[n // 2 :] = fp[: n - n // 2]  # duplicate keys (dedup path)
        cols = RequestColumns(
            fp=fp,
            algo=rng.integers(0, 2, n).astype(np.int32),
            behavior=rng.choice([0, 8, 32], size=n).astype(np.int32),
            hits=rng.integers(0, 4, n).astype(np.int64),
            limit=np.full(n, 100, dtype=np.int64),
            burst=np.zeros(n, dtype=np.int64),
            duration=np.full(n, 60_000, dtype=np.int64),
            created_at=np.full(n, now, dtype=np.int64),
            err=np.zeros(n, dtype=np.int8),
        )
        rc = ec.check_columns(cols, now_ms=now + step)
        rf = ef.check_columns(cols, now_ms=now + step)
        for f in ("status", "limit", "remaining", "reset_time", "err"):
            ok = ok and bool(np.array_equal(getattr(rc, f), getattr(rf, f)))
    w, wf = ec.take_wire_deltas(), ef.take_wire_deltas()
    log(
        f"[wire-parity] compact vs full on {jax.default_backend()}: "
        f"equal={ok}; bytes put {w['put']} vs {wf['put']}, "
        f"fetch {w['fetch']} vs {wf['fetch']}"
    )
    return ok


def _stage_p99_ms(scraped: dict, stages, q: float = 0.99) -> dict:
    """Per-stage tail estimate from the gubernator_tpu_stage_duration
    HISTOGRAM buckets (linear interpolation within the straddling bucket —
    the standard histogram_quantile estimate). The Summary-era bench could
    only report stage MEANS, which hid exactly the tail behavior the
    serving plane is judged on."""
    buckets = scraped.get("gubernator_tpu_stage_duration_bucket", {})
    counts = scraped.get("gubernator_tpu_stage_duration_count", {})
    out = {}
    for st in stages:
        total = counts.get((("stage", st),))
        if not total:
            continue
        bs = sorted(
            (float(dict(k)["le"]), v)
            for k, v in buckets.items()
            if dict(k).get("stage") == st and dict(k)["le"] != "+Inf"
        )
        target = q * total
        prev_le, prev_cum = 0.0, 0.0
        est = None
        for le, cum in bs:
            if cum >= target:
                frac = (target - prev_cum) / max(cum - prev_cum, 1e-12)
                est = prev_le + frac * (le - prev_le)
                break
            prev_le, prev_cum = le, cum
        if est is None:
            est = bs[-1][0] if bs else 0.0  # tail above the last bucket
        out[st] = round(est * 1e3, 3)
    return out


def e2e_serving_case() -> dict:
    """End-to-end serving: a real daemon (gRPC listener, pipelined batching
    front door, engine on this device) driven by the async client over
    loopback — the reference's headline is server-level req/s
    (README.md:131-154). The front door keeps ≤6 dispatches in flight
    (prepare → issue → fetch overlapped); per-stage means are scraped from
    the daemon's own gubernator_tpu_stage_duration summaries.

    On the tunneled axon platform every device put/launch/fetch pays a
    ~30-130 ms RTT, so this number is a LOWER bound for a co-located TPU
    host. Co-located p99 < 2 ms budget (BASELINE north star), computed
    from the measured stages with tunnel RTTs replaced by on-device costs:
    parse 0.2 ms + window 0.5 ms + put ~0.2 ms (PCIe-class transfer of one
    packed (12,B) array) + issue ~0.3 ms + device compute MEASURED by the
    on-device loop at serving shapes on a 128 MiB/1M-key table
    (exp/exp_serving_device*.py: 0.60 ms at 4K rows, 0.72 at 8K, 0.98 at
    16K; 4.11 ms at 16K on the 1 GiB table) + fetch ~0.3 ms (one packed
    output array) + encode 0.1 ms ≈ 2.2-2.6 ms request time at the
    defaults. With batch_wait at 0.2 ms the sum is 1.9 ms at coalesce
    ≤4K rows (device term 0.60) and 2.0 ms at 8K (0.72) — the p99 < 2 ms
    north-star point is the ≤4K setting, where one chip still serves
    6.8M decisions/s through the door (11.4M/s at 8K, device-loop
    measured)."""
    import asyncio

    from gubernator_tpu.client import V1Client
    from gubernator_tpu.config import BehaviorConfig, DaemonConfig
    from gubernator_tpu.proto import gubernator_pb2 as pb
    from gubernator_tpu.service.daemon import Daemon

    import os

    # closed-loop clients: offered load = CLIENTS × BATCH rows outstanding.
    # The pipelined front door (issue/compute/fetch overlapped, ≤6 in-flight
    # dispatches) absorbs 64 concurrent requests. On the tunneled dev TPU
    # the number is op-rate-bound: every device op (put/launch/fetch) is a
    # serialized ~RTT round trip, so deeper pipelines or bigger coalesced
    # dispatches just lengthen the fetch queue (measured: 128 clients ×
    # 32K coalesce × 8 inflight = 69K checks/s vs this config's 80K at
    # ~100 ms RTT weather). Env-overridable for tuning runs.
    CLIENTS = int(os.environ.get("E2E_CLIENTS", 64))
    # items per RPC; above 1000 the daemon's GUBER_MAX_BATCH_SIZE is raised
    # to match (the configurable wire cap — fewer RPCs of proto framing for
    # the same offered rows)
    BATCH = int(os.environ.get("E2E_BATCH", 1000))
    SECONDS = float(os.environ.get("E2E_SECONDS", 12.0))
    # gRPC channels the PUBLIC client fans requests over: one channel
    # serializes every response onto a single TCP stream, which caps the
    # measured number at the client, not the server
    CHANNELS = int(os.environ.get("E2E_CHANNELS", 4))

    async def run() -> dict:
        conf = DaemonConfig(
            grpc_address="127.0.0.1:0",
            http_address="",
            cache_size=1 << 20,
            max_batch_size=max(1000, BATCH),
            behaviors=BehaviorConfig(
                batch_wait_ms=2.0,
                pipeline_inflight=int(os.environ.get("E2E_INFLIGHT", 6)),
                coalesce_limit=int(os.environ.get("E2E_COALESCE", 16384)),
                front_workers=int(os.environ.get("E2E_FRONT_WORKERS", 0)),
            ),
        )
        d = await Daemon.spawn(conf)
        # Pre-warm every pow2 batch shape the front door can coalesce
        # (chunks of whole 1000-row enqueues up to the 16384 coalesce cap →
        # pad sizes 1024..16384). XLA compiles are seconds each on this
        # platform; without this they land inside the measured window
        # whenever arrival timing produces a shape the warm phase missed.
        from gubernator_tpu.ops.batch import RequestColumns

        size = 1024
        t0 = time.perf_counter()
        while size <= conf.behaviors.coalesce_limit:
            warm = RequestColumns(
                fp=np.arange(1, size + 1, dtype=np.int64),
                algo=np.zeros(size, dtype=np.int32),
                behavior=np.zeros(size, dtype=np.int32),
                hits=np.zeros(size, dtype=np.int64),
                limit=np.full(size, 1 << 30, dtype=np.int64),
                burst=np.zeros(size, dtype=np.int64),
                duration=np.ones(size, dtype=np.int64),
                created_at=np.zeros(size, dtype=np.int64),
                err=np.zeros(size, dtype=np.int8),
            )
            await d.runner.check(warm)
            size *= 2
        log(f"[e2e-serving] shape pre-warm: {time.perf_counter() - t0:.1f}s")
        client = V1Client(d.conf.grpc_address, timeout_s=120.0, channels=CHANNELS)
        rng = np.random.default_rng(9)
        reqs = [
            [
                pb.RateLimitReq(
                    name="bench", unique_key=f"c{c}k{i}", hits=1,
                    limit=1 << 30, duration=60_000,
                )
                for i in range(BATCH)
            ]
            for c in range(CLIENTS)
        ]
        # thundering-herd corpus: every client hammers ONE key (reference
        # benchmark_test.go:121-148, 100-way herd). The pass planner folds
        # the same-key flood into ≤ max_exact sequential passes per dispatch
        # (ops/plan.py — the analog of the reference's per-key worker
        # serialization), so the door keeps serving instead of collapsing
        # to one row per dispatch.
        hot_reqs = [
            [
                pb.RateLimitReq(
                    name="bench", unique_key="herd", hits=1,
                    limit=1 << 30, duration=60_000,
                )
                for _ in range(BATCH)
            ]
            for _ in range(CLIENTS)
        ]
        lat: list = []
        counts = [0]

        # the PUBLIC client path — request build + serialize per call, multi-
        # channel round-robin — so the measured number is what users get,
        # not a hand-rolled stub's
        async def worker(c, corpus):
            my = corpus[c]
            while time.perf_counter() < deadline:
                t0 = time.perf_counter()
                resp = await client.get_rate_limits(my, timeout_s=120.0)
                lat.append(time.perf_counter() - t0)
                counts[0] += len(resp.responses)

        # warm every coalesced shape first (different arrival timings produce
        # different padded batch shapes; each compiles once)
        warm_deadline = time.perf_counter() + 6
        deadline = warm_deadline
        await asyncio.gather(*(worker(c, reqs) for c in range(CLIENTS)))
        lat.clear()
        counts[0] = 0
        t0 = time.perf_counter()
        deadline = t0 + SECONDS
        await asyncio.gather(*(worker(c, reqs) for c in range(CLIENTS)))
        distinct_elapsed = time.perf_counter() - t0
        distinct_lat = list(lat)
        distinct_count = counts[0]
        # scrape the per-stage breakdown NOW, before herd traffic pollutes
        # the cumulative stage_duration summaries — these means must explain
        # the distinct-phase latency figures they are reported next to
        from gubernator_tpu.service.metrics import parse_metrics

        scraped = parse_metrics(d.metrics.render().decode())

        # hot-key phase through the SAME door (planner warm from above)
        deadline = time.perf_counter() + 3  # shape warm for the herd corpus
        await asyncio.gather(*(worker(c, hot_reqs) for c in range(CLIENTS)))
        lat.clear()
        counts[0] = 0
        t0 = time.perf_counter()
        deadline = t0 + SECONDS
        await asyncio.gather(*(worker(c, hot_reqs) for c in range(CLIENTS)))
        hot_elapsed = time.perf_counter() - t0
        hot_count = counts[0]
        # per-stage pipeline breakdown from the distinct-phase scrape —
        # where a request's time actually goes; means AND p99 (histogram
        # buckets) so BENCH_r06+ can track per-stage tail behavior
        STAGES = ("parse", "queue", "put", "issue", "fetch", "encode")
        stages = {}
        for st in STAGES:
            key = (("stage", st),)
            cnt = scraped.get("gubernator_tpu_stage_duration_count", {}).get(key)
            tot = scraped.get("gubernator_tpu_stage_duration_sum", {}).get(key)
            if cnt:
                stages[st] = round(tot / cnt * 1e3, 3)
        stage_p99 = _stage_p99_ms(scraped, STAGES)
        # table-health snapshot through the daemon's own background-scan
        # path (engine-thread launch, off-thread fetch) — lands in the
        # bench JSON next to the serving numbers it contextualizes
        telemetry = (await d.runner.table_telemetry()).to_dict()
        await client.close()
        await d.close()
        arr = np.asarray(sorted(distinct_lat)) * 1e3
        hot_cps = round(hot_count / hot_elapsed, 1)
        dis_cps = round(distinct_count / distinct_elapsed, 1)
        return {
            "checks_per_sec": dis_cps,
            "clients": CLIENTS,
            "channels": CHANNELS,
            "batch": BATCH,
            "request_p50_ms": round(float(np.percentile(arr, 50)), 2),
            "request_p99_ms": round(float(np.percentile(arr, 99)), 2),
            "stage_mean_ms": stages,
            "stage_p99_ms": stage_p99,
            # front-door path accounting: fused = wire bytes staged straight
            # into the dispatch grid (parse once, stage once)
            "fused_dispatches": d.batcher.fused_dispatches,
            "column_dispatches": d.batcher.column_dispatches,
            "adaptive_closes": d.batcher.adaptive_closes,
            "window_expires": d.batcher.window_expires,
            "table_telemetry": telemetry,
            # thundering herd: one key, CLIENTS-way closed loop; the ratio
            # is the planner's hot-key cost (max_exact sequential passes +
            # aggregate tail per dispatch vs 1 pass for distinct keys)
            "hotkey_checks_per_sec": hot_cps,
            "hotkey_vs_distinct": round(hot_cps / max(dis_cps, 1e-9), 3),
        }

    out = asyncio.run(run())
    log(
        f"[e2e-serving] {out['checks_per_sec']/1e3:.1f}K checks/s through the "
        f"gRPC front door; request p50={out['request_p50_ms']}ms "
        f"p99={out['request_p99_ms']}ms ({CLIENTS} clients x {BATCH}-item batches); "
        f"hot-key herd {out['hotkey_checks_per_sec']/1e3:.1f}K checks/s "
        f"({out['hotkey_vs_distinct']:.2f}x distinct)"
    )
    return out


# --------------------------------------------------------------- overload
# Replayable load-scenario harness (docs/robustness.md "Overload & QoS").
# A scenario is a FIXED schedule of steps — (label, worker-count
# multiplier, corpus kind) — driven through a loopback daemon with the
# overload plane armed. The corpus is seeded and pre-serialized, the
# schedule is data, and the daemon knobs are pinned by the caller, so a
# run is replayable bit-for-bit on the request side; what moves between
# runs is only machine weather. Each step emits one record — offered
# rows/s, goodput rows/s (rows answered without a shed/error), shed
# split, per-tier request p99 — and the records across a scenario ARE
# its goodput-vs-offered-load curve. ci/bench_cpu.py drives the same
# function for the overload_smoke CI gate.

OVERLOAD_SHED_MARK = "shed under overload"

# tier mix for "mixed" corpora: mostly best-effort, a thin critical band —
# the shape that makes priority inversions visible if they exist
_TIER_CYCLE = (0, 0, 0, 1, 0, 1, 2, 0, 0, 1, 2, 3)

_OVERLOAD_SCENARIOS = {
    # slow ramp up and back down — the daily curve; nothing should shed
    # at the trough, the peak probes the admission boundary
    "diurnal": [("t025", 1, "mixed"), ("t05", 2, "mixed"),
                ("peak", 4, "mixed"), ("t05b", 2, "mixed"),
                ("t025b", 1, "mixed")],
    # 10x step overload: the headline robustness scenario — the door must
    # keep top-tier p99 bounded and shed the excess instead of queueing
    "flash_crowd": [("pre", 1, "mixed"), ("flash", 10, "mixed"),
                    ("post", 1, "mixed")],
    # every worker hammers ONE key: pass-planner pressure + queue growth
    "hotkey_storm": [("pre", 1, "mixed"), ("storm", 6, "hot"),
                     ("post", 1, "mixed")],
    # one tenant (single fingerprint bucket) offers far beyond its fair
    # share while the victims stay steady — fairness must cap the abuser
    "abusive_tenant": [("pre", 2, "mixed"), ("abuse", 2, "abuse"),
                       ("post", 2, "mixed")],
    # wide mixed traffic over a >=1M-key corpus at moderate overload
    "mixed_1m": [("steady", 3, "mixed")],
}


def _overload_corpus(kind: str, *, keys: int, rows: int, workers: int,
                     seed: int, per_worker: int = 16) -> "list[list[bytes]]":
    """Pre-serialized request bytes per worker: `per_worker` distinct
    GetRateLimitsReq payloads each worker cycles through. Deterministic in
    (kind, keys, rows, workers, seed) — the replayable half of the
    harness. Tier rides behavior bits 6-7 (types.with_priority)."""
    from gubernator_tpu.proto import gubernator_pb2 as pb
    from gubernator_tpu.types import with_priority

    out = []
    for w in range(workers):
        tier = _TIER_CYCLE[w % len(_TIER_CYCLE)]
        if kind == "abuse":
            # half the workers are the abuser: ONE tenant keyspace whose
            # payloads all lead with the same key (= one fingerprint
            # bucket at the batcher), offered at full tilt, lowest tier;
            # the other half are steady distinct-tenant victims
            abuser = w % 2 == 1
            tier = 0 if abuser else _TIER_CYCLE[w % len(_TIER_CYCLE)]
        reqs = []
        for r in range(per_worker):
            items = []
            for i in range(rows):
                if kind == "hot":
                    key = "storm-key"
                elif kind == "abuse" and w % 2 == 1:
                    # abuser: tiny keyset, stable leading key → one bucket
                    key = f"abuser-k{i % 8}"
                else:
                    key = f"w{w}r{r}i{i}-{(w * per_worker * rows + r * rows + i) % keys}"
                items.append(pb.RateLimitReq(
                    name="ovl", unique_key=key, hits=1,
                    limit=1 << 30, duration=60_000,
                    behavior=with_priority(0, tier),
                ))
            reqs.append(pb.GetRateLimitsReq(requests=items).SerializeToString())
        out.append(reqs)
    return out


def drive_overload_scenario(
    scenario: str,
    *,
    seconds_per_step: float = 2.0,
    base_workers: int = 6,
    rows_per_req: int = 256,
    keys: int = 1 << 17,
    overload_deadline_ms: float = 75.0,
    batch_queue_rows: int = 4096,
    coalesce_limit: int = 2048,
    batch_wait_ms: float = 1.0,
    tenant_share: float = 0.5,
    seed: int = 0,
) -> dict:
    """Run one named scenario through a fresh loopback daemon with the
    overload plane armed; returns the per-step goodput-vs-offered-load
    curve plus the daemon's own shed/inversion accounting."""
    import asyncio

    from gubernator_tpu.config import BehaviorConfig, DaemonConfig
    from gubernator_tpu.proto import gubernator_pb2 as pb
    from gubernator_tpu.service.daemon import Daemon

    steps = _OVERLOAD_SCENARIOS[scenario]
    max_workers = max(m for _l, m, _k in steps) * base_workers

    async def run() -> dict:
        conf = DaemonConfig(
            grpc_address="127.0.0.1:0", http_address="",
            cache_size=1 << 21 if scenario == "mixed_1m" else 1 << 18,
            max_batch_size=max(1000, rows_per_req),
            behaviors=BehaviorConfig(
                batch_wait_ms=batch_wait_ms,
                coalesce_limit=coalesce_limit,
                batch_queue_rows=batch_queue_rows,
                # spawn UNARMED: the warm waves below must all dispatch
                # (an armed door sheds them, leaving chunk shapes
                # uncompiled); armed right before the timed windows
                overload_deadline_ms=0.0,
                overload_tenant_share=tenant_share,
            ),
        )
        d = await Daemon.spawn(conf)
        n_keys = max(keys, 1 << 20) if scenario == "mixed_1m" else keys
        corpus = {
            kind: _overload_corpus(
                kind, keys=n_keys, rows=rows_per_req,
                workers=max_workers, seed=seed,
            )
            for kind in {k for _l, _m, k in steps}
        }
        # shape warm, through the UNARMED door (backpressure, no sheds —
        # every wave dispatches): ramp the wave width so each pow2 coalesce
        # chunk the schedule can produce compiles BEFORE a timed window —
        # an XLA compile landing inside the flash step would masquerade as
        # queueing latency. A wave that ran slow probably just compiled
        # something; repeat it until a pass comes back fast (compile-free)
        warm = corpus[steps[0][2]]
        n_w = 1
        ramp = []
        while n_w < max_workers:
            ramp.append(n_w)
            n_w *= 2
        ramp.append(max_workers)
        for r, n_w in enumerate(ramp + [max_workers]):
            for _attempt in range(5):
                t0 = time.perf_counter()
                await asyncio.gather(*(
                    d.get_rate_limits_raw(warm[w][r % len(warm[w])])
                    for w in range(n_w)
                ))
                if time.perf_counter() - t0 < 0.25:
                    break
        d.batcher.arm_overload(overload_deadline_ms)

        async def worker(w: int, tier: int, reqs, stop: list, rec: dict):
            i = 0
            while not stop[0]:
                data = reqs[i % len(reqs)]
                i += 1
                t0 = time.perf_counter()
                try:
                    raw = await d.get_rate_limits_raw(data)
                except Exception:
                    rec["errors"] += rows_per_req
                    continue
                dt = time.perf_counter() - t0
                resp = pb.GetRateLimitsResp.FromString(raw)
                served = shed = errs = 0
                for r in resp.responses:
                    if not r.error:
                        served += 1
                    elif OVERLOAD_SHED_MARK in r.error:
                        shed += 1
                    else:
                        errs += 1
                rec["offered"] += len(resp.responses)
                rec["served"] += served
                rec["shed"] += shed
                rec["errors"] += errs
                rec["lat_by_tier"].setdefault(tier, []).append(dt)

        curve = []
        for label, mult, kind in steps:
            n_w = mult * base_workers
            rec = {"offered": 0, "served": 0, "shed": 0, "errors": 0,
                   "lat_by_tier": {}}
            stop = [False]
            dbg0 = d.batcher.debug()
            tasks = [
                asyncio.ensure_future(worker(
                    w,
                    # the corpus's own tier assignment (abusers ride tier 0)
                    0 if kind == "abuse" and w % 2 == 1
                    else _TIER_CYCLE[w % len(_TIER_CYCLE)],
                    corpus[kind][w], stop, rec,
                ))
                for w in range(n_w)
            ]
            t0 = time.perf_counter()
            await asyncio.sleep(seconds_per_step)
            stop[0] = True
            await asyncio.gather(*tasks)
            elapsed = time.perf_counter() - t0
            dbg1 = d.batcher.debug()
            p99 = {
                str(t): round(
                    float(np.percentile(np.asarray(v) * 1e3, 99)), 2
                )
                for t, v in sorted(rec["lat_by_tier"].items())
            }
            curve.append({
                "step": label,
                "workers": n_w,
                "offered_rows_per_s": round(rec["offered"] / elapsed, 1),
                "goodput_rows_per_s": round(rec["served"] / elapsed, 1),
                "shed_rows_per_s": round(rec["shed"] / elapsed, 1),
                "error_rows": rec["errors"],
                "request_p99_ms_by_tier": p99,
                "sheds": {
                    k: dbg1["shed_rows"][k] - dbg0["shed_rows"][k]
                    for k in dbg1["shed_rows"]
                },
            })
        dbg = d.batcher.debug()
        await d.close()
        return {
            "scenario": scenario,
            "curve": curve,
            "priority_inversions": dbg["priority_inversions"],
            "shed_rows": dbg["shed_rows"],
            "shed_by_tier": dbg["shed_by_tier"],
            "admitted_by_tier": dbg["admitted_by_tier"],
            "knobs": {
                "overload_deadline_ms": overload_deadline_ms,
                "batch_queue_rows": batch_queue_rows,
                "tenant_share": tenant_share,
                "rows_per_req": rows_per_req,
                "seconds_per_step": seconds_per_step,
            },
        }

    return asyncio.run(run())


def overload_case() -> dict:
    """Bench-matrix overload phase: all five scenarios, each its own
    loopback daemon, the per-step records forming the
    goodput-vs-offered-load curves the robustness doc points at."""
    import os

    out: dict = {}
    secs = float(os.environ.get("OVL_SECONDS", 2.0))
    for name in _OVERLOAD_SCENARIOS:
        res = drive_overload_scenario(name, seconds_per_step=secs)
        out[name] = res
        peak = max(res["curve"], key=lambda s: s["offered_rows_per_s"])
        log(
            f"[overload:{name}] peak offered "
            f"{peak['offered_rows_per_s']/1e3:.1f}K rows/s, goodput "
            f"{peak['goodput_rows_per_s']/1e3:.1f}K, shed "
            f"{peak['shed_rows_per_s']/1e3:.1f}K; inversions="
            f"{res['priority_inversions']}"
        )
        if res["priority_inversions"]:
            out["error"] = f"{name}: priority inversions observed"
    return out


def algorithms_case(rng, now) -> dict:
    """ISSUE-10 scenario-breadth phase: per-algorithm device throughput at
    the headline geometry (10M live keys on TPU / 1M on CPU, 128K batch).

    The acceptance headline is the GCRA-vs-token ratio: GCRA's decision
    table runs one TAT compare-and-advance over a single raw-int64 lane
    (fewer decode/writeback lanes than token's remaining/status machinery),
    so its device decisions/s must be ≥ token bucket's at identical batch
    and table geometry. Sliding-window and lease rates are recorded
    alongside (both all-integer graphs)."""
    on_tpu = jax.default_backend() == "tpu"
    LIVE = 10_000_000 if on_tpu else 1 << 20
    BATCH = 1 << 17
    CAPACITY = 1 << 24 if on_tpu else 1 << 21
    out: dict = {"live_keys": LIVE, "batch": BATCH}
    rates: dict = {}
    for label, algo_v, math in (
        ("token_bucket", int(Algorithm.TOKEN_BUCKET), "token"),
        ("gcra", int(Algorithm.GCRA), "gcra"),
        ("sliding_window", int(Algorithm.SLIDING_WINDOW), "int"),
        ("concurrency_lease", int(Algorithm.CONCURRENCY_LEASE), "int"),
    ):
        keyspace = rng.integers(1, (1 << 63) - 1, size=LIVE, dtype=np.int64)
        perm = rng.permutation(LIVE)
        algo = np.full(BATCH, algo_v, dtype=np.int32)
        batches = [
            jax.device_put(
                make_req_batch(
                    keyspace[perm[i * BATCH: (i + 1) * BATCH]], now,
                    algo=algo, limit=1 << 20, duration=3_600_000,
                )
            )
            for i in range(min(8, LIVE // BATCH))
        ]
        seed = [
            jax.device_put(
                make_req_batch(
                    keyspace[i * BATCH: (i + 1) * BATCH], now, algo=algo,
                    limit=1 << 20, duration=3_600_000,
                )
            )
            for i in range(LIVE // BATCH)
        ]
        case = Case(f"algo-{label}", CAPACITY, batches, seed_batches=seed,
                    math=math)
        case.seed()
        res = case.device_loop()
        out[label] = res
        if "device_decisions_per_sec" in res:
            rates[label] = res["device_decisions_per_sec"]
        # release this algorithm's table before the next seeds
        case.table = None
    if "gcra" in rates and "token_bucket" in rates:
        ratio = rates["gcra"] / max(rates["token_bucket"], 1e-9)
        out["gcra_vs_token_loop"] = round(ratio, 3)

    # apples-to-apples kernel A/B (the acceptance comparison): the SAME
    # batch of fps through one dispatch per algorithm against identical
    # fresh tables — no loop-harness state drift, best-of-6 walls. GCRA's
    # decision table (one TAT compare-and-advance, no new/existing fork,
    # no sticky status) must not be slower than token's.
    from gubernator_tpu.ops.batch import HostBatch, pack_host_batch
    from gubernator_tpu.ops.kernel2 import decide2_packed_cols

    fps = rng.integers(1, (1 << 63) - 1, size=BATCH, dtype=np.int64)
    kernel_ms = {}
    for label, algo_v, math in (
        ("token_bucket", 0, "token"), ("gcra", 2, "gcra"),
    ):
        tbl = new_table2(CAPACITY)
        rb = make_req_batch(fps, now, algo=np.full(BATCH, algo_v, np.int32),
                            limit=1 << 20, duration=3_600_000)
        hb = HostBatch(**{f: np.asarray(getattr(rb, f))
                          for f in HostBatch._fields})
        arr = jax.device_put(jnp.asarray(pack_host_batch(hb)))
        tbl, o = decide2_packed_cols(tbl, arr, write=WRITE, math=math)
        np.asarray(o)  # compile + seed
        best = None
        for _ in range(6):
            t0 = time.perf_counter()
            tbl, o = decide2_packed_cols(tbl, arr, write=WRITE, math=math)
            np.asarray(o)
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        kernel_ms[label] = best * 1e3
        del tbl
    out["token_kernel_ms"] = round(kernel_ms["token_bucket"], 2)
    out["gcra_kernel_ms"] = round(kernel_ms["gcra"], 2)
    kratio = kernel_ms["token_bucket"] / max(kernel_ms["gcra"], 1e-9)
    out["gcra_vs_token"] = round(kratio, 3)
    out["gcra_no_worse"] = bool(kratio >= 1.0)
    log(f"[algorithms] gcra/token kernel ratio: {kratio:.3f} "
        f"({'OK' if kratio >= 1.0 else 'BELOW TOKEN'}); "
        f"loop-harness ratio {out.get('gcra_vs_token_loop')}")
    return out


def cascade_case(rng, now) -> dict:
    """ISSUE-10 cascade phase: a 3-level cascade (per-user + per-tenant +
    global) against three sequential single-level checks.

    Two rungs: (a) ENGINE — one compact-wire dispatch carrying all levels
    vs three dependent dispatches of the same rows (the kernel-launch
    amortization); (b) E2E — a loopback daemon driven with one cascade RPC
    per check vs three dependent RPCs (the round-trip amortization the
    serving plane actually buys; acceptance ≥ 2.5x, gated in
    ci/bench_cpu.py algo_smoke)."""
    import asyncio

    from gubernator_tpu.hashing import fingerprint
    from gubernator_tpu.ops.batch import RequestColumns
    from gubernator_tpu.ops.engine import LocalEngine

    N = 1 << 12
    out: dict = {"cascades": N}

    def level_cols(tag, level, algo_v, n, t):
        return RequestColumns(
            fp=np.array(
                [fingerprint("cph", f"{tag}{i}") for i in range(n)],
                dtype=np.int64,
            ),
            algo=np.full(n, algo_v, dtype=np.int32),
            behavior=np.full(n, level << 8, dtype=np.int32),
            hits=np.ones(n, dtype=np.int64),
            limit=np.full(n, 1 << 20, dtype=np.int64),
            burst=np.zeros(n, dtype=np.int64),
            duration=np.full(n, 3_600_000, dtype=np.int64),
            created_at=np.full(n, t, dtype=np.int64),
            err=np.zeros(n, dtype=np.int8),
        )

    def interleave(parts):
        cols = [np.stack([p[k] for p in parts], axis=1).reshape(-1)
                for k in range(len(parts[0]))]
        return RequestColumns(*cols)

    eng = LocalEngine(capacity=1 << 18, wire="compact")
    u = lambda t: level_cols("u", 0, 0, N, t)
    ten = lambda t: level_cols("t", 1, int(Algorithm.SLIDING_WINDOW), N, t)
    gl = lambda t: level_cols("g", 2, int(Algorithm.GCRA), N, t)
    casc = lambda t: interleave([u(t), ten(t), gl(t)])
    # warm both shapes
    eng.check_columns(casc(now), now_ms=now)
    for f in (u, ten, gl):
        eng.check_columns(f(now), now_ms=now)
    K = 12

    def wall(fn):
        best = None
        for r in range(3):
            t0 = time.perf_counter()
            for k in range(K):
                fn(now + 1 + r * K + k)
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        return best

    casc_s = wall(lambda t: eng.check_columns(casc(t), now_ms=t))
    seq_s = wall(lambda t: [eng.check_columns(f(t), now_ms=t)
                            for f in (u, ten, gl)])
    d0 = eng.stats.dispatches
    eng.check_columns(casc(now + 10_000_000), now_ms=now + 10_000_000)
    out["engine_single_dispatch"] = int(eng.stats.dispatches - d0) == 1
    out["engine_cascade_ms_per_batch"] = round(casc_s / K * 1e3, 3)
    out["engine_sequential_ms_per_batch"] = round(seq_s / K * 1e3, 3)
    out["engine_speedup"] = round(seq_s / max(casc_s, 1e-9), 3)

    # ---- e2e rung: loopback daemon, dependent round trips
    from gubernator_tpu.config import BehaviorConfig, DaemonConfig
    from gubernator_tpu.proto import gubernator_pb2 as pb
    from gubernator_tpu.service.daemon import Daemon

    N_CHECKS, WORKERS = 384, 48

    def creq(i, t):
        r = pb.RateLimitReq(name="cph", unique_key=f"eu{i}", hits=1,
                            limit=1 << 20, duration=3_600_000, created_at=t)
        r.cascade.add(name="cph_t", unique_key=f"et{i % 16}", limit=1 << 20,
                      duration=3_600_000, algorithm=pb.SLIDING_WINDOW)
        r.cascade.add(name="cph_g", unique_key="all", limit=1 << 20,
                      duration=3_600_000, algorithm=pb.GCRA)
        return r

    def sreqs(i, t):
        return [
            pb.RateLimitReq(name="cph", unique_key=f"eu{i}", hits=1,
                            limit=1 << 20, duration=3_600_000, created_at=t),
            pb.RateLimitReq(name="cph_t", unique_key=f"et{i % 16}", hits=1,
                            limit=1 << 20, duration=3_600_000, created_at=t,
                            algorithm=pb.SLIDING_WINDOW),
            pb.RateLimitReq(name="cph_g", unique_key="all", hits=1,
                            limit=1 << 20, duration=3_600_000, created_at=t,
                            algorithm=pb.GCRA),
        ]

    async def run_e2e():
        d = await Daemon.spawn(DaemonConfig(
            grpc_address="127.0.0.1:0", http_address="",
            cache_size=1 << 18,
            behaviors=BehaviorConfig(batch_wait_ms=0.5),
        ))

        async def casc_worker(w, t):
            for i in range(w, N_CHECKS, WORKERS):
                await d.get_rate_limits_raw(pb.GetRateLimitsReq(
                    requests=[creq(i, t)]).SerializeToString())

        async def seq_worker(w, t):
            for i in range(w, N_CHECKS, WORKERS):
                for r in sreqs(i, t):
                    await d.get_rate_limits_raw(pb.GetRateLimitsReq(
                        requests=[r]).SerializeToString())

        async def drive(worker, t):
            t0 = time.perf_counter()
            await asyncio.gather(*(worker(w, t) for w in range(WORKERS)))
            return time.perf_counter() - t0

        await drive(casc_worker, now)
        await drive(seq_worker, now)
        c = min([await drive(casc_worker, now + 20 + k) for k in range(3)])
        s = min([await drive(seq_worker, now + 30 + k) for k in range(3)])
        await d.close()
        return c, s

    e2e_c, e2e_s = asyncio.run(run_e2e())
    out["e2e_cascade_checks_per_sec"] = round(N_CHECKS / e2e_c, 1)
    out["e2e_sequential_checks_per_sec"] = round(N_CHECKS / e2e_s, 1)
    out["e2e_speedup"] = round(e2e_s / max(e2e_c, 1e-9), 3)
    out["e2e_accept_2_5x"] = bool(e2e_s / max(e2e_c, 1e-9) >= 2.5)
    log(f"[cascade] engine {out['engine_speedup']}x, "
        f"e2e {out['e2e_speedup']}x (accept >= 2.5x: "
        f"{out['e2e_accept_2_5x']})")
    return out


def _attempt(label: str, fn, attempts: int = 2) -> dict:
    """Run one bench case, retrying ONCE on failure: the tunneled platform
    throws transient infra errors (observed: a remote_compile response cut
    mid-body killed a whole headline), and the driver records exactly one
    run — a one-shot transient must not zero the record. The thunk rebuilds
    its case from scratch, so a retry never reuses state poisoned by a
    failed donated computation."""
    err = ""
    for a in range(attempts):
        try:
            return fn()
        except Exception as exc:  # the record must print regardless
            # keep only the MESSAGE: holding the exception would pin its
            # traceback (and through it the failed case's device buffers)
            # alive across the retry — fatal when the retry needs the HBM
            # the first attempt was supposed to release
            err = f"{type(exc).__name__}: {exc}"
            log(f"[{label}] FAILED (attempt {a + 1}/{attempts}): {err}")
    return {"error": err[:200]}


def main() -> None:
    dev = jax.devices()[0]
    log(f"device: {dev}  write mode: {WRITE}")
    now = int(time.time() * 1000)

    # each case draws from its OWN deterministic generator: a retried case
    # (transient tunnel failure) must not shift the entropy every later
    # case sees, or the published matrix stops being comparable run-to-run
    parity_ok = _attempt(
        "parity", lambda: sweep_parity_smoke(np.random.default_rng(41), now)
    )

    headline = _attempt(
        "headline-10M",
        lambda: headline_case(np.random.default_rng(42), now).run(),
    )
    matrix = {"parity_sweep_vs_xla": parity_ok}
    # compact-wire vs full-width row-for-row parity (acceptance smoke for
    # the ISSUE 5 wire work; also runs under pytest on the CPU mesh)
    matrix["parity_wire_compact"] = _attempt(
        "wire-parity", lambda: wire_parity_smoke(np.random.default_rng(50), now)
    )
    matrix["e2e-serving"] = _attempt("e2e-serving", e2e_serving_case)

    def run_config(builder, name, seed):
        case = builder(np.random.default_rng(seed), now)
        assert case.name == name, (case.name, name)  # key-drift tripwire
        res = case.run(dispatches=24, latency_probes=12)
        if hasattr(case, "logical_batch") and "device_decisions_per_sec" in res:
            # throughput in *client decisions* (pre-aggregation) per second:
            # each dispatch's ~active unique keys answer logical_batch
            # client rows
            mean_active = case.expected_decisions(len(case.batches)) / len(
                case.batches
            )
            scale = case.logical_batch / mean_active
            res["client_decisions_per_sec"] = round(
                res["device_decisions_per_sec"] * scale, 1
            )
        return res

    configs = [
        (config1_case, "config1-token-1K", 43),
        (config2_case, "config2-leaky-1M-zipf", 44),
        (config4_case, "config4-mixed-flags-1M", 45),
    ]
    for builder, name, seed in configs:
        matrix[name] = _attempt(
            name, lambda b=builder, n=name, s=seed: run_config(b, n, s)
        )

    matrix["config3-global"] = _attempt(
        "config3-global",
        lambda: config3_global_case(np.random.default_rng(46), now),
    )

    # mesh-ingress phase: sharded vs local dispatch with the host-stage /
    # device split at 1M/10M live keys (docs/latency.md "mesh ingress")
    matrix["sharded-ingress"] = _attempt(
        "sharded-ingress",
        lambda: sharded_ingress_case(np.random.default_rng(49), now),
    )

    # pod-scaling phase: decisions/s vs device count for both exchange
    # schedules + the exchange-leg stage split (per-hop ring ms) — the
    # horizontal-scaling record (docs/architecture.md "Pod-scale topology")
    matrix["pod-scaling"] = _attempt(
        "pod-scaling",
        lambda: pod_scaling_case(np.random.default_rng(51), now),
    )

    # durability phase: incremental checkpoint vs full snapshot + warm
    # restart vs cold re-seed (docs/durability.md acceptance surface)
    matrix["durability"] = _attempt(
        "durability",
        lambda: durability_case(np.random.default_rng(52), now),
    )

    # scenario-breadth phases (ISSUE 10): per-algorithm device rates at
    # headline geometry (GCRA >= token acceptance) + the cascade
    # single-dispatch-vs-sequential ratio (docs/algorithms.md)
    matrix["algorithms"] = _attempt(
        "algorithms",
        lambda: algorithms_case(np.random.default_rng(53), now),
    )
    matrix["cascade"] = _attempt(
        "cascade",
        lambda: cascade_case(np.random.default_rng(54), now),
    )

    # packed slot-layout phase (PR 11): full vs gcra32 device rates at the
    # biggest geometry the backend affords + bytes/slot and keys/GB — the
    # ≥1.5×-decisions / 2×-capacity acceptance surface. Late for the same
    # HBM reason as config6.
    matrix["layout"] = _attempt(
        "layout",
        lambda: layout_case(np.random.default_rng(55), now),
    )

    # fused probe-megakernel phase (ISSUE 14): XLA gather+write vs the
    # Pallas probe→decide→write kernel, both layouts, 10M + 100M keys on
    # TPU (≥1.3× at 100M is the record-book acceptance bit) with the HBM
    # bytes/decision roofline attached — docs/kernel.md. Late for the
    # same HBM-claim reason as the layout phase.
    matrix["probe"] = _attempt(
        "probe",
        lambda: probe_case(np.random.default_rng(56), now),
    )

    # multi-region replication phase (ISSUE 12): codec bytes/row (merge
    # wire vs proto fallback) + the two-region loopback convergence wall
    # in sync intervals — the record the robustness doc's bound points at
    matrix["regions"] = _attempt(
        "regions",
        lambda: regions_case(np.random.default_rng(56), now),
    )

    # edge quota-lease phase (ISSUE 13): client-side admissions/s vs the
    # per-check RPC rate (the ≥50× fan-in cut) + the adaptive grant trace
    matrix["leases"] = _attempt(
        "leases",
        lambda: leases_case(np.random.default_rng(57), now),
    )

    # overload phase (ISSUE 19): the replayable scenario harness — diurnal
    # / 10× flash crowd / hot-key storm / abusive tenant / mixed ≥1M keys
    # through an armed loopback door, each step one point on the
    # goodput-vs-offered-load curve — docs/robustness.md "Overload & QoS"
    matrix["overload"] = _attempt("overload", overload_case)

    # hot-set tiering phase (ISSUE 15): tracked-keys-vs-capacity curve on
    # a shadow-armed engine + hot-set rate vs the no-tiering baseline
    # (the ≥0.9× acceptance bit on the TPU run) with HBM bytes/decision
    # attached — docs/tiering.md
    matrix["tiering"] = _attempt(
        "tiering",
        lambda: tiering_case(np.random.default_rng(58), now),
    )

    # dispatch-budget phase (ISSUE 17): serving dispatch wall per batch
    # size × {ring, direct} against the bare device term, plus the
    # fused-vs-two-pass install/merge walls at 1M live keys —
    # docs/latency.md "Dispatch budget"
    matrix["dispatch"] = _attempt(
        "dispatch",
        lambda: dispatch_case(np.random.default_rng(59), now),
    )

    # latency phase (sweep vs sparse vs xla device terms per table size);
    # runs late so its 100M case sees the HBM other cases released
    matrix["config6-latency"] = _attempt(
        "config6-latency",
        lambda: config6_latency_case(np.random.default_rng(48), now),
    )

    if jax.default_backend() == "tpu":
        # BASELINE #5 scale needs the real chip's HBM (8 GiB table); runs
        # last so every other case's memory is already released, and must
        # never sink the headline
        matrix["config5-100M"] = _attempt(
            "config5-100M",
            lambda: config5_case(np.random.default_rng(47), now).run(
                dispatches=24, latency_probes=6
            ),
        )

    # headline = on-device loop rate (chip compute, RTT-immune); the host
    # serving slope is never promoted to the headline — if the device loop
    # failed its guards the record says so instead of publishing weather
    dps = headline.get("device_decisions_per_sec")
    matrix["headline-10M"] = headline
    record = {
        "metric": "ratelimit_decisions_per_sec_per_chip",
        "value": dps if dps is not None else 0.0,
        "unit": "decisions/s",
        "vs_baseline": round((dps or 0.0) / PER_CHIP_BASELINE, 3),
        "matrix": matrix,
    }
    if dps is None:
        record["invalid"] = (
            headline.get("device_invalid")
            or headline.get("error")
            or "no headline rate"
        )
    print(json.dumps(record))


if __name__ == "__main__":
    main()
