#!/usr/bin/env python3
"""Generate a shared CA + server keypair for the TLS compose cluster / local
mTLS experiments (the reference ships a pre-generated corpus in
contrib/certs/; generating on demand keeps private keys out of git).

    python contrib/certs/gen_certs.py [outdir] [san ...]

Writes ca.pem, server.pem, server.key. Default SANs cover the compose node
hostnames and localhost.
"""

import sys

sys.path.insert(0, ".")  # repo root invocation

from gubernator_tpu.service.tls import generate_self_signed  # noqa: E402


def main() -> None:
    import os

    outdir = sys.argv[1] if len(sys.argv) > 1 else "contrib/certs"
    sans = sys.argv[2:] or [
        "node-1", "node-2", "node-3", "node-4", "localhost", "127.0.0.1",
    ]
    bundle = generate_self_signed(tuple(sans))
    os.makedirs(outdir, exist_ok=True)
    for name, data in (
        ("ca.pem", bundle.ca_pem),
        ("server.pem", bundle.cert_pem),
        ("server.key", bundle.key_pem),
    ):
        path = os.path.join(outdir, name)
        with open(path, "wb") as f:
            f.write(data)
        print(f"wrote {path}")


if __name__ == "__main__":
    main()
