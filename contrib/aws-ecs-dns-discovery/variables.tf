variable "prefix" {
  description = "Name prefix for every resource"
  type        = string
  default     = "guber-tpu"
}

variable "image" {
  description = "gubernator-tpu container image (build from the repo Dockerfile)"
  type        = string
}

variable "desired_count" {
  description = "Number of peer tasks"
  type        = number
  default     = 3
}

variable "task_cpu" {
  type    = number
  default = 1024
}

variable "task_memory" {
  type    = number
  default = 2048
}

variable "cache_size" {
  description = "GUBER_CACHE_SIZE per daemon"
  type        = number
  default     = 1048576
}

variable "extra_env" {
  description = "Additional GUBER_* env vars merged into the container"
  type        = map(string)
  default     = {}
}

variable "dns_namespace" {
  description = "Private Cloud Map namespace (VPC-internal DNS zone)"
  type        = string
  default     = "guber.internal"
}

variable "service_name" {
  description = "Discovery service name; peers poll <service>.<namespace>"
  type        = string
  default     = "peers"
}

variable "vpc_cidr" {
  type    = string
  default = "10.40.0.0/16"
}

variable "subnet_cidrs" {
  type    = list(string)
  default = ["10.40.1.0/24", "10.40.2.0/24"]
}

variable "availability_zones" {
  description = "AZs for the subnets (match your region)"
  type        = list(string)
  default     = ["us-east-1a", "us-east-1b"]
}
