# gubernator-tpu on AWS ECS Fargate with Cloud Map DNS peer discovery.
#
# Peers find each other the same way the k8s/compose deployments do: AWS
# Cloud Map registers every task's IP under one service name, and each
# daemon polls that FQDN with GUBER_PEER_DISCOVERY_TYPE=dns (multi-A
# records -> full peer list; the pool keeps the last non-empty answer on
# transient DNS failures). Mirrors the reference's ECS service-discovery
# example (contrib/aws-ecs-service-discovery-deployment) with this
# framework's env surface.

data "aws_region" "current" {}

# ------------------------------------------------------------------ network
resource "aws_vpc" "this" {
  cidr_block           = var.vpc_cidr
  enable_dns_support   = true
  enable_dns_hostnames = true
  tags                 = { Name = "${var.prefix}-vpc" }
}

# Public subnets + IGW so Fargate can pull the image from ECR and ship logs
# (the reference example does the same; for a fully private deployment swap
# in ECR/S3/logs VPC endpoints and drop assign_public_ip)
resource "aws_subnet" "public" {
  count                   = length(var.subnet_cidrs)
  vpc_id                  = aws_vpc.this.id
  cidr_block              = var.subnet_cidrs[count.index]
  availability_zone       = var.availability_zones[count.index]
  map_public_ip_on_launch = true
  tags                    = { Name = "${var.prefix}-public-${count.index}" }
}

resource "aws_internet_gateway" "this" {
  vpc_id = aws_vpc.this.id
}

resource "aws_route_table" "public" {
  vpc_id = aws_vpc.this.id
  route {
    cidr_block = "0.0.0.0/0"
    gateway_id = aws_internet_gateway.this.id
  }
}

resource "aws_route_table_association" "public" {
  count          = length(var.subnet_cidrs)
  subnet_id      = aws_subnet.public[count.index].id
  route_table_id = aws_route_table.public.id
}

resource "aws_security_group" "peers" {
  name   = "${var.prefix}-peers"
  vpc_id = aws_vpc.this.id

  # peer gRPC + HTTP/metrics, cluster-internal only
  ingress {
    from_port   = 1050
    to_port     = 1051
    protocol    = "tcp"
    cidr_blocks = [var.vpc_cidr]
  }
  egress {
    from_port   = 0
    to_port     = 0
    protocol    = "-1"
    cidr_blocks = ["0.0.0.0/0"]
  }
}

# ------------------------------------------------- Cloud Map DNS namespace
resource "aws_service_discovery_private_dns_namespace" "this" {
  name = var.dns_namespace
  vpc  = aws_vpc.this.id
}

resource "aws_service_discovery_service" "peers" {
  name = var.service_name
  dns_config {
    namespace_id = aws_service_discovery_private_dns_namespace.this.id
    dns_records {
      ttl  = 10 # short TTL: the DNS pool re-polls at min-TTL cadence
      type = "A"
    }
    routing_policy = "MULTIVALUE"
  }
  health_check_custom_config {
    failure_threshold = 1
  }
}

# ---------------------------------------------------------------- ECS bits
resource "aws_ecs_cluster" "this" {
  name = "${var.prefix}-cluster"
}

resource "aws_cloudwatch_log_group" "this" {
  name              = "/ecs/${var.prefix}"
  retention_in_days = 7
}

resource "aws_iam_role" "execution" {
  name               = "${var.prefix}-execution"
  assume_role_policy = data.aws_iam_policy_document.ecs_assume.json
}

data "aws_iam_policy_document" "ecs_assume" {
  statement {
    actions = ["sts:AssumeRole"]
    principals {
      type        = "Service"
      identifiers = ["ecs-tasks.amazonaws.com"]
    }
  }
}

resource "aws_iam_role_policy_attachment" "execution" {
  role       = aws_iam_role.execution.name
  policy_arn = "arn:aws:iam::aws:policy/service-role/AmazonECSTaskExecutionRolePolicy"
}

locals {
  peers_fqdn = "${var.service_name}.${var.dns_namespace}"
  guber_env = merge({
    # listeners bind all interfaces; peers dial the task IP that Cloud Map
    # publishes (ECS injects it as the task's private address)
    GUBER_GRPC_ADDRESS        = "0.0.0.0:1051"
    GUBER_HTTP_ADDRESS        = "0.0.0.0:1050"
    GUBER_PEER_DISCOVERY_TYPE = "dns"
    GUBER_DNS_FQDN            = local.peers_fqdn
    GUBER_DNS_POLL            = "5s"
    GUBER_CACHE_SIZE          = tostring(var.cache_size)
  }, var.extra_env)
}

resource "aws_ecs_task_definition" "this" {
  family                   = "${var.prefix}-task"
  requires_compatibilities = ["FARGATE"]
  network_mode             = "awsvpc"
  cpu                      = var.task_cpu
  memory                   = var.task_memory
  execution_role_arn       = aws_iam_role.execution.arn

  container_definitions = jsonencode([
    {
      name      = "gubernator-tpu"
      image     = var.image
      essential = true
      # awsvpc mode: the container's interface IP IS the task IP that Cloud
      # Map publishes — resolve it at startup and advertise it, or no
      # daemon ever matches itself in the peer list and every health check
      # reports "this instance is not in the peer list"
      entryPoint = ["/bin/sh", "-c"]
      command = [
        "export GUBER_ADVERTISE_ADDRESS=$(hostname -i | cut -d' ' -f1):1051 && exec python -m gubernator_tpu"
      ]
      portMappings = [
        { containerPort = 1050, protocol = "tcp" },
        { containerPort = 1051, protocol = "tcp" },
      ]
      environment = [
        for k, v in local.guber_env : { name = k, value = v }
      ]
      healthCheck = {
        # the k8s probe binary doubles as the ECS health check
        command  = ["CMD-SHELL", "python -m gubernator_tpu.cmd.healthcheck || exit 1"]
        interval = 15
        timeout  = 5
        retries  = 3
      }
      logConfiguration = {
        logDriver = "awslogs"
        options = {
          awslogs-group         = aws_cloudwatch_log_group.this.name
          awslogs-region        = data.aws_region.current.name
          awslogs-stream-prefix = "gubernator-tpu"
        }
      }
    }
  ])
}

resource "aws_ecs_service" "this" {
  name            = "${var.prefix}-service"
  cluster         = aws_ecs_cluster.this.id
  task_definition = aws_ecs_task_definition.this.arn
  desired_count   = var.desired_count
  launch_type     = "FARGATE"

  network_configuration {
    subnets          = aws_subnet.public[*].id
    security_groups  = [aws_security_group.peers.id]
    assign_public_ip = true # required for ECR pull/log delivery without NAT
  }

  service_registries {
    registry_arn = aws_service_discovery_service.peers.arn
  }

  deployment_circuit_breaker {
    enable   = true
    rollback = true
  }
}

output "peers_fqdn" {
  description = "FQDN every daemon polls for the peer list (GUBER_DNS_FQDN)"
  value       = local.peers_fqdn
}
