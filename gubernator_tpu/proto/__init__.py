"""Generated protobuf message modules (wire-compatible with the reference
pb.gubernator package). Regenerate with:

    cd gubernator_tpu/proto && protoc --python_out=. -I. gubernator.proto peers.proto
"""

import os
import sys

# protoc-generated modules use absolute imports (peers_pb2 imports
# gubernator_pb2); make them resolvable from inside the package.
_here = os.path.dirname(__file__)
if _here not in sys.path:
    sys.path.insert(0, _here)

import gubernator_pb2  # noqa: E402
import handoff_pb2  # noqa: E402
import peers_pb2  # noqa: E402

__all__ = ["gubernator_pb2", "handoff_pb2", "peers_pb2"]
