# -*- coding: utf-8 -*-
"""TransferState messages for the ownership-handoff peer RPC.

Unlike gubernator_pb2/peers_pb2 (protoc output vendored from the reference's
schema), these messages have no reference counterpart — the handoff protocol
is this repo's own (docs/robustness.md "Topology change & drain") — so the
FileDescriptorProto is built programmatically instead of vendoring protoc
bytes; the result is a normal proto3 wire-compatible message set.

Schema (proto3, package pb.gubernator):

    message TransferStateReq {
      string transfer_id    = 1;  // idempotency scope (one per handoff round)
      uint32 chunk          = 2;  // chunk index within the transfer
      uint32 total_chunks   = 3;
      string source_address = 4;  // advertise address of the handing-off peer
      int64  now_ms         = 5;  // source clock at extract (diagnostic only;
                                  // the receiver merges on its own clock)
      uint32 count          = 6;  // rows in this chunk
      bytes  fps            = 7;  // count × int64 LE fingerprints
      bytes  points         = 8;  // count × uint32 LE ring points
      bytes  slots          = 9;  // count × F × int32 LE slot fields, in the
                                  // sender's slot layout (ops/layout.py)
      uint32 layout         = 10; // sender's slot-layout code (0 = full —
                                  // the proto3 default, so pre-layout
                                  // senders decode as full automatically)
    }
    message TransferStateResp {
      uint32 merged   = 1;  // rows merged/installed by the receiver
      bool  duplicate = 2;  // chunk had already been applied (idempotent replay)
    }

Rows travel as packed little-endian arrays, not repeated messages: a chunk is
a straight memory image of the extract (table2.extract_live_rows), so a 4096-
row chunk costs three buffer copies instead of 4096 message objects each way.
"""

from google.protobuf import descriptor_pb2 as _dpb
from google.protobuf import descriptor_pool as _descriptor_pool
from google.protobuf import message_factory as _message_factory

_FD = _dpb.FieldDescriptorProto

_fdp = _dpb.FileDescriptorProto()
_fdp.name = "handoff.proto"
_fdp.package = "pb.gubernator"
_fdp.syntax = "proto3"
_fdp.options.go_package = "github.com/gubernator-io/gubernator"

_req = _fdp.message_type.add()
_req.name = "TransferStateReq"
for _name, _num, _type in (
    ("transfer_id", 1, _FD.TYPE_STRING),
    ("chunk", 2, _FD.TYPE_UINT32),
    ("total_chunks", 3, _FD.TYPE_UINT32),
    ("source_address", 4, _FD.TYPE_STRING),
    ("now_ms", 5, _FD.TYPE_INT64),
    ("count", 6, _FD.TYPE_UINT32),
    ("fps", 7, _FD.TYPE_BYTES),
    ("points", 8, _FD.TYPE_BYTES),
    ("slots", 9, _FD.TYPE_BYTES),
    ("layout", 10, _FD.TYPE_UINT32),
):
    _f = _req.field.add()
    _f.name, _f.number, _f.type = _name, _num, _type
    _f.label = _FD.LABEL_OPTIONAL

_resp = _fdp.message_type.add()
_resp.name = "TransferStateResp"
for _name, _num, _type in (
    ("merged", 1, _FD.TYPE_UINT32),
    ("duplicate", 2, _FD.TYPE_BOOL),
):
    _f = _resp.field.add()
    _f.name, _f.number, _f.type = _name, _num, _type
    _f.label = _FD.LABEL_OPTIONAL

_pool = _descriptor_pool.Default()
try:
    _fd = _pool.Add(_fdp)
except Exception:  # already registered (module re-import under both names)
    _fd = _pool.FindFileByName("handoff.proto")

if hasattr(_message_factory, "GetMessageClass"):
    TransferStateReq = _message_factory.GetMessageClass(
        _fd.message_types_by_name["TransferStateReq"]
    )
    TransferStateResp = _message_factory.GetMessageClass(
        _fd.message_types_by_name["TransferStateResp"]
    )
else:  # protobuf < 4.21
    _factory = _message_factory.MessageFactory(_pool)
    TransferStateReq = _factory.GetPrototype(
        _fd.message_types_by_name["TransferStateReq"]
    )
    TransferStateResp = _factory.GetPrototype(
        _fd.message_types_by_name["TransferStateResp"]
    )

__all__ = ["TransferStateReq", "TransferStateResp"]
