# -*- coding: utf-8 -*-
"""SyncGlobalsWire messages for the inter-slice GLOBAL hit sync.

Like handoff_pb2, these messages have no reference counterpart — the
compact inter-slice sync is this repo's own (docs/architecture.md
"Pod-scale topology") — so the FileDescriptorProto is built
programmatically; the result is a normal proto3 wire-compatible message.

Schema (proto3, package pb.gubernator):

    message SyncGlobalsWireReq {
      string source    = 1;  // sender's advertise address (diagnostics)
      uint32 count     = 2;  // entries in this batch
      int64  base      = 3;  // created_at base of the lane encoding
      bytes  lanes     = 4;  // 5 × count int32 LE — ops/wire.pack_wire_rows
                             // image (fp/limit/duration|algo/flag lanes; the
                             // 18-bit lane hits field is IGNORED on receive)
      bytes  hits      = 5;  // count × int64 LE full-precision accumulated
                             // hits (inter-slice accumulations overflow the
                             // 18-bit lane budget under hot keys)
      bytes  name_lens = 6;  // count × uint16 LE rate-limit name lengths
      bytes  key_lens  = 7;  // count × uint16 LE unique_key lengths
      bytes  strings   = 8;  // concatenated utf8 name_i ‖ unique_key_i
    }
    message SyncGlobalsWireResp {
      uint32 applied = 1;  // entries the owner applied
    }

Numeric config rides the PR-5 compact lane codec (20 B/entry instead of a
nested RateLimitReq message each); the key strings — which the owner needs
to queue its authoritative broadcasts — travel as one length-prefixed blob
instead of per-message string fields. Non-encodable batches (Gregorian
durations, exotic behaviors, oversized fields) fall back to the classic
GetPeerRateLimits proto path with identical semantics.
"""

from google.protobuf import descriptor_pb2 as _dpb
from google.protobuf import descriptor_pool as _descriptor_pool
from google.protobuf import message_factory as _message_factory

_FD = _dpb.FieldDescriptorProto

_fdp = _dpb.FileDescriptorProto()
_fdp.name = "globalsync.proto"
_fdp.package = "pb.gubernator"
_fdp.syntax = "proto3"
_fdp.options.go_package = "github.com/gubernator-io/gubernator"

_req = _fdp.message_type.add()
_req.name = "SyncGlobalsWireReq"
for _name, _num, _type in (
    ("source", 1, _FD.TYPE_STRING),
    ("count", 2, _FD.TYPE_UINT32),
    ("base", 3, _FD.TYPE_INT64),
    ("lanes", 4, _FD.TYPE_BYTES),
    ("hits", 5, _FD.TYPE_BYTES),
    ("name_lens", 6, _FD.TYPE_BYTES),
    ("key_lens", 7, _FD.TYPE_BYTES),
    ("strings", 8, _FD.TYPE_BYTES),
):
    _f = _req.field.add()
    _f.name, _f.number, _f.type = _name, _num, _type
    _f.label = _FD.LABEL_OPTIONAL

_resp = _fdp.message_type.add()
_resp.name = "SyncGlobalsWireResp"
_f = _resp.field.add()
_f.name, _f.number, _f.type = "applied", 1, _FD.TYPE_UINT32
_f.label = _FD.LABEL_OPTIONAL

_pool = _descriptor_pool.Default()
try:
    _fd = _pool.Add(_fdp)
except Exception:  # already registered (module re-import under both names)
    _fd = _pool.FindFileByName("globalsync.proto")

if hasattr(_message_factory, "GetMessageClass"):
    SyncGlobalsWireReq = _message_factory.GetMessageClass(
        _fd.message_types_by_name["SyncGlobalsWireReq"]
    )
    SyncGlobalsWireResp = _message_factory.GetMessageClass(
        _fd.message_types_by_name["SyncGlobalsWireResp"]
    )
else:  # protobuf < 4.21
    _factory = _message_factory.MessageFactory(_pool)
    SyncGlobalsWireReq = _factory.GetPrototype(
        _fd.message_types_by_name["SyncGlobalsWireReq"]
    )
    SyncGlobalsWireResp = _factory.GetPrototype(
        _fd.message_types_by_name["SyncGlobalsWireResp"]
    )

__all__ = ["SyncGlobalsWireReq", "SyncGlobalsWireResp"]
