# -*- coding: utf-8 -*-
"""SyncRegionsWire messages for the cross-region replication plane.

Like globalsync_pb2, these messages have no reference counterpart — the
reference's MULTI_REGION push loop was never implemented (its README marks
the behavior "not fully implemented") — so the FileDescriptorProto is built
programmatically; the result is a normal proto3 wire-compatible message.

Schema (proto3, package pb.gubernator):

    message SyncRegionsWireReq {
      string source    = 1;   // sender's advertise address (diagnostics)
      string region    = 2;   // sender's data_center label
      uint32 count     = 3;   // entries in this batch
      int64  base      = 4;   // created_at base of the lane encoding
      bytes  lanes     = 5;   // 5 × count int32 LE — ops/wire lane image
                              // (fp/limit/duration|algo/flag lanes; the
                              // 18-bit lane hits field is IGNORED)
      bytes  hits      = 6;   // count × int64 LE per-key HIT DELTAS since
                              // the sender's last successful sync
      bytes  name_lens = 7;   // count × uint16 LE rate-limit name lengths
      bytes  key_lens  = 8;   // count × uint16 LE unique_key lengths
      bytes  strings   = 9;   // concatenated utf8 name_i ‖ unique_key_i
      bytes  slots     = 10;  // count × layout.F int32 LE — the sender's
                              // own stored slot rows in ITS slot layout
                              // (zero row = slot evicted sender-side;
                              // empty buffer = sender shipped no rows)
      uint32 layout    = 11;  // ops/layout code of `slots` (0 = full)
      bytes  cums      = 12;  // count × int64 LE per-key CUMULATIVE hit
                              // counters (total hits the sender has ever
                              // queued toward this region for the key) —
                              // the receiver-side dedup ledger skips
                              // re-shipped batches after a lost ack
                              // EXACTLY (ops/reconcile.dedup_source_
                              // deltas); empty buffer = sender predates
                              // the dedup plane (receiver applies deltas
                              // verbatim — the legacy under-grant rule)
    }
    message SyncRegionsWireResp {
      uint32 applied = 1;  // rows the receiver merged
    }

The receiver reconciles through kernel2.merge2 (ops/reconcile.py), never
the serving path; non-encodable items and pre-upgrade peers ride the
classic GetPeerRateLimits proto fallback with the legacy DRAIN semantics
(docs/robustness.md "Multi-region active-active").
"""

from google.protobuf import descriptor_pb2 as _dpb
from google.protobuf import descriptor_pool as _descriptor_pool
from google.protobuf import message_factory as _message_factory

_FD = _dpb.FieldDescriptorProto

_fdp = _dpb.FileDescriptorProto()
_fdp.name = "regionsync.proto"
_fdp.package = "pb.gubernator"
_fdp.syntax = "proto3"
_fdp.options.go_package = "github.com/gubernator-io/gubernator"

_req = _fdp.message_type.add()
_req.name = "SyncRegionsWireReq"
for _name, _num, _type in (
    ("source", 1, _FD.TYPE_STRING),
    ("region", 2, _FD.TYPE_STRING),
    ("count", 3, _FD.TYPE_UINT32),
    ("base", 4, _FD.TYPE_INT64),
    ("lanes", 5, _FD.TYPE_BYTES),
    ("hits", 6, _FD.TYPE_BYTES),
    ("name_lens", 7, _FD.TYPE_BYTES),
    ("key_lens", 8, _FD.TYPE_BYTES),
    ("strings", 9, _FD.TYPE_BYTES),
    ("slots", 10, _FD.TYPE_BYTES),
    ("layout", 11, _FD.TYPE_UINT32),
    ("cums", 12, _FD.TYPE_BYTES),
):
    _f = _req.field.add()
    _f.name, _f.number, _f.type = _name, _num, _type
    _f.label = _FD.LABEL_OPTIONAL

_resp = _fdp.message_type.add()
_resp.name = "SyncRegionsWireResp"
_f = _resp.field.add()
_f.name, _f.number, _f.type = "applied", 1, _FD.TYPE_UINT32
_f.label = _FD.LABEL_OPTIONAL

_pool = _descriptor_pool.Default()
try:
    _fd = _pool.Add(_fdp)
except Exception:  # already registered (module re-import under both names)
    _fd = _pool.FindFileByName("regionsync.proto")

if hasattr(_message_factory, "GetMessageClass"):
    SyncRegionsWireReq = _message_factory.GetMessageClass(
        _fd.message_types_by_name["SyncRegionsWireReq"]
    )
    SyncRegionsWireResp = _message_factory.GetMessageClass(
        _fd.message_types_by_name["SyncRegionsWireResp"]
    )
else:  # protobuf < 4.21
    _factory = _message_factory.MessageFactory(_pool)
    SyncRegionsWireReq = _factory.GetPrototype(
        _fd.message_types_by_name["SyncRegionsWireReq"]
    )
    SyncRegionsWireResp = _factory.GetPrototype(
        _fd.message_types_by_name["SyncRegionsWireResp"]
    )

__all__ = ["SyncRegionsWireReq", "SyncRegionsWireResp"]
