"""Incremental checkpointing — the daemon's durability plane.

The seed persists nothing by default: the Loader hooks snapshot the whole
table at graceful shutdown only (reference store.go:49-78), so a `kill -9`
loses every counter since the last clean stop and a 100M-key cold restart
re-seeds for minutes. This manager bounds both:

* a background loop (GUBER_CHECKPOINT_INTERVAL_MS) takes the engine's dirty
  epoch (ops/checkpoint.EpochTracker — blocks touched since the last take),
  extracts just those blocks' live rows ON DEVICE (engine.checkpoint_begin
  on the engine thread, fetch off it — the PR-7 telemetry overlap split, so
  checkpointing overlaps serving), and appends one CRC-framed delta to the
  log beside the base snapshot (store.DeltaLog). Checkpoint cost is
  proportional to the write rate, never table size.
* every GUBER_CHECKPOINT_COMPACT_FRAMES frames the log compacts: one full
  snapshot becomes the new base (atomic rename FIRST), then the log resets
  — a crash between the two steps leaves stale deltas atop a newer base,
  which the epoch filter skips and the conservative merge renders harmless
  anyway.
* warm restart replays base + clean frame prefix through the engine's
  conservative merge (kernel2.merge2: remaining=min, expiry=max, OVER
  sticks) — a stale, duplicated, or torn checkpoint can only UNDER-grant.
  Recovery after an unclean death is bounded by the cadence: at most one
  interval of admitted writes is forgotten (re-granted), proven by the
  chaos test in tests/test_durability.py.

Failure discipline: a failed delta append re-arms the taken dirty set
(EpochTracker.remark) so a full disk defers dirt instead of dropping it; a
failed restore logs and cold-starts instead of dying at boot; a failed
shutdown snapshot is logged and counted, never allowed to wedge close().
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import Optional

import numpy as np

log = logging.getLogger("gubernator_tpu.checkpoint")


class CheckpointManager:
    """One daemon's incremental-checkpoint plane. Inert (enabled=False)
    unless GUBER_CHECKPOINT_INTERVAL_MS > 0 and a checkpoint path is
    configured — the classic restore-on-boot / snapshot-on-close Loader
    behavior is untouched then."""

    def __init__(self, daemon):
        self.daemon = daemon
        conf = daemon.conf
        self.interval_s = conf.checkpoint_interval_ms / 1e3
        self.compact_frames = int(conf.checkpoint_compact_frames)
        self.base_path = conf.checkpoint_path
        self.delta_path = conf.checkpoint_delta_path or (
            self.base_path + ".delta" if self.base_path else ""
        )
        self.enabled = self.interval_s > 0 and bool(self.base_path)
        self._log = None
        if self.enabled:
            from gubernator_tpu.store import DeltaLog

            self._log = DeltaLog(self.delta_path)
        # epoch the on-disk base snapshot includes (frames ≤ this are
        # already compacted and skipped on replay)
        self.base_epoch = 0
        self.frames_since_compaction = 0
        self.last_epoch = 0  # last epoch durably persisted (frame or base)
        self.last_epoch_ts: Optional[float] = None  # wall time of ^
        self.last_error: Optional[str] = None
        self.replayed_frames = 0
        self.replayed_rows = 0
        self.restored = "none"  # none | cold | base | base+delta
        self._lock = asyncio.Lock()  # one checkpoint/compaction at a time

    # ---------------------------------------------------------------- boot
    def restore(self) -> None:
        """Warm restart: base snapshot + delta-frame replay, validated —
        any damage (missing/corrupt/geometry-mismatched base, torn log)
        degrades to a logged cold start, never a boot failure. Runs BEFORE
        the tracker attaches, so replay marks nothing dirty (the restored
        state already equals what is on disk)."""
        daemon = self.daemon
        engine = daemon.engine
        self.restored = "cold"
        rows = None
        base_layout = None
        if os.path.exists(self.base_path):
            from gubernator_tpu.store import load_snapshot_meta

            try:
                rows, self.base_epoch, layout_name = load_snapshot_meta(
                    self.base_path
                )
                from gubernator_tpu.ops.layout import LAYOUTS

                base_layout = LAYOUTS[layout_name]
            except Exception as exc:
                log.warning(
                    "base snapshot %s unreadable (%s); cold start",
                    self.base_path, exc,
                )
                daemon.metrics.checkpoint_errors.labels(stage="restore").inc()
                rows = None
        if rows is not None:
            try:
                # cross-layout restores (snapshot written under a different
                # GUBER_SLOT_LAYOUT) convert through the canonical full row
                # inside engine.restore
                engine.restore(np.asarray(rows), layout=base_layout)
                self.restored = "base"
            except Exception as exc:
                # geometry/schema mismatch (cache_size changed across
                # restart, corrupted array): serve cold rather than die
                log.warning(
                    "base snapshot %s does not fit the configured table "
                    "(%s); cold start", self.base_path, exc,
                )
                daemon.metrics.checkpoint_errors.labels(stage="restore").inc()
                self.base_epoch = 0
        self.last_epoch = self.base_epoch
        scan = self._log.scan()
        if scan.error:
            log.warning(
                "delta log %s: %s — replaying the clean %d-frame prefix, "
                "skipping %d bytes",
                self.delta_path, scan.error, len(scan.frames),
                scan.skipped_bytes,
            )
            daemon.metrics.checkpoint_errors.labels(stage="restore").inc()
            # repair BEFORE serving: appends land at the physical end of
            # the file but replay stops at the first bad frame, so new
            # frames written after a torn tail would be unreachable until
            # the next compaction — a second unclean death before then
            # would lose them, breaking the one-interval recovery bound
            try:
                self._log.repair(scan)
                log.info(
                    "delta log %s truncated to its %d-byte clean prefix",
                    self.delta_path, scan.clean_bytes,
                )
            except Exception as exc:
                self.last_error = f"delta-log repair: {exc}"
                daemon.metrics.checkpoint_errors.labels(
                    stage="restore"
                ).inc()
                log.warning(
                    "delta log repair failed (%s); frames appended before "
                    "the next compaction may not survive another unclean "
                    "death", exc,
                )
        from gubernator_tpu.store import TOMBSTONE, fps_from_slots

        t0 = time.perf_counter()
        for epoch, _now_ms, slots, frame_layout in scan.frames:
            if epoch <= self.base_epoch:
                continue  # already compacted into the base
            if slots.shape[0] == 0:
                self.last_epoch = max(self.last_epoch, epoch)
                continue
            if frame_layout is TOMBSTONE:
                # demote-on-idle removal record (hot-set tiering): applied
                # in file order so a row demoted AFTER its last state
                # frame does not resurrect — it faults back from the
                # shadow spill instead (docs/tiering.md)
                try:
                    engine.tombstone_fps(fps_from_slots(slots))
                except Exception as exc:
                    log.warning(
                        "tombstone frame (epoch %d) replay failed (%s)",
                        epoch, exc,
                    )
                    daemon.metrics.checkpoint_errors.labels(
                        stage="restore"
                    ).inc()
                    break
                self.last_epoch = max(self.last_epoch, epoch)
                continue
            try:
                # frames written under another layout (restart with a
                # different GUBER_SLOT_LAYOUT) convert through the
                # canonical full row inside merge_rows — replay stays
                # conservative whatever the layouts
                engine.merge_rows(
                    fps_from_slots(slots), slots, layout=frame_layout
                )
            except Exception as exc:
                log.warning(
                    "delta frame (epoch %d) replay failed (%s); stopping "
                    "replay at the last clean frame", epoch, exc,
                )
                daemon.metrics.checkpoint_errors.labels(stage="restore").inc()
                break
            self.replayed_frames += 1
            self.replayed_rows += slots.shape[0]
            self.last_epoch = max(self.last_epoch, epoch)
        if self.restored == "base" and self.replayed_frames:
            self.restored = "base+delta"
        elif self.restored == "cold" and self.replayed_frames:
            self.restored = "delta"  # frames landed before the first base
        if self.restored != "cold":
            log.info(
                "warm restart: %s — base epoch %d + %d delta frames "
                "(%d rows) in %.1f ms",
                self.restored, self.base_epoch, self.replayed_frames,
                self.replayed_rows, (time.perf_counter() - t0) * 1e3,
            )
        self.last_epoch_ts = time.monotonic()

    def attach(self) -> None:
        """Create the engine's epoch tracker (clean — everything restored
        is already durable) and continue the epoch lineage past every
        frame on disk. Must run before the listeners start serving."""
        from gubernator_tpu.ops.checkpoint import EpochTracker

        engine = self.daemon.engine
        engine.ckpt = EpochTracker(
            int(engine.table.rows.shape[-2]),
            n_shards=getattr(engine, "n_shards", 1),
            start_epoch=self.last_epoch,
        )

    # ---------------------------------------------------------------- loop
    async def loop(self) -> None:
        while not self.daemon._shutting_down:
            await asyncio.sleep(self.interval_s)
            try:
                await self.checkpoint_once()
            except asyncio.CancelledError:
                raise
            except Exception:  # pragma: no cover - defensive
                log.exception("checkpoint tick failed")

    async def checkpoint_once(self) -> dict:
        """One delta epoch: take the dirty set + launch the extract
        atomically on the engine thread, fetch off it, append the frame off
        the event loop. A failed append re-arms the dirty set."""
        daemon = self.daemon
        async with self._lock:
            t0 = time.perf_counter()
            epoch, gids, fps, slots = await daemon.runner.checkpoint_extract()
            out = dict(
                epoch=epoch, dirty_blocks=int(gids.shape[0]),
                rows=int(fps.shape[0]), bytes=0,
            )
            if gids.shape[0] == 0:
                # nothing dirtied: the previous epoch is still fresh
                self.last_epoch = epoch
                self.last_epoch_ts = time.monotonic()
                self._observe_age()
                return out
            loop = asyncio.get_running_loop()
            now_ms = daemon.now_ms()
            lay = daemon.engine.table.layout
            try:
                nbytes = await loop.run_in_executor(
                    None, lambda: self._log.append(
                        epoch, now_ms, slots, layout=lay
                    )
                )
            except Exception as exc:
                # disk full / unwritable path: defer the dirt to the next
                # epoch instead of dropping it, count + surface the error
                daemon.engine.ckpt.remark(gids)
                self.last_error = f"delta append: {exc}"
                daemon.metrics.checkpoint_errors.labels(stage="delta").inc()
                log.warning("delta frame append failed: %s", exc)
                return {**out, "error": str(exc)}
            dt = time.perf_counter() - t0
            self.frames_since_compaction += 1
            self.last_epoch = epoch
            self.last_epoch_ts = time.monotonic()
            self.last_error = None
            m = daemon.metrics
            m.checkpoint_duration.labels(kind="delta").observe(dt)
            m.checkpoint_bytes.labels(kind="delta").inc(nbytes)
            m.checkpoint_rows.labels(kind="delta").inc(int(fps.shape[0]))
            self._observe_age()
            out["bytes"] = nbytes
        if self.frames_since_compaction >= self.compact_frames:
            await self.compact()
        return out

    async def append_tombstones(self, fps) -> int:
        """Record demote-on-idle removals in the delta log (hot-set
        tiering): one tombstone frame stamped with the UPCOMING epoch
        (tracker.epoch + 1 — always past the base even right after a
        compaction; the log reset at compaction discards it once the base
        itself no longer holds the rows). Failure is non-fatal: the row
        merely resurrects on a warm restart, which the fault-back merge
        renders harmless (docs/tiering.md)."""
        if not self.enabled or fps.shape[0] == 0:
            return 0
        daemon = self.daemon
        tracker = getattr(daemon.engine, "ckpt", None)
        epoch = (tracker.epoch + 1) if tracker is not None else (
            self.last_epoch + 1
        )
        now_ms = daemon.now_ms()
        loop = asyncio.get_running_loop()
        async with self._lock:
            try:
                return await loop.run_in_executor(
                    None,
                    lambda: self._log.append_tombstones(epoch, now_ms, fps),
                )
            except Exception as exc:
                self.last_error = f"tombstone append: {exc}"
                daemon.metrics.checkpoint_errors.labels(stage="delta").inc()
                log.warning("tombstone frame append failed: %s", exc)
                return 0

    async def compact(self) -> None:
        """Fold the delta log into a fresh base: full snapshot (engine
        thread for coherence, disk write off-loop, atomic rename), THEN
        log reset. Dirty bits marked since the snapshot stay armed — the
        next delta may duplicate a little state, which replay's
        conservative merge absorbs."""
        daemon = self.daemon
        async with self._lock:
            t0 = time.perf_counter()
            rows, epoch, lay = await daemon.runner.checkpoint_snapshot()
            loop = asyncio.get_running_loop()
            from gubernator_tpu.ops.table2 import live_count2, Table2
            from gubernator_tpu.store import save_snapshot

            now_ms = daemon.now_ms()

            def write_base():
                # everything that touches disk stays off the event loop:
                # snapshot write + rename, log reset, size stat
                save_snapshot(self.base_path, rows, epoch,
                              layout_name=lay.name)
                self._log.reset()
                # the rows are already host-side; the live count is one
                # vectorized pass over memory the save just touched
                return (
                    live_count2(Table2(rows=rows, layout=lay), now_ms),
                    os.path.getsize(self.base_path),
                )

            try:
                base_rows, base_bytes = await loop.run_in_executor(
                    None, write_base
                )
            except Exception as exc:
                self.last_error = f"compaction: {exc}"
                daemon.metrics.checkpoint_errors.labels(stage="base").inc()
                log.warning("delta-log compaction failed: %s", exc)
                return
            dt = time.perf_counter() - t0
            self.base_epoch = epoch
            self.frames_since_compaction = 0
            self.last_epoch = max(self.last_epoch, epoch)
            self.last_epoch_ts = time.monotonic()
            self.last_error = None
            m = daemon.metrics
            m.checkpoint_duration.labels(kind="base").observe(dt)
            m.checkpoint_bytes.labels(kind="base").inc(base_bytes)
            m.checkpoint_rows.labels(kind="base").inc(base_rows)
            self._observe_age()
            log.info(
                "delta log compacted into base (epoch %d) in %.1f ms",
                epoch, dt * 1e3,
            )

    async def final_checkpoint(self) -> None:
        """Shutdown flush: one last compaction so the base alone carries
        the final state (the incremental plane's maybe_checkpoint analog).
        Caller guards exceptions — shutdown must always complete."""
        await self.compact()

    def _observe_age(self) -> None:
        self.daemon.metrics.checkpoint_epoch_age.set(self.epoch_age_s())

    def epoch_age_s(self) -> float:
        """Seconds since the last durable epoch — the live bound on what a
        kill -9 would lose right now."""
        if self.last_epoch_ts is None:
            return 0.0
        return max(0.0, time.monotonic() - self.last_epoch_ts)

    # --------------------------------------------------------------- status
    def status(self) -> dict:
        """/v1/debug/durability snapshot."""
        tracker = getattr(self.daemon.engine, "ckpt", None)
        out = {
            "enabled": self.enabled,
            "interval_ms": self.interval_s * 1e3,
            "base_path": self.base_path,
            "delta_path": self.delta_path,
            "restored": self.restored,
            "base_epoch": self.base_epoch,
            "last_epoch": self.last_epoch,
            "epoch_age_s": round(self.epoch_age_s(), 3),
            "frames_since_compaction": self.frames_since_compaction,
            "compact_frames": self.compact_frames,
            "delta_log_bytes": self._log.size_bytes() if self._log else 0,
            "replayed_frames": self.replayed_frames,
            "replayed_rows": self.replayed_rows,
            "last_error": self.last_error,
        }
        if tracker is not None:
            out["pending_dirty_blocks"] = tracker.dirty_blocks
            out["tracker_blk"] = tracker.blk
            out["marked_fps"] = tracker.marked_fps
        return out
