"""Transport: gRPC services + HTTP/JSON gateway + /metrics.

The reference serves gRPC (V1 + PeersV1) and an HTTP gateway that maps
/v1/GetRateLimits, /v1/HealthCheck, /v1/LiveCheck to the same handlers with
proto-names JSON (reference daemon.go:131-196, 264-311). Here: grpc.aio with
hand-built generic handlers over the repo's pb2 messages (no generated service
stubs needed), and an aiohttp app for the gateway + Prometheus /metrics.
"""

from __future__ import annotations

import time
from typing import Optional

import grpc
from aiohttp import web
from google.protobuf import json_format

from gubernator_tpu import tracing
from gubernator_tpu.service import deadline as deadline_mod
from gubernator_tpu.proto import globalsync_pb2 as globalsync_pb
from gubernator_tpu.proto import gubernator_pb2 as pb
from gubernator_tpu.proto import handoff_pb2 as handoff_pb
from gubernator_tpu.proto import peers_pb2 as peers_pb
from gubernator_tpu.proto import regionsync_pb2 as regionsync_pb

V1 = "pb.gubernator.V1"
PEERS_V1 = "pb.gubernator.PeersV1"

# OpenMetrics exposition content type (the format that carries exemplars)
OPENMETRICS_CT = "application/openmetrics-text"


def _timed(metrics, method):
    def wrap(fn):
        async def run(request, context):
            t0 = time.perf_counter()
            status = "ok"
            try:
                return await fn(request, context)
            except Exception:
                status = "error"
                raise
            finally:
                metrics.grpc_request_counts.labels(
                    method=method, status=status
                ).inc()
                # the handler's request scope has already closed; its span
                # is this context's last-ended — the request-duration bucket
                # carries the request's trace_id as its exemplar
                span = tracing.last_ended_span()
                metrics.grpc_request_duration.labels(method=method).observe(
                    time.perf_counter() - t0,
                    exemplar=(
                        {"trace_id": span.trace_id} if span is not None else None
                    ),
                )

        return run

    return wrap


def build_grpc_services(daemon):
    """Generic handlers for the V1 + PeersV1 services."""
    m = daemon.metrics

    @_timed(m, "/v1.GetRateLimits")
    async def get_rate_limits(request: bytes, context):
        # raw wire bytes: the native ingress parses them straight into
        # columns (daemon.get_rate_limits_raw); pb fallback inside
        deadline_mod.set_inbound_deadline(context.time_remaining())
        try:
            return await daemon.get_rate_limits_raw(request)
        except ValueError as exc:  # batch too large etc.
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(exc))

    @_timed(m, "/v1.HealthCheck")
    async def health_check(request: pb.HealthCheckReq, context):
        return await daemon.health_check()

    @_timed(m, "/v1.LiveCheck")
    async def live_check(request: pb.LiveCheckReq, context):
        try:
            return daemon.live_check()
        except RuntimeError as exc:
            await context.abort(grpc.StatusCode.UNAVAILABLE, str(exc))

    @_timed(m, "/v1.LeaseQuota")
    async def lease_quota(request: pb.LeaseQuotaReq, context):
        return await daemon.lease_quota(request)

    @_timed(m, "/peers.GetPeerRateLimits")
    async def get_peer_rate_limits(request: peers_pb.GetPeerRateLimitsReq, context):
        deadline_mod.set_inbound_deadline(context.time_remaining())
        return await daemon.get_peer_rate_limits(request)

    @_timed(m, "/peers.UpdatePeerGlobals")
    async def update_peer_globals(request: peers_pb.UpdatePeerGlobalsReq, context):
        return await daemon.update_peer_globals(request)

    @_timed(m, "/peers.TransferState")
    async def transfer_state(request: handoff_pb.TransferStateReq, context):
        try:
            return await daemon.transfer_state(request)
        except ValueError as exc:  # malformed chunk buffers
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(exc))

    @_timed(m, "/peers.SyncGlobalsWire")
    async def sync_globals_wire(
        request: "globalsync_pb.SyncGlobalsWireReq", context
    ):
        try:
            return await daemon.sync_globals_wire(request)
        except ValueError as exc:  # malformed lane/string buffers
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(exc))

    @_timed(m, "/peers.SyncRegionsWire")
    async def sync_regions_wire(
        request: "regionsync_pb.SyncRegionsWireReq", context
    ):
        try:
            return await daemon.sync_regions_wire(request)
        except ValueError as exc:  # malformed lane/slot/string buffers
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(exc))

    def unary(fn, req_cls, resp_cls):
        return grpc.unary_unary_rpc_method_handler(
            fn,
            request_deserializer=req_cls.FromString,
            response_serializer=lambda msg: msg.SerializeToString(),
        )

    v1 = grpc.method_handlers_generic_handler(
        V1,
        {
            # GetRateLimits passes wire bytes through untouched — the
            # native ingress owns (de)serialization
            "GetRateLimits": grpc.unary_unary_rpc_method_handler(
                get_rate_limits,
                request_deserializer=lambda b: b,
                response_serializer=lambda b: b,
            ),
            "HealthCheck": unary(health_check, pb.HealthCheckReq, pb.HealthCheckResp),
            "LiveCheck": unary(live_check, pb.LiveCheckReq, pb.LiveCheckResp),
            "LeaseQuota": unary(lease_quota, pb.LeaseQuotaReq, pb.LeaseQuotaResp),
        },
    )
    peers = grpc.method_handlers_generic_handler(
        PEERS_V1,
        {
            "GetPeerRateLimits": unary(
                get_peer_rate_limits,
                peers_pb.GetPeerRateLimitsReq,
                peers_pb.GetPeerRateLimitsResp,
            ),
            "UpdatePeerGlobals": unary(
                update_peer_globals,
                peers_pb.UpdatePeerGlobalsReq,
                peers_pb.UpdatePeerGlobalsResp,
            ),
            "TransferState": unary(
                transfer_state,
                handoff_pb.TransferStateReq,
                handoff_pb.TransferStateResp,
            ),
            "SyncGlobalsWire": unary(
                sync_globals_wire,
                globalsync_pb.SyncGlobalsWireReq,
                globalsync_pb.SyncGlobalsWireResp,
            ),
            "SyncRegionsWire": unary(
                sync_regions_wire,
                regionsync_pb.SyncRegionsWireReq,
                regionsync_pb.SyncRegionsWireResp,
            ),
        },
    )
    return [v1, peers]


def build_http_app(daemon, status_only: bool = False) -> web.Application:
    """The grpc-gateway analog: JSON in/out with proto field names
    (UseProtoNames — reference daemon.go:267-273), plus /metrics.
    `status_only` builds the reduced status-listener app: health, liveness
    and /metrics, no rate-limit surface (reference daemon.go:324-352)."""

    def to_json(msg) -> web.Response:
        return web.json_response(
            json_format.MessageToDict(
                msg,
                preserving_proto_field_name=True,
                always_print_fields_with_no_presence=True,
            )
        )

    async def get_rate_limits(request: web.Request) -> web.Response:
        try:
            body = await request.json()
            req = json_format.ParseDict(body, pb.GetRateLimitsReq())
        except Exception as exc:
            return web.json_response(
                {"code": 3, "message": f"invalid request: {exc}"}, status=400
            )
        try:
            resps = await daemon.get_rate_limits(list(req.requests))
        except ValueError as exc:
            return web.json_response({"code": 3, "message": str(exc)}, status=400)
        return to_json(pb.GetRateLimitsResp(responses=resps))

    async def lease_quota(request: web.Request) -> web.Response:
        try:
            body = await request.json()
            req = json_format.ParseDict(body, pb.LeaseQuotaReq())
        except Exception as exc:
            return web.json_response(
                {"code": 3, "message": f"invalid request: {exc}"}, status=400
            )
        return to_json(await daemon.lease_quota(req))

    async def health(request: web.Request) -> web.Response:
        return to_json(await daemon.health_check())

    async def live(request: web.Request) -> web.Response:
        try:
            daemon.live_check()
        except RuntimeError as exc:
            return web.json_response({"code": 14, "message": str(exc)}, status=503)
        return web.json_response({})

    async def metrics(request: web.Request) -> web.Response:
        daemon.metrics.cache_size.set(await daemon.runner.live_count())
        daemon.metrics.global_sync_staleness.set(
            daemon.global_sync_staleness_s()
        )
        daemon.metrics.region_sync_staleness.set(
            daemon.region_manager.oldest_delta_age_s()
        )
        # content negotiation: scrapers that Accept the OpenMetrics format
        # get it (WITH the trace exemplars on latency buckets); everyone
        # else keeps the classic text exposition
        if OPENMETRICS_CT in request.headers.get("Accept", ""):
            return web.Response(
                body=daemon.metrics.render(openmetrics=True),
                headers={
                    "Content-Type": f"{OPENMETRICS_CT}; version=1.0.0; "
                    "charset=utf-8"
                },
            )
        return web.Response(
            body=daemon.metrics.render(),
            content_type="text/plain",
            charset="utf-8",
        )

    async def debug(request: web.Request) -> web.Response:
        """/v1/debug/{table,pipeline,peers,global}: live JSON snapshots of
        the planes the scrape-and-assert metrics model cannot show
        (docs/observability.md)."""
        kind = request.match_info["kind"]
        try:
            if kind == "table":
                return web.json_response(await daemon.debug_table())
            if kind == "pipeline":
                return web.json_response(daemon.debug_pipeline())
            if kind == "peers":
                return web.json_response(daemon.debug_peers())
            if kind == "global":
                return web.json_response(daemon.debug_global())
            if kind == "regions":
                return web.json_response(daemon.debug_regions())
            if kind == "durability":
                return web.json_response(daemon.debug_durability())
            if kind == "leases":
                return web.json_response(daemon.debug_leases())
            if kind == "tier":
                return web.json_response(daemon.debug_tier())
        except Exception as exc:  # pragma: no cover - defensive
            return web.json_response(
                {"code": 13, "message": f"debug snapshot failed: {exc}"},
                status=500,
            )
        return web.json_response(
            {"code": 5, "message": f"unknown debug plane {kind!r}; one of: "
             "table, pipeline, peers, global, regions, durability, leases, "
             "tier"},
            status=404,
        )

    app = web.Application()
    if not status_only:
        app.router.add_post("/v1/GetRateLimits", get_rate_limits)
        app.router.add_post("/v1/LeaseQuota", lease_quota)
    app.router.add_get("/v1/HealthCheck", health)
    app.router.add_post("/v1/HealthCheck", health)
    app.router.add_get("/v1/LiveCheck", live)
    app.router.add_post("/v1/LiveCheck", live)
    app.router.add_get("/metrics", metrics)
    if daemon.conf.debug_endpoints:
        # the debug plane rides the status listener too: it is exactly what
        # an operator probes when the serving listener is the thing broken
        app.router.add_get("/v1/debug/{kind}", debug)
    return app


class GrpcHandle:
    def __init__(self, server: grpc.aio.Server):
        self.server = server

    async def stop(self) -> None:
        await self.server.stop(grace=1.0)


class HttpHandle:
    def __init__(self, runner: web.AppRunner):
        self.runner = runner

    async def stop(self) -> None:
        await self.runner.cleanup()


async def start_servers(daemon) -> None:
    """Bind + start the gRPC server and HTTP gateway; records actual ports on
    the daemon (port 0 supported for tests)."""
    # transport limits mirroring the reference's server options
    # (daemon.go:131-144): 1 MiB receive cap — a wire batch maxes out at
    # GUBER_MAX_BATCH_SIZE small messages, so anything bigger is abuse, not
    # traffic (the cap scales at ~1 KiB/item when the batch limit is raised
    # past the reference's 1000) — plus optional connection-age bounds for
    # LB churn (GUBER_GRPC_MAX_CONN_AGE_SEC, config.go:351).
    recv_cap = max(1024 * 1024, daemon.conf.max_batch_size * 1024)
    options = [("grpc.max_receive_message_length", recv_cap)]
    if daemon.conf.grpc_max_conn_age_s > 0:
        age_ms = int(daemon.conf.grpc_max_conn_age_s * 1000)
        options += [
            ("grpc.max_connection_age_ms", age_ms),
            ("grpc.max_connection_age_grace_ms", age_ms),
        ]
    server = grpc.aio.server(options=options)
    for h in build_grpc_services(daemon):
        server.add_generic_rpc_handlers((h,))
    creds = None
    if daemon.conf.tls_cert_file or daemon.conf.tls_auto:
        from gubernator_tpu.service.tls import server_credentials, client_credentials

        creds = server_credentials(daemon.conf)
        daemon._client_creds = client_credentials(daemon.conf)
    if creds is not None:
        port = server.add_secure_port(daemon.conf.grpc_address, creds)
    else:
        port = server.add_insecure_port(daemon.conf.grpc_address)
    if port == 0:
        raise RuntimeError(f"failed to bind {daemon.conf.grpc_address}")
    daemon.grpc_port = port
    # rewrite :0 addresses with the real port so advertise/peer wiring works
    host = daemon.conf.grpc_address.rsplit(":", 1)[0]
    daemon.conf.grpc_address = f"{host}:{port}"
    if daemon.conf.advertise_address.endswith(":0"):
        daemon.conf.advertise_address = f"{host}:{port}"
    await server.start()
    daemon._servers.append(GrpcHandle(server))

    # with TLS on, the gateway serves HTTPS with the daemon's client-auth
    # mode — otherwise /v1 JSON and /metrics would leave the host in the
    # clear while gRPC is encrypted (VERDICT r3 missing #5; reference
    # daemon.go:150-155 terminates the gateway behind the same TLS config)
    gw_ssl = status_ssl = None
    if creds is not None:
        from gubernator_tpu.service.tls import http_ssl_context

        gw_ssl = http_ssl_context(daemon.conf)
        status_ssl = http_ssl_context(daemon.conf, require_client_auth=False)
        # live contexts: the daemon's cert watcher reloads the chain in
        # place on rotation (new handshakes pick it up; gRPC reloads
        # per-handshake, these must not lag behind it)
        daemon._http_ssl_contexts = [
            c for c in (gw_ssl, status_ssl) if c is not None
        ]

    async def start_http(address: str, status_only: bool, ssl_ctx):
        app = build_http_app(daemon, status_only=status_only)
        runner = web.AppRunner(app, access_log=None)
        await runner.setup()
        hhost, _, hport = address.rpartition(":")
        site = web.TCPSite(
            runner, hhost or "127.0.0.1", int(hport), ssl_context=ssl_ctx
        )
        await site.start()
        real = runner.addresses[0][1] if runner.addresses else int(hport)
        daemon._servers.append(HttpHandle(runner))
        return f"{hhost or '127.0.0.1'}:{real}", real

    if daemon.conf.http_address:
        addr, real = await start_http(daemon.conf.http_address, False, gw_ssl)
        daemon.http_port = real
        daemon.conf.http_address = addr
    if daemon.conf.status_http_address:
        # status listener: health + /metrics only, TLS without client certs
        # so k8s probes and Prometheus scrape in mTLS clusters (reference
        # HTTPStatusListenAddress, daemon.go:324-352)
        addr, real = await start_http(
            daemon.conf.status_http_address, True, status_ssl
        )
        daemon.status_http_port = real
        daemon.conf.status_http_address = addr
