"""Inbound-deadline propagation for the serving path.

gRPC carries the caller's deadline on every call; the work it gates —
batch-window queueing (service/batcher.py) and peer forwarding
(service/peer_client.py) — happens in asyncio tasks far from the handler.
This module carries the deadline to them as a contextvar: the server
handler stamps the call's absolute expiry once (`set_inbound_deadline`),
and because asyncio tasks inherit the contextvars of their creator, every
await downstream can ask `remaining()` for the budget left and fail fast
instead of doing work whose answer nobody is waiting for.

The value is an ABSOLUTE time.monotonic() instant (not a duration), so it
survives any number of hops without accumulating read-time drift. None
means "no deadline" — direct embedded-engine callers and tests that never
touch gRPC see the legacy unbounded behavior.
"""

from __future__ import annotations

import contextvars
import time
from typing import Optional

_deadline: contextvars.ContextVar[Optional[float]] = contextvars.ContextVar(
    "guber_inbound_deadline", default=None
)


def set_inbound_deadline(remaining_s: Optional[float]) -> None:
    """Stamp the current call's deadline from its remaining seconds
    (gRPC `context.time_remaining()`); None / non-positive∞ clears it."""
    if remaining_s is None or remaining_s <= 0 or remaining_s == float("inf"):
        _deadline.set(None)
    else:
        _deadline.set(time.monotonic() + remaining_s)


def inbound_deadline() -> Optional[float]:
    """The absolute monotonic deadline of the inbound call, or None."""
    return _deadline.get()


def remaining(default: Optional[float] = None) -> Optional[float]:
    """Seconds left until the inbound deadline (may be negative once
    past it), or `default` when no deadline is set."""
    d = _deadline.get()
    if d is None:
        return default
    return d - time.monotonic()
