"""Edge quota leases — the server-side admission-delegation plane.

The V1 ``LeaseQuota`` RPC hands a *bounded slice* of a limit to a client
library (gubernator_tpu/edge): N tokens with a TTL and a lease id. The
client then admits at memory speed from its local budget and only comes
back to renew, to return unused tokens, or when its slice is exhausted —
cutting the per-check fan-in into the daemon by the grant size
(docs/leases.md has the delegation model and the bound math).

Everything here is built from primitives the kernel already proves:

* **The grant is just hits.** A grant of N tokens is ``hits = N`` through
  the NORMAL decide path (``daemon.get_rate_limits``), so ring ownership,
  GLOBAL broadcast queueing, and MULTI_REGION replication apply to leased
  consumption verbatim — a lease is indistinguishable from N ordinary hits
  to every other plane, and the region/handoff conservatism bounds hold
  unchanged.
* **The outstanding ledger is a CONCURRENCY_LEASE row** (PR 10) on a
  derived key (``name + "\\x00lease"``): acquires are ``hits = +N``
  (denied when Σ outstanding would pass the per-key cap), returns are
  ``hits = -N``, and because lease acquires refresh ``ExpireAt = now +
  TTL``, the table's TTL eviction IS the reclamation — a crashed client's
  ledger tokens flow back with no scan, no timer wheel, no tombstones.
* **Unreturned real-limit tokens stay consumed** until the limit's own
  window resets — the conservative direction (the daemon can't know how
  many of a dead client's tokens were really used). Returned tokens refund
  through ``hits = -N`` on the real key, bounded by the LEASE RECORD
  (``min(return_tokens, outstanding)``) — a refund can never exceed what
  this lease's grants consumed, whatever the algorithm's own negative-hit
  semantics (token buckets bank credit by reference rule; the extension
  lanes additionally clamp in-kernel — ops/math.py miss-safety).

Over-admission bound: at any instant, admissions across the fleet ≤
tokens consumed through the decide path + Σ outstanding leased tokens
(``/v1/debug/leases`` reports the live Σ). The in-memory lease records
here are bookkeeping only (ids, per-key totals, expiry accounting) — the
DEVICE ledger row is the authority, so a daemon restart loses nothing
that matters: records vanish, the restored/reclaimed ledger still bounds
new grants, and late returns against vanished leases are miss-safe.
"""

from __future__ import annotations

import heapq
import time
import uuid
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from gubernator_tpu.proto import gubernator_pb2 as pb
from gubernator_tpu.types import (
    Behavior,
    PRIORITY_MASK,
    PRIORITY_SHIFT,
    PRIORITY_TIERS,
    priority_tier,
)

import logging

log = logging.getLogger("gubernator_tpu.lease")

# ledger-key name suffix: NUL can't appear in a sane client namespace, so
# the per-key outstanding ledger can never collide with real traffic
LEDGER_SUFFIX = "\x00lease"

# behavior bits a lease grant forwards into the decide path — the client's
# routing/replication intent plus its priority tier (so the front door's
# overload plane sees leased consumption at the edge's tier), never
# RESET/DRAIN (a grant must consume honestly) and never Gregorian (lease
# windows are always milliseconds)
_GRANT_BEHAVIOR = int(
    Behavior.NO_BATCHING | Behavior.GLOBAL | Behavior.MULTI_REGION
) | (PRIORITY_MASK << PRIORITY_SHIFT)


@dataclass
class LeaseRecord:
    lease_id: str
    name: str
    unique_key: str
    hash_key: str
    outstanding: int  # granted - returned tokens still out at the edge
    expires_at: int  # epoch ms
    granted_total: int


class LeaseManager:
    def __init__(self, daemon):
        self.daemon = daemon
        conf = daemon.conf
        self.max_fraction = conf.lease_max_fraction
        self.min_ttl_ms = conf.lease_min_ttl_ms
        self.max_ttl_ms = conf.lease_max_ttl_ms
        self.max_outstanding = conf.lease_max_outstanding
        # tier-aware sizing (GUBER_PRIORITY_LEASE_SCALING, default off):
        # grants scale with the requester's priority tier and pressured
        # keys push shrink hints at low-tier edges
        self.priority_scaling = getattr(conf, "lease_priority_scaling", False)
        self.metrics = daemon.metrics
        self._leases: Dict[str, LeaseRecord] = {}
        self._by_key: Dict[str, int] = {}  # hash_key → Σ outstanding
        # (expires_at, lease_id) min-heap so pruning is O(expired · log n),
        # not a scan of every live lease per op
        self._expiry: List[Tuple[int, str]] = []
        # lifetime counters (debug plane; prometheus carries the same)
        self.acquires = 0
        self.renews = 0
        self.returns = 0
        self.denies = 0
        self.expirations = 0
        self.unknown_returns = 0
        self.shrink_hints = 0
        self.tokens_granted = 0
        self.tokens_returned = 0
        self.tokens_expired = 0

    # ------------------------------------------------------------- internals
    def _cap(self, limit: int) -> int:
        """Per-key ceiling on Σ outstanding leased tokens: a bounded
        fraction of the limit (GUBER_LEASE_MAX_FRACTION), optionally capped
        absolutely (GUBER_LEASE_MAX_OUTSTANDING) — the knob that sizes the
        documented over-admission bound."""
        cap = max(1, int(limit * self.max_fraction))
        if self.max_outstanding > 0:
            cap = min(cap, self.max_outstanding)
        return cap

    def _ttl(self, req_ttl_ms: int) -> int:
        if req_ttl_ms <= 0:
            req_ttl_ms = int(self.max_ttl_ms) // 4
        return int(min(max(req_ttl_ms, self.min_ttl_ms), self.max_ttl_ms))

    def _ledger_item(self, req, hits: int, ttl_ms: int) -> "pb.RateLimitReq":
        """The outstanding-ledger row: a CONCURRENCY_LEASE check whose limit
        is the per-key outstanding cap and whose duration is the lease TTL
        (acquires refresh ExpireAt, so TTL eviction reclaims a crashed
        client's ledger tokens — the PR-10 rule)."""
        return pb.RateLimitReq(
            name=req.name + LEDGER_SUFFIX,
            unique_key=req.unique_key,
            hits=hits,
            limit=self._cap(req.limit),
            duration=ttl_ms,
            algorithm=int(pb.CONCURRENCY_LEASE),
            behavior=int(Behavior.NO_BATCHING),
        )

    def _grant_item(self, req, hits: int) -> "pb.RateLimitReq":
        """The real-limit consumption/refund row — plain hits through the
        normal decide path, with the client's routing behaviors intact."""
        return pb.RateLimitReq(
            name=req.name,
            unique_key=req.unique_key,
            hits=hits,
            limit=req.limit,
            duration=req.duration,
            algorithm=int(req.algorithm),
            behavior=(int(req.behavior) & _GRANT_BEHAVIOR)
            | int(Behavior.NO_BATCHING),
            burst=req.burst,
        )

    async def _check(self, item) -> "pb.RateLimitResp":
        resps = await self.daemon.get_rate_limits([item])
        return resps[0]

    def _prune(self, now_ms: int) -> None:
        """Expire in-memory records past their TTL. The device ledger
        reclaims itself (TTL eviction); this keeps the Σ-outstanding gauge
        and the per-key map honest without any background task."""
        while self._expiry and self._expiry[0][0] <= now_ms:
            exp_at, lease_id = heapq.heappop(self._expiry)
            rec = self._leases.get(lease_id)
            if rec is None or rec.expires_at != exp_at:
                continue  # renewed (re-pushed under the new deadline) or gone
            del self._leases[lease_id]
            self._drop_outstanding(rec.hash_key, rec.outstanding)
            self.expirations += 1
            self.tokens_expired += rec.outstanding
            self.metrics.lease_ops.labels(op="expire").inc()
            self.metrics.lease_tokens.labels(kind="expired").inc(
                rec.outstanding
            )
        self._observe()

    def _drop_outstanding(self, hash_key: str, n: int) -> None:
        left = self._by_key.get(hash_key, 0) - n
        if left > 0:
            self._by_key[hash_key] = left
        else:
            self._by_key.pop(hash_key, None)

    def _observe(self) -> None:
        self.metrics.lease_outstanding.set(sum(self._by_key.values()))
        self.metrics.lease_active.set(len(self._leases))

    @staticmethod
    def _retry_after(resp: "pb.RateLimitResp", now_ms: int) -> int:
        raw = resp.metadata.get("retry_after_ms", "")
        if raw:
            try:
                return max(0, int(raw))
            except ValueError:
                pass
        return max(0, int(resp.reset_time) - now_ms)

    # ------------------------------------------------------------- the RPC
    async def lease_quota(self, req: "pb.LeaseQuotaReq") -> "pb.LeaseQuotaResp":
        """One acquire / renew / return operation (proto/gubernator.proto
        LeaseQuotaReq). Order of effects: returns first (they free budget),
        then the ledger acquire (caps Σ outstanding), then the real-limit
        grant — a denied grant releases its ledger acquisition so the two
        rows can never drift apart by more than one in-flight op."""
        if req.unique_key == "":
            return pb.LeaseQuotaResp(error="field 'unique_key' cannot be empty")
        if req.name == "":
            return pb.LeaseQuotaResp(error="field 'namespace' cannot be empty")
        if req.limit <= 0 or req.duration <= 0:
            return pb.LeaseQuotaResp(
                error="lease quota requires a positive limit and duration"
            )
        if req.tokens < 0 or req.return_tokens < 0:
            return pb.LeaseQuotaResp(
                error="tokens/return_tokens must be >= 0 (returns travel in "
                "return_tokens, not negative grants)"
            )
        now = self.daemon.now_ms()
        self._prune(now)
        ttl = self._ttl(int(req.ttl_ms))
        hash_key = req.name + "_" + req.unique_key
        rec = self._leases.get(req.lease_id) if req.lease_id else None
        if rec is not None and rec.hash_key != hash_key:
            # a lease id minted for a DIFFERENT key: honoring it would
            # refund/attribute tokens across keys — treat as unknown (the
            # renew becomes a fresh acquire, the return refunds nothing)
            rec = None

        # ---- 1. return unused tokens (early return, renewal shrink, close).
        # The refund is clamped by the LEASE RECORD, not the request: a
        # return may only give back tokens this daemon granted this lease —
        # otherwise a forged/duplicated return would refund tokens other
        # traffic legitimately consumed (token buckets BANK negative hits
        # past the limit by reference rule, so the record clamp is the
        # load-bearing bound here). After a daemon restart the records are
        # gone, so late returns refund nothing (conservative: the tokens
        # stay consumed until the window resets; the device ledger
        # reclaims its side by TTL regardless, miss-safely — ops/math.py).
        remaining = -1
        if req.return_tokens > 0:
            give = 0
            if rec is not None:
                give = min(int(req.return_tokens), rec.outstanding)
            elif req.lease_id:
                self.unknown_returns += 1
                self.metrics.lease_ops.labels(op="unknown_return").inc()
            if give > 0:
                await self._check(self._ledger_item(req, -give, ttl))
                r = await self._check(self._grant_item(req, -give))
                remaining = int(r.remaining)
                rec.outstanding -= give
                self._drop_outstanding(hash_key, give)
                self.returns += 1
                self.tokens_returned += give
                self.metrics.lease_ops.labels(op="return").inc()
                self.metrics.lease_tokens.labels(kind="returned").inc(give)

        # ---- 2. the new grant, ledger first
        want = int(req.tokens)
        granted = 0
        retry_after = 0
        error = ""
        if want > 0:
            want = min(want, self._cap(int(req.limit)))
            if self.priority_scaling:
                # tier-aware sizing: tier 3 keeps the full slice, each tier
                # below loses a quarter (tier 0 → 25%) — high-priority
                # edges find budget first when every edge is asking
                tier = priority_tier(req.behavior)
                want = max(1, (want * (tier + 1)) // PRIORITY_TIERS)
            lr = await self._check(self._ledger_item(req, want, ttl))
            if lr.error:
                error = lr.error
            elif lr.status == pb.OVER_LIMIT:
                # partial: re-try at whatever the ledger still allows
                avail = int(lr.remaining)
                if avail > 0:
                    lr2 = await self._check(
                        self._ledger_item(req, avail, ttl)
                    )
                    if lr2.status == pb.UNDER_LIMIT and not lr2.error:
                        want = avail
                    else:
                        want = 0
                else:
                    want = 0
                if want == 0:
                    retry_after = self._retry_after(lr, now)
            if not error and want > 0:
                gr = await self._check(self._grant_item(req, want))
                if gr.error:
                    error = gr.error
                    granted = 0
                elif gr.status == pb.UNDER_LIMIT:
                    granted = want
                else:
                    # real limit can't cover the slice — shrink to what's
                    # left (one retry), like the adaptive client would
                    avail = max(0, int(gr.remaining))
                    if avail > 0:
                        gr2 = await self._check(self._grant_item(req, avail))
                        if gr2.status == pb.UNDER_LIMIT and not gr2.error:
                            granted = avail
                            gr = gr2
                    if granted == 0:
                        retry_after = self._retry_after(gr, now)
                remaining = int(gr.remaining)
                if granted < want:
                    # release the ledger slack so Σ outstanding matches the
                    # tokens actually out at the edge
                    await self._check(
                        self._ledger_item(req, granted - want, ttl)
                    )

        # ---- 3. bookkeeping + response
        expires_at = now + ttl
        if granted > 0:
            if rec is None:
                # ALWAYS mint a fresh id: adopting a caller-supplied one
                # (a stale/foreign lease_id on a renew-after-restart)
                # would overwrite whatever record that id still names —
                # the client adopts the returned id (LocalLimiter does)
                rec = LeaseRecord(
                    lease_id=uuid.uuid4().hex,
                    name=req.name,
                    unique_key=req.unique_key,
                    hash_key=hash_key,
                    outstanding=0,
                    expires_at=expires_at,
                    granted_total=0,
                )
                self._leases[rec.lease_id] = rec
                self.acquires += 1
                self.metrics.lease_ops.labels(op="acquire").inc()
            else:
                self.renews += 1
                self.metrics.lease_ops.labels(op="renew").inc()
            rec.outstanding += granted
            rec.granted_total += granted
            rec.expires_at = expires_at
            heapq.heappush(self._expiry, (expires_at, rec.lease_id))
            self._by_key[hash_key] = self._by_key.get(hash_key, 0) + granted
            self.tokens_granted += granted
            self.metrics.lease_tokens.labels(kind="granted").inc(granted)
        elif want >= 0 and req.tokens > 0:
            self.denies += 1
            self.metrics.lease_ops.labels(op="deny").inc()
        self._observe()
        # push-shrink hint: when the key is pressured (Σ outstanding ≥ 80%
        # of the cap), ask lower-tier edges to cut their local grant ahead
        # of the TTL so high-tier traffic finds budget; tier 3 never
        # shrinks, and the hint is advisory (an edge that ignores it is
        # still bounded by TTL reclamation)
        shrink_to = 0
        if (
            self.priority_scaling
            and rec is not None
            and rec.outstanding > 0
        ):
            tier = priority_tier(req.behavior)
            cap = self._cap(int(req.limit))
            pressured = self._by_key.get(hash_key, 0) * 5 >= cap * 4
            if pressured and tier < PRIORITY_TIERS - 1:
                target = (rec.outstanding * (tier + 1)) // PRIORITY_TIERS
                if target < rec.outstanding:
                    shrink_to = max(1, target)
                    self.shrink_hints += 1
                    self.metrics.lease_ops.labels(op="shrink_hint").inc()
        return pb.LeaseQuotaResp(
            lease_id=rec.lease_id if rec is not None else "",
            granted=granted,
            expires_at=rec.expires_at if rec is not None else 0,
            limit=req.limit,
            remaining=max(0, remaining) if remaining >= 0 else 0,
            retry_after_ms=retry_after,
            outstanding=self._by_key.get(hash_key, 0),
            error=error,
            shrink_to=shrink_to,
        )

    # -------------------------------------------------------- introspection
    def outstanding_total(self) -> int:
        """Σ outstanding leased tokens on this daemon — the live
        over-admission bound contribution."""
        return sum(self._by_key.values())

    def debug(self) -> dict:
        """Live lease-plane state for /v1/debug/leases."""
        self._prune(self.daemon.now_ms())
        keys = sorted(
            self._by_key.items(), key=lambda kv: -kv[1]
        )[:64]
        return {
            "active_leases": len(self._leases),
            # Σ outstanding tokens = the proven over-admission bound the
            # delegation adds on top of the limits themselves
            "outstanding_tokens_total": self.outstanding_total(),
            "over_admission_bound": self.outstanding_total(),
            "outstanding_by_key": {k: v for k, v in keys},
            "ops": {
                "acquires": self.acquires,
                "renews": self.renews,
                "returns": self.returns,
                "denies": self.denies,
                "expirations": self.expirations,
                "unknown_returns": self.unknown_returns,
                "shrink_hints": self.shrink_hints,
            },
            "tokens": {
                "granted": self.tokens_granted,
                "returned": self.tokens_returned,
                "expired": self.tokens_expired,
            },
            "knobs": {
                "max_fraction": self.max_fraction,
                "min_ttl_ms": self.min_ttl_ms,
                "max_ttl_ms": self.max_ttl_ms,
                "max_outstanding": self.max_outstanding,
                "priority_scaling": self.priority_scaling,
            },
        }
