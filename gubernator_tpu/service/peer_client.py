"""PeerClient — one per remote peer; forwarding with batch coalescing.

Mirrors reference peer_client.go: a gRPC connection plus a batching queue that
flushes at BatchLimit (1000) or BatchWait (500 µs), a NO_BATCHING direct path,
a graceful Shutdown that drains in-flight requests, and a recent-error LRU
feeding the health check (reference peer_client.go:86-451).

Raw grpc.aio unary calls are built from method paths + pb2 serializers — no
generated stubs needed (the repo's pb2 files carry messages only).
"""

from __future__ import annotations

import asyncio
import collections
import time
from typing import List, Optional, Tuple

import grpc

from gubernator_tpu import tracing
from gubernator_tpu.proto import globalsync_pb2 as globalsync_pb
from gubernator_tpu.proto import gubernator_pb2 as pb
from gubernator_tpu.proto import handoff_pb2 as handoff_pb
from gubernator_tpu.proto import peers_pb2 as peers_pb
from gubernator_tpu.service import deadline as deadline_mod
from gubernator_tpu.service.breaker import CircuitBreaker
from gubernator_tpu.types import Behavior, PeerInfo, has_behavior

GET_PEER_RATE_LIMITS = "/pb.gubernator.PeersV1/GetPeerRateLimits"
UPDATE_PEER_GLOBALS = "/pb.gubernator.PeersV1/UpdatePeerGlobals"
TRANSFER_STATE = "/pb.gubernator.PeersV1/TransferState"
SYNC_GLOBALS_WIRE = "/pb.gubernator.PeersV1/SyncGlobalsWire"
SYNC_REGIONS_WIRE = "/pb.gubernator.PeersV1/SyncRegionsWire"
GET_RATE_LIMITS = "/pb.gubernator.V1/GetRateLimits"
HEALTH_CHECK = "/pb.gubernator.V1/HealthCheck"

LAST_ERRS_CAP = 100  # reference peer_client.go:211-240 — LRU(100)
LAST_ERRS_TTL_S = 300.0  # 5-minute TTL


class PeerError(Exception):
    """RPC-level failure talking to a peer (carries the address)."""

    def __init__(self, address: str, cause: BaseException):
        super().__init__(f"peer {address}: {cause}")
        self.address = address
        self.cause = cause


class PeerCircuitOpenError(PeerError):
    """Fast-fail: the peer's circuit breaker refused the attempt — no RPC
    was made (and none should be retried against the same peer until the
    cooldown elapses)."""

    def __init__(self, address: str, retry_after_s: float = 0.0):
        super().__init__(
            address,
            RuntimeError(
                f"circuit breaker open (retry in {retry_after_s * 1e3:.0f} ms)"
            ),
        )
        self.retry_after_s = retry_after_s


class PeerClient:
    def __init__(
        self,
        info: PeerInfo,
        batch_wait_ms: float = 0.5,
        batch_limit: int = 1000,
        batch_timeout_ms: float = 500.0,
        metrics=None,
        channel_credentials=None,
        breaker: Optional[CircuitBreaker] = None,
    ):
        self.info = info
        self.batch_wait_s = batch_wait_ms / 1e3
        self.batch_limit = batch_limit
        self.timeout_s = batch_timeout_ms / 1e3
        self.metrics = metrics
        self._creds = channel_credentials
        if breaker is None:
            breaker = CircuitBreaker()
        self.breaker = breaker
        if metrics is not None and breaker._on_state is None:
            gauge = metrics.circuit_breaker_state.labels(
                peer=info.grpc_address
            )
            gauge.set(int(breaker.state))
            breaker._on_state = lambda s: gauge.set(int(s))
        self._channel: Optional[grpc.aio.Channel] = None
        self._queue: List[Tuple[pb.RateLimitReq, asyncio.Future]] = []
        self._wake: Optional[asyncio.Event] = None
        self._loop_task: Optional[asyncio.Task] = None
        self._inflight = 0
        self._closed = False
        self.last_errs: collections.deque = collections.deque(maxlen=LAST_ERRS_CAP)

    # ------------------------------------------------------------- transport
    def _chan(self) -> grpc.aio.Channel:
        if self._channel is None:
            opts = [
                ("grpc.max_receive_message_length", 1 << 20),  # daemon.go:133
            ]
            if self._creds is not None:
                self._channel = grpc.aio.secure_channel(
                    self.info.grpc_address, self._creds, options=opts
                )
            else:
                self._channel = grpc.aio.insecure_channel(
                    self.info.grpc_address, options=opts
                )
        return self._channel

    def _record_err(self, exc: BaseException) -> None:
        self.last_errs.append((time.monotonic(), str(exc)))

    def recent_errors(self) -> List[str]:
        cutoff = time.monotonic() - LAST_ERRS_TTL_S
        return [msg for ts, msg in self.last_errs if ts >= cutoff]

    async def _unary(self, path: str, req, resp_cls, timeout: Optional[float] = None):
        # the breaker gates EVERY unary RPC toward this peer — forwards,
        # GLOBAL hit-syncs and broadcasts all fail fast while it is open
        # instead of stacking timeout waits on a dead peer
        if not self.breaker.allow():
            raise PeerCircuitOpenError(
                self.info.grpc_address, self.breaker.retry_after_s()
            )
        call = self._chan().unary_unary(
            path,
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=resp_cls.FromString,
        )
        try:
            resp = await call(req, timeout=timeout or self.timeout_s)
        except asyncio.CancelledError:
            # task cancellation must propagate, not become a PeerError; it is
            # no verdict on the peer either — release any probe slot
            self.breaker.record_discard()
            raise
        except BaseException as exc:
            self.breaker.record_failure()
            self._record_err(exc)
            raise PeerError(self.info.grpc_address, exc) from exc
        self.breaker.record_success()
        return resp

    # ------------------------------------------------------------ peer RPCs
    async def get_peer_rate_limits(
        self, req: "peers_pb.GetPeerRateLimitsReq", timeout: Optional[float] = None
    ) -> "peers_pb.GetPeerRateLimitsResp":
        return await self._unary(
            GET_PEER_RATE_LIMITS, req, peers_pb.GetPeerRateLimitsResp, timeout
        )

    async def update_peer_globals(
        self, req: "peers_pb.UpdatePeerGlobalsReq", timeout: Optional[float] = None
    ) -> "peers_pb.UpdatePeerGlobalsResp":
        return await self._unary(
            UPDATE_PEER_GLOBALS, req, peers_pb.UpdatePeerGlobalsResp, timeout
        )

    async def sync_globals_wire(
        self,
        req: "globalsync_pb.SyncGlobalsWireReq",
        timeout: Optional[float] = None,
    ) -> "globalsync_pb.SyncGlobalsWireResp":
        """Ship one compact GLOBAL hit-sync batch (service/wire.sync_wire_pb)
        to the owning peer — the inter-slice half of the hierarchical sync.
        `wire_sync_ok` latches False when the peer answers UNIMPLEMENTED (a
        pre-compact build), so the manager falls back to the proto path
        permanently for that peer instead of probing every round."""
        return await self._unary(
            SYNC_GLOBALS_WIRE, req, globalsync_pb.SyncGlobalsWireResp, timeout
        )

    # latched by GlobalManager on UNIMPLEMENTED — peer runs a pre-compact
    # build; the proto path serves it with identical semantics
    wire_sync_ok = True

    async def sync_regions_wire(
        self,
        req,
        timeout: Optional[float] = None,
    ):
        """Ship one compact cross-region delta batch
        (service/wire.sync_regions_pb) to the key owner in a remote region.
        `region_wire_ok` latches False when the peer answers UNIMPLEMENTED
        (a pre-region-merge build), so the RegionManager falls back to the
        classic GetPeerRateLimits proto path permanently for that peer."""
        from gubernator_tpu.proto import regionsync_pb2 as regionsync_pb

        return await self._unary(
            SYNC_REGIONS_WIRE, req, regionsync_pb.SyncRegionsWireResp,
            timeout,
        )

    # latched by RegionManager on UNIMPLEMENTED — peer predates the region
    # merge plane; the proto fallback serves it with legacy semantics
    region_wire_ok = True

    async def transfer_state(
        self, req: "handoff_pb.TransferStateReq", timeout: Optional[float] = None
    ) -> "handoff_pb.TransferStateResp":
        """One ownership-handoff chunk toward this peer. Breaker-gated like
        every unary (an open breaker fast-fails so the handoff's deadline is
        spent on reachable destinations); idempotent on the receiver, so the
        caller retries failed chunks freely."""
        return await self._unary(
            TRANSFER_STATE, req, handoff_pb.TransferStateResp, timeout
        )

    # ------------------------------------------------- forwarding (batched)
    async def get_peer_rate_limit(self, item: "pb.RateLimitReq") -> "pb.RateLimitResp":
        """Forward one item to this peer. BATCHING (default) coalesces into
        the 500 µs / 1000-item window; NO_BATCHING and GLOBAL-accumulated
        sends go direct (reference peer_client.go:126-162)."""
        if self._closed:
            raise PeerError(self.info.grpc_address, RuntimeError("peer client closed"))
        if self.breaker.blocked:
            # fail BEFORE enqueueing: a request queued behind an open breaker
            # would strand until the queue-wait deadline, defeating the
            # fail-fast point of the breaker. `blocked` is side-effect-free —
            # when the cooldown has elapsed, the flush RPC itself becomes the
            # half-open probe via _unary's allow().
            raise PeerCircuitOpenError(
                self.info.grpc_address, self.breaker.retry_after_s()
            )
        # propagate the active trace to the owner via request metadata
        # (reference peer_client.go:140-142, 364-367)
        tracing.inject(item.metadata)
        if has_behavior(item.behavior, Behavior.NO_BATCHING):
            resp = await self.get_peer_rate_limits(
                peers_pb.GetPeerRateLimitsReq(requests=[item])
            )
            if len(resp.rate_limits) != 1:
                raise PeerError(
                    self.info.grpc_address,
                    RuntimeError("expected 1 rate limit in response"),
                )
            return resp.rate_limits[0]
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._queue.append((item, fut))
        if self.metrics is not None:
            self.metrics.batch_queue_length.set(len(self._queue))
        if self._loop_task is None or self._loop_task.done():
            self._wake = asyncio.Event()
            self._loop_task = loop.create_task(
                self._run(), name=f"peer-batch:{self.info.grpc_address}"
            )
        self._wake.set()
        # queue-wait deadline (BatchTimeout analog, reference config.go:138):
        # a request must never strand in the queue awaiting a flush that does
        # not come. The loop drains a deep queue in sequential chunks, so the
        # budget scales with this item's chunk position — a burst's tail is
        # legitimately behind several RPCs, not timed out.
        chunks_ahead = (len(self._queue) + self.batch_limit - 1) // self.batch_limit
        deadline = self.batch_wait_s + self.timeout_s * max(1, chunks_ahead) + 1.0
        # ... but never past the caller's own remaining gRPC deadline: a
        # deep queue can push the computed budget beyond what the inbound
        # request has left, and waiting out the difference only burns a
        # worker on an answer nobody is listening for
        inbound = deadline_mod.remaining()
        if inbound is not None:
            deadline = min(deadline, max(inbound, 0.001))
        try:
            return await asyncio.wait_for(asyncio.shield(fut), timeout=deadline)
        except asyncio.TimeoutError:
            try:
                self._queue.remove((item, fut))
                if self.metrics is not None:
                    self.metrics.batch_queue_length.set(len(self._queue))
            except ValueError:
                pass  # already picked up by a flush; its result is dropped
            fut.cancel()
            err = PeerError(
                self.info.grpc_address,
                TimeoutError("request timed out awaiting the batch flush"),
            )
            self._record_err(err)
            raise err

    async def _run(self) -> None:
        """The long-lived flush loop (reference runBatch, one goroutine per
        peer, peer_client.go:289-344): wake on enqueue, wait out the batch
        window unless the limit is already met, then send chunks until the
        queue is empty. Items enqueued while a send is in flight are picked
        up by the next iteration — nothing strands, and a running send is
        never cancelled by new arrivals (the one-shot-task design this loop
        replaced could do both)."""
        while not self._closed:
            await self._wake.wait()
            self._wake.clear()
            if not self._queue:
                continue
            if len(self._queue) < self.batch_limit and self.batch_wait_s > 0:
                await asyncio.sleep(self.batch_wait_s)
            await self._drain()

    async def _drain(self) -> None:
        """Send the queue in batch_limit chunks until empty (shared by the
        flush loop and shutdown so metrics/chunking can't diverge)."""
        while self._queue:
            batch = self._queue[: self.batch_limit]
            self._queue = self._queue[self.batch_limit :]
            if self.metrics is not None:
                self.metrics.batch_queue_length.set(len(self._queue))
            await self._send(batch)

    async def _send(self, batch) -> None:
        self._inflight += 1
        try:
            req = peers_pb.GetPeerRateLimitsReq(requests=[i for i, _ in batch])
            try:
                resp = await self.get_peer_rate_limits(req)
                if len(resp.rate_limits) != len(batch):
                    raise PeerError(
                        self.info.grpc_address,
                        RuntimeError("mismatched response count"),
                    )
                for (item, fut), r in zip(batch, resp.rate_limits):
                    if not fut.done():
                        fut.set_result(r)
            except asyncio.CancelledError:
                # loop-task cancellation mid-RPC: fail the batch, then let the
                # cancellation end the task (never swallow it — the loop would
                # otherwise survive cancel() forever)
                for _, fut in batch:
                    if not fut.done():
                        fut.set_exception(
                            PeerError(
                                self.info.grpc_address,
                                RuntimeError("peer client cancelled"),
                            )
                        )
                raise
            except BaseException as exc:
                for _, fut in batch:
                    if not fut.done():
                        fut.set_exception(
                            exc
                            if isinstance(exc, PeerError)
                            else PeerError(self.info.grpc_address, exc)
                        )
        finally:
            self._inflight -= 1

    # -------------------------------------------------------------- shutdown
    async def shutdown(self) -> None:
        """Drain: stop the flush loop, send anything still queued, wait for
        in-flight sends, close the channel (reference peer_client.go:415-451)."""
        self._closed = True
        try:
            if self._loop_task is not None and not self._loop_task.done():
                self._wake.set()
                await self._loop_task
            # single-drainer invariant: the loop has exited, so no send is in
            # flight here — this drain is the only sender left
            await self._drain()
        finally:
            # a failing peer (PeerError/cancellation out of the final drain)
            # must never leak the channel
            if self._channel is not None:
                await self._channel.close()
                self._channel = None
