"""PeerClient — one per remote peer; forwarding with batch coalescing.

Mirrors reference peer_client.go: a gRPC connection plus a batching queue that
flushes at BatchLimit (1000) or BatchWait (500 µs), a NO_BATCHING direct path,
a graceful Shutdown that drains in-flight requests, and a recent-error LRU
feeding the health check (reference peer_client.go:86-451).

Raw grpc.aio unary calls are built from method paths + pb2 serializers — no
generated stubs needed (the repo's pb2 files carry messages only).
"""

from __future__ import annotations

import asyncio
import collections
import time
from typing import List, Optional, Tuple

import grpc

from gubernator_tpu import tracing
from gubernator_tpu.proto import gubernator_pb2 as pb
from gubernator_tpu.proto import peers_pb2 as peers_pb
from gubernator_tpu.types import Behavior, PeerInfo, has_behavior

GET_PEER_RATE_LIMITS = "/pb.gubernator.PeersV1/GetPeerRateLimits"
UPDATE_PEER_GLOBALS = "/pb.gubernator.PeersV1/UpdatePeerGlobals"
GET_RATE_LIMITS = "/pb.gubernator.V1/GetRateLimits"
HEALTH_CHECK = "/pb.gubernator.V1/HealthCheck"

LAST_ERRS_CAP = 100  # reference peer_client.go:211-240 — LRU(100)
LAST_ERRS_TTL_S = 300.0  # 5-minute TTL


class PeerError(Exception):
    """RPC-level failure talking to a peer (carries the address)."""

    def __init__(self, address: str, cause: BaseException):
        super().__init__(f"peer {address}: {cause}")
        self.address = address
        self.cause = cause


class PeerClient:
    def __init__(
        self,
        info: PeerInfo,
        batch_wait_ms: float = 0.5,
        batch_limit: int = 1000,
        batch_timeout_ms: float = 500.0,
        metrics=None,
        channel_credentials=None,
    ):
        self.info = info
        self.batch_wait_s = batch_wait_ms / 1e3
        self.batch_limit = batch_limit
        self.timeout_s = batch_timeout_ms / 1e3
        self.metrics = metrics
        self._creds = channel_credentials
        self._channel: Optional[grpc.aio.Channel] = None
        self._queue: List[Tuple[pb.RateLimitReq, asyncio.Future]] = []
        self._flush_task: Optional[asyncio.Task] = None
        self._inflight = 0
        self._closed = False
        self.last_errs: collections.deque = collections.deque(maxlen=LAST_ERRS_CAP)

    # ------------------------------------------------------------- transport
    def _chan(self) -> grpc.aio.Channel:
        if self._channel is None:
            opts = [
                ("grpc.max_receive_message_length", 1 << 20),  # daemon.go:133
            ]
            if self._creds is not None:
                self._channel = grpc.aio.secure_channel(
                    self.info.grpc_address, self._creds, options=opts
                )
            else:
                self._channel = grpc.aio.insecure_channel(
                    self.info.grpc_address, options=opts
                )
        return self._channel

    def _record_err(self, exc: BaseException) -> None:
        self.last_errs.append((time.monotonic(), str(exc)))

    def recent_errors(self) -> List[str]:
        cutoff = time.monotonic() - LAST_ERRS_TTL_S
        return [msg for ts, msg in self.last_errs if ts >= cutoff]

    async def _unary(self, path: str, req, resp_cls, timeout: Optional[float] = None):
        call = self._chan().unary_unary(
            path,
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=resp_cls.FromString,
        )
        try:
            return await call(req, timeout=timeout or self.timeout_s)
        except BaseException as exc:
            self._record_err(exc)
            raise PeerError(self.info.grpc_address, exc) from exc

    # ------------------------------------------------------------ peer RPCs
    async def get_peer_rate_limits(
        self, req: "peers_pb.GetPeerRateLimitsReq", timeout: Optional[float] = None
    ) -> "peers_pb.GetPeerRateLimitsResp":
        return await self._unary(
            GET_PEER_RATE_LIMITS, req, peers_pb.GetPeerRateLimitsResp, timeout
        )

    async def update_peer_globals(
        self, req: "peers_pb.UpdatePeerGlobalsReq", timeout: Optional[float] = None
    ) -> "peers_pb.UpdatePeerGlobalsResp":
        return await self._unary(
            UPDATE_PEER_GLOBALS, req, peers_pb.UpdatePeerGlobalsResp, timeout
        )

    # ------------------------------------------------- forwarding (batched)
    async def get_peer_rate_limit(self, item: "pb.RateLimitReq") -> "pb.RateLimitResp":
        """Forward one item to this peer. BATCHING (default) coalesces into
        the 500 µs / 1000-item window; NO_BATCHING and GLOBAL-accumulated
        sends go direct (reference peer_client.go:126-162)."""
        if self._closed:
            raise PeerError(self.info.grpc_address, RuntimeError("peer client closed"))
        # propagate the active trace to the owner via request metadata
        # (reference peer_client.go:140-142, 364-367)
        tracing.inject(item.metadata)
        if has_behavior(item.behavior, Behavior.NO_BATCHING):
            resp = await self.get_peer_rate_limits(
                peers_pb.GetPeerRateLimitsReq(requests=[item])
            )
            if len(resp.rate_limits) != 1:
                raise PeerError(
                    self.info.grpc_address,
                    RuntimeError("expected 1 rate limit in response"),
                )
            return resp.rate_limits[0]
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._queue.append((item, fut))
        if self.metrics is not None:
            self.metrics.batch_queue_length.set(len(self._queue))
        if len(self._queue) >= self.batch_limit:
            self._kick(immediate=True)
        else:
            self._kick(immediate=False)
        return await fut

    def _kick(self, immediate: bool) -> None:
        if self._flush_task is not None and not self._flush_task.done():
            if immediate:
                self._flush_task.cancel()
            else:
                return
        self._flush_task = asyncio.get_running_loop().create_task(
            self._flush_after(0.0 if immediate else self.batch_wait_s)
        )

    async def _flush_after(self, delay: float) -> None:
        if delay > 0:
            try:
                await asyncio.sleep(delay)
            except asyncio.CancelledError:
                return
        await self._flush()

    async def _flush(self) -> None:
        batch = self._queue[: self.batch_limit]
        self._queue = self._queue[self.batch_limit :]
        if self.metrics is not None:
            self.metrics.batch_queue_length.set(len(self._queue))
        if not batch:
            return
        if self._queue:
            self._kick(immediate=len(self._queue) >= self.batch_limit)
        self._inflight += 1
        try:
            req = peers_pb.GetPeerRateLimitsReq(requests=[i for i, _ in batch])
            try:
                resp = await self.get_peer_rate_limits(req)
                if len(resp.rate_limits) != len(batch):
                    raise PeerError(
                        self.info.grpc_address,
                        RuntimeError("mismatched response count"),
                    )
                for (item, fut), r in zip(batch, resp.rate_limits):
                    if not fut.done():
                        fut.set_result(r)
            except BaseException as exc:
                for _, fut in batch:
                    if not fut.done():
                        fut.set_exception(
                            exc
                            if isinstance(exc, PeerError)
                            else PeerError(self.info.grpc_address, exc)
                        )
        finally:
            self._inflight -= 1

    # -------------------------------------------------------------- shutdown
    async def shutdown(self) -> None:
        """Drain: flush the queue, wait for in-flight sends, close the
        channel (reference peer_client.go:415-451)."""
        self._closed = True
        while self._queue or self._inflight:
            if self._flush_task is not None and not self._flush_task.done():
                self._flush_task.cancel()
            await self._flush()
            await asyncio.sleep(0)
        if self._channel is not None:
            await self._channel.close()
            self._channel = None
