"""Prometheus metrics for the daemon — load-bearing for convergence tests.

The reference's functional suite asserts distributed behavior by scraping each
node's /metrics endpoint and checking exact counter values (reference
functional_test.go:1760-2167 via getMetrics/waitForBroadcast; series catalog
docs/prometheus.md:17-43). This module exposes the same-named series backed by
the TPU engine's host-side counters, on a PRIVATE registry per daemon so an
in-process test cluster scrapes N independent endpoints.
"""

from __future__ import annotations

import logging

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    Summary,
    generate_latest,
)
from prometheus_client.core import CounterMetricFamily
from prometheus_client.openmetrics import exposition as om_exposition
from prometheus_client.parser import text_string_to_metric_families

# one bucket scheme for every request/stage-latency histogram on the serving
# path (stage_duration since PR 6; grpc_request_duration/batch_send_duration
# since the observability PR — Summaries hid exactly the tails the serving
# plane is judged on, and Summaries cannot carry OpenMetrics exemplars)
LATENCY_BUCKETS = (
    1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 10.0,
)


class _OtelSpanCollector:
    """Surfaces the process-global OTLP exporter's own health as scrapeable
    series (gubernator_otel_spans_*): export failures used to be counted in
    exporter attributes nobody could scrape — a silently dead trace pipeline
    looked identical to an idle one. Reads tracing.exporter at collect time
    (zeros when no exporter is configured), so every daemon's registry in a
    shared process reports the shared pipeline, like the process collectors
    do."""

    def collect(self):
        from gubernator_tpu import tracing

        exp = tracing.exporter
        for name, doc, value in (
            ("exported", "Spans successfully exported over OTLP",
             getattr(exp, "exported", 0)),
            ("dropped", "Spans dropped by the bounded export buffer",
             getattr(exp, "dropped", 0)),
            ("export_errors", "Failed OTLP export POSTs (batch dropped)",
             getattr(exp, "export_errors", 0)),
        ):
            fam = CounterMetricFamily(f"gubernator_otel_spans_{name}", doc)
            fam.add_metric([], value)
            yield fam


class DaemonMetrics:
    """One daemon's metric family set (names mirror docs/prometheus.md).

    `metric_flags` (GUBER_METRIC_FLAGS, comma-separated) opts into optional
    runtime collectors, mirroring the reference's FlagOSMetrics /
    FlagGolangMetrics (reference flags.go:19-57, daemon.go:293-306):
      * "os"     → process collector (RSS/vsize, fds, CPU seconds, start
                   time) under the gubernator namespace;
      * "python" → interpreter runtime collectors (GC generations +
                   platform info), the analog of the reference's Go
                   collector ("golang" accepted as an alias).
    Unknown flags are logged and ignored, like the reference's
    getEnvMetricFlags."""

    def __init__(self, metric_flags: str = "") -> None:
        self.registry = CollectorRegistry()
        r = self.registry
        flags = {f.strip().lower() for f in metric_flags.split(",") if f.strip()}
        for bad in sorted(flags - {"os", "python", "golang"}):
            logging.getLogger("gubernator_tpu.metrics").error(
                "invalid flag %r for GUBER_METRIC_FLAGS; valid options are "
                "['os', 'python', 'golang']", bad,
            )
        if "os" in flags:
            from prometheus_client import process_collector

            process_collector.ProcessCollector(
                namespace="gubernator", registry=r
            )
        if flags & {"python", "golang"}:
            from prometheus_client import gc_collector, platform_collector

            gc_collector.GCCollector(registry=r)
            platform_collector.PlatformCollector(registry=r)
        # --- request plane (grpc_stats.go:41-131 analog)
        self.grpc_request_counts = Counter(
            "gubernator_grpc_request_counts",
            "The count of gRPC/HTTP requests",
            ["method", "status"],
            registry=r,
        )
        self.grpc_request_duration = Histogram(
            # a HISTOGRAM (was a Summary): request-plane TAILS are the
            # serving plane's acceptance metric, and histogram buckets can
            # carry trace-exemplars — _sum/_count series names unchanged
            "gubernator_grpc_request_duration",
            "Request handling duration in seconds",
            ["method"],
            registry=r,
            buckets=LATENCY_BUCKETS,
        )
        self.concurrent_checks = Gauge(
            "gubernator_concurrent_checks_counter",
            "Number of rate limit checks in flight",
            registry=r,
        )
        self.check_error_counter = Counter(
            "gubernator_check_error_counter",
            "Count of per-item errors returned",
            ["error"],
            registry=r,
        )
        self.over_limit_counter = Counter(
            "gubernator_over_limit_counter",
            "Count of OVER_LIMIT responses",
            registry=r,
        )
        # --- cache / table (lrucache.go:48-59 analog)
        self.cache_size = Gauge(
            "gubernator_cache_size",
            "Number of live keys in the device table",
            registry=r,
        )
        self.cache_access = Counter(
            "gubernator_cache_access_count",
            "Device table lookups",
            ["type"],  # hit | miss
            registry=r,
        )
        self.unexpired_evictions = Counter(
            "gubernator_unexpired_evictions_count",
            "Live (unexpired) items evicted for new keys",
            registry=r,
        )
        # the same kernel stat under the TPU-native name the tiering plane
        # documents (renders gubernator_tpu_evicted_live_total): each
        # increment is LIVE state displaced by the claim — silent loss
        # with tiering off, a demotion with it on (docs/tiering.md)
        self.evicted_live = Counter(
            "gubernator_tpu_evicted_live",
            "Live (unexpired) rows the decision kernel's claim displaced "
            "(kernel2 evicted_unexpired stat) — state loss when tiering "
            "is off, demote-on-evict events when it is on",
            registry=r,
        )
        # --- hot-set tiering (gubernator_tpu/tier/; docs/tiering.md)
        self.tier_demoted = Counter(
            # renders gubernator_tier_demoted_rows_total
            "gubernator_tier_demoted_rows",
            "Rows demoted from HBM to the host-RAM shadow, by trigger "
            "(evict = displaced by the claim, idle = background sweep)",
            ["reason"],  # evict | idle
            registry=r,
        )
        self.tier_promoted = Counter(
            "gubernator_tier_promoted_rows",
            "Shadow rows faulted back into HBM through the conservative "
            "merge ahead of a decide dispatch",
            registry=r,
        )
        self.tier_shed = Counter(
            "gubernator_tier_shed_rows",
            "Shadow rows dropped at the RAM byte bound with no spill "
            "file configured — counted state loss, identical to the "
            "pre-tiering eviction behavior",
            registry=r,
        )
        self.tier_promote_returned = Counter(
            "gubernator_tier_promote_returned_rows",
            "Promote rows returned to the shadow after their claim "
            "dropped (> K same-bucket promotes in one batch) — their "
            "decide that batch may have fresh-granted (docs/tiering.md "
            "bound)",
            registry=r,
        )
        self.tier_shadow_rows = Gauge(
            "gubernator_tier_shadow_rows",
            "Shadow rows resident in host RAM",
            registry=r,
        )
        self.tier_shadow_bytes = Gauge(
            "gubernator_tier_shadow_bytes",
            "Nominal bytes (64 B/row) of the RAM-resident shadow — "
            "bounded by GUBER_TIER_SHADOW_BYTES",
            registry=r,
        )
        self.tier_spilled_rows = Gauge(
            "gubernator_tier_spilled_rows",
            "Rows indexed in the shadow spill file (fault back with one "
            "seek+read)",
            registry=r,
        )
        # --- TPU dispatch plane (no reference analog; the kernel is ours)
        self.dispatch_count = Counter(
            "gubernator_tpu_dispatch_count",
            "Decision-kernel dispatches",
            registry=r,
        )
        self.dispatch_launches = Counter(
            # renders as gubernator_tpu_dispatch_launches_total
            "gubernator_tpu_dispatch_launches",
            "Decision-kernel launches by feed path: ring = per-slot "
            "dispatches from the request ring's host issue loop "
            "(service/ring.py), fused = multi-slot drain launches that "
            "retire up to GUBER_RING_DRAIN_K published slots each "
            "(ops/ring_drain.py), xla = the direct per-flush dispatch "
            "round-trip (docs/latency.md 'Launch budget')",
            ["path"],  # ring | fused | xla
            registry=r,
        )
        self.ring_occupancy = Gauge(
            "gubernator_tpu_ring_occupancy",
            "Request-ring slots published but not yet consumed — bounded "
            "by GUBER_RING_SLOTS; sustained saturation means submitters "
            "are in backpressure and the serving loop is the bottleneck",
            registry=r,
        )
        self.ring_drain_slots = Histogram(
            "gubernator_tpu_ring_drain_slots",
            "Published ring slots retired per fused drain launch — "
            "_sum/_count is the scrapeable launch-amortization factor "
            "(slots/launch; docs/latency.md 'Launch budget')",
            registry=r,
            buckets=(1, 2, 4, 8, 16, 32, 64),
        )
        self.dispatch_duration = Histogram(
            "gubernator_tpu_dispatch_duration",
            "Seconds per decision-kernel dispatch (host-observed)",
            registry=r,
            buckets=(0.0005, 0.001, 0.002, 0.005, 0.01, 0.025, 0.05, 0.1, 0.5, 2.5),
        )
        self.stage_duration = Histogram(
            "gubernator_tpu_stage_duration",
            "Seconds per serving-pipeline stage",
            # parse | queue | put | issue | fetch | encode, plus the mesh
            # ingress host-staging split shard_route | shard_pack |
            # shard_put (ShardedEngine host work per dispatch — route plan,
            # grid pack, device transfer; docs/latency.md "mesh ingress")
            # and the compact-wire codec stages wire_pack | wire_decode
            # (host encode of the 5-lane ingress grid / decode of the int32
            # egress; docs/latency.md "wire budget").
            # The request-ring plane adds ring_put (submit-side slot claim
            # + payload staging + ingress-fence publish) and ring_poll
            # (the egress-fence wait for the coalesced response) —
            # service/ring.py, docs/latency.md "Dispatch budget".
            # A HISTOGRAM (was a Summary) so per-stage TAILS are scrapeable:
            # _sum/_count keep the same series names the e2e bench means
            # used, and the buckets let BENCH_r06+ report per-stage p99 —
            # means hid exactly the tail behavior the serving plane is
            # judged on (docs/latency.md "Serving plane")
            ["stage"],
            registry=r,
            buckets=LATENCY_BUCKETS,
        )
        self.decisions_total = Counter(
            # renders as gubernator_tpu_decisions_total
            "gubernator_tpu_decisions",
            "Rate-limit decisions served, by algorithm (cascade levels "
            "count one decision per level — docs/algorithms.md)",
            ["algorithm"],  # token_bucket | leaky_bucket | gcra |
            # sliding_window | concurrency_lease | invalid
            registry=r,
        )
        self.cascade_depth = Histogram(
            "gubernator_tpu_cascade_depth",
            "Levels per cascaded multi-limit check (the request's own "
            "level plus its cascade entries)",
            registry=r,
            buckets=(2, 3, 4, 6, 8, 16, 32),
        )
        self.wire_bytes = Counter(
            # renders as gubernator_tpu_wire_bytes_total
            "gubernator_tpu_wire_bytes",
            "Bytes crossing the host-device boundary on the serving decide "
            "path (ingress grids and fetched outputs, whichever wire format "
            "ran) — bytes/decision is this over the dispatch row count",
            ["direction"],  # put | fetch
            registry=r,
        )
        self.dropped_rows = Counter(
            "gubernator_tpu_dropped_rows_count",
            "Rows whose decision could not be persisted after retries",
            registry=r,
        )
        self.unprocessed_dropped = Counter(
            "gubernator_tpu_unprocessed_dropped_count",
            "Rows that exhausted retries without ever reaching the decision "
            "kernel (a2a exchange-capacity drops) — absent from hit/miss "
            "counters by definition",
            registry=r,
        )
        self.a2a_overflow = Counter(
            # renders as gubernator_tpu_a2a_overflow_total
            "gubernator_tpu_a2a_overflow",
            "Rows the device-routed ownership exchange capacity-dropped "
            "before they reached a kernel (FLAG_UNPROCESSED — retried, so "
            "not lost; sustained growth means pair_capacity is undersized "
            "for the traffic skew, GUBER_A2A_CAPACITY_SIGMA)",
            ["impl"],  # ring | collective (GUBER_A2A_IMPL)
            registry=r,
        )
        self.global_wire_entries = Counter(
            # renders as gubernator_global_wire_sync_entries_total
            "gubernator_global_wire_sync_entries",
            "Inter-slice GLOBAL hit-sync entries by path: sent = shipped on "
            "the compact SyncGlobalsWire codec, fallback = shipped on the "
            "classic GetPeerRateLimits proto path (non-encodable batch or "
            "pre-compact peer), recv = decoded and applied as owner",
            ["direction"],  # sent | fallback | recv
            registry=r,
        )
        # --- batching front door (gubernator.go:98-112 analog)
        self.queue_length = Gauge(
            "gubernator_queue_length",
            "Items waiting in the front-door coalescing buffer",
            registry=r,
        )
        self.batch_send_duration = Histogram(
            # Histogram (was Summary): see grpc_request_duration
            "gubernator_batch_send_duration",
            "Seconds per coalesced front-door batch",
            registry=r,
            buckets=LATENCY_BUCKETS,
        )
        self.batch_queue_length = Gauge(
            "gubernator_batch_queue_length",
            "Items queued toward peers (forwarding)",
            registry=r,
        )
        # --- overload plane (service/batcher.py shed policy;
        # docs/robustness.md "Overload & QoS")
        self.shed_total = Counter(
            # renders as gubernator_tpu_shed_total
            "gubernator_tpu_shed",
            "Rate-limit rows shed by the front-door overload plane before "
            "reaching the engine, by reason (queue_full = bounded ring had "
            "no space the item could wait out, deadline = the item's "
            "enqueue deadline passed or the queue-wait estimate exceeded "
            "it, fairness = the item's tenant bucket was over its fair "
            "share of the window, preempted = evicted from the queue by a "
            "higher-priority arrival) and the item's priority tier "
            "(0 = best-effort .. 3 = shed last)",
            ["reason", "tier"],
            registry=r,
        )
        self.queue_wait_seconds = Histogram(
            "gubernator_tpu_queue_wait_seconds",
            "Seconds each admitted front-door batch waited in the coalesce "
            "queue before its dispatch began (per enqueued batch, not per "
            "chunk — the p99 of this series is the queueing half of the "
            "overload story; shed items never appear here)",
            registry=r,
            buckets=LATENCY_BUCKETS,
        )
        self.batch_send_retries = Counter(
            "gubernator_batch_send_retries",
            "Forwarded requests re-sent after peer errors/ownership moves",
            registry=r,
        )
        # --- peer fault tolerance (service/breaker.py; docs/robustness.md)
        self.circuit_breaker_state = Gauge(
            "gubernator_circuit_breaker_state",
            "Per-peer circuit breaker state (0=closed, 1=half-open, 2=open)",
            ["peer"],
            registry=r,
        )
        self.degraded_responses = Counter(
            "gubernator_degraded_response_count",
            "Responses served from local state because the owner was "
            "unreachable (DegradationPolicy.LOCAL)",
            registry=r,
        )
        self.global_requeued = Counter(
            "gubernator_global_requeue_count",
            "GLOBAL pending hits re-merged into the queue after a failed "
            "owner send (instead of dropped)",
            registry=r,
        )
        self.global_requeue_dropped = Counter(
            "gubernator_global_requeue_dropped_count",
            "GLOBAL pending hits dropped after exhausting requeue retries "
            "or hitting the queue cap",
            registry=r,
        )
        # --- multi-region replication (service/region_manager.py;
        # docs/robustness.md "Multi-region active-active")
        self.region_queue_length = Gauge(
            "gubernator_region_queue_length",
            "Pending cross-region hit deltas awaiting the region sync tick "
            "(summed over destination regions)",
            registry=r,
        )
        self.region_requeued = Counter(
            "gubernator_region_requeue_count",
            "Cross-region delta batches re-merged into the pending queue "
            "after a failed send (instead of dropped)",
            registry=r,
        )
        self.region_requeue_dropped = Counter(
            "gubernator_region_requeue_dropped_count",
            "Cross-region pending deltas dropped after exhausting requeue "
            "retries or hitting the queue cap",
            registry=r,
        )
        self.region_wire_entries = Counter(
            "gubernator_region_wire_entries_total",
            "Cross-region replication entries by path: sent/recv ride the "
            "compact SyncRegionsWire merge codec, fallback the classic "
            "GetPeerRateLimits proto path",
            ["direction"],  # sent | recv | fallback
            registry=r,
        )
        self.region_rows_merged = Counter(
            "gubernator_region_rows_merged_total",
            "Replicated rows applied through the conservative merge kernel "
            "(kernel2.merge2) on the region receive path",
            registry=r,
        )
        self.region_dedup_skipped = Counter(
            "gubernator_region_dedup_skipped_hits_total",
            "Duplicate cross-region hit deltas skipped EXACTLY by the "
            "per-source cumulative-counter ledger (re-shipped batches "
            "after a lost ack) — convergence stays exact under retries "
            "instead of degrading to under-grant",
            registry=r,
        )
        # --- edge quota leases (service/lease_manager.py; docs/leases.md):
        # the client-side admission plane's server-side accounting. The
        # outstanding gauge IS the live over-admission bound the delegation
        # adds on top of the limits (Σ tokens granted out, not yet returned
        # or expired).
        self.lease_ops = Counter(
            # renders as gubernator_lease_ops_total
            "gubernator_lease_ops",
            "Edge quota-lease operations by kind (acquire = new lease, "
            "renew = TTL/grant refresh, return = unused tokens back, deny "
            "= zero-token answer, expire = TTL reclamation of an "
            "unrenewed lease, unknown_return = return against a lease "
            "this daemon no longer remembers)",
            ["op"],  # acquire | renew | return | deny | expire |
            # unknown_return
            registry=r,
        )
        self.lease_tokens = Counter(
            # renders as gubernator_lease_tokens_total
            "gubernator_lease_tokens",
            "Edge quota-lease tokens by flow: granted out to edge "
            "limiters, returned unused, expired (reclaimed by TTL with "
            "the real-limit consumption kept — conservative)",
            ["kind"],  # granted | returned | expired
            registry=r,
        )
        self.lease_outstanding = Gauge(
            "gubernator_lease_outstanding_tokens",
            "Σ outstanding leased tokens across keys on this daemon — the "
            "live over-admission bound contribution (docs/leases.md)",
            registry=r,
        )
        self.lease_active = Gauge(
            "gubernator_lease_active",
            "Live (unexpired) edge quota leases tracked by this daemon",
            registry=r,
        )
        # --- topology-change handoff (service/handoff.py; docs/robustness.md
        # "Topology change & drain") — the rolling-restart chaos test asserts
        # row-count parity between phases across daemons, so phase labels are
        # load-bearing: extracted (rows leaving the source table) ≥
        # transferred (acked by a destination) = merged (applied by a
        # destination) + tombstoned (zeroed at the source post-ack);
        # snapshotted = the unacked remainder left for the shutdown
        # checkpoint.
        self.handoff_rows = Counter(
            "gubernator_handoff_rows",
            "Live rows moved through each ownership-handoff phase",
            ["phase"],  # extracted|transferred|merged|tombstoned|snapshotted
            registry=r,
        )
        self.handoff_duration = Histogram(
            "gubernator_handoff_duration",
            "Seconds per ownership-handoff round (extract → transfer → "
            "tombstone)",
            registry=r,
            buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0),
        )
        self.handoff_chunk_retries = Counter(
            "gubernator_handoff_chunk_retries",
            "TransferState chunks re-sent after a peer error",
            registry=r,
        )
        # --- GLOBAL behavior (global.go:53-79 analog; names must match, the
        # convergence tests key on them)
        self.global_send_duration = Summary(
            "gubernator_global_send_duration",
            "Seconds per async hit-sync send to owners",
            registry=r,
        )
        self.broadcast_duration = Summary(
            "gubernator_broadcast_duration",
            "Seconds per owner broadcast round",
            registry=r,
        )
        self.broadcast_counter = Counter(
            "gubernator_broadcast_counter",
            "Owner UpdatePeerGlobals broadcasts sent",
            ["condition"],  # broadcast | update_peer_globals (received)
            registry=r,
        )
        self.global_queue_length = Gauge(
            "gubernator_global_queue_length",
            "Pending async GLOBAL hits awaiting the sync tick",
            registry=r,
        )
        self.broadcast_queue_length = Gauge(
            "gubernator_broadcast_queue_length",
            "Owner-side keys queued for an authoritative broadcast",
            registry=r,
        )
        self.updates_installed = Counter(
            "gubernator_update_peer_globals_installed",
            "Authoritative GLOBAL statuses installed from owner broadcasts",
            registry=r,
        )
        # --- mesh-global collective plane (parallel/global_sync.py; the
        # in-mesh analog of the global.go series — convergence tests scrape
        # these for exact counts, like waitForBroadcast does)
        self.mesh_sync_rounds = Counter(
            "gubernator_mesh_sync_rounds",
            "Collective GLOBAL sync rounds executed over the device mesh",
            registry=r,
        )
        self.mesh_broadcasts_applied = Counter(
            "gubernator_mesh_broadcasts_applied",
            "GLOBAL entries applied+broadcast as owner during mesh sync",
            registry=r,
        )
        self.mesh_updates_installed = Counter(
            "gubernator_mesh_updates_installed",
            "Authoritative GLOBAL statuses installed into replica tables",
            registry=r,
        )
        self.mesh_hits_queued = Counter(
            "gubernator_mesh_global_hits_queued",
            "GLOBAL hits accumulated for the collective sync tick",
            registry=r,
        )
        self.mesh_global_queue_length = Gauge(
            "gubernator_mesh_global_queue_length",
            "Pending mesh-GLOBAL outbox entries awaiting the collective sync",
            registry=r,
        )
        self.created_at_clamped = Counter(
            "gubernator_created_at_clamped_count",
            "Requests whose client created_at was outside the skew tolerance",
            registry=r,
        )
        # --- device-side table telemetry (ops/telemetry.py; the background
        # scan EngineRunner.table_telemetry feeds via observe_table). These
        # are SNAPSHOT gauges, not event counters: each scan replaces the
        # previous values; distribution families use a bucket label like a
        # histogram's `le` but stay gauges because the population they
        # describe (live keys right now) shrinks as well as grows.
        self.table_live_keys = Gauge(
            "gubernator_tpu_table_live_keys",
            "Live (non-empty, unexpired) keys at the last telemetry scan",
            registry=r,
        )
        self.table_occupied_slots = Gauge(
            "gubernator_tpu_table_occupied_slots",
            "Occupied slots (live + expired-not-yet-evicted)",
            registry=r,
        )
        self.table_capacity = Gauge(
            "gubernator_tpu_table_capacity",
            "Total table slots (buckets x slots-per-bucket)",
            registry=r,
        )
        self.table_load_factor = Gauge(
            "gubernator_tpu_table_load_factor",
            "live_keys / capacity — eviction pressure precursor (buckets "
            "degrade past ~0.6)",
            registry=r,
        )
        self.table_over_fraction = Gauge(
            "gubernator_tpu_table_over_fraction",
            "Fraction of live keys whose stored status is OVER_LIMIT",
            registry=r,
        )
        self.table_bucket_occupancy = Gauge(
            "gubernator_tpu_table_bucket_occupancy",
            "Buckets holding exactly `slots` live entries (collision "
            "pressure: mass at slots=8 predicts unexpired_evictions)",
            ["slots"],  # "0".."8"
            registry=r,
        )
        self.table_probe_depth = Gauge(
            "gubernator_tpu_table_probe_depth",
            "Live keys by their bucket's occupancy (a lookup gathers the "
            "whole bucket row — depth is the key's collision exposure)",
            ["depth"],  # "1".."8"
            registry=r,
        )
        self.table_block_fill = Gauge(
            "gubernator_tpu_table_block_fill",
            "Sweep-block fill-fraction histogram (64-bucket blocks, decile "
            "bins) — hot-block skew the sparse write kernel sees",
            ["decile"],  # "0".."9"
            registry=r,
        )
        self.table_ttl_horizon = Gauge(
            "gubernator_tpu_table_ttl_horizon",
            "Live keys expiring within the horizon (cumulative; le in "
            "seconds) — how much of the table frees itself soon",
            ["le"],
            registry=r,
        )
        self.table_remaining_frac = Gauge(
            "gubernator_tpu_table_remaining_frac",
            "Live keys with remaining/limit at or below the bound "
            "(cumulative) — admission headroom distribution",
            ["le"],
            registry=r,
        )
        self.table_scan_duration = Histogram(
            "gubernator_tpu_table_scan_duration",
            "Seconds per background telemetry scan (launch to decoded "
            "snapshot; the scan overlaps serving dispatches)",
            registry=r,
            buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0),
        )
        self.table_hbm_bytes_per_decision = Gauge(
            "gubernator_table_hbm_bytes_per_decision",
            "Modeled HBM bytes the decide path's table walk moves per "
            "decision (worst case) at the engine's current slot layout, "
            "write mode, probe kernel and last dispatch geometry "
            "(ops/pallas_probe.hbm_bytes_per_decision) — the roofline "
            "denominator behind the decisions/s record (docs/kernel.md)",
            registry=r,
        )
        # --- durability plane (service/checkpoint.py; docs/durability.md):
        # the incremental checkpoint loop's cost, volume, and freshness —
        # kind=delta for epoch frames, kind=base for compactions/shutdown
        # snapshots. epoch_age is THE recovery-bound signal: a kill -9 loses
        # at most the writes admitted in that window.
        self.checkpoint_duration = Histogram(
            "gubernator_tpu_checkpoint_duration_seconds",
            "Seconds per checkpoint operation (dirty-block extract + frame "
            "append, or base compaction)",
            ["kind"],  # delta | base
            registry=r,
            buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0),
        )
        self.checkpoint_bytes = Counter(
            # renders as gubernator_tpu_checkpoint_bytes_total
            "gubernator_tpu_checkpoint_bytes",
            "Bytes written to the checkpoint plane (delta frames vs base "
            "snapshots) — delta bytes track the write rate, not table size",
            ["kind"],  # delta | base
            registry=r,
        )
        self.checkpoint_rows = Counter(
            # renders as gubernator_tpu_checkpoint_rows_total
            "gubernator_tpu_checkpoint_rows",
            "Live slot rows captured per checkpoint kind",
            ["kind"],  # delta | base
            registry=r,
        )
        self.checkpoint_epoch_age = Gauge(
            "gubernator_tpu_checkpoint_epoch_age_seconds",
            "Seconds since the last durable checkpoint epoch — the upper "
            "bound on state a kill -9 can lose right now",
            registry=r,
        )
        self.checkpoint_errors = Counter(
            # renders as gubernator_tpu_checkpoint_errors_total
            "gubernator_tpu_checkpoint_errors",
            "Failed checkpoint operations by stage (the dirty set is "
            "re-armed on delta failures, so dirt is deferred, not lost)",
            ["stage"],  # delta | base | restore | shutdown
            registry=r,
        )
        # --- GLOBAL convergence lag (docs/observability.md): age of the
        # oldest un-synced GLOBAL hit across the cross-daemon queue
        # (service/global_manager.py) and the mesh outbox
        # (parallel/global_sync.PendingHits) — the signal the multi-region
        # reconcile roadmap item is judged on. 0 = nothing pending.
        self.global_sync_staleness = Gauge(
            "gubernator_global_sync_staleness_seconds",
            "Age in seconds of the oldest GLOBAL hit not yet synced to its "
            "owner (cross-daemon queue and mesh outbox)",
            registry=r,
        )
        # region-plane convergence lag, built the same way: age of the
        # oldest hit delta not yet acked by every remote region's owner —
        # survives requeues; a partitioned region's gauge grows for exactly
        # as long as the partition, then drains to 0 on heal
        self.region_sync_staleness = Gauge(
            "gubernator_region_sync_staleness_seconds",
            "Age in seconds of the oldest cross-region hit delta not yet "
            "replicated to every remote region",
            registry=r,
        )
        # OTLP exporter health (satellite: export failures were attributes
        # nobody could scrape)
        r.register(_OtelSpanCollector())

    def observe_engine(self, stats) -> None:
        """Refresh counter families from an EngineStats snapshot (engine
        counters are cumulative; prometheus Counters only go up, so set via
        delta)."""
        # Counters in prometheus_client can't be set; track last-seen and inc
        # the difference.
        last = getattr(self, "_last_engine", None)
        if last is None:
            last = dict(
                hits=0, misses=0, over=0, evic=0, dropped=0, disp=0, clamped=0
            )
        d_hits = stats.cache_hits - last["hits"]
        d_miss = stats.cache_misses - last["misses"]
        d_over = stats.over_limit - last["over"]
        d_evic = stats.evicted_unexpired - last["evic"]
        d_drop = stats.dropped - last["dropped"]
        d_disp = stats.dispatches - last["disp"]
        if d_hits > 0:
            self.cache_access.labels(type="hit").inc(d_hits)
        if d_miss > 0:
            self.cache_access.labels(type="miss").inc(d_miss)
        if d_over > 0:
            self.over_limit_counter.inc(d_over)
        if d_evic > 0:
            self.unexpired_evictions.inc(d_evic)
            self.evicted_live.inc(d_evic)
        if d_drop > 0:
            self.dropped_rows.inc(d_drop)
        if d_disp > 0:
            self.dispatch_count.inc(d_disp)
        d_clamp = stats.created_at_clamped - last.get("clamped", 0)
        if d_clamp > 0:
            self.created_at_clamped.inc(d_clamp)
        d_unproc = stats.unprocessed_dropped - last.get("unproc", 0)
        if d_unproc > 0:
            self.unprocessed_dropped.inc(d_unproc)
        self._last_engine = dict(
            hits=stats.cache_hits,
            misses=stats.cache_misses,
            over=stats.over_limit,
            evic=stats.evicted_unexpired,
            dropped=stats.dropped,
            disp=stats.dispatches,
            clamped=stats.created_at_clamped,
            unproc=stats.unprocessed_dropped,
        )

    def observe_global(self, gs) -> None:
        """Refresh mesh-global counters from a GlobalStats snapshot (same
        delta pattern as observe_engine). NOT thread-safe: every caller runs
        on the EngineRunner thread (dispatch path and sync path both), which
        serializes the _last_global read-modify-write."""
        last = getattr(self, "_last_global", None)
        if last is None:
            last = dict(rounds=0, bcast=0, inst=0, queued=0)
        d = gs.sync_rounds - last["rounds"]
        if d > 0:
            self.mesh_sync_rounds.inc(d)
        d = gs.broadcasts_applied - last["bcast"]
        if d > 0:
            self.mesh_broadcasts_applied.inc(d)
        d = gs.updates_installed - last["inst"]
        if d > 0:
            self.mesh_updates_installed.inc(d)
        d = gs.hits_queued - last["queued"]
        if d > 0:
            self.mesh_hits_queued.inc(d)
        self.mesh_global_queue_length.set(gs.send_queue_length)
        self._last_global = dict(
            rounds=gs.sync_rounds,
            bcast=gs.broadcasts_applied,
            inst=gs.updates_installed,
            queued=gs.hits_queued,
        )

    def observe_table(self, snap) -> None:
        """Publish one table-telemetry snapshot (ops/telemetry.TableSnapshot)
        into the gubernator_tpu_table_* families. Snapshot semantics: every
        series is overwritten; a shrinking table shrinks its gauges."""
        from gubernator_tpu.ops.telemetry import REMAIN_EDGES, TTL_EDGES_MS

        self.table_live_keys.set(snap.live_keys)
        self.table_occupied_slots.set(snap.occupied_slots)
        self.table_capacity.set(snap.capacity)
        self.table_load_factor.set(snap.load_factor)
        self.table_over_fraction.set(snap.over_fraction)
        for j, v in enumerate(snap.bucket_occupancy):
            self.table_bucket_occupancy.labels(slots=str(j)).set(v)
        for j, v in enumerate(snap.probe_depth, start=1):
            self.table_probe_depth.labels(depth=str(j)).set(v)
        for j, v in enumerate(snap.block_fill):
            self.table_block_fill.labels(decile=str(j)).set(v)
        for e, v in zip(TTL_EDGES_MS, snap.ttl_horizon):
            self.table_ttl_horizon.labels(le=str(e // 1000)).set(v)
        self.table_ttl_horizon.labels(le="+Inf").set(snap.live_keys)
        for e, v in zip(REMAIN_EDGES, snap.remaining_frac):
            self.table_remaining_frac.labels(le=str(e)).set(v)
        self.table_remaining_frac.labels(le="+Inf").set(snap.live_keys)
        self.table_scan_duration.observe(snap.scan_ms / 1e3)

    def render(self, openmetrics: bool = False) -> bytes:
        """Prometheus exposition (the /metrics body). `openmetrics=True`
        emits the OpenMetrics format — the one that carries the exemplars
        (trace_ids on latency buckets); scrapers ask for it via the Accept
        header (service/server.py negotiates)."""
        if openmetrics:
            return om_exposition.generate_latest(self.registry)
        return generate_latest(self.registry)


def parse_metrics(text: str):
    """Scrape helper for tests: text exposition → {name: {labelset: value}}.
    The analog of the reference tests' expfmt parsing (functional_test.go:2245)."""
    out = {}
    for fam in text_string_to_metric_families(text):
        for sample in fam.samples:
            out.setdefault(sample.name, {})[
                tuple(sorted(sample.labels.items()))
            ] = sample.value
    return out
