from gubernator_tpu.service.daemon import Daemon
from gubernator_tpu.service.metrics import DaemonMetrics, parse_metrics

__all__ = ["Daemon", "DaemonMetrics", "parse_metrics"]
