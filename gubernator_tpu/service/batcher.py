"""Front-door request coalescing — the BatchWait tick.

The reference's defining serving mechanic: requests arriving within a 500 µs
window (up to a batch limit) coalesce into one batch (reference
peer_client.go:289-344 does this toward peers; config.go:138-140 sets the
window). Here the same window feeds the DEVICE: concurrent GetRateLimits
handlers enqueue column slices, and a dedicated flush loop concatenates them
into a single kernel dispatch — one TPU batch instead of one channel message
per item.

NO_BATCHING items bypass the window (reference peer_client.go:126-162's fast
path) by calling the runner directly.
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from typing import Deque, Optional, Tuple

import numpy as np

from gubernator_tpu.ops.batch import RequestColumns, ResponseColumns
from gubernator_tpu.ops.engine import ms_now
from gubernator_tpu.service.wire import concat_columns

# device batches coalesce far beyond the reference's 1000-item RPC cap — the
# kernel's throughput comes from large batches; this caps one dispatch.
DEFAULT_COALESCE_LIMIT = 16384


class Batcher:
    """Coalesce concurrent column batches into single engine dispatches.

    One long-lived flush loop (the runBatch goroutine analog,
    peer_client.go:289-344) wakes on enqueue, waits out the batch window
    unless the coalesce limit is already met, and flushes. Items enqueued
    while a flush's dispatch is in flight are picked up by the next loop
    iteration — nothing can strand in the queue.
    """

    def __init__(
        self,
        runner,
        batch_wait_ms: float = 0.5,
        coalesce_limit: int = DEFAULT_COALESCE_LIMIT,
        metrics=None,
        max_inflight: int = 4,
    ):
        self.runner = runner
        self.batch_wait_s = batch_wait_ms / 1e3
        self.coalesce_limit = coalesce_limit
        self.metrics = metrics
        # deque: _flush pops from the head per coalesced chunk — a list's
        # pop(0) is O(n) per pop, O(n²) across a backlog drain
        self._pending: Deque[Tuple[RequestColumns, asyncio.Future, float]] = (
            deque()
        )
        self._pending_rows = 0
        self._wake: Optional[asyncio.Event] = None
        self._loop_task: Optional[asyncio.Task] = None
        self._closed = False
        # pipelining: up to `max_inflight` dispatches run concurrently — the
        # engine thread issues N+1 while N executes on-device and N-1's
        # fetch streams back (host pack, device compute, fetch overlap)
        self._inflight_sem = asyncio.Semaphore(max_inflight)
        self._inflight: set = set()

    async def check(
        self, cols: RequestColumns, now_ms: Optional[int] = None
    ) -> ResponseColumns:
        """Enqueue a column batch; resolves with this batch's slice of the
        coalesced response."""
        now = now_ms if now_ms is not None else ms_now()
        # stamp unset created_at at ENQUEUE time (reference stamps at request
        # entry, gubernator.go:225-227), not at flush time
        cols = cols._replace(
            created_at=np.where(cols.created_at == 0, now, cols.created_at)
        )
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._pending.append((cols, fut, time.perf_counter()))
        self._pending_rows += cols.fp.shape[0]
        if self.metrics is not None:
            self.metrics.queue_length.set(self._pending_rows)
        if self._closed:
            # shutdown path: no loop to wake; dispatch inline
            await self._flush()
        else:
            if self._loop_task is None or self._loop_task.done():
                self._wake = asyncio.Event()
                self._loop_task = loop.create_task(self._run())
            self._wake.set()
        return await fut

    async def _run(self) -> None:
        while not self._closed:
            await self._wake.wait()
            self._wake.clear()
            if not self._pending:
                continue
            if self._pending_rows < self.coalesce_limit and self.batch_wait_s > 0:
                await asyncio.sleep(self.batch_wait_s)
            await self._flush()

    async def _flush(self) -> None:
        # the coalesce limit is a real per-dispatch cap: flush in chunks of
        # whole enqueued batches (a single oversized enqueue dispatches
        # alone), bounding dispatch latency and compile-shape spread. Chunks
        # dispatch CONCURRENTLY up to the in-flight cap, and — crucially —
        # each chunk forms AFTER its in-flight slot frees: requests arriving
        # while every slot is busy keep coalescing into the next chunk, so
        # backpressure produces FEWER, LARGER dispatches instead of a queue
        # of tiny ones (the natural batching the serial design had).
        while self._pending:
            await self._inflight_sem.acquire()
            if not self._pending:  # drained while waiting for the slot
                self._inflight_sem.release()
                break
            chunk = [self._pending.popleft()]
            rows = chunk[0][0].fp.shape[0]
            while (
                self._pending
                and rows + self._pending[0][0].fp.shape[0] <= self.coalesce_limit
            ):
                entry = self._pending.popleft()
                chunk.append(entry)
                rows += entry[0].fp.shape[0]
            self._pending_rows -= rows
            task = asyncio.get_running_loop().create_task(
                self._dispatch_guarded(chunk)
            )
            self._inflight.add(task)
            task.add_done_callback(self._inflight.discard)
        # one clamped gauge update per flush, after the chunk loop — per-chunk
        # sets only churned the gauge with intermediate values
        if self.metrics is not None:
            self.metrics.queue_length.set(max(self._pending_rows, 0))

    async def _dispatch_guarded(self, chunk) -> None:
        try:
            await self._dispatch(chunk)
        finally:
            self._inflight_sem.release()

    async def _dispatch(self, batch) -> None:
        t0 = time.perf_counter()
        if self.metrics is not None:
            oldest = min(ts for _, _, ts in batch)
            self.metrics.stage_duration.labels(stage="queue").observe(
                t0 - oldest
            )
        cat = concat_columns([c for c, _, _ in batch])
        try:
            rc = await self.runner.check(cat)
        except Exception as exc:  # pragma: no cover - defensive
            for _, fut, _ in batch:
                if not fut.done():
                    fut.set_exception(exc)
            return
        if self.metrics is not None:
            self.metrics.batch_send_duration.observe(time.perf_counter() - t0)
        off = 0
        for cols, fut, _ in batch:
            n = cols.fp.shape[0]
            sl = slice(off, off + n)
            if not fut.done():
                fut.set_result(
                    ResponseColumns(
                        status=rc.status[sl],
                        limit=rc.limit[sl],
                        remaining=rc.remaining[sl],
                        reset_time=rc.reset_time[sl],
                        err=rc.err[sl],
                    )
                )
            off += n

    async def drain(self) -> None:
        """Stop the flush loop and flush anything pending (shutdown path).
        Lets in-flight dispatches finish rather than cancelling them —
        cancelled dispatches would strand their callers' futures."""
        self._closed = True
        if self._loop_task is not None and not self._loop_task.done():
            self._wake.set()
            await self._loop_task
        await self._flush()
        if self._inflight:
            await asyncio.gather(*list(self._inflight), return_exceptions=True)
