"""Front-door request coalescing — the multi-worker adaptive batch window.

The reference's defining serving mechanic: requests arriving within a 500 µs
window (up to a batch limit) coalesce into one batch (reference
peer_client.go:289-344 does this toward peers; config.go:138-140 sets the
window). Here the same window feeds the DEVICE: concurrent GetRateLimits
handlers enqueue column slices (or pre-parsed wire batches), and N flush
workers pull coalesced chunks off a bounded ring into the single engine
thread's prepare/issue/finish pipeline — one TPU batch instead of one
channel message per item.

Three serving-plane mechanics live here (docs/latency.md "Serving plane"):

* **Bounded ring.** Enqueues append to a deque capped at `max_queue_rows`;
  past the cap, callers await drain progress (backpressure) instead of
  growing an unbounded queue whose tail latency nobody sees until OOM.
* **N workers.** Each worker forms a chunk, dispatches it, and slices the
  coalesced response back onto its callers' futures — so chunk formation
  and response fan-out for dispatch K run in parallel with dispatch K+1's,
  keeping the engine's depth-N pipeline saturated instead of starving it
  behind one event-loop task.
* **Adaptive window.** Under load the window closes on accumulated
  rows/bytes (engine-sized dispatches), not a wall-clock tick; when the
  engine is idle the window closes immediately (light load pays no
  batching latency). `batch_wait_ms` remains the hard ceiling.

* **Overload plane** (docs/robustness.md "Overload & QoS"). Armed by
  `GUBER_OVERLOAD_DEADLINE_MS` (a ms value, or `auto` to derive the
  deadline from the engine's issue-stage EWMA — OVERLOAD_AUTO_DEADLINE_MULT
  below) or an inbound gRPC deadline; each enqueue carries a deadline and a
  priority tier (types.PRIORITY_SHIFT behavior bits). A full ring or a
  hopeless queue-wait estimate sheds the LOWEST tier first with a fast
  per-item OVER_LIMIT-style overload row (ops/batch.ERR_OVERLOAD) instead
  of queueing work whose answer nobody will wait for; a higher-tier arrival
  preempts queued lower-tier entries rather than being shed itself, which
  makes priority inversions zero by construction. Per-tenant fair admission
  (fingerprint buckets) caps any one tenant at its share of the window once
  the queue is under pressure. The admission estimate and fairness shares
  are COST-weighted (_payload_cost: cascade levels and lease rows dispatch
  more device work per row), so an expensive tenant cannot starve cheap
  traffic by staying under a raw row budget. With the knob unset and no
  inbound deadline, behavior is exactly the legacy unbounded backpressure.

NO_BATCHING items bypass the window (reference peer_client.go:126-162's fast
path) by calling the runner directly.
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from typing import Deque, List, Optional

import numpy as np

from gubernator_tpu import tracing
from gubernator_tpu.ops.batch import (
    ERR_OVERLOAD,
    RequestColumns,
    ResponseColumns,
)
from gubernator_tpu.ops.engine import ms_now
from gubernator_tpu.service import deadline as deadline_mod
from gubernator_tpu.service.wire import WireBatch, concat_columns
from gubernator_tpu.types import (
    CASCADE_LEVEL_MASK,
    CASCADE_LEVEL_SHIFT,
    PRIORITY_MASK,
    PRIORITY_SHIFT,
    Algorithm,
)

# device batches coalesce far beyond the reference's 1000-item RPC cap — the
# kernel's throughput comes from large batches; this caps one dispatch.
DEFAULT_COALESCE_LIMIT = 16384

# GUBER_OVERLOAD_DEADLINE_MS=auto: the per-item deadline is this multiple of
# the engine's issue-stage EWMA (runner.issue_ewma, the device-launch half of
# a dispatch), floored at shed_retry_ms. 200 launches of queue-wait headroom
# ≈ tens of ms on CPU loopback / low ms on TPU — deep enough that the door
# only closes under genuine backlog, shallow enough that a doomed caller gets
# its overload verdict while a retry is still useful (docs/robustness.md
# "Overload & QoS").
OVERLOAD_AUTO_DEADLINE_MULT = 200


def _payload_rows(payload) -> int:
    return (
        payload.rows
        if isinstance(payload, WireBatch)
        else payload.fp.shape[0]
    )


def _payload_cols(payload) -> RequestColumns:
    return payload.cols if isinstance(payload, WireBatch) else payload


def _payload_tier(payload) -> int:
    """The enqueue's priority tier: the MAX tier among its rows — a batch
    carrying any high-priority row is protected as a whole (shedding is
    per-enqueue; one RPC's batch shares one future)."""
    beh = _payload_cols(payload).behavior
    if beh.shape[0] == 0:
        return 0
    return int(((beh.astype(np.int64) >> PRIORITY_SHIFT) & PRIORITY_MASK).max())


def _payload_cost(payload) -> int:
    """The enqueue's dispatch cost in row-equivalents: 1 per row, plus the
    row's cascade depth (every extra level walks its own kernel row) and a
    +1 surcharge for concurrency-lease rows (lease acquire/renew carries
    install/reclaim work a plain bucket row doesn't). The overload door's
    admission estimate and fairness shares are denominated in this, not raw
    row count — a cascade-heavy tenant consumes its share proportionally to
    the device work it dispatches. For plain single-level traffic cost ==
    rows, so uniform workloads see exactly the legacy row-weighted door."""
    cols = _payload_cols(payload)
    if cols.fp.shape[0] == 0:
        return 0
    beh = cols.behavior.astype(np.int64)
    casc = (beh >> CASCADE_LEVEL_SHIFT) & CASCADE_LEVEL_MASK
    lease = (cols.algo == int(Algorithm.CONCURRENCY_LEASE)).astype(np.int64)
    return int((1 + casc + lease).sum())


def _payload_bucket(payload, buckets: int) -> int:
    """The enqueue's tenant bucket: its first row's fingerprint folded into
    `buckets` — key fingerprints are name+key hashes, so a tenant's
    namespace lands in a stable bucket without a host-side tenant table."""
    fp = _payload_cols(payload).fp
    if fp.shape[0] == 0:
        return 0
    return int(fp[0]) & (buckets - 1)


class _Entry:
    """One enqueued batch awaiting dispatch."""

    __slots__ = ("payload", "fut", "t_enq", "span", "rows", "cost", "tier",
                 "bucket", "deadline")

    def __init__(self, payload, fut, t_enq, span, rows, cost, tier, bucket,
                 deadline):
        self.payload = payload
        self.fut = fut
        self.t_enq = t_enq  # perf_counter at enqueue
        self.span = span
        self.rows = rows
        self.cost = cost  # row-equivalents (_payload_cost)
        self.tier = tier  # 0 (best-effort) .. 3 (shed last)
        self.bucket = bucket  # tenant fingerprint bucket
        self.deadline = deadline  # absolute monotonic instant, or None


class Batcher:
    """Coalesce concurrent column/wire batches into single engine dispatches.

    `workers` long-lived flush tasks (the runBatch goroutine analog,
    peer_client.go:289-344, N-way) wake on enqueue, wait out the adaptive
    batch window, and each flushes + fans out one chunk at a time. Items
    enqueued while every worker's dispatch is in flight keep coalescing —
    backpressure produces FEWER, LARGER dispatches instead of a queue of
    tiny ones. FIFO chunk formation preserves each request's contiguous
    slice of the coalesced response."""

    def __init__(
        self,
        runner,
        batch_wait_ms: float = 0.5,
        coalesce_limit: int = DEFAULT_COALESCE_LIMIT,
        metrics=None,
        max_inflight: int = 4,
        workers: int = 0,
        adaptive: bool = True,
        close_rows: int = 0,
        close_bytes: int = 1 << 20,
        max_queue_rows: int = 0,
        ring=None,
        overload_deadline_ms: float = 0.0,
        overload_deadline_auto: bool = False,
        tenant_share: float = 0.5,
        tenant_buckets: int = 64,
        shed_retry_ms: int = 25,
    ):
        self.runner = runner
        # device-resident request ring (service/ring.py): when armed,
        # all-wire chunks are staged into ring slots and consumed by the
        # persistent serving loop instead of paying a fresh dispatch
        # round-trip per flush; None = the direct path
        self.ring = ring
        self.batch_wait_s = batch_wait_ms / 1e3
        self.coalesce_limit = coalesce_limit
        self.metrics = metrics
        # worker count IS the dispatch concurrency cap: each worker runs one
        # dispatch at a time, so `workers` replaces the old in-flight
        # semaphore. Sized to the engine pipeline depth unless overridden.
        self.workers = workers if workers > 0 else max(1, max_inflight)
        self.adaptive = adaptive
        # adaptive close thresholds: rows defaults to one engine-sized
        # dispatch, bytes bounds parse-heavy wire traffic
        self.close_rows = close_rows if close_rows > 0 else coalesce_limit
        self.close_bytes = close_bytes
        self.max_queue_rows = (
            max_queue_rows if max_queue_rows > 0 else coalesce_limit * 8
        )
        # overload plane (docs/robustness.md "Overload & QoS"): the default
        # per-item deadline; 0 disarms everything but inbound-gRPC-deadline
        # bounding (legacy unbounded backpressure otherwise)
        self.overload_deadline_s = max(0.0, overload_deadline_ms) / 1e3
        # auto mode (GUBER_OVERLOAD_DEADLINE_MS=auto): armed with a deadline
        # derived per enqueue from the runner's issue-stage EWMA
        # (OVERLOAD_AUTO_DEADLINE_MULT × issue_ewma, floored at
        # shed_retry_ms) — self-tuning to what a launch costs here
        self.overload_deadline_auto = bool(overload_deadline_auto)
        self.armed = self.overload_deadline_s > 0 or self.overload_deadline_auto
        self.tenant_share = tenant_share
        # fairness bucket count, forced to a power of two (fp & (n-1) fold)
        tb = max(1, tenant_buckets)
        self.tenant_buckets = 1 << (tb - 1).bit_length()
        self.shed_retry_ms = shed_retry_ms
        # deque of _Entry: workers pop from the head per coalesced chunk —
        # a list's pop(0) is O(n) per pop, O(n²) across a backlog drain.
        # entry.span is the enqueueing request's trace context, linked to
        # the dispatch span that ends up serving it (batching breaks
        # parent-child causality; OTLP links restore it —
        # docs/observability.md).
        self._pending: Deque[_Entry] = deque()
        self._bucket_cost: dict = {}  # tenant bucket → queued cost units
        # EWMA of the drain rate (cost units/s over dispatch completions) —
        # the queue-wait estimate `pending_cost / rate` that sheds doomed
        # enqueues up front instead of letting them expire in the queue.
        # Cost units (_payload_cost), NOT raw rows: a cascade row drains
        # slower than a plain row, and the estimate must know that.
        self._drain_rate = 0.0
        self._drain_t = 0.0
        self._drain_cost = 0
        self._pending_rows = 0
        self._pending_cost = 0
        self._pending_bytes = 0
        self._wake: Optional[asyncio.Event] = None
        self._full: Optional[asyncio.Event] = None  # adaptive early close
        self._space: Optional[asyncio.Event] = None  # backpressure release
        self._worker_tasks: List[asyncio.Task] = []
        self._closed = False
        self._inflight = 0
        # introspection counters (CI serving smoke + tests read these)
        self.fused_dispatches = 0  # rode the fused wire→grid path
        self.column_dispatches = 0  # generic columns path
        self.wire_fallbacks = 0  # all-wire chunk that could NOT fuse
        self.ring_dispatches = 0  # all-wire chunk staged into the ring
        self.adaptive_closes = 0  # window closed on rows/bytes/idle engine
        self.window_expires = 0  # window closed on the wall-clock ceiling
        # adaptive-close reason split (the /v1/debug/pipeline payload):
        # rows/bytes thresholds, idle engine, freed dispatch slot
        self.close_reasons = {"rows": 0, "bytes": 0, "idle": 0, "slot": 0}
        # overload-plane counters (tests + /v1/debug/pipeline + CI gate)
        self.shed_rows = {
            "queue_full": 0, "deadline": 0, "fairness": 0, "preempted": 0
        }
        self.shed_by_tier = [0, 0, 0, 0]
        self.admitted_by_tier = [0, 0, 0, 0]
        # capacity sheds that left a strictly lower tier still queued —
        # zero by construction (preemption runs first); the CI overload
        # smoke gates this at exactly 0
        self.priority_inversions = 0

    # ------------------------------------------------------------- enqueue
    async def check(self, payload, now_ms: Optional[int] = None) -> ResponseColumns:
        """Enqueue a column batch (RequestColumns) or a pre-parsed wire
        batch (service/wire.WireBatch); resolves with this batch's slice of
        the coalesced response."""
        now = now_ms if now_ms is not None else ms_now()
        # stamp unset created_at at ENQUEUE time (reference stamps at request
        # entry, gubernator.go:225-227), not at flush time
        if isinstance(payload, WireBatch):
            cols = payload.cols
            payload = payload._replace(
                cols=cols._replace(
                    created_at=np.where(cols.created_at == 0, now, cols.created_at)
                )
            )
        else:
            payload = payload._replace(
                created_at=np.where(
                    payload.created_at == 0, now, payload.created_at
                )
            )
        rows = _payload_rows(payload)
        loop = asyncio.get_running_loop()
        if self._wake is None:
            self._wake = asyncio.Event()
            self._full = asyncio.Event()
            self._space = asyncio.Event()
        tier = _payload_tier(payload)
        cost = _payload_cost(payload)
        bucket = _payload_bucket(payload, self.tenant_buckets)
        deadline = self._item_deadline()
        entry = _Entry(
            payload, loop.create_future(), time.perf_counter(),
            tracing.current_span(), rows, cost, tier, bucket, deadline,
        )
        # per-tenant fair admission: once the queue is under pressure
        # (≥ half full), no tenant bucket may hold more than its share of
        # the window — one abusive tenant saturating the ring cannot starve
        # the rest (armed mode only). Shares are COST units against the
        # row-denominated window: a cascade-heavy tenant exhausts its share
        # in proportion to the device work it dispatches, so it cannot
        # starve cheap single-row traffic by staying under a raw row count.
        if (
            self.armed
            and self._pending_cost * 2 >= self.max_queue_rows
            and self._bucket_cost.get(bucket, 0) + cost
            > self.tenant_share * self.max_queue_rows
        ):
            return self._shed(entry, "fairness")
        # queue-wait estimate: work that cannot be served before its
        # deadline is answered NOW, not after expiring in the queue
        # (cost units over a cost-unit drain rate)
        if deadline is not None:
            remain = deadline - time.monotonic()
            if remain <= 0 or (
                self._drain_rate > 0
                and self._pending_cost / self._drain_rate > remain
            ):
                return self._shed(entry, "deadline")
        # bounded ring: callers past the cap wait for drain progress instead
        # of growing the queue without limit (an oversized single batch is
        # admitted alone rather than deadlocking). A higher-tier arrival
        # first PREEMPTS queued strictly-lower-tier entries (shed lowest
        # first) — capacity pressure falls on the lowest tier by
        # construction; an item with a deadline never waits past it.
        while (
            not self._closed
            and self._pending_rows > 0
            and self._pending_rows + rows > self.max_queue_rows
        ):
            if self.armed and self._preempt_lower(entry):
                break
            if deadline is None:
                self._space.clear()
                await self._space.wait()
                continue
            remain = deadline - time.monotonic()
            if remain <= 0:
                return self._shed(entry, "queue_full")
            self._space.clear()
            try:
                await asyncio.wait_for(self._space.wait(), remain)
            except asyncio.TimeoutError:
                return self._shed(entry, "queue_full")
        self._pending.append(entry)
        self._pending_rows += rows
        self._pending_cost += cost
        self._bucket_cost[bucket] = self._bucket_cost.get(bucket, 0) + cost
        self.admitted_by_tier[tier] += rows
        self._pending_bytes += (
            payload.nbytes if isinstance(payload, WireBatch) else 0
        )
        if self._closed:
            # shutdown path: no workers to wake; dispatch inline
            await self._flush_all()
        else:
            self._ensure_workers(loop)
            self._wake.set()
            if (
                self._pending_rows >= self.close_rows
                or self._pending_bytes >= self.close_bytes
            ):
                self._full.set()
        return await entry.fut

    # ------------------------------------------------------ overload plane
    def _item_deadline(self) -> Optional[float]:
        """This enqueue's absolute monotonic deadline: the tighter of the
        overload knob and the inbound gRPC deadline (service/deadline.py);
        None when neither applies — the legacy unbounded contract.

        Auto mode (GUBER_OVERLOAD_DEADLINE_MS=auto) derives the knob per
        enqueue: OVERLOAD_AUTO_DEADLINE_MULT × the runner's issue-stage
        EWMA, floored at shed_retry_ms (and at any explicit ms value also
        set). Re-evaluated every enqueue, so the door tracks the engine's
        actual launch cost as load and batch shapes shift."""
        knob_s = self.overload_deadline_s
        if self.overload_deadline_auto:
            knob_s = max(
                knob_s,
                self.shed_retry_ms / 1e3,
                OVERLOAD_AUTO_DEADLINE_MULT
                * getattr(self.runner, "issue_ewma", 0.0),
            )
        knob = time.monotonic() + knob_s if knob_s > 0 else None
        inbound = deadline_mod.inbound_deadline()
        if knob is None:
            return inbound
        if inbound is None:
            return knob
        return min(knob, inbound)

    def _shed(self, entry: _Entry, reason: str) -> ResponseColumns:
        """Answer an entry WITHOUT dispatching it: a fast per-item
        OVER_LIMIT-style overload row (ERR_OVERLOAD, reset_time = the
        suggested retry instant). The caller's RPC succeeds — overload is
        a per-item decision, like every other limit verdict."""
        self.shed_rows[reason] += entry.rows
        self.shed_by_tier[entry.tier] += entry.rows
        if reason in ("queue_full", "preempted") and any(
            e.tier < entry.tier for e in self._pending
        ):
            # should be unreachable (preemption sheds lowest-first); the
            # counter existing — and being gated at 0 in CI — is the proof
            self.priority_inversions += 1
        if self.metrics is not None:
            self.metrics.shed_total.labels(
                reason=reason, tier=str(entry.tier)
            ).inc(entry.rows)
        rc = self._overload_columns(entry.payload)
        if not entry.fut.done():
            entry.fut.set_result(rc)
        return rc

    def _overload_columns(self, payload) -> ResponseColumns:
        cols = _payload_cols(payload)
        n = cols.fp.shape[0]
        reset = ms_now() + self.shed_retry_ms
        return ResponseColumns(
            status=np.ones(n, dtype=np.int32),  # Status.OVER_LIMIT
            limit=cols.limit.astype(np.int64, copy=True),
            remaining=np.zeros(n, dtype=np.int64),
            reset_time=np.full(n, reset, dtype=np.int64),
            err=np.full(n, ERR_OVERLOAD, dtype=np.int8),
        )

    def _preempt_lower(self, entry: _Entry) -> bool:
        """Make room for a higher-tier arrival by evicting queued entries of
        STRICTLY lower tiers, lowest tier first then oldest first. Only
        evicts when the freed rows actually admit the newcomer (no pointless
        victims); returns True when space was made."""
        need = self._pending_rows + entry.rows - self.max_queue_rows
        victims = sorted(
            (e for e in self._pending if e.tier < entry.tier),
            key=lambda e: (e.tier, e.t_enq),
        )
        avail = sum(e.rows for e in victims)
        if avail < need:
            return False
        freed = 0
        chosen = []
        for v in victims:
            chosen.append(v)
            freed += v.rows
            if freed >= need:
                break
        for v in chosen:
            self._pending.remove(v)
            self._pending_rows -= v.rows
            self._pending_cost -= v.cost
            self._drop_bucket_cost(v)
            self._shed(v, "preempted")
        self._pending_bytes = sum(
            e.payload.nbytes
            for e in self._pending
            if isinstance(e.payload, WireBatch)
        )
        return True

    def _drop_bucket_cost(self, entry: _Entry) -> None:
        left = self._bucket_cost.get(entry.bucket, 0) - entry.cost
        if left > 0:
            self._bucket_cost[entry.bucket] = left
        else:
            self._bucket_cost.pop(entry.bucket, None)

    def _note_drained(self, cost: int) -> None:
        """Fold one dispatch completion into the drain-rate EWMA (cost
        units/s — the same units the queue-wait estimate divides by)."""
        now = time.monotonic()
        if self._drain_t == 0.0:
            self._drain_t = now
            self._drain_cost = cost
            return
        self._drain_cost += cost
        dt = now - self._drain_t
        if dt < 1e-4:
            return
        inst = self._drain_cost / dt
        self._drain_rate = (
            inst if self._drain_rate == 0.0
            else 0.7 * self._drain_rate + 0.3 * inst
        )
        self._drain_t = now
        self._drain_cost = 0

    def _ensure_workers(self, loop) -> None:
        self._worker_tasks = [t for t in self._worker_tasks if not t.done()]
        while len(self._worker_tasks) < self.workers:
            self._worker_tasks.append(
                loop.create_task(
                    self._run(), name=f"batcher-{len(self._worker_tasks)}"
                )
            )

    # ------------------------------------------------------------- workers
    async def _run(self) -> None:
        while not self._closed:
            if not self._pending:
                self._wake.clear()
                if self._pending:  # raced an enqueue between check and clear
                    continue
                await self._wake.wait()
                continue
            await self._window()
            chunk = self._take_chunk()
            if chunk is None:
                continue
            await self._dispatch(chunk)

    async def _window(self) -> None:
        """Hold the coalesce window open until it should close: on
        accumulated rows/bytes (engine-sized dispatch ready), on an idle
        engine (light load — why wait?), on a dispatch slot freeing (refill
        the pipeline), or on the `batch_wait_ms` wall-clock ceiling."""
        if self.batch_wait_s <= 0:
            return
        if (
            self._pending_rows >= self.close_rows
            or self._pending_bytes >= self.close_bytes
        ):
            self._close_adaptive()
            return
        if self.adaptive and self._inflight == 0:
            # engine idle: dispatching now beats waiting for company —
            # requests arriving during THIS dispatch coalesce into the next
            self.adaptive_closes += 1
            self.close_reasons["idle"] += 1
            return
        if not self.adaptive:
            await asyncio.sleep(self.batch_wait_s)
            return
        self._full.clear()
        if (
            self._pending_rows >= self.close_rows
            or self._pending_bytes >= self.close_bytes
        ):  # filled while clearing
            self._close_adaptive()
            return
        try:
            await asyncio.wait_for(self._full.wait(), self.batch_wait_s)
            self._close_adaptive()
        except asyncio.TimeoutError:
            self.window_expires += 1

    def _close_adaptive(self) -> None:
        """Count one adaptive close, attributed to what actually tripped it
        (rows/bytes threshold, else a freed dispatch slot re-evaluating)."""
        self.adaptive_closes += 1
        if self._pending_rows >= self.close_rows:
            self.close_reasons["rows"] += 1
        elif self._pending_bytes >= self.close_bytes:
            self.close_reasons["bytes"] += 1
        else:
            self.close_reasons["slot"] += 1

    def _take_chunk(self):
        """Pop a chunk of whole enqueued batches up to the coalesce limit
        (a single oversized enqueue dispatches alone), bounding dispatch
        latency and compile-shape spread. Armed mode orders the window by
        tier (highest first, FIFO within a tier) once a backlog has mixed
        tiers, and sheds deadline-expired entries instead of serving them
        — an answer after the caller stopped waiting is pure waste. One
        clamped gauge update per flush — per-enqueue sets only churned the
        gauge with intermediate values (hot-path metric cost at high
        request rates)."""
        if not self._pending:
            return None
        if (
            self.armed
            and len(self._pending) > 1
            and len({e.tier for e in self._pending}) > 1
        ):
            # stable sort: FIFO preserved within each tier
            self._pending = deque(
                sorted(self._pending, key=lambda e: -e.tier)
            )
        chunk = []
        rows = 0
        now = time.monotonic()
        while self._pending:
            head = self._pending[0]
            if chunk and rows + head.rows > self.coalesce_limit:
                break
            entry = self._pending.popleft()
            self._pending_rows -= entry.rows
            self._pending_cost -= entry.cost
            self._drop_bucket_cost(entry)
            if entry.deadline is not None and now > entry.deadline:
                self._shed(entry, "deadline")
                continue
            chunk.append(entry)
            rows += entry.rows
        self._pending_bytes = sum(
            e.payload.nbytes
            for e in self._pending
            if isinstance(e.payload, WireBatch)
        )
        if self._space is not None:
            self._space.set()
        if self.metrics is not None:
            self.metrics.queue_length.set(max(self._pending_rows, 0))
        return chunk if chunk else None

    # ------------------------------------------------------------ dispatch
    async def _dispatch(self, batch) -> None:
        self._inflight += 1
        # one `dispatch` span per flush: batching breaks request→engine
        # parent-child causality (N requests share one flush), so the flush
        # gets its OWN trace with stage child spans (queue here; put/issue/
        # fetch in the runner) and every request span gains an OTLP link to
        # it — minted only when spans actually export
        disp_span = tracing.new_span() if tracing.exporter is not None else None
        fused = False
        try:
            t0 = time.perf_counter()
            oldest = min(e.t_enq for e in batch)
            if self.metrics is not None:
                self.metrics.stage_duration.labels(stage="queue").observe(
                    t0 - oldest,
                    exemplar=(
                        {"trace_id": disp_span.trace_id} if disp_span else None
                    ),
                )
                # per-enqueue queue wait (the shed policy's p99 story):
                # "queue" above is per-CHUNK (its oldest member); these are
                # per admitted batch, the distribution deadlines cut into
                qw = self.metrics.stage_duration.labels(stage="queue_wait")
                for e in batch:
                    wait = t0 - e.t_enq
                    qw.observe(wait)
                    self.metrics.queue_wait_seconds.observe(wait)
            if disp_span is not None:
                q_ns = time.time_ns()
                tracing.record_span(
                    "queue", tracing.new_span(disp_span), disp_span.span_id,
                    q_ns - int((t0 - oldest) * 1e9), q_ns,
                )
            payloads = [e.payload for e in batch]
            rc = None
            if all(isinstance(p, WireBatch) for p in payloads):
                if self.ring is not None:
                    # ring path: stage the chunk into a request-ring slot;
                    # the persistent serving loop consumes it in ticket
                    # order through the SAME runner surface (byte-identical
                    # responses). A ring racing drain falls through to the
                    # direct path below — zero loss.
                    from gubernator_tpu.service.ring import RingClosed

                    try:
                        rc = await self.ring.submit(payloads, span=disp_span)
                        self.ring_dispatches += 1
                        fused = True
                    except RingClosed:
                        rc = None
                if rc is None:
                    # fused path: pre-packed parser lanes scatter straight
                    # into one staged compact grid
                    # (ops/engine.prepare_check_wire) — the request bytes
                    # are traversed exactly once end to end
                    rc = await self.runner.check_wire(
                        payloads, span=disp_span
                    )
                    if rc is not None:
                        self.fused_dispatches += 1
                        fused = True
                    else:
                        self.wire_fallbacks += 1
            if rc is None:
                cat = concat_columns([_payload_cols(p) for p in payloads])
                rc = await self.runner.check(cat, span=disp_span)
                self.column_dispatches += 1
        except Exception as exc:  # pragma: no cover - defensive
            for e in batch:
                if not e.fut.done():
                    e.fut.set_exception(exc)
            return
        finally:
            self._inflight -= 1
            self._note_drained(sum(e.cost for e in batch))
            if self._full is not None:
                # a slot freed: a worker holding its window open should
                # re-evaluate — refilling the pipeline beats waiting
                self._full.set()
        if self.metrics is not None:
            self.metrics.batch_send_duration.observe(
                time.perf_counter() - t0,
                exemplar=(
                    {"trace_id": disp_span.trace_id} if disp_span else None
                ),
            )
        if disp_span is not None:
            # request spans → dispatch span links (registered while their
            # scopes are still open: the futures resolve after this), and
            # the dispatch span itself links back to every distinct request
            req_spans = [e.span for e in batch if e.span is not None]
            for rs in req_spans:
                tracing.add_span_link(rs, disp_span)
            end_ns = time.time_ns()
            tracing.record_span(
                "dispatch", disp_span, "",
                end_ns - int((time.perf_counter() - oldest) * 1e9), end_ns,
                attributes={
                    "batch.rows": sum(e.rows for e in batch),
                    "batch.requests": len(batch),
                    "batch.fused": fused,
                },
                links=req_spans,
            )
        off = 0
        for e in batch:
            payload, fut = e.payload, e.fut
            n = e.rows
            sl = slice(off, off + n)
            if not fut.done():
                fut.set_result(
                    ResponseColumns(
                        status=rc.status[sl],
                        limit=rc.limit[sl],
                        remaining=rc.remaining[sl],
                        reset_time=rc.reset_time[sl],
                        err=rc.err[sl],
                    )
                )
            off += n

    def arm_overload(self, deadline_ms: float) -> None:
        """(Re)arm or disarm the overload door at runtime. The scenario
        harness (bench.py) warms XLA chunk shapes through the OPEN door and
        only then arms it for the timed windows — a warm wave shed by the
        armed door never dispatches, leaving its chunk shape uncompiled so
        the compile lands inside a measured step disguised as queueing
        latency. Per-entry deadlines are stamped at enqueue, so flipping
        between windows never retro-affects queued items."""
        self.overload_deadline_s = max(0.0, deadline_ms) / 1e3
        self.armed = self.overload_deadline_s > 0 or self.overload_deadline_auto

    def debug(self) -> dict:
        """Live front-door state for /v1/debug/pipeline (docs/observability.md):
        ring depth, worker liveness, dispatch-path counters, and WHY the
        adaptive window has been closing."""
        return {
            "pending_requests": len(self._pending),
            "pending_rows": self._pending_rows,
            "pending_cost": self._pending_cost,
            "pending_bytes": self._pending_bytes,
            "inflight": self._inflight,
            "workers": self.workers,
            "workers_alive": sum(1 for t in self._worker_tasks if not t.done()),
            "adaptive": self.adaptive,
            "batch_wait_ms": self.batch_wait_s * 1e3,
            "coalesce_limit": self.coalesce_limit,
            "close_rows": self.close_rows,
            "close_bytes": self.close_bytes,
            "max_queue_rows": self.max_queue_rows,
            "fused_dispatches": self.fused_dispatches,
            "column_dispatches": self.column_dispatches,
            "wire_fallbacks": self.wire_fallbacks,
            "ring_dispatches": self.ring_dispatches,
            "ring": self.ring.debug() if self.ring is not None else None,
            "adaptive_closes": self.adaptive_closes,
            "window_expires": self.window_expires,
            "close_reasons": dict(self.close_reasons),
            "overload_armed": self.armed,
            "overload_deadline_ms": self.overload_deadline_s * 1e3,
            "overload_deadline_auto": self.overload_deadline_auto,
            "tenant_share": self.tenant_share,
            "tenant_buckets": self.tenant_buckets,
            "shed_rows": dict(self.shed_rows),
            "shed_by_tier": list(self.shed_by_tier),
            "admitted_by_tier": list(self.admitted_by_tier),
            "priority_inversions": self.priority_inversions,
            "drain_rate_cost_per_s": self._drain_rate,
            "closed": self._closed,
        }

    async def _flush_all(self) -> None:
        """Drain every pending chunk inline (shutdown path)."""
        while self._pending:
            chunk = self._take_chunk()
            if chunk is None:
                break
            await self._dispatch(chunk)

    async def drain(self) -> None:
        """Stop the flush workers and flush anything pending (shutdown
        path). Lets in-flight dispatches finish rather than cancelling them
        — cancelled dispatches would strand their callers' futures."""
        self._closed = True
        if self._wake is not None:
            self._wake.set()
            self._full.set()
            self._space.set()
        if self._worker_tasks:
            await asyncio.gather(*self._worker_tasks, return_exceptions=True)
            self._worker_tasks = []
        await self._flush_all()
