"""EngineRunner: the single-writer dispatch thread.

The device table has exactly one owner — the kernel — and the host side
funnels every mutation through ONE thread, the TPU analog of the reference's
"each worker owns its cache, no mutexes" rule (reference workers.go:19-37).
asyncio handlers await engine work through this runner; ordering of submitted
jobs is FIFO, which is what makes the front-door batcher's request-order
contract hold.
"""

from __future__ import annotations

import asyncio
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

import numpy as np

from gubernator_tpu import tracing
from gubernator_tpu.ops.batch import RequestColumns, ResponseColumns
from gubernator_tpu.ops.engine import LocalEngine


def _exemplar(span) -> Optional[dict]:
    """OpenMetrics exemplar payload for a stage observation: the dispatch
    span's trace_id, so a p99 bucket is one click from its trace. None when
    the dispatch is untraced (no exporter) — prometheus_client treats None
    as no-exemplar."""
    return {"trace_id": span.trace_id} if span is not None else None


# gubernator_tpu_decisions_total label values (types.Algorithm order)
_ALGO_LABELS = (
    "token_bucket", "leaky_bucket", "gcra", "sliding_window",
    "concurrency_lease", "invalid",
)


class EngineRunner:
    """Serializes engine table access onto one thread; async façade.

    The pipelined path (`check`) splits each request batch into an ISSUE
    half on the engine thread (pack + enqueue kernel dispatches, no fetch)
    and a FINISH half on a small fetch pool (materialize outputs) — so the
    engine thread packs dispatch N+1 while N executes on-device and N-1's
    results stream back. Rare feedback (claim drops, Store rehydrates) runs
    back on the engine thread via the `fixup` hook; stats deltas are folded
    in on the engine thread too, keeping every engine mutation single-
    writer."""

    def __init__(self, engine: LocalEngine, metrics=None, fetch_workers: int = 4):
        self.engine = engine
        self.metrics = metrics
        self._exec = ThreadPoolExecutor(max_workers=1, thread_name_prefix="engine")
        # sized to the configured pipeline depth: fewer fetch workers than
        # in-flight dispatches would silently cap the pipeline
        self._fetch = ThreadPoolExecutor(
            max_workers=max(1, fetch_workers), thread_name_prefix="fetch"
        )
        # preparation pool separate from the fetch pool: finish() blocks a
        # worker for a device round trip, and a prepare stuck behind blocked
        # fetchers would stall the whole pipeline's intake
        self._prep = ThreadPoolExecutor(
            max_workers=max(2, fetch_workers // 2), thread_name_prefix="prep"
        )
        # background telemetry fetches get their OWN single thread (lazy):
        # a table scan parked on a fetch worker would steal a pipeline slot
        self._telemetry: Optional[ThreadPoolExecutor] = None
        # checkpoint-extract fetches likewise (lazy): the dirty-block
        # fetch overlaps serving dispatches, never competes with them
        self._ckpt: Optional[ThreadPoolExecutor] = None
        # cumulative per-algorithm decision counts (the debug-plane mirror
        # of gubernator_tpu_decisions_total; /v1/debug/pipeline)
        self.algo_counts = {k: 0 for k in _ALGO_LABELS}
        # EWMA of the issue stage (seconds) — the device-launch half of a
        # dispatch. The batcher's auto overload deadline
        # (GUBER_OVERLOAD_DEADLINE_MS=auto) is derived from this: a queue
        # estimate denominated in what a launch actually costs on THIS
        # deployment, not a hand-tuned wall-clock guess.
        self.issue_ewma = 0.0

    def _count_decisions(self, algo_col) -> None:
        """Per-algorithm decision accounting (the
        gubernator_tpu_decisions_total{algorithm} family) — one vectorized
        bincount per dispatch, never per row. Cascade member rows carry
        their own algorithm, so every level counts as one decision."""
        a = np.asarray(algo_col)
        if a.size == 0:
            return
        lab = np.where((a >= 0) & (a < len(_ALGO_LABELS) - 1), a,
                       len(_ALGO_LABELS) - 1)
        counts = np.bincount(lab, minlength=len(_ALGO_LABELS))
        for v, c in enumerate(counts):
            if c:
                self.algo_counts[_ALGO_LABELS[v]] += int(c)
                if self.metrics is not None:
                    self.metrics.decisions_total.labels(
                        algorithm=_ALGO_LABELS[v]
                    ).inc(int(c))

    async def check(
        self, cols: RequestColumns, now_ms: Optional[int] = None, span=None,
        launch_path: str = "xla",
    ) -> ResponseColumns:
        """Pipelined check when the engine supports the prepare/issue/finish
        split, else the serial path. Store-configured engines stay serial:
        write-through ordering and miss-rehydrates must serialize against
        every same-key dispatch, which interleaved pipelined chunks cannot
        guarantee — durability trades pipeline throughput. Engines may also
        veto per batch via `can_pipeline(cols)`; engines whose batches need
        a custom split (the mesh-global engine's replica/owner fork) provide
        their own pending type through the prepare_columns/issue_pending/
        finish_pending hooks instead of vetoing.

        `span` is the batcher's dispatch SpanContext: each pipeline stage
        emits a child span under it (and stage_duration exemplars carry its
        trace_id), so a coalesced flush decomposes per-stage in the trace
        view."""
        can = getattr(self.engine, "can_pipeline", None)
        if (
            not getattr(self.engine, "supports_pipeline", False)
            or getattr(self.engine, "store", None) is not None
            or (can is not None and not can(cols))
        ):
            return await self.check_columns(
                cols, now_ms=now_ms, launch_path=launch_path
            )
        self._count_decisions(cols.algo)
        from gubernator_tpu.ops.engine import prepare_check_columns

        loop = asyncio.get_running_loop()

        def prepare():
            t0 = time.perf_counter()
            prepared = prepare_check_columns(self.engine, cols, now_ms=now_ms)
            self._observe_stage("put", t0, span)
            if self.metrics is not None:
                self._observe_shard_stages()
            return prepared

        prepared = await loop.run_in_executor(self._prep, prepare)
        return await self._issue_and_finish(
            prepared, span=span, launch_path=launch_path
        )

    async def check_wire(
        self, parts, now_ms=None, span=None, launch_path: str = "xla"
    ) -> Optional[ResponseColumns]:
        """Fused front-door check: pre-parsed WireBatch pieces
        (service/wire.py — native-parser lanes) staged straight into ONE
        compact ingress grid, no column concat and no HostBatch pack.
        Returns None when the batch cannot ride the fused path (engine not
        wire-capable, duplicate keys, non-encodable rows, Store attached) —
        the caller falls back to the columns path, which is semantically
        identical."""
        engine = self.engine
        if (
            not getattr(engine, "supports_wire_ingress", False)
            or getattr(engine, "store", None) is not None
        ):
            return None
        from gubernator_tpu.ops.engine import prepare_check_wire

        loop = asyncio.get_running_loop()

        def prepare():
            t0 = time.perf_counter()
            prepared = prepare_check_wire(engine, parts, now_ms=now_ms)
            if prepared is not None:
                self._observe_stage("put", t0, span)
            return prepared

        prepared = await loop.run_in_executor(self._prep, prepare)
        if prepared is None:
            return None
        for p in parts:
            self._count_decisions(p.cols.algo)
        return await self._issue_and_finish(
            prepared, span=span, launch_path=launch_path
        )

    def _observe_stage(self, stage: str, t0: float, span) -> None:
        """One pipeline-stage observation: histogram sample (with the
        dispatch trace_id as its OpenMetrics exemplar) plus a child span
        under the dispatch span. Wall-clock ns for the span are derived
        from the same perf_counter interval the histogram measured."""
        dt = time.perf_counter() - t0
        if stage == "issue":
            self.issue_ewma = (
                dt if self.issue_ewma == 0.0
                else 0.9 * self.issue_ewma + 0.1 * dt
            )
        if self.metrics is not None:
            self.metrics.stage_duration.labels(stage=stage).observe(
                dt, exemplar=_exemplar(span)
            )
        if span is not None and tracing.exporter is not None:
            end_ns = time.time_ns()
            tracing.record_span(
                stage, tracing.new_span(span), span.span_id,
                end_ns - int(dt * 1e9), end_ns,
            )

    async def _issue_and_finish(
        self, prepared, span=None, launch_path: str = "xla"
    ) -> ResponseColumns:
        """Shared issue/finish halves of the pipelined dispatch: ISSUE on
        the engine thread (enqueue kernel launches, no fetch), FINISH on a
        fetch worker (materialize outputs, rare fixups back on the engine
        thread), stats folded in on the engine thread."""
        from gubernator_tpu.ops.engine import (
            finish_check_columns,
            issue_check_columns,
        )

        loop = asyncio.get_running_loop()

        def issue(prepared):
            t0 = time.perf_counter()
            pending = issue_check_columns(self.engine, prepared)
            self._observe_stage("issue", t0, span)
            if self.metrics is not None:
                # feed-path accounting (docs/latency.md "Dispatch budget"):
                # ring = launched from the device-resident request ring's
                # serving loop, xla = the direct per-flush round-trip
                self.metrics.dispatch_launches.labels(path=launch_path).inc()
            return pending

        def fixup(fn):
            # executes fn on the engine thread; called FROM a fetch thread
            # (never from the engine thread — that would deadlock the
            # single-worker executor)
            return self._exec.submit(fn).result()

        def finish(pending):
            t0 = time.perf_counter()
            rc, delta = finish_check_columns(self.engine, pending, fixup)
            self._observe_stage("fetch", t0, span)

            def apply():
                self.engine.stats.merge(delta)
                if self.metrics is not None:
                    self.metrics.dispatch_duration.observe(
                        time.perf_counter() - t0
                    )
                    self.metrics.observe_engine(self.engine.stats)
                    self._observe_probe_bytes()
                    # GLOBAL batches ride the pipeline too: without this the
                    # queue-length gauge would only ever be observed post-
                    # drain (sync_global) and read 0 forever
                    gs = getattr(self.engine, "global_stats", None)
                    if gs is not None:
                        self.metrics.observe_global(gs)

            self._exec.submit(apply)  # fire-and-forget, engine thread
            return rc

        pending = await loop.run_in_executor(self._exec, lambda: issue(prepared))
        return await loop.run_in_executor(self._fetch, lambda: finish(pending))

    # ------------------------------------------------- fused ring drain
    # (ops/ring_drain.py) — the multi-slot twin of _issue_and_finish: one
    # ENGINE-THREAD launch decides a whole group of published ring slots,
    # one FETCH-THREAD materialization decodes every slot's egress bank.
    # Split into two awaitables (not one) so the ring's consume loop can
    # serialize LAUNCH order across groups while group j's finish overlaps
    # group j+1's issue — the same pipelining shape the host issue loop has.

    async def drain_ring_issue(self, dring, group, start: int, span=None):
        """ENGINE-THREAD half of one fused drain: per-slot issue-time work
        (shadow promote for the group head, checkpoint marks) in ticket
        order, stage each slot's grid + ingress fence into the device ring,
        then ONE `drain_ring` launch over the whole group. Returns the
        un-fetched (bank, drained) device handles."""
        loop = asyncio.get_running_loop()

        def issue():
            t0 = time.perf_counter()
            from gubernator_tpu.ops.engine import promote_rows

            engine = self.engine
            for prep in group:
                pending = prep.pending
                if pending.promote is not None:
                    # shadow fault-back through the conservative merge
                    # BEFORE the drain launch — grouping guarantees only
                    # the HEAD slot carries a promote, so merge→decide
                    # order matches the per-slot path exactly
                    _, pending.promote_putback = promote_rows(
                        engine, pending.promote, pending.now
                    )
                    pending.promote = None
                if (
                    pending.mark is not None
                    and getattr(engine, "ckpt", None) is not None
                ):
                    engine.ckpt.mark(pending.mark)
            head = group[0]
            if engine._batch_needs_full(head.math):
                engine.migrate_layout_full()
            engine._seen_pad_sizes.add(dring.width)
            engine.last_dispatch_rows = dring.width
            for i, prep in enumerate(group):
                dring.stage((start + i) % dring.slots, prep.grid, start + i)
            bank, n = dring.drain(
                engine, start, len(group), head.math, head.cascade
            )
            self._observe_stage("issue", t0, span)
            if self.metrics is not None:
                self.metrics.dispatch_launches.labels(path="fused").inc()
                self.metrics.ring_drain_slots.observe(len(group))
            return bank, n

        return await loop.run_in_executor(self._exec, issue)

    async def drain_ring_finish(self, group, bank, n, span=None):
        """FETCH-THREAD half of one fused drain: ONE bank fetch covers the
        whole group; each slot's PendingCheck then runs the standard
        finish (dropped-claim retries and shadow rehydrates via the engine
        thread, evictee harvest, cascade folds) over its egress slice.
        Returns the per-slot responses in ticket order."""
        loop = asyncio.get_running_loop()

        def fixup(fn):
            return self._exec.submit(fn).result()

        def finish():
            t0 = time.perf_counter()
            from gubernator_tpu.ops.engine import finish_check_columns

            fetched = np.asarray(bank)
            drained = int(n)
            if drained != len(group):
                raise RuntimeError(
                    f"ring drain fence violation: group of {len(group)} "
                    f"published slots, device retired {drained}"
                )
            done = []
            for i, prep in enumerate(group):
                pending = prep.pending
                pending.passes[0][3] = fetched[i]
                done.append(finish_check_columns(self.engine, pending, fixup))
            self._observe_stage("fetch", t0, span)

            def apply():
                for _rc, delta in done:
                    self.engine.stats.merge(delta)
                if self.metrics is not None:
                    self.metrics.dispatch_duration.observe(
                        time.perf_counter() - t0
                    )
                    self.metrics.observe_engine(self.engine.stats)
                    self._observe_probe_bytes()
                    gs = getattr(self.engine, "global_stats", None)
                    if gs is not None:
                        self.metrics.observe_global(gs)

            self._exec.submit(apply)  # fire-and-forget, engine thread
            return [rc for rc, _delta in done]

        return await loop.run_in_executor(self._fetch, finish)

    def _observe_shard_stages(self) -> None:
        """Fold the mesh engine's host-staging split (route/pack/put ms
        accumulated in ShardedEngine._stage*) into the stage_duration
        summaries as shard_* labels — the mesh-path mirror of the local
        pipeline's put/issue/fetch stages, and the series the ingress bench
        reads to show staging cost ∝ batch rows. The compact-wire codec
        stages keep their own wire_pack/wire_decode labels, and the bytes
        the engine moved across the boundary feed the
        gubernator_tpu_wire_bytes_total counter so bytes/decision is
        scrapeable rather than bench-computed."""
        take = getattr(self.engine, "take_stage_deltas", None)
        if take is not None:
            for k, ms in take().items():
                if ms > 0:
                    label = k if k.startswith("wire_") else f"shard_{k}"
                    self.metrics.stage_duration.labels(stage=label).observe(
                        ms / 1e3
                    )
        wtake = getattr(self.engine, "take_wire_deltas", None)
        if wtake is not None:
            for direction, nbytes in wtake().items():
                if nbytes > 0:
                    self.metrics.wire_bytes.labels(direction=direction).inc(
                        nbytes
                    )
        otake = getattr(self.engine, "take_a2a_overflow_delta", None)
        if otake is not None:
            impl, rows = otake()
            if rows > 0:
                self.metrics.a2a_overflow.labels(impl=impl).inc(rows)

    def _observe_probe_bytes(self) -> None:
        """Refresh the gubernator_table_hbm_bytes_per_decision gauge from
        the engine's current layout × write-mode × probe-kernel × dispatch
        geometry (a few integer ops — the model, not a measurement)."""
        est = getattr(self.engine, "hbm_bytes_per_decision_estimate", None)
        if est is not None:
            self.metrics.table_hbm_bytes_per_decision.set(est())

    async def check_columns(
        self, cols: RequestColumns, now_ms: Optional[int] = None,
        launch_path: str = "xla",
    ) -> ResponseColumns:
        self._count_decisions(cols.algo)
        loop = asyncio.get_running_loop()

        def run():
            t0 = time.perf_counter()
            rc = self.engine.check_columns(cols, now_ms=now_ms)
            if self.metrics is not None:
                self.metrics.dispatch_launches.labels(path=launch_path).inc()
                self.metrics.dispatch_duration.observe(time.perf_counter() - t0)
                self._observe_shard_stages()
                self.metrics.observe_engine(self.engine.stats)
                self._observe_probe_bytes()
                gs = getattr(self.engine, "global_stats", None)
                if gs is not None:
                    self.metrics.observe_global(gs)
            return rc

        return await loop.run_in_executor(self._exec, run)

    async def install_columns(self, **kw) -> int:
        loop = asyncio.get_running_loop()

        def run():
            n = self.engine.install_columns(**kw)
            if self.metrics is not None:
                self.metrics.observe_engine(self.engine.stats)
            return n

        return await loop.run_in_executor(self._exec, run)

    async def sync_global(self) -> None:
        """One collective GLOBAL sync (mesh engines): drain pending hits
        through the all_gather/aggregate/install step, serialized onto the
        engine thread like every other table mutation. Metric observation
        happens HERE (on the engine thread) so observe_global's read-modify-
        write of its delta baseline is never concurrent with the dispatch
        path's."""
        loop = asyncio.get_running_loop()

        def run():
            t0 = time.perf_counter()
            self.engine.sync()
            if self.metrics is not None:
                self.metrics.global_send_duration.observe(time.perf_counter() - t0)
                self.metrics.observe_global(self.engine.global_stats)

        await loop.run_in_executor(self._exec, run)

    async def live_count(self) -> int:
        """Table live-key count, serialized onto the engine thread — reading
        engine.table from another thread races the donated-buffer dispatch."""
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(self._exec, self.engine.live_count)

    async def table_telemetry(self, now_ms: Optional[int] = None):
        """One background table-telemetry scan (ops/telemetry.py), split
        like a serving dispatch: the LAUNCH runs on the engine thread (the
        scan must read a coherent table — every mutation is single-writer
        there, and the enqueue costs microseconds), the FETCH runs on a
        dedicated telemetry thread so the device streams the table WHILE
        the engine thread keeps issuing serving dispatches. The scan is
        never on the serving path; its only engine-thread cost is the
        launch."""
        from gubernator_tpu.ops.telemetry import finish_scan

        loop = asyncio.get_running_loop()
        if self._telemetry is None:
            self._telemetry = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="telemetry"
            )
        pending = await loop.run_in_executor(
            self._exec, lambda: self.engine.telemetry_begin(now_ms)
        )
        return await loop.run_in_executor(
            self._telemetry, lambda: finish_scan(pending)
        )

    async def snapshot(self) -> np.ndarray:
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(self._exec, self.engine.snapshot)

    # ------------------------------------------------------------- tiering

    async def tier_demote_idle(
        self, idle_ms: int, max_rows: int = 1 << 16, now_ms=None
    ):
        """One demote-on-idle sweep (gubernator_tpu/tier/): extract rows
        idle past the horizon AND tombstone them out of HBM in ONE
        engine-thread job — no decide can interleave between the read and
        the removal, so the demoted copy is exactly the state that left
        the table. Returns (now_ms, fps, canonical full rows); the caller
        (TierManager) appends them to the shadow. Crash ordering: a death
        after the tombstone but before the shadow append loses nothing
        the delta log doesn't still hold — restart replays the row back
        (no tombstone frame was written yet), which is the conservative
        direction."""
        loop = asyncio.get_running_loop()

        def run():
            from gubernator_tpu.ops.engine import ms_now

            eng = self.engine
            now = now_ms if now_ms is not None else ms_now()
            fps, slots = eng.extract_idle(now, idle_ms, max_rows)
            if fps.shape[0] == 0:
                return now, fps, np.empty((0, 16), dtype=np.int32)
            eng.tombstone_fps(fps)
            # canonical rows at the shadow boundary (the one cross-layout
            # conversion point, ops/layout.py)
            full = np.asarray(eng.table.layout.unpack(slots))
            return now, fps, full

        return await loop.run_in_executor(self._exec, run)

    # ------------------------------------------------- incremental checkpoint
    # (service/checkpoint.py) — split like telemetry: take+launch atomically
    # on the engine thread, fetch on a dedicated lazy thread so the extract
    # streams off-device WHILE serving dispatches keep issuing.

    async def checkpoint_extract(self, now_ms: Optional[int] = None):
        """One checkpoint epoch's dirty-block extract: (epoch, gids, fps,
        slots). The tracker take() and the extract LAUNCH run in one
        engine-thread job — the ordering contract that makes every
        mark→mutate pair land wholly inside one epoch (ops/checkpoint.py)."""
        loop = asyncio.get_running_loop()

        def begin():
            tracker = self.engine.ckpt
            epoch, gids = tracker.take()
            if gids.shape[0] == 0:
                return epoch, gids, None
            return epoch, gids, self.engine.checkpoint_begin(gids, now_ms)

        epoch, gids, pending = await loop.run_in_executor(self._exec, begin)
        if pending is None:
            width = self.engine.table.layout.F
            return (
                epoch, gids,
                np.empty(0, dtype=np.int64),
                np.empty((0, width), dtype=np.int32),
            )
        if self._ckpt is None:
            self._ckpt = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="ckpt"
            )
        fps, slots = await loop.run_in_executor(
            self._ckpt, lambda: self.engine.checkpoint_finish(pending)
        )
        return epoch, gids, fps, slots

    async def checkpoint_snapshot(self):
        """(full table rows, epoch, slot layout) read atomically on the
        engine thread — the compaction input (rows coherent with the epoch
        counter AND the layout those bytes are in)."""
        loop = asyncio.get_running_loop()

        def run():
            tracker = self.engine.ckpt
            return (
                self.engine.snapshot(),
                tracker.epoch if tracker is not None else 0,
                self.engine.table.layout,
            )

        return await loop.run_in_executor(self._exec, run)

    # ---------------------------------------------------------- handoff ops
    # All three mutate (or scan state coherent with) the device table, so
    # they serialize onto the engine thread like every dispatch.

    async def extract_live(self, now_ms: Optional[int] = None):
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self._exec, lambda: self.engine.extract_live(now_ms)
        )

    async def merge_rows(
        self, fps: np.ndarray, slots: np.ndarray,
        now_ms: Optional[int] = None, layout=None,
    ) -> int:
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self._exec,
            lambda: self.engine.merge_rows(fps, slots, now_ms, layout=layout),
        )

    async def tombstone_fps(self, fps: np.ndarray) -> int:
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self._exec, lambda: self.engine.tombstone_fps(fps)
        )

    async def read_state(self, fps: np.ndarray):
        """(found, full-width slots) stored-state read — engine thread for
        a coherent table view (the GLOBAL broadcast aux source)."""
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self._exec, lambda: self.engine.read_state(fps)
        )

    async def read_state_raw(self, fps: np.ndarray):
        """(found, slots, layout) stored-state read in the table's OWN slot
        layout — the region-sync sender's staging read. The layout is
        captured inside the same engine-thread job as the gather, so a
        concurrent layout migration can never mis-tag the rows."""
        loop = asyncio.get_running_loop()

        def run():
            found, slots = self.engine.read_state(fps, raw=True)
            return found, slots, self.engine.table.layout

        return await loop.run_in_executor(self._exec, run)

    async def apply_region(
        self, fps: np.ndarray, deltas: np.ndarray, cfg: dict,
        sender_slots, sender_layout,
    ) -> int:
        """Apply one received cross-region delta batch through the
        conservative merge (ops/reconcile.apply_region_sync). ONE engine
        job, so the read→reconcile→merge triplet is atomic with respect to
        serving dispatches — no concurrent hit slips between the stored-
        state read and the merge."""
        from gubernator_tpu.ops.reconcile import apply_region_sync

        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self._exec,
            lambda: apply_region_sync(
                self.engine, fps, deltas, cfg, sender_slots, sender_layout
            ),
        )

    async def maybe_grow(self, **kw) -> bool:
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self._exec, lambda: self.engine.maybe_grow(**kw)
        )

    def snapshot_sync(self) -> np.ndarray:
        """Synchronous snapshot for shutdown paths with no running loop."""
        return self._exec.submit(self.engine.snapshot).result()

    def close(self) -> None:
        if self._ckpt is not None:
            self._ckpt.shutdown(wait=True)
        if self._telemetry is not None:
            self._telemetry.shutdown(wait=True)
        self._prep.shutdown(wait=True)
        self._fetch.shutdown(wait=True)
        self._exec.shutdown(wait=True)
