"""EngineRunner: the single-writer dispatch thread.

The device table has exactly one owner — the kernel — and the host side
funnels every mutation through ONE thread, the TPU analog of the reference's
"each worker owns its cache, no mutexes" rule (reference workers.go:19-37).
asyncio handlers await engine work through this runner; ordering of submitted
jobs is FIFO, which is what makes the front-door batcher's request-order
contract hold.
"""

from __future__ import annotations

import asyncio
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

import numpy as np

from gubernator_tpu.ops.batch import RequestColumns, ResponseColumns
from gubernator_tpu.ops.engine import LocalEngine


class EngineRunner:
    """Serializes engine access onto one thread; async façade."""

    def __init__(self, engine: LocalEngine, metrics=None):
        self.engine = engine
        self.metrics = metrics
        self._exec = ThreadPoolExecutor(max_workers=1, thread_name_prefix="engine")

    async def check_columns(
        self, cols: RequestColumns, now_ms: Optional[int] = None
    ) -> ResponseColumns:
        loop = asyncio.get_running_loop()

        def run():
            t0 = time.perf_counter()
            rc = self.engine.check_columns(cols, now_ms=now_ms)
            if self.metrics is not None:
                self.metrics.dispatch_duration.observe(time.perf_counter() - t0)
                self.metrics.observe_engine(self.engine.stats)
                gs = getattr(self.engine, "global_stats", None)
                if gs is not None:
                    self.metrics.observe_global(gs)
            return rc

        return await loop.run_in_executor(self._exec, run)

    async def install_columns(self, **kw) -> int:
        loop = asyncio.get_running_loop()

        def run():
            n = self.engine.install_columns(**kw)
            if self.metrics is not None:
                self.metrics.observe_engine(self.engine.stats)
            return n

        return await loop.run_in_executor(self._exec, run)

    async def sync_global(self) -> None:
        """One collective GLOBAL sync (mesh engines): drain pending hits
        through the all_gather/aggregate/install step, serialized onto the
        engine thread like every other table mutation. Metric observation
        happens HERE (on the engine thread) so observe_global's read-modify-
        write of its delta baseline is never concurrent with the dispatch
        path's."""
        loop = asyncio.get_running_loop()

        def run():
            t0 = time.perf_counter()
            self.engine.sync()
            if self.metrics is not None:
                self.metrics.global_send_duration.observe(time.perf_counter() - t0)
                self.metrics.observe_global(self.engine.global_stats)

        await loop.run_in_executor(self._exec, run)

    async def live_count(self) -> int:
        """Table live-key count, serialized onto the engine thread — reading
        engine.table from another thread races the donated-buffer dispatch."""
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(self._exec, self.engine.live_count)

    async def snapshot(self) -> np.ndarray:
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(self._exec, self.engine.snapshot)

    async def maybe_grow(self, **kw) -> bool:
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self._exec, lambda: self.engine.maybe_grow(**kw)
        )

    def snapshot_sync(self) -> np.ndarray:
        """Synchronous snapshot for shutdown paths with no running loop."""
        return self._exec.submit(self.engine.snapshot).result()

    def close(self) -> None:
        self._exec.shutdown(wait=True)
