"""Device-resident request ring — the always-on serving loop's front end.

The dispatch round-trip is the last fixed cost the serving plane pays per
flush: every coalesced batch walks the full host dispatch machinery
(executor hop → XLA launch → fetch) even when the device is idle and the
next batch is already parsed. On a real TPU the fix is a PERSISTENT serving
kernel fed by a fixed ring of compact wire-grid slots in device memory: the
host DMAs a packed (5, B+1) ingress grid into slot `t % S`, publishes a
sequence word, and the always-running kernel picks the slot up without any
launch round-trip; results come back through a per-slot egress fence the
host polls. This module is the FUNCTIONAL EMULATION of that protocol on
the CPU build — it drives the exact same runner surface
(`EngineRunner.check_wire`) the direct path drives, so responses are
byte-identical by construction, while exercising the full ring protocol:

* **slot claim / publish ordering** — a submitter claims ticket `t`
  (slot `t % S`) under the submit lock, stages the payload into the slot,
  and only THEN publishes `seq_in[slot] = t + 1` — the store fence that
  makes a published slot's payload visible before its sequence word, the
  ordering a device ring needs for the kernel's poll to be race-free;
* **sequence-number fencing** — the consumer checks `seq_in[slot] == t+1`
  before touching a slot and publishes `seq_out[slot] = t + 1` only after
  the result is materialized; a submitter's result wait is exactly the
  egress-fence poll;
* **bounded backpressure** — when all S slots hold published-but-unconsumed
  batches, submit WAITS (no drops, FIFO ticket order preserved) until the
  consumer retires the oldest slot;
* **drain on shutdown** — `drain()` stops intake, lets every published
  ticket complete in order, and only then parks the serving loop (zero
  loss, the contract ci/bench_cpu.py's ring_smoke gate pins).

Consumption is strictly in ticket order (the persistent kernel walks slots
in sequence), but the finish half of each dispatch overlaps the next
ticket's issue through the runner's own prepare/issue/finish pipeline —
the ring serializes LAUNCH ORDER, not completion latency.

Knobs: GUBER_RING_ENABLE turns the plane on (service/daemon.py routes
all-wire flushes here), GUBER_RING_SLOTS sizes the ring. Metrics:
gubernator_tpu_dispatch_launches_total{path="ring"|"xla"} splits launch
counts by feed path, gubernator_tpu_ring_occupancy gauges published-but-
unconsumed slots, and the ring_put / ring_poll stage_duration labels time
the submit-side staging and the egress-fence wait (docs/latency.md
"Dispatch budget").
"""

from __future__ import annotations

import asyncio
import time
from typing import List, Optional, Tuple

import numpy as np

from gubernator_tpu.ops.batch import ResponseColumns
from gubernator_tpu.service.wire import concat_columns


class RingClosed(RuntimeError):
    """Raised to a submitter racing drain(): the caller (Batcher._dispatch)
    falls back to the direct dispatch path — no request is lost."""


class RequestRing:
    """Fixed ring of S request slots with sequence-number fencing.

    `seq_in` / `seq_out` are the ingress/egress fence words — int64 arrays
    indexed by slot, exactly the layout the device ring keeps resident in
    HBM (docs/latency.md "Dispatch budget"). Slot `t % S` carries ticket
    `t`; fence value `t + 1` (never 0, so an unused slot is unambiguous).
    """

    def __init__(self, runner, slots: int = 64, metrics=None):
        if slots < 2:
            raise ValueError("RequestRing needs at least 2 slots")
        self.runner = runner
        self.slots = int(slots)
        self.metrics = metrics
        self.seq_in = np.zeros(self.slots, dtype=np.int64)
        self.seq_out = np.zeros(self.slots, dtype=np.int64)
        # slot payload staging (the emulation's stand-in for the DMA'd
        # wire grids): (parts, span) per slot, cleared on consume
        self._staged: List[Optional[Tuple[list, object]]] = (
            [None] * self.slots
        )
        self._head = 0  # next ticket to claim (== tickets published)
        self._consumed = 0  # tickets fully retired (seq_out published)
        self._done = {}  # ticket -> result future (the egress poll)
        self._lock: Optional[asyncio.Lock] = None
        self._published: Optional[asyncio.Event] = None
        self._space: Optional[asyncio.Event] = None
        self._drained: Optional[asyncio.Event] = None
        self._issue_task: Optional[asyncio.Task] = None
        self._finish_task: Optional[asyncio.Task] = None
        self._inorder: Optional[asyncio.Queue] = None
        self._closed = False
        # introspection counters (ring_smoke + /v1/debug/pipeline)
        self.launches = 0  # dispatches fed from the ring
        self.fallbacks = 0  # non-fusable slots that rode the columns path
        self.backpressure_waits = 0  # submits that found the ring full
        self.max_occupancy = 0

    # ------------------------------------------------------------ lifecycle
    def _ensure_started(self) -> None:
        if self._lock is not None:
            return
        loop = asyncio.get_running_loop()
        self._lock = asyncio.Lock()
        self._published = asyncio.Event()
        self._space = asyncio.Event()
        self._drained = asyncio.Event()
        self._inorder = asyncio.Queue()
        self._issue_task = loop.create_task(self._issue_loop(),
                                            name="ring-issue")
        self._finish_task = loop.create_task(self._finish_loop(),
                                             name="ring-finish")

    def _set_occupancy(self) -> None:
        occ = self._head - self._consumed
        if occ > self.max_occupancy:
            self.max_occupancy = occ
        if self.metrics is not None:
            self.metrics.ring_occupancy.set(occ)

    # -------------------------------------------------------------- submit
    async def submit(self, parts, span=None) -> ResponseColumns:
        """Claim a ticket, stage the payload, publish the ingress fence,
        and poll the egress fence for the coalesced response. `parts` is
        the all-WireBatch chunk the batcher formed — the same value the
        direct path hands `runner.check_wire`, which is what makes the two
        paths byte-identical."""
        self._ensure_started()
        if self._closed:
            raise RingClosed("request ring is draining")
        t0 = time.perf_counter()
        async with self._lock:
            ticket = self._head
            # bounded backpressure: every slot published-but-unconsumed →
            # wait for the serving loop to retire the oldest (FIFO under
            # the lock: later submitters queue behind this one)
            while not self._closed and (
                ticket - self._consumed >= self.slots
            ):
                self.backpressure_waits += 1
                self._space.clear()
                await self._space.wait()
            if self._closed:
                raise RingClosed("request ring is draining")
            self._head = ticket + 1
            slot = ticket % self.slots
            fut = asyncio.get_running_loop().create_future()
            self._done[ticket] = fut
            # STAGE before PUBLISH — the store-fence ordering: the payload
            # must be slot-resident before seq_in makes it claimable
            self._staged[slot] = (parts, span)
            self.seq_in[slot] = ticket + 1
            self._published.set()
        self._set_occupancy()
        self.runner._observe_stage("ring_put", t0, span)
        # egress-fence poll: resolve when the serving loop publishes
        # seq_out[slot] == ticket + 1
        t1 = time.perf_counter()
        try:
            rc = await fut
        finally:
            self._done.pop(ticket, None)
        self.runner._observe_stage("ring_poll", t1, span)
        return rc

    # ------------------------------------------------------- serving loop
    async def _dispatch(self, parts, span):
        """One slot's dispatch: the exact runner surface the direct path
        drives. Non-fusable chunks (duplicate keys, non-encodable rows)
        fall back to the columns path, same as Batcher._dispatch."""
        rc = await self.runner.check_wire(parts, span=span,
                                          launch_path="ring")
        if rc is None:
            self.fallbacks += 1
            cat = concat_columns([p.cols for p in parts])
            rc = await self.runner.check(cat, span=span, launch_path="ring")
        return rc

    async def _issue_loop(self) -> None:
        """Walk tickets strictly in order (the persistent kernel's slot
        walk): check the ingress fence, lift the payload, and start its
        dispatch. Completion ordering is the finish loop's job."""
        t = 0
        loop = asyncio.get_running_loop()
        while True:
            while t >= self._head:
                if self._closed:
                    await self._inorder.put(None)  # finish-loop sentinel
                    return
                self._published.clear()
                if t < self._head:  # raced a publish
                    break
                await self._published.wait()
            slot = t % self.slots
            # ingress fence: the slot must carry exactly this ticket
            assert int(self.seq_in[slot]) == t + 1, (
                f"ring fence violation: slot {slot} has seq "
                f"{int(self.seq_in[slot])}, expected {t + 1}"
            )
            parts, span = self._staged[slot]
            self._staged[slot] = None
            await self._inorder.put(
                (t, loop.create_task(self._dispatch(parts, span)))
            )
            t += 1

    async def _finish_loop(self) -> None:
        """Retire tickets in order: await each dispatch, publish the egress
        fence, resolve the submitter's poll, free the slot."""
        while True:
            item = await self._inorder.get()
            if item is None:
                self._drained.set()
                return
            t, task = item
            slot = t % self.slots
            fut = self._done.get(t)
            try:
                rc = await task
            except Exception as exc:  # pragma: no cover - defensive
                if fut is not None and not fut.done():
                    fut.set_exception(exc)
            else:
                if fut is not None and not fut.done():
                    fut.set_result(rc)
            self.launches += 1
            # egress fence AFTER the result is materialized — the order the
            # submitter's poll relies on
            self.seq_out[slot] = t + 1
            self._consumed = t + 1
            self._set_occupancy()
            self._space.set()

    # --------------------------------------------------------------- drain
    async def drain(self) -> None:
        """Stop intake and retire every published ticket in order before
        parking the serving loop — zero-loss shutdown (the ring_smoke
        drain gate). Safe to call with nothing ever submitted."""
        self._closed = True
        if self._lock is None:
            return  # never started
        self._published.set()  # wake the issue loop to emit its sentinel
        self._space.set()  # release submitters blocked on backpressure
        await self._drained.wait()
        for task in (self._issue_task, self._finish_task):
            if task is not None and not task.done():
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass

    def debug(self) -> dict:
        """Ring-plane state for /v1/debug/pipeline."""
        return {
            "slots": self.slots,
            "occupancy": self._head - self._consumed,
            "published": self._head,
            "consumed": self._consumed,
            "launches": self.launches,
            "fallbacks": self.fallbacks,
            "backpressure_waits": self.backpressure_waits,
            "max_occupancy": self.max_occupancy,
            "closed": self._closed,
        }
