"""Device-resident request ring — the always-on serving loop's front end.

The dispatch round-trip is the last fixed cost the serving plane pays per
flush: every coalesced batch walks the full host dispatch machinery
(executor hop → XLA launch → fetch) even when the device is idle and the
next batch is already parsed. On a real TPU the fix is a PERSISTENT serving
kernel fed by a fixed ring of compact wire-grid slots in device memory: the
host DMAs a packed (5, B+1) ingress grid into slot `t % S`, publishes a
sequence word, and the always-running kernel picks the slot up without any
launch round-trip; results come back through a per-slot egress fence the
host polls. This module is the FUNCTIONAL EMULATION of that protocol on
the CPU build — it drives the exact same runner surface
(`EngineRunner.check_wire`) the direct path drives, so responses are
byte-identical by construction, while exercising the full ring protocol:

* **slot claim / publish ordering** — a submitter claims ticket `t`
  (slot `t % S`) under the submit lock, stages the payload into the slot,
  and only THEN publishes `seq_in[slot] = t + 1` — the store fence that
  makes a published slot's payload visible before its sequence word, the
  ordering a device ring needs for the kernel's poll to be race-free;
* **sequence-number fencing** — the consumer checks `seq_in[slot] == t+1`
  before touching a slot and publishes `seq_out[slot] = t + 1` only after
  the result is materialized; a submitter's result wait is exactly the
  egress-fence poll;
* **bounded backpressure** — when all S slots hold published-but-unconsumed
  batches, submit WAITS (no drops, FIFO ticket order preserved) until the
  consumer retires the oldest slot;
* **drain on shutdown** — `drain()` stops intake, lets every published
  ticket complete in order, and only then parks the serving loop (zero
  loss, the contract ci/bench_cpu.py's ring_smoke gate pins).

Consumption is strictly in ticket order (the persistent kernel walks slots
in sequence), but the finish half of each dispatch overlaps the next
ticket's issue through the runner's own prepare/issue/finish pipeline —
the ring serializes LAUNCH ORDER, not completion latency.

The CONSUME side has three tiers behind GUBER_RING_ISSUE (docs/latency.md
"Launch budget"):

* **host** — the original loop: one runner dispatch (one XLA launch) per
  published slot. The CPU default and the byte-parity oracle.
* **fused** — the device-resident drain (ops/ring_drain.py): slots and
  fence words live in device buffers, and ONE jitted while_loop launch
  decides up to GUBER_RING_DRAIN_K consecutively published slots with the
  donated table in the carry, amortizing the launch round-trip K×. Slots
  the fused path can't take (duplicate keys, non-encodable rows, chunks
  wider than the slot) ride the per-slot host path in ticket order —
  byte-identical either way. The TPU default.
* **persistent** — staged for the TPU run: the Pallas fence-claim kernel
  (ops/ring_drain.fence_claim, interpreter-mode parity-tested) replaces
  the host's claim loop so steady state pays zero XLA launches; until the
  device run validates the resident loop this mode runs the fused drain
  with a watchdog that re-launches a failed drain once (preemption cover)
  and counts `watchdog_relaunches`.

Knobs: GUBER_RING_ENABLE turns the plane on (service/daemon.py routes
all-wire flushes here), GUBER_RING_SLOTS sizes the ring, GUBER_RING_ISSUE
picks the consume tier, GUBER_RING_DRAIN_K bounds slots per fused launch,
GUBER_RING_SLOT_WIDTH fixes the device slot width (0 = auto-size to the
first fused chunk). Metrics:
gubernator_tpu_dispatch_launches_total{path="ring"|"fused"|"xla"} splits
launch counts by feed path, gubernator_tpu_ring_drain_slots records
published slots retired per fused launch (the scrapeable amortization
factor), gubernator_tpu_ring_occupancy gauges published-but-unconsumed
slots, and the ring_put / ring_poll stage_duration labels time the
submit-side staging and the egress-fence wait.
"""

from __future__ import annotations

import asyncio
import time
from typing import List, Optional, Tuple

import numpy as np

from gubernator_tpu.ops.batch import ResponseColumns
from gubernator_tpu.service.wire import concat_columns


class RingClosed(RuntimeError):
    """Raised to a submitter racing drain(): the caller (Batcher._dispatch)
    falls back to the direct dispatch path — no request is lost."""


class RequestRing:
    """Fixed ring of S request slots with sequence-number fencing.

    `seq_in` / `seq_out` are the ingress/egress fence words — int64 arrays
    indexed by slot, exactly the layout the device ring keeps resident in
    HBM (docs/latency.md "Dispatch budget"). Slot `t % S` carries ticket
    `t`; fence value `t + 1` (never 0, so an unused slot is unambiguous).
    """

    def __init__(self, runner, slots: int = 64, metrics=None,
                 issue_mode: str = "host", drain_k: int = 8,
                 slot_width: int = 0):
        if slots < 2:
            raise ValueError("RequestRing needs at least 2 slots")
        if issue_mode not in ("host", "fused", "persistent"):
            raise ValueError(
                f"GUBER_RING_ISSUE must be host|fused|persistent, "
                f"got {issue_mode!r}"
            )
        if drain_k < 1:
            raise ValueError("GUBER_RING_DRAIN_K must be >= 1")
        self.runner = runner
        self.slots = int(slots)
        self.metrics = metrics
        self.issue_mode = issue_mode
        self.drain_k = int(min(drain_k, slots))
        # fixed device slot width (rows); 0 = auto-size to the first fused
        # chunk's padded size (wider chunks then ride the host path)
        self.slot_width = int(slot_width)
        self._dring = None  # ops/ring_drain.DeviceRing, fused tiers only
        self.seq_in = np.zeros(self.slots, dtype=np.int64)
        self.seq_out = np.zeros(self.slots, dtype=np.int64)
        # slot payload staging (the emulation's stand-in for the DMA'd
        # wire grids): (parts, span) per slot, cleared on consume
        self._staged: List[Optional[Tuple[list, object]]] = (
            [None] * self.slots
        )
        self._head = 0  # next ticket to claim (== tickets published)
        self._consumed = 0  # tickets fully retired (seq_out published)
        self._done = {}  # ticket -> result future (the egress poll)
        self._lock: Optional[asyncio.Lock] = None
        self._published: Optional[asyncio.Event] = None
        self._space: Optional[asyncio.Event] = None
        self._drained: Optional[asyncio.Event] = None
        self._issue_task: Optional[asyncio.Task] = None
        self._finish_task: Optional[asyncio.Task] = None
        self._inorder: Optional[asyncio.Queue] = None
        self._closed = False
        # introspection counters (ring_smoke + /v1/debug/pipeline)
        self.launches = 0  # tickets retired through the ring
        self.fallbacks = 0  # non-fusable slots that rode the columns path
        self.backpressure_waits = 0  # submits that found the ring full
        self.max_occupancy = 0
        # fused-tier counters (ring_drain_smoke + /v1/debug/pipeline)
        self.drain_launches = 0  # fused drain launches (XLA launches)
        self.drained_slots = 0  # tickets retired by fused drains
        self.host_slots = 0  # fused-ineligible tickets (per-slot path)
        self.watchdog_relaunches = 0  # persistent-tier drain re-launches

    # ------------------------------------------------------------ lifecycle
    def _ensure_started(self) -> None:
        if self._lock is not None:
            return
        loop = asyncio.get_running_loop()
        self._lock = asyncio.Lock()
        self._published = asyncio.Event()
        self._space = asyncio.Event()
        self._drained = asyncio.Event()
        self._inorder = asyncio.Queue()
        consume = (
            self._issue_loop if self.issue_mode == "host"
            else self._issue_loop_fused
        )
        self._issue_task = loop.create_task(consume(), name="ring-issue")
        self._finish_task = loop.create_task(self._finish_loop(),
                                             name="ring-finish")

    def _set_occupancy(self) -> None:
        occ = self._head - self._consumed
        if occ > self.max_occupancy:
            self.max_occupancy = occ
        if self.metrics is not None:
            self.metrics.ring_occupancy.set(occ)

    # -------------------------------------------------------------- submit
    async def submit(self, parts, span=None) -> ResponseColumns:
        """Claim a ticket, stage the payload, publish the ingress fence,
        and poll the egress fence for the coalesced response. `parts` is
        the all-WireBatch chunk the batcher formed — the same value the
        direct path hands `runner.check_wire`, which is what makes the two
        paths byte-identical."""
        self._ensure_started()
        if self._closed:
            raise RingClosed("request ring is draining")
        t0 = time.perf_counter()
        async with self._lock:
            ticket = self._head
            # bounded backpressure: every slot published-but-unconsumed →
            # wait for the serving loop to retire the oldest (FIFO under
            # the lock: later submitters queue behind this one)
            while not self._closed and (
                ticket - self._consumed >= self.slots
            ):
                self.backpressure_waits += 1
                self._space.clear()
                await self._space.wait()
            if self._closed:
                raise RingClosed("request ring is draining")
            self._head = ticket + 1
            slot = ticket % self.slots
            fut = asyncio.get_running_loop().create_future()
            self._done[ticket] = fut
            # STAGE before PUBLISH — the store-fence ordering: the payload
            # must be slot-resident before seq_in makes it claimable
            self._staged[slot] = (parts, span)
            self.seq_in[slot] = ticket + 1
            self._published.set()
        self._set_occupancy()
        self.runner._observe_stage("ring_put", t0, span)
        # egress-fence poll: resolve when the serving loop publishes
        # seq_out[slot] == ticket + 1
        t1 = time.perf_counter()
        try:
            rc = await fut
        finally:
            self._done.pop(ticket, None)
        self.runner._observe_stage("ring_poll", t1, span)
        return rc

    # ------------------------------------------------------- serving loop
    async def _dispatch(self, parts, span):
        """One slot's dispatch: the exact runner surface the direct path
        drives. Non-fusable chunks (duplicate keys, non-encodable rows)
        fall back to the columns path, same as Batcher._dispatch."""
        rc = await self.runner.check_wire(parts, span=span,
                                          launch_path="ring")
        if rc is None:
            self.fallbacks += 1
            cat = concat_columns([p.cols for p in parts])
            rc = await self.runner.check(cat, span=span, launch_path="ring")
        return rc

    async def _issue_loop(self) -> None:
        """Walk tickets strictly in order (the persistent kernel's slot
        walk): check the ingress fence, lift the payload, and start its
        dispatch. Completion ordering is the finish loop's job."""
        t = 0
        loop = asyncio.get_running_loop()
        while True:
            while t >= self._head:
                if self._closed:
                    await self._inorder.put(None)  # finish-loop sentinel
                    return
                self._published.clear()
                if t < self._head:  # raced a publish
                    break
                await self._published.wait()
            slot = t % self.slots
            # ingress fence: the slot must carry exactly this ticket
            assert int(self.seq_in[slot]) == t + 1, (
                f"ring fence violation: slot {slot} has seq "
                f"{int(self.seq_in[slot])}, expected {t + 1}"
            )
            parts, span = self._staged[slot]
            self._staged[slot] = None
            await self._inorder.put(
                ([t], loop.create_task(self._dispatch(parts, span)))
            )
            t += 1

    # ------------------------------------------------- fused consume tier
    def _prepare_slot(self, parts, span):
        """Prep-pool half of one fused slot: assemble the fixed-width wire
        grid + PendingCheck (ops/engine.prepare_ring_slot). None routes
        the chunk to the per-slot host path. Auto-sizes the device ring on
        the first fusable chunk when GUBER_RING_SLOT_WIDTH=0."""
        import time as _time

        from gubernator_tpu.ops.engine import _pad_size, prepare_ring_slot

        engine = self.runner.engine
        if self._dring is None and self.slot_width == 0:
            # first fused chunk sizes the slots: wide enough for its own
            # padded dispatch, floored so ordinary coalesced flushes fit
            n = sum(p.cols.fp.shape[0] for p in parts)
            self.slot_width = max(64, _pad_size(n))
        t0 = _time.perf_counter()
        prep = prepare_ring_slot(engine, parts, self.slot_width)
        if prep is not None:
            self.runner._observe_stage("put", t0, span)
            for p in parts:
                self.runner._count_decisions(p.cols.algo)
        return prep

    def _ensure_dring(self):
        if self._dring is None:
            from gubernator_tpu.ops.ring_drain import DeviceRing

            engine = self.runner.engine
            self._dring = DeviceRing(
                self.slots, self.slot_width, self.drain_k,
                evictees=bool(getattr(engine, "_evictees", False)),
            )
        return self._dring

    async def _fail(self, exc):
        raise exc

    async def _issue_loop_fused(self) -> None:
        """Fused consume loop (GUBER_RING_ISSUE=fused|persistent): walk
        tickets strictly in order, group consecutively published fusable
        slots that share the drain graph's static modes (math, cascade),
        and retire each group with ONE device drain launch
        (ops/ring_drain.drain_ring). Launch order across groups — and
        across the interleaved per-slot host dispatches — stays strict
        ticket order, the byte-parity contract; each group's finish
        overlaps the next group's prepare/issue through the runner's fetch
        pool, same as the host tier."""
        t = 0
        loop = asyncio.get_running_loop()
        while True:
            while t >= self._head:
                if self._closed:
                    await self._inorder.put(None)  # finish-loop sentinel
                    return
                self._published.clear()
                if t < self._head:  # raced a publish
                    break
                await self._published.wait()
            # lift every currently published ticket, up to one drain's worth
            todo = []
            while t < self._head and len(todo) < self.drain_k:
                slot = t % self.slots
                assert int(self.seq_in[slot]) == t + 1, (
                    f"ring fence violation: slot {slot} has seq "
                    f"{int(self.seq_in[slot])}, expected {t + 1}"
                )
                parts, span = self._staged[slot]
                self._staged[slot] = None
                todo.append((t, parts, span))
                t += 1
            preps = await asyncio.gather(*(
                loop.run_in_executor(
                    self.runner._prep, self._prepare_slot, parts, span
                )
                for _t, parts, span in todo
            ))
            i = 0
            while i < len(todo):
                if preps[i] is None:
                    # fused-ineligible: per-slot host dispatch. Awaited in
                    # full (not pipelined) so a following fused drain can
                    # never launch before this earlier ticket's dispatch —
                    # strict launch order is what byte-parity rests on.
                    tk, parts, span = todo[i]
                    self.host_slots += 1
                    task = loop.create_task(self._dispatch(parts, span))
                    await asyncio.wait({task})
                    await self._inorder.put(([tk], task))
                    i += 1
                    continue
                j = i + 1
                while (
                    j < len(todo)
                    and preps[j] is not None
                    and preps[j].math == preps[i].math
                    and preps[j].cascade == preps[i].cascade
                    # a slot with shadow fault-backs must HEAD its group:
                    # its promote-merge precedes the whole launch, so any
                    # earlier slot in the same drain would decide against
                    # post-merge state the per-slot path never saw
                    and preps[j].pending.promote is None
                ):
                    j += 1
                group = [preps[x] for x in range(i, j)]
                tickets = [todo[x][0] for x in range(i, j)]
                span = todo[i][2]
                try:
                    bank, n = await self.runner.drain_ring_issue(
                        self._ensure_dring(), group, tickets[0], span=span
                    )
                except Exception as exc:
                    if self.issue_mode == "persistent":
                        # watchdog: a preempted/failed drain re-launches
                        # once before the group is failed out
                        self.watchdog_relaunches += 1
                        try:
                            bank, n = await self.runner.drain_ring_issue(
                                self._ensure_dring(), group, tickets[0],
                                span=span,
                            )
                        except Exception as exc2:
                            await self._inorder.put(
                                (tickets, loop.create_task(self._fail(exc2)))
                            )
                            i = j
                            continue
                    else:
                        await self._inorder.put(
                            (tickets, loop.create_task(self._fail(exc)))
                        )
                        i = j
                        continue
                self.drain_launches += 1
                self.drained_slots += len(group)
                task = loop.create_task(
                    self.runner.drain_ring_finish(group, bank, n, span=span)
                )
                await self._inorder.put((tickets, task))
                i = j

    async def _finish_loop(self) -> None:
        """Retire tickets in order: await each dispatch (one ticket on the
        host/fallback path, a whole drain group on the fused path),
        publish the egress fences, resolve the submitters' polls, free the
        slots."""
        while True:
            item = await self._inorder.get()
            if item is None:
                self._drained.set()
                return
            tickets, task = item
            try:
                rc = await task
            except Exception as exc:  # pragma: no cover - defensive
                results = [exc] * len(tickets)
            else:
                results = rc if isinstance(rc, list) else [rc]
            for t, res in zip(tickets, results):
                slot = t % self.slots
                fut = self._done.get(t)
                if fut is not None and not fut.done():
                    if isinstance(res, Exception):
                        fut.set_exception(res)
                    else:
                        fut.set_result(res)
                self.launches += 1
                # egress fence AFTER the result is materialized — the
                # order the submitter's poll relies on
                self.seq_out[slot] = t + 1
                self._consumed = t + 1
            self._set_occupancy()
            self._space.set()

    # --------------------------------------------------------------- drain
    async def drain(self) -> None:
        """Stop intake and retire every published ticket in order before
        parking the serving loop — zero-loss shutdown (the ring_smoke
        drain gate). Safe to call with nothing ever submitted."""
        self._closed = True
        if self._lock is None:
            return  # never started
        self._published.set()  # wake the issue loop to emit its sentinel
        self._space.set()  # release submitters blocked on backpressure
        await self._drained.wait()
        for task in (self._issue_task, self._finish_task):
            if task is not None and not task.done():
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass

    def debug(self) -> dict:
        """Ring-plane state for /v1/debug/pipeline."""
        return {
            "slots": self.slots,
            "occupancy": self._head - self._consumed,
            "published": self._head,
            "consumed": self._consumed,
            "launches": self.launches,
            "fallbacks": self.fallbacks,
            "backpressure_waits": self.backpressure_waits,
            "max_occupancy": self.max_occupancy,
            "closed": self._closed,
            "issue_mode": self.issue_mode,
            "drain_k": self.drain_k,
            "slot_width": self.slot_width,
            "drain_launches": self.drain_launches,
            "drained_slots": self.drained_slots,
            "host_slots": self.host_slots,
            "watchdog_relaunches": self.watchdog_relaunches,
        }
