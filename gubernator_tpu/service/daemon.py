"""Daemon: process assembly + the V1 request-routing core.

The TPU analog of the reference's V1Instance + Daemon (reference
gubernator.go:121-302, daemon.go:90-434). One daemon owns one device engine
(single-writer dispatch thread), a batching front door, a peer plane
(consistent-hash ownership + forwarding with retry), the GLOBAL manager, and
the gRPC/HTTP listeners.

Routing per request item (reference GetRateLimits, gubernator.go:186-302):
  1. validate + fingerprint (columns at the edge, wire.py)
  2. ForceGlobal config flips every item to GLOBAL (config.go:65-66)
  3. owner = consistent-hash ring on the item's hash key
  4. owner == self        → coalescing batcher → device kernel
     GLOBAL && not owner  → answer from LOCAL state now, queue async hit
                            (gubernator.go:401-429)
     not owner            → forward to owner, ≤5 retries re-resolving
                            ownership (gubernator.go:318-399)
"""

from __future__ import annotations

import asyncio
import collections
import random
import time
from typing import Dict, List, Optional

import numpy as np

from gubernator_tpu.config import DaemonConfig, DegradationPolicy
from gubernator_tpu.hashing import fingerprint
from gubernator_tpu.ops.batch import ERROR_STRINGS, RequestColumns
from gubernator_tpu.ops.engine import LocalEngine, ms_now
from gubernator_tpu.peers.hash_ring import ReplicatedConsistentHash
from gubernator_tpu.peers.ownership import OwnershipIndex
from gubernator_tpu.peers.picker import RegionPicker
from gubernator_tpu.proto import globalsync_pb2 as globalsync_pb
from gubernator_tpu.proto import gubernator_pb2 as pb
from gubernator_tpu.proto import handoff_pb2 as handoff_pb
from gubernator_tpu.proto import peers_pb2 as peers_pb
from gubernator_tpu.service.batcher import Batcher
from gubernator_tpu.service.global_manager import GlobalManager
from gubernator_tpu.service.breaker import BreakerState, CircuitBreaker
from gubernator_tpu.service.metrics import DaemonMetrics
from gubernator_tpu.service.peer_client import (
    PeerCircuitOpenError,
    PeerClient,
    PeerError,
)
from gubernator_tpu.service.runner import EngineRunner
from gubernator_tpu.service.wire import (
    batch_too_large_error,
    columns_from_pb,
    expand_cascades,
    pb_from_cascade_response_columns,
    pb_from_response_columns,
    subset_columns,
)
from gubernator_tpu.types import Behavior, HitEvent, PeerInfo, has_behavior
from gubernator_tpu import tracing

import logging

log = logging.getLogger("gubernator_tpu.daemon")

FORWARD_RETRIES = 5  # reference asyncRequest retries (gubernator.go:333-359)


def _hashkey_fp(key: str) -> int:
    """Fingerprint of a pre-joined hash key ('name_uniquekey') — identical to
    fingerprint(name, unique_key) because that joins with '_' (client.go:39-41)."""
    import xxhash

    from gubernator_tpu.hashing import _MASK63, _SEED

    h = xxhash.xxh64_intdigest(key, seed=_SEED) & _MASK63
    return h if h != 0 else 1


class Daemon:
    """One serving process. Use `await Daemon.spawn(conf)`."""

    cert_watch_interval_s = 30.0  # PEM rotation poll cadence (class-level
    # so tests can speed it up before spawn)

    def __init__(
        self,
        conf: DaemonConfig,
        engine: Optional[LocalEngine] = None,
        event_channel: Optional[asyncio.Queue] = None,
        store=None,
        loader=None,
    ):
        conf.validate()
        self.conf = conf
        # optional Loader hook (startup restore / shutdown save); None falls
        # back to GUBER_CHECKPOINT_PATH file snapshots
        self.loader = loader
        # optional audit hook: HitEvent per owner-side hit (reference
        # config.go:128-135); non-blocking — events drop when the consumer
        # lags rather than stalling the serving path
        self.event_channel = event_channel
        self.events_dropped = 0
        self.metrics = DaemonMetrics(metric_flags=conf.metric_flags)
        if engine is not None:
            self.engine = engine
            if store is not None:
                engine.store = store
        elif conf.engine == "sharded":
            # one daemon serving a whole device mesh: the table shards over
            # every local device, ownership = fingerprint % n_shards. The
            # mesh-global engine additionally serves the GLOBAL behavior as
            # collectives (replica answers + all_gather sync over ICI) when
            # this daemon runs standalone — the BASELINE #3 topology where
            # the mesh IS the peer group.
            import jax

            from gubernator_tpu.parallel import make_mesh
            from gubernator_tpu.parallel.global_sync import GlobalShardedEngine

            n_dev = len(jax.devices())
            self.engine = GlobalShardedEngine(
                # topology resolves inside make_mesh: GUBER_MESH_HOSTS (the
                # simulated multi-host mode) or jax.process_count() fold the
                # devices into 2-D (host, device) axes; single hosts keep
                # the seed's 1-D "shard" axis
                make_mesh(n_dev),
                capacity_per_shard=max(1, conf.cache_size // n_dev),
                created_at_tolerance_ms=int(conf.created_at_tolerance_ms),
                store=store,
                # "auto" = the backend default (device routing + in-trace
                # dedup on TPU meshes, host grid + pass planner elsewhere)
                route=None if conf.shard_route == "auto" else conf.shard_route,
                dedup=None if conf.shard_dedup == "auto" else conf.shard_dedup,
                # exchange schedule for device-routed dispatches
                # (parallel/ring.py; "auto" = ring on TPU backends)
                a2a=None if conf.a2a_impl == "auto" else conf.a2a_impl,
                # table-walk kernel (ops/pallas_probe.py; "auto" = xla
                # until the device bench record flips the default)
                probe=None if conf.probe_kernel == "auto"
                else conf.probe_kernel,
                # install/merge walk kernel (fused vs two-pass; same
                # default-flip policy, independent knob)
                walk=None if conf.walk_kernel == "auto"
                else conf.walk_kernel,
            )
        else:
            self.engine = LocalEngine(
                capacity=conf.cache_size,
                created_at_tolerance_ms=int(conf.created_at_tolerance_ms),
                store=store,
                probe=None if conf.probe_kernel == "auto"
                else conf.probe_kernel,
                walk=None if conf.walk_kernel == "auto"
                else conf.walk_kernel,
            )
        self.runner = EngineRunner(
            self.engine,
            metrics=self.metrics,
            fetch_workers=conf.behaviors.pipeline_inflight,
        )
        # device-resident request ring (service/ring.py; docs/latency.md
        # "Dispatch budget"): when armed, all-wire flushes stage into ring
        # slots and the persistent serving loop consumes them in ticket
        # order — the CPU build runs the functional emulation of the
        # device ring protocol over the same runner surface
        self.ring = None
        if conf.behaviors.ring_enable:
            from gubernator_tpu.ops.ring_drain import default_ring_issue
            from gubernator_tpu.service.ring import RequestRing

            ring_issue = conf.behaviors.ring_issue
            if ring_issue == "auto":
                # fused drain on real TPU, host issue loop on CPU builds
                # (docs/latency.md "Launch budget")
                ring_issue = default_ring_issue()
            self.ring = RequestRing(
                self.runner,
                slots=conf.behaviors.ring_slots,
                metrics=self.metrics,
                issue_mode=ring_issue,
                drain_k=conf.behaviors.ring_drain_k,
                slot_width=conf.behaviors.ring_slot_width,
            )
        self.batcher = Batcher(
            self.runner,
            batch_wait_ms=conf.behaviors.batch_wait_ms,
            coalesce_limit=conf.behaviors.coalesce_limit,
            metrics=self.metrics,
            max_inflight=conf.behaviors.pipeline_inflight,
            workers=conf.behaviors.front_workers,
            adaptive=conf.behaviors.adaptive_batch,
            close_rows=conf.behaviors.batch_close_rows,
            close_bytes=conf.behaviors.batch_close_bytes,
            max_queue_rows=conf.behaviors.batch_queue_rows,
            ring=self.ring,
            overload_deadline_ms=conf.behaviors.overload_deadline_ms,
            overload_deadline_auto=conf.behaviors.overload_deadline_auto,
            tenant_share=conf.behaviors.overload_tenant_share,
            tenant_buckets=conf.behaviors.overload_tenant_buckets,
            shed_retry_ms=conf.behaviors.overload_retry_ms,
        )
        # front-door parse/encode pool: the native parser and response
        # encoder drop the GIL, so offloading big request buffers here lets
        # N workers parse/encode concurrently while the event loop keeps
        # accepting connections. Tiny requests stay inline — the executor
        # hop costs more than the parse.
        from concurrent.futures import ThreadPoolExecutor

        n_door = conf.behaviors.front_workers or max(
            2, conf.behaviors.pipeline_inflight // 2
        )
        self._door = ThreadPoolExecutor(
            max_workers=n_door, thread_name_prefix="door"
        )
        self.global_manager = GlobalManager(self)
        from gubernator_tpu.service.region_manager import RegionManager

        self.region_manager = RegionManager(self)
        # edge quota leases (docs/leases.md): the V1 LeaseQuota surface —
        # bounded slices of a limit delegated to client-side admission,
        # accounted through the normal decide path + a CONCURRENCY_LEASE
        # outstanding ledger (TTL reclamation)
        from gubernator_tpu.service.lease_manager import LeaseManager

        self.lease_manager = LeaseManager(self)
        # incremental-checkpoint plane (service/checkpoint.py): inert unless
        # GUBER_CHECKPOINT_INTERVAL_MS > 0 — then a background loop appends
        # dirty-block delta frames beside the base snapshot and restart
        # replays base + deltas (docs/durability.md)
        from gubernator_tpu.service.checkpoint import CheckpointManager

        self.checkpointer = CheckpointManager(self)
        # hot-set tiering plane (gubernator_tpu/tier/; docs/tiering.md):
        # inert unless GUBER_TIER_ENABLED — then evicted/idle rows demote
        # to a host-RAM shadow instead of vanishing, and host staging
        # faults them back through the conservative merge
        from gubernator_tpu.tier.manager import TierManager

        self.tier = TierManager(self)
        self._tier_task = None
        self._checkpoint_task = None
        self._maintenance_task = None
        self._global_sync_task = None  # mesh-global collective sync tick
        self._telemetry_task = None  # background table-telemetry cadence
        self._table_telemetry = None  # last ops/telemetry.TableSnapshot
        self._local_picker = ReplicatedConsistentHash()
        self._region_picker = RegionPicker()
        self._peer_clients: Dict[str, PeerClient] = {}
        # breakers OUTLIVE their clients, keyed by address: a flapping
        # discovery backend that drops and re-adds a peer must not reset an
        # open breaker to closed (the peer is no healthier for having
        # blinked out of the peer list)
        self._peer_breakers: Dict[str, CircuitBreaker] = {}
        # clients dropped by set_peers while no event loop was running —
        # drained on the next loop entry (or close) instead of leaking
        self._orphaned_clients: List[PeerClient] = []
        # topology-change handoff (service/handoff.py): fp→ring-point
        # sidecar + the transfer manager + idempotency ledger for received
        # chunks ((transfer_id, chunk) → merged count)
        from gubernator_tpu.service.handoff import HandoffManager

        self.ownership = OwnershipIndex()
        self.handoff = HandoffManager(self)
        self._applied_transfers: "collections.OrderedDict" = (
            collections.OrderedDict()
        )
        self._handoff_tasks: set = set()
        self._leaving = False  # drain in progress → health shows "leaving"
        self._shutting_down = False
        self._servers = []  # transport handles (service/server.py)
        self._pool = None  # discovery pool
        self.grpc_port: Optional[int] = None
        self.http_port: Optional[int] = None
        self.status_http_port: Optional[int] = None
        self._client_creds = None  # set by TLS setup
        self._cert_watch_task = None
        self._http_ssl_contexts = []  # live HTTPS listener contexts

    # ---------------------------------------------------------------- spawn
    @classmethod
    async def spawn(
        cls,
        conf: DaemonConfig,
        engine: Optional[LocalEngine] = None,
        event_channel: Optional[asyncio.Queue] = None,
        store=None,
        loader=None,
    ):
        """SpawnDaemon analog (reference daemon.go:75-88): build, restore
        checkpoint, start listeners + loops + discovery."""
        d = cls(
            conf, engine=engine, event_channel=event_channel, store=store,
            loader=loader,
        )
        if tracing.exporter is None:
            # standard OTEL_* envs wire a real span exporter (reference
            # cmd/gubernator/main.go:90-97 InitTracing); process-global —
            # in-process clusters share one pipeline like one binary would
            from gubernator_tpu.otel import exporter_from_env

            exp = exporter_from_env()
            if exp is not None:
                tracing.set_exporter(exp)
                log.info("OTLP trace export enabled → %s", exp.endpoint)
        d.maybe_restore()
        await d.warm_up()
        if d.checkpointer.enabled:
            # epoch tracker attaches BEFORE the listeners open: every
            # serving mutation from the first request onward is marked
            d.checkpointer.attach()
        if d.tier.enabled:
            # AFTER the checkpoint restore (delta replay — including
            # tombstone frames — settles HBM first), before serving
            d.tier.attach()
        from gubernator_tpu.service.server import start_servers

        await start_servers(d)
        d.global_manager.start()
        d.region_manager.start()
        if getattr(d.engine, "mesh_global", False):
            # collective GLOBAL sync tick (GlobalSyncWait cadence, reference
            # config.go:142-146) — the in-mesh analog of runAsyncHits +
            # runBroadcasts, collapsed into one collective step
            d._global_sync_task = asyncio.create_task(
                d._global_sync_loop(), name="mesh-global-sync"
            )
        if conf.telemetry_interval_ms > 0:
            # background table-telemetry cadence (docs/observability.md):
            # the scan is issued on the engine thread and fetched off it, so
            # it overlaps serving dispatches — never the serving path
            d._telemetry_task = asyncio.create_task(
                d._telemetry_loop(), name="table-telemetry"
            )
        if d.checkpointer.enabled:
            # incremental checkpoint cadence (docs/durability.md): extract
            # launch on the engine thread, fetch + frame append off it —
            # checkpointing overlaps serving like the telemetry scan does
            d._checkpoint_task = asyncio.create_task(
                d.checkpointer.loop(), name="checkpoint"
            )
        if d.tier.enabled:
            # demote-on-idle sweep on the telemetry cadence: extract +
            # tombstone in one engine job, shadow append + spill flush +
            # tombstone frame off it (docs/tiering.md)
            d._tier_task = asyncio.create_task(
                d.tier.loop(), name="tier-sweep"
            )
        if d._client_creds is not None and conf.tls_cert_file:
            # rotation watcher: the gRPC server hot-reloads per handshake,
            # but peer-forwarding CLIENTS hold credentials from startup — on
            # a cert rotation they must re-dial with the new pair or
            # verify-mode clusters break both directions until restart
            d._cert_watch_task = asyncio.create_task(
                d._cert_watch_loop(), name="cert-watch"
            )
        await d._start_discovery()
        if conf.cache_max_size > conf.cache_size:
            if getattr(d.engine, "supports_grow", False):
                d._maintenance_task = asyncio.create_task(
                    d._maintenance_loop(), name="table-maintenance"
                )
            else:
                log.warning(
                    "GUBER_CACHE_MAX_SIZE is set but the %s engine cannot "
                    "auto-grow; the table stays at its construction size",
                    conf.engine,
                )
        return d

    async def _global_sync_loop(self) -> None:
        """Mesh-global sync tick: drain accumulated GLOBAL hits through the
        collective step every GlobalSyncWait. Empty ticks skip the dispatch —
        the reference's timer also idles when no hits are queued
        (global.go:125-151)."""
        wait_s = self.conf.behaviors.global_sync_wait_ms / 1e3
        while not self._shutting_down:
            await asyncio.sleep(wait_s)
            try:
                if self.engine.has_pending():
                    await self.runner.sync_global()
            except asyncio.CancelledError:
                raise
            except Exception:  # pragma: no cover - defensive
                log.exception("mesh global sync tick failed")

    async def _telemetry_loop(self) -> None:
        """Background table-telemetry cadence (GUBER_TELEMETRY_INTERVAL_MS):
        refresh the gubernator_tpu_table_* families, the /v1/debug/table
        snapshot, cache_size, and the GLOBAL staleness gauge."""
        wait_s = self.conf.telemetry_interval_ms / 1e3
        while not self._shutting_down:
            await asyncio.sleep(wait_s)
            try:
                await self.collect_telemetry()
            except asyncio.CancelledError:
                raise
            except Exception:  # pragma: no cover - defensive
                log.exception("table telemetry tick failed")

    async def collect_telemetry(self):
        """One telemetry round: scan the table (engine-thread launch, off-
        thread fetch — EngineRunner.table_telemetry) and publish the
        snapshot. Also callable on demand (the debug endpoint uses it when
        the loop is disabled)."""
        snap = await self.runner.table_telemetry()
        self._table_telemetry = snap
        self.metrics.observe_table(snap)
        # the scan counts live keys anyway — keep cache_size fresh between
        # /metrics scrapes for free
        self.metrics.cache_size.set(snap.live_keys)
        self.metrics.global_sync_staleness.set(self.global_sync_staleness_s())
        self.metrics.region_sync_staleness.set(
            self.region_manager.oldest_delta_age_s()
        )
        return snap

    def global_sync_staleness_s(self) -> float:
        """Age of the oldest un-synced GLOBAL hit across BOTH planes: the
        cross-daemon async queue (GlobalManager) and the in-mesh outbox
        (GlobalShardedEngine.pending). The convergence-lag signal the
        multi-region roadmap item is judged on — if this grows while
        traffic flows, replicas are falling behind their owners."""
        age = self.global_manager.oldest_hit_age_s()
        mesh_age = getattr(self.engine, "oldest_pending_age_s", None)
        if mesh_age is not None:
            age = max(age, mesh_age())
        return age

    async def _maintenance_loop(self) -> None:
        """Auto-grow tick: double the table when live keys pass 60% of
        capacity, up to GUBER_CACHE_MAX_SIZE."""
        while not self._shutting_down:
            await asyncio.sleep(2.0)
            try:
                grew = await self.runner.maybe_grow(
                    max_capacity=self.conf.cache_max_size
                )
                if grew:
                    live = await self.runner.live_count()
                    self.metrics.cache_size.set(live)
                    log.info(
                        "table grew to %d slots (%d live)",
                        self.engine.table.capacity, live,
                    )
            except asyncio.CancelledError:
                raise
            except Exception:  # pragma: no cover - defensive
                log.exception("table maintenance tick failed")

    async def _cert_watch_loop(self) -> None:
        """Rebuild peer-client credentials + channels when the PEM files
        rotate (complements the server side's per-handshake hot reload)."""
        from gubernator_tpu.service.tls import (
            _validate_keypair,
            bundle_from_config,
            cert_files_mtimes,
            client_credentials,
        )

        last = cert_files_mtimes(self.conf)
        while not self._shutting_down:
            await asyncio.sleep(self.cert_watch_interval_s)
            try:
                now_mt = cert_files_mtimes(self.conf)
                if now_mt is None or now_mt == last:
                    continue
                # a torn rotation (cert written, key not yet) must neither
                # commit `last` (so the next tick retries) nor tear down
                # working channels — same guard as the server-side reloader
                try:
                    _validate_keypair(bundle_from_config(self.conf))
                except Exception:
                    log.warning(
                        "rotated TLS files failed validation; keeping the "
                        "current peer credentials until the next check"
                    )
                    continue
                last = now_mt
                self._client_creds = client_credentials(self.conf)
                # force-recreate every peer channel with the new credentials;
                # set_peers reuses clients by address, so drop them first and
                # drain the old ones
                old = self._peer_clients
                self._peer_clients = {}
                peers = self.local_peers() + self.region_peers()
                self.set_peers([PeerInfo(**vars(p)) for p in peers])
                await asyncio.gather(
                    *(c.shutdown() for c in old.values()), return_exceptions=True
                )
                # HTTPS listeners share long-lived SSLContexts: reload the
                # chain in place so new handshakes serve the rotated pair
                # (gRPC reloads per-handshake; these must not lag behind)
                for ctx in self._http_ssl_contexts:
                    try:
                        ctx.load_cert_chain(
                            self.conf.tls_cert_file, self.conf.tls_key_file
                        )
                    except Exception:
                        log.warning(
                            "HTTP listener certificate reload failed; "
                            "keeping the current pair"
                        )
                log.info("TLS certificates rotated; peer channels re-dialed")
            except asyncio.CancelledError:
                raise
            except Exception:  # pragma: no cover - defensive
                log.exception("certificate rotation check failed")

    async def warm_up(self) -> None:
        """Compile the decision + install kernels for the smallest batch shape
        BEFORE serving: the first XLA compile takes seconds, which would blow
        the 500 ms peer-RPC budgets (global_timeout, batch_timeout) and drop
        the first GLOBAL sync round of a fresh daemon. All three static math
        variants compile (engine._math_mode picks per dispatch): an all-token
        warm batch alone would leave the first leaky- or GCRA-carrying
        request to pay the mixed/int graph's compile on the request path.
        Packed-layout tables (GUBER_SLOT_LAYOUT) warm ONLY their own
        family's graph — an off-family warm batch would migrate the table
        to full before the first real request arrives."""
        lay = getattr(self.engine.table, "layout", None)
        variants = (
            [0],  # math="token" graph
            [2],  # math="gcra" graph (all-GCRA specialization)
            [2, 3],  # math="int" graph (mixed integer algorithms)
            [1],  # math="mixed" graph
        )
        if lay is not None and lay.algos is not None:
            variants = tuple(
                v for v in variants
                if lay.supports_algos(np.asarray(v, dtype=np.int32))
            )
        for algos in variants:
            n = len(algos)
            warm = RequestColumns(
                fp=np.arange(1, n + 1, dtype=np.int64),
                algo=np.asarray(algos, dtype=np.int32),
                behavior=np.zeros(n, dtype=np.int32),
                hits=np.zeros(n, dtype=np.int64),
                limit=np.ones(n, dtype=np.int64),
                burst=np.zeros(n, dtype=np.int64),
                duration=np.ones(n, dtype=np.int64),  # expires ~immediately
                created_at=np.zeros(n, dtype=np.int64),
                err=np.zeros(n, dtype=np.int8),
            )
            await self.runner.check_columns(warm)
        warm_install_algo = (
            lay.algos[0]
            if lay is not None and lay.algos is not None else 0
        )
        await self.runner.install_columns(
            fp=np.asarray([1], dtype=np.int64),
            algo=np.full(1, warm_install_algo, dtype=np.int32),
            status=np.zeros(1, dtype=np.int32),
            limit=np.ones(1, dtype=np.int64),
            remaining=np.ones(1, dtype=np.int64),
            reset_time=np.ones(1, dtype=np.int64),
            duration=np.ones(1, dtype=np.int64),
            now_ms=1,
        )
        # the install warm above already traced the install walk under the
        # engine's resolved walk_mode (GUBER_WALK_KERNEL threads through
        # install2/merge2 transparently). The merge walk is warmed for
        # region daemons (the replication receive path) AND whenever the
        # fused Pallas walks are armed — tiering promotes and handoff
        # merges ride merge2 too, and a fused-walk graph compiling on the
        # first promote would stall the engine thread mid-serving.
        fused_walks = getattr(self.engine, "walk_mode", "xla") == "pallas"
        if self.conf.data_center or fused_walks:
            # region plane (docs/robustness.md "Multi-region active-
            # active"): pre-trace the stored-state read (the sender's
            # staging gather) and the conservative merge (the receiver's
            # reconcile) so the first replicated batch doesn't pay an XLA
            # compile inside a peer's RPC deadline — a timed-out first
            # sync would requeue and re-apply as a duplicate (under-
            # granting, but needlessly). DC-less daemons never replicate,
            # so they skip the staging-read compile.
            from gubernator_tpu.ops.table2 import F as F_FULL

            fp1 = np.asarray([1], dtype=np.int64)
            if self.conf.data_center:
                await self.runner.read_state_raw(fp1)
            # an all-zero incoming row is expired at every clock: the
            # merge kernel compiles, the table keeps its bytes
            await self.runner.merge_rows(
                fp1, np.zeros((1, F_FULL), dtype=np.int32)
            )
        # GUBER_WARM_SHAPES=pow2[-mixed]: additionally compile every pow2
        # coalesce geometry up to the coalesce cap (like bench.py's e2e
        # prewarm) so no production batch shape ever compiles on the
        # request path; off by default — it multiplies spawn time by the
        # shape count, which in-process test clusters cannot afford
        mode = self.conf.behaviors.warm_shapes
        if mode in ("pow2", "pow2-mixed"):
            from gubernator_tpu.ops.engine import _pad_size

            algos = [0] if mode == "pow2" else [0, 1]
            size = 16
            # up to the PADDED top shape: a non-pow2 coalesce_limit still
            # pads saturated batches to the next pow2, which must be warm
            top = _pad_size(int(self.conf.behaviors.coalesce_limit))
            while size <= top:
                for a in algos:
                    warm = RequestColumns(
                        fp=np.arange(1, size + 1, dtype=np.int64),
                        algo=np.full(size, a, dtype=np.int32),
                        behavior=np.zeros(size, dtype=np.int32),
                        hits=np.zeros(size, dtype=np.int64),
                        limit=np.ones(size, dtype=np.int64),
                        burst=np.zeros(size, dtype=np.int64),
                        duration=np.ones(size, dtype=np.int64),
                        created_at=np.zeros(size, dtype=np.int64),
                        err=np.zeros(size, dtype=np.int8),
                    )
                    await self.runner.check_columns(warm)
                size *= 2
            # herd geometries: a same-key batch plans j sequential passes
            # (j ≤ max_exact) whose same-shape outputs fuse into one
            # stacked fetch (ops/engine._stack_pass_outputs) — trace the
            # stack kernel for every pass count now, or the first
            # production herd pays that compile on the request path
            max_exact = getattr(self.engine, "max_exact_passes", 8)
            for j in range(2, max_exact + 1):
                warm = RequestColumns(
                    fp=np.full(j, 7, dtype=np.int64),
                    algo=np.zeros(j, dtype=np.int32),
                    behavior=np.zeros(j, dtype=np.int32),
                    hits=np.zeros(j, dtype=np.int64),
                    limit=np.ones(j, dtype=np.int64),
                    burst=np.zeros(j, dtype=np.int64),
                    duration=np.ones(j, dtype=np.int64),
                    created_at=np.zeros(j, dtype=np.int64),
                    err=np.zeros(j, dtype=np.int8),
                )
                # through the PIPELINED door: the stack kernel only traces
                # on the issue path (serial check_columns never stacks)
                await self.runner.check(warm)
            if (
                getattr(self.engine, "mesh_global", False)
                and self.engine.store is None
            ):
                # pre-trace the collective sync steps (single + fused R
                # variants) so the first deep GLOBAL backlog can't compile
                # on the engine thread mid-tick
                await asyncio.get_running_loop().run_in_executor(
                    self.runner._exec, self.engine.warm_sync_steps
                )
                from gubernator_tpu.parallel.global_sync import GlobalStats

                self.engine.global_stats = GlobalStats()
        # warm-up is not traffic: reset counters so tests and metrics see
        # only real requests. The pipelined warms above apply their stats
        # deltas fire-and-forget on the engine executor — flush it first or
        # a late apply lands AFTER the reset and resurrects warm-up counts
        from gubernator_tpu.ops.engine import EngineStats

        await asyncio.get_running_loop().run_in_executor(
            self.runner._exec, lambda: None
        )
        self.engine.stats = EngineStats()
        self.metrics._last_engine = None

    async def _start_discovery(self) -> None:
        kind = self.conf.peer_discovery_type
        if kind == "dns":
            from gubernator_tpu.discovery.dns import DNSPool

            self._pool = DNSPool(
                fqdn=self.conf.dns_fqdn,
                poll_ms=self.conf.dns_poll_ms,
                on_update=self.set_peers,
                self_address=self.conf.advertise_address,
                http_address=self.conf.http_address,
                data_center=self.conf.data_center,
            )
        elif kind == "etcd":
            from gubernator_tpu.discovery.etcd import EtcdPool

            self._pool = EtcdPool(
                endpoint=self.conf.etcd_endpoint,
                on_update=self.set_peers,
                peer_info=self.peer_info(),
                key_prefix=self.conf.etcd_key_prefix,
                lease_ttl_s=self.conf.etcd_lease_ttl_s,
                poll_ms=self.conf.etcd_poll_ms,
            )
        elif kind == "member-list":
            from gubernator_tpu.discovery.memberlist import MemberlistPool

            self._pool = MemberlistPool(
                bind_address=self.conf.memberlist_address,
                advertise_address=self.conf.memberlist_advertise_address,
                known_nodes=[
                    n.strip()
                    for n in self.conf.memberlist_known_nodes.split(",")
                    if n.strip()
                ],
                on_update=self.set_peers,
                peer_info=self.peer_info(),
                gossip_interval_ms=self.conf.memberlist_gossip_interval_ms,
                secret_keys=self.conf.memberlist_keyring(),
            )
        elif kind == "k8s":
            from gubernator_tpu.discovery.kubernetes import K8sPool

            self._pool = K8sPool(
                on_update=self.set_peers,
                pod_ip=self.conf.k8s_pod_ip,
                pod_port=self.conf.k8s_pod_port
                or self.conf.grpc_address.rsplit(":", 1)[-1],
                namespace=self.conf.k8s_namespace,
                selector=self.conf.k8s_selector,
                mechanism=self.conf.k8s_mechanism,
                api_url=self.conf.k8s_api_url,
                poll_ms=self.conf.k8s_poll_ms,
            )
        if self._pool is not None:
            await self._pool.start()
        # "none": explicit set_peers calls (reference daemon.go:258-262)

    # ---------------------------------------------------------------- peers
    def peer_info(self) -> PeerInfo:
        return PeerInfo(
            grpc_address=self.conf.advertise_address,
            http_address=self.conf.http_address,
            data_center=self.conf.data_center,
            is_owner=True,
        )

    def set_peers(self, peers: List[PeerInfo]) -> None:
        """Hot-swap the peer set (reference SetPeers, gubernator.go:694-789):
        rebuild both pickers from scratch, reuse live PeerClients by address
        (and CircuitBreakers across churn — a flapping discovery backend
        must not reset open breakers), drain clients for peers that
        disappeared, and launch a device-side ownership handoff for live
        rows whose ring owner moved (service/handoff.py)."""
        old_local = self._local_picker
        local = ReplicatedConsistentHash()
        region = RegionPicker()
        keep: Dict[str, PeerClient] = {}
        for info in peers:
            info.is_owner = info.grpc_address == self.conf.advertise_address
            if not info.data_center or info.data_center == self.conf.data_center:
                local.add(info)
            else:
                region.add(info)
            if not info.is_owner:
                client = self._peer_clients.get(info.grpc_address)
                if client is None:
                    b = self.conf.behaviors
                    breaker = self._peer_breakers.get(info.grpc_address)
                    if breaker is None:
                        breaker = CircuitBreaker(
                            failure_threshold=b.peer_breaker_errors,
                            backoff_base_ms=b.peer_breaker_backoff_base_ms,
                            backoff_cap_ms=b.peer_breaker_backoff_cap_ms,
                            probe_budget=b.peer_breaker_probes,
                        )
                        self._peer_breakers[info.grpc_address] = breaker
                    client = PeerClient(
                        info,
                        batch_wait_ms=b.batch_wait_ms,
                        batch_limit=b.batch_limit,
                        batch_timeout_ms=b.batch_timeout_ms,
                        metrics=self.metrics,
                        channel_credentials=self._client_creds,
                        breaker=breaker,
                    )
                keep[info.grpc_address] = client
        dropped = [
            c for a, c in self._peer_clients.items() if a not in keep
        ]
        self._peer_clients = keep
        self._local_picker = local
        self._region_picker = region
        # closed breakers of departed peers carry no state worth keeping;
        # open/half-open ones persist so a re-added peer resumes its cooldown
        for addr in list(self._peer_breakers):
            if (
                addr not in keep
                and self._peer_breakers[addr].state is BreakerState.CLOSED
            ):
                del self._peer_breakers[addr]
        self._orphaned_clients.extend(dropped)
        self._flush_orphans()
        # ---- topology-change handoff: live rows whose ownership moved away
        # from this daemon follow it to the new owner (rebalance diff). The
        # initial set_peers (old ring empty) and no-op swaps (same address
        # set — e.g. the cert watcher's re-dial, a peer restart) skip it.
        if (
            self.conf.behaviors.handoff_enabled
            and not self._shutting_down
            and old_local.size() > 0
            and local.size() > 0
            and {p.grpc_address for p in old_local.peers()}
            != {p.grpc_address for p in local.peers()}
        ):
            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                pass  # no loop (synchronous test wiring): nothing to move yet
            else:
                t = loop.create_task(
                    self._rebalance_handoff(old_local, local),
                    name="handoff-rebalance",
                )
                self._handoff_tasks.add(t)
                t.add_done_callback(self._handoff_tasks.discard)

    async def _rebalance_handoff(self, old_picker, new_picker) -> None:
        try:
            await self.handoff.rebalance(old_picker, new_picker)
        except asyncio.CancelledError:
            raise
        except Exception:  # pragma: no cover - defensive
            log.exception("ownership rebalance handoff failed")

    def _flush_orphans(self) -> None:
        """Drain clients dropped by set_peers. With no running loop (tests
        wiring daemons synchronously) the clients stay queued and close on
        the next loop entry — previously they leaked their channels."""
        if not self._orphaned_clients:
            return
        clients, self._orphaned_clients = self._orphaned_clients, []

        async def drain():
            await asyncio.gather(
                *(c.shutdown() for c in clients), return_exceptions=True
            )

        try:
            asyncio.get_running_loop().create_task(drain())
        except RuntimeError:
            self._orphaned_clients = clients  # retried on next loop entry

    def local_peers(self) -> List[PeerInfo]:
        return self._local_picker.peers()

    def region_peers(self) -> List[PeerInfo]:
        return self._region_picker.peers()

    def region_owners(self, key: str) -> List[PeerInfo]:
        """The key's owner in every OTHER datacenter (region picker holds only
        non-local DCs, see set_peers)."""
        return self._region_picker.get_clients(key)

    def get_peer(self, key: str) -> PeerInfo:
        return self._local_picker.get(key)

    def is_self(self, info: PeerInfo) -> bool:
        return info.grpc_address == self.conf.advertise_address

    def peer_client(self, info: PeerInfo) -> Optional[PeerClient]:
        return self._peer_clients.get(info.grpc_address)

    def now_ms(self) -> int:
        return ms_now()

    # ------------------------------------------------------------ V1 service
    async def get_rate_limits(
        self, items: List["pb.RateLimitReq"]
    ) -> List["pb.RateLimitResp"]:
        if len(items) > self.conf.max_batch_size:
            raise ValueError(batch_too_large_error(self.conf.max_batch_size))
        self.metrics.concurrent_checks.inc()
        # ingress scope: adopt the client's trace when one is propagated in
        # request metadata, else start a fresh root span
        token = None
        for it in items:
            parent = tracing.extract(it.metadata)
            if parent is not None:
                token = tracing.start_scope("GetRateLimits", parent)
                break
        if token is None:
            token = tracing.start_scope("GetRateLimits")
        try:
            return await self._route(items)
        finally:
            tracing.end_scope(token)
            self.metrics.concurrent_checks.dec()

    async def _route(self, items) -> List["pb.RateLimitResp"]:
        n = len(items)
        if self.conf.behaviors.force_global:
            for it in items:
                it.behavior |= int(Behavior.GLOBAL)
        cols, hash_keys = columns_from_pb(items)
        out: List[Optional[pb.RateLimitResp]] = [None] * n

        standalone = self._local_picker.size() == 0
        local_rows: List[int] = []
        global_rows: List[int] = []
        forwards: List[tuple] = []  # (row, key, item)
        owner_global_rows: List[int] = []
        owner_region_rows: List[int] = []
        for i in range(n):
            if cols.err[i] != 0:
                out[i] = pb.RateLimitResp(error=ERROR_STRINGS[int(cols.err[i])])
                continue
            is_global = bool(cols.behavior[i] & int(Behavior.GLOBAL))
            is_mr = bool(cols.behavior[i] & int(Behavior.MULTI_REGION))
            if standalone:
                local_rows.append(i)
                if is_global:
                    owner_global_rows.append(i)
                if is_mr:
                    owner_region_rows.append(i)
                continue
            info = self.get_peer(hash_keys[i])
            if self.is_self(info):
                local_rows.append(i)
                if is_global:
                    owner_global_rows.append(i)
                if is_mr:
                    owner_region_rows.append(i)
            elif is_global:
                global_rows.append(i)
            else:
                forwards.append((i, hash_keys[i], items[i]))

        if local_rows and not standalone and self.conf.behaviors.handoff_enabled:
            # sidecar for topology-change handoff: remember each owned row's
            # ring point (fp and point are not mutually derivable — the
            # native path records the wire parser's points vectorized)
            self.ownership.record_keys(
                (cols.fp[i] for i in local_rows),
                (hash_keys[i] for i in local_rows),
                self._local_picker.hash_fn,
            )
        if owner_global_rows and not standalone:
            # clustered: owner-daemon GLOBAL answers must stay authoritative
            # so the cross-daemon broadcast (queue_update below) carries a
            # fresh status; the engine's mesh replica plane serves GLOBAL only
            # when this daemon runs standalone (the mesh IS the peer group)
            cols.behavior[np.asarray(owner_global_rows)] &= ~np.int32(
                int(Behavior.GLOBAL)
            )
        tasks = []
        if local_rows:
            rows = np.asarray(local_rows)
            tasks.append(self._check_rows(cols, rows, out, items))
        if global_rows:
            rows = np.asarray(global_rows)
            # answer from local state with GLOBAL stripped + NO_BATCHING
            # forced (reference gubernator.go:416-422), and queue async hits
            gcols = subset_columns(cols, rows)
            gcols = gcols._replace(
                behavior=(gcols.behavior & ~np.int32(int(Behavior.GLOBAL)))
                | np.int32(int(Behavior.NO_BATCHING))
            )
            for i in global_rows:
                self.global_manager.queue_hit(hash_keys[i], items[i])
            tasks.append(self._check_subset(gcols, rows, out, items))
        for row, key, item in forwards:
            tasks.append(self._forward(row, key, item, out))
        if tasks:
            await asyncio.gather(*tasks)
        # owner-side GLOBAL items broadcast their fresh status (reference
        # getLocalRateLimit → QueueUpdate, gubernator.go:670-672). A
        # standalone mesh-global daemon skips this: the collective plane IS
        # the broadcast, and there are no peer daemons to push to.
        if not (standalone and getattr(self.engine, "mesh_global", False)):
            for i in owner_global_rows:
                self.global_manager.queue_update(hash_keys[i], items[i])
        # owner-side MULTI_REGION hits replicate to the other DCs' owners
        for i in owner_region_rows:
            self.region_manager.queue_hit(hash_keys[i], items[i])
        # audit events fire for locally-executed (owner-side) hits only
        # (reference gubernator.go:676-688)
        if self.event_channel is not None:
            for i in local_rows:
                self._emit_event(items[i], out[i])
        for i in range(n):
            if out[i] is None:  # pragma: no cover - defensive
                out[i] = pb.RateLimitResp(error="internal: row not routed")
            if out[i].status == pb.OVER_LIMIT:
                self.metrics.over_limit_counter.inc()
        return out  # type: ignore[return-value]

    async def lease_quota(self, req: "pb.LeaseQuotaReq") -> "pb.LeaseQuotaResp":
        """One edge quota-lease operation (service/lease_manager.py): grant
        a bounded slice of a limit for client-side admission, renew it, or
        take unused tokens back. The grant/refund rows ride the exact
        routing this daemon's GetRateLimits uses, so ownership, GLOBAL and
        MULTI_REGION behaviors see leased consumption as ordinary hits."""
        return await self.lease_manager.lease_quota(req)

    # ------------------------------------------------- native raw fast path
    # requests below this many wire bytes parse inline: the door-pool
    # executor hop costs more than the parse itself for small buffers
    DOOR_OFFLOAD_BYTES = 4096

    async def get_rate_limits_raw(self, data: bytes) -> bytes:
        """Serve GetRateLimitsReq wire bytes → GetRateLimitsResp wire bytes.

        The native ingress (gubernator_tpu/native) parses the request buffer
        straight into column arrays AND pre-packed compact-wire lanes in one
        pass — no per-item Python objects on the owner-local path, and (for
        wire-encodable batches against a compact-wire local engine) no
        column re-pack either: the batcher stages the parser's lanes
        directly into the dispatch grid. Big buffers parse on the door pool
        (the C parser drops the GIL, so N workers parse concurrently); only
        items that must travel as messages (forwards, GLOBAL/MULTI_REGION
        queue entries) materialize lazily from their wire spans. Falls back
        to the pb path when the extension is unavailable or an event channel
        needs full request objects."""
        from gubernator_tpu.service.wire import wire_batch_from_wire

        parsed = None
        parse_s = 0.0
        if self.event_channel is None:
            t0 = time.perf_counter()
            if len(data) >= self.DOOR_OFFLOAD_BYTES:
                parsed = await asyncio.get_running_loop().run_in_executor(
                    self._door, wire_batch_from_wire, data
                )
            else:
                parsed = wire_batch_from_wire(data)
            parse_s = time.perf_counter() - t0
        if parsed is None:
            req = pb.GetRateLimitsReq.FromString(data)
            resps = await self.get_rate_limits(list(req.requests))
            return pb.GetRateLimitsResp(responses=resps).SerializeToString()
        wb, ring, spans, traceparent = parsed
        n = wb.rows
        if n > self.conf.max_batch_size:
            raise ValueError(batch_too_large_error(self.conf.max_batch_size))
        self.metrics.concurrent_checks.inc()
        parent = tracing.parse_traceparent(traceparent) if traceparent else None
        token = tracing.start_scope("GetRateLimits", parent)
        # parse is a stage of THIS request (not of any batch dispatch):
        # observed under the request span so its exemplar resolves to the
        # request's own trace; the child span makes "where did my p99 go"
        # decomposable per request
        self._observe_request_stage("parse", parse_s, token.span)
        try:
            return await self._route_raw(data, wb, ring, spans)
        finally:
            tracing.end_scope(token)
            self.metrics.concurrent_checks.dec()

    async def _route_raw(self, data, wb, ring, spans) -> bytes:
        from gubernator_tpu.service.wire import (
            encode_response_columns,
            item_from_span,
            subset_wire,
        )

        cols = wb.cols
        n = cols.fp.shape[0]
        force_global = self.conf.behaviors.force_global
        if force_global:
            # GLOBAL is kernel-inert (dropped on the compact wire), so the
            # routing-only behavior flip leaves the parser's lanes valid
            cols = cols._replace(
                behavior=cols.behavior | np.int32(int(Behavior.GLOBAL))
            )
            wb = wb._replace(cols=cols)

        def materialize(i):
            """Lazy pb item from its wire span; a forced GLOBAL bit must
            follow the item into queues/forwards (the pb path mutates items
            in place, gubernator.go:239-241)."""
            item = item_from_span(data, spans[i])
            if force_global:
                item.behavior |= int(Behavior.GLOBAL)
            return item
        status = np.zeros(n, dtype=np.int64)
        limit = np.zeros(n, dtype=np.int64)
        remaining = np.zeros(n, dtype=np.int64)
        reset = np.zeros(n, dtype=np.int64)
        errors: Dict[int, str] = {
            int(i): ERROR_STRINGS[int(cols.err[i])]
            for i in np.nonzero(cols.err)[0]
        }
        valid = cols.err == 0
        is_global = (cols.behavior & np.int32(int(Behavior.GLOBAL))) != 0
        is_mr = (cols.behavior & np.int32(int(Behavior.MULTI_REGION))) != 0

        if self._local_picker.size() == 0:
            mine = valid
        else:
            owners = self._local_picker.owners_of(ring)
            self_addr = self.conf.advertise_address
            mine = valid & np.fromiter(
                (o.grpc_address == self_addr for o in owners), bool, n
            )
        local_rows = np.nonzero(mine)[0]
        global_rows = np.nonzero(valid & ~mine & is_global)[0]
        fwd_rows = np.nonzero(valid & ~mine & ~is_global)[0]
        if self._local_picker.size() > 0:
            if self.conf.behaviors.handoff_enabled and local_rows.size:
                # handoff sidecar: the native parser already computed each
                # item's ring point — record owned rows vectorized
                self.ownership.record(cols.fp[local_rows], ring[local_rows])
            # clustered: keep owner-side GLOBAL authoritative (see _route)
            lg = local_rows[is_global[local_rows]]
            if lg.size:
                cols.behavior[lg] &= ~np.int32(int(Behavior.GLOBAL))

        def place(rows, rc) -> None:
            status[rows] = rc.status
            limit[rows] = rc.limit
            remaining[rows] = rc.remaining
            reset[rows] = rc.reset_time
            for j, i in enumerate(rows):
                if rc.err[j]:
                    errors[int(i)] = ERROR_STRINGS[int(rc.err[j])]

        async def run_local():
            # the WireBatch subset keeps the parser's pre-packed lanes with
            # the columns — an all-local encodable batch stages straight
            # into the dispatch grid (fused path, service/batcher.py)
            rc = await self.batcher.check(subset_wire(wb, local_rows))
            place(local_rows, rc)

        async def run_global():
            # answer from local state with GLOBAL stripped + NO_BATCHING
            # forced, and queue the async hits (gubernator.go:401-429).
            # Both touched bits are kernel-inert — the lane image stays
            # valid, so the fused path serves GLOBAL answer rows too.
            g = subset_wire(wb, global_rows)
            g = g._replace(
                cols=g.cols._replace(
                    behavior=(g.cols.behavior & ~np.int32(int(Behavior.GLOBAL)))
                    | np.int32(int(Behavior.NO_BATCHING))
                )
            )
            for i in global_rows:
                item = materialize(i)
                self.global_manager.queue_hit(
                    item.name + "_" + item.unique_key, item
                )
            rc = await self.batcher.check(g)
            place(global_rows, rc)

        degraded_rows: set = set()

        async def run_forward(row: int):
            item = materialize(row)
            out: List[Optional[pb.RateLimitResp]] = [None]
            await self._forward(0, item.name + "_" + item.unique_key, item, out)
            r = out[0]
            status[row] = r.status
            limit[row] = r.limit
            remaining[row] = r.remaining
            reset[row] = r.reset_time
            if r.error:
                errors[int(row)] = r.error
            if "degraded" in r.metadata:
                degraded_rows.add(int(row))

        tasks = []
        if local_rows.size:
            tasks.append(run_local())
        if global_rows.size:
            tasks.append(run_global())
        tasks.extend(run_forward(int(i)) for i in fwd_rows)
        if tasks:
            await asyncio.gather(*tasks)
        # owner-side GLOBAL broadcasts + MULTI_REGION replication (standalone
        # mesh-global daemons skip queue_update — see _route)
        if not (
            self._local_picker.size() == 0
            and getattr(self.engine, "mesh_global", False)
        ):
            for i in local_rows[is_global[local_rows]]:
                item = materialize(i)
                self.global_manager.queue_update(
                    item.name + "_" + item.unique_key, item
                )
        for i in local_rows[is_mr[local_rows]]:
            item = materialize(i)
            self.region_manager.queue_hit(
                item.name + "_" + item.unique_key, item
            )
        over = int((status == int(pb.OVER_LIMIT)).sum())
        if over:
            self.metrics.over_limit_counter.inc(over)
        if degraded_rows:
            # degraded responses carry the metadata marker, which the native
            # encoder does not emit — partitions are the rare path, so fall
            # back to pb encoding for the whole batch
            resps = []
            for i in range(n):
                r = pb.RateLimitResp(
                    status=int(status[i]),
                    limit=int(limit[i]),
                    remaining=int(remaining[i]),
                    reset_time=int(reset[i]),
                    error=errors.get(i, ""),
                )
                if i in degraded_rows:
                    r.metadata["degraded"] = "true"
                resps.append(r)
            return pb.GetRateLimitsResp(responses=resps).SerializeToString()
        t0 = time.perf_counter()
        now = self.now_ms()  # retry_after_ms metadata basis (denied rows)
        if n * 8 >= self.DOOR_OFFLOAD_BYTES:
            # native encode drops the GIL — responder workers encode big
            # batches in parallel off the event loop
            out_bytes = await asyncio.get_running_loop().run_in_executor(
                self._door,
                encode_response_columns,
                status, limit, remaining, reset, errors, now,
            )
        else:
            out_bytes = encode_response_columns(
                status, limit, remaining, reset, errors, now
            )
        self._observe_request_stage(
            "encode", time.perf_counter() - t0, tracing.current_span()
        )
        return out_bytes

    def _observe_request_stage(self, stage: str, dt_s: float, span) -> None:
        """One request-scoped stage (parse/encode — stages that belong to a
        single request, unlike the per-flush queue/put/issue/fetch): the
        histogram sample carries the REQUEST trace as its exemplar and the
        child span hangs under the request span."""
        self.metrics.stage_duration.labels(stage=stage).observe(
            dt_s,
            exemplar={"trace_id": span.trace_id} if span is not None else None,
        )
        if span is not None and tracing.exporter is not None:
            end_ns = time.time_ns()
            tracing.record_span(
                stage, tracing.new_span(span), span.span_id,
                end_ns - int(dt_s * 1e9), end_ns,
            )

    def _emit_event(self, item, resp) -> None:
        if resp is None:  # pragma: no cover - defensive
            return
        try:
            self.event_channel.put_nowait(HitEvent(request=item, response=resp))
        except asyncio.QueueFull:
            self.events_dropped += 1

    async def _check_rows(self, cols, rows: np.ndarray, out, items=None) -> None:
        await self._check_subset(subset_columns(cols, rows), rows, out, items)

    async def _check_subset(self, sub, rows: np.ndarray, out, items=None) -> None:
        """Serve a column subset through the batcher. `items` (the full pb
        item list, indexed by the ORIGINAL row ids in `rows`) enables
        cascade expansion: every level of a cascade request becomes one
        engine row — all levels of all requests still resolve in a single
        engine dispatch — and the per-level responses contract back into
        the top-level response's `cascade` list."""
        resps = await self._serve_items(sub, (
            None if items is None else [items[int(i)] for i in rows]
        ))
        for j, i in enumerate(rows):
            out[int(i)] = resps[j]

    async def _serve_items(self, cols, items) -> "List[pb.RateLimitResp]":
        """Columns (+ aligned pb items, for cascade expansion) → pb
        responses via one batcher dispatch."""
        exp, counts = expand_cascades(
            cols, items, self.conf.cascade_max_levels
        )
        rc = await self.batcher.check(exp)
        now = self.now_ms()
        if counts is None:
            return pb_from_response_columns(rc, now_ms=now)
        for m in counts:
            if m:
                self.metrics.cascade_depth.observe(1 + m)
        return pb_from_cascade_response_columns(
            rc, counts, self.conf.cascade_max_levels, now_ms=now
        )

    async def _forward(self, row: int, key: str, item, out) -> None:
        """Forward to the owner with ownership re-resolution on failure
        (reference asyncRequest, gubernator.go:318-399), consulting the
        owner's circuit breaker: an open breaker fails fast (no RPC, no
        timeout wait) straight into the degradation policy, and retry
        sleeps are jittered-exponential instead of fixed-linear (Dean &
        Barroso, *The Tail at Scale*)."""
        last_err = "no peers available"
        for attempt in range(FORWARD_RETRIES):
            try:
                info = self.get_peer(key)
            except Exception as exc:
                last_err = str(exc)
                break
            if self.is_self(info):
                # ownership moved to us mid-flight — serve locally
                cols, _ = columns_from_pb([item])
                out[row] = (await self._serve_items(cols, [item]))[0]
                return
            client = self.peer_client(info)
            if client is None:
                last_err = f"no client for peer {info.grpc_address}"
                break
            try:
                out[row] = await client.get_peer_rate_limit(item)
                return
            except PeerCircuitOpenError as exc:
                # cooling down: retrying the same owner is pointless until
                # the breaker half-opens — degrade/error immediately
                last_err = str(exc)
                break
            except PeerError as exc:
                last_err = str(exc)
                self.metrics.batch_send_retries.inc()
                await asyncio.sleep(random.uniform(0, 0.002 * (2**attempt)))
        await self._forward_fallback(row, key, item, out, last_err)

    async def _forward_fallback(self, row: int, key: str, item, out, last_err) -> None:
        """Owner unreachable: apply the degradation policy. LOCAL answers
        from this daemon's own store (route-around first for pure reads),
        marked metadata["degraded"]="true"; ERROR keeps the reference's
        error response (gubernator.go:389-398)."""
        if (
            self.conf.behaviors.degradation_policy
            == DegradationPolicy.LOCAL.value
        ):
            if item.hits == 0:
                resp = await self._forward_around(key, item)
                if resp is not None:
                    out[row] = resp
                    return
            out[row] = await self._degraded_local(item)
            return
        self.metrics.check_error_counter.labels(error="forward").inc()
        out[row] = pb.RateLimitResp(
            error=f"Error while fetching rate limit from peer: {last_err}"
        )

    async def _forward_around(self, key: str, item) -> Optional["pb.RateLimitResp"]:
        """Route a zero-hit read around the dead owner to the next live peer
        on the ring — its replica state (GLOBAL broadcasts) may be fresher
        than ours. Returns None when no usable alternate exists (the local
        fallback handles it)."""
        try:
            owner = self.get_peer(key)
        except Exception:
            return None
        exclude = {owner.grpc_address}
        for addr, client in self._peer_clients.items():
            if client.breaker.blocked:
                exclude.add(addr)
        try:
            alt = self._local_picker.get(key, frozenset(exclude))
        except RuntimeError:
            return None
        if self.is_self(alt):
            return None
        client = self.peer_client(alt)
        if client is None:
            return None
        try:
            resp = await client.get_peer_rate_limit(item)
        except PeerError:
            return None
        resp.metadata["degraded"] = "true"
        self.metrics.degraded_responses.inc()
        return resp

    async def _degraded_local(self, item) -> "pb.RateLimitResp":
        """Best-effort local decision against this daemon's own store —
        clients keep getting rate-limit answers during partitions, each
        marked degraded so callers can tell it is not owner-authoritative."""
        cols, _ = columns_from_pb([item])
        resp = (await self._serve_items(cols, [item]))[0]
        resp.metadata["degraded"] = "true"
        self.metrics.degraded_responses.inc()
        return resp

    # --------------------------------------------------------- peers service
    async def get_peer_rate_limits(
        self, req: "peers_pb.GetPeerRateLimitsReq"
    ) -> "peers_pb.GetPeerRateLimitsResp":
        """Owner executes a forwarded/async batch (reference
        gubernator.go:476-559). GLOBAL-accumulated hits apply with
        DRAIN_OVER_LIMIT forced (gubernator.go:526-532)."""
        items = list(req.requests)
        # pick up the forwarder's trace context (reference gubernator.go:522-524
        # extracts the propagated TraceContext from request metadata)
        token = None
        for it in items:
            parent = tracing.extract(it.metadata)
            if parent is not None:
                token = tracing.start_scope("GetPeerRateLimits", parent)
                break
        try:
            return await self._get_peer_rate_limits(items)
        finally:
            if token is not None:
                tracing.end_scope(token)

    async def _get_peer_rate_limits(
        self, items
    ) -> "peers_pb.GetPeerRateLimitsResp":
        for it in items:
            if has_behavior(it.behavior, Behavior.GLOBAL):
                it.behavior |= int(Behavior.DRAIN_OVER_LIMIT)
        cols, hash_keys = columns_from_pb(items)
        if self._local_picker.size() > 0 and self.conf.behaviors.handoff_enabled:
            # forwarded batches execute owner-side too: record their ring
            # points for the handoff sidecar
            ok = [i for i in range(len(items)) if cols.err[i] == 0]
            self.ownership.record_keys(
                (cols.fp[i] for i in ok),
                (hash_keys[i] for i in ok),
                self._local_picker.hash_fn,
            )
        # strip GLOBAL before the local check so the engine path does not
        # depend on it; broadcast queueing happens below. Forwarded cascade
        # requests execute owner-side HERE — same expansion/contraction as
        # the front door, so the forwarder receives the folded verdict +
        # per-level sub-responses over the peer wire unchanged.
        cols = cols._replace(behavior=cols.behavior & ~np.int32(int(Behavior.GLOBAL)))
        resps = await self._serve_items(cols, items)
        for i, it in enumerate(items):
            if cols.err[i] != 0:
                continue
            if has_behavior(it.behavior, Behavior.GLOBAL):
                self.global_manager.queue_update(hash_keys[i], it)
            # forwarded MULTI_REGION hits reach the owner HERE, not in _route
            # — they must replicate cross-region too (replicated copies have
            # MULTI_REGION stripped by RegionManager, so no ping-pong)
            if has_behavior(it.behavior, Behavior.MULTI_REGION):
                self.region_manager.queue_hit(hash_keys[i], it)
        if self.event_channel is not None:
            # peer-batch execution is owner-side too (the reference's event
            # fires inside getLocalRateLimit, on every owner execution)
            for it, r in zip(items, resps):
                self._emit_event(it, r)
        return peers_pb.GetPeerRateLimitsResp(rate_limits=resps)

    async def update_peer_globals(
        self, req: "peers_pb.UpdatePeerGlobalsReq"
    ) -> "peers_pb.UpdatePeerGlobalsResp":
        """Install owner-authoritative statuses (reference gubernator.go:434-474)."""
        g = list(req.globals)
        n = len(g)
        if n:
            fp = np.fromiter((_hashkey_fp(u.key) for u in g), dtype=np.int64, count=n)
            remaining = np.fromiter(
                (u.status.remaining for u in g), dtype=np.int64, count=n
            )
            # sliding-window fidelity metadata (w_prev / w_rem — see
            # global_manager._broadcast): replicas interpolate the same
            # `used` as the owner. Absent (old senders / non-window rows)
            # the install falls back to the conservative weighted rebuild.
            aux = np.zeros(n, dtype=np.int64)
            rem_store = remaining.copy()
            has_meta = False
            for i, u in enumerate(g):
                md = u.status.metadata
                if "w_prev" in md:
                    try:
                        aux[i] = int(md["w_prev"])
                        rem_store[i] = int(md.get("w_rem", remaining[i]))
                        has_meta = True
                    except ValueError:
                        pass
            await self.runner.install_columns(
                fp=fp,
                algo=np.fromiter((u.algorithm for u in g), dtype=np.int32, count=n),
                status=np.fromiter(
                    (u.status.status for u in g), dtype=np.int32, count=n
                ),
                limit=np.fromiter((u.status.limit for u in g), dtype=np.int64, count=n),
                remaining=remaining,
                reset_time=np.fromiter(
                    (u.status.reset_time for u in g), dtype=np.int64, count=n
                ),
                duration=np.fromiter((u.duration for u in g), dtype=np.int64, count=n),
                aux=aux if has_meta else None,
                rem_store=rem_store if has_meta else None,
            )
            self.metrics.updates_installed.inc(n)
            self.metrics.broadcast_counter.labels(
                condition="update_peer_globals"
            ).inc()
        return peers_pb.UpdatePeerGlobalsResp()

    async def sync_globals_wire(
        self, req: "globalsync_pb.SyncGlobalsWireReq"
    ) -> "globalsync_pb.SyncGlobalsWireResp":
        """Receive one compact inter-slice GLOBAL hit-sync batch
        (service/wire.sync_wire_items): decode the lane image back to
        items and drive them through the exact owner path the proto
        GetPeerRateLimits fallback drives — DRAIN forced, broadcast
        queueing, MULTI_REGION replication (excluded by the codec's
        encodability rule) all behave identically."""
        from gubernator_tpu.service.wire import sync_wire_items

        items = sync_wire_items(req)
        self.metrics.global_wire_entries.labels(direction="recv").inc(
            len(items)
        )
        await self._get_peer_rate_limits(items)
        return globalsync_pb.SyncGlobalsWireResp(applied=len(items))

    async def sync_regions_wire(self, req):
        """Receive one compact cross-region delta batch
        (service/wire.sync_regions_pb): decode the lane image + hit-delta
        sidecar + the sender's stored rows, and reconcile through the
        conservative merge kernel (ops/reconcile.apply_region_sync → ONE
        engine job → kernel2.merge2) — never the serving path, so a
        replicated batch cannot queue broadcasts or re-replicate
        (ping-pong is structurally impossible). The sender's rows arrive
        in ITS slot layout and convert through the canonical full row
        (the PR-11 conversion point), so a packed-layout sender cannot
        corrupt or over-grant a differently-laid-out receiver.

        The body runs SHIELDED: once the merge job is committed to the
        engine thread it will land whether or not the sender's RPC
        deadline survives, so the apply and its accounting (note_recv,
        ownership sidecar) can never be split by a client-side cancel —
        the sender's retry then re-applies a FULLY accounted batch, which
        the merge turns into under-grant, never a half-recorded one."""
        task = asyncio.ensure_future(self._sync_regions_wire(req))
        return await asyncio.shield(task)

    async def _sync_regions_wire(self, req):
        from gubernator_tpu.proto import regionsync_pb2 as regionsync_pb
        from gubernator_tpu.service.wire import sync_regions_arrays

        fps, deltas, cfg, hash_keys, slots, layout, cums = (
            sync_regions_arrays(req)
        )
        # per-source exact dedup: a re-shipped batch (lost ack + sender
        # requeue) applies only the hits this receiver has not merged yet
        # — convergence stays exact under retries. The ledger commits only
        # after the merge lands (this handler runs shielded, so the pair
        # cannot be split by a client-side cancel).
        deltas, commit_dedup = self.region_manager.dedup_recv(
            req.source, fps, deltas, cums
        )
        applied = await self.runner.apply_region(
            fps, deltas, cfg, slots, layout
        )
        commit_dedup()
        if (
            self._local_picker.size() > 0
            and self.conf.behaviors.handoff_enabled
        ):
            # merged rows live on this daemon now: record their ring points
            # so a later rebalance can route them onward (handoff sidecar).
            # Steady-state rows travel string-less ("" marker) — their
            # points were recorded by the key's bootstrap batch.
            idx = [i for i, k in enumerate(hash_keys) if k]
            if idx:
                self.ownership.record_keys(
                    (fps[i] for i in idx),
                    (hash_keys[i] for i in idx),
                    self._local_picker.hash_fn,
                )
        self.region_manager.note_recv(len(hash_keys), applied)
        return regionsync_pb.SyncRegionsWireResp(applied=applied)

    async def transfer_state(
        self, req: "handoff_pb.TransferStateReq"
    ) -> "handoff_pb.TransferStateResp":
        """Receive one ownership-handoff chunk (service/handoff.py): merge
        the rows through the conservative merge kernel (kernel2.merge2 —
        remaining=min, expiry=max, newest config wins) and remember their
        ring points so a later rebalance can route them onward. Idempotent:
        a replayed (transfer_id, chunk) answers from the ledger without
        re-merging — and the merge semantics make even a ledger miss
        harmless (min/max can only tighten)."""
        from gubernator_tpu.service.wire import transfer_chunk_arrays

        key = (req.transfer_id, int(req.chunk))
        cached = self._applied_transfers.get(key)
        if cached is not None:
            return handoff_pb.TransferStateResp(merged=cached, duplicate=True)
        fps, points, slots, chunk_layout = transfer_chunk_arrays(req)
        merged = await self.runner.merge_rows(fps, slots, layout=chunk_layout)
        self.ownership.record(fps, points)
        self.metrics.handoff_rows.labels(phase="merged").inc(merged)
        self._applied_transfers[key] = merged
        while len(self._applied_transfers) > 4096:
            self._applied_transfers.popitem(last=False)
        return handoff_pb.TransferStateResp(merged=merged)

    # ------------------------------------------------------------ debug plane
    # JSON snapshots behind /v1/debug/{table,pipeline,peers,global}
    # (docs/observability.md): what to look at when p99 regresses (pipeline),
    # when evictions start (table), when forwards fail (peers), and when
    # GLOBAL convergence lags (global).

    async def debug_table(self) -> dict:
        """Latest table-telemetry snapshot; scans on demand when the
        background cadence is disabled or has not ticked yet. Grows the
        cumulative live-eviction count (the state-loss signal tiering
        turns into demotions — gubernator_tpu_evicted_live_total) and a
        tiering summary when the plane is armed."""
        snap = self._table_telemetry
        if snap is None:
            snap = await self.collect_telemetry()
        out = snap.to_dict()
        out["evicted_live_total"] = self.engine.stats.evicted_unexpired
        if self.tier.enabled:
            out["tiering"] = {
                "shadow_rows": self.tier.shadow.ram_rows,
                "tracked_rows": self.tier.shadow.tracked_rows,
            }
        return out

    def debug_tier(self) -> dict:
        """Hot-set tiering plane: shadow occupancy/bounds, demote/promote
        counters, spill state — what an operator checks when capacity or
        fault-back behavior is in question (docs/tiering.md)."""
        return self.tier.debug()

    def debug_pipeline(self) -> dict:
        """Front-door + engine pipeline state: ring depth, worker liveness,
        dispatch-path counters, adaptive-close reasons, engine identity."""
        eng = self.engine
        return {
            "batcher": self.batcher.debug(),
            "engine": {
                "kind": type(eng).__name__,
                "wire": getattr(eng, "wire", None),
                "write_mode": getattr(eng, "write_mode", None),
                # table-walk kernel (GUBER_PROBE_KERNEL) + the modeled HBM
                # bytes/decision at the current layout × write × geometry —
                # the live view of gubernator_table_hbm_bytes_per_decision
                "probe_kernel": getattr(eng, "probe_mode", None),
                "hbm_bytes_per_decision": (
                    round(eng.hbm_bytes_per_decision_estimate(), 1)
                    if hasattr(eng, "hbm_bytes_per_decision_estimate")
                    else None
                ),
                "n_shards": getattr(eng, "n_shards", 1),
                "n_hosts": getattr(eng, "n_hosts", 1),
                "devices_per_host": getattr(eng, "devices_per_host", None),
                "route": getattr(eng, "route", None),
                "dedup": getattr(eng, "dedup", None),
                "a2a_impl": getattr(eng, "a2a_impl", None),
                # exchange capacity-overflow rows (FLAG_UNPROCESSED before
                # reaching a kernel): the live view of
                # gubernator_tpu_a2a_overflow_total — sustained growth means
                # pair_capacity is undersized for the traffic's skew
                # (GUBER_A2A_CAPACITY_SIGMA)
                "a2a_overflow": getattr(eng, "a2a_overflow", 0),
                "poisoned": getattr(eng, "poisoned", None),
                "checks": eng.stats.checks,
                "dispatches": eng.stats.dispatches,
                "dropped": eng.stats.dropped,
            },
            # per-algorithm decision counts (live view of
            # gubernator_tpu_decisions_total) — scenario breadth at a glance
            "decisions_by_algorithm": dict(self.runner.algo_counts),
            "cascade_max_levels": self.conf.cascade_max_levels,
            "pipeline_inflight": self.conf.behaviors.pipeline_inflight,
            "concurrent_checks": self.metrics.concurrent_checks._value.get(),
        }

    def debug_peers(self) -> dict:
        """Peer plane: per-peer breaker state + recent errors, and ownership
        handoff progress."""
        peers = []
        for addr, client in self._peer_clients.items():
            peers.append({
                "address": addr,
                "breaker_state": client.breaker.state_name,
                "recent_errors": client.recent_errors()[:5],
            })
        h = self.handoff
        return {
            "self": self.conf.advertise_address,
            "local_peer_count": self._local_picker.size(),
            "region_peer_count": self._region_picker.size(),
            "leaving": self._leaving,
            "peers": peers,
            "handoff": {
                "enabled": h.enabled,
                "active": h.active,
                "rounds": h.rounds,
                "last_round": dict(h.last_round),
                "tracked_fps": len(self.ownership),
            },
        }

    def debug_durability(self) -> dict:
        """Durability plane: checkpoint epoch freshness, delta-log volume,
        compaction progress and the last persistence error — what an
        operator checks before trusting a rolling restart (or after an
        unclean one)."""
        out = self.checkpointer.status()
        self.metrics.checkpoint_epoch_age.set(
            self.checkpointer.epoch_age_s() if self.checkpointer.enabled
            else 0.0
        )
        loader = self._loader()
        out["loader"] = type(loader).__name__ if loader is not None else None
        return out

    def debug_regions(self) -> dict:
        """Multi-region replication plane: per-region breaker states, queue
        depths, last-sync ages, wire-vs-fallback counts — what an operator
        checks when a partition is suspected or after a heal (is the
        backlog draining?)."""
        out = self.region_manager.debug()
        self.metrics.region_sync_staleness.set(out["staleness_s"])
        return out

    def debug_leases(self) -> dict:
        """Edge quota-lease plane: outstanding tokens per key, grant/renew/
        return/expire rates, and the live over-admission bound = Σ
        outstanding leased tokens (docs/leases.md)."""
        out = self.lease_manager.debug()
        self.metrics.lease_outstanding.set(out["outstanding_tokens_total"])
        return out

    def debug_global(self) -> dict:
        """GLOBAL behavior: cross-daemon queue ages + mesh outbox depth —
        the convergence-lag view behind the staleness gauge."""
        out = {
            "staleness_s": round(self.global_sync_staleness_s(), 3),
            "manager": self.global_manager.debug(),
        }
        self.metrics.global_sync_staleness.set(out["staleness_s"])
        if getattr(self.engine, "mesh_global", False):
            gs = self.engine.global_stats
            out["mesh"] = {
                "pending": sum(len(p) for p in self.engine.pending),
                "oldest_age_s": round(self.engine.oldest_pending_age_s(), 3),
                "sync_rounds": gs.sync_rounds,
                "hits_queued": gs.hits_queued,
                "broadcasts_applied": gs.broadcasts_applied,
                "updates_installed": gs.updates_installed,
            }
        return out

    # ----------------------------------------------------------------- health
    async def health_check(self) -> "pb.HealthCheckResp":
        """Aggregate per-peer recent errors + breaker states (reference
        gubernator.go:562-643). Tri-state status so probes can tell a
        *degraded* instance (peer errors / open breakers, still serving
        every request) from an *unhealthy* one (structurally broken —
        e.g. not in its own peer list)."""
        errs: List[str] = []
        breaker_alarm = False
        local = self.local_peers()
        for c in self._peer_clients.values():
            errs.extend(c.recent_errors())
            if c.breaker.state is not BreakerState.CLOSED:
                breaker_alarm = True
        fatal: List[str] = []
        if local and not any(self.is_self(p) for p in local):
            fatal.append(
                f"this instance ({self.conf.advertise_address}) is not in the peer list"
            )
        poisoned = getattr(self.engine, "poisoned", None)
        if poisoned:
            # a donated collective launch died mid-flight: the engine's
            # device buffers are suspect, so this instance must read
            # unhealthy even though the process is alive
            fatal.append(f"engine poisoned: {poisoned}")
        if self._leaving:
            # graceful drain in progress: probes and peers must route around
            # this instance BEFORE it disappears (its owned state is moving
            # to the ring successors right now)
            status = "leaving"
        elif fatal:
            status = "unhealthy"
        elif errs or breaker_alarm:
            status = "degraded"
        else:
            status = "healthy"
        resp = pb.HealthCheckResp(
            status=status,
            message="; ".join((fatal + errs)[:5]),
            peer_count=self._local_picker.size() + self._region_picker.size(),
            advertise_address=self.conf.advertise_address,
            region=self.conf.data_center,
        )

        def peer_entry(p: PeerInfo) -> "pb.PeerHealthResp":
            e = pb.PeerHealthResp(
                grpc_address=p.grpc_address, data_center=p.data_center
            )
            c = self._peer_clients.get(p.grpc_address)
            if c is not None:  # no client toward self
                e.breaker_state = c.breaker.state_name
                e.recent_errors.extend(c.recent_errors()[:5])
            return e

        for p in local:
            resp.local_peers.append(peer_entry(p))
        for p in self.region_peers():
            resp.region_peers.append(peer_entry(p))
        return resp

    def live_check(self) -> "pb.LiveCheckResp":
        """Liveness gate (reference gubernator.go:646-651): fails during
        shutdown so load balancers de-register before the listeners close."""
        if self._shutting_down:
            raise RuntimeError("shutting down")
        return pb.LiveCheckResp()

    # ------------------------------------------------------------ checkpoint
    def _loader(self):
        """The active Loader: an injected one, else a FileLoader over
        GUBER_CHECKPOINT_PATH, else None (reference wires Loader the same
        way — an embedding hook the server binary points at a file,
        store.go:49-60)."""
        if self.loader is not None:
            return self.loader
        if self.conf.checkpoint_path:
            from gubernator_tpu.store import FileLoader

            return FileLoader(self.conf.checkpoint_path)
        return None

    def maybe_restore(self) -> None:
        """Boot-time restore. The incremental plane replays base + delta
        frames (service/checkpoint.py); the classic Loader path loads one
        snapshot. EITHER degrades to a logged cold start on damage — a
        snapshot whose geometry/schema no longer matches the configured
        table (cache_size changed across restart), a corrupt file, or a
        loader that throws must never kill the boot."""
        if self.checkpointer.enabled:
            self.checkpointer.restore()
            return
        loader = self._loader()
        if loader is None:
            return
        try:
            rows = loader.load()
            if rows is not None:
                self.engine.restore(np.asarray(rows))
        except Exception:
            log.warning(
                "checkpoint restore failed; starting cold", exc_info=True
            )
            self.metrics.checkpoint_errors.labels(stage="restore").inc()

    def maybe_checkpoint(self) -> None:
        """Shutdown snapshot through the Loader hook. Guarded: a failed
        save (disk full, unwritable path) is logged + counted — it must
        never wedge close() before _door.shutdown/runner.close run."""
        loader = self._loader()
        if loader is None:
            return
        try:
            rows = self.runner.snapshot_sync()
            lay = self.engine.table.layout
            try:
                # FileLoader records the slot layout so a later meta read
                # interprets the bytes; Loader subclasses without the kw
                # keep the classic single-arg contract
                loader.save(rows, layout_name=lay.name)
            except TypeError:
                loader.save(rows)
        except Exception:
            log.exception("shutdown checkpoint failed; state not persisted")
            self.metrics.checkpoint_errors.labels(stage="shutdown").inc()

    # ---------------------------------------------------------------- close
    async def abort(self) -> None:
        """Unclean-death surface for chaos tests — the in-process analog of
        `kill -9`: listeners, loops and executors stop, but NOTHING runs
        that a SIGKILL would skip — no drain, no GLOBAL flush, no handoff,
        no final checkpoint. Whatever the incremental checkpoint plane
        already made durable is ALL a restart gets; the recovery-bound
        chaos test (tests/test_durability.py) drives this path."""
        if self._shutting_down:
            return
        self._shutting_down = True
        for t in (
            self._cert_watch_task, self._maintenance_task,
            self._global_sync_task, self._telemetry_task,
            self._checkpoint_task, self._tier_task, *self._handoff_tasks,
        ):
            if t is not None:
                t.cancel()
        if self._pool is not None:
            await self._pool.close()
        # kill the GLOBAL/region loops WITHOUT the flush their close() does
        for t in (
            *self.global_manager._tasks,
            *( [self.region_manager._task]
               if self.region_manager._task is not None else [] ),
        ):
            t.cancel()
        await asyncio.gather(
            *(c.shutdown() for c in self._peer_clients.values()),
            *(c.shutdown() for c in self._orphaned_clients),
            return_exceptions=True,
        )
        self._orphaned_clients = []
        for s in self._servers:
            await s.stop()
        self._door.shutdown(wait=False)
        self.runner.close()

    async def stop(self, drain: bool = False) -> None:
        """Graceful shutdown; `drain=True` additionally hands every owned
        live row to its ring successor before the listeners close (the
        deployable-under-load path, docs/robustness.md "Topology change &
        drain")."""
        await self.close(drain=drain)

    async def close(self, drain: bool = False) -> None:
        """Graceful shutdown (reference daemon.go:388-434): stop intake,
        drain batches + global queues, [hand off owned state], checkpoint,
        stop listeners."""
        if self._shutting_down:
            return
        if drain:
            # health flips to "leaving" first so probes/peers route around
            # this instance while its state moves
            self._leaving = True
        self._shutting_down = True  # live_check now fails → LBs de-register
        if self.conf.graceful_termination_delay_s > 0:
            # keep serving while load balancers notice the failing liveness
            # probe (reference daemon.go:389-391)
            await asyncio.sleep(self.conf.graceful_termination_delay_s)
        if self._cert_watch_task is not None:
            self._cert_watch_task.cancel()
            try:
                await self._cert_watch_task
            except asyncio.CancelledError:
                pass
        if self._maintenance_task is not None:
            self._maintenance_task.cancel()
            try:
                await self._maintenance_task
            except asyncio.CancelledError:
                pass
        if self._global_sync_task is not None:
            self._global_sync_task.cancel()
            try:
                await self._global_sync_task
            except asyncio.CancelledError:
                pass
        if self._telemetry_task is not None:
            self._telemetry_task.cancel()
            try:
                await self._telemetry_task
            except asyncio.CancelledError:
                pass
        if self._checkpoint_task is not None:
            self._checkpoint_task.cancel()
            try:
                await self._checkpoint_task
            except asyncio.CancelledError:
                pass
        if self._tier_task is not None:
            self._tier_task.cancel()
            try:
                await self._tier_task
            except asyncio.CancelledError:
                pass
        if self._pool is not None:
            await self._pool.close()
        # in-flight rebalance handoffs yield to the final drain pass (or to
        # plain shutdown — their rows simply stay local)
        for t in list(self._handoff_tasks):
            t.cancel()
        if self._handoff_tasks:
            await asyncio.gather(*self._handoff_tasks, return_exceptions=True)
        await self.global_manager.close()  # flushes pending GLOBAL queues
        await self.region_manager.close()
        await self.batcher.drain()
        if self.ring is not None:
            # after the batcher: its drain flushes pending chunks THROUGH
            # the ring; only then can the ring retire every published
            # ticket and park the serving loop (zero-loss ordering)
            await self.ring.drain()
        if drain and self.conf.behaviors.handoff_enabled:
            # hand owned live rows to ring successors under the deadline;
            # whatever stays unacked is snapshotted by maybe_checkpoint below
            try:
                await self.handoff.drain()
            except Exception:  # pragma: no cover - defensive
                log.exception("graceful drain handoff failed")
        await asyncio.gather(
            *(c.shutdown() for c in self._peer_clients.values()),
            *(c.shutdown() for c in self._orphaned_clients),
            return_exceptions=True,
        )
        self._orphaned_clients = []
        for s in self._servers:
            await s.stop()
        if getattr(self.engine, "mesh_global", False) and self.engine.has_pending():
            # final collective flush so queued GLOBAL hits reach their owner
            # shards before the checkpoint (global_manager.close analog)
            await self.runner.sync_global()
        if self.tier.enabled:
            # persist unspilled shadow rows so a graceful restart faults
            # them back from disk (no-op without a spill file). Guarded:
            # shutdown always completes.
            try:
                await asyncio.get_running_loop().run_in_executor(
                    None, lambda: self.tier.close(self.now_ms())
                )
            except Exception:
                log.exception("tier shadow flush failed")
        if self.checkpointer.enabled:
            # incremental plane: one last compaction folds the delta log
            # into the base so a restart replays nothing. Guarded like
            # maybe_checkpoint — shutdown always completes.
            try:
                await self.checkpointer.final_checkpoint()
            except Exception:
                log.exception("final checkpoint compaction failed")
                self.metrics.checkpoint_errors.labels(stage="shutdown").inc()
        else:
            self.maybe_checkpoint()
        self._door.shutdown(wait=True)
        self.runner.close()
        if tracing.exporter is not None:
            # flush (not close): the exporter is process-global and other
            # daemons in this process may still be serving. Off-loop — the
            # flush POST blocks up to its timeout
            try:
                await asyncio.get_running_loop().run_in_executor(
                    None, tracing.exporter.flush
                )
            except Exception:  # pragma: no cover - defensive
                log.exception("trace export flush failed")
