"""MULTI_REGION behavior — active-active cross-region replication.

The reference declares MULTI_REGION (gubernator.proto:131-134) and builds the
per-region machinery (RegionPicker rings + a request queue,
region_picker.go:19-103) but ships no cross-region push loop; its README
marks the behavior "not fully implemented". This module is the push loop,
rebuilt the way the GLOBAL inter-slice sync works (docs/robustness.md
"Multi-region active-active"):

* every region serves every decision LOCALLY at full speed — replication is
  asynchronous and never sits on the serving path;
* the key's in-region owner aggregates its MULTI_REGION hits per key (sum
  Hits, newest config wins) into one pending queue PER DESTINATION REGION,
  and every sync tick ships each region's queue to the key's owner in that
  region (RegionPicker rings);
* encodable batches ride the compact ``SyncRegionsWire`` codec — per-key hit
  deltas + config lanes + the sender's own stored slot rows — and the
  receiver reconciles through ``kernel2.merge2`` via ``engine.merge_rows``
  (ops/reconcile.py), NEVER the serving path: replication is convergent and
  can only under-grant, by the same pinned conservatism that covers
  checkpoint replay and handoff. Non-encodable items (resets, Gregorian,
  lease releases, metadata carriers) and pre-upgrade peers fall back per
  item to the classic GetPeerRateLimits proto path with MULTI_REGION
  stripped and DRAIN_OVER_LIMIT forced (the legacy semantics — and still no
  ping-pong: the stripped copy is not re-replicated by the receiver);
* the plane is partition-tolerant: every send is gated by the destination
  peer's circuit breaker (fail fast, no timeout stacking), failed batches
  REQUEUE bounded by GUBER_REGION_REQUEUE_RETRIES / GUBER_REGION_QUEUE_CAP
  (mirroring the PR-1 GLOBAL requeue) instead of the reference's
  count-and-drop, and a partitioned region keeps serving degraded-local with
  over-admission bounded by the sum of its unreplicated deltas. After heal
  the requeued backlog drains through the merge and regions reconverge to
  the exact union of hits.

Cascade requests (PR 10) span regions too: a MULTI_REGION cascade carrier
queues its own delta AND one delta per cascade level (each under the level's
own key), so every level's count converges across regions — the
GLOBAL-behavior cascade semantics extended to the region plane.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Dict, List, Optional

import numpy as np

from gubernator_tpu.proto import gubernator_pb2 as pb
from gubernator_tpu.proto import peers_pb2 as peers_pb
from gubernator_tpu.service.global_manager import _unimplemented
from gubernator_tpu.types import Behavior

log = logging.getLogger("gubernator_tpu.region")

# behavior bits a replicated cascade level inherits from its carrier —
# the client-facing flags minus GREGORIAN (level durations are always ms,
# service/wire._CASCADE_INHERIT) and minus GLOBAL (the level's region copy
# must not ALSO enter the receiver's GLOBAL queues)
_LEVEL_INHERIT = int(
    Behavior.NO_BATCHING
    | Behavior.MULTI_REGION
    | Behavior.DRAIN_OVER_LIMIT
)


class RegionManager:
    def __init__(self, daemon):
        self.daemon = daemon
        b = daemon.conf.behaviors
        self.sync_wait_s = (
            b.region_sync_wait_ms or b.global_sync_wait_ms
        ) / 1e3
        self.batch_limit = b.global_batch_limit
        # replication sends get a deliberately generous deadline (derived
        # max(global_timeout, 2 s) unless GUBER_REGION_TIMEOUT overrides):
        # nothing user-facing waits on this plane, and a deadline that
        # cancels the receiver mid-merge turns one slow round into a
        # duplicate delivery on retry — under-granting, but needless
        self.timeout_s = (
            b.region_timeout_ms or max(b.global_timeout_ms, 2_000.0)
        ) / 1e3
        self.concurrency = b.global_peer_concurrency
        self.requeue_retries = b.region_requeue_retries
        self.queue_cap = b.region_queue_cap
        self.wire_sync = b.region_wire_sync
        self.metrics = daemon.metrics
        # destination region (data_center) → hash_key → aggregated item.
        # Fanning out at QUEUE time (not send time) keeps per-region
        # delivery independent: a partition toward one region must not
        # stall or re-send another region's already-acked deltas.
        self._pending: Dict[str, Dict[str, pb.RateLimitReq]] = {}
        # dc → hash_key → monotonic ts of the key's FIRST un-replicated
        # hit; survives requeues and is cleared only when the key's deltas
        # reach that region's owner (or are dropped). min() over every
        # region is the gubernator_region_sync_staleness_seconds gauge.
        self._age: Dict[str, Dict[str, float]] = {}
        # dc → hash_key → failed-send count (bounded retries)
        self._attempts: Dict[str, Dict[str, int]] = {}
        # dc → monotonic ts of the last successful send (debug plane)
        self.last_sync: Dict[str, float] = {}
        # dc → keys whose bootstrap detail (strings + sender slot row)
        # already reached that region: steady-state deltas for them ship
        # as pure 32 B lane+hits entries. Cleared wholesale at the cap —
        # re-shipping detail is merely bytes, never wrong.
        self._shipped: Dict[str, set] = {}
        # dc → hash_key → CUMULATIVE hits ever queued toward that region
        # (incremented at queue time ONLY — a requeue re-merges already-
        # counted hits). Shipped alongside each delta so the receiver's
        # per-source ledger skips re-shipped batches after a lost ack
        # EXACTLY (ops/reconcile.dedup_source_deltas). Cleared wholesale
        # at the cap: the receiver sees the counter go backwards and falls
        # back to the legacy under-grant rule for one round, never over.
        self._cum: Dict[str, Dict[str, int]] = {}
        # source address → fp → highest cumulative counter already MERGED
        # on this daemon (the RECEIVE half; committed only after the merge
        # lands so a cancelled apply is re-appliable)
        self._recv_cum: Dict[str, Dict[int, int]] = {}
        self.dedup_skipped = 0  # duplicate hits skipped exactly (receive)
        # lifetime path counters (debug plane; prometheus carries the same)
        self.wire_sent = 0
        self.wire_fallback = 0
        self.wire_recv = 0
        self.rows_merged = 0
        self._wake = asyncio.Event()
        self._task = None
        self._closed = False

    def start(self) -> None:
        self._task = asyncio.create_task(self._loop(), name="region-hits")

    async def close(self) -> None:
        self._closed = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        await self._send()

    # --------------------------------------------------------------- queueing
    def queue_hit(self, key: str, item: "pb.RateLimitReq") -> None:
        """Owner-side MULTI_REGION hit to replicate across DCs. Cascade
        carriers additionally queue one delta per level (module docstring).
        Zero-hit requests replicate nothing — reads are local."""
        if item.hits == 0:
            return
        dcs = [
            dc for dc, ring in self.daemon._region_picker.pickers().items()
            if ring.size() > 0
        ]
        if not dcs:
            return
        now_ms = self.daemon.now_ms()
        entries = []
        rep = pb.RateLimitReq()
        rep.CopyFrom(item)
        if len(rep.cascade):
            # the carrier replicates WITHOUT its levels (they queue as
            # their own keys below) — otherwise the proto fallback would
            # re-expand the cascade at the receiver and consume every
            # level a second time
            rep.ClearField("cascade")
        if not rep.HasField("created_at"):
            # stamp at queue time: the compact codec needs the hit's
            # instant, and "now" IS when these hits happened
            rep.created_at = now_ms
        entries.append((key, rep))
        inherit = item.behavior & _LEVEL_INHERIT
        for lvl in item.cascade:
            if lvl.name == "" or lvl.unique_key == "":
                continue
            entries.append((
                lvl.name + "_" + lvl.unique_key,
                pb.RateLimitReq(
                    name=lvl.name,
                    unique_key=lvl.unique_key,
                    hits=item.hits,
                    limit=lvl.limit,
                    burst=lvl.burst,
                    duration=lvl.duration,
                    algorithm=lvl.algorithm,
                    behavior=inherit,
                    created_at=rep.created_at,
                ),
            ))
        t = time.monotonic()
        for dc in dcs:
            pend = self._pending.setdefault(dc, {})
            ages = self._age.setdefault(dc, {})
            cum = self._cum.setdefault(dc, {})
            for k, it in entries:
                if it.hits > 0:
                    if len(cum) >= self._SHIPPED_CAP and k not in cum:
                        cum.clear()
                    cum[k] = cum.get(k, 0) + int(it.hits)
                ages.setdefault(k, t)
                agg = pend.get(k)
                if agg is None:
                    agg = pb.RateLimitReq()
                    agg.CopyFrom(it)
                    pend[k] = agg
                else:
                    hits = agg.hits + it.hits
                    reset = (agg.behavior | it.behavior) & int(
                        Behavior.RESET_REMAINING
                    )
                    agg.CopyFrom(it)  # newest config wins
                    agg.hits = hits
                    agg.behavior |= reset
        total = self._queue_len()
        self.metrics.region_queue_length.set(total)
        if total >= self.batch_limit:
            self._wake.set()

    def _queue_len(self) -> int:
        return sum(len(p) for p in self._pending.values())

    # -------------------------------------------------------------- sync loop
    async def _loop(self) -> None:
        while not self._closed:
            try:
                await asyncio.wait_for(self._wake.wait(), self.sync_wait_s)
            except asyncio.TimeoutError:
                pass
            self._wake.clear()
            try:
                await self._send()
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("multi-region sync round failed")

    async def _send(self) -> None:
        if not any(self._pending.values()):
            return
        t0 = time.perf_counter()
        sem = asyncio.Semaphore(self.concurrency)
        tasks = []
        for dc in list(self._pending.keys()):
            batch = self._pending.get(dc)
            if not batch:
                continue
            self._pending[dc] = {}
            ring = self.daemon._region_picker.pickers().get(dc)
            if ring is None or ring.size() == 0:
                # the region left the peer set: its deltas have nowhere to
                # go (eventual consistency tolerates the loss, like the
                # reference's no-peers drop)
                for k in batch:
                    self._clear_key(dc, k)
                continue
            by_addr: Dict[str, list] = {}
            infos = {}
            for k, it in batch.items():
                try:
                    info = ring.get(k)
                except Exception:
                    self._clear_key(dc, k)
                    continue
                by_addr.setdefault(info.grpc_address, []).append((k, it))
                infos[info.grpc_address] = info
            for addr, pairs in by_addr.items():
                tasks.append(self._send_peer(dc, infos[addr], pairs, sem))
        if tasks:
            await asyncio.gather(*tasks)
            self.metrics.global_send_duration.observe(
                time.perf_counter() - t0
            )
        self.metrics.region_queue_length.set(self._queue_len())

    async def _send_peer(self, dc: str, info, pairs, sem) -> None:
        client = self.daemon.peer_client(info)
        if client is None or client.breaker.blocked:
            # fail fast: no RPC (and no timeout wait) toward a missing
            # client or an open breaker; the batch requeues bounded
            self.metrics.check_error_counter.labels(
                error="region_send"
            ).inc()
            self._requeue(dc, pairs)
            return
        async with sem:
            try:
                await self._ship(dc, client, pairs)
            except asyncio.CancelledError:
                raise
            except Exception:
                self.metrics.check_error_counter.labels(
                    error="region_send"
                ).inc()
                self._requeue(dc, pairs)
            else:
                for k, _ in pairs:
                    self._clear_key(dc, k)
                self.last_sync[dc] = time.monotonic()

    _SHIPPED_CAP = 1 << 20  # per-region bootstrap-ledger bound

    async def _ship(self, dc: str, client, pairs) -> None:
        """One region-owner-bound batch: the compact SyncRegionsWire merge
        codec for every encodable item (per-item split — one exotic item
        never forces the batch off the merge path), the classic proto
        fallback for the rest. A key's FIRST batch to a region carries the
        bootstrap detail (strings + the sender's stored slot row in its
        native layout); steady-state deltas ship as pure 32 B lane+hits
        entries merged by fingerprint. An UNIMPLEMENTED answer latches
        `region_wire_ok` off for that peer (a pre-region-merge build) and
        the whole batch re-ships as proto in the same round. A failure
        ANYWHERE raises and the caller requeues the full batch — a batch
        whose wire half already landed then re-applies it, which the merge
        turns into under-grant, never over."""
        from gubernator_tpu.service.wire import (
            split_region_encodable,
            sync_regions_pb,
        )

        enc: list = []
        fb = list(pairs)
        if self.wire_sync and getattr(client, "region_wire_ok", True):
            enc, fb = split_region_encodable(pairs)
        if enc:
            from gubernator_tpu.hashing import fingerprint

            shipped = self._shipped.setdefault(dc, set())
            detail = np.fromiter(
                (k not in shipped for k, _ in enc), dtype=bool,
                count=len(enc),
            )
            slots = layout = None
            if detail.any():
                fps = np.fromiter(
                    (fingerprint(it.name, it.unique_key)
                     for _k, it in enc),
                    dtype=np.int64, count=len(enc),
                )
                # the sender's own stored rows for first-shipped keys, in
                # the table's native layout (zero rows for keys already
                # evicted): the receiver bootstraps keys it has never
                # seen from them — gathered as ONE engine job
                _found, got, layout = (
                    await self.daemon.runner.read_state_raw(fps[detail])
                )
                slots = np.zeros((len(enc), layout.F), dtype=np.int32)
                slots[detail] = got
            # per-key cumulative counters ride every batch: the receiver's
            # per-source ledger turns a re-shipped batch (lost ack +
            # requeue) into an EXACT skip instead of an under-grant
            cum = self._cum.get(dc, {})
            cums = np.fromiter(
                (cum.get(k, 0) for k, _ in enc), dtype=np.int64,
                count=len(enc),
            )
            req = sync_regions_pb(
                enc,
                self.daemon.conf.advertise_address,
                self.daemon.conf.data_center,
                slots,
                layout,
                detail_rows=detail,
                cums=cums,
            )
            try:
                await client.sync_regions_wire(req, timeout=self.timeout_s)
            except Exception as exc:
                if not _unimplemented(exc):
                    raise
                client.region_wire_ok = False
                fb = list(pairs)  # re-ship everything classic, same round
            else:
                self.wire_sent += len(enc)
                self.metrics.region_wire_entries.labels(
                    direction="sent"
                ).inc(len(enc))
                shipped.update(k for k, _ in enc)
                if len(shipped) > self._SHIPPED_CAP:
                    shipped.clear()
        if fb:
            items = [self._fallback_item(it) for _k, it in fb]
            await client.get_peer_rate_limits(
                peers_pb.GetPeerRateLimitsReq(requests=items),
                timeout=self.timeout_s,
            )
            self.wire_fallback += len(fb)
            self.metrics.region_wire_entries.labels(
                direction="fallback"
            ).inc(len(fb))

    @staticmethod
    def _fallback_item(item: "pb.RateLimitReq") -> "pb.RateLimitReq":
        """The legacy replication transform (mirror of the GLOBAL owner
        rule, gubernator.go:526-532): MULTI_REGION stripped so the
        receiving owner applies locally and does NOT re-replicate (which
        would ping-pong hits between DCs forever), DRAIN_OVER_LIMIT forced
        so the remote hits always drain the replica bucket."""
        rep = pb.RateLimitReq()
        rep.CopyFrom(item)
        rep.behavior = (
            rep.behavior & ~int(Behavior.MULTI_REGION)
        ) | int(Behavior.DRAIN_OVER_LIMIT)
        return rep

    def _requeue(self, dc: str, pairs) -> None:
        """Re-merge a failed region batch into that region's pending queue,
        bounded by a per-key retry cap and a per-region queue cap — a
        partition longer than retries × sync_wait degrades to the
        reference's drop behavior instead of growing memory without bound
        (dropped deltas are counted AND widen the documented over-admission
        bound; size the knobs to the partitions you want to ride out)."""
        pend = self._pending.setdefault(dc, {})
        att = self._attempts.setdefault(dc, {})
        ages = self._age.setdefault(dc, {})
        requeued = dropped = 0
        for key, item in pairs:
            attempts = att.get(key, 0) + 1
            if attempts > self.requeue_retries or (
                key not in pend and len(pend) >= self.queue_cap
            ):
                att.pop(key, None)
                ages.pop(key, None)
                dropped += 1
                continue
            att[key] = attempts
            agg = pend.get(key)
            if agg is None:
                pend[key] = item
            else:
                # fresh hits arrived for the key since the failed send:
                # fold the failed batch back in (hits add, newest config —
                # already in `agg` — stays, RESET_REMAINING sticks)
                agg.hits += item.hits
                agg.behavior |= item.behavior & int(Behavior.RESET_REMAINING)
            requeued += 1
        if requeued:
            self.metrics.region_requeued.inc(requeued)
        if dropped:
            self.metrics.region_requeue_dropped.inc(dropped)
        self.metrics.region_queue_length.set(self._queue_len())

    def _clear_key(self, dc: str, key: str) -> None:
        a = self._attempts.get(dc)
        if a is not None:
            a.pop(key, None)
        g = self._age.get(dc)
        if g is not None:
            g.pop(key, None)

    # ----------------------------------------------------------- introspection
    def oldest_delta_age_s(self) -> float:
        """Age of the oldest MULTI_REGION hit delta not yet acked by every
        remote region's owner (0 when nothing is pending) — queued AND
        in-flight/requeued keys count; a delta is only "replicated" once
        its region's owner send succeeded. The region-plane analog of
        GlobalManager.oldest_hit_age_s."""
        oldest: Optional[float] = None
        for ages in self._age.values():
            if ages:
                m = min(ages.values())
                oldest = m if oldest is None else min(oldest, m)
        if oldest is None:
            return 0.0
        return max(0.0, time.monotonic() - oldest)

    def dedup_recv(self, source: str, fps, deltas, cums):
        """Receive-side exact dedup (ops/reconcile.dedup_source_deltas):
        returns (effective_deltas, commit). The caller applies the
        effective deltas through the merge and calls `commit()` ONLY after
        the merge landed — so a cancelled/failed apply leaves the ledger
        untouched and the sender's retry re-applies in full."""
        from gubernator_tpu.ops.reconcile import (
            commit_source_cums,
            dedup_source_deltas,
        )

        ledger = self._recv_cum.setdefault(source, {})
        eff = dedup_source_deltas(ledger, fps, deltas, cums)
        skipped = int(
            (np.asarray(deltas, dtype=np.int64) - eff).sum()
        ) if cums is not None else 0

        def commit():
            commit_source_cums(ledger, fps, cums)
            if skipped > 0:
                self.dedup_skipped += skipped
                self.metrics.region_dedup_skipped.inc(skipped)

        return eff, commit

    def note_recv(self, n_entries: int, n_merged: int) -> None:
        """Receive-side accounting (daemon.sync_regions_wire)."""
        self.wire_recv += n_entries
        self.rows_merged += n_merged
        self.metrics.region_wire_entries.labels(direction="recv").inc(
            n_entries
        )
        self.metrics.region_rows_merged.inc(n_merged)

    def debug(self) -> dict:
        """Live region-plane state for /v1/debug/regions."""
        now = time.monotonic()
        pickers = self.daemon._region_picker.pickers()
        regions = {}
        for dc in sorted(set(self._pending) | set(pickers)):
            ring = pickers.get(dc)
            peers = []
            for p in (ring.peers() if ring is not None else []):
                c = self.daemon.peer_client(p)
                peers.append({
                    "address": p.grpc_address,
                    "breaker_state": (
                        c.breaker.state_name if c is not None else None
                    ),
                    "region_wire_ok": (
                        getattr(c, "region_wire_ok", True)
                        if c is not None else None
                    ),
                })
            ages = self._age.get(dc) or {}
            regions[dc] = {
                "queue_depth": len(self._pending.get(dc) or {}),
                "unreplicated_keys": len(ages),
                "oldest_delta_age_s": (
                    round(now - min(ages.values()), 3) if ages else 0.0
                ),
                "last_sync_age_s": (
                    round(now - self.last_sync[dc], 3)
                    if dc in self.last_sync else None
                ),
                "requeue_attempts": len(self._attempts.get(dc) or {}),
                "peers": peers,
            }
        return {
            "region": self.daemon.conf.data_center,
            "staleness_s": round(self.oldest_delta_age_s(), 3),
            "sync_wait_ms": self.sync_wait_s * 1e3,
            "wire_sync": self.wire_sync,
            "requeue_retries": self.requeue_retries,
            "queue_cap": self.queue_cap,
            "wire": {
                "sent": self.wire_sent,
                "recv": self.wire_recv,
                "fallback": self.wire_fallback,
                "rows_merged": self.rows_merged,
                # duplicate hits skipped EXACTLY by the per-source
                # cumulative-counter ledger (re-shipped batches after a
                # lost ack) — nonzero means retries happened AND exactness
                # held instead of degrading to under-grant
                "dedup_skipped_hits": self.dedup_skipped,
            },
            "regions": regions,
        }
