"""MULTI_REGION behavior — async cross-datacenter hit replication.

The reference declares MULTI_REGION (gubernator.proto:131-134) and builds the
per-region machinery (RegionPicker rings + a request queue,
region_picker.go:19-103) but ships no cross-region push loop; its README
marks the behavior "not fully implemented". This module supplies the flow the
reference's design sketches, reusing the GLOBAL manager's two-stage batching
shape (global.go:102-199):

* the OWNER of a MULTI_REGION key (within its own DC) aggregates its hits per
  key (sum Hits, OR RESET_REMAINING) exactly like the GLOBAL hits loop;
* every sync tick it forwards each key's aggregate to the key's owner in
  EVERY OTHER region (one peer per DC, via the RegionPicker rings) through
  GetPeerRateLimits, so each region's replica bucket drains by the remote
  hits too;
* MULTI_REGION is stripped and DRAIN_OVER_LIMIT forced on the replicated
  items (mirror of the GLOBAL owner rule, gubernator.go:526-532) — the
  receiving owner applies them locally and must NOT re-replicate, which would
  ping-pong hits between DCs forever.

Eventual consistency: each region's count converges to the union of all
regions' hits within one sync interval; send failures are counted and
dropped, never retried (same loss model as GLOBAL, global.go:190-195).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Dict

from gubernator_tpu.proto import gubernator_pb2 as pb
from gubernator_tpu.proto import peers_pb2 as peers_pb
from gubernator_tpu.types import Behavior

log = logging.getLogger("gubernator_tpu.region")


class RegionManager:
    def __init__(self, daemon):
        self.daemon = daemon
        b = daemon.conf.behaviors
        self.sync_wait_s = b.global_sync_wait_ms / 1e3
        self.batch_limit = b.global_batch_limit
        self.timeout_s = b.global_timeout_ms / 1e3
        self.concurrency = b.global_peer_concurrency
        self.metrics = daemon.metrics
        self._hits: Dict[str, pb.RateLimitReq] = {}
        self._wake = asyncio.Event()
        self._task = None
        self._closed = False

    def start(self) -> None:
        self._task = asyncio.create_task(self._loop(), name="region-hits")

    async def close(self) -> None:
        self._closed = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        await self._send()

    def queue_hit(self, key: str, item: "pb.RateLimitReq") -> None:
        """Owner-side MULTI_REGION hit to replicate across DCs."""
        if item.hits == 0 or self.daemon.region_peers() == []:
            return
        agg = self._hits.get(key)
        if agg is None:
            agg = pb.RateLimitReq()
            agg.CopyFrom(item)
            self._hits[key] = agg
        else:
            hits = agg.hits + item.hits
            reset = (agg.behavior | item.behavior) & int(Behavior.RESET_REMAINING)
            agg.CopyFrom(item)
            agg.hits = hits
            agg.behavior |= reset
        if len(self._hits) >= self.batch_limit:
            self._wake.set()

    async def _loop(self) -> None:
        while not self._closed:
            try:
                await asyncio.wait_for(self._wake.wait(), self.sync_wait_s)
            except asyncio.TimeoutError:
                pass
            self._wake.clear()
            try:
                await self._send()
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("multi-region sync round failed")

    async def _send(self) -> None:
        if not self._hits:
            return
        batch, self._hits = self._hits, {}
        t0 = time.perf_counter()
        # per remote region, group this batch's items by that region's owner
        by_peer: Dict[str, list] = {}
        infos = {}
        for key, item in batch.items():
            rep = pb.RateLimitReq()
            rep.CopyFrom(item)
            rep.behavior = (
                rep.behavior & ~int(Behavior.MULTI_REGION)
            ) | int(Behavior.DRAIN_OVER_LIMIT)
            for info in self.daemon.region_owners(key):
                by_peer.setdefault(info.grpc_address, []).append(rep)
                infos[info.grpc_address] = info
        sem = asyncio.Semaphore(self.concurrency)

        async def send(addr, items):
            client = self.daemon.peer_client(infos[addr])
            if client is None:
                return
            async with sem:
                try:
                    await client.get_peer_rate_limits(
                        peers_pb.GetPeerRateLimitsReq(requests=items),
                        timeout=self.timeout_s,
                    )
                    self.metrics.broadcast_counter.labels(
                        condition="multi_region"
                    ).inc()
                except Exception:
                    self.metrics.check_error_counter.labels(
                        error="multi_region_send"
                    ).inc()

        await asyncio.gather(*(send(a, i) for a, i in by_peer.items()))
        if by_peer:
            self.metrics.global_send_duration.observe(time.perf_counter() - t0)
