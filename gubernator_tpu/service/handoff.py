"""Ownership handoff — device-side state migration on topology change.

The reference accepts that a SetPeers hot-swap strands counter state: keys
whose ring ownership moves are answered fresh by the new owner while the old
owner's rows linger until TTL — a double-capacity window on every scale
event or rolling restart (reference gubernator.go:694-789 rebuilds the
pickers and does nothing with the cache). This manager closes that window
Dynamo-style (DeCandia et al., SOSP'07) adapted to an HBM-resident table:

* **rebalance** (set_peers diff): the device packs every live slot in one
  filter pass (table2.extract_live_rows — the TPU pays for partitioning,
  the host fetches only the live prefix); rows owned by this daemon under
  the OLD ring whose NEW owner is another peer are chunked into idempotent
  TransferState RPCs; the destination merges them through the conservative
  merge kernel (kernel2.merge2 — remaining=min, expiry=max, newest config
  wins, so a retried or crossed transfer can never grant extra capacity);
  the source tombstones rows only after their chunk is acked.
* **drain** (daemon.stop(drain=True)): same machinery with ownership
  computed as if this daemon had already left the ring (owners_of(...,
  exclude=self)) — every owned live row moves to its ring successor under
  a deadline; the unacked remainder stays in the table for the shutdown
  checkpoint (store.FileLoader) and is counted `snapshotted`.

Chunk sends are breaker-gated (service/breaker.py) with jittered-exponential
retry inside the round's deadline — mid-handoff faults cost retries, not
lost rows. Fingerprint → ring-point mapping comes from the daemon's
OwnershipIndex sidecar (peers/ownership.py); rows without a recorded point
cannot be routed and degrade to the reference's behavior for exactly those
rows.
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from typing import Dict, List, Optional

import numpy as np

from gubernator_tpu.service.peer_client import PeerCircuitOpenError, PeerError
from gubernator_tpu.service.wire import transfer_chunk_pb

log = logging.getLogger("gubernator_tpu.handoff")


class HandoffManager:
    def __init__(self, daemon):
        self.daemon = daemon
        b = daemon.conf.behaviors
        self.enabled = b.handoff_enabled
        self.chunk_rows = int(b.handoff_chunk_rows)
        self.deadline_s = b.handoff_deadline_ms / 1e3
        self.rpc_timeout_s = b.batch_timeout_ms / 1e3
        self.metrics = daemon.metrics
        self._seq = 0
        # one round at a time: overlapping rebalances (a flapping discovery
        # backend) would race extract/tombstone against each other
        self._lock = asyncio.Lock()
        # progress surface for /v1/debug/peers: is a round running, and what
        # did the last one move
        self.active = False
        self.rounds = 0
        self.last_round: Dict[str, float] = {}

    # ------------------------------------------------------------- entries
    async def rebalance(self, old_picker, new_picker) -> Dict[str, int]:
        """Move rows whose ownership left this daemon between two ring
        generations (the set_peers diff path)."""
        async with self._lock:
            return await self._round(old_picker, new_picker, frozenset())

    async def drain(self) -> Dict[str, int]:
        """Hand every owned live row to its ring successor (graceful-drain
        path): new ownership is computed with this daemon excluded, exactly
        what the surviving peers' rings will resolve once it is gone."""
        picker = self.daemon._local_picker
        self_addr = self.daemon.conf.advertise_address
        if picker.size() <= 1:
            return dict(extracted=0, transferred=0, tombstoned=0,
                        snapshotted=0, unroutable=0)
        async with self._lock:
            stats = await self._round(picker, picker, frozenset({self_addr}))
        snapshotted = stats["extracted"] - stats["transferred"]
        if snapshotted > 0:
            self.metrics.handoff_rows.labels(phase="snapshotted").inc(
                snapshotted
            )
        stats["snapshotted"] = snapshotted
        return stats

    # --------------------------------------------------------------- round
    async def _round(self, old_picker, new_picker, exclude) -> Dict[str, int]:
        t0 = time.perf_counter()
        daemon = self.daemon
        self_addr = daemon.conf.advertise_address
        stats = dict(extracted=0, transferred=0, tombstoned=0, unroutable=0)
        self.active = True
        try:
            fps, slots = await daemon.runner.extract_live()
            if fps.shape[0] == 0:
                return stats
            points, found = daemon.ownership.points_for(fps)
            stats["unroutable"] = int((~found).sum())
            idx = np.nonzero(found)[0]
            if idx.size == 0:
                return stats
            pts = points[idx]
            old_addr = np.array(
                [o.grpc_address for o in old_picker.owners_of(pts)]
            )
            new_owners = new_picker.owners_of(pts, exclude=exclude)
            new_addr = np.array([o.grpc_address for o in new_owners])
            move = (old_addr == self_addr) & (new_addr != self_addr)
            n_move = int(move.sum())
            if n_move == 0:
                return stats
            stats["extracted"] = n_move
            self.metrics.handoff_rows.labels(phase="extracted").inc(n_move)
            self._seq += 1
            transfer_id = f"{self_addr}/{daemon.conf.instance_id}/{self._seq}"
            now = daemon.now_ms()
            deadline = asyncio.get_running_loop().time() + self.deadline_s
            acked: List[np.ndarray] = []
            sends = []
            for dest in sorted(set(new_addr[move].tolist())):
                rows = idx[move & (new_addr == dest)]
                info = new_picker.get_by_address(dest)
                if info is None:  # pragma: no cover - defensive
                    continue
                sends.append(
                    self._send_dest(
                        info, fps[rows], points[rows], slots[rows],
                        f"{transfer_id}/{dest}", now, deadline, acked,
                    )
                )
            await asyncio.gather(*sends)
            if acked:
                acked_fps = np.concatenate(acked)
                stats["transferred"] = int(acked_fps.shape[0])
                removed = await daemon.runner.tombstone_fps(acked_fps)
                daemon.ownership.discard(acked_fps)
                stats["tombstoned"] = removed
                self.metrics.handoff_rows.labels(phase="tombstoned").inc(
                    removed
                )
            if stats["transferred"] < n_move:
                log.warning(
                    "handoff round incomplete: %d/%d rows acked before the "
                    "deadline (unacked rows stay in the local table)",
                    stats["transferred"], n_move,
                )
            return stats
        finally:
            self.active = False
            self.rounds += 1
            self.last_round = {
                **stats, "duration_ms": round((time.perf_counter() - t0) * 1e3, 1),
            }
            self.metrics.handoff_duration.observe(time.perf_counter() - t0)
            log.info(
                "handoff round: %s in %.1f ms",
                stats, (time.perf_counter() - t0) * 1e3,
            )

    async def _send_dest(
        self, info, fps, points, slots, transfer_id, now, deadline, acked_out
    ) -> None:
        """Ship one destination's rows in chunks; each chunk retries with
        jittered-exponential backoff inside the round deadline. Acked chunk
        fps land in `acked_out` (the source tombstones only those)."""
        daemon = self.daemon
        client = daemon.peer_client(info)
        if client is None:
            return
        loop = asyncio.get_running_loop()
        n = fps.shape[0]
        total = -(-n // self.chunk_rows)
        # chunks travel in this daemon's own slot layout; the receiver
        # converts through the canonical full row on mismatch (merge_rows)
        layout = daemon.engine.table.layout
        for ci in range(total):
            sl = slice(ci * self.chunk_rows, (ci + 1) * self.chunk_rows)
            req = transfer_chunk_pb(
                transfer_id, ci, total, daemon.conf.advertise_address, now,
                fps[sl], points[sl], slots[sl], layout=layout,
            )
            attempt = 0
            while True:
                remaining = deadline - loop.time()
                if remaining <= 0:
                    return  # this chunk (and the rest) stays local
                try:
                    resp = await client.transfer_state(
                        req, timeout=min(self.rpc_timeout_s, remaining)
                    )
                except PeerCircuitOpenError as exc:
                    # cooldown, then the next attempt is the half-open probe
                    await asyncio.sleep(
                        max(0.0, min(exc.retry_after_s, remaining, 0.25))
                    )
                except PeerError:
                    attempt += 1
                    self.metrics.handoff_chunk_retries.inc()
                    await asyncio.sleep(
                        max(0.0, min(
                            random.uniform(0, 0.02 * (2 ** min(attempt, 6))),
                            remaining,
                        ))
                    )
                else:
                    count = int(fps[sl].shape[0])
                    acked_out.append(fps[sl])
                    self.metrics.handoff_rows.labels(
                        phase="transferred"
                    ).inc(count)
                    if resp.duplicate:
                        log.debug(
                            "transfer chunk %s/%d was an idempotent replay",
                            transfer_id, ci,
                        )
                    break
