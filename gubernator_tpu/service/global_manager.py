"""Cross-daemon GLOBAL behavior — async hit sync + owner broadcasts.

The host-side peer plane for GLOBAL rate limits across daemons (reference
global.go:31-307). Complements the in-mesh collective path
(parallel/global_sync.py): inside one TPU slice the sync is two all_gathers
over ICI; ACROSS daemons (slices, regions) it is this manager speaking the
reference's own two-stage protocol over gRPC:

* runAsyncHits analog: non-owner aggregates hits per key (sum Hits, OR
  RESET_REMAINING — reference global.go:109-123) and ships them to owners via
  GetPeerRateLimits every GlobalSyncWait (100 ms) or at GlobalBatchLimit.
* runBroadcasts analog: the owner re-reads each updated key's status with
  Hits=0 and pushes UpdatePeerGlobals to every local peer except itself
  (reference global.go:255-298), bounded by GlobalPeerRequestsConcurrency.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Dict, Optional

log = logging.getLogger("gubernator_tpu.global")

from gubernator_tpu.proto import gubernator_pb2 as pb
from gubernator_tpu.proto import peers_pb2 as peers_pb
from gubernator_tpu.types import Behavior, has_behavior


def _unimplemented(exc: BaseException) -> bool:
    """Does this (possibly PeerError-wrapped) failure mean the peer does not
    implement the RPC (a pre-compact build)?"""
    import grpc

    seen = set()
    while exc is not None and id(exc) not in seen:
        seen.add(id(exc))
        code = getattr(exc, "code", None)
        if callable(code):
            try:
                if code() == grpc.StatusCode.UNIMPLEMENTED:
                    return True
            except Exception:
                pass
        exc = getattr(exc, "cause", None) or exc.__cause__
    return False


class GlobalManager:
    def __init__(self, daemon):
        self.daemon = daemon
        b = daemon.conf.behaviors
        self.sync_wait_s = b.global_sync_wait_ms / 1e3
        self.batch_limit = b.global_batch_limit
        self.timeout_s = b.global_timeout_ms / 1e3
        self.concurrency = b.global_peer_concurrency
        self.metrics = daemon.metrics
        self.requeue_retries = b.global_requeue_retries
        self.queue_cap = b.global_queue_cap
        # inter-slice compact sync (SyncGlobalsWire): batches ≥ _WIRE_MIN
        # encodable entries ship as ONE lane-codec message instead of N
        # nested RateLimitReq protos (service/wire.sync_wire_pb); smaller
        # or non-encodable batches take the classic proto path
        self.wire_sync = b.global_wire_sync
        # pending hits: hash_key → aggregated RateLimitReq (non-owner side)
        self._hits: Dict[str, pb.RateLimitReq] = {}
        # hash_key → monotonic ts of the key's FIRST un-synced hit; survives
        # requeues (the hit is that old however many sends failed) and is
        # dropped only when the key's hits reach the owner or are dropped.
        # min() over this is the gubernator_global_sync_staleness_seconds
        # gauge — the convergence-lag signal (docs/observability.md).
        self._hit_age: Dict[str, float] = {}
        # requeue accounting: hash_key → failed-send count (bounded retries)
        self._hit_attempts: Dict[str, int] = {}
        # pending broadcasts: hash_key → latest owner-side request (config carrier)
        self._updates: Dict[str, pb.RateLimitReq] = {}
        self._hits_wake = asyncio.Event()
        self._bcast_wake = asyncio.Event()
        self._tasks = []
        self._closed = False

    def start(self) -> None:
        self._tasks = [
            asyncio.create_task(self._hits_loop(), name="global-hits"),
            asyncio.create_task(self._broadcast_loop(), name="global-bcast"),
        ]

    async def close(self) -> None:
        self._closed = True
        self._hits_wake.set()
        self._bcast_wake.set()
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except asyncio.CancelledError:
                pass
        # final flush so queued hits/updates aren't lost on graceful shutdown
        await self._send_hits()
        await self._broadcast()

    # --------------------------------------------------------------- queueing
    def queue_hit(self, key: str, item: "pb.RateLimitReq") -> None:
        """Non-owner hit on a GLOBAL key (reference global.go:85-123).
        Zero-hit requests are never queued (global.go:85-95)."""
        if item.hits == 0:
            return
        self._hit_age.setdefault(key, time.monotonic())
        agg = self._hits.get(key)
        if agg is None:
            agg = pb.RateLimitReq()
            agg.CopyFrom(item)
            self._hits[key] = agg
        else:
            hits = agg.hits + item.hits
            reset = (agg.behavior | item.behavior) & int(Behavior.RESET_REMAINING)
            agg.CopyFrom(item)  # newest config wins
            agg.hits = hits
            agg.behavior |= reset
        self.metrics.global_queue_length.set(len(self._hits))
        if len(self._hits) >= self.batch_limit:
            self._hits_wake.set()

    def queue_update(self, key: str, item: "pb.RateLimitReq") -> None:
        """Owner-side: mark the key for an authoritative broadcast (reference
        QueueUpdate, global.go:92-99)."""
        self._updates[key] = item
        self.metrics.broadcast_queue_length.set(len(self._updates))
        if len(self._updates) >= self.batch_limit:
            self._bcast_wake.set()

    # ------------------------------------------------------------- hits loop
    async def _hits_loop(self) -> None:
        while not self._closed:
            try:
                await asyncio.wait_for(self._hits_wake.wait(), self.sync_wait_s)
            except asyncio.TimeoutError:
                pass
            self._hits_wake.clear()
            try:
                await self._send_hits()
            except asyncio.CancelledError:
                raise
            except Exception:
                # a failed round must not kill the loop (reference counts and
                # moves on, global.go:190-195)
                log.exception("global hit-sync round failed")

    async def _send_hits(self) -> None:
        if not self._hits:
            return
        batch, self._hits = self._hits, {}
        self.metrics.global_queue_length.set(len(self._hits))
        t0 = time.perf_counter()
        # group by owning peer (reference sendHits, global.go:155-199)
        by_peer: Dict[str, list] = {}  # addr → [(hash_key, item)]
        infos = {}
        for key, item in batch.items():
            try:
                info = self.daemon.get_peer(key)
            except Exception:
                # no peers; drop (eventual consistency tolerates it)
                self._hit_attempts.pop(key, None)
                self._hit_age.pop(key, None)
                continue
            if self.daemon.is_self(info):
                # became owner since queueing; owner path handles it
                self._hit_attempts.pop(key, None)
                self._hit_age.pop(key, None)
                continue
            by_peer.setdefault(info.grpc_address, []).append((key, item))
            infos[info.grpc_address] = info
        sem = asyncio.Semaphore(self.concurrency)

        async def send(addr, pairs):
            client = self.daemon.peer_client(infos[addr])
            if client is None:
                # peer vanished between grouping and send — requeue so the
                # next round re-resolves ownership
                self._requeue(pairs)
                return
            if client.breaker.blocked:
                # fail fast: no RPC (and no timeout wait) toward an open
                # breaker; the batch requeues for the next sync round
                self.metrics.check_error_counter.labels(
                    error="global_send"
                ).inc()
                self._requeue(pairs)
                return
            async with sem:
                try:
                    await self._ship(client, pairs)
                except asyncio.CancelledError:
                    raise
                except Exception:
                    # counted + REQUEUED (bounded): brief owner outages no
                    # longer lose replication traffic (the reference drops
                    # on error, global.go:190-195)
                    self.metrics.check_error_counter.labels(
                        error="global_send"
                    ).inc()
                    self._requeue(pairs)
                else:
                    for key, _ in pairs:
                        self._hit_attempts.pop(key, None)
                        self._hit_age.pop(key, None)

        await asyncio.gather(*(send(a, p) for a, p in by_peer.items()))
        if by_peer:
            self.metrics.global_send_duration.observe(time.perf_counter() - t0)

    _WIRE_MIN = 4  # below this the proto path's framing overhead is moot

    async def _ship(self, client, pairs) -> None:
        """One owner-bound batch over the wire: the compact SyncGlobalsWire
        codec when enabled, the batch is big enough to pay off, every entry
        is representable, AND the peer speaks it — the classic
        GetPeerRateLimits proto path otherwise (identical semantics). An
        UNIMPLEMENTED answer latches `wire_sync_ok` off for that peer (a
        pre-compact build) and the batch re-ships as proto in the same
        round, so mixed-version clusters converge without losing a tick."""
        req = None
        if (
            self.wire_sync
            and len(pairs) >= self._WIRE_MIN
            and getattr(client, "wire_sync_ok", True)
        ):
            from gubernator_tpu.service.wire import sync_wire_pb

            req = sync_wire_pb(pairs, self.daemon.conf.advertise_address)
        if req is not None:
            try:
                await client.sync_globals_wire(req, timeout=self.timeout_s)
            except Exception as exc:
                if not _unimplemented(exc):
                    raise
                client.wire_sync_ok = False
            else:
                self.metrics.global_wire_entries.labels(
                    direction="sent"
                ).inc(len(pairs))
                return
        await client.get_peer_rate_limits(
            peers_pb.GetPeerRateLimitsReq(requests=[i for _, i in pairs]),
            timeout=self.timeout_s,
        )
        if self.wire_sync and len(pairs) >= self._WIRE_MIN:
            self.metrics.global_wire_entries.labels(
                direction="fallback"
            ).inc(len(pairs))

    def _requeue(self, pairs) -> None:
        """Re-merge a failed batch into the pending queue, bounded by a
        per-key retry cap and a total queue-size cap (so a long partition
        degrades to the reference's drop behavior instead of growing
        memory without bound)."""
        requeued = dropped = 0
        for key, item in pairs:
            attempts = self._hit_attempts.get(key, 0) + 1
            if attempts > self.requeue_retries or (
                key not in self._hits and len(self._hits) >= self.queue_cap
            ):
                self._hit_attempts.pop(key, None)
                self._hit_age.pop(key, None)
                dropped += 1
                continue
            self._hit_attempts[key] = attempts
            agg = self._hits.get(key)
            if agg is None:
                self._hits[key] = item
            else:
                # fresh hits arrived for the key since the failed send: fold
                # the failed batch back in (same merge rule as queue_hit —
                # newest config wins, hits add, RESET_REMAINING sticks)
                agg.hits += item.hits
                agg.behavior |= item.behavior & int(Behavior.RESET_REMAINING)
            requeued += 1
        if requeued:
            self.metrics.global_requeued.inc(requeued)
        if dropped:
            self.metrics.global_requeue_dropped.inc(dropped)
        self.metrics.global_queue_length.set(len(self._hits))

    # ----------------------------------------------------------- introspection
    def oldest_hit_age_s(self) -> float:
        """Age of the oldest GLOBAL hit not yet acked by its owner (0 when
        nothing is pending) — queued AND in-flight/requeued keys count; a
        hit is only "synced" once an owner send succeeded."""
        if not self._hit_age:
            return 0.0
        return max(0.0, time.monotonic() - min(self._hit_age.values()))

    def debug(self) -> dict:
        """Live GLOBAL-plane state for /v1/debug/global."""
        return {
            "pending_hits": len(self._hits),
            "pending_updates": len(self._updates),
            "unsynced_keys": len(self._hit_age),
            "requeue_attempts": len(self._hit_attempts),
            "oldest_hit_age_s": round(self.oldest_hit_age_s(), 3),
            "sync_wait_ms": self.sync_wait_s * 1e3,
            "batch_limit": self.batch_limit,
            "wire_sync": self.wire_sync,
        }

    # -------------------------------------------------------- broadcast loop
    async def _broadcast_loop(self) -> None:
        while not self._closed:
            try:
                await asyncio.wait_for(self._bcast_wake.wait(), self.sync_wait_s)
            except asyncio.TimeoutError:
                pass
            self._bcast_wake.clear()
            try:
                await self._broadcast()
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("global broadcast round failed")

    async def _broadcast(self) -> None:
        if not self._updates:
            return
        batch, self._updates = self._updates, {}
        self.metrics.broadcast_queue_length.set(0)
        t0 = time.perf_counter()
        # re-read each key's current status with Hits=0 (reference
        # global.go:255-262) — a zero-hit check is the authoritative read
        import numpy as np

        from gubernator_tpu.service.wire import columns_from_pb

        reads = []
        for key, item in batch.items():
            r = pb.RateLimitReq()
            r.CopyFrom(item)
            r.hits = 0
            r.behavior &= ~int(Behavior.GLOBAL)  # local read, not re-queued
            reads.append(r)
        cols, _ = columns_from_pb(reads)
        rc = await self.daemon.runner.check_columns(cols)
        # sliding-window fidelity (PR 11): the wire's (status, remaining)
        # alone cannot rebuild a window — replicas need the previous-window
        # count and the STORED-style remaining to interpolate the same
        # `used` as the owner. Read the owner's stored slots for the
        # window keys once per broadcast and ride them as status metadata
        # (the frozen proto schema has no field; old receivers ignore it —
        # mixed-version clusters degrade to the legacy permissive rebuild).
        win_meta: dict = {}
        win_rows = [
            i for i, (_k, it) in enumerate(batch.items())
            if it.algorithm == int(pb.SLIDING_WINDOW)
        ]
        if win_rows:
            import numpy as np

            from gubernator_tpu.ops.table2 import (
                LIMIT, REM_I, REMF_HI, REMF_LO,
            )

            found, slots = await self.daemon.runner.read_state(
                np.asarray(cols.fp)[win_rows]
            )
            for j, i in enumerate(win_rows):
                if not found[j]:
                    continue
                prev = (int(slots[j, REMF_HI]) << 32) | (
                    int(slots[j, REMF_LO]) & 0xFFFFFFFF
                )
                rem_store = int(slots[j, REM_I])
                win_meta[i] = (prev, rem_store)
        globals_ = []
        for i, (key, item) in enumerate(batch.items()):
            status = pb.RateLimitResp(
                status=int(rc.status[i]),
                limit=int(rc.limit[i]),
                remaining=int(rc.remaining[i]),
                reset_time=int(rc.reset_time[i]),
            )
            if i in win_meta:
                prev, rem_store = win_meta[i]
                status.metadata["w_prev"] = str(prev)
                status.metadata["w_rem"] = str(rem_store)
            globals_.append(
                peers_pb.UpdatePeerGlobal(
                    key=key,
                    status=status,
                    algorithm=item.algorithm,
                    duration=item.duration,
                    created_at=item.created_at or self.daemon.now_ms(),
                )
            )
        req = peers_pb.UpdatePeerGlobalsReq(globals=globals_)
        peers = [p for p in self.daemon.local_peers() if not self.daemon.is_self(p)]
        sem = asyncio.Semaphore(self.concurrency)

        async def push(info):
            client = self.daemon.peer_client(info)
            if client is None:
                return
            if client.breaker.blocked:
                # skip (no RPC) — broadcasts are not requeued: every owner
                # update re-reads authoritative state, so the next round
                # after the breaker closes refreshes this peer anyway
                self.metrics.check_error_counter.labels(
                    error="broadcast"
                ).inc()
                return
            async with sem:
                try:
                    await client.update_peer_globals(req, timeout=self.timeout_s)
                    self.metrics.broadcast_counter.labels(condition="broadcast").inc()
                except asyncio.CancelledError:
                    raise
                except Exception:
                    self.metrics.check_error_counter.labels(
                        error="broadcast"
                    ).inc()

        await asyncio.gather(*(push(p) for p in peers))
        self.metrics.broadcast_duration.observe(time.perf_counter() - t0)
