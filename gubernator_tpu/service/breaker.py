"""Per-peer circuit breaker — closed → open → half-open with jittered backoff.

The peer plane's fault-tolerance primitive (Nygard, *Release It!*; Dean &
Barroso, *The Tail at Scale*). Each PeerClient owns one breaker; every unary
RPC consults it. Consecutive failures trip the breaker OPEN; while open,
calls fail fast (no RPC, no timeout wait) until a jittered exponential
cooldown elapses. The first call after the cooldown becomes a HALF_OPEN
probe (bounded by `probe_budget` concurrent probes); a probe success closes
the breaker, a probe failure re-opens it with a doubled cooldown.

Backoff uses *equal jitter*: half the exponential delay is deterministic,
half uniform-random — spreading reconnect storms across peers without the
near-zero sleeps full jitter allows (which would turn the open state into a
busy retry loop).
"""

from __future__ import annotations

import enum
import random
import time
from typing import Callable, Optional


class BreakerState(enum.IntEnum):
    # gauge values: the metric (gubernator_circuit_breaker_state) exports the
    # integer, so order is meaning: 0 healthy → 2 tripped
    CLOSED = 0
    HALF_OPEN = 1
    OPEN = 2


_STATE_NAMES = {
    BreakerState.CLOSED: "closed",
    BreakerState.HALF_OPEN: "half-open",
    BreakerState.OPEN: "open",
}


class CircuitBreaker:
    """Single-threaded (asyncio) circuit breaker for one peer.

    allow()           — reserve the right to attempt an RPC now (may
                        transition OPEN → HALF_OPEN and consume a probe slot)
    record_success()  — RPC completed; closes the breaker, resets backoff
    record_failure()  — RPC failed; counts toward the trip threshold, or
                        re-opens from HALF_OPEN with a doubled cooldown
    record_discard()  — RPC neither succeeded nor failed (cancellation);
                        releases a probe slot without a verdict
    blocked           — side-effect-free "would allow() refuse right now?"
    """

    def __init__(
        self,
        failure_threshold: int = 5,
        backoff_base_ms: float = 200.0,
        backoff_cap_ms: float = 30_000.0,
        probe_budget: int = 1,
        clock: Callable[[], float] = time.monotonic,
        rng: Optional[random.Random] = None,
        on_state: Optional[Callable[[BreakerState], None]] = None,
    ):
        self.failure_threshold = max(1, int(failure_threshold))
        self.backoff_base_s = backoff_base_ms / 1e3
        self.backoff_cap_s = max(backoff_cap_ms, backoff_base_ms) / 1e3
        self.probe_budget = max(1, int(probe_budget))
        self._clock = clock
        self._rng = rng or random
        self._on_state = on_state
        self._state = BreakerState.CLOSED
        self._failures = 0  # consecutive failures while CLOSED
        self._openings = 0  # consecutive open cycles (backoff exponent)
        self._open_until = 0.0
        self._probes = 0  # in-flight HALF_OPEN probes

    # ---------------------------------------------------------------- state
    @property
    def state(self) -> BreakerState:
        return self._state

    @property
    def state_name(self) -> str:
        return _STATE_NAMES[self._state]

    def _set_state(self, s: BreakerState) -> None:
        if s is not self._state:
            self._state = s
            if self._on_state is not None:
                self._on_state(s)

    @property
    def blocked(self) -> bool:
        """True when an attempt right now would be refused — open and still
        cooling down, or half-open with the probe budget exhausted."""
        if self._state is BreakerState.OPEN:
            return self._clock() < self._open_until
        if self._state is BreakerState.HALF_OPEN:
            return self._probes >= self.probe_budget
        return False

    def retry_after_s(self) -> float:
        """Remaining cooldown (0 when an attempt is allowed)."""
        if self._state is BreakerState.OPEN:
            return max(0.0, self._open_until - self._clock())
        return 0.0

    # ------------------------------------------------------------- protocol
    def allow(self) -> bool:
        if self._state is BreakerState.CLOSED:
            return True
        if self._state is BreakerState.OPEN:
            if self._clock() < self._open_until:
                return False
            # cooldown elapsed: this caller becomes the first probe
            self._set_state(BreakerState.HALF_OPEN)
            self._probes = 1
            return True
        # HALF_OPEN: bounded concurrent probes
        if self._probes < self.probe_budget:
            self._probes += 1
            return True
        return False

    def record_success(self) -> None:
        if self._state is BreakerState.HALF_OPEN:
            self._probes = max(0, self._probes - 1)
        # any completed RPC is proof of life — also closes from OPEN when a
        # long pre-trip call finishes late
        self._failures = 0
        self._openings = 0
        self._set_state(BreakerState.CLOSED)

    def record_failure(self) -> None:
        if self._state is BreakerState.HALF_OPEN:
            self._probes = max(0, self._probes - 1)
            self._trip()
        elif self._state is BreakerState.CLOSED:
            self._failures += 1
            if self._failures >= self.failure_threshold:
                self._trip()
        # OPEN: a stale in-flight failure must not extend the cooldown

    def record_discard(self) -> None:
        if self._state is BreakerState.HALF_OPEN:
            self._probes = max(0, self._probes - 1)

    def _trip(self) -> None:
        self._failures = 0
        self._openings += 1
        exp = min(self._openings - 1, 32)  # bound 2**n
        ceiling = min(self.backoff_cap_s, self.backoff_base_s * (2**exp))
        # equal jitter: [ceiling/2, ceiling)
        delay = ceiling / 2 + self._rng.uniform(0, ceiling / 2)
        self._open_until = self._clock() + delay
        self._set_state(BreakerState.OPEN)
