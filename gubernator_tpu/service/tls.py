"""TLS subsystem: file-based certs, auto self-signed CA, client-auth modes.

Mirrors the reference's tls.go scope (reference tls.go:50-520): server + client
credentials from PEM files, an AutoTLS mode that generates a self-signed CA and
server certificate in memory (reference tls.go:364-520), and client-auth
("require" = any client cert, "verify" = must chain to the CA — reference
TLSConfig.ClientAuth). Certificates are built with `cryptography`; gRPC takes
raw PEM bytes, aiohttp takes an ssl.SSLContext — both come from one CertBundle.
"""

from __future__ import annotations

import datetime
import ipaddress
import ssl
import tempfile
from dataclasses import dataclass
from typing import Optional

import grpc


@dataclass
class CertBundle:
    ca_pem: bytes
    cert_pem: bytes
    key_pem: bytes


_auto_cache: dict = {}


def generate_self_signed(hostnames=("localhost",)) -> CertBundle:
    """Self-signed CA + server cert (reference AutoTLS, tls.go:364-520)."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    ca_key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    now = datetime.datetime.now(datetime.timezone.utc)
    ca_name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, "gubernator-tpu auto CA")]
    )
    ca_cert = (
        x509.CertificateBuilder()
        .subject_name(ca_name)
        .issuer_name(ca_name)
        .public_key(ca_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=365))
        .add_extension(x509.BasicConstraints(ca=True, path_length=None), critical=True)
        .sign(ca_key, hashes.SHA256())
    )
    sans = []
    for h in hostnames:
        try:
            sans.append(x509.IPAddress(ipaddress.ip_address(h)))
        except ValueError:
            sans.append(x509.DNSName(h))
    sans.append(x509.IPAddress(ipaddress.ip_address("127.0.0.1")))
    cert = (
        x509.CertificateBuilder()
        .subject_name(
            x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, hostnames[0])])
        )
        .issuer_name(ca_name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=365))
        .add_extension(x509.SubjectAlternativeName(sans), critical=False)
        .sign(ca_key, hashes.SHA256())
    )
    return CertBundle(
        ca_pem=ca_cert.public_bytes(serialization.Encoding.PEM),
        cert_pem=cert.public_bytes(serialization.Encoding.PEM),
        key_pem=key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption(),
        ),
    )


def bundle_from_config(conf) -> CertBundle:
    """Resolve the cert bundle once per daemon: files when given, else AutoTLS
    (cached per advertise address so server and client sides agree)."""
    if conf.tls_cert_file and conf.tls_key_file:
        ca = b""
        if conf.tls_ca_file:
            with open(conf.tls_ca_file, "rb") as f:
                ca = f.read()
        with open(conf.tls_cert_file, "rb") as f:
            cert = f.read()
        with open(conf.tls_key_file, "rb") as f:
            key = f.read()
        return CertBundle(ca_pem=ca, cert_pem=cert, key_pem=key)
    host = conf.advertise_address.rsplit(":", 1)[0] or "localhost"
    if host not in _auto_cache:
        _auto_cache[host] = generate_self_signed((host,))
    return _auto_cache[host]


def server_credentials(conf) -> grpc.ServerCredentials:
    require = conf.tls_client_auth in ("require", "verify")
    if conf.tls_cert_file and conf.tls_key_file:
        # hot certificate reload (the keypairReloader analog, reference
        # tls.go:295-362): the per-handshake fetcher re-reads the PEM files
        # when their mtimes change, so rotated certs take effect without a
        # restart; a pair that fails validation (mid-rotation torn write,
        # mismatched key) keeps the last good pair serving, like the Go
        # reloader's LoadX509KeyPair guard
        state = {"mtimes": None, "config": None}

        def _maybe_load():
            """New ServerCertificateConfiguration when the files changed and
            validate, else None (the gRPC fetcher no-change contract)."""
            mtimes = cert_files_mtimes(conf)
            if mtimes is None:
                # unreadable files: loud at startup (initial load), treated
                # as no-change by the fetcher's guard afterwards
                raise FileNotFoundError("TLS cert/key files unreadable")
            if state["config"] is not None and mtimes == state["mtimes"]:
                return None
            b = bundle_from_config(conf)
            _validate_keypair(b)  # raises on torn/mismatched rotation
            state["config"] = grpc.ssl_server_certificate_configuration(
                [(b.key_pem, b.cert_pem)],
                root_certificates=b.ca_pem if require else None,
            )
            state["mtimes"] = mtimes
            return state["config"]

        initial = _maybe_load()

        def fetcher():
            try:
                return _maybe_load()
            except Exception:
                return None  # keep serving the last good pair

        return grpc.dynamic_ssl_server_credentials(
            initial, fetcher, require_client_authentication=require
        )
    b = bundle_from_config(conf)
    return grpc.ssl_server_credentials(
        [(b.key_pem, b.cert_pem)],
        root_certificates=b.ca_pem if require else None,
        require_client_auth=require,
    )


def _validate_keypair(b: CertBundle) -> None:
    """Reject torn/mismatched cert+key pairs before they reach handshakes."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    with tempfile.NamedTemporaryFile(suffix=".pem") as cf, tempfile.NamedTemporaryFile(
        suffix=".pem"
    ) as kf:
        cf.write(b.cert_pem)
        cf.flush()
        kf.write(b.key_pem)
        kf.flush()
        ctx.load_cert_chain(cf.name, kf.name)  # raises ssl.SSLError on mismatch


def cert_files_mtimes(conf):
    """Snapshot of the configured PEM files' mtimes (None when not
    file-based) — the daemon's rotation watcher keys on this."""
    import os

    if not (conf.tls_cert_file and conf.tls_key_file):
        return None
    paths = [conf.tls_cert_file, conf.tls_key_file] + (
        [conf.tls_ca_file] if conf.tls_ca_file else []
    )
    try:
        return tuple(os.path.getmtime(p) for p in paths)
    except OSError:
        return None


def client_credentials(conf) -> grpc.ChannelCredentials:
    """Peer-to-peer client credentials; with client-auth modes the peers
    present the same cert (the reference's peers share the server TLS setup,
    tls.go:138-238)."""
    b = bundle_from_config(conf)
    if conf.tls_client_auth in ("require", "verify"):
        return grpc.ssl_channel_credentials(
            root_certificates=b.ca_pem or None,
            private_key=b.key_pem,
            certificate_chain=b.cert_pem,
        )
    return grpc.ssl_channel_credentials(root_certificates=b.ca_pem or None)


def http_ssl_context(
    conf, require_client_auth: Optional[bool] = None
) -> Optional[ssl.SSLContext]:
    """Server-side ssl context for an HTTP listener. `require_client_auth`
    defaults to the daemon's client-auth mode; the status listener passes
    False so probes/scrapers work without certs (reference
    daemon.go:324-352)."""
    b = bundle_from_config(conf)
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    with tempfile.NamedTemporaryFile(suffix=".pem") as cf, tempfile.NamedTemporaryFile(
        suffix=".pem"
    ) as kf:
        cf.write(b.cert_pem)
        cf.flush()
        kf.write(b.key_pem)
        kf.flush()
        ctx.load_cert_chain(cf.name, kf.name)
    require = (
        conf.tls_client_auth in ("require", "verify")
        if require_client_auth is None
        else require_client_auth
    )
    if require:
        if not b.ca_pem:
            # never silently downgrade: the operator asked for client auth
            raise ValueError(
                "tls_client_auth is set but no CA is available to verify "
                "client certificates (set GUBER_TLS_CA or use GUBER_TLS_AUTO)"
            )
        ctx.verify_mode = ssl.CERT_REQUIRED
        ctx.load_verify_locations(cadata=b.ca_pem.decode())
    return ctx
