"""Protobuf ↔ column conversion at the serving edge.

The wire surface is the reference's exact proto schema (proto/gubernator.proto,
re-created wire-compatibly); internally everything is columns
(ops/batch.py RequestColumns). The per-item loops live here, at the edge, and
nowhere else on the serving path.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from gubernator_tpu.hashing import fingerprint
from gubernator_tpu.ops.batch import (
    ERR_CASCADE_DEEP,
    ERR_EMPTY_KEY,
    ERR_EMPTY_NAME,
    ERROR_STRINGS,
    RequestColumns,
    ResponseColumns,
)
from gubernator_tpu.proto import gubernator_pb2 as pb
from gubernator_tpu.proto import peers_pb2 as peers_pb
from gubernator_tpu import types
from gubernator_tpu.types import Behavior

# the reference rejects batches above this size outright (gubernator.go:41-42);
# GUBER_MAX_BATCH_SIZE overrides per daemon (config.max_batch_size) — this
# constant is the wire-compatible default and the rejection-string template.
MAX_BATCH_SIZE = 1000


def batch_too_large_error(cap: int) -> str:
    """The reference's exact rejection wording (gubernator.go:41-42),
    parameterized by the configured cap."""
    return f"Requests.RateLimits list too large; max size is '{cap}'"


class WireBatch(NamedTuple):
    """One parsed request batch carrying BOTH serving forms: the legacy
    column view (routing, pb fallback, non-encodable dispatches) and the
    pre-packed compact-wire lanes the native parser produced in the same
    pass over the bytes. When every row is `encodable`, the batcher stages
    `lanes` straight into the engine's ingress grid (ops/wire.py layout,
    created-delta stamped at flush) — the proto bytes are traversed exactly
    once on the whole serving path."""

    cols: RequestColumns
    lanes: np.ndarray  # (5, n) int32, lane-4 created-delta bits zero
    encodable: np.ndarray  # (n,) bool — compact-wire representable
    nbytes: int  # request wire size (adaptive-window byte accounting)

    @property
    def rows(self) -> int:
        return self.cols.fp.shape[0]


def subset_wire(wb: WireBatch, rows: np.ndarray) -> WireBatch:
    return WireBatch(
        cols=subset_columns(wb.cols, rows),
        lanes=wb.lanes[:, rows],
        encodable=wb.encodable[rows],
        nbytes=int(wb.nbytes * len(rows) / max(wb.rows, 1)),
    )


def columns_from_pb(
    items: Sequence["pb.RateLimitReq"],
) -> Tuple[RequestColumns, List[str]]:
    """RateLimitReq list → (RequestColumns, hash_keys). hash_keys feed the
    peer ring (ownership is decided on the string key, reference
    gubernator.go:243 + replicated_hash.go:104)."""
    n = len(items)
    fp = np.zeros(n, dtype=np.int64)
    err = np.zeros(n, dtype=np.int8)
    algo = np.zeros(n, dtype=np.int32)
    behavior = np.zeros(n, dtype=np.int32)
    hits = np.zeros(n, dtype=np.int64)
    limit = np.zeros(n, dtype=np.int64)
    burst = np.zeros(n, dtype=np.int64)
    duration = np.zeros(n, dtype=np.int64)
    created_at = np.zeros(n, dtype=np.int64)
    hash_keys: List[str] = [""] * n
    clip = 1 << 62
    for i, r in enumerate(items):
        if r.unique_key == "":
            err[i] = ERR_EMPTY_KEY
            continue
        if r.name == "":
            err[i] = ERR_EMPTY_NAME
            continue
        hash_keys[i] = r.name + "_" + r.unique_key
        fp[i] = fingerprint(r.name, r.unique_key)
        algo[i] = r.algorithm
        # client-facing bits only — flag values 1..32 plus the 2-bit
        # priority tier at bits 6-7 (native parser applies the same mask):
        # the behavior word's high bits carry the INTERNAL cascade level,
        # which must never arrive from the wire
        behavior[i] = r.behavior & 255
        hits[i] = min(max(r.hits, -clip), clip)
        limit[i] = min(max(r.limit, -clip), clip)
        burst[i] = min(max(r.burst, -clip), clip)
        duration[i] = min(max(r.duration, -clip), clip)
        created_at[i] = r.created_at if r.HasField("created_at") else 0
    return (
        RequestColumns(
            fp=fp, algo=algo, behavior=behavior, hits=hits, limit=limit,
            burst=burst, duration=duration, created_at=created_at, err=err,
        ),
        hash_keys,
    )


def pb_from_response_columns(
    rc: ResponseColumns, rows: Sequence[int] = None,
    now_ms: Optional[int] = None,
) -> List["pb.RateLimitResp"]:
    """ResponseColumns → RateLimitResp list (optionally a row subset).
    With `now_ms`, denied rows additionally surface
    metadata["retry_after_ms"] — the ms until the reset/conforming instant
    (for GCRA denials reset_time IS the exact TAT-derived conforming
    instant, ops/math.py). The frozen proto schema has no field for it;
    metadata keeps old clients compatible."""

    def resp(i):
        st = int(rc.status[i])
        r = pb.RateLimitResp(
            status=st,
            limit=int(rc.limit[i]),
            remaining=int(rc.remaining[i]),
            reset_time=int(rc.reset_time[i]),
            error=ERROR_STRINGS[int(rc.err[i])],
        )
        if now_ms is not None and st == 1:
            r.metadata["retry_after_ms"] = str(
                max(0, int(rc.reset_time[i]) - int(now_ms))
            )
        return r

    idx = range(rc.status.shape[0]) if rows is None else rows
    return [resp(i) for i in idx]


def subset_columns(cols: RequestColumns, rows: np.ndarray) -> RequestColumns:
    return RequestColumns(*[f[rows] for f in cols])


def concat_columns(parts: Sequence[RequestColumns]) -> RequestColumns:
    if len(parts) == 1:
        return parts[0]
    return RequestColumns(
        *[np.concatenate([p[k] for p in parts]) for k in range(len(parts[0]))]
    )


def empty_response_columns(n: int) -> ResponseColumns:
    return ResponseColumns(
        status=np.zeros(n, dtype=np.int32),
        limit=np.zeros(n, dtype=np.int64),
        remaining=np.zeros(n, dtype=np.int64),
        reset_time=np.zeros(n, dtype=np.int64),
        err=np.zeros(n, dtype=np.int8),
    )


def merge_response_columns(
    dst: ResponseColumns, rows: np.ndarray, src: ResponseColumns
) -> None:
    """Scatter `src` (len(rows) entries) into `dst` at `rows` in place."""
    dst.status[rows] = src.status
    dst.limit[rows] = src.limit
    dst.remaining[rows] = src.remaining
    dst.reset_time[rows] = src.reset_time
    dst.err[rows] = src.err


def resp_pb_into_columns(
    dst: ResponseColumns, rows: Sequence[int], resps: Sequence["pb.RateLimitResp"]
) -> None:
    """Install peer-returned RateLimitResp messages into response columns.
    Free-form peer error strings don't fit the ERR_* enum; they're carried in
    an overflow list keyed by row (see ResponseAssembly)."""
    for row, r in zip(rows, resps):
        dst.status[row] = r.status
        dst.limit[row] = r.limit
        dst.remaining[row] = r.remaining
        dst.reset_time[row] = r.reset_time


def peer_req_pb(items: Sequence["pb.RateLimitReq"]) -> "peers_pb.GetPeerRateLimitsReq":
    return peers_pb.GetPeerRateLimitsReq(requests=items)


# ------------------------------------------------------------- cascades
#
# A cascade request (RateLimitReq.cascade — per-tenant, global, … levels on
# top of the request's own level-0 limit) expands into one engine row per
# level, carrier first then members in level order, with the level riding
# the behavior word's high bits (types.CASCADE_LEVEL_SHIFT). Expansion
# happens AFTER peer routing (the whole cascade lives on the level-0 key's
# owner) and the engine evaluates every level in ONE dispatch, folding the
# combined verdict into the carrier row (deny-if-any; kernel2
# fold_cascade_packed / engine._fold_cascades_host). Contraction maps the
# rows back: carrier → the top-level RateLimitResp, member rows → its
# `cascade` list.

# behavior bits a cascade level inherits from its parent request: the
# kernel-visible flags plus the routing bits (whatever routing treatment
# the parent received applies to the whole group — levels must never split
# across the GLOBAL/local forks, or the host verdict fold would misgroup).
# DURATION_IS_GREGORIAN is deliberately NOT inherited: level durations are
# always milliseconds.
_CASCADE_INHERIT = int(
    Behavior.NO_BATCHING
    | Behavior.GLOBAL
    | Behavior.RESET_REMAINING
    | Behavior.MULTI_REGION
    | Behavior.DRAIN_OVER_LIMIT
)


def cascade_too_deep_error(cap: int) -> str:
    return f"Cascade levels list too large; max size is '{cap}'"


def expand_cascades(
    cols: RequestColumns, items, max_levels: int
) -> Tuple[RequestColumns, Optional[List[int]]]:
    """Expand cascade requests of a column batch into per-level rows.

    `items` are the pb RateLimitReq objects aligned with `cols` rows (None
    when the caller knows no cascades are present). Returns
    (expanded_cols, member_counts): member_counts[j] is the number of
    member rows inserted after original row j, or None when nothing
    expanded (the common case — zero-copy). A cascade deeper than
    `max_levels` total levels errors the CARRIER row (reference-style
    per-item isolation); invalid level keys error their member row, which
    surfaces in that level's sub-response."""
    if items is None or not any(len(it.cascade) for it in items):
        return cols, None
    n = cols.fp.shape[0]
    parts: List[RequestColumns] = []
    counts: List[int] = []
    for j in range(n):
        it = items[j]
        m = len(it.cascade)
        row = subset_columns(cols, np.array([j]))
        if m == 0 or row.err[0] != 0:
            # no levels, or the carrier itself failed validation: the
            # request errors whole — no level is evaluated (or consumed)
            parts.append(row)
            counts.append(0)
            continue
        if 1 + m > max_levels:
            # per-item isolation, like the reference's oversized-batch rule:
            # the carrier row becomes an error, no level is evaluated
            parts.append(row._replace(
                fp=np.zeros(1, dtype=np.int64),
                err=np.full(1, ERR_CASCADE_DEEP, dtype=np.int8),
            ))
            counts.append(0)
            continue
        inherit = int(row.behavior[0]) & _CASCADE_INHERIT
        fp = np.zeros(1 + m, dtype=np.int64)
        err = np.zeros(1 + m, dtype=np.int8)
        algo = np.zeros(1 + m, dtype=np.int32)
        behavior = np.zeros(1 + m, dtype=np.int32)
        hits = np.full(1 + m, row.hits[0], dtype=np.int64)
        limit = np.zeros(1 + m, dtype=np.int64)
        burst = np.zeros(1 + m, dtype=np.int64)
        duration = np.zeros(1 + m, dtype=np.int64)
        created_at = np.full(1 + m, row.created_at[0], dtype=np.int64)
        fp[0] = row.fp[0]
        err[0] = row.err[0]
        algo[0] = row.algo[0]
        behavior[0] = row.behavior[0]
        limit[0] = row.limit[0]
        burst[0] = row.burst[0]
        duration[0] = row.duration[0]
        clip = 1 << 62
        for k, lvl in enumerate(it.cascade, start=1):
            if lvl.unique_key == "":
                err[k] = ERR_EMPTY_KEY
            elif lvl.name == "":
                err[k] = ERR_EMPTY_NAME
            else:
                fp[k] = fingerprint(lvl.name, lvl.unique_key)
            algo[k] = lvl.algorithm
            behavior[k] = inherit | (min(k, 255) << 8)
            limit[k] = min(max(lvl.limit, -clip), clip)
            burst[k] = min(max(lvl.burst, -clip), clip)
            duration[k] = min(max(lvl.duration, -clip), clip)
        parts.append(RequestColumns(
            fp=fp, algo=algo, behavior=behavior, hits=hits, limit=limit,
            burst=burst, duration=duration, created_at=created_at, err=err,
        ))
        counts.append(m)
    return concat_columns(parts), counts


def pb_from_cascade_response_columns(
    rc: ResponseColumns, counts: List[int], max_levels: int,
    now_ms: Optional[int] = None,
) -> List["pb.RateLimitResp"]:
    """Contract an expanded response back to per-request RateLimitResp
    messages: the carrier row (already folded to the combined verdict)
    becomes the top-level response; its member rows become the `cascade`
    sub-responses in level order."""
    out: List[pb.RateLimitResp] = []
    off = 0
    for m in counts:
        top = _resp_at(rc, off, max_levels, now_ms)
        for k in range(1, m + 1):
            top.cascade.append(_resp_at(rc, off + k, max_levels, now_ms))
        out.append(top)
        off += 1 + m
    return out


def _resp_at(
    rc: ResponseColumns, i: int, max_levels: int,
    now_ms: Optional[int] = None,
) -> "pb.RateLimitResp":
    code = int(rc.err[i])
    msg = (
        cascade_too_deep_error(max_levels)
        if code == ERR_CASCADE_DEEP
        else ERROR_STRINGS[code]
    )
    st = int(rc.status[i])
    r = pb.RateLimitResp(
        status=st,
        limit=int(rc.limit[i]),
        remaining=int(rc.remaining[i]),
        reset_time=int(rc.reset_time[i]),
        error=msg,
    )
    if now_ms is not None and st == 1:
        # the carrier's folded reset is the latest denying level's reset —
        # exactly the retry-after bound (kernel2.fold_cascade_packed)
        r.metadata["retry_after_ms"] = str(
            max(0, int(rc.reset_time[i]) - int(now_ms))
        )
    return r


# ------------------------------------------------------------ state handoff


def transfer_chunk_pb(
    transfer_id: str,
    chunk: int,
    total_chunks: int,
    source_address: str,
    now_ms: int,
    fps: np.ndarray,
    points: np.ndarray,
    slots: np.ndarray,
    layout=None,
):
    """One TransferState chunk from extract arrays (little-endian memory
    images — no per-row message objects; see proto/handoff_pb2.py). The
    slot rows travel in the SENDER's slot layout, tagged by `layout` (code
    0 = full, the proto3 default — a pre-layout peer's chunks decode as
    full automatically)."""
    from gubernator_tpu.ops.layout import FULL
    from gubernator_tpu.proto import handoff_pb2 as handoff_pb

    layout = layout or FULL
    return handoff_pb.TransferStateReq(
        transfer_id=transfer_id,
        chunk=chunk,
        total_chunks=total_chunks,
        source_address=source_address,
        now_ms=now_ms,
        count=int(fps.shape[0]),
        fps=np.ascontiguousarray(fps, dtype=np.int64).tobytes(),
        points=np.ascontiguousarray(points, dtype=np.uint32).tobytes(),
        slots=np.ascontiguousarray(slots, dtype=np.int32).tobytes(),
        layout=layout.code,
    )


def transfer_chunk_arrays(req):
    """Decode a TransferStateReq back into (fps, points, slots, layout),
    validating the advertised count against every buffer length (a short
    buffer must fail loudly, not merge garbage rows). `slots` come back in
    the SENDER's layout (`layout`); the receiver converts through the
    canonical full row (engine.merge_rows(layout=...))."""
    from gubernator_tpu.ops.layout import layout_by_code

    layout = layout_by_code(int(req.layout))
    F = layout.F
    n = int(req.count)
    fps = np.frombuffer(req.fps, dtype=np.int64)
    points = np.frombuffer(req.points, dtype=np.uint32)
    slots = np.frombuffer(req.slots, dtype=np.int32)
    if fps.shape[0] != n or points.shape[0] != n or slots.shape[0] != n * F:
        raise ValueError(
            f"transfer chunk length mismatch: count={n} fps={fps.shape[0]} "
            f"points={points.shape[0]} slots={slots.shape[0]} "
            f"(layout {layout.name})"
        )
    return fps, points, slots.reshape(n, F), layout


# ----------------------------------------------------------- native ingress


def wire_batch_from_wire(data: bytes):
    """Native parse of GetRateLimitsReq wire bytes (gubernator_tpu.native):
    → (WireBatch, ring_points uint32, spans (n,2) int64, traceparent) or
    None when the extension is unavailable OR any item carries a cascade —
    cascade requests need their levels expanded from the full pb message,
    so such batches take the pb path (Daemon._route) end to end.
    ring_points are fnv1a_32 of each item's hash key (the ring lookup hash)
    and spans are each item's byte range in `data` for lazy pb
    materialization — only items that must travel as messages (forwards,
    GLOBAL queue entries) ever become Python objects. The WireBatch
    additionally carries the parser's pre-packed compact-wire lanes — the
    "parse once, stage once" ingress image."""
    from gubernator_tpu import native

    m = native.load()
    if m is None:
        return None
    (
        n, fp, algo, beh, hits, lim, burst, dur, ca, err, ring, span,
        traceparent, lanes, enc, casc,
    ) = m.parse_get_rate_limits(data)
    if n and np.frombuffer(casc, np.int8).any():
        return None  # cascade batch → pb path (level expansion needs items)
    # np.frombuffer over bytes is read-only; routing mutates behavior/err
    cols = RequestColumns(
        fp=np.frombuffer(fp, np.int64),
        algo=np.frombuffer(algo, np.int32),
        behavior=np.frombuffer(beh, np.int32).copy(),
        hits=np.frombuffer(hits, np.int64),
        limit=np.frombuffer(lim, np.int64),
        burst=np.frombuffer(burst, np.int64),
        duration=np.frombuffer(dur, np.int64),
        created_at=np.frombuffer(ca, np.int64),
        err=np.frombuffer(err, np.int8).copy(),
    )
    wb = WireBatch(
        cols=cols,
        lanes=np.frombuffer(lanes, np.int32).reshape(5, n),
        encodable=np.frombuffer(enc, np.int8).astype(bool),
        nbytes=len(data),
    )
    return (
        wb,
        np.frombuffer(ring, np.uint32),
        np.frombuffer(span, np.int64).reshape(-1, 2),
        traceparent,  # first propagated trace context in the batch, or None
    )


def columns_from_wire(data: bytes):
    """Column-only view of wire_batch_from_wire (kept for callers that
    don't ride the fused lane path)."""
    got = wire_batch_from_wire(data)
    if got is None:
        return None
    wb, ring, spans, traceparent = got
    return wb.cols, ring, spans, traceparent


def item_from_span(data: bytes, span) -> "pb.RateLimitReq":
    """Materialize one request item from its wire span (lazy pb path)."""
    s, ln = int(span[0]), int(span[1])
    return pb.RateLimitReq.FromString(data[s : s + ln])


def encode_response_columns(
    status: np.ndarray,
    limit: np.ndarray,
    remaining: np.ndarray,
    reset_time: np.ndarray,
    errors: dict,
    now_ms: Optional[int] = None,
) -> bytes:
    """Native GetRateLimitsResp encode from response columns; `errors` is a
    sparse {row: message} dict. Arrays cross the boundary via the buffer
    protocol — contiguous int64 columns encode ZERO-COPY (no .tobytes()
    staging), and the C assembly loop drops the GIL so responder workers
    encode in parallel. With `now_ms`, denied rows carry
    metadata["retry_after_ms"] (the exact conforming-instant delta for
    GCRA — see ops/math.py)."""
    from gubernator_tpu import native

    m = native.load()
    assert m is not None, "native module required (guarded by columns_from_wire)"
    return m.encode_responses(
        np.ascontiguousarray(status, dtype=np.int64),
        np.ascontiguousarray(limit, dtype=np.int64),
        np.ascontiguousarray(remaining, dtype=np.int64),
        np.ascontiguousarray(reset_time, dtype=np.int64),
        errors,
        -1 if now_ms is None else int(now_ms),
    )


# ----------------------------------------- inter-slice GLOBAL sync codec
# The PR-5 compact lane layout applied to the cross-daemon hit sync
# (docs/architecture.md "Pod-scale topology"): numeric config rides ONE
# 5-lane int32 image (ops/wire.pack_wire_rows — 20 B/entry instead of a
# nested RateLimitReq message), full-precision accumulated hits ride an
# int64 sidecar (inter-slice accumulations overflow the 18-bit lane
# budget), and the key strings the owner needs for its broadcast queue
# travel as one length-prefixed blob. Non-representable batches return
# None and the caller falls back to the classic GetPeerRateLimits proto
# path — identical semantics, more bytes (the PR-5 fallback contract).

_SYNC_WIRE_BEHAVIOR = int(
    Behavior.NO_BATCHING | Behavior.GLOBAL | Behavior.RESET_REMAINING
    | Behavior.DRAIN_OVER_LIMIT
) | (types.PRIORITY_MASK << types.PRIORITY_SHIFT)


def sync_wire_pb(
    pairs: Sequence[Tuple[str, "pb.RateLimitReq"]], source: str
) -> Optional["globalsync_pb.SyncGlobalsWireReq"]:
    """Pack one owner's pending-hit batch into a SyncGlobalsWireReq, or
    None when any entry cannot ride the compact layout exactly (Gregorian /
    MULTI_REGION behaviors must not be dropped, created_at must be present
    and within the ±511 ms delta budget of the batch base, tracing
    metadata has no compact lane). The receive half is sync_wire_items."""
    from gubernator_tpu.ops import wire as wire_mod

    n = len(pairs)
    if n == 0:
        return None
    items = [it for _k, it in pairs]
    base = None
    names: List[bytes] = []
    keys: List[bytes] = []
    for it in items:
        if (
            not it.HasField("created_at")
            or it.behavior & ~_SYNC_WIRE_BEHAVIOR
            or not (0 <= it.algorithm <= wire_mod._MAX_ALGO)
            or it.hits < 0  # lease releases keep the proto fallback
            or not (0 <= it.duration <= wire_mod._DUR_MASK)
            or not (0 <= it.limit <= wire_mod.I32_MAX)
            or it.metadata  # trace propagation has no compact lane
            or len(it.cascade)  # cascade levels need the full message
            or not (
                it.burst == 0
                or (it.algorithm in (1, 2) and it.burst == it.limit)
            )
            or it.name == ""
            or it.unique_key == ""
        ):
            return None
        if base is None:
            base = it.created_at
        if not (-wire_mod.DELTA_BIAS <= it.created_at - base
                < wire_mod.DELTA_BIAS):
            return None
        nb, kb = it.name.encode(), it.unique_key.encode()
        if len(nb) >= 1 << 16 or len(kb) >= 1 << 16:
            return None
        names.append(nb)
        keys.append(kb)
    lanes = np.zeros((wire_mod.WIRE_LANES, n), dtype=np.int32)
    hits64 = np.zeros(n, dtype=np.int64)
    for i, it in enumerate(items):
        fp = fingerprint(it.name, it.unique_key)
        lanes[0, i] = np.int64(fp).astype(np.int32)
        lanes[1, i] = np.int64(fp >> 32).astype(np.int32)
        lanes[2, i] = it.limit
        lanes[3, i] = np.int64(
            (it.duration & wire_mod._DUR_MASK)
            | (int(it.algorithm) << wire_mod.DUR_BITS)
        ).astype(np.int32)
        reset = 1 if it.behavior & int(Behavior.RESET_REMAINING) else 0
        drain = 1 if it.behavior & int(Behavior.DRAIN_OVER_LIMIT) else 0
        prio = types.priority_tier(it.behavior)
        delta = (it.created_at - base + wire_mod.DELTA_BIAS)
        # lane hits stay 0: hits64 is authoritative on this codec
        lanes[4, i] = np.int64(
            ((delta & wire_mod._DELTA_MASK) << wire_mod.HITS_BITS)
            | (prio << wire_mod.PRIO_SHIFT)
            | (reset << 30) | (drain << 31)
        ).astype(np.int32)
        hits64[i] = it.hits
    from gubernator_tpu.proto import globalsync_pb2 as globalsync_pb

    return globalsync_pb.SyncGlobalsWireReq(
        source=source,
        count=n,
        base=base,
        lanes=lanes.tobytes(),
        hits=hits64.tobytes(),
        name_lens=np.array([len(b) for b in names], dtype="<u2").tobytes(),
        key_lens=np.array([len(b) for b in keys], dtype="<u2").tobytes(),
        strings=b"".join(
            b for pair in zip(names, keys) for b in pair
        ),
    )


# ----------------------------------------- cross-region replication codec
# The SyncGlobalsWire shape applied to the region plane (ops/reconcile.py
# receive path): per-key hit DELTAS + config lanes + the sender's own
# stored slot rows in its slot layout. Items that cannot ride the compact
# layout exactly fall back PER ITEM to the classic GetPeerRateLimits proto
# path (legacy DRAIN semantics — the pre-upgrade behavior), so one exotic
# item never forces a whole batch off the merge path.

_REGION_WIRE_BEHAVIOR = int(
    Behavior.NO_BATCHING | Behavior.MULTI_REGION | Behavior.DRAIN_OVER_LIMIT
) | (types.PRIORITY_MASK << types.PRIORITY_SHIFT)


def region_wire_item_ok(it: "pb.RateLimitReq") -> bool:
    """Static (base-independent) encodability of one replicated item.
    RESET_REMAINING is deliberately NOT encodable: a reset cannot travel
    through a min-remaining merge (min can never raise remaining), so
    resets ride the classic serving-path fallback, which can."""
    from gubernator_tpu.ops import wire as wire_mod

    return bool(
        it.HasField("created_at")
        and not (it.behavior & ~_REGION_WIRE_BEHAVIOR)
        and 0 <= it.algorithm <= wire_mod._MAX_ALGO
        and it.hits >= 0  # lease releases keep the proto fallback
        and 0 <= it.duration <= wire_mod._DUR_MASK
        and 0 <= it.limit <= wire_mod.I32_MAX
        and not it.metadata
        and not len(it.cascade)
        and (
            it.burst == 0
            or (it.algorithm in (1, 2) and it.burst == it.limit)
        )
        and it.name != ""
        and it.unique_key != ""
        and len(it.name.encode()) < (1 << 16)
        and len(it.unique_key.encode()) < (1 << 16)
    )


def split_region_encodable(pairs):
    """Partition one region-bound batch into (encodable, fallback) pairs.
    The lane base is the first encodable item's created_at; items outside
    its ±511 ms delta budget spill to the fallback too."""
    from gubernator_tpu.ops import wire as wire_mod

    enc, fb = [], []
    base = None
    for key, it in pairs:
        if not region_wire_item_ok(it):
            fb.append((key, it))
            continue
        if base is None:
            base = it.created_at
        if not (
            -wire_mod.DELTA_BIAS
            <= it.created_at - base
            < wire_mod.DELTA_BIAS
        ):
            fb.append((key, it))
            continue
        enc.append((key, it))
    return enc, fb


def sync_regions_pb(
    pairs: Sequence[Tuple[str, "pb.RateLimitReq"]],
    source: str,
    region: str,
    slots: Optional[np.ndarray] = None,
    layout=None,
    detail_rows: Optional[np.ndarray] = None,
    cums: Optional[np.ndarray] = None,
):
    """Pack one region-bound delta batch (already split_region_encodable-
    filtered) into a SyncRegionsWireReq. `slots` are the sender's stored
    rows for the batch keys in the sender's own slot layout ((n, layout.F)
    i32, zero rows for missing keys; None ships no rows).

    `detail_rows` (bool (n,), default all-True) marks the rows that carry
    the BOOTSTRAP detail — key strings and the sender's stored slot row.
    A key's FIRST replication to a region ships detailed; steady-state
    deltas for already-shipped keys are pure 32 B lane+hits entries
    (zero-length strings, zero slot row) — the receiver merges them by
    fingerprint against its own stored state.

    `cums` (int64 (n,), optional) are the sender's PER-KEY CUMULATIVE hit
    counters toward this region (total ever queued, including this batch's
    deltas) — the receiver's per-source dedup ledger uses them to skip
    re-shipped batches after a lost ack EXACTLY instead of under-granting
    (ops/reconcile.dedup_source_deltas). Absent = pre-dedup sender; the
    receiver then applies deltas verbatim (the legacy at-least-once rule).
    The receive half is sync_regions_arrays → apply_region_sync."""
    from gubernator_tpu.ops import wire as wire_mod
    from gubernator_tpu.ops.layout import FULL
    from gubernator_tpu.proto import regionsync_pb2 as regionsync_pb

    n = len(pairs)
    assert n > 0, "empty region batch"
    layout = layout or FULL
    items = [it for _k, it in pairs]
    base = items[0].created_at
    if detail_rows is None:
        detail_rows = np.ones(n, dtype=bool)
    names = [
        it.name.encode() if detail_rows[i] else b""
        for i, it in enumerate(items)
    ]
    keys = [
        it.unique_key.encode() if detail_rows[i] else b""
        for i, it in enumerate(items)
    ]
    lanes = np.zeros((wire_mod.WIRE_LANES, n), dtype=np.int32)
    hits64 = np.zeros(n, dtype=np.int64)
    for i, it in enumerate(items):
        fp = fingerprint(it.name, it.unique_key)
        lanes[0, i] = np.int64(fp).astype(np.int32)
        lanes[1, i] = np.int64(fp >> 32).astype(np.int32)
        lanes[2, i] = it.limit
        lanes[3, i] = np.int64(
            (it.duration & wire_mod._DUR_MASK)
            | (int(it.algorithm) << wire_mod.DUR_BITS)
        ).astype(np.int32)
        drain = 1 if it.behavior & int(Behavior.DRAIN_OVER_LIMIT) else 0
        prio = types.priority_tier(it.behavior)
        delta = it.created_at - base + wire_mod.DELTA_BIAS
        # lane hits stay 0: the hits64 sidecar is authoritative
        lanes[4, i] = np.int64(
            ((delta & wire_mod._DELTA_MASK) << wire_mod.HITS_BITS)
            | (prio << wire_mod.PRIO_SHIFT)
            | (drain << 31)
        ).astype(np.int32)
        hits64[i] = it.hits
    slot_bytes = b""
    if slots is not None and slots.size and detail_rows.any():
        assert slots.shape == (n, layout.F), "slots misaligned with pairs"
        slots = np.where(detail_rows[:, None], slots, 0)
        slot_bytes = np.ascontiguousarray(slots, dtype=np.int32).tobytes()
    cum_bytes = b""
    if cums is not None:
        assert len(cums) == n, "cums misaligned with pairs"
        cum_bytes = np.ascontiguousarray(cums, dtype=np.int64).tobytes()
    return regionsync_pb.SyncRegionsWireReq(
        source=source,
        region=region,
        count=n,
        base=base,
        lanes=lanes.tobytes(),
        hits=hits64.tobytes(),
        name_lens=np.array([len(b) for b in names], dtype="<u2").tobytes(),
        key_lens=np.array([len(b) for b in keys], dtype="<u2").tobytes(),
        strings=b"".join(b for pair in zip(names, keys) for b in pair),
        slots=slot_bytes,
        layout=layout.code,
        cums=cum_bytes,
    )


def sync_regions_arrays(req):
    """Decode a SyncRegionsWireReq into the reconcile inputs:
    (fps i64, deltas i64, cfg column dict, hash_keys, slots, layout, cums).
    `slots` come back in the SENDER's layout (None when the sender shipped
    no rows); `cums` are the per-key cumulative counters (None when the
    sender predates the dedup plane); every buffer length is validated — a
    short buffer must fail loudly, not merge garbage rows."""
    from gubernator_tpu.ops.layout import layout_by_code
    from gubernator_tpu.ops.wire import WIRE_LANES, decode_wire_host

    n = int(req.count)
    lanes = np.frombuffer(req.lanes, dtype="<i4").reshape(WIRE_LANES, n)
    cfg = decode_wire_host(lanes, int(req.base))
    deltas = np.frombuffer(req.hits, dtype="<i8")
    name_lens = np.frombuffer(req.name_lens, dtype="<u2")
    key_lens = np.frombuffer(req.key_lens, dtype="<u2")
    if not (
        deltas.shape[0] == n and name_lens.shape[0] == n
        and key_lens.shape[0] == n
        and int(name_lens.sum()) + int(key_lens.sum()) == len(req.strings)
    ):
        raise ValueError("SyncRegionsWireReq: inconsistent buffer lengths")
    layout = layout_by_code(int(req.layout))
    slots = None
    if req.slots:
        slots = np.frombuffer(req.slots, dtype="<i4")
        if slots.shape[0] != n * layout.F:
            raise ValueError(
                f"SyncRegionsWireReq: slots buffer holds {slots.shape[0]} "
                f"lanes, want {n}×{layout.F} (layout {layout.name})"
            )
        slots = slots.reshape(n, layout.F)
    cums = None
    if req.cums:
        cums = np.frombuffer(req.cums, dtype="<i8")
        if cums.shape[0] != n:
            raise ValueError(
                f"SyncRegionsWireReq: cums buffer holds {cums.shape[0]} "
                f"entries, want {n}"
            )
        cums = cums.astype(np.int64)
    hash_keys = []
    off = 0
    blob = req.strings
    for i in range(n):
        name = blob[off : off + int(name_lens[i])].decode()
        off += int(name_lens[i])
        key = blob[off : off + int(key_lens[i])].decode()
        off += int(key_lens[i])
        # steady-state rows travel string-less (fingerprint-only merge);
        # "" marks them so the receiver skips ownership recording
        hash_keys.append(name + "_" + key if (name or key) else "")
    return (
        np.asarray(cfg["fp"], dtype=np.int64),
        deltas.astype(np.int64),
        cfg,
        hash_keys,
        slots,
        layout,
        cums,
    )


def sync_wire_items(
    req: "globalsync_pb.SyncGlobalsWireReq",
) -> List["pb.RateLimitReq"]:
    """Decode a SyncGlobalsWireReq back to RateLimitReq items (owner side).
    GLOBAL is re-set on every entry — this codec only ever carries GLOBAL
    hit syncs — so the rebuilt items drive the exact
    _get_peer_rate_limits path the proto fallback drives."""
    from gubernator_tpu.ops.wire import WIRE_LANES, decode_wire_host

    n = int(req.count)
    lanes = np.frombuffer(req.lanes, dtype="<i4").reshape(WIRE_LANES, n)
    cols = decode_wire_host(lanes, int(req.base))
    hits = np.frombuffer(req.hits, dtype="<i8")
    name_lens = np.frombuffer(req.name_lens, dtype="<u2")
    key_lens = np.frombuffer(req.key_lens, dtype="<u2")
    if not (
        hits.shape[0] == n and name_lens.shape[0] == n
        and key_lens.shape[0] == n
        and int(name_lens.sum()) + int(key_lens.sum()) == len(req.strings)
    ):
        raise ValueError("SyncGlobalsWireReq: inconsistent buffer lengths")
    items: List[pb.RateLimitReq] = []
    off = 0
    blob = req.strings
    for i in range(n):
        name = blob[off : off + int(name_lens[i])].decode()
        off += int(name_lens[i])
        key = blob[off : off + int(key_lens[i])].decode()
        off += int(key_lens[i])
        items.append(
            pb.RateLimitReq(
                name=name,
                unique_key=key,
                hits=int(hits[i]),
                limit=int(cols["limit"][i]),
                duration=int(cols["duration"][i]),
                algorithm=int(cols["algo"][i]),
                behavior=int(cols["behavior"][i]) | int(Behavior.GLOBAL),
                created_at=int(cols["created_at"][i]),
            )
        )
    return items
