"""LocalLimiter — admit at memory speed from a leased slice of a limit.

The client half of the edge quota-lease plane (docs/leases.md;
service/lease_manager.py is the server half). One LocalLimiter guards one
(name, unique_key) limit:

* ``allow(hits)`` is the SYNCHRONOUS hot path: a lock-guarded counter
  decrement against the leased budget — no RPC, no event loop, safe from
  any thread. This is what turns ~10⁵ checks/s of per-RPC fan-in into
  ~10⁷ local admissions/s (the bench.py ``leases`` phase records it).
* A background task renews ahead of expiry with ADAPTIVE grant sizing:
  exhaustion before renewal doubles the next grant; a mostly-unused grant
  (returned-unused fraction above ``waste_fraction``) halves it — so a hot
  key converges to few, fat grants and an idle key gives its tokens back.
* ``check(hits)`` is the graceful-degradation path: local first, then a
  per-check GetRateLimits RPC when the lease lane is exhausted — honoring
  the server's ``retry_after_ms`` (denials short-circuit locally until the
  conforming instant, so a denied edge never hammers the daemon).

Honesty bounds (asserted by tests/test_edge_lease.py and the CI
``lease_smoke``): local admissions never exceed tokens granted; a limiter
stops admitting the instant its lease expires (an unreachable daemon
degrades, never over-admits); across a daemon crash + restart, total
admissions ≤ limit + outstanding-at-crash.
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Optional, Union

from gubernator_tpu.client import V1Client, response_retry_after_ms
from gubernator_tpu.proto import gubernator_pb2 as pb

log = logging.getLogger("gubernator_tpu.edge")


def _now_ms() -> int:
    return int(time.time() * 1000)


@dataclass
class LimiterStats:
    """Lifetime counters — the edge-side mirror of the daemon's lease
    metric families."""

    local_admits: int = 0
    local_denies: int = 0  # no budget AND no RPC fallback taken
    rpc_checks: int = 0
    rpc_admits: int = 0
    rpc_denies: int = 0
    backoff_denies: int = 0  # denied locally inside a retry_after window
    grants: int = 0
    tokens_granted: int = 0
    tokens_returned: int = 0
    renew_errors: int = 0
    exhaustions: int = 0
    shrinks: int = 0  # push-shrink hints honored (docs/robustness.md)
    grant_sizes: list = field(default_factory=list)


class LocalLimiter:
    """Client-side admission against one leased limit. Use::

        lim = LocalLimiter("host:port", "requests", "tenant-1",
                           limit=10_000, duration=60_000)
        await lim.start()
        ...
        if lim.allow():          # sync hot path (any thread)
            handle_request()
        ...
        ok, retry_ms = await lim.check()   # local-then-RPC path
        ...
        await lim.close()        # returns unused tokens

    ``behavior`` may carry GLOBAL / MULTI_REGION — leased consumption then
    replicates exactly like ordinary hits (a grant IS hits to the daemon).
    """

    def __init__(
        self,
        target: Union[str, V1Client],
        name: str,
        unique_key: str,
        limit: int,
        duration: int,
        algorithm: int = 0,
        behavior: int = 0,
        burst: int = 0,
        *,
        ttl_ms: int = 2_000,
        initial_grant: int = 0,  # 0 = max(min_grant, limit // 16)
        min_grant: int = 1,
        max_grant: int = 0,  # 0 = no client-side ceiling (server caps)
        renew_fraction: float = 0.6,  # renew at this fraction of the TTL
        waste_fraction: float = 0.5,  # unused/grant above this shrinks
        timeout_s: float = 5.0,
    ):
        if limit <= 0 or duration <= 0:
            raise ValueError("limit and duration must be positive")
        if isinstance(target, V1Client):
            self._client = target
            self._own_client = False
        else:
            self._client = V1Client(target, timeout_s=timeout_s)
            self._own_client = True
        self.name = name
        self.unique_key = unique_key
        self.limit = int(limit)
        self.duration = int(duration)
        self.algorithm = int(algorithm)
        self.behavior = int(behavior)
        self.burst = int(burst)
        self.ttl_ms = int(ttl_ms)
        self.min_grant = max(1, int(min_grant))
        self.max_grant = int(max_grant) or self.limit
        self.renew_fraction = renew_fraction
        self.waste_fraction = waste_fraction
        self.timeout_s = timeout_s
        self._grant = int(initial_grant) or max(
            self.min_grant, self.limit // 16
        )
        self._grant = min(self._grant, self.max_grant)
        self.stats = LimiterStats()
        # the admission-hot state, guarded by a plain lock: allow() must be
        # callable from any thread while the renewal task runs on the loop
        self._lock = threading.Lock()
        self._budget = 0
        self._expires_at = 0  # epoch ms; 0 = no live lease
        self._exhausted = False  # budget hit 0 since the last renewal
        self._lease_id = ""
        self._backoff_until = 0  # epoch ms gate on the RPC fallback
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._wake: Optional[asyncio.Event] = None
        self._renew_task: Optional[asyncio.Task] = None
        self._closed = False

    # ----------------------------------------------------------- lifecycle
    async def start(self) -> "LocalLimiter":
        """Acquire the first grant and start the background renewal task.
        A daemon that is unreachable or out of lease budget does NOT fail
        start(): the limiter comes up budget-less and serves through the
        per-check fallback until a later renewal succeeds."""
        self._loop = asyncio.get_running_loop()
        self._wake = asyncio.Event()
        try:
            await self._renew_once()
        except Exception as exc:
            self.stats.renew_errors += 1
            log.warning("initial lease acquire failed: %s", exc)
        self._renew_task = self._loop.create_task(
            self._renew_loop(), name=f"lease-renew:{self.name}"
        )
        return self

    async def close(self) -> None:
        """Stop renewing and return every unused token to the limit."""
        self._closed = True
        if self._renew_task is not None:
            self._renew_task.cancel()
            try:
                await self._renew_task
            except asyncio.CancelledError:
                pass
        with self._lock:
            give, self._budget = self._budget, 0
            lease_id, self._lease_id = self._lease_id, ""
            self._expires_at = 0
        if give > 0 and lease_id:
            try:
                await self._client.lease_quota(
                    self._req(tokens=0, return_tokens=give, lease_id=lease_id),
                    timeout_s=self.timeout_s,
                )
                self.stats.tokens_returned += give
            except Exception as exc:
                log.warning("final token return failed: %s", exc)
        if self._own_client:
            await self._client.close()

    # ------------------------------------------------------------ hot path
    def allow(self, hits: int = 1) -> bool:
        """Admit `hits` from the leased budget — the memory-speed path.
        Returns False when the budget is exhausted OR the lease has
        expired (never over-admits on a dead lease); exhaustion wakes the
        renewal task so the next grant is already in flight while callers
        fall back to check()."""
        if hits <= 0:
            return True
        now = _now_ms()
        with self._lock:
            if self._budget >= hits and now < self._expires_at:
                self._budget -= hits
                self.stats.local_admits += hits
                if self._budget == 0:
                    self._exhausted = True
                    self._signal()
                return True
            self._exhausted = True
            self.stats.local_denies += 1
        self._signal()
        return False

    @property
    def budget(self) -> int:
        with self._lock:
            return self._budget

    @property
    def lease_expires_at(self) -> int:
        return self._expires_at

    def _signal(self) -> None:
        """Wake the renewal task from any thread (lock may be held)."""
        loop, wake = self._loop, self._wake
        if loop is None or wake is None or loop.is_closed():
            return
        try:
            loop.call_soon_threadsafe(wake.set)
        except RuntimeError:
            pass  # loop shut down mid-signal

    # -------------------------------------------------- degradation path
    async def check(self, hits: int = 1) -> "tuple[bool, int]":
        """Local-first admission with per-check RPC fallback. Returns
        (admitted, retry_after_ms). Honors the server's retry_after: a
        denial short-circuits further RPCs locally until its conforming
        instant, so a saturated edge backs off instead of turning the
        fan-in reduction back into RPC load."""
        if self.allow(hits):
            return True, 0
        now = _now_ms()
        if now < self._backoff_until:
            self.stats.backoff_denies += 1
            return False, self._backoff_until - now
        self.stats.rpc_checks += 1
        try:
            resp = (
                await self._client.get_rate_limits([
                    pb.RateLimitReq(
                        name=self.name,
                        unique_key=self.unique_key,
                        hits=hits,
                        limit=self.limit,
                        duration=self.duration,
                        algorithm=self.algorithm,
                        behavior=self.behavior,
                        burst=self.burst,
                    )
                ], timeout_s=self.timeout_s)
            ).responses[0]
        except Exception:
            # unreachable daemon: fail closed (the lease plane already
            # bounds what an edge may admit while partitioned)
            self.stats.rpc_denies += 1
            return False, 0
        if resp.status == pb.UNDER_LIMIT and not resp.error:
            self.stats.rpc_admits += 1
            return True, 0
        retry = response_retry_after_ms(resp)
        if retry > 0:
            self._backoff_until = max(self._backoff_until, now + retry)
        self.stats.rpc_denies += 1
        return False, retry

    # ------------------------------------------------------------- renewal
    def _req(self, tokens: int, return_tokens: int, lease_id: str):
        return pb.LeaseQuotaReq(
            name=self.name,
            unique_key=self.unique_key,
            tokens=tokens,
            limit=self.limit,
            duration=self.duration,
            algorithm=self.algorithm,
            behavior=self.behavior,
            burst=self.burst,
            ttl_ms=self.ttl_ms,
            lease_id=lease_id,
            return_tokens=return_tokens,
        )

    def _next_deadline_s(self) -> float:
        """Seconds until the renewal should fire: renew_fraction through
        the TTL, or soon-ish when no lease is live (retry cadence)."""
        if self._expires_at <= 0:
            return max(self.ttl_ms / 1e3 / 4, 0.05)
        lead = self._expires_at - self.ttl_ms * (1.0 - self.renew_fraction)
        return max((lead - _now_ms()) / 1e3, 0.01)

    async def _renew_loop(self) -> None:
        while not self._closed:
            try:
                await asyncio.wait_for(
                    self._wake.wait(), timeout=self._next_deadline_s()
                )
            except asyncio.TimeoutError:
                pass
            self._wake.clear()
            if self._closed:
                return
            try:
                await self._renew_once()
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                # daemon unreachable: keep serving the remaining local
                # budget until lease expiry (bounded by outstanding), then
                # allow() fails closed; the loop keeps retrying
                self.stats.renew_errors += 1
                log.debug("lease renewal failed: %s", exc)
                await asyncio.sleep(
                    min(0.25, self.ttl_ms / 1e3 / 4)
                )

    async def _renew_once(self) -> None:
        """One renewal round: adapt the grant size, return excess budget,
        acquire the next slice. The budget decrement for returned tokens
        happens BEFORE the RPC (restored on failure), so a token can never
        be both returned and locally admitted."""
        with self._lock:
            b = self._budget
            exhausted, self._exhausted = self._exhausted, False
        if exhausted:
            self._grant = min(self._grant * 2, self.max_grant)
        elif b >= self._grant * self.waste_fraction and self.stats.grants:
            self._grant = max(self.min_grant, self._grant // 2)
        give = 0
        if b > self._grant:
            with self._lock:
                give = max(0, self._budget - self._grant)
                self._budget -= give
        ask = max(self.min_grant, self._grant - (b - give))
        try:
            resp = await self._client.lease_quota(
                self._req(
                    tokens=ask, return_tokens=give, lease_id=self._lease_id
                ),
                timeout_s=self.timeout_s,
            )
        except Exception:
            if give:
                with self._lock:
                    self._budget += give  # nothing was returned
            raise
        if resp.error:
            if give:
                with self._lock:
                    self._budget += give
            raise RuntimeError(f"lease denied: {resp.error}")
        if give:
            self.stats.tokens_returned += give
        granted = int(resp.granted)
        with self._lock:
            if granted > 0:
                self._budget += granted
                self._lease_id = resp.lease_id
                self._expires_at = int(resp.expires_at)
            elif resp.lease_id and resp.lease_id == self._lease_id:
                # returns against a live lease still refresh its deadline
                self._expires_at = max(
                    self._expires_at, int(resp.expires_at)
                )
        # push-shrink hint (LeaseQuotaResp.shrink_to): the daemon is asking
        # this edge to run on a smaller slice — clamp the adaptive grant
        # target BEFORE the next admission burst, so the following renewal
        # round returns the excess (the b > _grant giveback above) instead
        # of holding pressured quota until the TTL
        shrink = int(getattr(resp, "shrink_to", 0))
        if shrink > 0 and shrink < self._grant:
            self._grant = max(self.min_grant, shrink)
            self.stats.shrinks += 1
            self._wake.set()  # return the excess promptly, not at the TTL
        if granted > 0:
            self.stats.grants += 1
            self.stats.tokens_granted += granted
            self.stats.grant_sizes.append(granted)
        else:
            # lease lane exhausted: honor the hint before asking again
            retry = int(resp.retry_after_ms)
            if retry > 0:
                self._backoff_until = max(
                    self._backoff_until, _now_ms() + retry
                )
