"""Edge admission library — client-side quota leases (docs/leases.md).

The first subsystem that lives OUTSIDE the daemon: a ``LocalLimiter``
acquires a bounded slice of a limit over the V1 ``LeaseQuota`` RPC and then
admits at memory speed from its local budget — renewing in the background
ahead of expiry with adaptive grant sizing, returning unused tokens early,
and degrading to per-check RPCs (honoring ``retry_after_ms``) when the
lease lane is exhausted or the daemon is unreachable.
"""

from gubernator_tpu.edge.local_limiter import LocalLimiter, LimiterStats

__all__ = ["LocalLimiter", "LimiterStats"]
