"""Sanity guards between benchmark timing loops and the published record.

Round 4's driver-recorded benchmark published two numbers that were not
engineering: a headline 24x below the in-session measurement because every
timed dispatch absorbed a degraded tunnel round trip, and a physically
impossible 2.5e16 decisions/s from a dt that two noisy host timings drove
to 0.000 s (min-of-3 on jittered clocks can make t_long <= t_short). Both
failure modes are properties of the *timing arithmetic*, so the defense
lives here as pure functions the suite can pin under simulated jitter
(tests/test_bench_guard.py) — the bench publishes a rate only when these
accept it, and publishes the refusal reason otherwise.

The reference's CI has the same shape of defense at a coarser grain: it
gates benchmark results relative to master with a +-200% band
(reference .github/workflows/on-pull-request.yml:47-80) rather than
trusting any single run.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

# A v5e chip cannot exceed ~2e9 decisions/s: each decision reads a 512 B
# bucket row (gather) and the sweep write streams the whole table per
# dispatch, so >=1 GiB tables bound throughput to ~1e8/s at headline batch
# and even a degenerate tiny-table case is HBM-bound orders of magnitude
# below this ceiling. Anything above it is a timing artifact, never a chip.
MAX_SANE_RATE = 2e9


class WorkMismatchError(Exception):
    """A timed window's device counters did not reconcile with the decisions
    its rate would claim (check_work refusal). Deliberately NOT a
    RuntimeError: jaxlib's XlaRuntimeError subclasses RuntimeError, and a
    catch broad enough to take both would mislabel infrastructure failures
    as guard refusals (and then keep using a table poisoned by the failed
    donated computation)."""


class Slope(NamedTuple):
    rate: Optional[float]  # decisions/s, None if rejected
    per_iter_ms: Optional[float]
    reason: Optional[str]  # rejection reason, None if accepted


def slope(
    t_short: float,
    t_long: float,
    n_short: int,
    n_long: int,
    rows_per_iter: int,
    *,
    min_dt: float = 0.050,
    min_ratio: float = 1.4,
    max_rate: float = MAX_SANE_RATE,
) -> Slope:
    """Validate a two-point slope timing and derive a rate.

    t_short/t_long: wall time of a run of n_short/n_long iterations (each
    run is ONE device launch when used with ops/loop.decide_loop, so the
    per-run constant — launch + fetch RTT — cancels in the difference).

    Rejections:
      * dt under `min_dt` — the difference is smaller than host clock +
        RTT jitter can resolve; round 4's config5 published 2.5e16/s from
        exactly this (dt floored at 1e-9 instead of rejected).
      * t_long < min_ratio * t_short — the run time is dominated by the
        per-run constant, not the iterations: the slope would measure
        transport weather, not compute. The caller's remedy is a longer
        window (bigger n_long), not a retry of the same one.
      * rate > max_rate — physically impossible for this hardware
        regardless of how plausible the arithmetic looked.
    """
    if n_long <= n_short:
        return Slope(None, None, f"n_long {n_long} <= n_short {n_short}")
    dt = t_long - t_short
    if dt < min_dt:
        return Slope(
            None, None,
            f"dt {dt*1e3:.1f}ms under {min_dt*1e3:.0f}ms floor "
            "(jitter-resolvable only)",
        )
    if t_long < min_ratio * t_short:
        return Slope(
            None, None,
            f"t_long {t_long:.3f}s < {min_ratio}x t_short {t_short:.3f}s: "
            "per-run constant dominates; grow the window",
        )
    rate = (n_long - n_short) * rows_per_iter / dt
    if rate > max_rate:
        return Slope(
            None, None,
            f"rate {rate:.3e}/s exceeds physical ceiling {max_rate:.0e}/s",
        )
    return Slope(rate, dt / (n_long - n_short) * 1e3, None)


def check_work(
    counted: int, expected: int, *, label: str = "decisions"
) -> Optional[str]:
    """Proof-of-work cross-check: the device-side counters accumulated by
    the timed loop must equal the decisions the window claims to have made.
    Returns a refusal reason, or None if the work is accounted for."""
    if counted != expected:
        return (
            f"{label} counted {counted} != expected {expected}: "
            "timed window did not do the work its rate claims"
        )
    return None


# Transfer-bandwidth plausibility band for the transport-dominance gate.
# Upper bound: no host<->device link this code runs over beats PCIe gen5
# x16-class speed; a timed window whose bytes/second exceed it did NOT move
# the bytes it reports (the win is timing drift, not wire engineering).
# Lower bound: a "transfer" phase moving under ~1 MB/s isn't transfer at
# all — the window's transport share is dominated by something the byte
# count can't account for (RTT weather, a stall), so attributing a wire win
# to it would publish drift as engineering.
MAX_SANE_BANDWIDTH = 64e9  # bytes/s
MIN_SANE_BANDWIDTH = 1e6  # bytes/s


def check_transport(
    transfer_s: float,
    bytes_on_wire: int,
    *,
    min_bandwidth: float = MIN_SANE_BANDWIDTH,
    max_bandwidth: float = MAX_SANE_BANDWIDTH,
    label: str = "window",
) -> Optional[str]:
    """Transport-dominance gate: a timed window's transfer share must be
    accountable against its reported bytes at a physically plausible
    bandwidth. `transfer_s` is the wall time the window attributes to
    host<->device transfers; `bytes_on_wire` the bytes its wire counters
    say crossed the boundary in that time (ShardedEngine.take_wire_deltas).

    The compact-wire work makes dispatch claims byte-denominated, which
    cuts both ways: a 'win' can be faked by a window whose timing happens
    to shrink for reasons unrelated to bytes. The implied bandwidth
    (bytes / transfer_s) exposes both failure modes — too fast means the
    bytes were never moved in the measured time, too slow means the
    measured time wasn't transfer. Returns a refusal reason, or None."""
    if bytes_on_wire < 0:
        return f"{label}: negative byte count {bytes_on_wire}"
    if bytes_on_wire == 0:
        return None  # nothing claimed against the wire
    if transfer_s <= 0:
        return (
            f"{label}: {bytes_on_wire} bytes claimed against a "
            f"{transfer_s * 1e3:.3f}ms transfer share — no time in which "
            "to move them"
        )
    implied = bytes_on_wire / transfer_s
    if implied > max_bandwidth:
        return (
            f"{label}: implied transfer bandwidth {implied:.3e} B/s exceeds "
            f"the physical ceiling {max_bandwidth:.0e} B/s — the window did "
            "not move the bytes its rate claims"
        )
    if implied < min_bandwidth:
        return (
            f"{label}: implied transfer bandwidth {implied:.3e} B/s is under "
            f"{min_bandwidth:.0e} B/s — the transfer share is not explained "
            "by bytes on the wire (measurement drift, not transport)"
        )
    return None


def check_dropped(
    dropped: int,
    decisions: int,
    *,
    max_frac: float = 0.01,
    label: str = "decisions",
) -> Optional[str]:
    """Write-path proof of work. hit/miss reconciliation (check_work) cannot
    see a write path that probes rows but fails to persist them — dropped
    rows still count as probed — so a broken write (e.g. a sparse grid
    mapping updates into the wrong blocks, or a window geometry that
    overflows every run) would sail through check_work while the timed loop
    'serves' decisions nobody could ever re-read. Such failures surface as a
    drop storm in the loop's own dropped counter; legitimate drops (claim
    dedup under contention, the rare window-overflow tail) stay far under
    `max_frac` for the bench's unique-fingerprint batches. Returns a refusal
    reason, or None if drops are within tolerance."""
    if decisions <= 0:
        return None
    if dropped > max_frac * decisions:
        return (
            f"{dropped} of {decisions} {label} dropped "
            f"(> {max_frac:.1%} tolerance): the write path did not persist "
            "the work its rate claims"
        )
    return None
