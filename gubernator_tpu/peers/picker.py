"""RegionPicker — per-datacenter consistent-hash rings.

The multi-region analog of the reference's RegionPicker (reference
region_picker.go:19-103): peers are grouped by their ``data_center`` label,
each region gets its own ReplicatedConsistentHash ring, and a key resolves to
one owning peer *per region* (cross-region replication targets). Within the
local region the plain ring (peers/hash_ring.py) decides ownership; the
RegionPicker exists so MULTI_REGION traffic and health checks can enumerate
every region's owner for a key.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from gubernator_tpu.peers.hash_ring import (
    DEFAULT_REPLICAS,
    ReplicatedConsistentHash,
    fnv1a_32,
)
from gubernator_tpu.types import PeerInfo


class RegionPicker:
    """Encapsulates one consistent-hash ring per region (data center)."""

    def __init__(
        self,
        hash_fn: Optional[Callable[[bytes], int]] = None,
        replicas: int = DEFAULT_REPLICAS,
    ):
        self.hash_fn = hash_fn or fnv1a_32
        self.replicas = replicas
        self._regions: Dict[str, ReplicatedConsistentHash] = {}

    def add(self, peer: PeerInfo) -> None:
        """Register a peer under its data_center's ring (created on first
        sighting — reference region_picker.go:96-103)."""
        ring = self._regions.get(peer.data_center)
        if ring is None:
            ring = ReplicatedConsistentHash(self.hash_fn, self.replicas)
            self._regions[peer.data_center] = ring
        ring.add(peer)

    def get_clients(
        self, key: str, exclude: frozenset = frozenset()
    ) -> List[PeerInfo]:
        """The owning peer of `key` in EVERY region (reference
        region_picker.go:57-69) — the cross-region replication fan-out set.
        `exclude` routes around unreachable (open-breaker) peers within each
        region's ring; a region whose peers are ALL excluded contributes no
        target rather than failing the whole fan-out."""
        out: List[PeerInfo] = []
        for ring in self._regions.values():
            try:
                out.append(ring.get(key, exclude))
            except RuntimeError:
                continue  # every peer in this region excluded
        return out

    def get_by_address(self, address: str) -> Optional[PeerInfo]:
        """First peer whose address matches, searching all regions
        (reference region_picker.go:72-79)."""
        for ring in self._regions.values():
            peer = ring.get_by_address(address)
            if peer is not None:
                return peer
        return None

    def pickers(self) -> Dict[str, ReplicatedConsistentHash]:
        """region → ring map (reference region_picker.go:82-84)."""
        return self._regions

    def peers(self) -> List[PeerInfo]:
        """All peers across all regions (reference region_picker.go:86-94)."""
        out: List[PeerInfo] = []
        for ring in self._regions.values():
            out.extend(ring.peers())
        return out

    def size(self) -> int:
        return sum(r.size() for r in self._regions.values())
