from gubernator_tpu.peers.hash_ring import ReplicatedConsistentHash
from gubernator_tpu.peers.picker import RegionPicker

__all__ = ["ReplicatedConsistentHash", "RegionPicker"]
