"""Replicated consistent hash ring — peer-level key ownership.

The cluster analog of the reference's LocalPicker (reference
replicated_hash.go:36-119): 512 virtual replicas per peer placed on a 32-bit
ring; a key's owner is the first replica clockwise from the key's hash
(binary search). The ring is rebuilt from scratch on every peer-set change
(reference gubernator.go:694-746) — cheap and simple.

Within a host, device-shard ownership uses fingerprint high bits
(parallel/mesh.py); this ring decides which HOST owns a key across the
cluster, exactly like the reference decides which node does.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Callable, Dict, List, Optional

import xxhash

from gubernator_tpu.types import PeerInfo

DEFAULT_REPLICAS = 512  # reference replicated_hash.go:29


def _hash32(data: bytes) -> int:
    return xxhash.xxh32_intdigest(data)


def fnv1a_32(data: bytes) -> int:
    h = 0x811C9DC5
    for b in data:
        h = ((h ^ b) * 0x01000193) & 0xFFFFFFFF
    return h


def fnv1_32(data: bytes) -> int:
    h = 0x811C9DC5
    for b in data:
        h = ((h * 0x01000193) & 0xFFFFFFFF) ^ b
    return h


HASH_FUNCTIONS: Dict[str, Callable[[bytes], int]] = {
    # the reference offers fnv1a (default) and fnv1 (config.go:479-502)
    "fnv1a": fnv1a_32,
    "fnv1": fnv1_32,
    "xxhash": _hash32,
}


class ReplicatedConsistentHash:
    """Peer picker with virtual-replica consistent hashing."""

    def __init__(
        self,
        hash_fn: Optional[Callable[[bytes], int]] = None,
        replicas: int = DEFAULT_REPLICAS,
    ):
        self.hash_fn = hash_fn or fnv1a_32
        self.replicas = replicas
        self._peers: Dict[str, PeerInfo] = {}
        self._ring: List[tuple] = []  # sorted (point, PeerInfo)

    def peers(self) -> List[PeerInfo]:
        return list(self._peers.values())

    def add(self, peer: PeerInfo) -> None:
        """Place `replicas` points for the peer; the replica key mixes the
        replica index with an md5 of the address (reference
        replicated_hash.go:78-91)."""
        self._peers[peer.grpc_address] = peer
        digest = hashlib.md5(peer.grpc_address.encode()).hexdigest()
        for i in range(self.replicas):
            point = self.hash_fn(f"{i}{digest}".encode())
            self._ring.append((point, peer))
        self._ring.sort(key=lambda t: t[0])
        self._ring_pts = None  # invalidate the vectorized-lookup cache

    def get(self, key: str, exclude: frozenset = frozenset()) -> PeerInfo:
        """Owner of `key` — first ring point at or after hash(key), wrapping
        (reference replicated_hash.go:104-119). `exclude` (grpc addresses)
        skips peers along the ring — the fault-tolerance route-around: the
        first non-excluded peer clockwise is the key's natural fallback
        owner. Raises when every peer is excluded."""
        if not self._ring:
            raise RuntimeError("unable to pick a peer; pool is empty")
        point = self.hash_fn(key.encode())
        idx = bisect.bisect_left(self._ring, (point,))
        if idx == len(self._ring):
            idx = 0
        if not exclude:
            return self._ring[idx][1]
        seen = set()
        for off in range(len(self._ring)):
            peer = self._ring[(idx + off) % len(self._ring)][1]
            if peer.grpc_address not in exclude:
                return peer
            seen.add(peer.grpc_address)
            if len(seen) == len(self._peers):
                break
        raise RuntimeError("unable to pick a peer; all peers excluded")

    def owners_of(self, points, exclude: frozenset = frozenset()) -> List[PeerInfo]:
        """Vectorized get(): precomputed 32-bit ring points (numpy array) →
        owner per element. Used by the native ingress path, which computes
        fnv1a ring points during wire parsing so no key strings need to be
        materialized for routing. `exclude` (grpc addresses) removes peers'
        replicas from the ring before the lookup — the vectorized form of
        get(key, exclude), used by the graceful drain to find every row's
        ring successor (ownership as if this peer were already gone)."""
        if not self._ring:
            raise RuntimeError("unable to pick a peer; pool is empty")
        import numpy as np

        if exclude:
            ring = [
                (p, peer)
                for p, peer in self._ring
                if peer.grpc_address not in exclude
            ]
            if not ring:
                raise RuntimeError("unable to pick a peer; all peers excluded")
            pts = np.fromiter((p for p, _ in ring), np.uint32, len(ring))
        else:
            ring = self._ring
            if getattr(self, "_ring_pts", None) is None or len(
                self._ring_pts
            ) != len(self._ring):
                self._ring_pts = np.fromiter(
                    (p for p, _ in self._ring), np.uint32, len(self._ring)
                )
            pts = self._ring_pts
        idx = np.searchsorted(pts, points, side="left")
        idx[idx == len(ring)] = 0
        return [ring[i][1] for i in idx]

    def size(self) -> int:
        return len(self._peers)

    def get_by_address(self, address: str) -> Optional[PeerInfo]:
        return self._peers.get(address)
