"""Fingerprint → ring-point ownership sidecar.

The device table stores only 63-bit fingerprints (raw keys never reach the
device, hashing.py), but PEER ownership is decided on the string hash key's
32-bit ring point (peers/hash_ring.py — fnv1a over "name_uniquekey"). The
two are not mutually derivable, so a topology-change handoff (which must map
each live table row to its new ring owner) needs this host-side sidecar: the
daemon records (fingerprint, ring_point) pairs for every row it serves AS
OWNER — both are already computed on the serving path (the native wire
parser emits ring points per item; the pb path hashes per item anyway) — and
the handoff reads the mapping back when partitioning extracted rows.

Rows with no recorded point (e.g. restored from a checkpoint taken by an
older build, or replica installs) cannot be routed; the handoff skips them,
degrading for exactly those rows to the pre-handoff behavior (fresh state at
the new owner, over-admission bounded by one config window). Transfer chunks
carry the points alongside the slots so a receiver can route the same rows
onward in a later rebalance (the hand-back half of a rolling restart).
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class OwnershipIndex:
    """Append-mostly {fingerprint: ring_point} map with vectorized batch
    record/lookup. Not thread-safe by design: every writer runs on the
    asyncio event loop (daemon routing paths), and the handoff reads from
    there too."""

    def __init__(self):
        self._map: dict = {}

    def __len__(self) -> int:
        return len(self._map)

    def record(self, fps: np.ndarray, points: np.ndarray) -> None:
        """Remember the ring point for each fingerprint (newest wins — the
        point for a given key never changes, so collisions are rewrites of
        the same value)."""
        if fps.shape[0] == 0:
            return
        self._map.update(
            zip(
                np.asarray(fps, dtype=np.int64).tolist(),
                np.asarray(points, dtype=np.uint32).tolist(),
            )
        )

    def record_keys(self, fps, keys, hash_fn) -> None:
        """pb-path variant: compute each key's ring point with the picker's
        own hash function (the native path gets points for free from the
        wire parser)."""
        for fp, key in zip(fps, keys):
            if key:
                self._map[int(fp)] = hash_fn(key.encode()) & 0xFFFFFFFF

    def points_for(self, fps: np.ndarray):
        """(points (N,) uint32, found (N,) bool) for a batch of
        fingerprints; unmapped entries carry point 0 with found=False."""
        n = fps.shape[0]
        points = np.zeros(n, dtype=np.uint32)
        found = np.zeros(n, dtype=bool)
        get = self._map.get
        for i, fp in enumerate(np.asarray(fps, dtype=np.int64).tolist()):
            p = get(fp)
            if p is not None:
                points[i] = p
                found[i] = True
        return points, found

    def discard(self, fps: np.ndarray) -> None:
        """Forget transferred-and-tombstoned rows (bounds sidecar memory to
        the live, still-owned key set over time)."""
        pop = self._map.pop
        for fp in np.asarray(fps, dtype=np.int64).tolist():
            pop(fp, None)

    def prune(self, live_fps: Optional[np.ndarray]) -> int:
        """Drop every entry not in `live_fps` (post-handoff housekeeping
        against the extract's live set). Returns the number pruned."""
        if live_fps is None:
            n = len(self._map)
            self._map.clear()
            return n
        keep = set(np.asarray(live_fps, dtype=np.int64).tolist())
        stale = [fp for fp in self._map if fp not in keep]
        for fp in stale:
            del self._map[fp]
        return len(stale)
