"""Packed-row HBM table (v2): one bucket per TPU lane row.

Layout chosen from measured v5e memory-op costs (exp/exp_mem*.py):

* XLA scatters serialize (~8 ns/element regardless of layout) — the v1 design's
  15 plane scatters cost ~16 ms per 131K-row dispatch;
* row gathers are fast (~1.3 ms for (131K, 128) int32), and a full streaming
  sweep of a 1 GB table through VMEM costs ~3.3 ms with int8 one-hot matmuls
  (the scatter-as-MXU-work trick) essentially free behind the DMA.

Hence the v2 layout: ``rows`` is an (NB, 128) int32 array — NB buckets, each
row = K=8 slots x 16 int32 fields, slot-major. A bucket row is exactly one TPU
vector lane row (128 lanes), so:

* probe+apply = ONE row gather of the request's whole bucket (every slot's
  full state arrives in one fetch — no separate probe plane);
* write = the Pallas sweep kernel (ops/kernel2.py) composing slot-granular
  updates into bucket rows via int8 one-hot matmuls on the MXU.

Per-slot field order (16 int32 lanes): fp_lo, fp_hi, limit, burst, rem_i,
flags(algo | status<<8), dur_lo, dur_hi, stamp_lo, stamp_hi, exp_lo, exp_hi,
remf_hi(f32 bits), remf_lo(f32 bits), reserved, reserved. Semantics mirror
TokenBucketItem/LeakyBucketItem (reference store.go:29-43) + CacheItem.ExpireAt
(reference cache.go:29-41); the leaky float64 remainder is double-single
(two f32, ~48-bit mantissa). fp == 0 marks an empty slot. Eviction is
expiry-stamp based exactly as in v1 (ops/table.py docstring; reference
lrucache.go:111-149).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

K = 8  # slots per bucket
F = 16  # int32 fields per slot in the canonical FULL layout
ROW = K * F  # 128 int32 lanes per full-layout bucket row

# field indices within a FULL-layout slot (packed layouts unpack to this
# order — ops/layout.py is the single conversion authority)
FP_LO, FP_HI, LIMIT, BURST, REM_I, FLAGS = 0, 1, 2, 3, 4, 5
DUR_LO, DUR_HI, STAMP_LO, STAMP_HI, EXP_LO, EXP_HI = 6, 7, 8, 9, 10, 11
REMF_HI, REMF_LO = 12, 13


class Table2:
    """One HBM table: a rows array plus the SlotLayout addressing it.

    ``rows`` is (NB, K·layout.F) int32 — one bucket per row, K slots of
    layout.F fields each (128 lanes for the full layout, 64 for the packed
    ones). The layout travels as pytree AUX data (static), so jitted
    programs key their compilation on it and shard_map/tree transforms
    preserve it for free; ``Table2(rows=...)`` without a layout infers the
    full layout from the 128-lane width (the pre-layout constructor every
    existing call site uses), while packed tables pass theirs explicitly."""

    __slots__ = ("rows", "layout")

    def __init__(self, rows, layout=None):
        if layout is None:
            from gubernator_tpu.ops.layout import layout_for_row

            layout = layout_for_row(int(rows.shape[-1]))
        self.rows = rows
        self.layout = layout

    @property
    def n_buckets(self) -> int:
        return self.rows.shape[-2]

    @property
    def capacity(self) -> int:
        return self.rows.shape[-2] * K

    def __repr__(self) -> str:  # pragma: no cover - debugging nicety
        return f"Table2(rows={getattr(self.rows, 'shape', None)}, " \
               f"layout={self.layout.name})"


def _t2_flatten(t: Table2):
    return (t.rows,), t.layout


def _t2_unflatten(layout, children):
    obj = object.__new__(Table2)
    obj.rows = children[0]
    obj.layout = layout
    return obj


jax.tree_util.register_pytree_node(Table2, _t2_flatten, _t2_unflatten)


def n_buckets_for(capacity: int) -> int:
    """Bucket count for a requested slot capacity: rounded up so the Pallas
    sweep's block partitioning divides evenly (power of two below 2048 blocks,
    multiple of 2048 above)."""
    if capacity <= 0:
        raise ValueError("capacity must be positive")
    nb = -(-capacity // K)
    if nb <= 2048:
        p = 1
        while p < nb:
            p *= 2
        return p
    return -(-nb // 2048) * 2048


def new_table2(capacity: int, layout=None) -> Table2:
    """Fresh empty table (the CacheSize analog, reference config.go:151).
    Keep load factor <= ~0.6 for healthy buckets. `layout` defaults to the
    canonical full layout (bit-compatible with every earlier PR)."""
    if layout is None:
        from gubernator_tpu.ops.layout import FULL as layout
    return Table2(
        rows=jnp.zeros((n_buckets_for(capacity), layout.row), dtype=jnp.int32),
        layout=layout,
    )


def live_count2(table: Table2, now_ms: int) -> int:
    """Live (non-empty, unexpired) slots — reference cache Size()
    (lrucache.go:152-157)."""
    lay = table.layout
    rows = np.asarray(table.rows).reshape(-1, K, lay.F)
    lo = rows[:, :, FP_LO]
    hi = rows[:, :, FP_HI]
    exp = (rows[:, :, lay.exp_lo_i].astype(np.int64) & 0xFFFFFFFF) | (
        rows[:, :, lay.exp_hi_i].astype(np.int64) << 32
    )
    nonempty = (lo != 0) | (hi != 0)
    return int((nonempty & (exp >= now_ms)).sum())


def decode_live_slots(rows: np.ndarray, now_ms: int, layout=None):
    """Flatten a rows array into live slot records: (slot_fields (N, F_layout)
    i32, fp (N,) i64, exp (N,) i64) for slots that are non-empty and
    unexpired at now_ms. Slots come back in the TABLE's own layout — convert
    with layout.unpack when full-width fields are needed."""
    if layout is None:
        from gubernator_tpu.ops.layout import layout_for_row

        layout = layout_for_row(int(rows.shape[-1]))
    slots = rows.reshape(-1, layout.F)
    lo = slots[:, FP_LO].astype(np.int64) & 0xFFFFFFFF
    hi = slots[:, FP_HI].astype(np.int64)
    fp = (hi << 32) | lo
    exp = (slots[:, layout.exp_lo_i].astype(np.int64) & 0xFFFFFFFF) | (
        slots[:, layout.exp_hi_i].astype(np.int64) << 32
    )
    live = (fp != 0) & (exp >= now_ms)
    return slots[live], fp[live], exp[live]


# ------------------------------------------------------------- handoff ops
#
# Topology-change survivability (docs/robustness.md "Topology change &
# drain"): when ring ownership moves, the owner's live rows must follow.
# The DEVICE pays for partitioning millions of live slots — a full-table
# filter+pack runs as one fused program and the host fetches only the live
# prefix (batch-proportional transfer), mirroring the sparse-write /
# packed-single-fetch idioms of the serving path.


@functools.partial(jax.jit, static_argnames=("layout",))
def _extract_sorted(rows: jnp.ndarray, now_ms: jnp.ndarray, *, layout):
    """Device filter+pack: all live slots sorted to the front. Accepts any
    (..., ROW_layout) rows array (single-device (NB, ·) or sharded
    (D, NB, ·) — the flatten makes the shard axis fold in). Returns
    (slots_packed (N, F_layout), fp_packed (N,), live_count) with live
    entries occupying the first `live_count` positions; slots stay in the
    table's own layout (the handoff/checkpoint wire format)."""
    slots = rows.reshape(-1, layout.F)
    lo = slots[:, FP_LO].astype(jnp.int64) & 0xFFFFFFFF
    hi = slots[:, FP_HI].astype(jnp.int64)
    fp = (hi << 32) | lo
    exp = (slots[:, layout.exp_lo_i].astype(jnp.int64) & 0xFFFFFFFF) | (
        slots[:, layout.exp_hi_i].astype(jnp.int64) << 32
    )
    live = (fp != 0) & (exp >= now_ms)
    order = jnp.argsort(jnp.where(live, 0, 1).astype(jnp.int32))
    return slots[order], fp[order], live.sum()


def extract_live_rows(rows, now_ms: int, layout=None):
    """Extract every live slot from a device-resident rows array:
    (fps (N,) i64, slots (N, F_layout) i32) host copies. The filter + pack
    runs on-device (_extract_sorted); the host fetches only the live prefix,
    padded to a power of two so the number of compiled slice shapes stays
    logarithmic in table capacity."""
    if layout is None:
        from gubernator_tpu.ops.layout import layout_for_row

        layout = layout_for_row(int(rows.shape[-1]))
    slots_s, fp_s, cnt = _extract_sorted(rows, np.int64(now_ms), layout=layout)
    n = int(cnt)
    if n == 0:
        return (
            np.empty(0, dtype=np.int64),
            np.empty((0, layout.F), dtype=np.int32),
        )
    pad = 256
    while pad < n:
        pad *= 2
    pad = min(pad, int(fp_s.shape[0]))
    return (
        np.asarray(fp_s[:pad])[:n].copy(),
        np.asarray(slots_s[:pad])[:n].copy(),
    )


def _extract_idle_core(rows2d, now_ms, idle_ms, layout):
    """Traced core of the tiering idle sweep (gubernator_tpu/tier/):
    live slots whose last-activity reference (layout.idle_ref — stamp, or
    exp-duration for layouts that drop it) is at least `idle_ms` behind
    `now_ms`, sorted to the front. `rows2d` is (T, ROW_layout); returns
    (slots (T·K, F_layout) idle-first, fp (T·K,), idle_count) — slots stay
    in the table's own layout, the demote path unpacks only the fetched
    prefix. Shared by the single-array jit below and the per-shard
    shard_map body (parallel/sharded.make_sharded_extract_idle)."""
    slots = rows2d.reshape(-1, layout.F)
    lo = slots[:, FP_LO].astype(jnp.int64) & 0xFFFFFFFF
    hi = slots[:, FP_HI].astype(jnp.int64)
    fp = (hi << 32) | lo
    exp = (slots[:, layout.exp_lo_i].astype(jnp.int64) & 0xFFFFFFFF) | (
        slots[:, layout.exp_hi_i].astype(jnp.int64) << 32
    )
    live = (fp != 0) & (exp >= now_ms)
    idle = live & ((now_ms - layout.idle_ref(slots)) >= idle_ms)
    order = jnp.argsort(jnp.where(idle, 0, 1).astype(jnp.int32))
    return slots[order], fp[order], idle.sum()


@functools.partial(jax.jit, static_argnames=("layout",))
def _extract_idle_sorted(rows, now_ms, idle_ms, *, layout):
    """Single-array entry: any (..., ROW_layout) rows array (the flatten
    folds a shard axis in, like _extract_sorted)."""
    return _extract_idle_core(
        rows.reshape(-1, layout.row), now_ms, idle_ms, layout
    )


def extract_idle_rows(rows, now_ms: int, idle_ms: int, layout=None,
                      max_rows: int = 1 << 16):
    """Idle-past-the-horizon live slots of a device-resident rows array:
    (fps (N,) i64, slots (N, F_layout) i32) host copies, N ≤ max_rows (the
    per-sweep demote cap — bounds the engine-thread job; the remainder
    stays for the next sweep). The filter + pack runs on-device; the host
    fetches only the idle prefix (the extract_live_rows fetch rule)."""
    if layout is None:
        from gubernator_tpu.ops.layout import layout_for_row

        layout = layout_for_row(int(rows.shape[-1]))
    slots_s, fp_s, cnt = _extract_idle_sorted(
        rows, jnp.asarray(np.int64(now_ms)), jnp.asarray(np.int64(idle_ms)),
        layout=layout,
    )
    n = min(int(cnt), int(max_rows))
    if n == 0:
        return (
            np.empty(0, dtype=np.int64),
            np.empty((0, layout.F), dtype=np.int32),
        )
    pad = 256
    while pad < n:
        pad *= 2
    pad = min(pad, int(fp_s.shape[0]))
    return (
        np.asarray(fp_s[:pad])[:n].copy(),
        np.asarray(slots_s[:pad])[:n].copy(),
    )


def gather_slots_impl(rows: jnp.ndarray, fp: jnp.ndarray,
                      active: jnp.ndarray, layout=None):
    """Read the slots holding each fingerprint WITHOUT mutating anything:
    one bucket-row gather + lane match, unpacked to canonical full-width
    fields. Returns (full_slots (B, 16) i32, found (B,) bool) — the
    stored-state read the GLOBAL broadcast plane uses to ship
    sliding-window aux with owner updates (service/global_manager.py).
    Unexpired-ness is NOT checked here; callers filter on the expiry pair
    if they need liveness."""
    if layout is None:
        from gubernator_tpu.ops.layout import layout_for_row

        layout = layout_for_row(int(rows.shape[-1]))
    NB = rows.shape[0]
    B = fp.shape[0]
    bucket = (fp % NB).astype(jnp.int32)
    full = layout.unpack(rows[bucket].reshape(B, K, layout.F))  # (B, K, 16)
    my_lo = fp.astype(jnp.int32)
    my_hi = (fp >> 32).astype(jnp.int32)
    s_lo = full[:, :, FP_LO]
    s_hi = full[:, :, FP_HI]
    empty = (s_lo == 0) & (s_hi == 0)
    match = (
        (s_lo == my_lo[:, None]) & (s_hi == my_hi[:, None]) & ~empty
        & active[:, None]
    )
    found = match.any(axis=1)
    lane = jnp.argmax(match, axis=1).astype(jnp.int32)
    lane16 = jnp.take_along_axis(full, lane[:, None, None], axis=1)[:, 0, :]
    return jnp.where(found[:, None], lane16, 0), found


gather_slots = functools.partial(jax.jit, static_argnames=("layout",))(
    gather_slots_impl
)


def tombstone_rows_impl(rows: jnp.ndarray, fp: jnp.ndarray, active: jnp.ndarray):
    """Zero the slot holding each fingerprint (handoff source side: rows are
    tombstoned only AFTER the destination acked their transfer). Missing
    fingerprints are no-ops — a kill mask over matched slots only, so a
    retried tombstone can never evict an unrelated live entry. Returns
    (rows', found_mask). Layout-agnostic by construction: only the
    fingerprint pair (fields 0/1 in every layout) and the row geometry
    (F = lanes // K) are read."""
    NB = rows.shape[0]
    B = fp.shape[0]
    F_l = rows.shape[-1] // K
    bucket = (fp % NB).astype(jnp.int32)
    b_rows = rows[bucket].reshape(B, K, F_l)
    my_lo = fp.astype(jnp.int32)
    my_hi = (fp >> 32).astype(jnp.int32)
    s_lo = b_rows[:, :, FP_LO]
    s_hi = b_rows[:, :, FP_HI]
    empty = (s_lo == 0) & (s_hi == 0)
    match = (
        (s_lo == my_lo[:, None]) & (s_hi == my_hi[:, None]) & ~empty
        & active[:, None]
    )
    lane = jnp.argmax(match, axis=1).astype(jnp.int32)
    found = match.any(axis=1)
    NBK = NB * K
    tgt = jnp.where(found, bucket * K + lane, NBK)
    kill = jnp.zeros(NBK + 1, dtype=bool).at[tgt].set(True)[:NBK]
    flat = rows.reshape(NBK, F_l)
    out = jnp.where(kill[:, None], 0, flat).reshape(NB, K * F_l)
    return out, found


tombstone_rows = functools.partial(jax.jit, donate_argnums=(0,))(
    tombstone_rows_impl
)


def rehash_rows(
    rows: np.ndarray, new_n_buckets: int, now_ms: int, layout=None
) -> "tuple[np.ndarray, int]":
    """Re-place every live slot into a table with `new_n_buckets` buckets —
    the host side of a resize (SURVEY §7 hard-parts: table growth is
    host-orchestrated; the kernel's placement rule is bucket = fp % NB).
    Buckets receiving more than K live entries keep the K latest-expiring and
    drop the rest (the same preference order as in-kernel eviction). Returns
    (new rows array, dropped count) in the same slot layout as the input."""
    if layout is None:
        from gubernator_tpu.ops.layout import layout_for_row

        layout = layout_for_row(int(rows.shape[-1]))
    slots, fp, exp = decode_live_slots(rows, now_ms, layout=layout)
    out = np.zeros((new_n_buckets, layout.row), dtype=np.int32)
    if fp.shape[0] == 0:
        return out, 0
    bucket = fp % new_n_buckets
    # rank entries within their new bucket, latest-expiring first
    order = np.lexsort((-exp, bucket))
    b_sorted = bucket[order]
    first = np.concatenate([[True], b_sorted[1:] != b_sorted[:-1]])
    pos = np.arange(b_sorted.shape[0])
    start = np.maximum.accumulate(np.where(first, pos, -1))
    lane = (pos - start).astype(np.int64)
    keep = lane < K
    dropped = int((~keep).sum())
    tgt = b_sorted[keep] * K + lane[keep]
    out.reshape(-1, layout.F)[tgt] = slots[order[keep]]
    return out, dropped
