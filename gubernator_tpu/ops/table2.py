"""Packed-row HBM table (v2): one bucket per TPU lane row.

Layout chosen from measured v5e memory-op costs (exp/exp_mem*.py):

* XLA scatters serialize (~8 ns/element regardless of layout) — the v1 design's
  15 plane scatters cost ~16 ms per 131K-row dispatch;
* row gathers are fast (~1.3 ms for (131K, 128) int32), and a full streaming
  sweep of a 1 GB table through VMEM costs ~3.3 ms with int8 one-hot matmuls
  (the scatter-as-MXU-work trick) essentially free behind the DMA.

Hence the v2 layout: ``rows`` is an (NB, 128) int32 array — NB buckets, each
row = K=8 slots x 16 int32 fields, slot-major. A bucket row is exactly one TPU
vector lane row (128 lanes), so:

* probe+apply = ONE row gather of the request's whole bucket (every slot's
  full state arrives in one fetch — no separate probe plane);
* write = the Pallas sweep kernel (ops/kernel2.py) composing slot-granular
  updates into bucket rows via int8 one-hot matmuls on the MXU.

Per-slot field order (16 int32 lanes): fp_lo, fp_hi, limit, burst, rem_i,
flags(algo | status<<8), dur_lo, dur_hi, stamp_lo, stamp_hi, exp_lo, exp_hi,
remf_hi(f32 bits), remf_lo(f32 bits), reserved, reserved. Semantics mirror
TokenBucketItem/LeakyBucketItem (reference store.go:29-43) + CacheItem.ExpireAt
(reference cache.go:29-41); the leaky float64 remainder is double-single
(two f32, ~48-bit mantissa). fp == 0 marks an empty slot. Eviction is
expiry-stamp based exactly as in v1 (ops/table.py docstring; reference
lrucache.go:111-149).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

K = 8  # slots per bucket
F = 16  # int32 fields per slot
ROW = K * F  # 128 int32 lanes per bucket row

# field indices within a slot
FP_LO, FP_HI, LIMIT, BURST, REM_I, FLAGS = 0, 1, 2, 3, 4, 5
DUR_LO, DUR_HI, STAMP_LO, STAMP_HI, EXP_LO, EXP_HI = 6, 7, 8, 9, 10, 11
REMF_HI, REMF_LO = 12, 13


class Table2(NamedTuple):
    rows: jnp.ndarray  # (NB, 128) int32

    @property
    def n_buckets(self) -> int:
        return self.rows.shape[-2]

    @property
    def capacity(self) -> int:
        return self.rows.shape[-2] * K


def n_buckets_for(capacity: int) -> int:
    """Bucket count for a requested slot capacity: rounded up so the Pallas
    sweep's block partitioning divides evenly (power of two below 2048 blocks,
    multiple of 2048 above)."""
    if capacity <= 0:
        raise ValueError("capacity must be positive")
    nb = -(-capacity // K)
    if nb <= 2048:
        p = 1
        while p < nb:
            p *= 2
        return p
    return -(-nb // 2048) * 2048


def new_table2(capacity: int) -> Table2:
    """Fresh empty table (the CacheSize analog, reference config.go:151).
    Keep load factor <= ~0.6 for healthy buckets."""
    return Table2(rows=jnp.zeros((n_buckets_for(capacity), ROW), dtype=jnp.int32))


def live_count2(table: Table2, now_ms: int) -> int:
    """Live (non-empty, unexpired) slots — reference cache Size()
    (lrucache.go:152-157)."""
    rows = np.asarray(table.rows).reshape(-1, K, F)
    lo = rows[:, :, FP_LO]
    hi = rows[:, :, FP_HI]
    exp = (rows[:, :, EXP_LO].astype(np.int64) & 0xFFFFFFFF) | (
        rows[:, :, EXP_HI].astype(np.int64) << 32
    )
    nonempty = (lo != 0) | (hi != 0)
    return int((nonempty & (exp >= now_ms)).sum())


def decode_live_slots(rows: np.ndarray, now_ms: int):
    """Flatten an (NB, 128) rows array into live slot records:
    (slot_fields (N, F) i32, fp (N,) i64, exp (N,) i64) for slots that are
    non-empty and unexpired at now_ms."""
    slots = rows.reshape(-1, F)
    lo = slots[:, FP_LO].astype(np.int64) & 0xFFFFFFFF
    hi = slots[:, FP_HI].astype(np.int64)
    fp = (hi << 32) | lo
    exp = (slots[:, EXP_LO].astype(np.int64) & 0xFFFFFFFF) | (
        slots[:, EXP_HI].astype(np.int64) << 32
    )
    live = (fp != 0) & (exp >= now_ms)
    return slots[live], fp[live], exp[live]


def rehash_rows(
    rows: np.ndarray, new_n_buckets: int, now_ms: int
) -> "tuple[np.ndarray, int]":
    """Re-place every live slot into a table with `new_n_buckets` buckets —
    the host side of a resize (SURVEY §7 hard-parts: table growth is
    host-orchestrated; the kernel's placement rule is bucket = fp % NB).
    Buckets receiving more than K live entries keep the K latest-expiring and
    drop the rest (the same preference order as in-kernel eviction). Returns
    (new rows array, dropped count)."""
    slots, fp, exp = decode_live_slots(rows, now_ms)
    out = np.zeros((new_n_buckets, ROW), dtype=np.int32)
    if fp.shape[0] == 0:
        return out, 0
    bucket = fp % new_n_buckets
    # rank entries within their new bucket, latest-expiring first
    order = np.lexsort((-exp, bucket))
    b_sorted = bucket[order]
    first = np.concatenate([[True], b_sorted[1:] != b_sorted[:-1]])
    pos = np.arange(b_sorted.shape[0])
    start = np.maximum.accumulate(np.where(first, pos, -1))
    lane = (pos - start).astype(np.int64)
    keep = lane < K
    dropped = int((~keep).sum())
    tgt = b_sorted[keep] * K + lane[keep]
    out.reshape(-1, F)[tgt] = slots[order[keep]]
    return out, dropped
