"""Single-device engine: the TPU analog of the reference WorkerPool.

Owns one HBM table and turns lists of RateLimitRequests into responses by
packing → pass-planning → dispatching the decision kernel. Replaces the
reference's WorkerPool.GetRateLimit channel machinery (workers.go:266-330);
"worker goroutines" collapse into SIMD lanes of one kernel call.

Batches are padded to bucketed static shapes so jit caches a handful of
compiled kernels instead of one per batch size.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from gubernator_tpu.ops.batch import (
    ERR_DROPPED,
    InstallBatch,
    ERROR_STRINGS,
    HostBatch,
    RequestColumns,
    ResponseColumns,
    columns_from_requests,
    pack_columns,
    pack_host_batch,
    pad_batch,
    to_device,
)
from gubernator_tpu.ops.kernel2 import (
    decide2_packed_cols,
    install2,
    pack_outputs,
    unpack_outputs,
)
from gubernator_tpu.ops.plan import Pass, plan_passes
from gubernator_tpu.ops.table2 import Table2, new_table2
from gubernator_tpu.types import RateLimitRequest, RateLimitResponse

# Error surfaced for rows whose decision could never be persisted (claim
# dropped after every retry). The reference never silently skips the cache
# write; returning the computed answer without persisting it would hand out
# free decisions under pathological contention.
ERR_NOT_PERSISTED = "rate limit state could not be persisted (contended table); retry"


def default_write_mode() -> str:
    """Block-sparse Pallas write on real TPU — write cost ∝ batch, not table
    size; kernel2.resolve_write falls each dispatch shape back to the full
    table-streaming sweep when the sparse grid's coverage crosses
    GUBER_WRITE_SPARSE_CROSSOVER (e.g. 131K-row bench batches). XLA scatter
    everywhere else (CPU test meshes, and any backend without the TPU Pallas
    pipeline — e.g. GPU, where the sweep kernel has never been lowered)."""
    return "sparse" if jax.default_backend() == "tpu" else "xla"


def ms_now() -> int:
    # reference store.go MillisecondNow()
    return time.time_ns() // 1_000_000


def _pad_size(n: int, floor: int = 16) -> int:
    size = floor
    while size < n:
        size *= 2
    return size


def _occurrence_rank(fps: np.ndarray) -> np.ndarray:
    """Per-row occurrence index of its fingerprint (0 for the first, 1 for
    the second duplicate, …) — the merge path's host-side analog of the
    planner's same-key pass split."""
    n = fps.shape[0]
    order = np.argsort(fps, kind="stable")
    sorted_f = fps[order]
    first = np.concatenate([[True], sorted_f[1:] != sorted_f[:-1]])
    idx = np.arange(n)
    start = np.maximum.accumulate(np.where(first, idx, -1))
    rank = np.empty(n, dtype=np.int64)
    rank[order] = idx - start
    return rank


def _math_mode(hb: HostBatch) -> str:
    """Static kernel specialization chosen host-side per dispatch
    (ops/math.bucket_math): an all-token batch (the common case — token is
    the reference's default algorithm) compiles ONLY the token lanes;
    GCRA / sliding-window / lease rows add the all-integer lanes; only a
    leaky row forces the emulated-f64 graph. Padding rows carry algo=0
    (token)."""
    algo = hb.algo
    if not algo.any():
        return "token"
    if (algo == 1).any():
        return "mixed"
    # all-GCRA specialization (headline single-algorithm traffic): only
    # the TAT lanes compile. Padding rows carry algo=0, so the check masks
    # to ACTIVE rows — inactive rows ride the gcra lanes harmlessly.
    act = algo[np.asarray(hb.active)]
    if act.size and (act == 2).all():
        return "gcra"
    return "int"


def batch_needs_full_layout(layout, math: str, hb=None) -> bool:
    """Host-side: can `layout` serve this batch? Shared by the local and
    mesh engines. Computed from the BATCH alone — migration only ever goes
    packed → full, so a prep-thread race reads at worst a stale packed
    layout and the engine-thread migrate call no-ops."""
    from gubernator_tpu.ops.layout import FULL

    if layout is FULL:
        return False
    if not layout.supports_math(math):
        return True
    if hb is not None and isinstance(hb, HostBatch):
        if not layout.greg_ok and (np.asarray(hb.greg_interval) != 0).any():
            return True
        if not layout.supports_algos(hb.algo, hb.active):
            return True
    return False


def effective_math(layout, hb) -> str:
    """The dispatch's math mode, layout-adjusted: an all-padding batch
    (warm-ups, all-error rows) defaults to "token" in _math_mode, which a
    packed non-token table cannot serve — padding rows ride ANY
    algorithm's lanes harmlessly (ops/math.py), so such batches take the
    layout's own mode instead of forcing a spurious migration."""
    math = _math_mode(hb)
    if not layout.supports_math(math) and not np.asarray(hb.active).any():
        return layout.modes[0]
    return math


def _has_cascade(hb) -> bool:
    """Whether a packed batch carries cascade level bits (behavior bits
    8-15, types.CASCADE_LEVEL_SHIFT)."""
    return bool((hb.behavior & np.int32(0xFF00)).any())


def _fold_cascades_host(
    behavior: np.ndarray,
    status: np.ndarray,
    remaining: np.ndarray,
    reset: np.ndarray,
    err: np.ndarray,
) -> None:
    """Host-side cascade verdict fold over assembled response columns:
    each carrier row (level 0) takes deny-if-any status, min remaining and
    the latest reset among denying levels of its group (members = the
    level>0 rows immediately following it). IDEMPOTENT over an already
    in-trace-folded carrier (kernel2.fold_cascade_packed), which is what
    lets it run unconditionally as the authoritative fold — it completes
    partial folds left by multi-pass plans, dropped-row retries and the
    mesh programs (whose routed/exchanged row order cannot fold in-trace).
    Rows with validation errors are excluded from the reductions; arrays
    mutate in place."""
    lvl = (behavior.astype(np.int64) >> 8) & 0xFF
    if not lvl.any():
        return
    n = lvl.shape[0]
    member = lvl > 0
    idx = np.arange(n)
    carrier = np.maximum.accumulate(np.where(~member, idx, -1))
    carrier = np.where(carrier < 0, idx, carrier)
    ok = err == 0
    mrows = np.nonzero(member & ok)[0]
    if mrows.size == 0:
        return
    c = carrier[mrows]
    np.maximum.at(status, c, status[mrows])
    np.minimum.at(remaining, c, remaining[mrows])
    deny = mrows[status[mrows] != 0]
    if deny.size:
        deny_reset = np.zeros(n, dtype=reset.dtype)
        np.maximum.at(deny_reset, carrier[deny], reset[deny])
        crows = np.nonzero(~member & ok & (status != 0))[0]
        reset[crows] = np.maximum(reset[crows], deny_reset[crows])


@dataclass
class EngineStats:
    """Host-side accumulation of kernel BatchStats (→ Prometheus layer)."""

    cache_hits: int = 0
    cache_misses: int = 0
    over_limit: int = 0
    evicted_unexpired: int = 0
    dropped: int = 0
    checks: int = 0
    dispatches: int = 0
    created_at_clamped: int = 0  # client timestamps outside the skew tolerance
    # rows that exhausted retries WITHOUT ever reaching the kernel (a2a
    # exchange-capacity drops, parallel/a2a.py): they appear in no
    # hit/miss/over counter, so without this the identity hits+misses ≈
    # checks would drift silently under sustained hot-shard overflow
    unprocessed_dropped: int = 0
    # packed-layout tables migrated to the full layout because off-family
    # traffic arrived (ops/layout.py selection contract) — a nonzero count
    # on a single-algorithm fleet means GUBER_SLOT_LAYOUT is misconfigured
    layout_migrations: int = 0

    def accumulate(self, stats, count_dropped: bool = True) -> None:
        self.cache_hits += int(stats.cache_hits)
        self.cache_misses += int(stats.cache_misses)
        self.over_limit += int(stats.over_limit)
        self.evicted_unexpired += int(stats.evicted_unexpired)
        if count_dropped:
            self.dropped += int(stats.dropped)

    def merge(self, d: "EngineStats") -> None:
        """Fold a pipelined check's stats delta in (applied on the engine
        thread so counter updates never race the dispatch path)."""
        self.cache_hits += d.cache_hits
        self.cache_misses += d.cache_misses
        self.over_limit += d.over_limit
        self.evicted_unexpired += d.evicted_unexpired
        self.dropped += d.dropped
        self.checks += d.checks
        self.dispatches += d.dispatches
        self.created_at_clamped += d.created_at_clamped
        self.unprocessed_dropped += d.unprocessed_dropped
        self.layout_migrations += d.layout_migrations


def _plan(engine, hb):
    """One batch's pass plan: the engine's `plan` hook when it has one
    (mesh engines aggregate duplicates in-trace and plan O(1) —
    parallel/sharded.ShardedEngine.plan), else the host group-by planner."""
    plan = getattr(engine, "plan", None)
    if plan is not None:
        return plan(hb)
    return plan_passes(hb, max_exact=engine.max_exact_passes)


def shadow_probe(engine, fps: np.ndarray, now_ms: int):
    """Fault-back probe (hot-set tiering, gubernator_tpu/tier/): exact-
    match the batch's fingerprints against the host-RAM shadow and REMOVE
    the hits — (fps, canonical rows) or None. Misses cost one dict lookup
    per unique fp, the off-hot-path contract; hits must be installed
    through the conservative merge BEFORE the batch's decide dispatch
    (promote_rows / PendingCheck.promote)."""
    shadow = getattr(engine, "shadow", None)
    if shadow is None:
        return None
    pf, rows = shadow.take(fps, now_ms)
    if pf.shape[0] == 0:
        return None
    return pf, rows


def promote_rows(engine, promote, now_ms: int):
    """Install a shadow_probe result into HBM through kernel2.merge2
    (engine thread — mutates the table). The merge's conservatism is the
    tiering soundness argument: a stale, duplicated, or raced promote can
    only UNDER-grant (docs/tiering.md). The closed-state-set discipline:
    live rows the installs displace demote onward to the shadow
    (merge2's evictee sidecar), and promote rows whose claim dropped
    (> K same-bucket inserters in one batch) retry and finally RETURN to
    the shadow instead of vanishing. Returns (installed_count,
    putback_fps) — the fingerprints this promote handed BACK to the
    shadow (returned leftovers + promote-displaced evictees): exactly
    the rows whose decide this batch may run against absent state, i.e.
    the miss re-check's eligibility set (_shadow_rehydrate). Rows the
    DECIDE dispatch itself later evicts are NOT eligible — their decide
    already served correctly from pre-evict state, and re-dispatching
    them would apply their hits twice."""
    if promote is None:
        return 0, np.empty(0, dtype=np.int64)
    from gubernator_tpu.ops.layout import FULL

    pf, rows = promote
    shadow = getattr(engine, "shadow", None)
    total = 0
    putback = []
    for _ in range(max(1, getattr(engine, "max_claim_retries", 3))):
        n, mask, ev_fps, ev_rows = engine.merge_rows(
            pf, rows, now_ms=now_ms, layout=FULL, collect=True
        )
        total += n
        if shadow is not None and ev_fps.shape[0]:
            shadow.offer(ev_fps, ev_rows, now_ms=0, reason="evict")
            putback.append(ev_fps)
        if mask.all():
            pf = pf[:0]
            break
        pf, rows = pf[~mask], rows[~mask]
    if shadow is not None and pf.shape[0]:
        shadow.offer(pf, rows, now_ms=0, reason="return")
        putback.append(pf)
    if not putback:
        return total, np.empty(0, dtype=np.int64)
    return total, np.concatenate(putback)


def _batch_fps(batch, n: int) -> np.ndarray:
    """Output-row-aligned fingerprints of a pass batch (HostBatch or the
    fused front door's lazy wire batch — cheap column view, no pack)."""
    if isinstance(batch, HostBatch):
        return np.asarray(batch.fp[:n])
    return batch.fp_view()[:n]


def _shadow_rehydrate(engine, batch, n, outs, active, now, redispatch,
                      eligible):
    """Tiering miss re-check (the Store `_rehydrate_misses` pattern):
    device-reported misses whose state the PROMOTE stage handed back to
    the shadow (`eligible` = promote_rows' putback fps — returned
    leftovers and promote-displaced evictees under > K-same-bucket
    pressure) are promoted through the conservative merge and
    RE-DISPATCHED, overwriting their phase-1 fresh-grant responses. The
    phase-1 slot merges with the shadow row (remaining = min), so the
    corrected response is exact when the shadow state is tighter and
    conservative otherwise. Eligibility is strictly the promote putback
    set: a row the DECIDE dispatch itself evicted was served correctly
    from pre-evict state before landing in the shadow, and re-dispatching
    it would double-apply its hits. Single-shot: a residual miss (a
    second >K collision within the re-dispatch itself) keeps its fresh
    grant, the state stays shadowed for the next batch, and the incident
    is bounded by one limit (docs/tiering.md). `redispatch(fn)` runs fn
    on the engine thread and returns its result. Returns
    (outs, changed)."""
    shadow = getattr(engine, "shadow", None)
    if shadow is None or eligible is None or eligible.shape[0] == 0:
        return outs, False
    s, l, r, t, dropped, hit = outs
    miss = ~hit[:n] & active
    if not miss.any():
        return outs, False
    rows = np.nonzero(miss)[0]
    fps = _batch_fps(batch, n)[rows]
    has = np.isin(fps, eligible) & shadow.contains(fps)
    if not has.any():
        return outs, False
    # unique-fp contract for the re-dispatch: duplicate-fp rows (mesh
    # member fan-outs) keep their phase-1 response; the first occurrence
    # carries the correction
    sel = np.nonzero(has)[0]
    _, first = np.unique(fps[sel], return_index=True)
    fr = rows[sel[np.sort(first)]]
    sub_fps = _batch_fps(batch, n)[fr]

    def run():
        promote_rows(engine, shadow_probe(engine, sub_fps, now), now)
        sub = HostBatch(*[f[fr] for f in batch])
        return engine._redispatch_rows(sub, len(fr))

    s2, l2, r2, t2, d2, h2 = redispatch(run)
    m = len(fr)
    s[fr], l[fr], r[fr], t[fr] = s2[:m], l2[:m], r2[:m], t2[:m]
    dropped[fr] = d2[:m]
    hit[fr] = h2[:m]
    return (s, l, r, t, dropped, hit), True


def serve_columns(engine, cols, now_ms, dispatch) -> ResponseColumns:
    """The shared columns-in/columns-out serving loop: pack + clamp-count,
    plan same-key passes, dispatch each (member-row fan-out, ERR_DROPPED for
    unpersisted rows), fold cascade verdicts, fire the Store hooks.
    `dispatch(pass_batch, n_rows, cascade=False)` returns (status, limit,
    remaining, reset, dropped, cache_hit) over the pass rows — the only
    thing that differs between the single-device and mesh engines;
    `cascade` asks for the in-trace verdict fold (single-device engines
    honor it, mesh engines ignore it and lean on the host fold)."""
    now = now_ms if now_ms is not None else ms_now()
    hb, err = pack_columns(cols, now, tolerance_ms=engine.created_at_tolerance_ms)
    engine.stats.created_at_clamped += int(
        ((cols.created_at != 0) & (hb.created_at != cols.created_at)).sum()
    )
    # fault-back (tiering): shadowed keys re-enter HBM through the
    # conservative merge BEFORE their decide dispatch — this serial path
    # already runs on the engine thread, so probe + promote inline. The
    # putback fps feed the miss re-check's eligibility below.
    _, promote_putback = promote_rows(
        engine, shadow_probe(engine, hb.fp, now), now
    )
    n = hb.fp.shape[0]
    status = np.zeros(n, dtype=np.int32)
    limit_o = np.zeros(n, dtype=np.int64)
    remaining = np.zeros(n, dtype=np.int64)
    reset = np.zeros(n, dtype=np.int64)
    passes = _plan(engine, hb)
    has_casc = _has_cascade(hb)
    # the in-trace cascade fold needs the whole batch in one dispatch
    # (carrier adjacency) AND an engine whose program preserves row order;
    # multi-pass plans and mesh engines rely on the idempotent host fold
    # below instead
    casc_intrace = (
        has_casc and len(passes) == 1
        and getattr(engine, "supports_cascade_intrace", False)
    )
    for pi, p in enumerate(passes):
        np_ = len(p.rows)
        outs = dispatch(p.batch, np_, cascade=casc_intrace)
        if pi == 0 and engine.store is not None:
            # cache miss → consult the store and re-apply against hydrated
            # state (reference algorithms.go:45-51). Only pass 0 can miss:
            # later passes hit what pass 0 created.
            outs = _rehydrate_misses(engine, p.batch, np_, outs, now, dispatch)
        if pi == 0 and getattr(engine, "shadow", None) is not None:
            # tiering miss re-check (serial path runs on the engine
            # thread already — redispatch inline)
            outs, _ = _shadow_rehydrate(
                engine, p.batch, np_,
                outs, np.asarray(p.batch.active[:np_]), now,
                lambda fn: fn(), promote_putback,
            )
        s, l, r, t, dropped, _hit = outs
        if p.member_rows:
            # fan the aggregate's response out to every member row
            members = np.concatenate(p.member_rows)
            src = np.repeat(np.arange(np_), [len(m) for m in p.member_rows])
            status[members] = s[src]
            limit_o[members] = l[src]
            remaining[members] = r[src]
            reset[members] = t[src]
            err[members[dropped[src]]] = ERR_DROPPED
        else:
            rows = p.rows
            status[rows] = s[:np_]
            limit_o[rows] = l[:np_]
            remaining[rows] = r[:np_]
            reset[rows] = t[:np_]
            err[rows[dropped[:np_]]] = ERR_DROPPED
    engine.stats.checks += n
    if engine.store is not None:
        ok = (err == 0) & (hb.fp != 0)
        if ok.any():
            from gubernator_tpu.store import ChangeSet

            idx = np.nonzero(ok)[0]
            # one row per unique fp, last occurrence wins — the changeset is
            # a STATE delta, not a request log (reference OnChange carries
            # the stored item, store.go:66-70)
            rev = idx[::-1]
            _, pos = np.unique(hb.fp[rev], return_index=True)
            keep = rev[pos]
            engine.store.on_change(
                ChangeSet(
                    fps=hb.fp[keep],
                    created_at=now,
                    algo=hb.algo[keep],
                    status=status[keep].astype(np.int32),
                    limit=limit_o[keep],
                    remaining=remaining[keep],
                    reset_time=reset[keep],
                    duration=hb.duration[keep],
                    burst=hb.burst[keep],
                    stamp=hb.created_at[keep],
                )
            )
    if has_casc:
        # authoritative fold AFTER the Store hook (the store records each
        # KEY's own state; only the carrier's RESPONSE takes the verdict)
        _fold_cascades_host(hb.behavior, status, remaining, reset, err)
    return ResponseColumns(
        status=status, limit=limit_o, remaining=remaining,
        reset_time=reset, err=err,
    )


def _rehydrate_misses(engine, batch, n: int, outs, now: int, dispatch):
    """Re-hydrate device cache misses from the Store: install found rows and
    re-dispatch just those requests against the stored state, overwriting
    their phase-1 (fresh-create) responses. The phase-1 slot is overwritten
    by the install, so hits apply exactly once — against the hydrated item."""
    s, l, r, t, dropped, hit = outs
    active = np.asarray(batch.active[:n])
    miss = ~hit[:n] & active
    if not miss.any():
        return outs
    rows = np.nonzero(miss)[0]
    res = engine.store.get_many(np.asarray(batch.fp[rows]), now)
    if res is None:
        return outs
    found = np.asarray(res["found"])
    if not found.any():
        return outs
    fr = rows[found]
    engine.install_columns(
        fp=np.asarray(batch.fp[fr]),
        algo=np.asarray(res["algo"])[found],
        status=np.asarray(res["status"])[found],
        limit=np.asarray(res["limit"])[found],
        remaining=np.asarray(res["remaining"])[found],
        reset_time=np.asarray(res["reset_time"])[found],
        duration=np.asarray(res["duration"])[found],
        now_ms=now,
        burst=np.asarray(res["burst"])[found],
        stamp=np.asarray(res["stamp"])[found],
    )
    sub = HostBatch(*[f[fr] for f in batch])
    m = len(fr)
    prev_status = s[fr].copy()
    prev_dropped = dropped[fr].copy()
    s2, l2, r2, t2, d2, h2 = dispatch(sub, m)
    for dst, src in ((s, s2), (l, l2), (r, r2), (t, t2), (dropped, d2), (hit, h2)):
        dst[fr] = src[:m]
    # a rehydrated row is ONE miss-then-warm, not a miss plus a hit — undo
    # the re-dispatch's double counting (reference counts Store.Get warms as
    # plain misses); likewise drop phase-1 over_limit/dropped for rows the
    # hydrated re-run superseded
    engine.stats.cache_hits -= int(h2[:m].sum())
    engine.stats.cache_misses -= int((~h2[:m]).sum())
    engine.stats.over_limit -= int((prev_status == 1).sum())
    engine.stats.dropped -= int((prev_dropped & ~d2[:m]).sum())
    return s, l, r, t, dropped, hit


class PendingCheck:
    """In-flight pipelined check: every pass's kernel dispatch has been
    ISSUED (device arrays pending) but nothing fetched yet. Produced on the
    engine thread by `issue_check_columns` (after `prepare_check_columns`
    staged the single-transfer ingress arrays off-thread), consumed on a
    fetch thread by `finish_check_columns` — the split that lets host pack +
    transfer of dispatch N+1 overlap device execution and fetch of N."""

    __slots__ = (
        "hb", "err", "now", "passes", "clamped", "stacked", "rows", "mark",
        "casc", "casc_intrace", "promote", "promote_putback",
    )

    def __init__(
        self, hb, err, now, passes, clamped, rows=None, mark=None,
        casc=False, casc_intrace=False, promote=None,
    ):
        self.stacked = None  # same-shape pass outputs fused for ONE fetch
        self.hb = hb
        self.err = err
        self.now = now
        self.passes = passes  # [(Pass, n_rows, padded HostBatch, dev arr)]
        self.clamped = clamped
        # total request rows (fused wire batches carry no eager HostBatch)
        self.rows = rows if rows is not None else int(hb.fp.shape[0])
        # fingerprints this batch will touch — recorded into the checkpoint
        # epoch tracker at ISSUE time (engine thread), in the same job as
        # the launches, so a dirtied block can never fall between epochs
        # (ops/checkpoint.py ordering contract)
        self.mark = mark
        # cascade bookkeeping: `casc` = the batch carries level bits;
        # `casc_intrace` = the dispatches fold verdicts in-trace (single
        # pass), so the finish half only re-folds host-side after a
        # dropped-row retry invalidated a carrier
        self.casc = casc
        self.casc_intrace = casc_intrace
        # shadow fault-back rows (tiering): (fps, canonical rows) probed
        # OUT of the shadow on the prep thread, merged into HBM by
        # issue_check_columns on the engine thread BEFORE the launches —
        # the promote-stage ordering that keeps a promoted row's state
        # ahead of the decide that needs it (races stay conservative)
        self.promote = promote
        # fps the promote handed back to the shadow (the miss re-check's
        # eligibility set — set by issue_check_columns)
        self.promote_putback = None


class _LazyWireBatch:
    """Padded HostBatch materialized ONLY if the rare dropped-claim retry
    needs it — the fused wire path stages pre-packed lanes directly and
    skips pack_columns entirely on the common path. Duck-types the two
    HostBatch uses inside the pipelined retry: field iteration
    (`HostBatch(*[f[rows] for f in batch])`) and the padded row count."""

    __slots__ = ("_parts", "_now", "_tol", "rows", "_hb")

    def __init__(self, parts, now, tol, rows):
        self._parts = parts  # RequestColumns pieces, concat on demand
        self._now = now
        self._tol = tol
        self.rows = rows  # padded dispatch rows
        self._hb = None

    def _materialize(self) -> HostBatch:
        if self._hb is None:
            if len(self._parts) == 1:
                cols = self._parts[0]
            else:
                cols = RequestColumns(
                    *[
                        np.concatenate([p[k] for p in self._parts])
                        for k in range(len(self._parts[0]))
                    ]
                )
            hb, _ = pack_columns(cols, self._now, tolerance_ms=self._tol)
            self._hb = pad_batch(hb, self.rows)
        return self._hb

    def __iter__(self):
        return iter(self._materialize())

    def fp_view(self) -> np.ndarray:
        """Fingerprint column without materializing the HostBatch (the
        tiering miss re-check's cheap gate)."""
        if self._hb is not None:
            return np.asarray(self._hb.fp)
        if len(self._parts) == 1:
            return self._parts[0].fp
        return np.concatenate([p.fp for p in self._parts])


def _padded_rows(batch) -> int:
    """Padded dispatch rows of a pass batch (HostBatch or lazy wire batch)."""
    if isinstance(batch, HostBatch):
        return int(batch.fp.shape[0])
    return batch.rows


def _assemble_wire_parts(engine, parts, now_ms=None, pad_to=None):
    """Shared gating + single-scatter grid assembly of the fused wire
    paths (direct front door and ring slots): pre-packed native lanes are
    scattered into ONE padded compact ingress grid. Returns None when the
    batch needs the general columns path (engine not wire-capable,
    non-encodable rows, duplicate fingerprints, created_at skew beyond the
    ±511 ms delta budget, Store attached, or rows exceeding `pad_to`),
    else (grid, cols_list, err, now, n, act_fp, clamped, casc, tol, pad).
    `pad_to` fixes the padded width (the ring's static slot shape); the
    default pads to the bucketed dispatch size."""
    if not getattr(engine, "supports_wire_ingress", False):
        return None
    if engine.store is not None or not engine.supports_pipeline:
        return None
    if not all(bool(p.encodable.all()) for p in parts):
        return None
    cols_list = [p.cols for p in parts]
    n = sum(c.fp.shape[0] for c in cols_list)
    if n == 0 or (pad_to is not None and n > pad_to):
        return None
    one = len(cols_list) == 1
    fp = cols_list[0].fp if one else np.concatenate([c.fp for c in cols_list])
    err = (
        cols_list[0].err.copy()
        if one
        else np.concatenate([c.err for c in cols_list])
    )
    active = err == 0
    n_act = int(active.sum())
    if n_act == 0:
        return None  # all-error batch: let the columns path produce it
    act_fp = fp[active]
    # unique-fingerprint kernel contract: duplicate keys need the host pass
    # planner (sequential same-key semantics) — general path
    if np.unique(act_fp).size != n_act:
        return None
    from gubernator_tpu.ops import wire as wire_mod
    from gubernator_tpu.ops.batch import created_at_tolerance_ms

    now = now_ms if now_ms is not None else ms_now()
    created = (
        cols_list[0].created_at
        if one
        else np.concatenate([c.created_at for c in cols_list])
    )
    tol = engine.created_at_tolerance_ms
    if tol is None:
        tol = created_at_tolerance_ms()
    stamped = np.where(created == 0, now, created)
    clipped = np.clip(stamped, now - tol, now + tol)
    clamped = int((clipped != stamped).sum())
    base = int(clipped[int(np.argmax(active))])
    delta = clipped - base
    if (
        (delta[active] < -wire_mod.DELTA_BIAS)
        | (delta[active] > wire_mod.DELTA_BIAS - 1)
    ).any():
        return None
    pad = pad_to if pad_to is not None else _pad_size(n)
    grid = wire_mod.assemble_wire_grid(
        [p.lanes for p in parts], clipped, base, pad, active
    )
    # cascade batches normally take the pb path (the native parser routes
    # them there), but an engine-level caller may assemble level-bit lanes
    # directly — the unique-fp contract above makes them single-pass, so
    # the in-trace fold is always sound here
    casc = wire_mod.grid_has_cascade(grid, n)
    return grid, cols_list, err, now, n, act_fp, clamped, casc, tol, pad


def _wire_pending(engine, assembled, staged):
    """PendingCheck over one assembled wire grid (direct or ring slot) —
    the object both finish halves consume unchanged."""
    _grid, cols_list, err, now, n, act_fp, clamped, casc, tol, pad = assembled
    lazy = _LazyWireBatch(cols_list, now, tol, pad)
    p = Pass(rows=np.arange(n), batch=lazy, member_rows=[])
    return PendingCheck(
        hb=lazy, err=err, now=now, passes=[[p, n, lazy, staged]],
        clamped=clamped, rows=n, mark=act_fp, casc=casc, casc_intrace=casc,
        promote=shadow_probe(engine, act_fp, now),
    )


def prepare_check_wire(engine, parts, now_ms=None) -> "PendingCheck | None":
    """Fused front-door preparation: pre-packed native wire lanes
    (service/wire.WireBatch pieces) are scattered into ONE staged compact
    ingress grid — the request bytes were traversed once by the parser and
    this scatter is the only further touch. Returns a PendingCheck for the
    standard issue/finish halves, or None when the batch needs the general
    columns path — the fallback is semantically identical, it just pays
    the full pack."""
    assembled = _assemble_wire_parts(engine, parts, now_ms=now_ms)
    if assembled is None:
        return None
    from gubernator_tpu.ops import wire as wire_mod

    grid, n = assembled[0], assembled[4]
    staged = engine.stage_wire(
        grid, wire_mod.grid_math_mode(grid, n), cascade=assembled[7]
    )
    return _wire_pending(engine, assembled, staged)


class RingSlotPrep:
    """One ring slot's prepared dispatch (prep pool, no engine state): the
    assembled HOST-side wire grid padded to the ring's FIXED slot width —
    the device slot buffer's static shape — plus the PendingCheck the
    standard finish half consumes once the fused drain's egress bank is
    fetched. The grid is staged into the device ring by the engine thread
    (ops/ring_drain.DeviceRing.stage, serialized with the drain launches),
    never device_put here; `math`/`cascade` are the static dispatch modes
    the ring groups consecutive slots by."""

    __slots__ = ("grid", "math", "cascade", "pending")

    def __init__(self, grid, math, cascade, pending):
        self.grid = grid
        self.math = math
        self.cascade = cascade
        self.pending = pending


def prepare_ring_slot(
    engine, parts, width: int, now_ms=None
) -> "RingSlotPrep | None":
    """Ring-slot variant of prepare_check_wire: same gating, same grid
    assembly, but padded to the ring's fixed `width`. None routes the
    chunk to the host per-slot path (which pays a launch but is
    byte-identical) — including chunks wider than the slot."""
    assembled = _assemble_wire_parts(engine, parts, now_ms=now_ms,
                                     pad_to=width)
    if assembled is None:
        return None
    from gubernator_tpu.ops import wire as wire_mod

    grid, n, casc = assembled[0], assembled[4], assembled[7]
    pending = _wire_pending(engine, assembled, None)
    return RingSlotPrep(grid, wire_mod.grid_math_mode(grid, n), casc,
                        pending)


def prepare_check_columns(engine, cols, now_ms=None) -> PendingCheck:
    """Preparation half of the pipelined serving path (any thread — touches
    no engine state): pack, clamp, plan same-key passes, and stage each
    pass's SINGLE packed ingress transfer on-device via the engine's
    `stage_pass` (LocalEngine: (12, B) array; ShardedEngine: routed
    (D, 12, b_local) grid).

    Engines with batch shapes the generic split cannot express (the
    mesh-global engine's replica/owner fork) provide `prepare_columns`,
    returning their own pending object — or None to fall through to the
    generic path for batches without the special rows."""
    hook = getattr(engine, "prepare_columns", None)
    if hook is not None:
        alt = hook(cols, now_ms=now_ms)
        if alt is not None:
            return alt
    now = now_ms if now_ms is not None else ms_now()
    hb, err = pack_columns(cols, now, tolerance_ms=engine.created_at_tolerance_ms)
    clamped = int(
        ((cols.created_at != 0) & (hb.created_at != cols.created_at)).sum()
    )
    plan = _plan(engine, hb)
    casc = _has_cascade(hb)
    casc_intrace = (
        casc and len(plan) == 1
        and getattr(engine, "supports_cascade_intrace", False)
    )
    passes = []
    for p in plan:
        n = len(p.rows)
        batch, staged = engine.stage_pass(p.batch, n, cascade=casc_intrace)
        passes.append([p, n, batch, staged])
    return PendingCheck(
        hb=hb, err=err, now=now, passes=passes, clamped=clamped, mark=hb.fp,
        casc=casc, casc_intrace=casc_intrace,
        promote=shadow_probe(engine, hb.fp, now),
    )


def issue_check_columns(engine, pending: PendingCheck) -> PendingCheck:
    """Engine-thread half: launch every staged pass WITHOUT fetching.
    Later passes depend only on device state, not fetched outputs, so the
    whole chain enqueues back-to-back; each entry's staged ingress is
    replaced by its pending (un-fetched) output handle."""
    if not isinstance(pending, PendingCheck):  # engine-specific pending
        return engine.issue_pending(pending)
    if pending.promote is not None:
        # shadow fault-back lands through the conservative merge BEFORE
        # this batch's launches (engine thread — merge_rows marks the
        # checkpoint tracker itself)
        _, pending.promote_putback = promote_rows(
            engine, pending.promote, pending.now
        )
        pending.promote = None
    if pending.mark is not None and getattr(engine, "ckpt", None) is not None:
        # dirty-block marking for incremental checkpoints: same engine-
        # thread job as the launches below (ops/checkpoint.py contract)
        engine.ckpt.mark(pending.mark)
    for entry in pending.passes:
        _p, _n, batch, staged = entry
        entry[3] = engine.issue_staged(staged, _padded_rows(batch))
    pending.stacked = _stack_pass_outputs(
        [_pending_out(entry[3]) for entry in pending.passes]
    )
    return pending


# Per-pass pending handles differ by engine: LocalEngine issues a bare
# output array, ShardedEngine a (staged, out) tuple. These two helpers are
# the only place that distinction exists.
def _pending_out(pend):
    return pend[1] if isinstance(pend, tuple) else pend


def _pending_with_out(pend, out):
    return (pend[0], out) if isinstance(pend, tuple) else out


# one extra launch that turns N per-pass output fetches into ONE — on
# platforms where every device->host fetch is a serialized round trip (the
# tunneled dev TPU: ~100 ms each), a multi-pass batch (hot-key herds plan up
# to max_exact sequential passes) otherwise pays N round trips per request
_stack_outs = jax.jit(lambda xs: jnp.stack(xs))


def _stack_pass_outputs(outs):
    """Fuse same-shape pass outputs into one stacked device array (None when
    there is nothing to fuse or shapes differ — hot-key herds produce
    uniformly tiny passes, the case that matters; mixed-shape pass lists
    would compile a new stack per combination, so they stay per-pass)."""
    if len(outs) < 2:
        return None
    shape = getattr(outs[0], "shape", None)
    if shape is None or any(getattr(o, "shape", None) != shape for o in outs[1:]):
        return None
    # dtype must match too: a batch can mix compact-wire (int32) and
    # full-width (int64) passes when one pass isn't wire-encodable, and
    # stacking would silently promote the int32 outputs to int64 —
    # destroying the dtype tag the host decoder dispatches on
    dtype = outs[0].dtype
    if any(o.dtype != dtype for o in outs[1:]):
        return None
    return _stack_outs(tuple(outs))


def finish_check_columns(
    engine, pending: PendingCheck, fixup
) -> "tuple[ResponseColumns, EngineStats]":
    """Fetch-thread half: materialize each pass's packed output and assemble
    the response. The rare feedback path — claim drops needing a re-dispatch
    — runs through `fixup(fn)`, which executes fn ON THE ENGINE THREAD and
    returns its result (table mutations stay single-writer). Returns the
    response plus a stats delta for the caller to apply on the engine
    thread. Store-configured engines never take this path (EngineRunner
    routes them to the serial one): the Store contract needs rehydrates and
    write-throughs ordered against every same-key dispatch, which a
    pipeline with interleaved chunks cannot guarantee."""
    if not isinstance(pending, PendingCheck):  # engine-specific pending
        return engine.finish_pending(pending, fixup)
    if pending.stacked is not None:
        # ONE fetch materializes every pass's output; hand each pass its
        # already-fetched slice (finish_staged's np.asarray is then a no-op)
        fetched = np.asarray(pending.stacked)
        for i, entry in enumerate(pending.passes):
            entry[3] = _pending_with_out(entry[3], fetched[i])
    err, now = pending.err, pending.now
    n = pending.rows
    status = np.zeros(n, dtype=np.int32)
    limit_o = np.zeros(n, dtype=np.int64)
    remaining = np.zeros(n, dtype=np.int64)
    reset = np.zeros(n, dtype=np.int64)
    delta = EngineStats(created_at_clamped=pending.clamped, checks=n)
    retried_any = False
    for pi, (p, np_, batch, pend) in enumerate(pending.passes):
        (s, l, r, t, dropped, hit), st, uncounted = engine.finish_staged(
            pend, np_
        )
        delta.cache_hits += st[0]
        delta.cache_misses += st[1]
        delta.over_limit += st[2]
        delta.evicted_unexpired += st[3]
        delta.dispatches += 1
        if dropped.any():
            # contended-claim retries mutate the table → engine thread;
            # _redispatch_rows counts dispatches/evictions only, exactly
            # like the sync path's retry loop
            retried_any = True
            rows = np.nonzero(dropped)[0]

            def retry(rows=rows, batch=batch, uncounted=uncounted):
                # padding conventions are the engine's own (LocalEngine pads
                # to _pad_size; ShardedEngine needs no row padding). Rows the
                # phase-1 pass never processed (a2a capacity drops) have
                # their outcome counted by the retry.
                sub = HostBatch(*[f[rows] for f in batch])
                unc = uncounted[rows] if uncounted is not None else None
                return engine._redispatch_rows(sub, len(rows), uncounted=unc)

            s2, l2, r2, t2, d2, h2 = fixup(retry)
            s[rows], l[rows], r[rows], t[rows] = s2, l2, r2, t2
            dropped[rows] = d2
            hit[rows] = h2
        if pi == 0 and getattr(engine, "shadow", None) is not None:
            # tiering miss re-check: promote + re-dispatch run on the
            # engine thread through the same fixup the dropped-claim
            # retries use. Fused wire batches carry no HostBatch activity
            # mask — their staged-inactive rows are exactly the error
            # rows (prepare_check_wire), so err==0 is the mask.
            if isinstance(batch, HostBatch):
                act = np.asarray(batch.active[:np_])
            else:
                act = (err == 0)[:np_]
            (s, l, r, t, dropped, hit), changed = _shadow_rehydrate(
                engine, batch, np_, (s, l, r, t, dropped, hit), act,
                pending.now, fixup, pending.promote_putback,
            )
            retried_any = retried_any or changed
        if p.member_rows:
            members = np.concatenate(p.member_rows)
            src = np.repeat(np.arange(np_), [len(m) for m in p.member_rows])
            status[members] = s[src]
            limit_o[members] = l[src]
            remaining[members] = r[src]
            reset[members] = t[src]
            err[members[dropped[src]]] = ERR_DROPPED
        else:
            rows = p.rows
            status[rows] = s[:np_]
            limit_o[rows] = l[:np_]
            remaining[rows] = r[:np_]
            reset[rows] = t[:np_]
            err[rows[dropped[:np_]]] = ERR_DROPPED
    if pending.casc and (retried_any or not pending.casc_intrace):
        # the in-trace fold (when it ran) predates any dropped-row retry;
        # the idempotent host fold makes the carriers authoritative again.
        # Fused wire batches materialize their HostBatch only on this rare
        # path (cascade batch AND a claim drop).
        hbm = pending.hb
        if not isinstance(hbm, HostBatch):
            hbm = hbm._materialize()
        _fold_cascades_host(hbm.behavior, status, remaining, reset, err)
    rc = ResponseColumns(
        status=status, limit=limit_o, remaining=remaining,
        reset_time=reset, err=err,
    )
    return rc, delta


class LocalEngine:
    """One device-resident rate-limit table + its dispatch loop.

    `decide_fn`/`table` injection exists for the differential test oracle
    (tests/oracle/ keeps the v1 plane kernel); production always runs the v2
    packed-row kernel (ops/kernel2.py).
    """

    supports_grow = True  # resize()/maybe_grow() are real (cf. ShardedEngine)
    supports_pipeline = True  # prepare/issue/finish split

    def __init__(
        self,
        capacity: int = 50_000,
        max_exact_passes: int = 8,
        write_mode: Optional[str] = None,
        decide_fn: Optional[Callable] = None,
        table=None,
        created_at_tolerance_ms: Optional[int] = None,
        store=None,
        wire: Optional[str] = None,
        layout: Optional[str] = None,
        probe: Optional[str] = None,
        walk: Optional[str] = None,
    ):
        from gubernator_tpu.ops.layout import resolve_layout
        from gubernator_tpu.ops.plan import (
            default_probe_kernel,
            default_walk_kernel,
        )
        from gubernator_tpu.ops.wire import default_wire_mode

        # slot layout (ops/layout.py): "full" (bit-compatible default),
        # "gcra32"/"token32" (32 B packed rows for single-algorithm
        # tables), or "auto"/"packed" policies; None reads
        # GUBER_SLOT_LAYOUT. Off-family traffic migrates a packed table to
        # full in place (one unpack) rather than erroring.
        if table is None:
            self._layout = resolve_layout(layout)
        else:
            # injected tables carry their own layout; the v1 oracle's
            # legacy Table has none (its plane layout predates descriptors)
            from gubernator_tpu.ops.layout import FULL

            self._layout = getattr(table, "layout", FULL)
        self.table = (
            table if table is not None
            else new_table2(capacity, layout=self._layout)
        )
        # host↔device wire format: "compact" ships 5-lane int32 ingress +
        # int32 egress (ops/wire.py, the TPU default — GUBER_WIRE_COMPACT),
        # "full" the 12-lane int64 grids (the parity oracle). Per-dispatch
        # encodability still falls compact batches back to full-width.
        if wire is not None and wire not in ("compact", "full"):
            raise ValueError(f"wire must be 'compact' or 'full', got {wire!r}")
        self.wire = wire or default_wire_mode()
        # one write mode for every dispatch: the block-sparse Pallas write
        # on TPU (kernel2.resolve_write falls big-batch shapes back to the
        # full sweep), XLA scatter on CPU meshes. A batch-size crossover to
        # the SCATTER used to exist on a "scatter costs ∝ batch" assumption
        # — measured FALSE
        # at scale (exp/exp_crossover.py, v5e, 1 GiB table: scatter ≈ 58 ms
        # at EVERY batch size 2K-16K vs sweep 4.1-4.9 ms), so it picked a
        # 13× slower path exactly where latency mattered.
        self.write_mode = write_mode or default_write_mode()
        # table-walk kernel for decide dispatches (GUBER_PROBE_KERNEL /
        # probe=): "xla" — the row gather + sweep/sparse write every PR
        # before the megakernel shipped — or "pallas", the fused
        # probe→decide→write kernel (ops/pallas_probe.py) streaming the
        # touched rows through VMEM with double-buffered DMA. A static jit
        # arg like write/math, so both kernels can serve side by side.
        if probe is not None and probe not in ("xla", "pallas"):
            raise ValueError(f"probe must be 'xla' or 'pallas', got {probe!r}")
        self.probe_mode = probe or default_probe_kernel()
        # table-walk kernel for the NON-decide walks — GLOBAL installs,
        # region/handoff merges, tiering promotes (GUBER_WALK_KERNEL /
        # walk=): "xla" two-pass gather + write, or "pallas" — the fused
        # probe→install/merge→write megakernel sharing the decide
        # kernel's claim/carry/write machinery (ops/pallas_probe.py).
        # Independent of probe_mode: serving latency and sync throughput
        # flip separately.
        if walk is not None and walk not in ("xla", "pallas"):
            raise ValueError(f"walk must be 'xla' or 'pallas', got {walk!r}")
        self.walk_mode = walk or default_walk_kernel()
        self._decide_fn = decide_fn
        # oracle engines return unpacked outputs; the begin/finish split
        # assumes the packed single-fetch layout
        self.supports_pipeline = decide_fn is None
        self.max_exact_passes = max_exact_passes
        self.max_claim_retries = 3
        # per-engine clock-skew bound; None = the ops.batch process default
        self.created_at_tolerance_ms = created_at_tolerance_ms
        # optional write-through hook (gubernator_tpu.store.Store): fires a
        # ChangeSet of persisted fingerprints after every check — the
        # Store.OnChange analog (reference store.go:63-78, algorithms.go:148)
        self.store = store
        # incremental-checkpoint epoch tracker (ops/checkpoint.EpochTracker),
        # attached by service/checkpoint.CheckpointManager when the daemon
        # runs with GUBER_CHECKPOINT_INTERVAL_MS > 0; None = zero marking
        # cost on the serving path
        self.ckpt = None
        # hot-set tiering (gubernator_tpu/tier/): host-RAM ShadowTable
        # attached by the daemon's TierManager (or tests). Non-None flips
        # the dispatch entries' static `evictees` flag — victim rows ride
        # the fetched outputs home and demote instead of vanishing — and
        # arms the fault-back probe in the serving paths. None = zero
        # cost, bit-identical dispatch graphs.
        self.shadow = None
        self.stats = EngineStats()
        self._seen_pad_sizes: set = set()  # compiled batch shapes (for resize warm)
        # reason string when a failed donated launch left device state
        # suspect (see GlobalShardedEngine._requeue_popped); surfaces as
        # health_check "unhealthy". Never set on the single-device path
        # today, but the daemon reads it engine-agnostically.
        self.poisoned: Optional[str] = None

    def _mark_dirty(self, fps) -> None:
        """Checkpoint hook: record the touched fingerprints' blocks in the
        epoch tracker (ops/checkpoint.py). Called on the engine thread in
        the same job as the mutation it precedes, so marks and takes
        interleave FIFO and no dirtied block falls between epochs."""
        if self.ckpt is not None:
            self.ckpt.mark(np.asarray(fps))

    # --------------------------------------------------------------- tiering

    @property
    def _evictees(self) -> bool:
        """Whether dispatches compile the evictee sidecar (a shadow tier
        is attached; the v1 oracle's unpacked outputs carry no sidecar)."""
        return self.shadow is not None and self._decide_fn is None

    def attach_shadow(self, shadow) -> None:
        """Arm hot-set tiering: evict capture + fault-back from `shadow`
        (tier.ShadowTable). Call before serving — flipping it mid-flight
        only costs recompiles, the sidecar decode keys off the flag at
        each dispatch's own issue."""
        self.shadow = shadow

    def _harvest_evictees(self, host_arr: np.ndarray) -> None:
        """Demote-on-evict: decode the dispatch's evictee sidecar and
        append the victim rows to the shadow. `host_arr` must come from a
        dispatch issued with evictees=True. Runs wherever the output was
        fetched (engine thread on the serial path, a fetch worker on the
        pipelined one) — ShadowTable is lock-guarded. Expiry filtering is
        left to promote time (`take` drops dead rows against the request
        timeline; wall clock here could disagree with a test's synthetic
        clock)."""
        if self.shadow is None:
            return
        # the stats row's evicted_unexpired cell gates the decode: the
        # common hot-set dispatch evicts nothing and pays ONE cell read
        if int(host_arr[-2, 3]) == 0:
            return
        from gubernator_tpu.ops.kernel2 import unpack_evictees

        fps, rows = unpack_evictees(host_arr)
        if fps.shape[0]:
            self.shadow.offer(fps, rows, now_ms=0, reason="evict")

    def extract_idle(self, now_ms: int, idle_ms: int,
                     max_rows: int = 1 << 16):
        """Live rows idle past `idle_ms`: (fps (N,) i64, slots (N,
        F_layout) i32), N ≤ max_rows — the demote-on-idle sweep's read
        half (EngineRunner.tier_demote_idle pairs it with tombstone_fps
        in ONE engine-thread job so no decide interleaves)."""
        from gubernator_tpu.ops.table2 import extract_idle_rows

        return extract_idle_rows(
            self.table.rows, now_ms, idle_ms, layout=self.table.layout,
            max_rows=max_rows,
        )

    # ---------------------------------------------------------- slot layout

    def _batch_needs_full(self, math: str, hb=None) -> bool:
        return batch_needs_full_layout(self.table.layout, math, hb)

    def _effective_math(self, hb: HostBatch) -> str:
        return effective_math(self.table.layout, hb)

    def migrate_layout_full(self, reason: str = "off-family traffic") -> bool:
        """Migrate a packed table to the canonical full layout in place —
        one jitted row unpack, engine thread only. Returns True when a
        migration actually happened. The one-way direction is deliberate:
        packed layouts are a boot-time bet on single-algorithm traffic,
        and losing the bet must degrade to correct-and-bigger, never to
        wrong bytes."""
        from gubernator_tpu.ops.layout import FULL

        lay = self.table.layout
        if lay is FULL:
            return False
        import logging

        logging.getLogger("gubernator_tpu.engine").warning(
            "migrating table layout %s -> full (%s)", lay.name, reason
        )
        rows_full = jax.jit(lay.unpack_rows)(self.table.rows)
        self.table = Table2(rows=rows_full, layout=FULL)
        self._layout = FULL
        self.stats.layout_migrations += 1
        return True

    def _decide_packed(self, hb: HostBatch, cascade: bool = False) -> np.ndarray:
        """One dispatch → ONE host transfer each way: compact 5-lane int32
        wire block (or full packed (12, B) ingress) in, compact int32 (or
        packed (B+2, 4) i64) output fetched. Updates self.table; returns
        the host array (unpack_outputs dispatches on its dtype). `cascade`
        compiles the in-trace verdict fold into the dispatch (single-pass
        batches with level bits only — the fold needs carrier adjacency)."""
        self._mark_dirty(hb.fp)
        if self._decide_fn is not None:
            # oracle engines return unpacked outputs; pack on device for the
            # same downstream shape
            self.table, resp, stats = self._decide_fn(self.table, to_device(hb))
            return np.asarray(pack_outputs(resp, stats, hb.behavior))
        math = self._effective_math(hb)
        if self._batch_needs_full(math, hb):
            self.migrate_layout_full()
        dev, wired = self._stage_ingress(hb)
        out = np.asarray(
            self._issue_from_dev(
                dev, int(hb.fp.shape[0]), math, wired, cascade
            )
        )
        if self._evictees:
            # serial path: the fetch happened right here — demote the
            # victims before the caller decodes responses
            self._harvest_evictees(out)
        return out

    def _stage_ingress(self, batch: HostBatch):
        """Stage ONE ingress array for a padded batch: the compact wire
        block when the engine is in compact mode and the batch is
        representable (ops/wire.wire_encodable — Gregorian rows, oversize
        hits/durations, skewed created_at fall back), else the full-width
        grid. Returns (device array, compact?)."""
        import jax

        if self.wire == "compact":
            from gubernator_tpu.ops import wire as wire_mod

            base = wire_mod.pick_base(batch)
            if wire_mod.wire_encodable(batch, base):
                return (
                    jax.device_put(wire_mod.pack_wire_full(batch, base)),
                    True,
                )
        return jax.device_put(pack_host_batch(batch)), False

    def _issue_from_dev(
        self, dev_arr, batch_rows: int, math: str, wired: bool = False,
        cascade: bool = False,
    ) -> "jax.Array":
        """Issue one dispatch from a staged ingress array WITHOUT fetching:
        the table advances immediately; the packed output is fetched later
        on a fetch thread while this thread launches the next dispatch."""
        ev = self._evictees
        if wired:
            from gubernator_tpu.ops.wire import decide2_wire_cols

            self.table, packed = decide2_wire_cols(
                self.table, dev_arr, write=self.write_mode, math=math,
                cascade=cascade, probe=self.probe_mode, evictees=ev,
            )
            return packed
        self.table, packed = decide2_packed_cols(
            self.table, dev_arr, write=self.write_mode, math=math,
            cascade=cascade, probe=self.probe_mode, evictees=ev,
        )
        return packed

    # ------------------------------------------------- pipelined protocol
    # stage_pass (any thread) → issue_staged (engine thread) → finish_staged
    # (fetch thread); the packed single-transfer layout stays private to the
    # engine so mesh engines can substitute routed grids (parallel/sharded.py).

    def stage_pass(self, pass_batch: HostBatch, n: int, cascade: bool = False):
        """(padded batch, staged ingress array + static math/wire/cascade
        modes + layout-mismatch flag) for one unique-fp pass."""
        batch = pad_batch(pass_batch, _pad_size(n))
        math = self._effective_math(batch)
        dev, wired = self._stage_ingress(batch)
        return batch, (
            dev, math, wired, cascade, self._batch_needs_full(math, batch)
        )

    @property
    def supports_cascade_intrace(self) -> bool:
        """Single-device dispatches preserve batch row order, so the
        kernel-side cascade fold (fold_cascade_packed) is sound here; mesh
        engines route/exchange rows and leave the fold to the host
        (_fold_cascades_host). Oracle engines (decide_fn) predate the
        packed entries and never fold in-trace."""
        return self._decide_fn is None

    @property
    def supports_wire_ingress(self) -> bool:
        """Whether the fused front-door path (prepare_check_wire: native
        parser lanes staged straight into a compact grid) may target this
        engine. Compact-wire single-device engines only — full-width mode
        stays the byte-for-byte parity oracle, and mesh engines stage routed
        per-shard grids the front door cannot pre-assemble."""
        return self.wire == "compact" and self._decide_fn is None

    def stage_wire(self, grid: np.ndarray, math: str, cascade: bool = False):
        """Stage a fused front-door grid (ops/wire.assemble_wire_grid
        output) — same staged tuple as stage_pass's, issued by
        issue_staged unchanged. Wire grids carry no Gregorian rows
        (wire_encodable excludes them) and their algorithm family is
        implied by the math mode, so the layout check needs no batch."""
        import jax

        return (
            jax.device_put(grid), math, True, cascade,
            self._batch_needs_full(math),
        )

    def issue_staged(self, staged, batch_rows: int):
        dev, math, wired, cascade, needs_full = staged
        if needs_full:
            # engine thread — the only thread allowed to swap the table
            self.migrate_layout_full()
        self._seen_pad_sizes.add(batch_rows)
        self.last_dispatch_rows = batch_rows
        return self._issue_from_dev(dev, batch_rows, math, wired, cascade)

    def hbm_bytes_per_decision_estimate(self) -> float:
        """Modeled HBM bytes the table walk moves per decision at the last
        dispatch geometry (ops/pallas_probe.hbm_bytes_per_decision) — the
        gubernator_table_hbm_bytes_per_decision gauge and the
        /v1/debug/pipeline roofline field."""
        from gubernator_tpu.ops.pallas_probe import hbm_bytes_per_decision

        rows = getattr(self, "last_dispatch_rows", 0)
        if not rows:
            rows = max(self._seen_pad_sizes, default=4096)
        return hbm_bytes_per_decision(
            self.table.layout, rows, int(self.table.rows.shape[-2]),
            self.write_mode, getattr(self, "probe_mode", "xla"),
        )

    def finish_staged(self, pending, n: int):
        """Materialize one pass's packed output → ((s, l, r, t, dropped,
        hit), (hits, misses, over, evicted), uncounted). The single-device
        kernel probes every row, so `uncounted` is always None here (cf.
        ShardedEngine's a2a capacity drops). With a shadow attached the
        fetched array carries the evictee sidecar — harvested here, on
        the fetch thread, before the response decode."""
        arr = np.asarray(pending)
        if self._evictees:
            self._harvest_evictees(arr)
        outs, st = unpack_outputs(arr, n)
        return outs, st, None

    def _redispatch_rows(self, batch, n: int, uncounted=None):
        """Re-dispatch rows whose phase-1 claim dropped (pipelined retry):
        accounts dispatches/evictions/final drops only — hits/misses/over
        were already counted by the dropped phase-1 pass, exactly like the
        sync path's retry loop. `uncounted` is a mesh-engine concern
        (ShardedEngine): ignored here."""
        batch = pad_batch(batch, _pad_size(n))
        (status, limit, remaining, reset, dropped, hit), st = unpack_outputs(
            self._decide_packed(batch), n
        )
        self.stats.dispatches += 1
        self.stats.evicted_unexpired += st[3]
        # this first dispatch already IS retry #1 of the dropped phase-1
        # rows, so the loop allows max_claim_retries-1 more — same total
        # attempt budget as the sync path
        dropped = self._retry_dropped(
            batch, n, status, limit, remaining, reset, dropped, hit, retries=1
        )
        self.stats.dropped += int(dropped.sum())
        return status, limit, remaining, reset, dropped, hit

    def _retry_dropped(
        self, batch, n, status, limit, remaining, reset, dropped, hit, retries
    ):
        """Shared claim-drop retry loop: re-dispatch dropped rows (evictions +
        dispatches counted only) until persisted or the attempt budget runs
        out. Mutates the response arrays in place; returns the final dropped
        mask."""
        while dropped.any() and retries < self.max_claim_retries:
            rows = np.nonzero(dropped)[0]
            sub = HostBatch(*[f[:n][rows] for f in batch])
            sub = pad_batch(sub, _pad_size(len(rows)))
            m = len(rows)
            (s2, l2, r2, t2, d2, h2), st = unpack_outputs(
                self._decide_packed(sub), m
            )
            self.stats.dispatches += 1
            self.stats.evicted_unexpired += st[3]
            status[rows], limit[rows], remaining[rows], reset[rows] = s2, l2, r2, t2
            hit[rows] = h2
            nd = np.zeros(n, dtype=bool)
            nd[rows] = d2
            dropped = nd
            retries += 1
        return dropped

    def check(
        self,
        requests: Sequence[RateLimitRequest],
        now_ms: Optional[int] = None,
    ) -> List[RateLimitResponse]:
        """Apply a batch; responses come back in request order (the API
        contract, reference gubernator.proto:58-61). Object-API wrapper over
        the columns fast path."""
        if not requests:
            return []
        from gubernator_tpu.types import retry_after_ms

        now = now_ms if now_ms is not None else ms_now()
        cols = columns_from_requests(requests)
        rc = self.check_columns(cols, now_ms=now)
        return [
            RateLimitResponse(
                status=int(rc.status[i]),
                limit=int(rc.limit[i]),
                remaining=int(rc.remaining[i]),
                reset_time=int(rc.reset_time[i]),
                error=ERROR_STRINGS[int(rc.err[i])],
                retry_after_ms=retry_after_ms(
                    int(rc.status[i]), int(rc.reset_time[i]), now
                ),
            )
            for i in range(len(requests))
        ]

    def check_columns(
        self,
        cols: RequestColumns,
        now_ms: Optional[int] = None,
    ) -> ResponseColumns:
        """Vectorized serving path: columns in, columns out (request order).
        Per-request validation errors come back as ERR_* codes instead of
        failing the batch (reference gubernator.go:215-237)."""

        def dispatch(pass_batch, n_rows: int, cascade: bool = False):
            batch = pad_batch(pass_batch, _pad_size(n_rows))
            return self._dispatch_with_retry(batch, n_rows, cascade)

        return serve_columns(self, cols, now_ms, dispatch)

    def _dispatch_with_retry(self, batch, n: int, cascade: bool = False):
        """Run one unique-fp pass; rows the claim auction dropped (contended
        bucket within a single dispatch) are re-dispatched — the decision is
        only authoritative once persisted. Rows still unpersisted after
        `max_claim_retries` surface a per-item error (`ERR_NOT_PERSISTED`)."""
        self._seen_pad_sizes.add(int(batch.fp.shape[0]))
        (status, limit, remaining, reset, dropped, hit), st = unpack_outputs(
            self._decide_packed(batch, cascade), n
        )
        self.stats.cache_hits += st[0]
        self.stats.cache_misses += st[1]
        self.stats.over_limit += st[2]
        self.stats.evicted_unexpired += st[3]
        self.stats.dispatches += 1
        dropped = self._retry_dropped(
            batch, n, status, limit, remaining, reset, dropped, hit, retries=0
        )
        # only rows still unpersisted after retries count as dropped
        self.stats.dropped += int(dropped.sum())
        return status, limit, remaining, reset, dropped, hit

    # ------------------------------------------------------------ peer plane

    def install_columns(
        self,
        fp: np.ndarray,
        algo: np.ndarray,
        status: np.ndarray,
        limit: np.ndarray,
        remaining: np.ndarray,
        reset_time: np.ndarray,
        duration: np.ndarray,
        now_ms: Optional[int] = None,
        burst: Optional[np.ndarray] = None,
        stamp: Optional[np.ndarray] = None,
        aux: Optional[np.ndarray] = None,
        rem_store: Optional[np.ndarray] = None,
    ) -> int:
        """Install owner-authoritative GLOBAL statuses as fresh items — the
        UpdatePeerGlobals receive path (reference gubernator.go:434-474).
        Returns the number installed. `burst`/`stamp` default to the wire
        path's lossy rebuild (Burst=Limit, stamp=now — exactly the
        reference's, gubernator.go:434-474); the Store rehydrate path passes
        the stored values for full fidelity. `aux`/`rem_store` carry
        sliding-window broadcast fidelity (previous-window count and the
        stored-style remaining) when the wire provides them."""
        if self._decide_fn is not None:
            raise RuntimeError("install_columns unsupported on the v1 oracle engine")
        now = now_ms if now_ms is not None else ms_now()
        n = fp.shape[0]
        if n == 0:
            return 0
        if burst is None:
            burst = np.asarray(limit, dtype=np.int64)
        if stamp is None:
            stamp = np.full(n, now, dtype=np.int64)
        if not self.table.layout.supports_algos(algo):
            self.migrate_layout_full("install of off-family algorithms")
        self._mark_dirty(fp)
        size = _pad_size(n)

        def pad(a, dtype):
            out = np.zeros(size, dtype=dtype)
            out[:n] = a
            return out

        import jax.numpy as jnp

        inst = InstallBatch(
            fp=jnp.asarray(pad(fp, np.int64)),
            algo=jnp.asarray(pad(algo, np.int32)),
            status=jnp.asarray(pad(status, np.int32)),
            limit=jnp.asarray(pad(limit, np.int64)),
            remaining=jnp.asarray(pad(remaining, np.int64)),
            reset_time=jnp.asarray(pad(reset_time, np.int64)),
            duration=jnp.asarray(pad(duration, np.int64)),
            now=jnp.asarray(pad(np.full(n, now, dtype=np.int64), np.int64)),
            active=jnp.asarray(pad(np.ones(n, dtype=bool), bool)),
            burst=jnp.asarray(pad(burst, np.int64)),
            stamp=jnp.asarray(pad(stamp, np.int64)),
            aux=None if aux is None else jnp.asarray(pad(aux, np.int64)),
            rem_store=(
                None if rem_store is None
                else jnp.asarray(pad(rem_store, np.int64))
            ),
        )
        self.table, installed = install2(
            self.table, inst, write=self.write_mode, probe=self.walk_mode
        )
        self.stats.dispatches += 1
        return int(np.asarray(installed).sum())

    # ------------------------------------------------------------- handoff
    # Topology-change survivability (docs/robustness.md): extract packs every
    # live slot on-device, merge applies transferred slots conservatively
    # (kernel2.merge2 — a retried/duplicated transfer can never grant extra
    # capacity), tombstone zeroes acked rows so they are neither re-served
    # nor re-snapshotted by the source.

    def extract_live(self, now_ms: Optional[int] = None):
        """All live slots as (fps (N,) i64, slots (N, F_layout) i32) host
        arrays — the device pays for the full-table filter+pack, the host
        fetches only the live prefix (ops/table2.extract_live_rows). Slots
        ride the table's own layout; the TransferState wire tags them with
        the layout code so a receiver on a different layout converts
        through the canonical full row."""
        from gubernator_tpu.ops.table2 import extract_live_rows

        now = now_ms if now_ms is not None else ms_now()
        return extract_live_rows(
            self.table.rows, now, layout=self.table.layout
        )

    def _slots_to_full(self, slots: np.ndarray, layout=None) -> np.ndarray:
        """Normalize incoming slot rows to the canonical full layout — the
        one cross-layout conversion point (ops/layout.py contract). With no
        explicit layout, a 16-field row is full and an 8-field row is
        assumed to be this table's own packed layout (same-fleet
        transfers); cross-layout senders always say theirs."""
        from gubernator_tpu.ops import layout as layout_mod

        if layout is None:
            if slots.shape[1] == layout_mod.FULL.F:
                layout = layout_mod.FULL
            elif slots.shape[1] == self.table.layout.F:
                layout = self.table.layout
            else:
                raise ValueError(
                    f"cannot infer slot layout for width {slots.shape[1]}"
                )
        return np.asarray(layout.unpack(slots))

    def merge_rows(
        self, fps: np.ndarray, slots: np.ndarray,
        now_ms: Optional[int] = None, layout=None, collect: bool = False,
    ):
        """Conservatively merge transferred slot rows (TransferState receive
        path): remaining=min, expiry=max, newest config wins. Returns the
        number of rows merged/installed. `slots` may arrive in any sender
        layout (`layout`; inferred for full-width / same-layout rows) — the
        merge itself always runs on canonical full rows, so the
        conservatism is layout-independent. Duplicate fingerprints within
        one call merge as sequential passes — the claim machinery's
        unique-fp contract, same as the serving planner's (a chunk from one
        extract is always unique, but crossed transfers may not be).

        `collect=True` (the tiering promote path — unique fps only)
        instead returns (count, merged_mask (n,), evictee_fps, evictee
        canonical rows): the mask says which incoming rows actually
        landed (a claim-dropped promote must return to the shadow, not
        vanish) and the evictees are LIVE rows the installs displaced
        (demoted onward instead of destroyed)."""
        import jax.numpy as jnp

        from gubernator_tpu.ops.kernel2 import merge2
        from gubernator_tpu.ops.table2 import FLAGS

        n = fps.shape[0]
        if n == 0:
            if collect:
                return 0, np.zeros(0, dtype=bool), np.empty(
                    0, dtype=np.int64
                ), np.empty((0, 16), dtype=np.int32)
            return 0
        slots = self._slots_to_full(slots, layout)
        rank = _occurrence_rank(fps)
        if rank.max() > 0:
            if collect:
                raise ValueError(
                    "merge_rows(collect=True) requires unique fingerprints"
                )
            return sum(
                self.merge_rows(fps[rank == r], slots[rank == r], now_ms)
                for r in range(int(rank.max()) + 1)
            )
        if not self.table.layout.supports_algos(slots[:, FLAGS] & 0xFF):
            self.migrate_layout_full("merge of off-family rows")
        now = now_ms if now_ms is not None else ms_now()
        self._mark_dirty(fps)
        size = _pad_size(n)
        fp_p = np.zeros(size, dtype=np.int64)
        fp_p[:n] = fps
        slots_p = np.zeros((size, slots.shape[1]), dtype=np.int32)
        slots_p[:n] = slots
        active = np.zeros(size, dtype=bool)
        active[:n] = True
        args = (
            self.table,
            jnp.asarray(fp_p),
            jnp.asarray(slots_p),
            jnp.asarray(np.full(size, now, dtype=np.int64)),
            jnp.asarray(active),
        )
        if collect:
            self.table, merged, ev = merge2(
                *args, write=self.write_mode, evictees=True,
                probe=self.walk_mode,
            )
            self.stats.dispatches += 1
            mask = np.asarray(merged)[:n].copy()
            ev_h = np.asarray(ev)
            ev_lo = ev_h[:, 0].astype(np.int64) & 0xFFFFFFFF
            ev_fp = (ev_h[:, 1].astype(np.int64) << 32) | ev_lo
            keep = ev_fp != 0
            return (
                int(mask.sum()), mask, ev_fp[keep], ev_h[keep].copy()
            )
        self.table, merged = merge2(
            *args, write=self.write_mode, probe=self.walk_mode
        )
        self.stats.dispatches += 1
        return int(np.asarray(merged).sum())

    def read_state(self, fps: np.ndarray, raw: bool = False):
        """Read the full-width stored slots for `fps` without mutating
        anything: (found (n,) bool, slots (n, 16) i32 canonical fields).
        One device bucket gather — the GLOBAL broadcast plane uses this to
        attach sliding-window aux (prev count, stored remaining) to owner
        updates (service/global_manager._broadcast). `raw=True` returns
        the rows re-packed into THIS table's own slot layout ((n,
        layout.F) — exact for in-family rows, ops/layout.py) so the
        region-sync sender ships its stored rows at the table's native
        width and the receiver converts through the canonical full row."""
        import jax.numpy as jnp

        from gubernator_tpu.ops.table2 import F as F_FULL, gather_slots

        n = fps.shape[0]
        if n == 0:
            width = self.table.layout.F if raw else F_FULL
            return (
                np.zeros(0, dtype=bool), np.zeros((0, width), dtype=np.int32)
            )
        size = _pad_size(n)
        fp_p = np.zeros(size, dtype=np.int64)
        fp_p[:n] = fps
        active = np.zeros(size, dtype=bool)
        active[:n] = True
        slots, found = gather_slots(
            self.table.rows, jnp.asarray(fp_p), jnp.asarray(active),
            layout=self.table.layout,
        )
        out = np.asarray(slots)[:n].copy()
        if raw:
            out = np.asarray(self.table.layout.pack(out))
        return np.asarray(found)[:n].copy(), out

    def tombstone_fps(self, fps: np.ndarray) -> int:
        """Zero the slots holding `fps` (post-ack handoff cleanup). Missing
        fingerprints are no-ops; returns the number actually removed."""
        import jax.numpy as jnp

        from gubernator_tpu.ops.table2 import Table2, tombstone_rows

        n = fps.shape[0]
        if n == 0:
            return 0
        self._mark_dirty(fps)
        size = _pad_size(n)
        fp_p = np.zeros(size, dtype=np.int64)
        fp_p[:n] = fps
        active = np.zeros(size, dtype=bool)
        active[:n] = True
        rows, found = tombstone_rows(
            self.table.rows, jnp.asarray(fp_p), jnp.asarray(active)
        )
        self.table = Table2(rows=rows, layout=self.table.layout)
        self.stats.dispatches += 1
        return int(np.asarray(found).sum())

    # ------------------------------------------------------------- telemetry

    def telemetry_begin(self, now_ms: Optional[int] = None):
        """Launch the fused table-telemetry scan (ops/telemetry.py) without
        fetching — called on the engine thread so it reads a coherent table,
        finished off-thread so the device scan overlaps serving dispatches
        (EngineRunner.table_telemetry)."""
        from gubernator_tpu.ops.telemetry import scan_begin

        return scan_begin(
            self.table.rows, now_ms if now_ms is not None else ms_now(),
            layout=self.table.layout,
        )

    # ---------------------------------------------------------- checkpointing

    def snapshot(self) -> np.ndarray:
        """Device→host copy of the whole table (the Loader.Save analog,
        reference store.go:49-60 / workers.go:457-540)."""
        return np.asarray(self.table.rows)

    def restore(self, rows: np.ndarray, layout=None) -> None:
        """Host→device restore of a snapshot taken by `snapshot()` (the
        Loader.Load analog, reference workers.go:335-419). A snapshot
        written under a DIFFERENT slot layout (`layout` — recorded in the
        snapshot file) converts through the canonical full row on the host
        when the bucket geometry matches; the engine's own layout is kept."""
        import jax
        import jax.numpy as jnp

        lay = self.table.layout
        if layout is not None and layout is not lay:
            from gubernator_tpu.ops.table2 import FLAGS, F as F_FULL

            if rows.shape[:-1] != tuple(self.table.rows.shape[:-1]):
                raise ValueError(
                    f"snapshot geometry {rows.shape} incompatible with "
                    f"table {tuple(self.table.rows.shape)}"
                )
            full = np.asarray(layout.unpack_rows(rows))
            slots = full.reshape(-1, F_FULL)
            occupied = (slots[:, 0] != 0) | (slots[:, 1] != 0)
            if not lay.supports_algos((slots[:, FLAGS] & 0xFF)[occupied]):
                # the snapshot holds rows this packed layout cannot store:
                # degrade the ENGINE to full rather than corrupt state
                self.migrate_layout_full("restore of off-family snapshot")
                lay = self.table.layout
            rows = np.asarray(lay.pack_rows(full))
        if rows.shape != tuple(self.table.rows.shape):
            raise ValueError(
                f"snapshot shape {rows.shape} != table {tuple(self.table.rows.shape)}"
            )
        self.table = Table2(
            rows=jax.device_put(jnp.asarray(rows, dtype=jnp.int32)),
            layout=lay,
        )
        if self.ckpt is not None:
            # a mid-life restore replaces state of unknown provenance: the
            # next delta epoch must capture everything live, not just what
            # was marked before (boot-time restores run with no tracker
            # attached, so the warm path never pays this)
            self.ckpt.mark_all()

    def checkpoint_begin(self, gids: np.ndarray, now_ms: Optional[int] = None):
        """LAUNCH half of a dirty-block checkpoint extract (engine thread —
        reads a coherent table, costs only the enqueue); finish with
        `checkpoint_finish` on any thread while serving keeps dispatching
        (the telemetry_begin overlap pattern)."""
        from gubernator_tpu.ops.checkpoint import extract_begin

        now = now_ms if now_ms is not None else ms_now()
        return extract_begin(
            self.table.rows, gids, self.ckpt.blk, now,
            layout=self.table.layout,
        )

    def checkpoint_finish(self, pending):
        """FETCH half: (fps (N,) i64, slots (N, F) i32) — only the live
        prefix of the dirty blocks crosses the device→host boundary."""
        from gubernator_tpu.ops.checkpoint import finish_extract

        return finish_extract(pending)

    def live_count(self, now_ms: Optional[int] = None) -> int:
        from gubernator_tpu.ops.table2 import live_count2

        return live_count2(self.table, now_ms if now_ms is not None else ms_now())

    # -------------------------------------------------------------- resizing

    def resize(self, new_capacity: int, now_ms: Optional[int] = None) -> int:
        """Grow (or shrink) the table to `new_capacity` slots, re-placing
        every live entry (host-orchestrated rehash — SURVEY §7 hard-parts).
        The reference's LRU never resizes (CacheSize is fixed, config.go:151);
        here growth is cheap enough to expose: one device→host snapshot, a
        vectorized host rehash, one host→device put. Every previously-compiled
        batch shape is re-warmed against the new bucket count BEFORE serving
        resumes (a new (NB, ·) geometry means fresh XLA compiles — paying them
        inside resize() keeps them out of the request path, the same incident
        Daemon.warm_up prevents at startup). Returns the number of live
        entries dropped by per-bucket overflow in the new geometry (counted as
        unexpired evictions)."""
        import jax
        import jax.numpy as jnp

        from gubernator_tpu.ops.batch import HostBatch
        from gubernator_tpu.ops.table2 import n_buckets_for, rehash_rows

        now = now_ms if now_ms is not None else ms_now()
        lay = self.table.layout
        new_rows, dropped = rehash_rows(
            self.snapshot(), n_buckets_for(new_capacity), now, layout=lay
        )
        self.table = Table2(
            rows=jax.device_put(jnp.asarray(new_rows)), layout=lay
        )
        self.stats.evicted_unexpired += dropped
        if self.ckpt is not None:
            # block ids do not survive a geometry change: fresh tracker,
            # same epoch lineage, everything dirty (the next delta carries
            # the rehashed live set once)
            self.ckpt = self.ckpt.rebuild(self.table.rows.shape[0])
        # warm compiles for the new geometry with all-inactive dummy batches
        # (no state mutation — _decide_packed counts nothing itself, and all
        # rows are inactive). Three static math variants warm: algo=0 rows
        # compile the token graph, a GCRA-marked row the all-integer one, a
        # leaky row the mixed one (_math_mode; the all-GCRA "gcra" variant
        # needs an ACTIVE row, so a rare pure-GCRA batch right after a
        # resize pays its own compile).
        from gubernator_tpu.ops.layout import FULL as _FULL

        # packed layouts warm only their own math graph (off-family probe
        # rows would trigger a spurious migration)
        probe_algos = (0, 2, 1) if lay is _FULL else (lay.algos[0],)
        for size in sorted(self._seen_pad_sizes):
            z64 = np.zeros(size, dtype=np.int64)
            for probe_algo in probe_algos:
                algo = np.zeros(size, dtype=np.int32)
                algo[0] = probe_algo
                dummy = HostBatch(
                    fp=z64, algo=algo,
                    behavior=np.zeros(size, dtype=np.int32), hits=z64,
                    limit=np.ones(size, dtype=np.int64), burst=z64,
                    duration=np.ones(size, dtype=np.int64), created_at=z64,
                    expire_new=z64, greg_interval=z64,
                    duration_eff=np.ones(size, dtype=np.int64),
                    active=np.zeros(size, dtype=bool),
                )
                self._decide_packed(dummy)
        return dropped

    def maybe_grow(
        self,
        threshold: float = 0.6,
        factor: int = 2,
        max_capacity: Optional[int] = None,
        now_ms: Optional[int] = None,
    ) -> bool:
        """Auto-grow policy: double the table when live slots exceed
        `threshold` of capacity (open-addressed buckets degrade past ~0.6
        load). Call from a maintenance tick. Returns True if resized.
        `max_capacity` bounds the REALIZED capacity: bucket counts round up to
        a valid sweep geometry (n_buckets_for), so the clamp picks the largest
        conforming geometry that stays under the ceiling."""
        from gubernator_tpu.ops.table2 import K, n_buckets_for

        cap = self.table.capacity
        if self.live_count(now_ms) <= threshold * cap:
            return False
        new_cap = cap * factor
        if max_capacity is not None:
            while new_cap > cap and n_buckets_for(new_cap) * K > max_capacity:
                new_cap //= factor
            if new_cap <= cap:
                return False
        self.resize(new_cap, now_ms)
        return True
