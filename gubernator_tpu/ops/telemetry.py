"""Device-side table telemetry: one fused scan → the glass-box counters.

The reference observes its LRU cache through per-operation counters
(lrucache.go:48-59); a 10M–100M-key HBM-resident hash-slotted table needs
*structural* health signals those counters cannot express: how full the
buckets actually are (collision pressure predicts `unexpired_evictions`
BEFORE it fires), how the TTL horizon is distributed (what fraction of the
table frees itself in the next minute), how much admission headroom remains,
and what fraction of live keys sit OVER limit.

One jitted scan computes all of it in a single pass over the rows array —
the same streaming-sweep cost model as the write kernel (ops/table2.py
docstring: a full table stream through VMEM is ~ms at 1 GiB). The scan runs
on a BACKGROUND cadence from EngineRunner.table_telemetry (issue on the
engine thread, fetch off it — it overlaps serving dispatches and never sits
on the serving path). Output is one small int64 stats vector; the host
decodes it into a `TableSnapshot` that feeds the `gubernator_tpu_table_*`
Prometheus families, the `/v1/debug/table` endpoint, and the bench JSON.

`host_telemetry` is the numpy oracle the parity tests (and skeptical
operators) check the device scan against.
"""

from __future__ import annotations

import functools
import time
from dataclasses import dataclass, field
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from gubernator_tpu.ops.table2 import (
    EXP_HI,
    EXP_LO,
    F,
    FLAGS,
    FP_HI,
    FP_LO,
    K,
    LIMIT,
    REM_I,
)

# TTL-horizon bucket edges (ms since `now`): live keys expiring within each
# horizon, cumulative — ≤1s, ≤10s, ≤1m, ≤10m, ≤1h, ≤1d, +Inf
TTL_EDGES_MS = (1_000, 10_000, 60_000, 600_000, 3_600_000, 86_400_000)
# remaining-capacity edges (remaining / limit, cumulative ≤): keys at ≤1% of
# their limit are one burst from OVER; ≥90% are idle
REMAIN_EDGES = (0.01, 0.1, 0.25, 0.5, 0.9)
# buckets per occupancy block: the sweep kernel's default block width
# (kernel2 sparse geometry), so block-fill deciles line up with the write
# kernel's launch granularity
BLOCK_BUCKETS = 64

# stats-vector layout (int64): decoders below and the shard_map variant in
# parallel/telemetry.py share it — keep in sync
_N_SCALAR = 3  # live, occupied, over
VEC_LEN = _N_SCALAR + (K + 1) + len(TTL_EDGES_MS) + len(REMAIN_EDGES) + 10


@dataclass
class TableSnapshot:
    """One decoded telemetry scan (host side)."""

    now_ms: int
    capacity: int  # total slots
    n_buckets: int
    live_keys: int
    occupied_slots: int  # fp != 0, including expired-not-yet-evicted
    over_keys: int  # live slots whose stored status is OVER_LIMIT
    # count of buckets holding exactly j live slots, j = 0..K
    bucket_occupancy: List[int] = field(default_factory=list)
    # cumulative live keys with (expire - now) <= TTL_EDGES_MS[i]
    ttl_horizon: List[int] = field(default_factory=list)
    # cumulative live keys with remaining/limit <= REMAIN_EDGES[i]
    remaining_frac: List[int] = field(default_factory=list)
    # sweep-block fill-fraction histogram, 10 decile bins
    block_fill: List[int] = field(default_factory=list)
    scan_ms: float = 0.0
    per_shard_live: Optional[List[int]] = None  # mesh engines only

    @property
    def load_factor(self) -> float:
        return self.live_keys / max(self.capacity, 1)

    @property
    def over_fraction(self) -> float:
        return self.over_keys / max(self.live_keys, 1)

    @property
    def probe_depth(self) -> List[int]:
        """Live keys by their bucket's occupancy (a lookup gathers the whole
        bucket row, so depth == how contended the key's bucket is):
        depth_hist[j] = j * bucket_occupancy[j], j = 1..K."""
        return [j * self.bucket_occupancy[j] for j in range(1, K + 1)]

    def to_dict(self) -> dict:
        d = {
            "now_ms": self.now_ms,
            "capacity": self.capacity,
            "n_buckets": self.n_buckets,
            "live_keys": self.live_keys,
            "occupied_slots": self.occupied_slots,
            "expired_slots": self.occupied_slots - self.live_keys,
            "over_keys": self.over_keys,
            "over_fraction": round(self.over_fraction, 6),
            "load_factor": round(self.load_factor, 6),
            "bucket_occupancy": self.bucket_occupancy,
            "probe_depth": self.probe_depth,
            "ttl_horizon_ms": dict(
                zip([str(e) for e in TTL_EDGES_MS] + ["+Inf"],
                    self.ttl_horizon + [self.live_keys])
            ),
            "remaining_frac": dict(
                zip([str(e) for e in REMAIN_EDGES] + ["+Inf"],
                    self.remaining_frac + [self.live_keys])
            ),
            "block_fill_deciles": self.block_fill,
            "scan_ms": round(self.scan_ms, 3),
        }
        if self.per_shard_live is not None:
            d["per_shard_live"] = self.per_shard_live
        return d


def _scan_body(rows: jnp.ndarray, now: jnp.ndarray, blk: int,
               layout=None) -> jnp.ndarray:
    """Traceable scan body over an (..., NB, ROW_layout) rows array →
    (VEC_LEN,) int64 stats vector. Every entry is additive across disjoint
    row sets, so the sharded variant sums per-device vectors. `blk`
    (static) is the occupancy-block width in buckets; `layout` the table's
    slot layout — packed fields unpack to the canonical 16 in registers,
    so the statistics themselves stay layout-blind while the scan streams
    half the HBM bytes on 32 B tables."""
    if layout is None:
        from gubernator_tpu.ops.layout import FULL as layout
    slots = layout.unpack(
        rows.reshape(-1, K, layout.F)
    )  # (M buckets, K slots, 16 canonical fields)
    lo = slots[:, :, FP_LO].astype(jnp.int64) & 0xFFFFFFFF
    hi = slots[:, :, FP_HI].astype(jnp.int64)
    fp = (hi << 32) | lo
    exp = (slots[:, :, EXP_LO].astype(jnp.int64) & 0xFFFFFFFF) | (
        slots[:, :, EXP_HI].astype(jnp.int64) << 32
    )
    occupied = fp != 0
    live = occupied & (exp >= now)
    status = slots[:, :, FLAGS] >> 8  # FLAGS = algo | status<<8
    over = live & (status == 1)

    live_count = live.sum(dtype=jnp.int64)
    parts = [
        live_count[None],
        occupied.sum(dtype=jnp.int64)[None],
        over.sum(dtype=jnp.int64)[None],
    ]
    # bucket occupancy histogram: buckets holding exactly j live slots
    bucket_occ = live.sum(axis=1).astype(jnp.int32)  # (M,)
    occ_hist = (
        (bucket_occ[:, None] == jnp.arange(K + 1, dtype=jnp.int32)[None, :])
        .sum(axis=0, dtype=jnp.int64)
    )
    parts.append(occ_hist)
    # TTL horizon (cumulative over live slots)
    rel = exp - now
    parts.append(
        jnp.stack(
            [(live & (rel <= e)).sum(dtype=jnp.int64) for e in TTL_EDGES_MS]
        )
    )
    # remaining-capacity fraction (cumulative): rem_i / limit per live slot
    rem = jnp.maximum(slots[:, :, REM_I], 0).astype(jnp.float32)
    lim = jnp.maximum(slots[:, :, LIMIT], 1).astype(jnp.float32)
    frac = rem / lim
    parts.append(
        jnp.stack(
            [(live & (frac <= e)).sum(dtype=jnp.int64) for e in REMAIN_EDGES]
        )
    )
    # sweep-block fill deciles
    block_live = bucket_occ.reshape(-1, blk).sum(axis=1)  # (M/blk,)
    fill = block_live.astype(jnp.float32) / float(blk * K)
    decile = jnp.clip((fill * 10).astype(jnp.int32), 0, 9)
    parts.append(
        (decile[:, None] == jnp.arange(10, dtype=jnp.int32)[None, :]).sum(
            axis=0, dtype=jnp.int64
        )
    )
    return jnp.concatenate(parts)


_scan = functools.partial(jax.jit, static_argnames=("blk", "layout"))(
    _scan_body
)


def block_width(n_buckets: int) -> int:
    """Occupancy-block width for a table geometry: the sweep's 64-bucket
    block when it divides, the whole (tiny) table otherwise."""
    return BLOCK_BUCKETS if n_buckets % BLOCK_BUCKETS == 0 else n_buckets


class PendingScan:
    """An ISSUED telemetry scan: the device computes while serving continues;
    `finish_scan` materializes the stats vector. Carries the geometry the
    decoder needs."""

    __slots__ = ("vec", "now_ms", "capacity", "n_buckets", "t0", "per_shard")

    def __init__(self, vec, now_ms, capacity, n_buckets, per_shard=False):
        self.vec = vec
        self.now_ms = now_ms
        self.capacity = capacity
        self.n_buckets = n_buckets
        self.t0 = time.perf_counter()
        self.per_shard = per_shard


def scan_begin(rows, now_ms: int, layout=None) -> PendingScan:
    """Launch the telemetry scan over a single-device rows array WITHOUT
    fetching (the engine-thread half — cheap enqueue, the serving pipeline
    keeps dispatching while the device streams the table)."""
    if layout is None:
        from gubernator_tpu.ops.layout import layout_for_row

        layout = layout_for_row(int(rows.shape[-1]))
    nb = int(rows.shape[-2])
    vec = _scan(rows, jnp.int64(now_ms), blk=block_width(nb), layout=layout)
    total_buckets = int(np.prod(rows.shape[:-1]))
    return PendingScan(vec, now_ms, total_buckets * K, total_buckets)


def decode_vec(vec: np.ndarray) -> dict:
    """Split one (VEC_LEN,) stats vector into named pieces."""
    i = _N_SCALAR
    out = {
        "live": int(vec[0]),
        "occupied": int(vec[1]),
        "over": int(vec[2]),
    }
    out["occ_hist"] = [int(x) for x in vec[i : i + K + 1]]
    i += K + 1
    out["ttl"] = [int(x) for x in vec[i : i + len(TTL_EDGES_MS)]]
    i += len(TTL_EDGES_MS)
    out["remain"] = [int(x) for x in vec[i : i + len(REMAIN_EDGES)]]
    i += len(REMAIN_EDGES)
    out["blocks"] = [int(x) for x in vec[i : i + 10]]
    return out


def finish_scan(pending: PendingScan) -> TableSnapshot:
    """Fetch + decode an issued scan (the off-engine-thread half)."""
    vech = np.asarray(pending.vec)
    per_shard = None
    if pending.per_shard:
        per_shard = [int(x) for x in vech[:, 0]]
        vech = vech.sum(axis=0)
    d = decode_vec(vech)
    return TableSnapshot(
        now_ms=pending.now_ms,
        capacity=pending.capacity,
        n_buckets=pending.n_buckets,
        live_keys=d["live"],
        occupied_slots=d["occupied"],
        over_keys=d["over"],
        bucket_occupancy=d["occ_hist"],
        ttl_horizon=d["ttl"],
        remaining_frac=d["remain"],
        block_fill=d["blocks"],
        scan_ms=(time.perf_counter() - pending.t0) * 1e3,
        per_shard_live=per_shard,
    )


def host_telemetry(rows: np.ndarray, now_ms: int, layout=None) -> TableSnapshot:
    """Numpy oracle: the same statistics computed host-side from a table
    snapshot — the parity reference for the device scan (tests) and the
    escape hatch for post-mortem analysis of a checkpoint file."""
    if layout is None:
        from gubernator_tpu.ops.layout import layout_for_row

        layout = layout_for_row(int(rows.shape[-1]))
    nb = int(rows.shape[-2])
    blk = block_width(nb)
    slots = np.asarray(layout.unpack(rows.reshape(-1, K, layout.F)))
    lo = slots[:, :, FP_LO].astype(np.int64) & 0xFFFFFFFF
    hi = slots[:, :, FP_HI].astype(np.int64)
    fp = (hi << 32) | lo
    exp = (slots[:, :, EXP_LO].astype(np.int64) & 0xFFFFFFFF) | (
        slots[:, :, EXP_HI].astype(np.int64) << 32
    )
    occupied = fp != 0
    live = occupied & (exp >= now_ms)
    status = slots[:, :, FLAGS] >> 8
    bucket_occ = live.sum(axis=1)
    rel = exp - now_ms
    rem = np.maximum(slots[:, :, REM_I], 0).astype(np.float32)
    lim = np.maximum(slots[:, :, LIMIT], 1).astype(np.float32)
    frac = rem / lim
    block_live = bucket_occ.reshape(-1, blk).sum(axis=1)
    decile = np.clip((block_live.astype(np.float32) / (blk * K) * 10).astype(
        np.int32), 0, 9)
    total_buckets = slots.shape[0]
    return TableSnapshot(
        now_ms=now_ms,
        capacity=total_buckets * K,
        n_buckets=total_buckets,
        live_keys=int(live.sum()),
        occupied_slots=int(occupied.sum()),
        over_keys=int((live & (status == 1)).sum()),
        bucket_occupancy=[int((bucket_occ == j).sum()) for j in range(K + 1)],
        ttl_horizon=[int((live & (rel <= e)).sum()) for e in TTL_EDGES_MS],
        remaining_frac=[int((live & (frac <= e)).sum()) for e in REMAIN_EDGES],
        block_fill=[int((decile == j).sum()) for j in range(10)],
    )
