"""Incremental device-side checkpointing: epoch tracker + dirty-block extract.

The full-snapshot Loader (store.py) pays table-size cost on every save — a
100M-key table is ~6 GiB of DMA + compression per checkpoint, which is why
the seed only snapshots at graceful shutdown (and a `kill -9` loses every
counter since the last clean stop). This module makes checkpoint cost
proportional to the WRITE RATE instead:

* **EpochTracker** — a host-side dirty-block bitmap at the same granularity
  family as the kernel2 sparse write (`bucket // CKPT_BLK`, cf. the sweep's
  scalar-prefetched `target // (K·BLK)` dirty-block indices). Every table
  mutation marks the touched fingerprints' blocks ON THE ENGINE THREAD,
  strictly before (or in the same engine-thread job as) the mutation's
  launch; `take()` runs on the engine thread too, immediately before the
  extract launch, so the mark→mutate / take→extract pairs interleave FIFO
  and a dirtied block can never fall between epochs.
* **extract pass** — the PR-4 `extract_live_rows` pattern applied to only
  the dirty blocks: one device gather of the dirty blocks' bucket rows, an
  in-trace live filter + pack (live slots sorted to the front), and a host
  fetch of just the live prefix. Cost ∝ dirty blocks, never table size.
  Mesh engines run the same core per-shard under shard_map
  (parallel/sharded.make_sharded_extract_dirty) so no slot row ever crosses
  a device boundary.

The extracted rows ride the table's own packed slot-field layout ((N, F)
int32 — the same wire format TransferState chunks use), which is exactly
what `kernel2.merge2` consumes on replay: a stale or duplicated frame can
only tighten admission (remaining=min, expiry=max, OVER sticks), never
over-grant. Framing/CRC/replay live in store.py + service/checkpoint.py.

Granularity note: a dirty block's extract carries EVERY live row of its
buckets, not just the written one — the amplification is bounded by
CKPT_BLK × K × (live density), the price of block-granular tracking. The
default CKPT_BLK=1 (bucket granularity) holds amplification at the
bucket-occupancy floor; GUBER_CHECKPOINT_BLK trades bitmap size against
frame amplification for tables where n_buckets bools of host memory
matter.
"""

from __future__ import annotations

import functools
import os
import threading
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from gubernator_tpu.ops.table2 import FP_HI, FP_LO, K


def ckpt_blk() -> int:
    """Buckets per checkpoint dirty block (GUBER_CHECKPOINT_BLK). Bucket
    granularity (1) by default: extract cost is dirty blocks × blk × K
    slots, so unlike the sparse WRITE block (DMA-efficiency bound, default
    64) the tracking block hugs the placement granularity — measured 11×
    cheaper extracts at blk=1 vs blk=8 under a random-key write load.
    Raising it shrinks the bitmap (n_buckets/blk bools) at the price of
    extract amplification; even blk=1 is 12.5 MB of host bitmap at 100M
    keys."""
    return int(os.environ.get("GUBER_CHECKPOINT_BLK", "1"))


class EpochTracker:
    """Host-side dirty-block accumulator between checkpoint epochs.

    One bitmap bit per (shard, block); `mark()` is a vectorized setitem on
    the serving path (engine thread), `take()` snapshots-and-clears for one
    checkpoint epoch, `remark()` re-arms a taken set whose save failed so a
    full disk never silently drops dirt. Thread-safe: marks come from the
    engine thread, takes from the checkpoint manager (which routes them to
    the engine thread anyway — see module docstring), and status reads from
    the debug plane."""

    def __init__(
        self,
        n_buckets: int,
        n_shards: int = 1,
        blk: Optional[int] = None,
        start_epoch: int = 0,
    ):
        if n_buckets <= 0:
            raise ValueError("n_buckets must be positive")
        b = blk or ckpt_blk()
        b = min(b, n_buckets)
        # conforming tables (new_table2) are pow2 below 2048 buckets or a
        # multiple of 2048 above — some pow2 ≤ b always divides
        while b > 1 and n_buckets % b:
            b //= 2
        self.blk = b
        self.n_buckets = n_buckets
        self.n_shards = n_shards
        self.nblk = n_buckets // b  # blocks per shard
        self._dirty = np.zeros(n_shards * self.nblk, dtype=bool)
        # completed checkpoint epochs; take() hands out epoch+1 and advances
        self.epoch = start_epoch
        self.marked_fps = 0  # cumulative fps marked (status surface)
        self._lock = threading.Lock()

    def _block_ids(self, fps: np.ndarray) -> np.ndarray:
        fps = np.asarray(fps, dtype=np.int64)
        fps = fps[fps != 0]  # padding/inactive rows carry fp == 0
        if fps.size == 0:
            return fps
        blkid = (fps % self.n_buckets) // self.blk
        if self.n_shards > 1:
            from gubernator_tpu.parallel.mesh import shard_of

            blkid = shard_of(fps, self.n_shards) * self.nblk + blkid
        return blkid

    def mark(self, fps: np.ndarray) -> None:
        """Mark the blocks holding `fps` dirty (fp == 0 entries ignored)."""
        blkid = self._block_ids(fps)
        if blkid.size == 0:
            return
        with self._lock:
            self._dirty[blkid] = True
            self.marked_fps += int(blkid.size)

    def mark_all(self) -> None:
        """Everything is dirty (restore/resize of unknown provenance): the
        next epoch extracts the whole live set — expensive once, never
        lossy."""
        with self._lock:
            self._dirty[:] = True

    def take(self) -> Tuple[int, np.ndarray]:
        """Snapshot-and-clear the dirty set for one checkpoint epoch.
        Returns (epoch_id, sorted global block ids); the epoch counter
        advances even on an empty take so frame ids stay monotone."""
        with self._lock:
            gids = np.nonzero(self._dirty)[0].astype(np.int64)
            self._dirty[:] = False
            self.epoch += 1
            return self.epoch, gids

    def remark(self, gids: np.ndarray) -> None:
        """Re-arm a taken block set whose frame could not be persisted
        (disk full, unwritable path): the dirt survives to the next epoch
        instead of silently vanishing from every future checkpoint."""
        if gids.size == 0:
            return
        with self._lock:
            self._dirty[np.asarray(gids, dtype=np.int64)] = True

    @property
    def dirty_blocks(self) -> int:
        with self._lock:
            return int(self._dirty.sum())

    def rebuild(self, n_buckets: int) -> "EpochTracker":
        """Tracker for a resized table: same epoch lineage, everything
        dirty (block ids do not survive a geometry change)."""
        t = EpochTracker(
            n_buckets, n_shards=self.n_shards, blk=self.blk,
            start_epoch=self.epoch,
        )
        t.mark_all()
        return t


# ------------------------------------------------------------- extract pass


def _extract_blocks_core(rows2d, bidx, now, blk: int, layout=None):
    """Traced core shared by the single-device jit and the per-shard
    shard_map body (parallel/sharded.py): gather the dirty blocks' bucket
    rows, filter live slots, pack them to the front.

    `rows2d` is (T, ROW_layout); `bidx` (g,) block ids with out-of-range
    sentinels for padding (jnp.take mode="fill" zero-fills them — fp == 0
    rows are never live). Returns (slots (g·blk·K, F_layout) live-first,
    fp (g·blk·K,), live_count) — slots stay in the table's own layout, so
    packed tables' delta frames carry HALF the bytes per row."""
    if layout is None:
        from gubernator_tpu.ops.layout import FULL as layout
    g = bidx.shape[0]
    rowidx = (
        bidx[:, None].astype(jnp.int32) * blk
        + jnp.arange(blk, dtype=jnp.int32)[None, :]
    ).reshape(-1)
    blocks = jnp.take(rows2d, rowidx, axis=0, mode="fill", fill_value=0)
    slots = blocks.reshape(g * blk * K, layout.F)
    lo = slots[:, FP_LO].astype(jnp.int64) & 0xFFFFFFFF
    hi = slots[:, FP_HI].astype(jnp.int64)
    fp = (hi << 32) | lo
    exp = (slots[:, layout.exp_lo_i].astype(jnp.int64) & 0xFFFFFFFF) | (
        slots[:, layout.exp_hi_i].astype(jnp.int64) << 32
    )
    live = (fp != 0) & (exp >= now)
    order = jnp.argsort(jnp.where(live, 0, 1).astype(jnp.int32))
    return slots[order], fp[order], live.sum()


@functools.partial(jax.jit, static_argnames=("blk", "layout"))
def _extract_blocks_sorted(rows, bidx, now, *, blk: int, layout):
    """Single-array entry: accepts any (..., ROW_layout) rows array
    ((NB, ·) local or (D, NB, ·) sharded — the flatten folds the shard
    axis in, exactly like table2._extract_sorted; block ids are then
    GLOBAL, shard-major)."""
    return _extract_blocks_core(
        rows.reshape(-1, layout.row), bidx, now, blk, layout
    )


def _pad_pow2(n: int, floor: int = 8) -> int:
    p = floor
    while p < n:
        p *= 2
    return p


def extract_begin(rows, gids: np.ndarray, blk: int, now_ms: int, layout=None):
    """LAUNCH half of a dirty-block extract (engine thread — must read a
    coherent table, costs only the enqueue): pads the dirty-block list to a
    pow2 grid width (log-many compiled shapes) with an out-of-range
    sentinel and launches the gather+filter+pack. Returns a pending handle
    for finish_extract. `layout` is the table's slot layout (full when
    omitted — the legacy geometry)."""
    if layout is None:
        from gubernator_tpu.ops.layout import layout_for_row

        layout = layout_for_row(int(rows.shape[-1]))
    # sentinel: one past the last valid block id in the flattened layout
    sentinel = int(np.prod(rows.shape[:-1])) // blk
    g = int(gids.shape[0])
    pad = _pad_pow2(max(g, 1))
    bidx = np.full(pad, sentinel, dtype=np.int64)
    bidx[:g] = gids
    slots_s, fp_s, cnt = _extract_blocks_sorted(
        rows, jnp.asarray(bidx), jnp.asarray(np.int64(now_ms)),
        blk=blk, layout=layout,
    )
    return slots_s, fp_s, cnt


def finish_extract(pending):
    """FETCH half (any thread): materialize the live count, then fetch only
    the live prefix padded to a pow2 so the compiled slice shapes stay
    logarithmic in extract size (the extract_live_rows fetch rule). Slots
    come back in the table's own layout (width = the pending arrays')."""
    slots_s, fp_s, cnt = pending
    n = int(cnt)
    if n == 0:
        width = int(slots_s.shape[-1])
        return (
            np.empty(0, dtype=np.int64),
            np.empty((0, width), dtype=np.int32),
        )
    pad = 256
    while pad < n:
        pad *= 2
    pad = min(pad, int(fp_s.shape[0]))
    return (
        np.asarray(fp_s[:pad])[:n].copy(),
        np.asarray(slots_s[:pad])[:n].copy(),
    )
