"""Batch pass planning: same-key sequential semantics on a parallel device.

The reference serializes same-key requests through a per-key worker goroutine
(workers.go:185-189), so two hits on one key within a batch window apply one
after the other. The decision kernel instead requires unique fingerprints per
dispatch. The planner restores sequential semantics by splitting a batch into
passes:

* occurrence 0 of every key → pass 0, occurrence 1 → pass 1, … (exact
  sequential semantics for up to `max_exact` occurrences);
* occurrences ≥ max_exact-1 for a key are *aggregated* into the final pass —
  hits summed, RESET_REMAINING OR-ed, config taken from the newest request, and
  the aggregate's response shared by all members. This mirrors the reference's own
  hot-key aggregation on the GLOBAL async path (global.go:109-123: sum Hits,
  OR RESET_REMAINING) and bounds worst-case passes under Zipf-skewed traffic.

For the common all-unique batch this is a single pass with zero copies.

This module also owns the PROBE-KERNEL plan (`probe_kernel_env` /
`default_probe_kernel`): which table-walk kernel a dispatch compiles —
the XLA gather + sweep/sparse write, or the fused double-buffered Pallas
megakernel (ops/pallas_probe.py). Like the pass plan it is a host-side,
per-engine decision that every dispatch path (local, mesh, wire) inherits
through the engine's resolved mode.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import List

import numpy as np

from gubernator_tpu.ops.batch import HostBatch


def probe_kernel_env() -> str:
    """The GUBER_PROBE_KERNEL knob: auto | xla | pallas. Read per engine
    construction (like GUBER_SLOT_LAYOUT) so a daemon restart picks up a
    flip without code changes."""
    v = os.environ.get("GUBER_PROBE_KERNEL", "auto")
    if v not in ("auto", "xla", "pallas"):
        raise ValueError(
            f"GUBER_PROBE_KERNEL must be auto, xla or pallas, got {v!r}"
        )
    return v


def default_probe_kernel() -> str:
    """Resolve the probe-kernel plan: "xla" (the gather + sweep path every
    PR before this one shipped) unless GUBER_PROBE_KERNEL=pallas opts into
    the fused megakernel. "auto" stays on xla until the bench `probe`
    phase records the Pallas path ≥1.3× at the 100M-key config on a real
    device run (ROADMAP; the CPU interpret path is a parity surface, not
    a perf one)."""
    v = probe_kernel_env()
    return "xla" if v == "auto" else v


def walk_kernel_env() -> str:
    """The GUBER_WALK_KERNEL knob: auto | xla | pallas — which kernel the
    NON-decide table walks (GLOBAL installs, region/handoff merges,
    tiering promotes) compile: the two-pass gather + sweep/sparse write,
    or the fused probe→install/merge→write megakernel
    (ops/pallas_probe.walk2_pallas_impl). Deliberately independent of
    GUBER_PROBE_KERNEL: the decide path is latency-critical per request
    while the walks are throughput paths on the sync/maintenance planes,
    so a deployment can flip either without the other. Read per engine
    construction, like the probe knob."""
    v = os.environ.get("GUBER_WALK_KERNEL", "auto")
    if v not in ("auto", "xla", "pallas"):
        raise ValueError(
            f"GUBER_WALK_KERNEL must be auto, xla or pallas, got {v!r}"
        )
    return v


def default_walk_kernel() -> str:
    """Resolve the walk-kernel plan: "xla" unless GUBER_WALK_KERNEL=pallas
    opts the install/merge walks into the fused megakernel — same
    conservative default-flip policy as default_probe_kernel (the bench
    `dispatch` phase's fused-vs-two-pass wall on a real device gates any
    auto flip)."""
    v = walk_kernel_env()
    return "xla" if v == "auto" else v


@dataclass
class Pass:
    rows: np.ndarray  # original row indices whose response comes from this pass
    batch: HostBatch
    # For the aggregated final pass, responses fan back out: member_rows[i]
    # lists every original row sharing batch row i's response.
    member_rows: List[np.ndarray]


def _subset(b: HostBatch, rows: np.ndarray) -> HostBatch:
    return HostBatch(*[f[rows] for f in b])


def single_pass(b: HostBatch) -> List[Pass]:
    """O(1) plan for engines that aggregate duplicate keys IN-TRACE
    (kernel2.dedup_packed_cols, ShardedEngine dedup="device"): one pass, the
    raw batch, no host group-by. The np.unique sweep below is the host-side
    cost the mesh path eliminates — on a 131K-row dispatch the sort alone is
    milliseconds of single-process work while every device idles. Member
    fan-out happens on-device too (kernel2.fanout_packed), so member_rows
    stays empty and each row comes back with its own (aggregate) response."""
    act = np.nonzero(b.active)[0]
    if act.size == b.fp.shape[0]:
        return [Pass(rows=act, batch=b, member_rows=[])]
    return [Pass(rows=act, batch=_subset(b, act), member_rows=[])]


def plan_passes(b: HostBatch, max_exact: int = 8) -> List[Pass]:
    """Split a packed batch into unique-fingerprint passes. Rows with
    active=False (padding or per-request validation errors) are skipped."""
    act = np.nonzero(b.active)[0]
    fp = b.fp[act]
    uniq, inv, counts = np.unique(fp, return_inverse=True, return_counts=True)
    if counts.max(initial=0) <= 1:
        if act.size == b.fp.shape[0]:
            return [Pass(rows=act, batch=b, member_rows=[])]
        return [Pass(rows=act, batch=_subset(b, act), member_rows=[])]

    order = np.argsort(inv, kind="stable")
    sorted_inv = inv[order]
    group_start = np.searchsorted(sorted_inv, sorted_inv)
    occ = np.empty(act.size, dtype=np.int64)
    occ[order] = np.arange(act.size) - group_start

    passes: List[Pass] = []
    for r in range(min(int(occ.max()) + 1, max_exact - 1)):
        rows = act[np.nonzero(occ == r)[0]]
        if rows.size == 0:
            break
        passes.append(Pass(rows=rows, batch=_subset(b, rows), member_rows=[]))

    tail_pos = np.nonzero(occ >= max_exact - 1)[0]
    if tail_pos.size:
        tail = act[tail_pos]
        # aggregation groups key on (fp, cascade level) — two LEVELS of one
        # cascade whose keys collide on a fingerprint carry different limit
        # configs and must not merge (kernel2.dedup_packed_cols applies the
        # same discriminator in-trace). `inv` indexes unique fps; pairing it
        # with the level keeps the group id dense enough for np.unique.
        tail_lvl = (b.behavior[tail].astype(np.int64) >> 8) & 0xFF
        tail_key = inv[tail_pos].astype(np.int64) * 256 + tail_lvl
        tuniq, tinv = np.unique(tail_key, return_inverse=True)
        # newest member of each group carries the config (clients send the full
        # config with every request; latest wins)
        last_rows = np.zeros(tuniq.size, dtype=np.int64)
        np.maximum.at(last_rows, tinv, tail)
        agg = _subset(b, last_rows)
        hits = np.zeros(tuniq.size, dtype=np.int64)
        np.add.at(hits, tinv, b.hits[tail])
        # Only RESET_REMAINING survives the merge (reference global.go:117-121);
        # OR-ing other flags would desynchronize the carrier row's pre-resolved
        # fields (e.g. Gregorian rate inputs).
        reset_bit = np.zeros(tuniq.size, dtype=np.int32)
        np.bitwise_or.at(reset_bit, tinv, b.behavior[tail] & 8)  # RESET_REMAINING
        agg = agg._replace(hits=hits, behavior=agg.behavior | reset_bit)
        member_rows = [tail[tinv == g] for g in range(tuniq.size)]
        passes.append(Pass(rows=last_rows, batch=agg, member_rows=member_rows))
    return passes
