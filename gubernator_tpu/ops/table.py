"""HBM-resident rate-limit state: a hash-slotted struct-of-arrays table.

This replaces the reference's per-worker LRU caches (reference lrucache.go:32-178,
workers.go:19-37): instead of N goroutine-private `map[string]*list.Element`
shards, a single fixed-capacity SoA of per-slot fields lives in device HBM and
is mutated in place by the vectorized decision kernel (ops/decide.py) with
donated buffers.

Design choices vs the reference:
* LRU eviction → expiry-stamp eviction: a slot whose `expire_at` has passed is
  dead (the reference removes expired items on read, lrucache.go:111-128) and
  may be reclaimed by any key probing it. When all probe slots for a new key
  are live, the slot with the soonest expiry is evicted; if that expiry is
  still in the future we count an "unexpired eviction", mirroring the
  reference's over-capacity alarm metric (lrucache.go:138-149).
* Per-slot fields mirror TokenBucketItem/LeakyBucketItem (reference
  store.go:29-43) plus CacheItem's ExpireAt/InvalidAt (reference cache.go:29-41).
  One int64 `remaining_i` for token buckets and one float64 `remaining_f` for
  leaky buckets (the reference keeps a float64 remainder, store.go:32).
* `stamp` holds TokenBucketItem.CreatedAt for token slots and
  LeakyBucketItem.UpdatedAt for leaky slots.
* fp == 0 marks an empty slot; fingerprints are remapped away from 0
  (hashing.py).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np


class Table(NamedTuple):
    """Per-slot state arrays, each of shape (capacity,)."""

    fp: jnp.ndarray  # uint64 key fingerprint; 0 == empty
    algo: jnp.ndarray  # int32 Algorithm
    status: jnp.ndarray  # int32 Status (token bucket only; sticky)
    limit: jnp.ndarray  # int64
    duration: jnp.ndarray  # int64 (raw request duration; drives change detection)
    remaining_i: jnp.ndarray  # int64 token-bucket remaining
    remaining_f: jnp.ndarray  # float64 leaky-bucket remaining
    stamp: jnp.ndarray  # int64 token CreatedAt / leaky UpdatedAt (epoch ms)
    burst: jnp.ndarray  # int64 leaky-bucket burst
    expire_at: jnp.ndarray  # int64 epoch ms (CacheItem.ExpireAt)
    invalid_at: jnp.ndarray  # int64 epoch ms; 0 = never (CacheItem.InvalidAt)

    @property
    def capacity(self) -> int:
        return self.fp.shape[0]


def new_table(capacity: int) -> Table:
    """Fresh empty table. `capacity` is the hard slot count (the analog of the
    reference's CacheSize, default 50_000, reference config.go:151); keep load
    factor ≤ ~0.5 for healthy probe lengths."""
    if capacity <= 0:
        raise ValueError("capacity must be positive")
    return Table(
        fp=jnp.zeros(capacity, dtype=jnp.uint64),
        algo=jnp.zeros(capacity, dtype=jnp.int32),
        status=jnp.zeros(capacity, dtype=jnp.int32),
        limit=jnp.zeros(capacity, dtype=jnp.int64),
        duration=jnp.zeros(capacity, dtype=jnp.int64),
        remaining_i=jnp.zeros(capacity, dtype=jnp.int64),
        remaining_f=jnp.zeros(capacity, dtype=jnp.float64),
        stamp=jnp.zeros(capacity, dtype=jnp.int64),
        burst=jnp.zeros(capacity, dtype=jnp.int64),
        expire_at=jnp.zeros(capacity, dtype=jnp.int64),
        invalid_at=jnp.zeros(capacity, dtype=jnp.int64),
    )


def live_count(table: Table, now_ms: int) -> int:
    """Number of live (non-empty, unexpired) slots — the analog of the
    reference cache Size() (lrucache.go:152-157)."""
    fp = np.asarray(table.fp)
    exp = np.asarray(table.expire_at)
    return int(((fp != 0) & (exp >= now_ms)).sum())
