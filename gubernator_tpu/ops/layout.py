"""Slot-layout descriptors: algorithm-specialized compressed table rows.

BENCH_r05 pinned the decision kernel's scaling wall on HBM bandwidth: at
100M live keys every probe and every sweep block drags 16×i32 (64 B) of
slot state per slot through HBM regardless of algorithm, and decisions/s
falls 13.4M → 9.8M from 10M → 100M keys. But a single-algorithm table does
not NEED 16 fields: an all-GCRA table is fully described by fp + TAT +
config (the TAT doubles as the expiry — ops/math.py "State is
self-expiring"), an all-token table by fp + remaining + expiry + config.
PR 10 already specializes the decision *graph* per algorithm
(engine._math_mode); this module extends the specialization to the table
bytes themselves.

A **SlotLayout** describes everything a surface needs to address slot
bytes: fields per slot (``F``), bytes/slot, where the fingerprint and
expiry pairs live, which math modes the layout can serve, and the
pack/unpack rules to and from the canonical 16-field full layout. Every
layer that touches slot bytes — the kernel's probe/write
(ops/kernel2.py), handoff extract/merge (ops/table2.py,
service/handoff.py), checkpoint frames (ops/checkpoint.py, store.py),
the telemetry scan (ops/telemetry.py) and the mesh staging
(parallel/) — goes through the descriptor instead of the module
constants, so a future layout (f32/quantized lanes, tiered cold rows) is
a registry entry, not a rewrite.

Three layouts ship:

* ``full``   — the existing 16×i32 (64 B) row, bit-compatible with every
  table written before this module existed. Pack/unpack are identity.
* ``gcra32`` — 8×i32 (32 B) for all-GCRA tables:
  ``fp_lo fp_hi tat_lo tat_hi limit burst dur_lo meta`` where
  ``meta = dur_hi[0:23] | status<<23``. The TAT pair IS the expiry pair
  (exp ≡ TAT — the kernel's own self-expiry rule) and the stored stamp is
  dropped (GCRA math never reads it; the conservative merge's
  config-newest-wins then defaults to the incoming side, documented in
  docs/layout.md).
* ``token32`` — 8×i32 (32 B) for all-token tables:
  ``fp_lo fp_hi rem_i limit exp_lo exp_hi dur_lo meta`` with the same
  ``meta`` packing. The stamp is derived as ``exp - duration`` — exact
  for every non-Gregorian token write (the token math maintains
  ``exp == stamp + stored_duration`` invariantly); Gregorian batches
  migrate the table to ``full`` first (``greg_ok``).

**Conversion contract.** Cross-layout state movement (checkpoint replay
under a different layout, handoff between daemons booted with different
layouts, layout migration) always round-trips through the canonical
full-width row: ``unpack`` → full 16-field slots → (merge2 / pack). The
conservative-merge rules (remaining=min, expiry=max, aux=max,
OVER-sticks) therefore apply verbatim whatever layouts the two sides run
— replay/transfer can only under-grant.

**Selection.** ``resolve_layout(mode, math_hint)`` implements the
``GUBER_SLOT_LAYOUT`` knob: ``full`` forces the bit-compatible layout,
``gcra32``/``token32`` force a packed one, and ``auto``/``packed`` pick
the packed layout matching a single-algorithm math hint (``gcra`` /
``token``) when the caller provides one, full otherwise — so default
deployments behave exactly like today and single-algorithm fleets opt in
with one env var. A packed table that sees off-family traffic is
migrated to ``full`` by the engine (one in-place unpack of the rows
array) rather than serving wrong bytes.
"""

from __future__ import annotations

import os

import jax.numpy as jnp
import numpy as np

K = 8  # slots per bucket — shared with table2 by construction

# canonical full-layout field indices (ops/table2.py)
_FP_LO, _FP_HI, _LIMIT, _BURST, _REM_I, _FLAGS = 0, 1, 2, 3, 4, 5
_DUR_LO, _DUR_HI, _STAMP_LO, _STAMP_HI, _EXP_LO, _EXP_HI = 6, 7, 8, 9, 10, 11
_REMF_HI, _REMF_LO = 12, 13

_ALGO_TOKEN = 0
_ALGO_GCRA = 2

_DUR_HI_MASK = 0x7FFFFF  # 23 bits of dur_hi → durations < 2^55 ms
_STATUS_SHIFT = 23


def _xp(arr):
    """numpy for host arrays, jnp for device arrays/tracers — the same
    pack/unpack source serves both the traced kernel and host converters."""
    return np if isinstance(arr, np.ndarray) else jnp


class SlotLayout:
    """One slot layout: geometry + pack/unpack to the canonical full row.

    Instances are module-level singletons (identity hash/eq), which makes
    them valid jit static arguments and Table2 pytree aux data — a table's
    layout is part of its treedef, so every compiled program is keyed by
    it automatically."""

    __slots__ = (
        "name", "code", "F", "modes", "algos", "greg_ok",
        "exp_lo_i", "exp_hi_i",
    )

    def __init__(self, name, code, F, modes, algos, greg_ok,
                 exp_lo_i, exp_hi_i):
        self.name = name
        self.code = code  # frame/wire version byte (full=0 — legacy value)
        self.F = F  # int32 fields per slot
        self.modes = modes  # math modes this layout can serve
        self.algos = algos  # storable algorithm ids (None = all)
        self.greg_ok = greg_ok  # Gregorian batches representable?
        # expiry pair position in the PACKED row (fp is always fields 0/1 —
        # the cross-layout invariant fps_from_slots and the extract filters
        # rely on)
        self.exp_lo_i = exp_lo_i
        self.exp_hi_i = exp_hi_i

    @property
    def row(self) -> int:
        return K * self.F

    @property
    def slot_bytes(self) -> int:
        return self.F * 4

    def __repr__(self) -> str:  # pragma: no cover - debugging nicety
        return f"SlotLayout({self.name}, F={self.F})"

    # --------------------------------------------------------- conversion

    def unpack(self, slots):
        """(..., F) packed slot fields → (..., 16) canonical full fields."""
        if self is FULL:
            return slots
        xp = _xp(slots)
        p = lambda i: slots[..., i]
        zero = xp.zeros_like(p(0))
        dur_hi = p(7) & _DUR_HI_MASK
        status = (p(7) >> _STATUS_SHIFT) & 0xFF
        if self is GCRA32:
            flags = (status << 8) | _ALGO_GCRA
            # EXP ≡ TAT; the aux (REMF) pair is the same raw TAT
            cols = [p(0), p(1), p(4), p(5), zero, flags, p(6), dur_hi,
                    zero, zero, p(2), p(3), p(3), p(2), zero, zero]
        elif self is TOKEN32:
            flags = (status << 8) | _ALGO_TOKEN
            # stamp = exp - duration (token invariant; Gregorian excluded
            # by greg_ok)
            i64 = xp.int64
            exp = (p(5).astype(i64) << 32) | (p(4).astype(i64) & 0xFFFFFFFF)
            dur = (dur_hi.astype(i64) << 32) | (p(6).astype(i64) & 0xFFFFFFFF)
            stamp = exp - dur
            st_lo = (stamp & 0xFFFFFFFF).astype(p(0).dtype)
            st_hi = (stamp >> 32).astype(p(0).dtype)
            cols = [p(0), p(1), p(3), zero, p(2), flags, p(6), dur_hi,
                    st_lo, st_hi, p(4), p(5), zero, zero, zero, zero]
        else:  # pragma: no cover - registry guards
            raise ValueError(f"no unpack rule for layout {self.name}")
        return xp.stack(cols, axis=-1)

    def pack(self, full):
        """(..., 16) canonical full fields → (..., F) packed fields.
        Lossy by design: fields the layout's algorithm family never reads
        are dropped (see the module docstring's per-layout notes)."""
        if self is FULL:
            return full
        xp = _xp(full)
        g = lambda i: full[..., i]
        status = (g(_FLAGS) >> 8) & 0xFF
        meta = (g(_DUR_HI) & _DUR_HI_MASK) | (status << _STATUS_SHIFT)
        if self is GCRA32:
            # raw aux pair (REMF_LO = lo32, REMF_HI = hi32) is the TAT
            cols = [g(_FP_LO), g(_FP_HI), g(_REMF_LO), g(_REMF_HI),
                    g(_LIMIT), g(_BURST), g(_DUR_LO), meta]
        elif self is TOKEN32:
            cols = [g(_FP_LO), g(_FP_HI), g(_REM_I), g(_LIMIT),
                    g(_EXP_LO), g(_EXP_HI), g(_DUR_LO), meta]
        else:  # pragma: no cover - registry guards
            raise ValueError(f"no pack rule for layout {self.name}")
        return xp.stack(cols, axis=-1)

    def unpack_rows(self, rows):
        """(..., K·F) packed bucket rows → (..., K·16) full bucket rows."""
        if self is FULL:
            return rows
        shape = rows.shape[:-1]
        out = self.unpack(rows.reshape(shape + (K, self.F)))
        return out.reshape(shape + (K * 16,))

    def pack_rows(self, rows_full):
        """(..., K·16) full bucket rows → (..., K·F) packed bucket rows."""
        if self is FULL:
            return rows_full
        shape = rows_full.shape[:-1]
        out = self.pack(rows_full.reshape(shape + (K, 16)))
        return out.reshape(shape + (K * self.F,))

    def idle_ref(self, slots):
        """Per-slot last-activity reference (ms, int64) for the tiering
        idle sweep (gubernator_tpu/tier/): the stored stamp (UpdatedAt)
        when the layout keeps one, else ``exp - duration`` — exact for
        token32 (the pack derives the stamp the same way) and the best
        available proxy for gcra32 (the stamp is dropped; TAT-duration
        under-estimates activity, which only makes the sweep demote
        LATER, never wrongly expire state — demote/fault-back is
        correctness-preserving either way). Works on numpy and traced
        arrays ((…, F) slot fields in THIS layout)."""
        xp = _xp(slots)
        i64 = xp.int64
        p = lambda i: slots[..., i]
        exp = (p(self.exp_hi_i).astype(i64) << 32) | (
            p(self.exp_lo_i).astype(i64) & 0xFFFFFFFF
        )
        if self is FULL:
            dur_hi = p(_DUR_HI)
        else:
            dur_hi = p(7) & _DUR_HI_MASK
        dur = (dur_hi.astype(i64) << 32) | (p(_DUR_LO).astype(i64) & 0xFFFFFFFF)
        ref = exp - dur
        if self is FULL:
            stamp = (p(_STAMP_HI).astype(i64) << 32) | (
                p(_STAMP_LO).astype(i64) & 0xFFFFFFFF
            )
            ref = xp.where(stamp != 0, stamp, ref)
        return ref

    # ---------------------------------------------------------- predicates

    def supports_math(self, math: str) -> bool:
        return math in self.modes

    def supports_algos(self, algo: np.ndarray, active=None) -> bool:
        """Host-side: can every ACTIVE row's algorithm live in this
        layout? (padding rows carry algo=0 and never persist)."""
        if self.algos is None:
            return True
        a = np.asarray(algo)
        if active is not None:
            a = a[np.asarray(active)]
        if a.size == 0:
            return True
        ok = np.zeros(a.shape, dtype=bool)
        for v in self.algos:
            ok |= a == v
        return bool(ok.all())


FULL = SlotLayout(
    name="full", code=0, F=16,
    modes=("token", "gcra", "int", "mixed"),
    algos=None, greg_ok=True, exp_lo_i=_EXP_LO, exp_hi_i=_EXP_HI,
)
GCRA32 = SlotLayout(
    name="gcra32", code=1, F=8,
    modes=("gcra",), algos=(_ALGO_GCRA,), greg_ok=True,
    exp_lo_i=2, exp_hi_i=3,  # the TAT pair IS the expiry pair
)
TOKEN32 = SlotLayout(
    name="token32", code=2, F=8,
    modes=("token",), algos=(_ALGO_TOKEN,), greg_ok=False,
    exp_lo_i=4, exp_hi_i=5,
)

LAYOUTS = {l.name: l for l in (FULL, GCRA32, TOKEN32)}
_BY_CODE = {l.code: l for l in LAYOUTS.values()}


def layout_by_code(code: int) -> SlotLayout:
    """Layout for a frame/wire version byte; raises on unknown codes (a
    reader must refuse bytes it cannot interpret, not guess)."""
    l = _BY_CODE.get(int(code))
    if l is None:
        raise ValueError(f"unknown slot-layout code {code}")
    return l


def layout_for_row(row_lanes: int) -> SlotLayout:
    """Layout inferred from a rows array's lane width. Only the full
    layout's 128-lane row is unambiguous — both packed layouts are 64
    lanes wide, so packed tables must carry their layout explicitly
    (Table2 aux, frame version byte, TransferState layout field)."""
    if row_lanes == FULL.row:
        return FULL
    raise ValueError(
        f"cannot infer slot layout from row width {row_lanes}; "
        "packed layouts must be passed explicitly"
    )


def slot_layout_env() -> str:
    """The GUBER_SLOT_LAYOUT knob: auto | full | packed | gcra32 | token32
    (see resolve_layout). Read per engine construction."""
    v = os.environ.get("GUBER_SLOT_LAYOUT", "auto")
    if v not in ("auto", "full", "packed") and v not in LAYOUTS:
        raise ValueError(
            f"GUBER_SLOT_LAYOUT must be auto, full, packed or a layout "
            f"name ({', '.join(LAYOUTS)}), got {v!r}"
        )
    return v


def resolve_layout(mode=None, math_hint=None) -> SlotLayout:
    """Resolve the table layout for an engine.

    `mode`: explicit engine arg (wins) or the GUBER_SLOT_LAYOUT env —
    "full" (today's bytes, pinned bit-identical), a layout name
    ("gcra32"/"token32"), or "auto"/"packed" which pick the packed layout
    matching `math_hint` ("gcra" → gcra32, "token" → token32) and fall
    back to full when the hint is absent or multi-algorithm — so a
    default boot without a hint is byte-identical to every earlier PR."""
    mode = mode or slot_layout_env()
    if mode in LAYOUTS:
        return LAYOUTS[mode]
    if mode == "full":
        return FULL
    if mode in ("auto", "packed"):
        if math_hint == "gcra":
            return GCRA32
        if math_hint == "token":
            return TOKEN32
        return FULL
    raise ValueError(f"unknown slot-layout mode {mode!r}")
