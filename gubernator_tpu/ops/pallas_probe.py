"""Fused Pallas probe→decide→write megakernel: the decide path as ONE
table-walking kernel instead of an XLA gather plus a separate write pass.

BENCH_r05 pinned the 100M-key scaling wall on HBM: the XLA decide graph
pays one uncoalesced row-gather round trip (`kernel2._probe_claim2`'s
``rows = rows_tbl[bucket]``) and a second full round trip in the
sweep/sparse write, with zero overlap between fetch and compute — at 100M
live keys the chip starves (13.4M → 9.8M decisions/s). This module runs
the whole decide path — bucket-row fetch, layout unpack, probe/claim,
algorithm math and dirty-row write-back — inside one Pallas kernel that
streams exactly the touched bucket rows through VMEM:

* the batch is **bucket-sorted** in a cheap XLA prologue (the same rank
  sort `_probe_claim2` already pays), so same-bucket requests coalesce
  into ONE fetched row slot per block — one DMA descriptor in, one out,
  however many requests share the bucket;
* the grid walks the sorted batch in blocks of ``GUBER_PROBE_BLK``
  requests with **double-buffered async row copies**: while block *i* is
  being decided, block *i+1*'s bucket rows are already in flight
  (`pltpu.make_async_copy` into the alternate VMEM slot — the SNIPPETS
  [1]–[3] pattern the PR-8 remote-DMA ring uses), and only rows a decision
  actually dirtied are copied back;
* a bucket whose request run straddles a block boundary is **carried**:
  its lane updates accumulate in VMEM scratch across steps and the row is
  written once, when the run ends — no block ever re-reads a row another
  block wrote, so every request observes the pre-dispatch table exactly
  like the XLA gather does.

Bit-identity contract: the claim machinery below reproduces
`_probe_claim2` decision-for-decision (owner match, exact lazy expiry,
insert rank over vacant-then-soonest-expiring lanes, owner-wins dedup,
multi-evict) and the decide/payload/response stages are literally shared
code (`kernel2.decide_payload` / `kernel2.assemble_resp`), pinned by
tests/test_pallas_probe.py across layouts × algorithms × the eviction/
dedup/reclaim corners and on the 8-device mesh. The ONE intentional
divergence: the sweep write's u-window overflow drop (`_probe_claim2`'s
``overflow``) does not exist here — the megakernel has no payload window,
so rows the XLA path would window-drop (pathological same-sweep-block
concentration past the 5-sigma Poisson bound) are simply served. The
Pallas path can only drop FEWER rows, never different decisions.

Execution: CPU backends run the kernel in interpret mode (the
`_sweep_x64_ctx` pattern) — that is what CI exercises (`probe_smoke`,
the oracle-parity suite). On TPU the kernel compiles through Mosaic; the
claim sort and the 64-bit decide lanes are the known lowering-risk spots,
which is why `GUBER_PROBE_KERNEL` defaults to ``xla`` and the bench
`probe` phase records the Pallas path per kernel × layout on the next
device run before any default flips.

Beyond decide, the same probe→payload→write structure serves the OTHER
two table walks (`walk2_pallas_impl`): GLOBAL installs (`install2`) and
conservative merges (`merge2` — region sync, handoff, tiering promotes)
run as fused probe→install/merge→write walks, sharing the claim/carry/
write machinery verbatim. Their payload stages are the factored
`kernel2.install_payload16` / `kernel2.merge_payload16` — the same
shared-stage contract that makes decide bit-identical. Selection rides
`GUBER_WALK_KERNEL` (ops/plan.py), independent of the decide knob.

Write-side overlap: dirty-row write-backs no longer serialize against
the next block. Block *g* only STARTS its write DMAs; block *g+1* waits
them (`wdirty` parity scratch) just before reusing the buffer half —
in the HBM-bound steady state stores fly concurrently with the next
block's compute and fetch-waits instead of stalling the inner loop.
The data-movement layer is its own knob (`GUBER_PROBE_MOVEMENT`) so the
deferred-wait DMA protocol is testable on CPU through the interpret
emulation, not just on device.
"""

from __future__ import annotations

import functools
import os
from types import SimpleNamespace
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gubernator_tpu.ops.batch import BatchStats, ReqBatch, RespBatch
from gubernator_tpu.ops.kernel2 import (
    _biased,
    _hi32,
    _join64,
    _lo32,
    _sweep_x64_ctx,
    assemble_resp,
    decide_payload,
    merge_payload16,
    resolve_write,
    sparse_geometry,
)
from gubernator_tpu.ops.table2 import (
    EXP_HI,
    EXP_LO,
    FP_HI,
    FP_LO,
    K,
    Table2,
)

i64 = jnp.int64
i32 = jnp.int32

_ANY = getattr(pltpu, "ANY", None)
if _ANY is None:  # jax 0.4.x spells it TPUMemorySpace.ANY
    _ANY = pltpu.TPUMemorySpace.ANY

# out_resp columns (sorted-domain, un-sorted by the epilogue)
_OC_STATUS, _OC_REM, _OC_RESET, _OC_EXISTS = 0, 1, 2, 3
_OC_WRITTEN, _OC_EVICT, _OC_AUX, _OC_REMSTORE = 4, 5, 6, 7
_OUTW = 8
# with evictees=True the out rows widen by 8 int64 lanes carrying the
# CANDIDATE victim row (the claimed lane's pre-dispatch canonical 16
# fields as (hi<<32)|lo pairs). Deferred inserters' candidates ride the
# carry machinery untouched (the patch only flips _OC_WRITTEN/_OC_EVICT),
# and the epilogue masks candidates by the FINAL _OC_EVICT verdict — so
# a carried inserter killed by a later owner emits no victim row.
_OUTW_EV = 16


def probe_blk(batch: int) -> int:
    """Requests per megakernel grid step (GUBER_PROBE_BLK). The block is
    the double-buffering unit: VMEM holds 2 × BLK fetched bucket rows
    (2 × 256 × 512 B = 256 KiB at the TPU default on the full layout)
    plus the decide stage's per-row temporaries. Bigger blocks amortize
    per-step pipeline overhead; smaller ones cut the VMEM footprint and
    shorten the pipeline's fill/drain. "auto" = 256 on TPU; the whole
    batch (one grid step, no carries) on CPU interpret, where per-step
    machinery is pure overhead. Read per trace (host-side), so tuning
    runs can flip it between compiles without a restart — like
    GUBER_WRITE_SPARSE_BLK, an already-compiled dispatch shape keeps its
    traced geometry."""
    v = os.environ.get("GUBER_PROBE_BLK", "auto")
    if v == "auto":
        blk = batch if jax.default_backend() == "cpu" else 256
    else:
        blk = int(v)
    blk = max(1, min(blk, batch))
    while blk > 1 and batch % blk:
        blk //= 2
    return blk


def probe_movement(interpret: bool) -> str:
    """GUBER_PROBE_MOVEMENT: auto | interp | dma — which data-movement
    layer the megakernel traces (_make_probe_kernel docstring). "auto" is
    the measured-best pairing: the vectorized-gather + epilogue-scatter
    variant on CPU interpret backends, real async-DMA descriptors on
    device. "dma" on a CPU backend forces the DMA protocol through the
    interpret emulation — ~12× slower per dispatch, but it is the only
    host-side way to exercise the deferred write-back waits and semaphore
    accounting, which is what the movement-parity tests pin. "interp" on
    a real device is meaningless (the gather variant's epilogue scatter
    defeats the fusion) and rejected."""
    v = os.environ.get("GUBER_PROBE_MOVEMENT", "auto")
    if v not in ("auto", "interp", "dma"):
        raise ValueError(
            f"GUBER_PROBE_MOVEMENT must be auto, interp or dma, got {v!r}"
        )
    if v == "auto":
        return "interp" if interpret else "dma"
    if v == "interp" and not interpret:
        raise ValueError(
            "GUBER_PROBE_MOVEMENT=interp is CPU-interpret-only; device "
            "backends must run the DMA movement"
        )
    return v


def hbm_bytes_per_decision(
    layout, batch: int, n_buckets: int, write: str, probe: str = "xla"
) -> float:
    """Roofline model: HBM bytes the table walk moves per decision, from
    the layout's row width, the dispatch geometry and the write mode —
    the denominator of the "is the chip HBM-bound?" argument
    (docs/kernel.md "Probe pipeline"), exported as the
    gubernator_table_hbm_bytes_per_decision gauge.

    Per decision the PROBE reads one bucket row (`layout.row` i32 lanes).
    The write side depends on the mode: the dense sweep streams the whole
    table through VMEM and back (2 · NB · row_bytes amortized over the
    batch); the sparse grid touches its dirty blocks both ways; the XLA
    scatter writes one slot. The fused Pallas kernel reads one row and
    writes back only dirty rows — worst case one full row per decision,
    with same-bucket coalescing only lowering it. The model is the
    WORST case (every request a distinct bucket, every row dirtied): real
    batches with duplicate buckets or read-only rows move fewer bytes."""
    row_b = float(layout.row * 4)
    read = row_b
    if probe == "pallas":
        return read + row_b
    w = resolve_write(write, n_buckets, batch, layout)
    if w == "sweep":
        write_b = 2.0 * n_buckets * row_b / max(batch, 1)
    elif w == "sparse":
        blk, _u, g = sparse_geometry(n_buckets, batch)
        write_b = 2.0 * min(g * blk, n_buckets) * row_b / max(batch, 1)
    else:  # xla scatter: slot-granular write
        write_b = float(layout.slot_bytes)
    return read + write_b


# --------------------------------------------------------------- prologue


def _req_lanes(req: ReqBatch) -> jnp.ndarray:
    """The decide stage's (12, B) i64 kernel ingress (req_from_arr
    layout); ONE gather in _sorted_schedule permutes every column at
    once."""
    return jnp.stack(
        [
            req.fp,
            req.algo.astype(i64),
            req.behavior.astype(i64),
            req.hits,
            req.limit,
            req.burst,
            req.duration,
            req.created_at,
            req.expire_new,
            req.greg_interval,
            req.duration_eff,
            req.active.astype(i64),
        ]
    )


def _sorted_schedule(fp, active, arrN, NB: int, rblk: int):
    """Bucket-sort the batch and derive the megakernel's block schedule.

    `arrN` is the stage's (N, B) i64 ingress lane stack — the 12 decide
    request columns (_req_lanes) or the 11 walk lanes (fp, now, active,
    8 payload pairs; walk2_pallas_impl). Returns (idx_s, arr_s, meta, sb,
    bkf, G):
      * idx_s    — (B,) i32 original index at each sorted position (the
                   epilogue's un-sort key);
      * arr_s    — (N, B) i64 sorted ingress lanes (the kernel's blocked
                   ingress);
      * meta     — (3, B) i32 [sort key, VMEM row slot, fetch bucket];
      * sb       — (G·rblk,) i32 per-(block, slot) bucket to fetch,
                   sentinel NB for unused slots (the DMA index vector);
      * bkf      — (G,) i32 first sort key of each block (the carry's
                   continuation test).

    The sort key is the bucket for active rows and NB (past every real
    bucket) for inactive ones — the exact `bkey` `_probe_claim2` ranks
    with, so segment-local rank/dedup below reproduce the sorted-domain
    machinery. Fetches use the REAL bucket (fp % NB) for every row,
    matching the XLA gather byte-for-byte (inactive rows gather their
    bucket too; their decide outputs are masked identically).

    Slot assignment dedups buckets GLOBALLY within each block (not just
    consecutive runs): every distinct bucket a block touches — including
    an inactive row whose bucket another row already fetches — maps to
    one VMEM slot, so it costs one DMA descriptor each way and the
    write-back scatter never carries duplicate row indices."""
    B = fp.shape[0]
    G = B // rblk
    bucket = (fp % NB).astype(i32)
    bkey = jnp.where(active, bucket, i32(NB))
    idx = jnp.arange(B, dtype=i32)
    bkey_s, idx_s = jax.lax.sort((bkey, idx), num_keys=1)
    fbucket_s = bucket[idx_s]

    arr_s = arrN[:, idx_s]

    pos = jnp.arange(B, dtype=i32)
    blk_id = pos // i32(rblk)
    # dense rank of distinct (block, bucket) pairs within each block: sort
    # by the pair key, count firsts, subtract the count at the block start
    key = blk_id.astype(i64) * i64(NB + 1) + fbucket_s.astype(i64)
    key_s2, pos_s2 = jax.lax.sort((key, pos), num_keys=1)
    kfirst = jnp.concatenate(
        [jnp.ones((1,), dtype=bool), key_s2[1:] != key_s2[:-1]]
    )
    bo = (key_s2 // i64(NB + 1)).astype(i32)
    bstart = jnp.concatenate(
        [jnp.ones((1,), dtype=bool), bo[1:] != bo[:-1]]
    )
    cs = jnp.cumsum(kfirst.astype(i32))
    base = jax.lax.cummax(jnp.where(bstart, cs - 1, -1))
    slot_s2 = (cs - 1 - base).astype(i32)
    rs = jnp.zeros((B,), dtype=i32).at[pos_s2].set(slot_s2)

    sb = jnp.full((B,), NB, dtype=i32).at[blk_id * i32(rblk) + rs].set(
        fbucket_s
    )
    bkf = bkey_s[:: rblk]
    meta = jnp.stack([bkey_s, rs, fbucket_s])
    return idx_s, arr_s, meta, sb, bkf, G


# --------------------------------------------------------------- kernel


def _make_probe_kernel(layout, rblk: int, NB: int, G: int, math: str,
                       interp: bool, evictees: bool = False,
                       stage: str = "decide"):
    """Kernel factory (closes over static geometry + layout + math mode).

    `stage` (static) picks the payload computed between probe and write —
    "decide" (kernel2.decide_payload, the request path), "install"
    (prologue-precomputed install_payload16 rows ride the ingress lanes;
    the kernel just unjoins them) or "merge" (kernel2.merge_payload16
    against the VMEM-resident claimed lane). Claim, carry, compose and
    write machinery are IDENTICAL across stages — that is the point: the
    fused walk inherits the decide kernel's proven coalescing and
    carry-correctness wholesale.

    Scratch protocol (persists across grid steps):
      fbuf  (2, rblk, rowl)  double-buffered fetched bucket rows
      wdirty (2, rblk)       per-parity dirty masks of IN-FLIGHT write-
                             backs: block g only STARTS its dirty-row
                             copies; the step that reuses that buffer
                             half (g+1, before refilling it) waits them —
                             write-backs overlap the next block's compute
                             instead of stalling the inner loop
      obuf  (rblk, _OUTW)    per-block response staging (DMA'd per step)
      cstage (1, rowl)       carry-flush row staging
      pstage (K, _OUTW)      deferred-response patch staging
      crow  (1, rowl)        carried bucket's ORIGINAL fetched row
      cop/cip (K, F)         carried owner / inserter lane payloads
      cmask (2, K)           carried owner / inserter lane counts
      cdo   (K, _OUTW)       deferred inserter responses (indexed by RANK —
                             ranks are unique across the whole carried
                             segment, so slots never collide)
      cdmeta (4, K)          deferred rowid / lane / valid / evictable
      cscal SMEM (8,)        [carry_valid, carry_sort_key, carry_rank,
                              carry_fetch_bucket, …]

    Carry invariant: a bucket's row is fetched by every block whose
    requests probe it (all read the pre-dispatch bytes — no block writes
    a row a later block fetches) and written by exactly the step where its
    sorted run ends, composed as owner-payload > inserter-payload >
    original per lane. Inserters of a still-open run are DEFERRED: their
    written/evicted verdict depends on owners later in the run, so their
    response rows are patched at flush time from the accumulated owner
    mask (at most K per run — ranks ≥ K are dropped regardless).

    `interp` (static, = CPU backend) swaps the DATA-MOVEMENT layer only:
    fetches become one vectorized ref gather per block, and instead of
    writing table rows in-kernel the composed dirty rows + their target
    buckets leave through dedicated outputs that the entry's XLA epilogue
    scatters into the DONATED table once (`_write_xla`'s own in-place
    pattern). Both alternatives were measured and rejected: the interpret
    emulation walks per-row DMA descriptors one dynamic-update-slice at a
    time (~12× the whole XLA path per dispatch), and an in-kernel ref
    SCATTER on the aliased table state forces the discharge machinery
    into a full-table copy per call (~30 ms at 128 MiB — the state is
    both read and swapped in one jaxpr). Claim, decide, compose and
    carry logic are shared byte-for-byte between the variants; the
    oracle-parity suite runs the interp movement, the bench `probe`
    phase exercises the DMA movement on device."""
    from gubernator_tpu.ops.math import StoredState  # noqa: F401 (doc link)

    Fl = layout.F
    rowl = layout.row

    def kern(sb_ref, bkf_ref, arr_ref, meta_ref, sbv_ref, tbl_ref, *rest):
        if interp:
            # slot-payload staging outputs + the epilogue-scatter protocol
            # (factory docstring); the table is a read-only input here
            (ptgt_out, pay_out, ctgt_out, crows_out, resp_out) = rest[:5]
            (fbuf, wdirty, obuf, cstage, pstage, crow, cop, cip, cmask,
             cdo, cdmeta, cscal, fsem, wsem, osem, psem) = rest[5:]
            rows_out = None
        else:
            rows_out, resp_out = rest[:2]
            (fbuf, wdirty, obuf, cstage, pstage, crow, cop, cip, cmask,
             cdo, cdmeta, cscal, fsem, wsem, osem, psem) = rest[2:]
        NBc = i32(NB)
        lane_iota_k = jax.lax.broadcasted_iota(i32, (rblk, K), 1)
        g = pl.program_id(0)
        p = jax.lax.rem(g, i32(2))

        @pl.when(g == i32(0))
        def _():
            cscal[0] = i32(0)  # no carry before the first block

        # ---------------- fetch wait + prefetch (double buffer) ----------
        def fetch_copy(blk_i32, parity, n):
            b = sb_ref[blk_i32 * i32(rblk) + n]
            return pltpu.make_async_copy(
                tbl_ref.at[b], fbuf.at[parity, n], fsem
            )

        sbb = sbv_ref[0, :]  # (rblk,) this block's slot→bucket vector
        if interp:
            fb = None  # per-request gather below — no slot indirection
        else:
            @pl.when(g == i32(0))
            def _():
                def issue0(n, c):
                    @pl.when(sb_ref[n] < NBc)
                    def _():
                        fetch_copy(i32(0), i32(0), n).start()
                    return c
                jax.lax.fori_loop(0, rblk, issue0, 0)

            def wait_cur(n, c):
                @pl.when(sb_ref[g * i32(rblk) + n] < NBc)
                def _():
                    fetch_copy(g, p, n).wait()
                return c
            jax.lax.fori_loop(0, rblk, wait_cur, 0)

            # retire block g-1's still-in-flight write-backs BEFORE the
            # next fetch refills their source buffer half (fbuf[1-p]) —
            # the only ordering the deferred-write protocol needs.
            # Bucket-sorted runs guarantee no later block FETCHES a row
            # an earlier block writes (the carry owns straddlers), so
            # the stores can fly concurrently with this block's fetch
            # waits and compute.
            def write_copy(blk_i32, parity, n):
                return pltpu.make_async_copy(
                    fbuf.at[parity, n],
                    rows_out.at[sb_ref[blk_i32 * i32(rblk) + n]],
                    wsem,
                )

            @pl.when(g > i32(0))
            def _():
                def wait_prev(n, c):
                    dn = jax.lax.dynamic_index_in_dim(
                        wdirty[i32(1) - p], n, keepdims=False
                    )
                    @pl.when(
                        (sb_ref[(g - i32(1)) * i32(rblk) + n] < NBc)
                        & (dn != 0)
                    )
                    def _():
                        write_copy(g - i32(1), i32(1) - p, n).wait()
                    return c
                jax.lax.fori_loop(0, rblk, wait_prev, 0)

            @pl.when(g + i32(1) < i32(G))
            def _():
                def issue_next(n, c):
                    @pl.when(sb_ref[(g + i32(1)) * i32(rblk) + n] < NBc)
                    def _():
                        fetch_copy(g + i32(1), i32(1) - p, n).start()
                    return c
                jax.lax.fori_loop(0, rblk, issue_next, 0)
            fb = fbuf[p]

        # ---------------- probe + claim (block-local `_probe_claim2`) ----
        arr = arr_ref[...]  # (NL, rblk) i64 sorted ingress lanes
        if stage == "decide":
            reqb = ReqBatch(
                fp=arr[0],
                algo=arr[1].astype(i32),
                behavior=arr[2].astype(i32),
                hits=arr[3],
                limit=arr[4],
                burst=arr[5],
                duration=arr[6],
                created_at=arr[7],
                expire_new=arr[8],
                greg_interval=arr[9],
                duration_eff=arr[10],
                active=arr[11] != 0,
            )
            fpv = reqb.fp
            active = reqb.active
            now = reqb.created_at
            in16 = None
        else:
            # walk ingress (walk2_pallas_impl): [fp, now, active,
            # 8 × (hi<<32)|lo payload pairs] — the incoming canonical
            # (rblk, 16) i32 rows, unjoined losslessly in-register
            fpv = arr[0]
            now = arr[1]
            active = arr[2] != 0
            pairs_t = arr[3:11].T  # (rblk, 8)
            in16 = jnp.stack(
                [_lo32(pairs_t), _hi32(pairs_t)], axis=-1
            ).reshape(rblk, 16)
        bk = meta_ref[0, :]  # (rblk,) sort keys
        rs = meta_ref[1, :]  # VMEM row slot per request

        # rows_r: (rblk, rowl) each request's bucket row — pre-dispatch
        # bytes in both movement variants (no block ever reads a row
        # another block wrote). The interp gather goes per request (the
        # XLA oracle's own access pattern, one gather op); the DMA path
        # reads each distinct bucket's row once from its VMEM slot.
        if interp:
            rows_r = tbl_ref[meta_ref[2, :]]
        else:
            rows_r = jnp.take(fb, rs, axis=0)
        slots = layout.unpack(rows_r.reshape(rblk, K, Fl))  # (rblk, K, 16)

        my_lo = _lo32(fpv)
        my_hi = _hi32(fpv)
        s_fp_lo = slots[:, :, FP_LO]
        s_fp_hi = slots[:, :, FP_HI]
        empty = (s_fp_lo == 0) & (s_fp_hi == 0)
        match = (
            (s_fp_lo == my_lo[:, None]) & (s_fp_hi == my_hi[:, None])
            & ~empty & active[:, None]
        )
        owns = match.any(axis=1)
        own_j = jnp.argmax(match, axis=1).astype(i32)

        exp_lo_k = slots[:, :, EXP_LO]
        exp_hi_k = slots[:, :, EXP_HI]
        now_hi = _hi32(now)
        now_lo_b = _biased(_lo32(now))
        dead = ~empty & (
            (exp_hi_k < now_hi[:, None])
            | ((exp_hi_k == now_hi[:, None])
               & (_biased(exp_lo_k) < now_lo_b[:, None]))
        )
        vacant = empty | dead
        live = ~vacant

        # segments over the sort key; the first segment may continue the
        # carried run from the previous block
        first = jnp.concatenate(
            [jnp.ones((1,), dtype=bool), bk[1:] != bk[:-1]]
        )
        seg = jnp.cumsum(first.astype(i32)) - 1
        in_seg0 = seg == 0
        if G > 1:
            cvalid = cscal[0]
            cont = (cvalid != i32(0)) & (bk[0] == cscal[1])
            crank = cscal[2]
            carry_om = cmask[0, :]  # (K,) carried owner counts
        else:
            # single-block grid: no run can straddle, the whole carry
            # plane (and its scratch traffic) drops out of the trace
            cont = jnp.bool_(False)
            crank = i32(0)
            carry_om = jnp.zeros((K,), dtype=i32)

        need = active & ~owns
        csum = jnp.cumsum(need.astype(i32))
        c_excl = csum - need
        seg_base = jax.lax.cummax(jnp.where(first, c_excl, -1))
        rank = (c_excl - seg_base).astype(i32) + jnp.where(
            cont & in_seg0, crank, i32(0)
        )

        # owner lane occupancy over the WHOLE segment (carry included):
        # the dedup authority — an inserter whose chosen lane any owner of
        # its bucket holds is dropped (owner wins, `_probe_claim2`'s
        # sorted-dup rule)
        ownerhot = (
            (lane_iota_k == own_j[:, None]) & owns[:, None]
        ).astype(i32)
        seg_own = jax.ops.segment_sum(ownerhot, seg, num_segments=rblk)
        om = (jnp.take(seg_own, seg, axis=0) > 0) | (
            (cont & in_seg0)[:, None] & (carry_om > 0)[None, :]
        )
        # earlier-owner counts (duplicate-fp robustness: first owner wins)
        pre_own = jnp.cumsum(ownerhot, axis=0) - ownerhot
        seg_base_own = jax.lax.cummax(
            jnp.where(first[:, None], pre_own, -1), axis=0
        )
        earlier = pre_own - seg_base_own + jnp.where(
            (cont & in_seg0)[:, None], carry_om[None, :], 0
        )
        own_earlier = jnp.take_along_axis(earlier, own_j[:, None], axis=1)[
            :, 0
        ]
        owner_killed = owns & (own_earlier > 0)

        # candidate lane order: the EXACT `_probe_claim2` sort — vacant
        # lanes first (by index), then live lanes by soonest expiry
        _, _, _, cand = jax.lax.sort(
            (live.astype(i32), exp_hi_k, _biased(exp_lo_k), lane_iota_k),
            num_keys=3, dimension=1,
        )
        rank_c = jnp.clip(rank, 0, K - 1)
        ins_lane = jnp.take_along_axis(cand, rank_c[:, None], axis=1)[:, 0]
        chosen = jnp.where(owns, own_j, ins_lane).astype(i32)
        claim_ok = need & (rank < K)
        got = active & (owns | claim_ok)
        lane_live = jnp.take_along_axis(live, chosen[:, None], axis=1)[:, 0]
        killed_ins = claim_ok & jnp.take_along_axis(
            om, chosen[:, None], axis=1
        )[:, 0]
        written = got & ~killed_ins & ~owner_killed

        # ---------------- payload (shared stage, bit-identical) ----------
        lane16 = jnp.take_along_axis(
            slots, chosen[:, None, None], axis=1
        )[:, 0, :]
        if stage == "decide":
            exists, d, new16 = decide_payload(lane16, reqb, owns, math=math)
        elif stage == "install":
            # install rows are a pure function of the batch — precomputed
            # by the entry's install_payload16 prologue, they ride the
            # ingress lanes; owners overwrite their lane unconditionally
            # (install2's own rule), so exists is bookkeeping only
            d = None
            exists = owns
            new16 = in16
        else:  # merge
            d = None
            exists, new16 = merge_payload16(fpv, in16, lane16, owns, now)
        pay = layout.pack(new16)  # (rblk, Fl)

        # ---------------- segment classification -------------------------
        nseg = seg[rblk - 1] + 1
        last_seg = seg == (nseg - 1)
        if G > 1:
            nxt_key = bkf_ref[jnp.minimum(g + i32(1), i32(G - 1))]
            cont_next = (g + i32(1) < i32(G)) & (nxt_key == bk[rblk - 1])
        else:
            cont_next = jnp.bool_(False)
        in_carry = (cont & in_seg0) | (cont_next & last_seg)

        # ---------------- in-block compose + dirty-row write-back --------
        wr_now = written & ~in_carry
        if interp:
            # stage each WRITTEN row's packed payload + its global slot
            # target for the entry's epilogue scatter (unwritten/carried
            # rows redirect to the out-of-bounds sentinel and drop) —
            # `_write_xla`'s own slot-granular pattern, one scatter per
            # dispatch instead of per-row copies
            ptgt_out[0, pl.ds(g * i32(rblk), rblk)] = jnp.where(
                wr_now, meta_ref[2, :] * i32(K) + chosen, i32(NB * K)
            )
            pay_out[pl.ds(g * i32(rblk), rblk)] = pay
        else:
            tgt = jnp.where(wr_now, rs * i32(K) + chosen, i32(rblk * K))
            fb_new = (
                fb.reshape(rblk * K, Fl)
                .at[tgt].set(pay, mode="drop")
                .reshape(rblk, rowl)
            )
            dirty = (
                jnp.zeros(rblk * K + 1, dtype=bool)
                .at[tgt].set(True, mode="drop")[: rblk * K]
                .reshape(rblk, K)
                .any(axis=1)
            )
            fbuf[p] = fb_new
            wdirty[p] = dirty.astype(i32)

            # START the dirty-row copies only — the step that next reuses
            # this buffer half waits them (wait_prev above), overlapping
            # the stores with block g+1's compute; the final grid step
            # retires its own writes before the kernel exits
            def write_row(n, c):
                dn = jax.lax.dynamic_index_in_dim(
                    wdirty[p], n, keepdims=False
                )
                @pl.when((sb_ref[g * i32(rblk) + n] < NBc) & (dn != 0))
                def _():
                    write_copy(g, p, n).start()
                return c
            jax.lax.fori_loop(0, rblk, write_row, 0)

            @pl.when(g == i32(G - 1))
            def _():
                def wait_last(n, c):
                    dn = jax.lax.dynamic_index_in_dim(
                        wdirty[p], n, keepdims=False
                    )
                    @pl.when((sb_ref[g * i32(rblk) + n] < NBc) & (dn != 0))
                    def _():
                        write_copy(g, p, n).wait()
                    return c
                jax.lax.fori_loop(0, rblk, wait_last, 0)

        # ---------------- per-block responses -----------------------------
        evict = claim_ok & lane_live & written
        if stage == "decide":
            outb = jnp.stack(
                [
                    d.resp_status.astype(i64),
                    d.resp_rem,
                    d.resp_reset,
                    exists.astype(i64),
                    written.astype(i64),
                    evict.astype(i64),
                    d.aux_out,
                    d.rem_i_out,
                ],
                axis=1,
            )  # (rblk, _OUTW)
        else:
            # walks answer only the masks; the response columns keep the
            # decide width so the carry patch machinery (cdo rows, the
            # _OC_WRITTEN/_OC_EVICT flips) is shared untouched
            z = jnp.zeros((rblk,), dtype=i64)
            outb = jnp.stack(
                [
                    z, z, z,
                    exists.astype(i64),
                    written.astype(i64),
                    evict.astype(i64),
                    z, z,
                ],
                axis=1,
            )  # (rblk, _OUTW)
        if evictees:
            # candidate victim row (pre-dispatch claimed-lane state); the
            # FINAL verdict is the patched _OC_EVICT — epilogue masks
            ev16 = jnp.where(
                (claim_ok & lane_live)[:, None], lane16, 0
            ).astype(i32)
            outb = jnp.concatenate(
                [outb, _join64(ev16[:, 0::2], ev16[:, 1::2])], axis=1
            )  # (rblk, _OUTW_EV)
        if interp:
            resp_out[pl.ds(g * i32(rblk), rblk)] = outb
        else:
            obuf[...] = outb
            oc = pltpu.make_async_copy(
                obuf, resp_out.at[pl.ds(g * i32(rblk), rblk)], osem
            )
            oc.start()
            oc.wait()

        # ---------------- carry resolution --------------------------------
        if G == 1:
            # single-block grid: no run can straddle a boundary, so the
            # whole carry plane below never traces
            return
        jpos = jax.lax.broadcasted_iota(i32, (rblk,), 0)
        if interp:
            # default: this step flushes nothing (the epilogue drops the
            # sentinel target); at most ONE flush can happen per step —
            # the old-carry and run-ends-here cases are mutually exclusive
            ctgt_out[0, g] = NBc

        def flush_carry():
            """Write the carried bucket's composed row + patch deferred
            responses from the FINAL owner mask."""
            com = cmask[0, :] > 0
            cim = cmask[1, :] > 0
            crow_slots = crow[0].reshape(K, Fl)
            final = jnp.where(
                com[:, None], cop[...],
                jnp.where((cim & ~com)[:, None], cip[...], crow_slots),
            )
            @pl.when((com | cim).any() & (cscal[3] < NBc))
            def _():
                if interp:
                    ctgt_out[0, g] = cscal[3]
                    crows_out[pl.ds(g, 1)] = final.reshape(1, rowl)
                else:
                    cstage[0] = final.reshape(rowl)
                    fc = pltpu.make_async_copy(
                        cstage.at[0], rows_out.at[cscal[3]], psem
                    )
                    fc.start()
                    fc.wait()

            def patch(k, c):
                @pl.when(cdmeta[2, k] != i32(0))
                def _():
                    lane = cdmeta[1, k]
                    killed = (
                        jax.lax.dynamic_index_in_dim(
                            cmask[0, :], lane, keepdims=False
                        ) > 0
                    )
                    wr = jnp.where(killed, i64(0), i64(1))
                    row = cdo[k]
                    row = row.at[_OC_WRITTEN].set(wr)
                    row = row.at[_OC_EVICT].set(row[_OC_EVICT] * wr)
                    if interp:
                        resp_out[cdmeta[0, k]] = row
                    else:
                        pstage[k] = row
                        pc = pltpu.make_async_copy(
                            pstage.at[k], resp_out.at[cdmeta[0, k]], psem
                        )
                        pc.start()
                        pc.wait()
                return c
            jax.lax.fori_loop(0, K, patch, 0)
            cscal[0] = i32(0)

        def clear_carry():
            cmask[...] = jnp.zeros((2, K), dtype=i32)
            cop[...] = jnp.zeros((K, Fl), dtype=i32)
            cip[...] = jnp.zeros((K, Fl), dtype=i32)
            cdmeta[...] = jnp.zeros((4, K), dtype=i32)

        def accumulate(sel):
            """Fold this block's rows of segment `sel` into the carry:
            rank offset, owner/inserter lane payloads + counts, deferred
            inserter responses (slot = rank, unique across the run)."""
            cscal[2] = cscal[2] + jnp.sum(
                (need & sel).astype(i32), dtype=i32
            )
            own_sel = sel & owns & got & ~owner_killed
            o_hot = ownerhot * own_sel[:, None].astype(i32)  # (rblk, K)
            cmask[0, :] = cmask[0, :] + o_hot.sum(axis=0).astype(i32)
            cop[...] = cop[...] + jnp.einsum(
                "rk,rf->kf", o_hot, pay
            ).astype(i32)
            ins_sel = sel & claim_ok
            i_hot = (
                (lane_iota_k == chosen[:, None]) & ins_sel[:, None]
            ).astype(i32)
            cmask[1, :] = cmask[1, :] + i_hot.sum(axis=0).astype(i32)
            cip[...] = cip[...] + jnp.einsum(
                "rk,rf->kf", i_hot, pay
            ).astype(i32)
            # deferred responses, keyed by rank (< K for every ins_sel row)
            rk = jnp.where(ins_sel, rank, i32(K))
            cdo[...] = cdo[...].at[rk].set(outb, mode="drop")
            cdmeta[0, :] = cdmeta[0, :].at[rk].set(
                g * i32(rblk) + jpos, mode="drop"
            )
            cdmeta[1, :] = cdmeta[1, :].at[rk].set(chosen, mode="drop")
            cdmeta[2, :] = cdmeta[2, :].at[rk].set(
                jnp.ones((rblk,), dtype=i32), mode="drop"
            )
            cdmeta[3, :] = cdmeta[3, :].at[rk].set(
                (claim_ok & lane_live).astype(i32), mode="drop"
            )

        # A: a carried run that did NOT continue ended at the last block
        @pl.when((cvalid != i32(0)) & ~cont)
        def _():
            flush_carry()

        # B: continuing run — fold this block's head segment in; flush if
        # the run ends inside this block (or the grid ends)
        @pl.when(cont)
        def _():
            accumulate(in_seg0)
        @pl.when(cont & ((nseg > 1) | ~cont_next))
        def _():
            flush_carry()

        # C: a run that straddles INTO the next block opens a new carry
        @pl.when(cont_next & ~(cont & (nseg == 1)))
        def _():
            clear_carry()
            cscal[0] = i32(1)
            cscal[1] = bk[rblk - 1]
            cscal[2] = i32(0)
            cscal[3] = meta_ref[2, rblk - 1]  # real fetch bucket
            crow[0] = rows_r[rblk - 1]
            accumulate(last_seg)

    return kern


# --------------------------------------------------------------- entry


def _launch_walk(table: Table2, arr_s, meta, sb, bkf, G: int, rblk: int, *,
                 math: str, evictees: bool, stage: str):
    """Shared pallas_call scaffolding for every stage (decide + the
    install/merge walks): block the sorted ingress lanes, wire the
    scratch protocol, run the kernel, and — interp movement — apply the
    staged slot/carry writes to the DONATED table in one epilogue
    scatter. Returns (rows_out, resp_s), responses still in sorted
    order."""
    layout = table.layout
    NB = table.rows.shape[0]
    nl, B = arr_s.shape
    outw = _OUTW_EV if evictees else _OUTW

    interpret = jax.default_backend() == "cpu"
    interp = probe_movement(interpret) == "interp"
    if interp:
        # slot-payload staging outputs; the table stays a read-only input
        # and the donated-scatter epilogue below applies the writes in
        # place (_make_probe_kernel docstring: an in-kernel ref scatter on
        # the aliased state costs a full-table copy under the discharge)
        out_shape = (
            jax.ShapeDtypeStruct((1, B), jnp.int32),  # ptgt (slot ids)
            jax.ShapeDtypeStruct((B, layout.F), jnp.int32),  # pay
            jax.ShapeDtypeStruct((1, G), jnp.int32),  # ctgt
            jax.ShapeDtypeStruct((G, layout.row), jnp.int32),  # crows
            jax.ShapeDtypeStruct((B, outw), jnp.int64),  # resp
        )
        out_specs = [pl.BlockSpec(memory_space=_ANY)] * 5
        aliases = {}
    else:
        out_shape = (
            jax.ShapeDtypeStruct(table.rows.shape, table.rows.dtype),
            jax.ShapeDtypeStruct((B, outw), jnp.int64),
        )
        out_specs = [pl.BlockSpec(memory_space=_ANY)] * 2
        aliases = {5: 0}
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(G,),
        in_specs=[
            pl.BlockSpec((nl, rblk), lambda g, sb, bkf: (0, g)),
            pl.BlockSpec((3, rblk), lambda g, sb, bkf: (0, g)),
            pl.BlockSpec((1, rblk), lambda g, sb, bkf: (0, g)),
            pl.BlockSpec(memory_space=_ANY),
        ],
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((2, rblk, layout.row), jnp.int32),  # fbuf
            pltpu.VMEM((2, rblk), jnp.int32),  # wdirty
            pltpu.VMEM((rblk, outw), jnp.int64),  # obuf
            pltpu.VMEM((1, layout.row), jnp.int32),  # cstage
            pltpu.VMEM((K, outw), jnp.int64),  # pstage
            pltpu.VMEM((1, layout.row), jnp.int32),  # crow
            pltpu.VMEM((K, layout.F), jnp.int32),  # cop
            pltpu.VMEM((K, layout.F), jnp.int32),  # cip
            pltpu.VMEM((2, K), jnp.int32),  # cmask
            pltpu.VMEM((K, outw), jnp.int64),  # cdo
            pltpu.VMEM((4, K), jnp.int32),  # cdmeta
            pltpu.SMEM((8,), jnp.int32),  # cscal
            pltpu.SemaphoreType.DMA,  # fsem
            pltpu.SemaphoreType.DMA,  # wsem
            pltpu.SemaphoreType.DMA,  # osem
            pltpu.SemaphoreType.DMA,  # psem
        ],
    )
    with _sweep_x64_ctx(interpret):
        outs = pl.pallas_call(
            _make_probe_kernel(layout, rblk, NB, G, math, interp,
                               evictees, stage),
            interpret=interpret,
            out_shape=out_shape,
            grid_spec=grid_spec,
            input_output_aliases=aliases,
        )(sb, bkf, arr_s, meta, sb.reshape(1, G * rblk), table.rows)
    if interp:
        ptgt, pay_s, ctgt, crows, resp_s = outs
        # the table write: one slot-granular scatter of the written rows'
        # payloads (`_write_xla`'s own pattern), then the carried buckets'
        # composed rows (disjoint target sets — a carried bucket is never
        # composed in-block); sentinel targets drop
        slot_view = table.rows.reshape(NB * K, layout.F)
        rows_out = (
            slot_view.at[ptgt[0]].set(pay_s, mode="drop")
            .reshape(NB, layout.row)
        )
        if G > 1:  # single-block grids carry (and flush) nothing
            rows_out = rows_out.at[ctgt[0]].set(crows, mode="drop")
    else:
        rows_out, resp_s = outs
    return rows_out, resp_s


def decide2_pallas_impl(
    table: Table2, req: ReqBatch, *, math: str = "mixed",
    evictees: bool = False,
):
    """Fused-megakernel twin of `kernel2.decide2_impl` (reached through its
    ``probe="pallas"`` switch — call sites never import this directly).
    Same signature contract: (table', RespBatch, BatchStats), decision-
    bit-identical modulo the sweep-window divergence documented above.
    ``evictees=True`` (static) widens the out rows by the candidate-victim
    lanes (_OUTW_EV) and returns a 4th element: the (B, 16) i32 evictee
    sidecar, victim rows where the final evict verdict holds."""
    layout = table.layout
    NB = table.rows.shape[0]
    B = req.fp.shape[0]
    rblk = probe_blk(B)
    idx_s, arr_s, meta, sb, bkf, G = _sorted_schedule(
        req.fp, req.active, _req_lanes(req), NB, rblk
    )
    rows_out, resp_s = _launch_walk(
        table, arr_s, meta, sb, bkf, G, rblk,
        math=math, evictees=evictees, stage="decide",
    )
    outw = _OUTW_EV if evictees else _OUTW

    # un-sort the response rows back to batch order
    out = jnp.zeros((B, outw), dtype=i64).at[idx_s].set(resp_s)
    d_like = SimpleNamespace(
        resp_status=out[:, _OC_STATUS].astype(i32),
        resp_rem=out[:, _OC_REM],
        resp_reset=out[:, _OC_RESET],
        aux_out=out[:, _OC_AUX],
        rem_i_out=out[:, _OC_REMSTORE],
    )
    exists = out[:, _OC_EXISTS] != 0
    written = out[:, _OC_WRITTEN] != 0
    evict_live = out[:, _OC_EVICT] != 0
    resp, stats = assemble_resp(req, d_like, exists, written, evict_live)
    if evictees:
        evcols = out[:, _OUTW:]  # (B, 8) i64 candidate victim pairs
        ev16 = jnp.stack(
            [_lo32(evcols), _hi32(evcols)], axis=-1
        ).reshape(B, 16)
        ev16 = jnp.where(evict_live[:, None], ev16, 0)
        return Table2(rows=rows_out, layout=layout), resp, stats, ev16
    return Table2(rows=rows_out, layout=layout), resp, stats


decide2_pallas = functools.partial(
    jax.jit, donate_argnums=(0,), static_argnames=("math", "evictees")
)(decide2_pallas_impl)


def walk2_pallas_impl(
    table: Table2, fp, pay16, now, active, *, stage: str,
    evictees: bool = False,
):
    """Fused-megakernel twin of `install2` / `merge2`: the probe→
    install/merge→write walk, reached through their ``probe="pallas"``
    switches — call sites never import this directly.

    `pay16` is the (B, 16) i32 canonical ingress: for ``stage="install"``
    the precomputed `kernel2.install_payload16` rows (the install payload
    never reads table state, so it rides the ingress lanes and the kernel
    just unjoins it), for ``stage="merge"`` the raw incoming slot rows
    (`kernel2.merge_payload16` runs in-kernel against the claimed VMEM
    lane). `now` broadcasts to per-row like the XLA path's (B,) clock.
    The caller applies merge's expired-incoming filter to `active` BEFORE
    this entry (merge2_impl does) — the walk itself treats `active` as
    the claim mask, exactly like `_probe_claim2`.

    Returns ``(table', active & written_mask)``, plus the (B, 16) i32
    evictee sidecar when ``evictees=True`` — the install2/merge2 return
    contracts exactly, bit-identical modulo the documented sweep-window
    divergence (the walk can only drop FEWER rows)."""
    if stage not in ("install", "merge"):
        raise ValueError(f"stage must be install or merge, got {stage!r}")
    layout = table.layout
    NB = table.rows.shape[0]
    B = fp.shape[0]
    rblk = probe_blk(B)
    now = jnp.broadcast_to(jnp.asarray(now, dtype=i64), fp.shape)
    pay16 = jnp.asarray(pay16, dtype=i32)
    pairs = _join64(pay16[:, 0::2], pay16[:, 1::2])  # (B, 8) lossless
    arr11 = jnp.concatenate(
        [fp[None, :], now[None, :], active.astype(i64)[None, :], pairs.T],
        axis=0,
    )
    idx_s, arr_s, meta, sb, bkf, G = _sorted_schedule(
        fp, active, arr11, NB, rblk
    )
    rows_out, resp_s = _launch_walk(
        table, arr_s, meta, sb, bkf, G, rblk,
        math="mixed", evictees=evictees, stage=stage,
    )
    outw = _OUTW_EV if evictees else _OUTW
    out = jnp.zeros((B, outw), dtype=i64).at[idx_s].set(resp_s)
    written = out[:, _OC_WRITTEN] != 0
    tbl = Table2(rows=rows_out, layout=layout)
    if evictees:
        evict_live = out[:, _OC_EVICT] != 0
        evcols = out[:, _OUTW:]  # (B, 8) i64 candidate victim pairs
        ev16 = jnp.stack(
            [_lo32(evcols), _hi32(evcols)], axis=-1
        ).reshape(B, 16)
        ev16 = jnp.where(evict_live[:, None], ev16, 0)
        return tbl, active & written, ev16
    return tbl, active & written


__all__ = [
    "decide2_pallas",
    "decide2_pallas_impl",
    "hbm_bytes_per_decision",
    "probe_blk",
    "probe_movement",
    "walk2_pallas_impl",
]
